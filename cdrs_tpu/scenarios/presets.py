"""Named scenario cells and sweep suites.

Two kinds of cells:

* **Presets** — named, hand-written specs.  The first two re-express the
  legacy benches over the scenario harness: ``control-shift`` is
  benchmarks/control_bench.py's controller side and ``chaos-kill`` is
  benchmarks/chaos_bench.py's kill-one-node scenario — same seeds, same
  knobs, so they reproduce the pinned artifacts
  (data/control_bench.json, data/chaos_bench.json) bit-identically
  (asserted in tests/test_scenarios.py).  The rest cover the fault /
  partition / storage / integrity / serving domains the CI smoke steps
  used to exercise one hand-wired config at a time, plus the new
  workload curves (diurnal, flash crowd) and drift patterns (gradual,
  adversarial) and fault templates (cascade, rolling decommission).
* **Random cells** — seeded compositions over ALL axes
  (``random_cell``): workload x topology x faults x serve x storage
  drawn from a deterministic per-(suite seed, index) stream, so the
  matrix keeps covering combinations no author thought to hand-wire —
  the CRUSH posture: robustness must hold across the space, not at
  sampled points.

``suite_cells("ci-smoke")`` is the CI matrix: >= 12 cells spanning at
least the five legacy smoke domains, each checked against the harness
invariants (zero silent loss, churn-budget conservation, domain
diversity, SLO bounds, sampled kill/resume bit-identity).
"""

from __future__ import annotations

import numpy as np

from .spec import ScenarioSpec

__all__ = ["PRESETS", "SUITES", "preset", "random_cell", "suite_cells"]

_RACKS6 = "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6"
_NODES6 = ("dn1", "dn2", "dn3", "dn4", "dn5", "dn6")
_NODES12 = tuple(f"dn{i}" for i in range(1, 13))
_RACKS12 = ("r0=dn1,dn2,dn3;r1=dn4,dn5,dn6;"
            "r2=dn7,dn8,dn9;r3=dn10,dn11,dn12")
#: Geo hierarchy: 3 regions x 2 racks x 2 nodes, WAN edges priced
#: (cross-region copies cost 4x budget bytes, reads +8x service time).
_GEO_TOPOLOGY = {
    "nodes": list(_NODES12),
    "levels": ["rack", "region"],
    "rack": {f"r{j}": [f"dn{2 * j + 1}", f"dn{2 * j + 2}"]
             for j in range(6)},
    "region": {"eu": ["r0", "r1"], "us": ["r2", "r3"],
               "ap": ["r4", "r5"]},
    "edge_bytes": {"rack": 1.0, "region": 4.0},
    "edge_latency": {"rack": 1.5, "region": 8.0},
}
#: Region-local Archival stripes: ec(2,1) pinned to the primary's
#: region (zero WAN bytes for cold data; a WAN partition STRANDS these
#: — the stranded != lost scenario), everything else replicates spread.
_GEO_LOCAL_STORAGE = {
    "strategies": {"Archival": {"k": 2, "m": 1, "tier": "cold",
                                "locality": "region"}}}



def _alerts(expect, forbid=("files_lost", "true_lost")) -> dict:
    """Alert expectations of a designed-bad cell: ``expect`` must fire,
    ``forbid`` must stay silent ("others" = everything outside
    ``expect``).  Defaults forbid the loss alerts — a preset whose
    faults are designed to heal must never actually lose data."""
    return {"expect": list(expect),
            "forbid": forbid if forbid == "others" else list(forbid)}

def _presets() -> dict[str, ScenarioSpec]:
    p: dict[str, ScenarioSpec] = {}

    # -- legacy benches re-expressed (pinned-artifact reproduction) --------
    p["control-shift"] = ScenarioSpec(
        name="control-shift", n_files=300, seed=7, duration=2400.0,
        n_windows=20, k=12, nodes=("dn1", "dn2", "dn3"),
        drift={"kind": "flip", "at_frac": 0.5},
        scoring="validated", default_rf=1, decay=0.7,
        drift_threshold=0.02, budget_frac=0.30)
    p["chaos-kill"] = ScenarioSpec(
        name="chaos-kill", n_files=400, seed=11, duration=1800.0,
        n_windows=15, k=12,
        faults={"specs": ["crash:dn2@6"]}, resume_window=8,
        alerts=_alerts(["durability_degraded"]))

    # -- failure domains / partitions (chaos_rack_bench lineage) -----------
    p["rack-kill"] = ScenarioSpec(
        name="rack-kill", n_files=400, seed=13, duration=1800.0,
        n_windows=15, k=12, nodes=_NODES6, racks=_RACKS6,
        faults={"specs": ["crash:dn3@5", "crash:dn4@5"]},
        alerts=_alerts(["durability_degraded", "repair_backlog"]))
    p["rack-partition"] = ScenarioSpec(
        name="rack-partition", n_files=400, seed=13, duration=1800.0,
        n_windows=15, k=12, nodes=_NODES6, racks=_RACKS6,
        faults={"specs": ["partition:dn3+dn4@4-6",
                          "degrade:dn5@4-6:0.25"]},
        resume_window=6,
        alerts=_alerts(["durability_degraded", "repair_backlog"]))

    # -- fault templates ---------------------------------------------------
    p["cascade"] = ScenarioSpec(
        name="cascade", n_files=300, seed=3, duration=1800.0,
        n_windows=15, k=12,
        faults={"template": "cascade", "nodes": ["dn2", "dn3"],
                "start": 4, "spacing": 2, "recover_after": 3},
        alerts=_alerts(["durability_degraded"]))
    p["rolling-decommission"] = ScenarioSpec(
        name="rolling-decommission", n_files=300, seed=4,
        duration=1800.0, n_windows=15, k=12, nodes=_NODES6,
        faults={"template": "rolling_decommission",
                "nodes": ["dn2", "dn3"], "start": 4, "spacing": 4},
        alerts=_alerts(["durability_degraded"]))

    # -- storage strategies (storage_bench lineage) ------------------------
    p["storage-ec"] = ScenarioSpec(
        name="storage-ec", n_files=400, seed=13, duration=1800.0,
        n_windows=15, k=12, nodes=_NODES12, racks=_RACKS12,
        storage="ec_archival",
        faults={"specs": ["crash:dn4@5", "crash:dn5@5", "crash:dn6@5"]},
        alerts=_alerts(["durability_degraded"]))

    # -- serving / SLO -----------------------------------------------------
    p["serve-chaos"] = ScenarioSpec(
        name="serve-chaos", n_files=300, seed=5, duration=1800.0,
        n_windows=15, k=12,
        serve={"policy": "p2c", "p99_max_ms": 50.0, "burn_max": 1.0},
        faults={"specs": ["partition:dn2@4-7", "degrade:dn3@4-7:0.25"]},
        alerts=_alerts(["durability_degraded", "repair_backlog", "budget_saturated"]))
    p["flash-crowd"] = ScenarioSpec(
        name="flash-crowd", n_files=300, seed=6, duration=1800.0,
        n_windows=15, k=12,
        workload={"kind": "flash_crowd", "start_frac": 0.5,
                  "duration_frac": 0.1, "boost": 40.0,
                  "cohort": "archival"},
        serve={"policy": "p2c", "p99_max_ms": 50.0},
        alerts=_alerts([], "others"))
    # A sustained crowd on the HOT cohort against an undersized service
    # budget and no elastic rescue: the SLO burn-rate pair (fast AND
    # slow) must fire while the crowd holds and resolve when it lifts —
    # the alerting regression suite's designed-bad SLO cell (flash-crowd
    # above stays the hotspot-feedback cell and must stay SILENT: its
    # archival burst re-clusters without ever touching the error
    # budget).
    p["slo-burn"] = ScenarioSpec(
        name="slo-burn", n_files=300, seed=24, duration=1800.0,
        n_windows=15, k=12,
        workload={"kind": "flash_crowd", "start_frac": 0.25,
                  "duration_frac": 0.3, "boost": 40.0, "cohort": "hot"},
        serve={"policy": "p2c", "service_ms": 6.0, "slo_ms": 60.0,
               "p99_max_ms": 60.0},
        alerts=_alerts(["slo_burn_fast", "slo_burn_slow"]))

    # -- data integrity (integrity_bench lineage) --------------------------
    p["integrity-scrub"] = ScenarioSpec(
        name="integrity-scrub", n_files=300, seed=9, duration=1800.0,
        n_windows=15, k=12,
        faults={"specs": ["corrupt:dn2@3:0.5"]},
        scrub=200_000_000, resume_window=7,
        alerts=_alerts(["corruption_detected", "scrub_starved",
                        "durability_degraded", "budget_saturated"],
                       ["true_lost"]))
    p["integrity-read"] = ScenarioSpec(
        name="integrity-read", n_files=300, seed=9, duration=1800.0,
        n_windows=15, k=12,
        faults={"specs": ["corrupt:dn2@3:0.5"]},
        serve={"policy": "p2c", "verify_reads": True},
        alerts=_alerts(["corruption_detected"], ["true_lost"]))

    # -- scale: mesh-sharded control loop ----------------------------------
    # The whole per-window device computation (cluster step, scoring
    # medians, feature fold, drift one-Lloyd-step) data-parallel over an
    # 8-device mesh, with a mid-cell kill/resume (mesh shape is a runtime
    # choice, not checkpoint state) and the mesh_engaged positive check.
    # On CPU this needs XLA_FLAGS=--xla_force_host_platform_device_count=8
    # (tests/conftest.py and the CI sweep step set it).
    p["scale-mesh"] = ScenarioSpec(
        name="scale-mesh", n_files=300, seed=8, duration=1800.0,
        n_windows=12, k=12, backend="jax", mesh={"data": 8},
        drift={"kind": "flip", "at_frac": 0.5}, drift_threshold=0.02,
        resume_window=7,
        alerts=_alerts([], "others"))

    # -- scale: functional placement ---------------------------------------
    # A drift flip under --placement functional: the CRUSH-style hash
    # chooser with exception-overlay checkpoints and a fault in the way
    # (repair retargets ARE the exceptions), gated on the
    # functional_engaged positive check plus a mid-cell kill/resume —
    # the sparse-snapshot round trip must be bit-identical.
    p["scale-placement"] = ScenarioSpec(
        name="scale-placement", n_files=400, seed=14, duration=1800.0,
        n_windows=15, k=12, nodes=_NODES6, racks=_RACKS6,
        placement="functional",
        drift={"kind": "flip", "at_frac": 0.5}, drift_threshold=0.02,
        faults={"specs": ["crash:dn3@6-9"]},
        serve={"policy": "p2c"}, resume_window=8,
        alerts=_alerts(["durability_degraded"]))

    # -- geo hierarchy: region loss / WAN partition / elasticity -----------
    # Kill a whole REGION (4 of 12 nodes, correlated): hierarchy-aware
    # placement spreads every file's copies across regions — replicate
    # rf>=2 and the spread EC(6,3) stripes (shards (3,3,3) per region;
    # 6 = k survive) both ride it out with ZERO loss, where the same
    # workload on a racks-only topology measurably loses files (the
    # contrast is pinned by tests/test_geo.py and benchmarks/geo_bench).
    # Functional placement + mid-cell kill/resume: the sparse overlay
    # snapshot must restore the region outage bit-identically.
    p["region-loss"] = ScenarioSpec(
        name="region-loss", n_files=400, seed=21, duration=1800.0,
        n_windows=15, k=12, nodes=_NODES12, topology=_GEO_TOPOLOGY,
        placement="functional", storage="ec_archival",
        faults={"specs": ["crash:region:eu@5-9"]},
        serve={"policy": "p2c"}, resume_window=7,
        alerts=_alerts(["durability_degraded", "repair_backlog", "budget_saturated"]))
    # Partition region eu off the WAN: its region-LOCAL Archival
    # stripes strand (unreachable > 0) but are never lost, repairs
    # STALL on them (partition backoff) instead of burning budget on
    # doomed WAN copies, and the heal converges every level's
    # correlated risk back to zero.
    p["wan-partition"] = ScenarioSpec(
        name="wan-partition", n_files=400, seed=22, duration=1800.0,
        n_windows=15, k=12, nodes=_NODES12, topology=_GEO_TOPOLOGY,
        placement="functional", storage=_GEO_LOCAL_STORAGE,
        faults={"specs": ["partition:region:eu@4-7"]},
        serve={"policy": "p2c"},
        alerts=_alerts(["durability_degraded", "reads_unavailable",
                        "repair_backlog", "unreachable_stranded",
                        "slo_burn_fast", "slo_burn_slow"]))
    # Black Friday: a flash crowd on the hot cohort saturates the
    # 3-node baseline; sustained SLO burn activates the standby pool
    # (capacity doubles), the addition-pruned epoch diff rebalances
    # inside the shared churn budget, p99 recovers within the SloSpec
    # bound by the final window, and the cool-down drains capacity back
    # to baseline via rolling decommission.  Kill/resume crosses the
    # scale-out boundary (grown-topology checkpoint restore).
    p["black-friday"] = ScenarioSpec(
        name="black-friday", n_files=300, seed=23, duration=1800.0,
        n_windows=15, k=12, placement="functional",
        workload={"kind": "flash_crowd", "start_frac": 0.25,
                  "duration_frac": 0.3, "boost": 25.0, "cohort": "hot"},
        serve={"policy": "p2c", "service_ms": 6.0, "slo_ms": 60.0,
               "p99_max_ms": 60.0},
        # burn_hot sits WELL inside the crowd/off-crowd separation
        # (burn ~0 quiet, >= 0.6 under the crowd on every suite seed):
        # the trigger must be decisive, not a coin flip at the
        # threshold.
        elastic={"pool": ["sb1", "sb2", "sb3"], "burn_hot": 0.4,
                 "util_hot": 0.9, "hot_windows": 2, "util_cool": 0.5,
                 "cool_windows": 2, "drain_spacing": 1},
        resume_window=8,
        alerts=_alerts(["durability_degraded"]))

    # -- streaming daemon --------------------------------------------------
    # The always-on controller daemon (cdrs_tpu/daemon) over a seeded
    # live feed: the cell's events land in a binary event log the daemon
    # tails, with a mid-stream category flip (the drift axis) and one
    # node killed under it (the fault axis).  Gated on the daemon
    # invariants — >= 2 epochs published (daemon_engaged), decisions
    # bit-identical to the windowed batch run, the pinned epoch frozen
    # and read-resolving, and SIGTERM-flag stop/checkpoint/resume
    # stitching bit-identical — on top of the usual zero-loss and
    # budget-conservation gates.
    p["daemon-stream"] = ScenarioSpec(
        name="daemon-stream", n_files=300, seed=17, duration=1800.0,
        n_windows=15, k=12, daemon=True,
        drift={"kind": "flip", "at_frac": 0.5}, drift_threshold=0.02,
        faults={"specs": ["crash:dn2@8"]},
        alerts=_alerts(["durability_degraded"]))

    # -- workload curves / drift patterns ----------------------------------
    p["diurnal"] = ScenarioSpec(
        name="diurnal", n_files=300, seed=10, duration=1800.0,
        n_windows=15, k=12,
        workload={"kind": "diurnal", "amplitude": 0.8},
        serve={"policy": "p2c", "p99_max_ms": 50.0},
        faults={"specs": ["crash:dn2@5-8"]},
        alerts=_alerts(["durability_degraded"]))
    p["adversarial-drift"] = ScenarioSpec(
        name="adversarial-drift", n_files=300, seed=11, duration=2400.0,
        n_windows=20, k=12, decay=0.7, drift_threshold=0.02,
        drift={"kind": "adversarial", "cycles": 3,
               "start_frac": 0.3, "end_frac": 0.8},
        alerts=_alerts([], "others"))
    p["gradual-drift"] = ScenarioSpec(
        name="gradual-drift", n_files=300, seed=12, duration=2400.0,
        n_windows=20, k=12, decay=0.7, drift_threshold=0.02,
        drift={"kind": "gradual", "steps": 3,
               "start_frac": 0.3, "end_frac": 0.7},
        alerts=_alerts([], "others"))

    for name, spec in p.items():
        # Generated cells own these namespaces (random_cell,
        # scenarios/search.py): a preset named into them would alias
        # the generated cells' scenario_<name>_* history/regress keys.
        if name.startswith(("random-", "search-")):
            raise ValueError(
                f"preset {name!r} uses a reserved generated-cell "
                f"name prefix")
        spec._preset = name
    return p


PRESETS: dict[str, ScenarioSpec] = _presets()


def preset(name: str) -> ScenarioSpec:
    if name not in PRESETS:
        raise KeyError(
            f"unknown preset {name!r} (have {sorted(PRESETS)})")
    return PRESETS[name]


def random_cell(index: int, seed: int = 0) -> ScenarioSpec:
    """A seeded random cell composing all axes (deterministic in
    ``(seed, index)``).  Draws stay inside the invariant-satisfiable
    region by construction: random faults are crash/flaky/straggler
    spans confined to the first ~60% of windows (data is never
    destroyed and every node is back before the run ends), budgets stay
    at the standard quarter-of-population per window."""
    rng = np.random.default_rng([int(seed), int(index)])
    n_windows = 12
    wl_kind = ("poisson", "diurnal", "flash_crowd")[int(rng.integers(3))]
    workload: dict = {"kind": wl_kind}
    if wl_kind == "diurnal":
        workload.update(amplitude=float(rng.uniform(0.4, 0.9)),
                        phase=float(rng.uniform(0.0, 6.28)))
    elif wl_kind == "flash_crowd":
        workload.update(start_frac=float(rng.uniform(0.3, 0.6)),
                        duration_frac=0.1,
                        boost=float(rng.uniform(20.0, 60.0)))
    drift = None
    if wl_kind == "poisson" and rng.random() < 0.7:
        drift = {"kind": ("flip", "gradual",
                          "adversarial")[int(rng.integers(3))]}
    racked = bool(rng.random() < 0.5)
    faults = {"random": {
        "n_windows": 7, "seed": int(rng.integers(2**31)),
        "crash_rate": 0.08, "recover_windows": [1, 2],
        "flaky_rate": 0.05, "degrade_rate": 0.05,
    }}
    serve = None
    if rng.random() < 0.5:
        serve = {"policy": ("p2c", "least_loaded",
                            "random")[int(rng.integers(3))]}
    storage = "replicate" if rng.random() < 0.3 else None
    # The name carries the suite seed: the cell IS a function of
    # (seed, index), so its history/regress metric keys
    # (scenario_random-s<seed>-<i>_*) must never alias a different
    # seed's scenario, and a repro with a mismatched --seed fails the
    # cell lookup instead of silently running something else.
    return ScenarioSpec(
        name=f"random-s{seed}-{index}",
        n_files=int(rng.integers(200, 400)),
        seed=int(rng.integers(1000)),
        duration=1440.0, n_windows=n_windows, k=10,
        nodes=_NODES6 if racked else ("dn1", "dn2", "dn3", "dn4", "dn5"),
        racks=_RACKS6 if racked else None,
        workload=workload, drift=drift, faults=faults,
        serve=serve, storage=storage)


#: Suite name -> (preset names, number of random cells).
SUITES: dict[str, tuple[tuple[str, ...], int]] = {
    # The CI matrix: every legacy smoke domain (chaos, partition, serve,
    # storage, integrity) plus the new curves/templates, and two random
    # compositions.  >= 12 cells.
    "ci-smoke": (("chaos-kill", "rack-kill", "rack-partition", "cascade",
                  "rolling-decommission", "storage-ec", "serve-chaos",
                  "flash-crowd", "slo-burn", "integrity-scrub",
                  "integrity-read", "diurnal", "adversarial-drift",
                  "gradual-drift", "scale-mesh", "scale-placement",
                  "region-loss", "wan-partition", "black-friday",
                  "daemon-stream"), 2),
    # Everything, including the slow legacy-reproduction preset.
    "full": (tuple(PRESETS), 4),
}


def suite_cells(suite: str, seed: int = 0) -> list[ScenarioSpec]:
    """The suite's cell list (deterministic in ``seed``).

    ``seed`` parameterizes the whole matrix, not just the random cells:
    a non-zero suite seed SHIFTS every preset cell's workload seed
    (``spec.seed + suite seed``) so a 3-seed CI loop re-checks the
    invariants against three different workloads per preset — the
    multi-seed "not a single-seed accident" dimension — instead of
    re-running 13 byte-identical cells.  Seed 0 keeps the presets'
    pinned workloads (the per-cell history baseline keys, and the
    control-shift/chaos-kill artifact reproduction) untouched."""
    if suite not in SUITES:
        raise KeyError(f"unknown suite {suite!r} (have {sorted(SUITES)})")
    names, n_random = SUITES[suite]
    cells = []
    for n in names:
        sp = preset(n)
        if seed:
            shifted = sp.replace(seed=sp.seed + int(seed))
            shifted._preset = n
            sp = shifted
        cells.append(sp)
    cells += [random_cell(i, seed) for i in range(n_random)]
    return cells
