"""ONE harness for every scenario cell: build, run, check invariants.

``run_cell`` turns a declarative ``ScenarioSpec`` into a controller run —
manifest, workload curve, drift phases, fault schedule, topology, storage
strategy, serve config, scrubber — and then checks **invariants**, not
just metrics:

* **zero silent loss** — no file ends the run lost (blind durability
  tier), and when the integrity layer is active no file ends TRULY lost
  (``true_lost``: clean copies below the survivable minimum — the state
  the blind tiers cannot see) and no rot survives an active scrubber.
* **churn-budget conservation** — every window's repair + migration +
  scrub traffic fits the one shared byte budget (integrity runs: to
  within ONE verified boundary task — verified repair deliberately
  charges the budget-crossing task's source-verification reads, see
  ``_check_invariants``).
* **domain diversity** — with a multi-rack topology, no file ends with
  all its reachable replicas in one domain (``correlated_risk``).
* **SLO bounds** — when serving, the final (post-heal) window routed
  every read (none unavailable), its p99 is finite, and optional
  per-cell ``p99_max_ms`` / ``burn_max`` bounds hold.
* **kill/resume bit-identity** — cells sampled with ``resume_window``
  re-run killed mid-cell and resumed from the checkpoint; the stitched
  record stream and final plan must equal the uninterrupted run's
  bit-for-bit.
* **positive engagement** — the axes must actually FIRE (fault events
  applied, corruption rotted/detected, EC stripes stored, reads routed,
  drift re-clustered): a cell whose injection silently became a no-op
  fails instead of passing every negative check vacuously.
* **alerting** — cells carrying an ``alerts`` axis gate the streaming
  alert rules (obs/alerts.py) the same way: a designed-bad cell's
  expected alerts must FIRE (``alerts_expected``) and its forbidden
  ones must stay silent (``alerts_silent``; ``"forbid": "others"`` =
  anything outside the expected set) — the sweep doubles as an
  alerting regression suite.

A failing cell's result carries a one-line seeded repro command
(``repro_line``) so the sweep output alone is enough to rerun exactly
that cell.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..config import (
    GeneratorConfig,
    KMeansConfig,
    ScoringConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..faults import FaultSchedule
from ..io.events import EventLog, Manifest
from ..sim.access import (
    simulate_access,
    simulate_access_phased,
    simulate_diurnal,
    simulate_flash_crowd,
)
from ..sim.generator import generate_population
from .spec import ScenarioSpec

__all__ = ["build_events", "build_schedule", "coverage_bits", "run_cell",
           "repro_line"]

_DEFAULT_FLIP = {"hot": "archival", "archival": "hot"}


def _scoring(spec: ScenarioSpec) -> ScoringConfig:
    """The cell's scoring table.  ``min_rf2`` = the chaos-bench posture
    (validated tables with Moderate raised to rf 2 so no category
    trivially loses a node's singletons)."""
    import dataclasses

    if spec.scoring == "default":
        return ScoringConfig()
    base = validated_scoring_config()
    if spec.scoring == "validated":
        return base
    rf = dict(base.replication_factors)
    rf["Moderate"] = max(2, rf["Moderate"])
    return dataclasses.replace(base, replication_factors=rf)


def build_events(spec: ScenarioSpec,
                 manifest: Manifest) -> tuple[EventLog, np.ndarray]:
    """The cell's event log from the workload x drift axes.

    Returns ``(events, changed)`` — ``changed`` marks files whose final
    planted category differs from the initial one (all-False for
    drift-free curves and fully reverted adversarial cycles)."""
    cfg = SimulatorConfig(duration_seconds=float(spec.duration),
                          seed=int(spec.seed) + 1)
    wl = spec.workload or {"kind": "poisson"}
    kind = wl.get("kind", "poisson")
    none = np.zeros(len(manifest), dtype=bool)
    if kind == "diurnal":
        period = float(wl.get("period_frac", 1.0)) * float(spec.duration)
        ev = simulate_diurnal(manifest, cfg,
                              amplitude=float(wl.get("amplitude", 0.8)),
                              period=period,
                              phase=float(wl.get("phase", 0.0)))
        return ev, none
    if kind == "flash_crowd":
        cat = wl.get("cohort", "archival")
        cohort = np.asarray([c == cat for c in manifest.category])
        ev, _ = simulate_flash_crowd(
            manifest, cfg, cohort=cohort,
            start=float(wl.get("start_frac", 0.5)) * float(spec.duration),
            duration=float(wl.get("duration_frac", 0.1))
            * float(spec.duration),
            boost=float(wl.get("boost", 40.0)))
        return ev, none
    if spec.drift is None:
        return simulate_access(manifest, cfg), none
    return simulate_access_phased(manifest, cfg,
                                  _drift_shifts(spec, manifest))


def _drift_shifts(spec: ScenarioSpec, manifest: Manifest) -> list[tuple]:
    """The drift axis as ``simulate_access_phased`` shifts."""
    d = spec.drift
    flip = d.get("flip", _DEFAULT_FLIP)
    duration = float(spec.duration)
    if d["kind"] == "flip":
        return [(float(d.get("at_frac", 0.5)) * duration, flip, None)]
    start = float(d.get("start_frac", 0.3)) * duration
    end = float(d.get("end_frac", 0.8)) * duration
    if d["kind"] == "adversarial":
        cycles = int(d.get("cycles", 3))
        times = np.linspace(start, end, cycles)
        return [(float(t), flip, None) for t in times]
    # gradual: the cohort (files whose planted category is a flip key)
    # migrates in `steps` index-ordered waves.
    steps = int(d.get("steps", 3))
    cohort = np.flatnonzero(
        np.asarray([c in flip and flip[c] != c
                    for c in manifest.category]))
    chunks = np.array_split(cohort, steps)
    times = np.linspace(start, end, steps)
    shifts = []
    for t, chunk in zip(times, chunks):
        mask = np.zeros(len(manifest), dtype=bool)
        mask[chunk] = True
        shifts.append((float(t), flip, mask))
    return shifts


def build_schedule(spec: ScenarioSpec) -> FaultSchedule | None:
    """The fault axis: explicit specs, templates and the seeded random
    generator merged into one window-keyed schedule."""
    f = spec.faults
    if f is None:
        return None
    events: list = []
    if f.get("specs"):
        events.extend(FaultSchedule.from_specs(f["specs"]))
    t = f.get("template")
    if t == "cascade":
        events.extend(FaultSchedule.cascade(
            f["nodes"], int(f["start"]), int(f.get("spacing", 1)),
            f.get("recover_after")))
    elif t == "rolling_decommission":
        events.extend(FaultSchedule.rolling_decommission(
            f["nodes"], int(f["start"]), int(f.get("spacing", 2))))
    elif t is not None:
        raise ValueError(
            f"cell {spec.name!r}: unknown fault template {t!r}")
    if f.get("random"):
        r = dict(f["random"])
        r.setdefault("seed", spec.seed)
        events.extend(FaultSchedule.random(
            spec.nodes, int(r.pop("n_windows")), **r))
    if not events:
        raise ValueError(
            f"cell {spec.name!r}: faults axis present but empty")
    return FaultSchedule(events)


def _controller(spec: ScenarioSpec, manifest: Manifest,
                schedule: FaultSchedule | None) -> ReplicationController:
    scoring = _scoring(spec)
    topology = None
    if spec.topology is not None:
        from ..cluster import ClusterTopology

        topology = ClusterTopology.from_hierarchy(spec.topology)
    elif spec.racks:
        from ..cluster import ClusterTopology

        topology = ClusterTopology.from_rack_spec(manifest.nodes,
                                                  spec.racks)
    elastic = None
    if spec.elastic is not None:
        from ..control.elastic import ElasticPolicy

        elastic = ElasticPolicy.from_dict(spec.elastic)
    storage = None
    if spec.storage:
        if isinstance(spec.storage, dict):
            from ..storage import storage_config_from_dict

            storage = storage_config_from_dict(spec.storage)
        else:
            from ..storage import resolve_storage_config

            storage = resolve_storage_config(spec.storage, scoring)
    serve = None
    if spec.serve is not None:
        from ..serve import ServeConfig, SloSpec

        s = spec.serve
        serve = ServeConfig(
            policy=s.get("policy", "p2c"), seed=int(s.get("seed", 0)),
            service_ms=float(s.get("service_ms", 0.5)),
            slo=SloSpec(target_ms=float(s.get("slo_ms", 10.0)),
                        availability=float(s.get("availability", 0.999))),
            recluster_on_hotspot=bool(s.get("recluster_on_hotspot", True)),
            verify_reads=bool(s.get("verify_reads", True)))
    scrub = None
    if spec.scrub is not None:
        from ..faults import ScrubConfig

        scrub = ScrubConfig(bytes_per_window=int(spec.scrub))
    max_bytes = None
    if spec.budget_frac is not None:
        sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
        max_bytes = int(float(spec.budget_frac) * float(sizes.sum()))
    cfg = ControllerConfig(
        window_seconds=spec.window_seconds,
        drift_threshold=spec.drift_threshold,
        full_recluster_drift=spec.full_recluster_drift,
        hysteresis_windows=spec.hysteresis,
        max_bytes_per_window=max_bytes,
        max_files_per_window=spec.max_files,
        decay=spec.decay,
        default_rf=spec.default_rf,
        backend=spec.backend,
        placement_mode=spec.placement,
        mesh_shape=dict(spec.mesh) if spec.mesh else None,
        kmeans=KMeansConfig(k=spec.k, seed=42),
        scoring=scoring,
        topology=topology,
        fault_schedule=(None if schedule is None
                        else FaultSchedule(schedule.events)),
        storage=storage,
        serve=serve,
        scrub=scrub,
        elastic=elastic,
    )
    return ReplicationController(manifest, cfg)


def _strip(records: list[dict]) -> list[dict]:
    """Records minus wall-clock noise: the bit-identity comparison key."""
    return [{k: v for k, v in r.items() if k != "seconds"}
            for r in records]


def _served_windows(records: list[dict]) -> list[dict]:
    """Windows where reads HAPPENED: routed or refused.  The ONE
    definition behind both the SLO invariants and the reported p99
    metric — filtering on routed>0 alone would retarget "final" onto
    the last healthy window when an outage refuses every read to the
    end of the run."""
    return [r for r in records if r.get("reads_routed") is not None
            and (int(r.get("reads_routed", 0))
                 + int(r.get("reads_unavailable", 0))) > 0]


def _check_invariants(spec: ScenarioSpec, records: list[dict],
                      max_bytes: int | None, budget_slack: int,
                      multi_domain: bool, has_corrupt: bool,
                      has_ec: bool, schedule=None,
                      alerts_fired: set | None = None) -> dict:
    inv: dict[str, bool] = {}
    dur = [r for r in records if r.get("durability")]
    if dur:
        inv["zero_lost_final"] = dur[-1]["durability"]["lost"] == 0
    # -- geo-hierarchical cells (topology axis) ----------------------------
    scoped = schedule is not None and any(
        ":" in n for ev in schedule for n in ev.node_list)
    has_partition = schedule is not None and any(
        ev.kind == "partition" for ev in schedule)
    if spec.topology is not None and dur:
        n_regions = len({str(d) for d in
                         (spec.topology.get(
                             spec.topology["levels"][-1]) or {})})
        if scoped:
            # A region-scale event must actually BITE: some window saw
            # fewer reachable regions than the topology defines.
            inv["region_engaged"] = any(
                0 < r["durability"].get("regions_reachable", n_regions)
                < n_regions for r in dur)
        if has_partition:
            # Stranded != lost: a partition strands data behind the WAN
            # split, it never destroys it — and repairs STALL on the
            # doomed files (deferred_partition) instead of burning
            # budget on copies that cannot land.
            stranded = [r for r in dur
                        if r["durability"].get("unreachable", 0) > 0]
            inv["stranded_not_lost"] = bool(
                stranded and all(r["durability"]["lost"] == 0
                                 for r in stranded))
            inv["partition_stall_engaged"] = any(
                r.get("repair_deferred_partition", 0) > 0
                for r in records)
        # Heal convergence: whatever the schedule did, the final window
        # is whole again — nothing stranded, nothing under target, and
        # every hierarchy level's correlated risk back to zero (the
        # cross-region spread was actually restored, not just counted).
        last = dur[-1]["durability"]
        inv["heal_converged"] = (
            last.get("unreachable", 0) == 0
            and last["under_replicated"] == 0
            and all(v == 0 for v in last.get(
                "correlated_risk_levels", {}).values()))
    # -- elastic cells -----------------------------------------------------
    if spec.elastic is not None:
        el = [r.get("elastic") or {} for r in records]
        moved = sum(e.get("moved", 0) for e in el)
        rebal = sum(e.get("rebalanced", 0) for e in el)
        drained = [n for e in el for n in e.get("drained", ())]
        inv["elastic_engaged"] = bool(
            any("added" in e for e in el)       # scale-out fired
            and moved == rebal                  # traffic == epoch diff
            and (el[-1].get("queue", 0) == 0))  # queue fully drained
        inv["elastic_drained"] = bool(
            drained
            and dur and dur[-1]["durability"]["nodes_up"]
            == len(spec.nodes))                 # capacity back to baseline
    # Positive engagement: a cell whose axis silently failed to inject
    # must not pass vacuously — the invariants below only bite when the
    # machinery they guard actually fired (the replaced CI steps
    # asserted detected_total > 0 / ec_files > 0 for the same reason).
    if spec.faults is not None:
        inv["faults_engaged"] = any(r.get("fault_events")
                                    for r in records)
    if spec.drift is not None:
        # Cold start is one re-cluster; a drift pattern that never
        # triggers another means the detector slept through the shift.
        inv["drift_engaged"] = \
            sum(1 for r in records if r.get("recluster")) >= 2
    if spec.mesh is not None:
        # The mesh axis must actually FIRE: every window record carries
        # the mesh stamp at the requested device count (the controller
        # only stamps it when the sharded path is wired in) and the
        # cluster step ran at least once on it — a cell whose mesh
        # silently fell back to single-device fails instead of passing
        # its other checks vacuously.
        ndev = 1
        for v in spec.mesh.values():
            ndev *= int(v)
        inv["mesh_engaged"] = bool(
            records
            and all((r.get("mesh") or {}).get("devices") == ndev
                    for r in records)
            and any(r.get("recluster") for r in records))
    if spec.placement != "materialized":
        # The placement axis must actually FIRE: every window record
        # carries the mode stamp (the controller only stamps it when the
        # hash-chooser path is wired in), and a functional fault run
        # additionally reports its exception count — a cell whose
        # placement silently fell back to the legacy path fails instead
        # of passing its other checks vacuously.
        inv["functional_engaged"] = bool(
            records
            and all((r.get("placement") or {}).get("mode")
                    == spec.placement for r in records)
            and (spec.faults is None or spec.placement != "functional"
                 or all("exceptions" in (r.get("placement") or {})
                        for r in records)))
    integ = [r for r in records if r.get("integrity")]
    if integ:
        inv["zero_silent_loss"] = integ[-1]["integrity"]["true_lost"] == 0
        if spec.scrub is not None:
            inv["rot_cleaned"] = \
                integ[-1]["integrity"]["corrupt_copies"] == 0
    if has_corrupt:
        rotted = any(int(r["integrity"].get("corrupt_copies", 0)) > 0
                     for r in integ)
        detected = sum(
            int(r["integrity"].get(k, 0)) for r in integ
            for k in ("detected_scrub", "detected_read",
                      "detected_repair"))
        inv["corruption_engaged"] = rotted or detected > 0
    if has_ec:
        # Anti-vacuousness: the EC axis must actually FIRE — a stripe
        # installed and accounted in SOME window.  Engagement is about
        # the run, not its final frame: a drift cell that legitimately
        # promotes planted-archival files to Hot after a workload flip
        # (cumulative features, decay=1.0) ends with zero EC files while
        # having exercised the whole encode/repair path mid-run — the
        # PR-19 search banked exactly that as a false violation.  A run
        # where no stripe ever lands still fails.
        st = [r for r in records if r.get("storage")]
        inv["ec_engaged"] = bool(st) and any(
            r["storage"]["ec_files"] > 0
            and r["storage"]["bytes_stored"] > r["storage"]["bytes_raw"]
            for r in st)
    if max_bytes is not None:
        # Integrity runs are allowed ONE verified boundary task past the
        # line (``budget_slack``): verified repair (faults/repair.py,
        # PR 9) charges the source-verification reads of the task that
        # crosses the budget — the traffic is real and rot must never
        # propagate — so the admission check sees the budget already
        # consumed and defers the copy.  Everything else (repair copies,
        # scrub rate, migration admission) checks BEFORE charging, so
        # corruption-free runs are gated strictly (slack 0).
        slack = budget_slack if integ else 0
        inv["budget_conserved"] = all(
            r.get("repair_bytes", 0) + r["bytes_migrated"]
            + (r.get("scrub") or {}).get("bytes", 0)
            + (r.get("elastic") or {}).get("rebalance_bytes", 0)
            <= max_bytes + slack
            for r in records)
    if multi_domain and dur:
        inv["domain_diversity"] = \
            dur[-1]["durability"].get("correlated_risk", 0) == 0
    # -- alerting (obs/alerts.py): the positive-engagement invariant of
    # the observability axis — a designed-bad cell must FIRE its
    # expected alerts (a sweep where the durability alert sleeps through
    # a region kill is an alerting regression, not a green run) and a
    # cell's forbidden alerts must stay silent (a healthy cell that
    # pages is the same bug from the other side).
    if spec.alerts is not None:
        fired = alerts_fired if alerts_fired is not None else set()
        expect = set(spec.alerts.get("expect") or ())
        inv["alerts_expected"] = expect <= fired
        forbid = spec.alerts.get("forbid")
        if forbid == "others":
            inv["alerts_silent"] = not (fired - expect)
        elif forbid:
            inv["alerts_silent"] = not (fired & set(forbid))
    if spec.serve is not None:
        served = _served_windows(records)
        inv["serve_engaged"] = sum(int(r.get("reads_routed", 0))
                                   for r in served) > 0
        if served:
            last = served[-1]
            p99 = last.get("latency_p99_ms")
            # A final window that routed nothing has no latency sample —
            # that is an SLO failure, not a vacuous pass.
            ok = p99 is not None and np.isfinite(p99)
            bound = spec.serve.get("p99_max_ms")
            if ok and bound is not None:
                ok = p99 <= float(bound)
            inv["slo_p99"] = bool(ok)
            inv["slo_no_unavailable_final"] = \
                last.get("reads_unavailable", 0) == 0
            burn_max = spec.serve.get("burn_max")
            if burn_max is not None:
                inv["slo_burn"] = \
                    last.get("slo_burn", 0.0) <= float(burn_max)
    return inv


def _daemon_invariants(spec: ScenarioSpec, manifest: Manifest,
                       schedule, events: EventLog,
                       batch_records: list[dict]) -> dict:
    """The streaming-daemon axis: the cell's whole event stream goes
    through the always-on daemon (binary-log tail -> same window grid ->
    same admission path), gated on

    * ``daemon_engaged`` — at least two placement epochs actually
      published (cold start + at least one live re-plan; a daemon that
      never re-publishes slept through the cell's drift/fault axes),
    * ``daemon_decisions_identical`` — the daemon's window records are
      bit-identical to the windowed batch controller's (same plan
      hashes, same budget charges, same durability tallies),
    * ``daemon_epoch_pinned`` — the pinned epoch is frozen (arrays
      non-writable), equal to the admitted plan, and resolves reads,
    * ``daemon_resume_bit_identical`` — stop mid-run via the SIGTERM
      flag path (``request_stop`` is exactly what the signal handler
      raises), checkpoint, resume: the stitched record stream and the
      final epoch must equal the uninterrupted daemon run's,
    * ``trace_engaged`` — the full run carried a metrics sink, so every
      processed window must have emitted exactly one ``decision_trace``
      event (obs/trace.py),
    * ``trace_reconciled`` — every decision's integer-ns segments sum
      to its measured total EXACTLY (the one-clock telescoping
      contract; any mismatch is an emitter bug, not noise),
    * ``endpoint_engaged`` — the live operational plane (obs/httpz.py)
      rode the SAME full run: the scraped ``/metrics`` exposition is
      format-clean with one snapshot per processed window, ``/statusz``
      agrees with the run digest, and ``/debug/trace`` serves exemplar
      decisions — all over real HTTP against the in-process endpoint.
    """
    import json as _json
    import os
    import tempfile
    import urllib.request

    from ..daemon import DaemonConfig, StreamDaemon
    from ..obs import prom
    from ..obs.httpz import ObsServer

    inv: dict[str, bool] = {}
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        events.write_binary(log, manifest)

        metrics = os.path.join(td, "daemon.jsonl")
        full = StreamDaemon(_controller(spec, manifest, schedule))
        with ObsServer() as srv:
            full.attach_http(srv)
            dig = full.run(log, metrics_path=metrics)

            def _scrape(path: str) -> str:
                with urllib.request.urlopen(srv.url + path,
                                            timeout=5) as r:
                    return r.read().decode("utf-8")

            snap = srv.snapshot
            text = _scrape("/metrics")
            statusz = _json.loads(_scrape("/statusz"))
            trace = _json.loads(_scrape("/debug/trace"))
            inv["endpoint_engaged"] = bool(
                prom.lint(text) == []
                and snap.seq == snap.windows_processed
                == snap.epochs_published
                == dig["windows_processed"] >= 2
                and statusz["seq"] == snap.seq
                and statusz["events_ingested"] == dig["events_ingested"]
                and trace["traceEvents"])
        inv["daemon_engaged"] = dig["epochs_published"] >= 2
        inv["daemon_decisions_identical"] = \
            _strip(full.records) == _strip(batch_records)

        # Decision tracing rides the metrics sink (telemetry is
        # observe-only, so the decision-identity gate above already ran
        # WITH tracing engaged — the trace cannot have changed a plan).
        with open(metrics) as f:
            traces = [e for e in map(_json.loads, f)
                      if e.get("kind") == "decision_trace"]
        inv["trace_engaged"] = (
            len(traces) == dig["windows_processed"]
            and dig["windows_processed"] >= 2)
        inv["trace_reconciled"] = bool(traces) and all(
            sum(int(v) for v in t["segments_ns"].values())
            == int(t["total_ns"]) for t in traces)

        ep = full.publisher.pin()
        ctl = full.controller
        pinned = (ep is not None
                  and not ep.rf.flags.writeable
                  and not ep.category_idx.flags.writeable
                  and np.array_equal(ep.rf, ctl.current_rf)
                  and np.array_equal(ep.category_idx, ctl.current_cat))
        if pinned:
            pids = np.arange(min(64, len(manifest)))
            rv = ep.read_view(pids)
            pinned = (rv.replica_map.shape[0] == len(pids)
                      and rv.replica_map.shape[1] >= 1)
        inv["daemon_epoch_pinned"] = bool(pinned)

        # Kill/resume across the daemon's one-file checkpoint: stop via
        # the same flag the SIGTERM handler sets, after roughly half the
        # windows, then resume and require the stitch to be exact.
        ck = os.path.join(td, "daemon.npz")
        stop_at = max(2, int(spec.n_windows) // 2)
        a = StreamDaemon(_controller(spec, manifest, schedule),
                         DaemonConfig(max_windows=stop_at))
        a.run(log, checkpoint_path=ck)
        b = StreamDaemon(_controller(spec, manifest, schedule))
        b.run(log, checkpoint_path=ck)
        bep = b.publisher.pin()
        inv["daemon_resume_bit_identical"] = bool(
            _strip(a.records) + _strip(b.records) == _strip(full.records)
            and ep is not None and bep is not None
            and bep.epoch_id == ep.epoch_id
            and np.array_equal(bep.rf, ep.rf)
            and np.array_equal(bep.category_idx, ep.category_idx))
    return inv


#: Durability tiers a cell can ENTER (any window with the tally > 0) —
#: one coverage bit each.  The blind tiers plus the integrity layer's
#: ``true_lost`` (clean copies below the survivable minimum).
_COVERAGE_TIERS = ("lost", "at_risk", "under_replicated", "unreachable",
                   "correlated_risk")
#: Repair-outcome branches (window-record counters > 0): each is an
#: error-handling path Yuan et al.'s catastrophic failures hide in.
_COVERAGE_REPAIR = ("repair_failed", "repair_rebalanced",
                    "repair_corrupt_sources", "repair_deferred_budget",
                    "repair_deferred_backoff", "repair_deferred_no_source",
                    "repair_deferred_no_target",
                    "repair_deferred_partition")


def coverage_bits(records: list[dict], inv: dict,
                  alerts_fired: set) -> list[str]:
    """The cell's coverage fingerprint bits — the behaviour the run
    actually exhibited, extracted from what the window records, alert
    evaluation and invariant gating already capture (nothing new is
    instrumented):

    * ``fault:<kind>``   — a fault event of that kind APPLIED in-window,
    * ``tier:<name>``    — a durability tier entered (incl. true_lost),
    * ``repair:<branch>``— a repair outcome/deferral branch taken,
    * ``degraded:*`` / ``scrub:*`` / ``integrity:detected_*`` — degraded
      modes and detection paths hit,
    * ``serve:*`` / ``recluster:<trigger>`` — read-path and re-plan
      behaviour observed,
    * ``cause:<name>``   — a lineage cause consumed churn budget,
    * ``alert:<name>``   — an alert rule fired,
    * ``inv:<name>``     — an invariant branch evaluated non-vacuously
      (the conditional gates only materialize when their machinery ran).

    Sorted and deterministic; the search (scenarios/search.py) unions
    these across a corpus and chases cells that light up new bits.
    """
    bits: set[str] = set()
    for r in records:
        for ev in r.get("fault_events") or ():
            bits.add("fault:" + str(ev).split(":", 1)[0])
        d = r.get("durability")
        if d:
            for tier in _COVERAGE_TIERS:
                if d.get(tier, 0):
                    bits.add("tier:" + tier)
        integ = r.get("integrity")
        if integ:
            if integ.get("true_lost", 0):
                bits.add("tier:true_lost")
            for k in ("detected_scrub", "detected_read",
                      "detected_repair"):
                if integ.get(k, 0):
                    bits.add("integrity:" + k)
        for k in _COVERAGE_REPAIR:
            if r.get(k, 0):
                bits.add("repair:" + k[len("repair_"):])
        if r.get("degraded_kernel"):
            bits.add("degraded:kernel_fallback")
        sc = r.get("scrub")
        if sc:
            if sc.get("corrupt_found", 0):
                bits.add("scrub:detected")
            if sc.get("starved"):
                bits.add("scrub:starved")
        if r.get("recluster"):
            bits.add("recluster:" + str(r.get("recluster_trigger")))
        if int(r.get("reads_unavailable", 0) or 0):
            bits.add("serve:unavailable")
        if r.get("hotspot_files"):
            bits.add("serve:hotspot")
        for cause in r.get("causes") or ():
            bits.add("cause:" + cause)
    bits.update("alert:" + a for a in alerts_fired)
    bits.update("inv:" + k for k in inv)
    return sorted(bits)


def repro_line(spec: ScenarioSpec, suite: str | None = None,
               suite_seed: int = 0) -> str:
    """One line that reruns exactly this cell.  The suite form carries
    the sweep's ``--seed`` explicitly: a random cell is a function of
    (suite seed, index), so a repro without the seed would silently
    rebuild a DIFFERENT scenario under the same name."""
    if suite:
        return (f"python -m cdrs_tpu scenarios run --suite {suite} "
                f"--seed {int(suite_seed)} --cell {spec.name}")
    if getattr(spec, "_preset", None):
        return (f"python -m cdrs_tpu scenarios run "
                f"--preset {spec._preset}")
    return ("python -m cdrs_tpu scenarios run --spec '"
            + json.dumps(spec.to_dict()) + "'")


def run_cell(spec: ScenarioSpec, *, suite: str | None = None,
             suite_seed: int = 0) -> dict:
    """Run one cell end to end; returns the cell record (invariants,
    headline metrics, per-cell regress bench_records, repro line)."""
    t0 = time.perf_counter()
    manifest = generate_population(GeneratorConfig(
        n_files=spec.n_files, seed=spec.seed, nodes=spec.nodes))
    events, changed = build_events(spec, manifest)
    schedule = build_schedule(spec)
    ctl = _controller(spec, manifest, schedule)
    max_bytes = ctl.cfg.max_bytes_per_window
    res = ctl.run(events)
    records = res.records

    multi_domain = False
    if spec.racks or spec.topology is not None:
        multi_domain = len(set(
            ctl.cfg.topology.domains)) > 1 if ctl.cfg.topology else False
    has_corrupt = schedule is not None and any(
        ev.kind == "corrupt" for ev in schedule)
    has_ec = ctl._storage is not None and bool(
        (np.asarray(ctl._storage.ec_k) > 0).any())
    # One verified boundary task's worst-case charge: every reachable
    # copy of the largest file verification-read through the slowest
    # straggler the schedule ever installs (verify_sources charges
    # shard_bytes / throughput per copy; copies <= node count).
    budget_slack = 0
    if has_corrupt:
        min_factor = min([float(ev.factor) for ev in schedule
                          if ev.kind == "degrade"] + [1.0])
        budget_slack = int(
            len(spec.nodes)
            * int(np.max(np.asarray(manifest.size_bytes))) / min_factor)
    from ..obs.alerts import evaluate_records

    alerts_fired = {r["name"] for r in evaluate_records(records)
                    if r["fired"]}
    inv = _check_invariants(spec, records, max_bytes, budget_slack,
                            multi_domain, has_corrupt, has_ec,
                            schedule=schedule, alerts_fired=alerts_fired)

    if spec.daemon:
        inv.update(_daemon_invariants(spec, manifest, schedule, events,
                                      records))

    if spec.resume_window is not None:
        import os
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "cell.npz")
            a = _controller(spec, manifest, schedule).run(
                events, checkpoint_path=ck,
                max_windows=int(spec.resume_window))
            b = _controller(spec, manifest, schedule).run(
                events, checkpoint_path=ck)
            inv["resume_bit_identical"] = bool(
                _strip(a.records) + _strip(b.records) == _strip(records)
                and np.array_equal(b.rf, res.rf)
                and np.array_equal(b.category_idx, res.category_idx))

    summary = res.summary()
    churn = int(summary["bytes_migrated"]
                + summary.get("durability", {}).get("repair_bytes_total", 0))
    metrics: dict = {
        "windows": summary["windows"],
        "events": summary["events"],
        "reclusters": summary["reclusters"],
        "bytes_migrated_total": summary["bytes_migrated"],
        "churn_bytes_total": churn,
        "plan_hash": summary["final_plan_hash"],
        "files_changed_planted": int(changed.sum()),
    }
    if "durability" in summary:
        d = summary["durability"]
        metrics.update({
            "repair_bytes_total": d["repair_bytes_total"],
            "files_lost_max": d["files_lost_max"],
            "lost_final": d["lost_final"],
            "unavailable_reads": d["unavailable_reads"],
        })
    metrics["alerts_fired"] = sorted(alerts_fired)
    served = _served_windows(records)
    if served:
        metrics["latency_p99_ms_final"] = served[-1].get("latency_p99_ms")
    if "integrity" in summary:
        metrics["true_lost_final"] = summary["integrity"][
            "true_lost_final"]
        metrics["corrupt_copies_final"] = summary["integrity"][
            "corrupt_copies_final"]
    bench_records = [{
        "metric": f"scenario_{spec.name}_churn_bytes",
        "value": float(churn), "unit": "bytes", "direction": "lower",
        "backend": "numpy",
    }]
    if served and metrics.get("latency_p99_ms_final") is not None:
        bench_records.append({
            "metric": f"scenario_{spec.name}_p99_ms",
            "value": float(metrics["latency_p99_ms_final"]), "unit": "ms",
            "backend": "numpy",
        })
    from ..obs.aggregate import coverage_fingerprint

    coverage = coverage_bits(records, inv, alerts_fired)
    return {
        "cell": spec.name,
        "seed": spec.seed,
        "spec": spec.to_dict(),
        "invariants": inv,
        "ok": all(inv.values()),
        "metrics": metrics,
        "coverage": coverage,
        "fingerprint": coverage_fingerprint(coverage),
        "bench_records": bench_records,
        "seconds": round(time.perf_counter() - t0, 3),
        "repro": repro_line(spec, suite, suite_seed),
    }
