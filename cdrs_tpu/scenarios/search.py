"""Coverage-guided failure-space search: mutate, measure, shrink.

Yuan et al. (OSDI 2014) found that 92% of catastrophic distributed-
system failures live in error-handling paths that were never exercised,
and that almost all of them reproduce with <= 3 input events.  The
scenario matrix (presets + blind ``random_cell``s) only checks
combinations an author happened to write; this module upgrades it into
a *searcher* over the same axes:

* **mutate** — ``mutate_spec`` applies seeded, validity-preserving
  mutations to a corpus cell: fault-schedule event edits (add / drop /
  retime — crash, partition, corrupt, degrade, decommission, flaky,
  domain scopes when the cell has a geo topology), workload-curve and
  drift changes, and topology / storage / serve / scrub / budget / scale
  axis toggles.  Every candidate revalidates through ``ScenarioSpec``
  and a schedule preflight, so the search never wastes budget on specs
  the harness would reject.
* **measure** — each candidate runs through the ONE harness
  (``run_cell``) and is scored by its **coverage fingerprint**
  (harness ``coverage_bits``): fault kinds applied, durability tiers
  entered, repair/detection branches taken, degraded modes, alerts
  fired, lineage causes, and the invariant branches evaluated
  non-vacuously.  Cells lighting up NEW bits join the corpus and are
  re-mutated (AFL's queue discipline over scenario space).
* **shrink** — any invariant violation (or harness crash) goes through
  ``shrink_cell``: delta debugging (Zeller/Hildebrandt ddmin) over the
  fault-schedule event list, minimizing toward the <= 3-event repro the
  OSDI study promises, and emitting the existing one-line ``repro_line``
  format verbatim.

The corpus is banked as JSON under ``data/search_corpus/`` (one file
per kept cell, violations under ``violations/``), and ``distill_corpus``
greedily picks a minimal cell set covering the discovered frontier —
the curated greatest-hits that can ride CI instead of hand-written
cells only.  Search cells are named ``search-s<seed>-<fp8>`` (seed +
fingerprint prefix) so their regress/history metric keys can never
alias a hand-written preset or a ``random-s<seed>-<i>`` cell.

Everything is deterministic in ``--seed`` for a fixed cell budget; a
wall-clock budget (``--budget-seconds``) only truncates the same
sequence.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from ..faults.schedule import FaultEvent, FaultSchedule
from ..obs.aggregate import coverage_fingerprint
from .harness import build_schedule, repro_line, run_cell
from .presets import PRESETS, preset
from .spec import ScenarioSpec

__all__ = ["SEARCH_BASE", "distill_corpus", "load_corpus", "mutate_spec",
           "planted_violation_spec", "run_search", "search_cell_name",
           "shrink_cell", "triage_corpus"]

#: Cell-name prefixes reserved for generated cells; presets must never
#: use them (regress/history keys are ``scenario_<name>_*`` — a preset
#: named like a generated cell would alias its baselines).
RESERVED_NAME_PREFIXES = ("random-", "search-", "triage-")

#: Seed corpus of the search: cheap, numpy-only presets spanning the
#: fault / partition / storage / integrity / serve / drift domains.
#: (Expensive axes — daemon, mesh, kill/resume sampling — are stripped
#: by ``_sanitize``; the search optimizes cells-per-second.)
SEARCH_BASE: tuple[str, ...] = (
    "chaos-kill", "rack-partition", "cascade", "rolling-decommission",
    "storage-ec", "serve-chaos", "integrity-scrub", "diurnal",
    "gradual-drift")

_RACKS6 = "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6"
_NODES6 = ("dn1", "dn2", "dn3", "dn4", "dn5", "dn6")
#: Small inline EC config valid on any >= 3-node cell (the named
#: ``ec_archival`` preset stripes wider than small topologies allow).
_EC_SMALL = {"strategies": {"Archival": {"k": 2, "m": 1, "tier": "cold"}}}


def search_cell_name(seed: int, fingerprint: str) -> str:
    """``search-s<seed>-<fp8>``: the cell IS a function of the search
    seed and its behaviour set, so the name (and with it every
    ``scenario_<name>_*`` history key) can never alias a preset or a
    ``random-s<seed>-<i>`` cell — the PR-10 non-aliasing guarantee
    extended to search-discovered cells."""
    return f"search-s{int(seed)}-{fingerprint[:8]}"


def _sanitize(spec: ScenarioSpec, name: str | None = None) -> ScenarioSpec:
    """A search-ready copy of ``spec``: drop the axes whose gates encode
    per-preset AUTHOR expectations (alert expect/forbid lists, tuned
    p99/burn bounds) — a mutant tripping those is stale tuning, not a
    robustness finding — and the expensive sampling axes (daemon,
    kill/resume triple-runs, jax mesh) that would cut cells-per-second
    without adding fault-space reach.  Alert FIRING stays a coverage
    signal either way (``alert:*`` bits come from evaluate_records, not
    from the alerts axis)."""
    serve = spec.serve
    if serve is not None:
        serve = {k: v for k, v in serve.items()
                 if k not in ("p99_max_ms", "burn_max")}
    kw = dict(alerts=None, resume_window=None, daemon=False, serve=serve)
    if spec.mesh is not None:
        kw.update(mesh=None, backend="numpy")
    if name is not None:
        kw["name"] = name
    out = spec.replace(**kw)
    return out


def _frozen_faults(spec: ScenarioSpec) -> ScenarioSpec:
    """The spec with its faults axis decomposed to explicit event specs
    (templates and the seeded random generator frozen into the concrete
    events they produce), so event-level edits can apply."""
    if spec.faults is None:
        return spec
    sched = build_schedule(spec)
    return spec.replace(faults={"specs": [e.spec() for e in sched]})


def _preflight(spec: ScenarioSpec) -> None:
    """Reject a candidate the harness would reject, without running it:
    the schedule must build, and after domain-scope expansion every
    event node must exist in the topology."""
    sched = build_schedule(spec)
    if sched is None:
        return
    if spec.topology is not None:
        from ..cluster import ClusterTopology

        sched = sched.expand_domains(
            ClusterTopology.from_hierarchy(spec.topology))
    sched.validate_nodes(spec.nodes)


# -- mutation operators ------------------------------------------------------
# Each operator takes (spec, rng) and returns a mutated spec or None
# (not applicable).  Operators may raise ValueError (spec revalidation);
# mutate_spec treats that as "try another draw".

def _events_of(spec: ScenarioSpec) -> list[FaultEvent]:
    sched = build_schedule(spec)
    return sched.events() if sched is not None else []


def _with_events(spec: ScenarioSpec,
                 events: list[FaultEvent]) -> ScenarioSpec | None:
    if not events:
        if spec.scrub is not None:
            return None  # scrub requires a faults axis
        return spec.replace(faults=None)
    sched = FaultSchedule.from_events(events)
    return spec.replace(faults={"specs": [e.spec() for e in sched]})


def _op_fault_add(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    events = _events_of(spec)
    nw = int(spec.n_windows)
    # Healing faults (spans) may land anywhere that heals by the end;
    # destructive ones (decommission) stay in the first ~60% so repair
    # has windows to act — a "loses data because nothing could ever
    # repair it" cell is noise, not a finding.
    kind = ("crash", "partition", "flaky", "degrade", "corrupt",
            "decommission")[int(rng.integers(6))]
    node = str(spec.nodes[int(rng.integers(len(spec.nodes)))])
    if kind == "decommission":
        n_dec = sum(1 for e in events if e.kind == "decommission")
        if n_dec + 1 >= max(len(spec.nodes) // 2, 1):
            return None  # keep the cluster survivable by construction
        w = 1 + int(rng.integers(max(int(nw * 0.6), 2)))
        ev = [FaultEvent(w, "decommission", node)]
    elif kind == "corrupt":
        w = 1 + int(rng.integers(max(nw - 3, 2)))
        if rng.random() < 0.25:
            ev = [FaultEvent(w, "corrupt", node,
                             file=int(rng.integers(spec.n_files)))]
        else:
            ev = [FaultEvent(w, "corrupt", node,
                             fail_prob=round(float(
                                 rng.uniform(0.05, 0.6)), 3))]
    else:
        lo = 1 + int(rng.integers(max(nw - 4, 2)))
        hi = min(lo + int(rng.integers(1, 4)) - 1, nw - 2)
        hi = max(hi, lo)
        if kind == "partition":
            group = node
            if len(spec.nodes) > 2 and rng.random() < 0.5:
                other = str(spec.nodes[int(rng.integers(len(spec.nodes)))])
                if other != node:
                    group = f"{node}+{other}"
            ev = [FaultEvent(lo, "partition", group),
                  FaultEvent(hi + 1, "heal", group)]
        elif kind == "crash":
            ev = [FaultEvent(lo, "crash", node),
                  FaultEvent(hi + 1, "recover", node)]
        elif kind == "flaky":
            ev = [FaultEvent(lo, "flaky", node,
                             fail_prob=round(float(
                                 rng.uniform(0.2, 0.9)), 3)),
                  FaultEvent(hi + 1, "unflaky", node)]
        else:
            ev = [FaultEvent(lo, "degrade", node,
                             factor=round(float(
                                 rng.uniform(0.1, 0.6)), 3)),
                  FaultEvent(hi + 1, "restore", node)]
    return _with_events(spec, events + ev)


def _op_fault_storm(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    """Correlated multi-node outage: overlapping crash spans on a
    random 2..4-node subset (healing by the end).  One draw reaches the
    states only SIMULTANEOUS failures produce — transient blind loss,
    repairs with no live source/target — that single-event edits need
    many lucky iterations to stack up."""
    if len(spec.nodes) < 3:
        return None
    events = _events_of(spec)
    n_hit = int(rng.integers(2, min(len(spec.nodes) - 1, 4) + 1))
    hit = list(rng.choice(len(spec.nodes), size=n_hit, replace=False))
    nw = int(spec.n_windows)
    lo = 1 + int(rng.integers(max(nw - 5, 2)))
    for j, ni in enumerate(hit):
        w0 = min(lo + int(rng.integers(2)), nw - 3)
        w1 = max(min(w0 + int(rng.integers(1, 3)), nw - 2), w0)
        node = str(spec.nodes[int(ni)])
        events += [FaultEvent(w0, "crash", node),
                   FaultEvent(w1 + 1, "recover", node)]
    return _with_events(spec, events)


def _op_fault_scope(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    """Add a whole-DOMAIN correlated event (geo cells only): crash or
    partition a random level:name scope — the failure mode a hierarchy
    exists to survive."""
    if spec.topology is None:
        return None
    events = _events_of(spec)
    levels = list(spec.topology.get("levels") or ())
    if not levels:
        return None
    level = levels[int(rng.integers(len(levels)))]
    domains = sorted(spec.topology.get(level) or ())
    if not domains:
        return None
    dom = domains[int(rng.integers(len(domains)))]
    nw = int(spec.n_windows)
    lo = 1 + int(rng.integers(max(nw - 5, 2)))
    hi = max(min(lo + int(rng.integers(1, 4)) - 1, nw - 2), lo)
    kind = "crash" if rng.random() < 0.5 else "partition"
    ev = [FaultEvent(lo, kind, f"{level}:{dom}"),
          FaultEvent(hi + 1,
                     "recover" if kind == "crash" else "heal",
                     f"{level}:{dom}")]
    return _with_events(spec, events + ev)


def _op_fault_drop(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    events = _events_of(spec)
    if not events:
        return None
    del events[int(rng.integers(len(events)))]
    return _with_events(spec, events)


def _op_fault_retime(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    events = _events_of(spec)
    if not events:
        return None
    i = int(rng.integers(len(events)))
    shift = int(rng.integers(1, 4)) * (1 if rng.random() < 0.5 else -1)
    w = max(events[i].window + shift, 0)
    sched = FaultSchedule.from_events(events).retime(i, w)
    return _with_events(spec, sched.events())


def _op_workload(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    kind = ("poisson", "diurnal", "flash_crowd")[int(rng.integers(3))]
    wl: dict = {"kind": kind}
    if kind == "diurnal":
        wl.update(amplitude=round(float(rng.uniform(0.3, 0.95)), 3),
                  phase=round(float(rng.uniform(0.0, 6.28)), 3))
    elif kind == "flash_crowd":
        wl.update(start_frac=round(float(rng.uniform(0.2, 0.6)), 3),
                  duration_frac=round(float(rng.uniform(0.05, 0.3)), 3),
                  boost=round(float(rng.uniform(15.0, 60.0)), 1),
                  cohort=("archival", "hot")[int(rng.integers(2))])
    drift = spec.drift if kind == "poisson" else None
    return spec.replace(workload=wl, drift=drift)


def _op_drift(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    if (spec.workload or {}).get("kind", "poisson") != "poisson":
        return None
    if spec.drift is not None and rng.random() < 0.3:
        return spec.replace(drift=None)
    kind = ("flip", "gradual", "adversarial")[int(rng.integers(3))]
    d: dict = {"kind": kind}
    if kind == "flip":
        d["at_frac"] = round(float(rng.uniform(0.3, 0.7)), 3)
    else:
        d.update(start_frac=round(float(rng.uniform(0.2, 0.4)), 3),
                 end_frac=round(float(rng.uniform(0.6, 0.85)), 3))
        if kind == "gradual":
            d["steps"] = int(rng.integers(2, 5))
        else:
            d["cycles"] = int(rng.integers(2, 5))
    return spec.replace(drift=d, drift_threshold=0.02)


def _op_serve(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    if spec.serve is not None:
        if spec.elastic is not None:
            return None  # elastic requires the serve axis
        return spec.replace(serve=None)
    return spec.replace(serve={
        "policy": ("p2c", "least_loaded", "random")[int(rng.integers(3))],
        "verify_reads": bool(rng.random() < 0.5)})


def _op_scrub(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    if spec.scrub is not None:
        return spec.replace(scrub=None)
    if spec.faults is None:
        return None
    return spec.replace(
        scrub=int(rng.integers(50, 500)) * 1_000_000)


def _op_storage(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    cur = spec.storage
    options: list = [None, "replicate"]
    if len(spec.nodes) >= 3:
        options.append(_EC_SMALL)
    options = [o for o in options if o != cur]
    return spec.replace(
        storage=options[int(rng.integers(len(options)))])


def _op_budget(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    return spec.replace(
        budget_frac=round(float(rng.uniform(0.08, 0.5)), 3))


def _op_racks(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    if spec.topology is not None:
        return None  # geo hierarchy subsumes the rack axis
    if spec.racks is None:
        # flat -> racked: the 5-node default grows to the racked 6.
        if set(spec.nodes) - set(_NODES6):
            return None
        return spec.replace(nodes=_NODES6, racks=_RACKS6)
    # racked -> flat: keep the node set, drop the domain map.
    return spec.replace(racks=None)


def _op_scale(spec: ScenarioSpec, rng) -> ScenarioSpec | None:
    if rng.random() < 0.5:
        return spec.replace(n_files=int(rng.integers(150, 450)))
    nw = int(np.clip(spec.n_windows + int(rng.integers(-2, 3)), 8, 20))
    return spec.replace(n_windows=nw)


#: (name, operator) — name order is the deterministic draw space.
MUTATORS: tuple = (
    ("fault_add", _op_fault_add),
    ("fault_add", _op_fault_add),      # double weight: the fault axis
    ("fault_storm", _op_fault_storm),  # is the failure-space frontier
    ("fault_scope", _op_fault_scope),
    ("fault_drop", _op_fault_drop),
    ("fault_retime", _op_fault_retime),
    ("workload", _op_workload),
    ("drift", _op_drift),
    ("serve", _op_serve),
    ("scrub", _op_scrub),
    ("storage", _op_storage),
    ("budget", _op_budget),
    ("racks", _op_racks),
    ("scale", _op_scale),
)


def mutate_spec(spec: ScenarioSpec, rng,
                n_ops: int = 1, max_tries: int = 24
                ) -> tuple[ScenarioSpec, list[str]] | None:
    """Apply ``n_ops`` seeded mutations to ``spec``, revalidating after
    each (ScenarioSpec invariants + schedule preflight).  Returns
    ``(mutant, [operator names])`` or None when ``max_tries`` draws
    could not produce a valid mutant.  Deterministic in ``rng``."""
    cur = _frozen_faults(_sanitize(spec))
    applied: list[str] = []
    tries = 0
    while len(applied) < int(n_ops) and tries < max_tries:
        tries += 1
        name, op = MUTATORS[int(rng.integers(len(MUTATORS)))]
        try:
            cand = op(cur, rng)
            if cand is None or cand.to_dict() == cur.to_dict():
                continue
            _preflight(cand)
        except ValueError:
            continue
        cur = cand
        applied.append(name)
    if not applied:
        return None
    return cur, applied


# -- shrinking (delta debugging over the fault schedule) ---------------------

def _failure_signature(spec: ScenarioSpec) -> frozenset | None:
    """What the cell did wrong: the set of failed invariants, or the
    exception class for harness crashes; None = the cell is green."""
    try:
        res = run_cell(spec)
    except Exception as err:  # a crash is a finding, not an abort
        return frozenset({f"error:{type(err).__name__}"})
    failed = frozenset(k for k, v in res["invariants"].items() if not v)
    return failed or None


def shrink_cell(spec: ScenarioSpec, *, max_runs: int = 200) -> dict:
    """Minimize a failing cell's fault schedule by delta debugging
    (ddmin): find a 1-minimal event subset that still reproduces the
    original failure (at least one originally-failed invariant still
    fails, or the same exception class).  Everything but the fault
    axis stays fixed — the OSDI-2014 claim is about EVENT count, and
    the schedule is the cell's event dimension.

    Returns ``{"spec", "events", "n_events", "failed", "repro",
    "oracle_runs"}``; deterministic for a given spec."""
    frozen = _frozen_faults(spec)
    original = _failure_signature(frozen)
    if original is None:
        raise ValueError(
            f"cell {spec.name!r} is green — nothing to shrink")
    events = _events_of(frozen)
    cache: dict[tuple, bool] = {}
    runs = 0

    def fails(subset: list[FaultEvent]) -> bool:
        nonlocal runs
        key = tuple(e.spec() for e in subset)
        if key in cache:
            return cache[key]
        if runs >= max_runs:
            return False  # budget exhausted: stop reducing
        cand = _with_events(frozen, subset)
        if cand is None:
            cache[key] = False
            return False
        runs += 1
        sig = _failure_signature(cand)
        out = sig is not None and bool(sig & original)
        cache[key] = out
        return out

    # ddmin (Zeller & Hildebrandt 2002): try subsets, then complements,
    # refining granularity until 1-minimal.
    n = 2
    while len(events) >= 2:
        chunk = max(len(events) // n, 1)
        subsets = [events[i:i + chunk]
                   for i in range(0, len(events), chunk)]
        reduced = False
        for sub in subsets:
            if len(sub) < len(events) and fails(sub):
                events, n, reduced = sub, 2, True
                break
        if not reduced:
            for i in range(len(subsets)):
                comp = [e for j, s in enumerate(subsets) if j != i
                        for e in s]
                if 0 < len(comp) < len(events) and fails(comp):
                    events, n, reduced = comp, max(n - 1, 2), True
                    break
        if not reduced:
            if n >= len(events):
                break
            n = min(len(events), 2 * n)

    shrunk = _with_events(frozen, events)
    final_sig = _failure_signature(shrunk)
    return {
        "spec": shrunk.to_dict(),
        "events": [e.spec() for e in events],
        "n_events": len(events),
        "failed": sorted(final_sig or ()),
        "repro": repro_line(shrunk),
        "oracle_runs": runs,
    }


def planted_violation_spec(seed: int = 0) -> ScenarioSpec:
    """The designed-bad oracle cell: on a 2-node rf-2 cluster, silent
    corruption of EVERY copy on dn2 (nothing verifies reads, no scrub)
    followed by decommission of dn1 — the last clean holder — leaves
    every file with only rotten bytes: ``true_lost`` = all files and
    ``zero_silent_loss`` fails, while the blind tiers still count dn2's
    copies as live.  Either event alone is survivable.  The noise spans
    (flaky/degrade/an early healed crash) are what the shrinker must
    strip: the known-minimal cause is exactly
    ``{corrupt:dn2@3:1, decommission:dn1@5}``."""
    return ScenarioSpec(
        name="planted-silent-loss", n_files=80, seed=int(seed),
        duration=960.0, n_windows=8, k=6, nodes=("dn1", "dn2"),
        faults={"specs": [
            "crash:dn2@1-1",
            "flaky:dn2@2-3:0.5",
            "degrade:dn1@2-4:0.5",
            "corrupt:dn2@3:1",
            "decommission:dn1@5",
        ]})


# -- corpus ------------------------------------------------------------------

def load_corpus(corpus_dir: str) -> list[dict]:
    """Banked corpus entries (green cells only), name-sorted for
    determinism.  Missing directory = empty corpus."""
    out = []
    if not os.path.isdir(corpus_dir):
        return out
    for fn in sorted(os.listdir(corpus_dir)):
        if not fn.endswith(".json") or fn == "distilled.json":
            continue
        path = os.path.join(corpus_dir, fn)
        if not os.path.isfile(path):
            continue
        with open(path, encoding="utf-8") as f:
            e = json.load(f)
        if "spec" in e and "coverage" in e:
            out.append(e)
    return out


def _bank(corpus_dir: str, entry: dict, sub: str | None = None) -> str:
    d = os.path.join(corpus_dir, sub) if sub else corpus_dir
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{entry['name']}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(entry, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def triage_corpus(corpus_dir: str, progress=None) -> dict:
    """Promote banked violations into named regression-locked cells.

    Every entry under ``<corpus_dir>/violations/`` is a bug the search
    once found; after the fix lands it must rerun GREEN forever.  This
    reruns each one (preferring the shrunk minimal spec) under a stable
    ``triage-*`` name and returns the same ``{"cells", "names"}`` shape
    ``distill_corpus`` emits — so the triage file plugs straight into
    the sweep's extra-cells slot and CI regression-locks the whole
    violation history.  ``ok`` is False while ANY violation still
    reproduces (the fix has not actually landed)."""
    vdir = os.path.join(corpus_dir, "violations")
    entries = []
    if os.path.isdir(vdir):
        for fn in sorted(os.listdir(vdir)):
            path = os.path.join(vdir, fn)
            if not fn.endswith(".json") or not os.path.isfile(path):
                continue
            with open(path, encoding="utf-8") as f:
                e = json.load(f)
            if isinstance(e, dict) and "spec" in e:
                entries.append(e)
    t0 = time.perf_counter()
    results, cells, names = [], [], []
    ok = True
    for e in entries:
        src = str(e.get("name") or "unnamed")
        name = "triage-" + (src[len("search-"):]
                            if src.startswith("search-") else src)
        doc = dict((e.get("shrunk") or {}).get("spec") or e["spec"])
        doc["name"] = name
        spec = ScenarioSpec.from_dict(doc)
        cell = run_cell(spec)
        green = bool(cell["ok"])
        ok = ok and green
        results.append({
            "name": name,
            "source": src,
            "ok": green,
            "failed": sorted(k for k, v in cell["invariants"].items()
                             if not v),
            "repro": cell["repro"],
            "seconds": cell["seconds"],
        })
        if progress is not None:
            progress(f"  [{'ok  ' if green else 'FAIL'}] {name} "
                     f"(from {src})")
        cells.append(spec.to_dict())
        names.append(name)
    return {
        "cells": cells,
        "names": names,
        "results": results,
        "n_violations": len(entries),
        "ok": ok,
        "seconds": round(time.perf_counter() - t0, 3),
    }


def distill_corpus(entries: list[dict]) -> dict:
    """Deterministic greedy set cover over the banked corpus: the
    minimal-ish cell list whose union covers every coverage bit any
    entry exhibits.  Ties break toward cheaper (seconds) then
    lexicographically earlier names, so the same corpus always distills
    to the same list."""
    remaining = {b for e in entries for b in e.get("coverage") or ()}
    covered: set[str] = set()
    chosen: list[dict] = []
    pool = list(entries)
    while remaining:
        scored = sorted(
            pool,
            key=lambda e: (-len(set(e.get("coverage") or ()) & remaining),
                           float(e.get("seconds", 0.0)),
                           str(e.get("name"))))
        best = scored[0] if scored else None
        if best is None or not (set(best.get("coverage") or ())
                                & remaining):
            break
        chosen.append(best)
        pool.remove(best)
        got = set(best.get("coverage") or ())
        covered |= got
        remaining -= got
    return {
        "cells": [e["spec"] for e in chosen],
        "names": [e["name"] for e in chosen],
        "coverage_bits": len(covered),
        "fingerprint": coverage_fingerprint(covered),
    }


# -- the search loop ---------------------------------------------------------

def run_search(*, seed: int = 0, budget_cells: int = 50,
               budget_seconds: float | None = None,
               corpus_dir: str = "data/search_corpus",
               base: tuple = SEARCH_BASE, shrink: bool = True,
               bank: bool = True, progress=None) -> dict:
    """The coverage-guided search loop (see module docstring).

    Deterministic in ``seed`` for a fixed ``budget_cells`` (per-
    iteration rng streams are ``[seed, 19, i]``); ``budget_seconds``
    only truncates the same sequence.  ``bank=False`` runs without
    touching ``corpus_dir`` (the benchmark's A/B mode)."""
    t0 = time.perf_counter()
    say = progress or (lambda line: None)

    for name in PRESETS:
        if name.startswith(RESERVED_NAME_PREFIXES):  # pragma: no cover
            raise ValueError(
                f"preset {name!r} uses a reserved generated-cell prefix")

    # Seed corpus: banked entries (already measured) + base presets.
    banked = load_corpus(corpus_dir) if bank else []
    frontier: set[str] = set()
    parents: list[ScenarioSpec] = []
    for e in banked:
        frontier |= set(e["coverage"])
        parents.append(ScenarioSpec.from_dict(e["spec"]))
    baseline_banked = set(frontier)
    for name in base:
        sp = _sanitize(preset(name), name=name)
        res = run_cell(sp)
        frontier |= set(res["coverage"])
        parents.append(sp)
    baseline = set(frontier)
    say(f"seed corpus: {len(parents)} cells "
        f"({len(banked)} banked), {len(baseline)} coverage bits")

    discovered: list[ScenarioSpec] = []
    kept: list[dict] = []
    violations: list[dict] = []
    cells_run = 0
    iterations = 0
    for i in range(int(budget_cells)):
        if budget_seconds is not None \
                and time.perf_counter() - t0 > float(budget_seconds):
            say(f"wall budget hit after {i} iterations")
            break
        iterations = i + 1
        rng = np.random.default_rng([int(seed), 19, i])
        # AFL-ish queue bias: half the draws mutate a recent discovery.
        if discovered and rng.random() < 0.5:
            parent = discovered[int(rng.integers(len(discovered)))]
        else:
            parent = parents[int(rng.integers(len(parents)))]
        m = mutate_spec(parent, rng, n_ops=1 + int(rng.integers(4)))
        if m is None:
            continue
        cand, ops = m
        cand = cand.replace(name=f"search-cand-{i}")
        try:
            res = run_cell(cand)
        except Exception as err:
            cells_run += 1
            v = {"name": f"search-s{seed}-err-{i}", "iteration": i,
                 "parent": parent.name, "ops": ops,
                 "error": f"{type(err).__name__}: {err}",
                 "spec": cand.to_dict(), "repro": repro_line(cand)}
            if shrink:
                v["shrunk"] = shrink_cell(cand)
            violations.append(v)
            if bank:
                _bank(corpus_dir, v, sub="violations")
            say(f"[{i}] CRASH {type(err).__name__} via {parent.name} "
                f"({'+'.join(ops)})")
            continue
        cells_run += 1
        bits = set(res["coverage"])
        new = bits - frontier
        frontier |= new
        if not res["ok"]:
            fp = res["fingerprint"]
            vname = search_cell_name(seed, fp) + "-bad"
            final = cand.replace(name=vname)
            v = {"name": vname, "iteration": i, "parent": parent.name,
                 "ops": ops, "spec": final.to_dict(),
                 "failed": sorted(k for k, ok in res["invariants"].items()
                                  if not ok),
                 "coverage": sorted(bits), "fingerprint": fp,
                 "repro": repro_line(final)}
            if shrink:
                v["shrunk"] = shrink_cell(final)
            violations.append(v)
            if bank:
                _bank(corpus_dir, v, sub="violations")
            say(f"[{i}] VIOLATION {','.join(v['failed'])} "
                f"via {parent.name} ({'+'.join(ops)})"
                + (f" -> {v['shrunk']['n_events']} events"
                   if shrink else ""))
            continue
        if new:
            fp = res["fingerprint"]
            cname = search_cell_name(seed, fp)
            final = cand.replace(name=cname)
            entry = {"name": cname, "iteration": i,
                     "parent": parent.name, "ops": ops,
                     "spec": final.to_dict(),
                     "coverage": sorted(bits), "fingerprint": fp,
                     "new_bits": sorted(new),
                     "seconds": res["seconds"],
                     "repro": repro_line(final)}
            kept.append(entry)
            discovered.append(final)
            parents.append(final)
            if bank:
                _bank(corpus_dir, entry)
            say(f"[{i}] +{len(new)} bits ({cname}) via {parent.name} "
                f"({'+'.join(ops)}): "
                + ", ".join(sorted(new)[:4])
                + ("..." if len(new) > 4 else ""))
    return {
        "seed": int(seed),
        "budget_cells": int(budget_cells),
        "budget_seconds": budget_seconds,
        "iterations": iterations,
        "cells_run": cells_run,
        "base": list(base),
        "baseline_bits": len(baseline),
        "baseline_banked_bits": len(baseline_banked),
        "coverage_bits": len(frontier),
        "coverage": sorted(frontier),
        "fingerprint": coverage_fingerprint(frontier),
        "new_coverage_cells": len(kept),
        "kept": kept,
        "violations": violations,
        "seconds": round(time.perf_counter() - t0, 3),
    }
