"""Drop-in reference API shims.

Users of the reference import ``kmeans`` from ``kmeans_plusplus`` and
``ClusterClassifier`` from ``scoring`` (reference: src/main.py:12-13).  This
module exposes the same call signatures backed by the new framework, so a
reference user can switch with an import change:

    from cdrs_tpu.compat.reference_api import kmeans, ClusterClassifier

Differences from the reference, by design (SURVEY.md §6.1):
* no crash for n > 10,000 (integer max_iter);
* empty-cluster reseeding respects ``random_state``;
* importing this module does NOT run a demo at import time (the reference's
  scoring.py executes a hardcoded example on import, scoring.py:133-175 —
  that example lives on as tests/test_scoring.py::test_reference_inline_example).
"""

from __future__ import annotations

import numpy as np

from ..config import ScoringConfig
from ..ops.kmeans_np import kmeans  # noqa: F401  (re-export, reference signature)
from ..ops.scoring_np import classify_medians

__all__ = ["kmeans", "ClusterClassifier"]


class ClusterClassifier:
    """Dict-in/dict-out classifier matching reference src/scoring.py:13-130."""

    def __init__(self, global_medians, weights, directions, replication_factors):
        self.global_medians = dict(global_medians)
        self.weights = {c: dict(w) for c, w in weights.items()}
        self.directions = {c: dict(d) for c, d in directions.items()}
        self.replication_factors = dict(replication_factors)
        self.features = tuple(global_medians.keys())
        self.categories = tuple(weights.keys())

    def _config(self) -> ScoringConfig:
        return ScoringConfig(
            features=self.features,
            global_medians=self.global_medians,
            weights=self.weights,
            directions=self.directions,
            replication_factors=self.replication_factors,
            categories=self.categories,
        )

    def f(self, x):
        return x ** 2  # reference: src/scoring.py:28-38

    def compute_cluster_medians(self, clusters):
        # reference: src/scoring.py:40-55
        return {
            name: {p: float(np.median(v)) for p, v in feats.items()}
            for name, feats in clusters.items()
        }

    def score_category(self, cluster_medians, category):
        # reference: src/scoring.py:57-84 — kept scalar for API parity.
        score = 0.0
        for p, m in cluster_medians.items():
            delta = m - self.global_medians[p]
            d = self.directions[category][p]
            if category == "Moderate":
                if abs(delta) < 0.1:
                    score += self.weights[category][p] * self.f(1 - abs(delta))
            elif d == 0 or np.sign(delta) == d:
                score += self.weights[category][p] * self.f(abs(delta))
        return score

    def classify_cluster(self, cluster_medians):
        # reference: src/scoring.py:86-109
        medians = np.asarray(
            [[cluster_medians[f] for f in self.features]], dtype=np.float64)
        winner, _ = classify_medians(medians, self._config())
        return self.categories[int(winner[0])]

    def classify(self, clusters):
        # reference: src/scoring.py:111-130
        medians = self.compute_cluster_medians(clusters)
        return {name: self.classify_cluster(m) for name, m in medians.items()}
