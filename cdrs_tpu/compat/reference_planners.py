"""The legacy object-at-a-time planners, kept as the equivalence oracle.

PR 8 rebuilt the per-window control plane as structure-of-arrays
(control/migrate.py, faults/repair.py).  The replaced implementations —
one Python ``PlanMove`` object per changed file, admission via a Python
``sorted`` loop, one ``RepairTask`` ``while`` loop per damaged file — live
on here, verbatim, for two consumers:

* the **equivalence property tests** (tests/test_plan_vectorized.py):
  random scenarios across CDRS_CHAOS_SEED assert the vectorized planners
  reproduce the admitted/deferred sets and byte accounting of this path
  bit-for-bit;
* **benchmarks/plan_bench.py**: the >= 10x planner wall-clock criterion is
  measured against this path on the same host, paired interleaved rounds.

Nothing in the production loop imports this module.  It intentionally
preserves the old algorithmic costs (O(n) object churn, O(n log n) Python
sorts) — do not "optimize" it, its slowness is the baseline.
"""

from __future__ import annotations

import numpy as np

from ..control.migrate import _NEVER, PlanMove
from ..faults.repair import _MAX_BACKOFF, RepairReport, RepairTask, _fail_roll

__all__ = ["reference_plan_diff", "ReferenceMigrationScheduler",
           "ReferenceRepairScheduler"]


def reference_plan_diff(rf_old, rf_new, cat_old, cat_new, size_bytes,
                        priority=None, move_bytes=None) -> list[PlanMove]:
    """The pre-SoA ``plan_diff``: one ``PlanMove`` per changed file."""
    rf_old = np.asarray(rf_old, dtype=np.int64)
    rf_new = np.asarray(rf_new, dtype=np.int64)
    cat_old = np.asarray(cat_old, dtype=np.int64)
    cat_new = np.asarray(cat_new, dtype=np.int64)
    size_bytes = np.asarray(size_bytes, dtype=np.int64)
    n = rf_old.shape[0]
    prio = np.zeros(n) if priority is None else np.asarray(priority,
                                                           dtype=np.float64)
    changed = np.flatnonzero((rf_new != rf_old) | (cat_new != cat_old))
    if move_bytes is None:
        bytes_moved = size_bytes * np.maximum(rf_new - rf_old, 0)
    else:
        bytes_moved = np.asarray(move_bytes, dtype=np.int64)
    return [PlanMove(file_index=int(i), rf_old=int(rf_old[i]),
                     rf_new=int(rf_new[i]), cat_old=int(cat_old[i]),
                     cat_new=int(cat_new[i]), bytes_moved=int(bytes_moved[i]),
                     priority=float(prio[i]))
            for i in changed]


class ReferenceMigrationScheduler:
    """The pre-SoA ``MigrationScheduler``: dict backlog, Python-loop
    admission.  Same constructor and ``schedule`` contract as the
    vectorized scheduler; ``schedule`` returns a ``list[PlanMove]``."""

    def __init__(self, n_files: int, max_bytes_per_window: int | None = None,
                 max_files_per_window: int | None = None,
                 hysteresis_windows: int = 0):
        self.n_files = int(n_files)
        self.max_bytes = max_bytes_per_window
        self.max_files = max_files_per_window
        self.hysteresis = int(hysteresis_windows)
        self.backlog: dict[int, PlanMove] = {}
        self.last_moved = np.full(n_files, _NEVER, dtype=np.int64)
        self.last_deferred_hysteresis = 0
        self.last_deferred_budget = 0

    def submit(self, moves) -> None:
        self.backlog = {m.file_index: m for m in moves}

    def schedule(self, window_index: int, *, bytes_reserved: int = 0,
                 files_reserved: int = 0) -> list[PlanMove]:
        order = sorted(self.backlog.values(),
                       key=lambda m: (-m.priority, m.file_index))
        applied: list[PlanMove] = []
        bytes_used = int(bytes_reserved)
        self.last_deferred_hysteresis = 0
        self.last_deferred_budget = 0
        for m in order:
            if self.max_files is not None \
                    and len(applied) + int(files_reserved) >= self.max_files:
                break
            if window_index < int(self.last_moved[m.file_index]) \
                    + 1 + self.hysteresis:
                self.last_deferred_hysteresis += 1
                continue
            if self.max_bytes is not None and m.bytes_moved > 0:
                over = bytes_used + m.bytes_moved > self.max_bytes
                first = bytes_used == 0 and self.max_bytes > 0
                if over and not first:
                    self.last_deferred_budget += 1
                    continue
            applied.append(m)
            bytes_used += m.bytes_moved
        for m in applied:
            del self.backlog[m.file_index]
            self.last_moved[m.file_index] = window_index
        return applied

    @property
    def backlog_bytes(self) -> int:
        return sum(m.bytes_moved for m in self.backlog.values())


class ReferenceRepairScheduler:
    """The pre-SoA ``RepairScheduler``: dict-of-``RepairTask`` backlog,
    per-task Python ``while`` loop.  Drives the SAME ``ClusterState`` API
    as the vectorized scheduler, so equivalence runs mutate two separate
    states from identical starting conditions and compare everything."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.backlog: dict[int, RepairTask] = {}

    def sync(self, state, target_rf: np.ndarray) -> None:
        state.trim_excess(target_rf)
        fids, _reach, _eff = state.repair_needs(target_rf)
        corr = np.flatnonzero(state.correlated_mask(target_rf))
        work = np.union1d(fids, corr)
        self.backlog = {int(f): self.backlog.get(int(f), RepairTask(int(f)))
                        for f in work}

    def _charge(self, state, fid: int, target: int) -> int:
        read_bytes = int(state.repair_read_bytes(fid))
        node_reach = state.node_reachable()
        row = state.replica_map[fid]
        srcs = [float(state.node_throughput[int(x)]) for x in row[row >= 0]
                if node_reach[int(x)]]
        k = int(state.ec_k[fid])
        if k > 1 and srcs:
            srcs.sort(reverse=True)
            src_m = srcs[min(k, len(srcs)) - 1]
        else:
            src_m = max(srcs, default=1.0)
        m = min(src_m, float(state.node_throughput[target]))
        return int(np.ceil(read_bytes / max(m, 1e-9)))

    def schedule(self, window: int, state, target_rf: np.ndarray,
                 cat: np.ndarray, *, max_bytes: int | None = None,
                 max_files: int | None = None) -> RepairReport:
        rep = RepairReport()
        if not self.backlog:
            return rep
        live = state.live_counts()
        reach = state.reachable_counts()
        eff = state.effective_target(target_rf)
        corr = state.correlated_mask(target_rf)
        rf_vec = np.asarray(target_rf, dtype=np.int64)
        need = state.min_live

        def prio(t: RepairTask):
            f = t.file_index
            if reach[f] < need[f]:
                tier = 0          # lost / wholly stranded
            elif reach[f] == need[f]:
                tier = 1          # at risk: one failure from loss
            elif reach[f] < eff[f]:
                tier = 2
            else:
                tier = 3          # correlated-risk rebalance: spread last
            return (tier, -int(rf_vec[f]), f)

        order = sorted(self.backlog.values(), key=prio)
        touched: set[int] = set()
        healed: list[int] = []
        for task in order:
            f = task.file_index
            if task.next_window > window:
                rep.deferred_backoff += 1
                continue
            if reach[f] < need[f]:
                if live[f] >= need[f]:
                    if task.stall_until > window:
                        rep.deferred_backoff += 1
                    else:
                        task.stalled += 1
                        task.stall_until = window + min(2 ** task.stalled,
                                                        _MAX_BACKOFF)
                        rep.deferred_partition += 1
                else:
                    rep.deferred_no_source += 1
                continue
            if max_files is not None and f not in touched \
                    and len(touched) >= max_files:
                rep.deferred_budget += 1
                continue
            size = int(state.shard_bytes[f])
            copy = 0
            rebalance = reach[f] >= eff[f] and bool(corr[f])
            spread_fixed = False
            while reach[f] < eff[f] or (rebalance and copy == 0):
                target = state.pick_repair_target(
                    f, rotate=task.attempts + copy,
                    new_domain_only=rebalance)
                if target < 0:
                    rep.deferred_no_target += 1
                    break
                charge = self._charge(state, f, target)
                if max_bytes is not None:
                    over = rep.bytes_used + charge > max_bytes
                    first = rep.bytes_used == 0 and max_bytes > 0
                    if over and not first:
                        rep.deferred_budget += 1
                        break
                p = float(state.node_fail_prob[target])
                if p > 0.0 and _fail_roll(self.seed, window, f,
                                          task.attempts, copy) < p:
                    task.attempts += 1
                    task.next_window = window + min(2 ** task.attempts,
                                                    _MAX_BACKOFF)
                    rep.failed += 1
                    rep.bytes_used += charge
                    touched.add(f)
                    break
                state.add_replica(f, target)
                rep.bytes_used += charge
                rep.bytes_copied += size
                rep.applied.append((f, int(target), size))
                touched.add(f)
                if rebalance:
                    state.drop_crowded(f)
                    rep.rebalanced += 1
                    rep.rebalanced_fids.append(f)
                    rep.rebalanced_bytes += charge
                    spread_fixed = True
                    break
                reach[f] += 1
                copy += 1
            if reach[f] >= eff[f] and (not bool(corr[f]) or spread_fixed):
                healed.append(f)
        for f in healed:
            self.backlog.pop(f, None)
        rep.files_touched = len(touched)
        return rep
