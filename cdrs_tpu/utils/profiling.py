"""Profiling helpers — jax.profiler traces + the per-stage wall-clock timers.

The reference's only observability is Spark's history-server UI and print()
statements (SURVEY.md §5).  Here every pipeline stage is timed (MetricsLog,
utils/logging.py) and any region can additionally emit a full XLA trace
viewable in TensorBoard/Perfetto via ``trace_region``.
"""

from __future__ import annotations

import contextlib

__all__ = ["trace_region"]


@contextlib.contextmanager
def trace_region(trace_dir: str | None):
    """Context manager: jax.profiler.trace into ``trace_dir`` (no-op when
    None or when jax/profiler is unavailable)."""
    if not trace_dir:
        yield
        return
    try:
        import jax
    except ImportError:
        import warnings

        # warnings.warn (not a bare stderr print) so callers and tests can
        # assert on / filter the degradation.
        warnings.warn("--profile requested but jax is not installed; "
                      "no trace will be written", RuntimeWarning,
                      stacklevel=2)
        yield
        return
    with jax.profiler.trace(trace_dir):
        yield
