"""Structured per-stage timing and metrics — thin shims over ``obs``.

Historically this module WAS the observability layer (per-stage timers and
a flat dict serialized into the benchmark records).  The real instrument
now lives in ``cdrs_tpu/obs`` (hierarchical spans, counters/histograms,
JSONL sink); ``StageTimer``/``MetricsLog`` keep their API so existing call
sites and the benchmark harness are untouched, while transparently
emitting through the active ``obs.Telemetry`` when one is installed
(``cdrs ... --metrics out.jsonl``).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

from ..obs import current as _current_telemetry

__all__ = ["StageTimer", "MetricsLog"]


class StageTimer:
    """Wall-clock a stage; opens an obs span when telemetry is active."""

    def __init__(self, name: str, metrics: "MetricsLog | None" = None):
        self.name = name
        self.metrics = metrics
        self.elapsed = 0.0
        self._span = None

    def __enter__(self) -> "StageTimer":
        tel = _current_telemetry()
        if tel is not None:
            self._span = tel.span(self.name)
            self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self._t0
        if self._span is not None:
            self._span.__exit__(*exc)
            self._span = None
        if self.metrics is not None:
            self.metrics.record(f"{self.name}.seconds", self.elapsed)


@dataclass
class MetricsLog:
    """Flat metric record dict (the benchmark-harness serialization shape).

    A repeated key no longer silently overwrites: the value becomes a list
    and later records append (two ``stream`` timers in one process keep
    both timings).  ``increment`` gives counter semantics on top.
    """

    records: dict[str, float | list[float]] = field(default_factory=dict)

    def record(self, key: str, value) -> None:
        value = value if value is None else float(value)
        if key in self.records:
            old = self.records[key]
            if isinstance(old, list):
                old.append(value)
            else:
                self.records[key] = [old, value]
        else:
            self.records[key] = value
        tel = _current_telemetry()
        if tel is not None and value is not None:
            tel.gauge(key, value)

    def increment(self, key: str, delta: float = 1.0) -> float:
        """Counter semantics: add ``delta`` to the key (0 when absent).
        A key previously recorded as a list cannot be incremented."""
        old = self.records.get(key, 0.0)
        if isinstance(old, list):
            raise TypeError(
                f"cannot increment {key!r}: it holds a list of records")
        value = float(old) + float(delta)
        self.records[key] = value
        tel = _current_telemetry()
        if tel is not None:
            tel.counter_inc(key, delta)
        return value

    def timer(self, name: str) -> StageTimer:
        return StageTimer(name, metrics=self)

    def to_json(self) -> str:
        return json.dumps(self.records, sort_keys=True)
