"""Structured per-stage timing and metrics.

The reference's only observability is bare ``print()`` calls (SURVEY.md §5
"Metrics/logging").  Here every pipeline stage runs under a ``StageTimer`` and
metrics accumulate into a ``MetricsLog`` that serializes to JSON — the same
records the benchmark harness emits.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

__all__ = ["StageTimer", "MetricsLog"]


class StageTimer:
    def __init__(self, name: str, metrics: "MetricsLog | None" = None):
        self.name = name
        self.metrics = metrics
        self.elapsed = 0.0

    def __enter__(self) -> "StageTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self._t0
        if self.metrics is not None:
            self.metrics.record(f"{self.name}.seconds", self.elapsed)


@dataclass
class MetricsLog:
    records: dict[str, float] = field(default_factory=dict)

    def record(self, key: str, value: float) -> None:
        self.records[key] = float(value)

    def timer(self, name: str) -> StageTimer:
        return StageTimer(name, metrics=self)

    def to_json(self) -> str:
        return json.dumps(self.records, sort_keys=True)
