"""Shared numeric defaults used by both the config layer and the kernels."""

from __future__ import annotations

__all__ = ["default_max_iter", "SEEDED_EPOCH"]

#: Wall-clock anchor used by the generator/simulator whenever a seed is given.
#: The reference stamps ``time.time()`` into creation/access timestamps
#: (src/generator.py:41-42, src/access_simulator.py:21), which makes even a
#: seeded workload differ run-to-run: the concurrency feature buckets events
#: by ``floor(ts)`` (src/compute_features.py:44-46), so the fractional
#: wall-clock offset shifts bucket boundaries and with them every downstream
#: clustering.  Seeded runs therefore anchor to this fixed epoch so a seed
#: fully determines the workload; unseeded runs keep wall-clock behaviour.
SEEDED_EPOCH: float = 1_700_000_000.0  # 2023-11-14T22:13:20Z


def default_max_iter(n: int) -> int:
    """Reference iteration cap ``max(100, n/100)`` with the float->int fix
    (reference: src/kmeans_plusplus.py:29 crashed ``range`` for n > 10,000 —
    SURVEY.md §6.1.1).  Single source for every backend."""
    return max(100, int(n) // 100)
