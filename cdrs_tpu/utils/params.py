"""Shared numeric defaults used by both the config layer and the kernels."""

from __future__ import annotations

__all__ = ["default_max_iter"]


def default_max_iter(n: int) -> int:
    """Reference iteration cap ``max(100, n/100)`` with the float->int fix
    (reference: src/kmeans_plusplus.py:29 crashed ``range`` for n > 10,000 —
    SURVEY.md §6.1.1).  Single source for every backend."""
    return max(100, int(n) // 100)
