"""Checkpoint/resume for long-running kernels and streams.

The reference has no checkpointing, but its file-boundary architecture is
accidentally restartable (SURVEY.md §5).  This module keeps that property for
the in-memory kernels:

* ``save_state``/``load_state`` — atomic npz snapshots of array pytrees
  (centroids, counts, streaming counters) + JSON scalars.
* ``kmeans_jax_checkpointed`` — the Lloyd loop executed in blocks of
  iterations with a durable centroid snapshot between blocks; a killed run
  resumes from the last block with identical results to an uninterrupted run
  (the convergence predicate and PRNG stream are carried in the snapshot).
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np

__all__ = ["CheckpointError", "save_state", "load_state",
           "kmeans_jax_checkpointed"]


class CheckpointError(RuntimeError):
    """A checkpoint file exists but cannot be read (corrupt/truncated).

    Raised instead of the raw ``zipfile``/``ValueError`` internals numpy
    leaks on a torn npz, with the offending path in the message.  Callers
    that retain snapshots can fall back to the ``.prev`` last-good copy
    ``save_state`` keeps (the controller does — control/controller.py)."""


def save_state(path: str, arrays: dict,
               meta: dict | None = None) -> dict:
    """Atomic npz snapshot (write temp + rename) with a JSON meta blob.

    The previous snapshot, when one exists, is retained as ``<path>.prev``
    (a hardlink, not a copy) before the new one lands, so a snapshot
    corrupted after the fact (disk fault, torn write surfaced later) has a
    one-older fallback behind it.  ``path`` itself never transiently
    disappears: the link is created first and the new snapshot replaces
    ``path`` atomically — deleting ``path`` by hand therefore always means
    "start over", never "resume from .prev".

    Returns ``{"bytes": <on-disk size>, "seconds": <wall clock>}`` and —
    when a telemetry instrument is active (obs/) — emits the
    ``checkpoint.bytes`` / ``checkpoint.save_seconds`` gauges and a
    ``checkpoint.saves`` counter: checkpoint size is the observable the
    functional placement mode exists to shrink (O(exceptions) vs
    O(n_files x rf) — ROADMAP item 3), so every save reports it.
    """
    import time

    t_start = time.perf_counter()
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
            # fsync BEFORE the rename: os.replace is atomic in the
            # namespace but says nothing about the data — a host crash
            # between write and rename can land a zero-length/torn npz
            # at ``path``, which the NEXT save would then hardlink into
            # ``.prev``, poisoning the last-good fallback too.
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(path):
            prev = path + ".prev"
            try:
                if os.path.exists(prev):
                    os.unlink(prev)
                os.link(path, prev)
            except OSError:
                # Filesystem without hardlinks: retain by copy instead —
                # slower, but ``path`` must never transiently disappear
                # (a crash in that window would silently restart the
                # controller instead of resuming).
                import shutil

                shutil.copyfile(path, prev + ".cp")
                os.replace(prev + ".cp", prev)
        os.replace(tmp, path)
        try:
            # Make the rename itself durable (the directory entry).
            dirfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:  # pragma: no cover - platform without dir-fsync
            pass
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    stats = {"bytes": int(os.path.getsize(path)),
             "seconds": round(time.perf_counter() - t_start, 6)}
    from ..obs import current as _obs_current

    tel = _obs_current()
    if tel is not None:
        tel.gauge("checkpoint.bytes", stats["bytes"])
        tel.gauge("checkpoint.save_seconds", stats["seconds"])
        tel.counter_inc("checkpoint.saves")
    return stats


def load_state(path: str) -> tuple[dict, dict]:
    """Returns (arrays, meta); raises FileNotFoundError when absent and
    :class:`CheckpointError` when present but corrupt/truncated."""
    try:
        with np.load(path) as z:
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
            meta = json.loads(bytes(z["__meta__"].tobytes()).decode()) \
                if "__meta__" in z.files else {}
    except FileNotFoundError:
        raise
    except Exception as e:
        raise CheckpointError(
            f"checkpoint {path!r} is corrupt or truncated "
            f"({type(e).__name__}: {e}); delete it to start over"
            + (f" or restore the retained snapshot {path + '.prev'!r}"
               if os.path.exists(path + ".prev") else "")) from e
    return arrays, meta


def kmeans_jax_checkpointed(
    X,
    k: int,
    checkpoint_path: str,
    tol: float = 1e-4,
    seed: int | None = None,
    max_iter: int = 100,
    block_iters: int = 25,
    mesh_shape: dict[str, int] | None = None,
    resume: bool = True,
    init_centroids=None,
    labels: str = "final",
    **kwargs,
):
    """Lloyd loop in durable blocks.  Returns (centroids, labels, total_iters).

    Each block runs ``block_iters`` Lloyd iterations on device (one compiled
    call), then snapshots (centroids, iters_done, converged?).  ``resume=True``
    picks up from an existing snapshot.  The reseed PRNG stream is keyed by
    the GLOBAL iteration index (kmeans_jax_full ``iter_offset``), so blocked,
    resumed, and uninterrupted runs draw identical streams — results match
    exactly regardless of where the blocks fall, including iterations where
    empty-cluster reseeds fire.

    ``labels`` selects the label semantics (VERDICT r2 weak #7):

    * ``"final"`` (default) — assignment against the FINAL centroids (one
      extra pass); consistent across fresh/resumed/already-complete runs.
    * ``"parity"`` — the reference's loop-order labels (assignment against
      the pre-update centroids of the last executed iteration,
      kmeans_plusplus.py:33-48), bit-identical to an uninterrupted
      ``kmeans_jax_full`` run.  The final snapshot stores them, so a resumed
      invocation of an already-complete run returns the same labels.
    """
    from ..ops.kmeans_jax import kmeans_jax_full

    if labels not in ("final", "parity"):
        raise ValueError(f"labels must be 'final' or 'parity', got {labels!r}")

    X = np.asarray(X) if not hasattr(X, "devices") else X
    iters_done = 0
    # ``init_centroids`` seeds only a fresh run; a checkpoint always wins.
    centroids = None if init_centroids is None else np.asarray(init_centroids)

    converged = False
    parity_labels = None
    if resume and os.path.exists(checkpoint_path):
        arrays, meta = load_state(checkpoint_path)
        centroids = arrays["centroids"]
        parity_labels = arrays.get("parity_labels")
        iters_done = int(meta["iters_done"])
        converged = bool(meta.get("converged", False))
        if meta.get("k") != int(k):
            raise ValueError(
                f"checkpoint k={meta.get('k')} != requested k={k}")

    base_seed = 0 if seed is None else int(seed)
    while not converged and iters_done < max_iter:
        block = min(block_iters, max_iter - iters_done)
        centroids_out, labels_out, it, shift = kmeans_jax_full(
            X, k, tol=tol,
            seed=base_seed,
            max_iter=block,
            init_centroids=centroids,
            mesh_shape=mesh_shape,
            iter_offset=iters_done,
            **kwargs,
        )
        centroids = np.asarray(centroids_out)
        iters_done += it
        converged = shift < tol
        done = converged or iters_done >= max_iter
        arrays = {"centroids": centroids}
        if labels == "parity" and done:
            # The block's labels ARE the reference-parity labels: the last
            # executed iteration's assignment against its pre-update
            # centroids.  Stored only in the final snapshot (the (n,) array
            # is dead weight mid-run).
            parity_labels = np.asarray(labels_out)
            arrays["parity_labels"] = parity_labels
        save_state(checkpoint_path, arrays,
                   {"iters_done": iters_done, "k": int(k),
                    "shift": shift, "converged": converged})

    if labels == "parity":
        if parity_labels is None:
            raise ValueError(
                "checkpoint predates labels='parity' (no stored labels); "
                "re-run with resume=False or use labels='final'")
        return centroids, parity_labels, iters_done

    import jax.numpy as jnp

    from ..ops.kmeans_jax import assign_labels_jax

    final_labels = assign_labels_jax(jnp.asarray(np.asarray(X)),
                                     jnp.asarray(centroids))
    return centroids, np.asarray(final_labels), iters_done
