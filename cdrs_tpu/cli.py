"""One coherent CLI for the whole framework.

The reference scatters its entry points across four argparse scripts plus a
bash pipeline and a Makefile (SURVEY.md §1 L5).  Here every stage is a
subcommand of ``python -m cdrs_tpu`` (or the ``cdrs`` console script):

  gen       synthetic population -> metadata.csv       (reference: generator.py)
  simulate  Poisson access events -> access.log        (reference: access_simulator.py)
  features  manifest+log -> features CSV               (reference: compute_features.py)
  cluster   features CSV -> final_categories.csv       (reference: main.py)
  pipeline  all of the above end-to-end      (reference: run_pipeline.sh)
            (alias: run)
  storage   storage strategies: EC/tier config resolution + cost estimate
  scenarios declarative scenario matrix: invariant-gated chaos sweeps
            (new; cdrs_tpu/scenarios)
  bench     benchmark harness                          (new; BASELINE.md configs)
  metrics   inspect telemetry JSONL streams            (new; obs/metrics_cli.py)
  trace     per-decision causal traces of the daemon   (new; obs/trace.py)

``--metrics out.jsonl`` on pipeline/cluster/stream/control/bench activates
the unified telemetry layer (cdrs_tpu/obs): hierarchical stage spans,
counters/histograms, per-iteration kmeans convergence traces, and a JIT
recompile counter, all as one JSONL event stream consumed by
``cdrs metrics summarize|tail|export``.

``--backend {numpy,jax}`` selects the execution backend per the BASELINE.json
north star; the numpy path preserves reference behaviour (minus crash bugs),
the jax path scales.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

from .config import (
    CLUSTERING_FEATURES,
    GeneratorConfig,
    KMeansConfig,
    PipelineConfig,
    ScoringConfig,
    SimulatorConfig,
)
from .utils.logging import StageTimer

__all__ = ["main"]


def _add_metrics_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="emit telemetry here (spans, counters, kmeans "
                        "convergence traces); inspect with "
                        "'cdrs metrics summarize'")
    p.add_argument("--metrics_max_bytes", type=int, default=None,
                   metavar="BYTES",
                   help="with --metrics: rotate the stream past this "
                        "size (.1/.2 suffixes, larger = older); readers "
                        "see the rotated set as one stream — bounds a "
                        "long soak's telemetry file")
    p.add_argument("--device_memory", action="store_true",
                   help="with --metrics: sample per-device memory_stats "
                        "gauges at every span exit (TPU backends)")


def _open_telemetry(args, stack, root_span: str):
    """Activate a Telemetry over a JSONL sink when --metrics was given.

    Returns the instrument (or None).  ``stack`` is a contextlib.ExitStack
    owning the activation and a root span named ``root_span`` so every
    stage span nests under one tree."""
    path = getattr(args, "metrics", None)
    if not path:
        return None
    from .obs import JsonlSink, Telemetry

    tel = Telemetry(JsonlSink(path,
                              max_bytes=getattr(args, "metrics_max_bytes",
                                                None)),
                    device_memory=getattr(args, "device_memory", False))
    stack.enter_context(tel)
    stack.enter_context(tel.span(root_span,
                                 backend=getattr(args, "backend", None)))
    return tel


def _add_backend_arg(p: argparse.ArgumentParser, mesh: bool = True,
                     default: str | None = "numpy") -> None:
    p.add_argument("--backend", choices=["numpy", "jax"], default=default)
    if mesh:
        p.add_argument(
            "--mesh", default=None, metavar="SPEC",
            help="device mesh for the jax backend: '8' or 'data=4,model=2'",
        )


def _add_init_method_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--init_method", choices=["auto", "d2", "kmeans||"], default="auto",
        help="centroid init (jax backend): 'd2' = reference KMeans++ "
             "semantics; 'kmeans||' = oversampling init whose cost does "
             "not grow with k; 'auto' (default) = kmeans|| at k >= 256, "
             "d2 below (quality gate: data/init_quality_r5.json)",
    )
    p.add_argument(
        "--dtype", choices=["float32", "bfloat16", "float64"], default=None,
        help="clustering points dtype (jax backend; default: the feature "
             "matrix's). bfloat16 halves the Lloyd HBM stream; centroids "
             "and stats stay float32",
    )


def _load_scoring(args) -> ScoringConfig:
    """ScoringConfig from --scoring_config (if given) with the
    --medians_from_data flag applied on top.

    ``--scoring_config validated`` selects the built-in tables tuned for the
    simulator's workload (config.validated_scoring_config); any other value
    is a JSON file path."""
    medians_from_data = getattr(args, "medians_from_data", False)
    if getattr(args, "scoring_config", None):
        import dataclasses

        if args.scoring_config == "validated":
            from .config import validated_scoring_config

            cfg = validated_scoring_config()
        else:
            from .config import load_scoring_config

            cfg = load_scoring_config(args.scoring_config)
        if medians_from_data:
            cfg = dataclasses.replace(cfg, compute_global_medians_from_data=True)
        return cfg
    return ScoringConfig(compute_global_medians_from_data=medians_from_data)


def _parse_mesh(spec: str | None) -> dict[str, int] | None:
    if not spec:
        return None
    if "=" not in spec:
        if int(spec) < 1:
            raise SystemExit(f"mesh size must be >= 1, got {spec}")
        return {"data": int(spec)}
    mesh = {k: int(v) for k, v in (part.split("=") for part in spec.split(","))}
    unknown = set(mesh) - {"data", "model"}
    if unknown:
        raise SystemExit(
            f"unknown mesh axis {sorted(unknown)}: --mesh takes 'data' and "
            f"'model' (e.g. 'data=4,model=2')")
    if any(v < 1 for v in mesh.values()):
        raise SystemExit(f"mesh axis sizes must be >= 1, got {mesh}")
    return mesh


def _cmd_gen(args) -> int:
    from .sim.generator import generate_population

    cfg = GeneratorConfig(
        n_files=args.n, base_dir=args.hdfs_dir, min_size=args.min_size,
        max_size=args.max_size, nodes=tuple(args.nodes.split(",")),
        age_days_max=args.age_days_max, seed=args.seed,
        write_payloads=args.write_payloads,
    )
    with StageTimer("gen") as t:
        manifest = generate_population(cfg)
        manifest.write_csv(args.out_manifest)
    print(f"Wrote {args.out_manifest} ({len(manifest)} files) in {t.elapsed:.2f}s")
    return 0


def _cmd_simulate(args) -> int:
    from .io.events import Manifest
    from .sim.access import simulate_access

    cfg = SimulatorConfig(
        duration_seconds=args.duration_seconds,
        clients=tuple(args.clients.split(",")),
        seed=args.seed,
    )
    fmt = args.format
    if fmt == "auto":
        fmt = "binary" if args.out.endswith(".cdrsb") else "csv"
    with StageTimer("simulate") as t:
        manifest = Manifest.read_csv(args.manifest)
        events = simulate_access(manifest, cfg, engine=args.engine)
        if fmt == "binary":
            events.write_binary(args.out, manifest)
        else:
            events.write_csv(args.out, manifest)
    print(f"Wrote {args.out} with {len(events)} entries in {t.elapsed:.2f}s")
    return 0


def _cmd_features(args) -> int:
    from .io.events import EventLog, Manifest

    # Validate the mesh spec before the potentially long log parse.
    mesh_shape = _parse_mesh(args.mesh)
    if args.backend == "jax":
        import functools

        from .features.jax_backend import compute_features_jax

        compute = functools.partial(compute_features_jax, mesh_shape=mesh_shape)
    else:
        if args.mesh:
            print("warning: --mesh ignored for the numpy backend",
                  file=sys.stderr)
        from .features.numpy_backend import compute_features as compute

    with StageTimer("features") as t:
        manifest = Manifest.read_csv(args.manifest)
        events = EventLog.read_csv(args.access_log, manifest)
        table = compute(manifest, events)
        out = args.out
        if os.path.isdir(out) or out.endswith(os.sep):
            os.makedirs(out, exist_ok=True)
            out = os.path.join(out, "part-00000-features.csv")
        else:
            parent = os.path.dirname(out)
            if parent:
                os.makedirs(parent, exist_ok=True)
        table.write_csv(out)
    print(f"Wrote features to {out} in {t.elapsed:.2f}s")
    return 0


def _cmd_cluster(args) -> int:
    from .io.features import load_feature_matrix
    from .models.replication import ReplicationPolicyModel

    model = ReplicationPolicyModel(
        kmeans_cfg=KMeansConfig(k=args.k, seed=args.seed,
                                init_method=getattr(args, 'init_method', 'auto'),
                                dtype=getattr(args, 'dtype', None)),
        scoring_cfg=_load_scoring(args),
        backend=args.backend,
        mesh_shape=_parse_mesh(args.mesh),
    )
    import contextlib

    with contextlib.ExitStack() as stack:
        _open_telemetry(args, stack, "cluster_cmd")
        with StageTimer("cluster") as t:
            X, paths = load_feature_matrix(args.input_path)
            decision = model.run(X)
            decision.write_csv(args.output_csv)
            if args.assignments_csv:
                decision.write_assignments_csv(args.assignments_csv, paths)
    print(f"Cluster centroid assignments ({args.k} clusters) saved to: "
          f"{args.output_csv} in {t.elapsed:.2f}s")
    return 0


def _cmd_pipeline(args) -> int:
    import contextlib

    from .pipeline import run_pipeline

    cfg = PipelineConfig(
        backend=args.backend,
        generator=GeneratorConfig(n_files=args.n, seed=args.seed),
        simulator=SimulatorConfig(duration_seconds=args.duration_seconds,
                                  seed=None if args.seed is None else args.seed + 1),
        kmeans=KMeansConfig(k=args.k, seed=args.seed,
                            init_method=getattr(args, 'init_method', 'auto'),
                            dtype=getattr(args, 'dtype', None)),
        scoring=_load_scoring(args),
        mesh_shape=_parse_mesh(args.mesh),
        evaluate=args.evaluate,
    )
    from .utils.profiling import trace_region

    with contextlib.ExitStack() as stack:
        _open_telemetry(args, stack, "pipeline")
        with trace_region(args.profile):
            result = run_pipeline(cfg, outdir=args.outdir)
    print(json.dumps(result.summary(), indent=2))
    return 0


def _read_assignments(manifest, path, categories):
    """Parse a cluster/control assignments CSV (path,category,...) into
    matched ``(file_id, category)`` pairs, with the shared no-match
    error / partial-match warning.  Returns None when rows exist but
    none matched."""
    import csv as _csv

    pairs, rows = [], 0
    with open(path, newline="") as f:
        for row in _csv.DictReader(f):
            rows += 1
            i = manifest.path_to_id.get(row.get("path"))
            c = row.get("category")
            if i is not None and c in categories:
                pairs.append((i, c))
    if rows and not pairs:
        print(f"error: no row of {path} matched a manifest path with a "
              f"known category — is this the cluster --assignments_csv "
              f"output?", file=sys.stderr)
        return None
    if len(pairs) < rows:
        print(f"warning: {rows - len(pairs)}/{rows} assignment rows "
              f"ignored (unknown path or category)", file=sys.stderr)
    return pairs


def _cmd_evaluate(args) -> int:
    """Apply decided replication factors on the simulated cluster and report
    locality/load/storage vs uniform baselines (the reference decides factors
    but never applies them — SURVEY.md §6)."""
    from .cluster import ClusterTopology, compare_policies
    from .io.events import EventLog, Manifest

    manifest = Manifest.read_csv(args.manifest)
    events = EventLog.read_csv(args.access_log, manifest)

    # Honor a custom scoring config: its category -> rf table must be the one
    # the cluster stage decided with, or the evaluation silently applies the
    # wrong factors.
    scoring = _load_scoring(args)
    rf = np.full(len(manifest), args.default_rf, dtype=np.int32)
    want_plan = bool(args.emit_plan or args.emit_setrep)
    plan_rows: list[tuple[str, str]] = []
    pairs = _read_assignments(manifest, args.assignments_csv,
                              scoring.replication_factors)
    if pairs is None:
        return 1
    for i, c in pairs:
        rf[i] = scoring.replication_factors[c]
        if want_plan:
            plan_rows.append((manifest.paths[i], c))

    if want_plan:
        from .cluster import build_plan, write_plan_csv, write_setrep_script

        entries = build_plan([p for p, _ in plan_rows],
                             [c for _, c in plan_rows], scoring)
        if args.emit_plan:
            write_plan_csv(args.emit_plan, entries)
            print(f"plan: {len(entries)} files -> {args.emit_plan}",
                  file=sys.stderr)
        if args.emit_setrep:
            n = write_setrep_script(args.emit_setrep, entries)
            print(f"setrep script: {n} commands -> {args.emit_setrep}",
                  file=sys.stderr)

    nodes = tuple(args.nodes.split(",")) if args.nodes else tuple(manifest.nodes)
    out = compare_policies(manifest, events, rf,
                           topology=ClusterTopology(nodes=nodes))
    print(json.dumps(out, indent=2))
    return 0


def _cmd_stream(args) -> int:
    """Streaming mode: fold the access log in fixed-size batches, then cluster.

    The batch pipeline's result on the same log is identical (the stream fold
    is exact — features/streaming.py, features/streaming_np.py); this path
    exists for logs too large to hold in memory and for continuous operation.
    ``--kmeans_batch`` additionally makes the clustering itself incremental
    (mini-batch KMeans, ops/kmeans_stream.py — the BASELINE config-5 mode).
    """
    import contextlib

    with contextlib.ExitStack() as stack:
        _open_telemetry(args, stack, "stream_cmd")
        return _cmd_stream_inner(args)


def _cmd_stream_inner(args) -> int:
    from .io.events import EventLog, Manifest
    from .models.replication import ReplicationPolicyModel

    mesh_shape = _parse_mesh(args.mesh)
    if args.kmeans_batch is not None:
        # Validate before the (potentially hours-long) streaming pass.
        if args.backend != "jax":
            print("error: --kmeans_batch (mini-batch KMeans) requires "
                  "--backend jax", file=sys.stderr)
            return 1
        if args.kmeans_batch < 1:
            print(f"error: --kmeans_batch must be >= 1, got "
                  f"{args.kmeans_batch}", file=sys.stderr)
            return 1
    if args.backend == "jax":
        try:
            from .features.streaming import fold_stream, stream_finalize
        except ImportError as e:
            print(f"--backend jax requires jax (the 'tpu' extra): {e}",
                  file=sys.stderr)
            return 1
        stats = {}
        with StageTimer("stream") as t:
            manifest = Manifest.read_csv(args.manifest)
            # Parse+prep pipelined against the device fold on a prefetch
            # thread (features/streaming.fold_stream).
            state = fold_stream(args.access_log, manifest,
                                batch_size=args.batch_size,
                                mesh_shape=mesh_shape, stats=stats,
                                checkpoint_path=args.checkpoint,
                                checkpoint_every=args.checkpoint_every)
            table = stream_finalize(state, manifest)
        n_batches = stats["batches"]
        if args.checkpoint and stats.get("resumed_from_offset"):
            print(f"Resumed from checkpoint at byte "
                  f"{stats['resumed_from_offset']}")
    else:
        from .features.streaming_np import (
            stream_finalize_np as stream_finalize,
            stream_init_np as stream_init,
            stream_update_np as stream_update,
        )
        if args.mesh:
            print("warning: --mesh ignored for the numpy backend",
                  file=sys.stderr)
        if args.checkpoint:
            print("warning: --checkpoint requires --backend jax; ignored",
                  file=sys.stderr)
        with StageTimer("stream") as t:
            manifest = Manifest.read_csv(args.manifest)
            state = stream_init(len(manifest))
            n_batches = 0
            for batch in EventLog.read_csv_batches(args.access_log, manifest,
                                                   batch_size=args.batch_size):
                state = stream_update(state, batch, manifest)
                n_batches += 1
            table = stream_finalize(state, manifest)
    print(f"Streamed {state.n_events} events in {n_batches} batches "
          f"({t.elapsed:.2f}s)")
    from .obs import current as _obs_current

    tel = _obs_current()
    if tel is not None:
        # Ingest rate: the streaming layer's headline operational number.
        if t.elapsed > 0:
            tel.gauge("stream.events_per_sec", state.n_events / t.elapsed)
        tel.counter_inc("stream.events", int(state.n_events))
        tel.counter_inc("stream.batches", int(n_batches))

    model = ReplicationPolicyModel(
        kmeans_cfg=KMeansConfig(k=args.k, seed=args.seed,
                                batch_size=args.kmeans_batch,
                                init_method=getattr(args, 'init_method', 'auto'),
                                dtype=getattr(args, 'dtype', None)),
        scoring_cfg=_load_scoring(args),
        backend=args.backend,
        mesh_shape=mesh_shape,
    )
    with StageTimer("cluster") as t:
        decision = model.run(np.asarray(table.norm))
        decision.write_csv(args.output_csv)
    mode = (f"mini-batch({args.kmeans_batch})" if args.kmeans_batch
            else "full-batch")
    print(f"Cluster centroid assignments ({args.k} clusters, {mode}) saved "
          f"to: {args.output_csv} in {t.elapsed:.2f}s")
    return 0


def _controller_cfg(args, fault_schedule=None, topology=None):
    """ControllerConfig from the shared control/chaos argument set."""
    from .control import ControllerConfig

    mesh_shape = _parse_mesh(args.mesh)
    if mesh_shape and args.backend != "jax":
        raise SystemExit(
            "--mesh requires --backend jax (the numpy backend is the "
            "single-host oracle)")
    scoring = _load_scoring(args)
    storage_cfg = None
    if getattr(args, "storage_config", None):
        from .storage import resolve_storage_config

        storage_cfg = resolve_storage_config(args.storage_config, scoring)
    serve_cfg = None
    if getattr(args, "serve", False):
        from .serve import ServeConfig, SloSpec

        serve_cfg = ServeConfig(
            policy=args.serve_policy, seed=args.serve_seed,
            service_ms=args.serve_service_ms,
            slo=SloSpec(target_ms=args.serve_slo_ms,
                        availability=args.serve_slo_availability),
            recluster_on_hotspot=not args.no_hotspot_recluster,
            verify_reads=not getattr(args, "no_verify_reads", False))
    scrub_cfg = None
    if getattr(args, "scrub", None):
        from .faults import ScrubConfig

        scrub_cfg = ScrubConfig(bytes_per_window=args.scrub)
    return ControllerConfig(
        topology=topology,
        placement_mode=getattr(args, "placement", "materialized"),
        serve=serve_cfg,
        storage=storage_cfg,
        window_seconds=args.window_seconds,
        drift_threshold=args.drift_threshold,
        full_recluster_drift=args.full_drift,
        warm_max_iter=args.warm_max_iter,
        max_bytes_per_window=args.max_bytes,
        max_files_per_window=args.max_files,
        hysteresis_windows=args.hysteresis,
        decay=args.decay,
        default_rf=args.default_rf,
        backend=args.backend,
        kmeans=KMeansConfig(k=args.k, seed=args.seed,
                            init_method=getattr(args, 'init_method', 'auto'),
                            dtype=getattr(args, 'dtype', None)),
        scoring=scoring,
        mesh_shape=mesh_shape,
        evaluate=not args.no_evaluate,
        fault_schedule=fault_schedule,
        repair_seed=getattr(args, "repair_seed", 0),
        overlap_windows=getattr(args, "overlap", False),
        scrub=scrub_cfg,
    )


def _run_controller(args, cfg, root_span: str, manifest=None) -> int:
    """Shared control/chaos driver: run the loop, export the plan, print
    the summary (chaos runs additionally carry a ``durability`` block)."""
    import contextlib

    from .control import ReplicationController
    from .io.events import Manifest

    if manifest is None:
        manifest = Manifest.read_csv(args.manifest)
    controller = ReplicationController(manifest, cfg)
    with contextlib.ExitStack() as stack:
        # One stream, two producers: the controller appends its per-window
        # records (kill/resume-safe, one line each) while the activated
        # Telemetry interleaves counters/histograms/kmeans traces — both
        # through obs/sink.JsonlSink, atomic per line.
        _open_telemetry(args, stack, root_span)
        with StageTimer(root_span) as t:
            result = controller.run(
                args.access_log, metrics_path=args.metrics,
                checkpoint_path=args.checkpoint,
                checkpoint_every=args.checkpoint_every,
                max_windows=args.max_windows, batch_size=args.batch_size)
    if args.plan_out:
        from .cluster.plan import write_plan_csv

        write_plan_csv(args.plan_out, result.plan_entries())
        print(f"plan: {len(manifest)} files -> {args.plan_out}",
              file=sys.stderr)
    out = result.summary()
    out["seconds"] = round(t.elapsed, 3)
    print(json.dumps(out, indent=2))
    return 0


def _cmd_control(args) -> int:
    """Online replication controller: consume the log as time windows,
    drift-gate incremental re-clusters, meter out bounded-churn migrations
    (control/controller.py)."""
    return _run_controller(args, _controller_cfg(args), "control_cmd")


def _cmd_daemon(args) -> int:
    """Always-on streaming controller (daemon/): tail the growing binary
    event log (or read it once), carve windows on the controller's grid,
    publish every admitted plan as a pinned placement epoch, evaluate
    the live alert rules, and land cursor-carrying checkpoints so
    SIGTERM -> restart resumes bit-identically over O(new data)."""
    import contextlib

    from .control import ReplicationController
    from .daemon import BrownoutConfig, DaemonConfig, StreamDaemon
    from .io.events import Manifest

    if args.supervise:
        # Re-exec ourselves as the supervised child, minus the
        # supervision flags (the child must not recurse into a
        # supervisor of its own).
        from .daemon import supervise as _supervise

        drop = ("--supervise", "--max_restarts")
        child, skip = [], False
        for tok in sys.argv[1:]:
            if skip:
                skip = False
                continue
            if tok in drop:
                skip = (tok == "--max_restarts")
                continue
            if tok.startswith("--max_restarts="):
                continue
            child.append(tok)
        return _supervise([sys.executable, "-m", "cdrs_tpu"] + child,
                          max_restarts=args.max_restarts)

    brownout = None
    if args.brownout:
        kw = {}
        if args.brownout_engage:
            kw["engage"] = tuple(
                float(x) for x in args.brownout_engage.split(","))
        if args.brownout_release:
            kw["release"] = tuple(
                float(x) for x in args.brownout_release.split(","))
        if args.shed_fraction is not None:
            kw["shed_fraction"] = args.shed_fraction
        brownout = BrownoutConfig(**kw)

    manifest = Manifest.read_csv(args.manifest)
    controller = ReplicationController(manifest, _controller_cfg(args))
    daemon = StreamDaemon(controller, DaemonConfig(
        follow=args.follow, poll=args.poll,
        checkpoint_every=args.checkpoint_every,
        max_windows=args.max_windows, max_seconds=args.max_seconds,
        recluster=args.recluster, minibatch_rows=args.minibatch_rows,
        brownout=brownout))
    daemon.install_signal_handlers()
    with contextlib.ExitStack() as stack:
        if args.http:
            from .obs.httpz import ObsServer

            host, _, port = args.http.rpartition(":")
            try:
                server = ObsServer(host or "127.0.0.1", int(port))
            except (OSError, ValueError) as e:
                print(f"error: cannot bind --http {args.http}: {e}",
                      file=sys.stderr)
                return 2
            stack.callback(server.close)
            server.start()
            daemon.attach_http(server)
            # The bound address, for port 0 (and for probes/scrapers to
            # copy): the one operational line the daemon prints.
            print(f"http: serving /metrics /healthz /readyz /statusz "
                  f"/debug/trace on {server.url}", file=sys.stderr)
        _open_telemetry(args, stack, "daemon_cmd")
        with StageTimer("daemon_cmd") as t:
            digest = daemon.run(
                args.access_log, metrics_path=args.metrics,
                checkpoint_path=args.checkpoint,
                batch_size=args.batch_size)
    if args.plan_out:
        from .cluster.plan import write_plan_csv
        from .control.controller import ControllerResult

        result = ControllerResult(records=daemon.records,
                                  rf=controller.current_rf,
                                  category_idx=controller.current_cat,
                                  manifest=manifest)
        write_plan_csv(args.plan_out, result.plan_entries())
        print(f"plan: {len(manifest)} files -> {args.plan_out}",
              file=sys.stderr)
    digest["seconds"] = round(t.elapsed, 3)
    if args.digest_out:
        with open(args.digest_out, "w", encoding="utf-8") as f:
            json.dump(digest, f, indent=2)
            f.write("\n")
    print(json.dumps(digest, indent=2))
    return 0


def _cmd_chaos(args) -> int:
    """Fault-injected controller run: the control loop plus a seeded
    FaultSchedule (node crash/recover/decommission/flaky, network
    partitions, stragglers), failure-domain-aware placement (--racks),
    durability accounting per window, and the repair planner competing
    with drift migrations for the same churn budget (faults/)."""
    from .faults import FaultSchedule
    from .io.events import Manifest

    manifest = Manifest.read_csv(args.manifest)
    topology = None
    if getattr(args, "topology", None):
        if args.racks:
            print("error: --topology and --racks are mutually exclusive "
                  "(the hierarchy spec subsumes the rack map)",
                  file=sys.stderr)
            return 2
        from .cluster import ClusterTopology

        text = args.topology
        if not text.lstrip().startswith("{"):
            with open(text, encoding="utf-8") as f:
                text = f.read()
        try:
            topology = ClusterTopology.from_hierarchy(json.loads(text))
        except ValueError as e:
            # from_hierarchy names the offending level/node/group.
            print(f"error: bad --topology spec: {e}", file=sys.stderr)
            return 2
        unknown = sorted(set(manifest.nodes) - set(topology.nodes))
        if unknown:
            print(f"error: --topology is missing manifest nodes "
                  f"{unknown}", file=sys.stderr)
            return 2
    elif args.racks:
        from .cluster import ClusterTopology

        topology = ClusterTopology.from_rack_spec(manifest.nodes,
                                                  args.racks)
    events = []
    for kind, flag in (("crash", args.kill), ("recover", args.recover),
                       ("decommission", args.decommission),
                       ("flaky", args.flaky),
                       ("partition", args.partition),
                       ("degrade", args.degrade),
                       ("corrupt", args.corrupt)):
        for spec in flag or ():
            events.extend(FaultSchedule.from_specs([f"{kind}:{spec}"]))
    if args.schedule:
        with open(args.schedule, encoding="utf-8") as f:
            events.extend(FaultSchedule.from_json(json.load(f)))
    if args.random_faults:
        events.extend(FaultSchedule.random(
            manifest.nodes, n_windows=args.random_faults,
            seed=args.fault_seed, corrupt_rate=args.corrupt_rate,
            corrupt_frac=args.corrupt_frac))
    if not events:
        print("error: chaos needs at least one fault (--kill/--recover/"
              "--decommission/--flaky/--partition/--degrade/--corrupt/"
              "--schedule/--random_faults)", file=sys.stderr)
        return 1
    schedule = FaultSchedule(events)
    if args.schedule_out:
        with open(args.schedule_out, "w", encoding="utf-8") as f:
            json.dump(schedule.to_json(), f, indent=2)
            f.write("\n")
        print(f"schedule: {len(schedule)} events -> {args.schedule_out}",
              file=sys.stderr)
    return _run_controller(args, _controller_cfg(args, schedule, topology),
                           "chaos_cmd", manifest=manifest)


def _cmd_serve(args) -> int:
    """Read-path SLO replay: drive the vectorized read router over the
    access log in time windows against a static placement (serve/), with
    optional fault injection (partitions, stragglers, crashes) shaping
    reachability and service times.  Prints a JSON serving digest; with
    --metrics, streams ``serve.*`` telemetry (latency hist_bulk, p99/SLO
    gauges, hotspot counters) plus per-window records that ``cdrs metrics
    summarize|report`` digest into the serving section."""
    import contextlib

    from .cluster.evaluate import _client_to_topology
    from .cluster.placement import ClusterTopology, place_replicas
    from .control.windows import iter_windows
    from .faults import FaultSchedule
    from .faults.state import ClusterState
    from .io.events import Manifest
    from .obs import current as _obs_current
    from .serve import (
        HotspotDetector,
        ReadRouter,
        ServeConfig,
        SloSpec,
        emit_window_telemetry,
        read_view,
    )

    manifest = Manifest.read_csv(args.manifest)
    topology = ClusterTopology(nodes=tuple(manifest.nodes))
    if args.racks:
        topology = ClusterTopology.from_rack_spec(manifest.nodes,
                                                  args.racks)
    serve_cfg = ServeConfig(
        policy=args.policy, seed=args.seed, service_ms=args.service_ms,
        slo=SloSpec(target_ms=args.slo_ms,
                    availability=args.slo_availability),
        verify_reads=not args.no_verify_reads)
    rf = np.full(len(manifest), args.default_rf, dtype=np.int32)
    placement_mode = getattr(args, "placement", "materialized")
    method = "rng" if placement_mode == "materialized" else "hash"

    events = []
    for kind, flag in (("crash", args.kill), ("partition", args.partition),
                       ("degrade", args.degrade),
                       ("corrupt", args.corrupt)):
        for spec in flag or ():
            events.extend(FaultSchedule.from_specs([f"{kind}:{spec}"]))
    schedule = FaultSchedule(events) if events else None
    state = None
    placement = None
    resolver = None
    if schedule is not None:
        # Faults need the mutable state either way; the hash family just
        # swaps the base chooser.
        placement = place_replicas(manifest, rf, topology, seed=0,
                                   method=method)
        schedule.validate_nodes(topology.nodes)
        state = ClusterState(placement,
                             np.asarray(manifest.size_bytes,
                                        dtype=np.int64))
    elif placement_mode == "functional":
        # The O(1)-memory router: no materialized map at all — each
        # window resolves only ITS files through the functional chooser
        # (serve/view.read_view compacts the rows and remaps pids).
        from .placement_fn import compute_placement, primary_on_topology

        fn_primary = primary_on_topology(manifest.nodes,
                                         manifest.primary_node_id,
                                         topology)

        def resolver(uniq):
            return compute_placement(uniq, rf[uniq], fn_primary[uniq],
                                     topology, 0)[0]
    else:
        placement = place_replicas(manifest, rf, topology, seed=0,
                                   method=method)

    router = ReadRouter(len(topology), serve_cfg)
    hotspot = HotspotDetector(
        len(manifest), alpha=serve_cfg.hotspot_alpha,
        spike_factor=serve_cfg.hotspot_spike_factor,
        min_reads=serve_cfg.hotspot_min_reads,
        top_k=serve_cfg.hotspot_top_k)

    records: list[dict] = []
    with contextlib.ExitStack() as stack:
        # --metrics activates the instrument; window records ride the same
        # stream with "kind": "window" (the controller's sink contract).
        _open_telemetry(args, stack, "serve_cmd")
        tel = _obs_current()
        with StageTimer("serve") as t:
            for w, ev in iter_windows(args.access_log, manifest,
                                      args.window_seconds,
                                      batch_size=args.batch_size):
                if args.max_windows is not None \
                        and len(records) >= args.max_windows:
                    break
                if state is not None:
                    for fev in schedule.for_window(w):
                        state.apply_event(fev)
                rec: dict = {"window": int(w), "n_events": int(len(ev))}
                if len(ev):
                    keep = ev.path_id >= 0
                    is_read = np.asarray(ev.op)[keep] == 0
                    pid = ev.path_id[keep][is_read]
                    ts = ev.ts[keep][is_read]
                    client = _client_to_topology(ev, topology)[keep][is_read]
                    hs = hotspot.observe(
                        np.bincount(pid, minlength=len(manifest)))
                    # The ONE state-vs-static resolution (serve/view.py)
                    # shared with the controller's serve wiring — the
                    # seam the functional mode plugs into.
                    view = read_view(pid, state=state, resolver=resolver,
                                     placement=placement,
                                     n_nodes=len(topology))
                    res = router.route(
                        view.replica_map, view.slot_ok,
                        view.node_throughput, ts=ts, pid=view.pid,
                        client=client,
                        window_seconds=args.window_seconds,
                        rng=np.random.default_rng([args.seed, int(w)]),
                        slot_corrupt=view.slot_corrupt)
                    if (res.corrupt_pairs is not None
                            and len(res.corrupt_pairs)):
                        # Detect-on-read: drop the rotten copies the
                        # window's reads exposed (same contract as the
                        # controller's serve wiring).
                        for fid, node in res.corrupt_pairs:
                            state.quarantine(int(fid), int(node))
                    rec["n_reads"] = res.n_reads
                    rec.update(res.record_fields())
                    rec["hotspot_score"] = round(hs.score, 6)
                    rec["hotspot_files"] = list(hs.files)
                    if tel is not None:
                        # Same serve.* emission path as the controller
                        # (serve/router.py) — the schemas cannot drift.
                        emit_window_telemetry(tel, rec, res.latency_ms)
                records.append(rec)
                if tel is not None:
                    tel._emit({"kind": "window", **rec})
    from .obs.aggregate import serve_digest

    out = serve_digest(records) or {"windows": len(records),
                                    "reads_routed": 0}
    out["policy"] = args.policy
    out["seconds"] = round(t.elapsed, 3)
    if out.get("reads_routed"):
        out["routed_reads_per_sec"] = round(
            out["reads_routed"] / max(t.elapsed, 1e-9), 1)
    print(json.dumps(out, indent=2))
    return 0


def _cmd_storage(args) -> int:
    """Storage-strategy inspection: resolve a strategy config against
    the category vocabulary (``show``) or estimate its byte/cost
    footprint over a real manifest + category assignment (``estimate``)
    — the offline counterpart of the per-window ``storage`` record the
    controller emits when ``--storage_config`` is set."""
    from .config import CATEGORIES
    from .storage import resolve_storage_config

    scoring = _load_scoring(args)
    cfg = resolve_storage_config(args.storage_config, scoring)
    rows = cfg.describe(CATEGORIES, scoring.replication_factors)

    if args.action == "show":
        print(json.dumps({
            "storage_config": args.storage_config,
            "pure_replication": cfg.pure_replication,
            "default_tier": cfg.default_tier,
            "tiers": cfg.to_dict()["tiers"],
            "categories": rows,
        }, indent=2))
        return 0

    # estimate
    from .io.events import Manifest

    if not args.manifest or not args.assignments_csv:
        print("error: storage estimate needs --manifest and "
              "--assignments_csv (the cluster/control per-file "
              "path,cluster,category table)", file=sys.stderr)
        return 1
    manifest = Manifest.read_csv(args.manifest)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    cat_idx = {c: i for i, c in enumerate(CATEGORIES)}
    cat = np.full(len(manifest), -1, dtype=np.int64)
    pairs = _read_assignments(manifest, args.assignments_csv, cat_idx)
    if pairs is None:
        return 1
    for i, c in pairs:
        cat[i] = cat_idx[c]
    rf_vec = np.asarray([scoring.replication_factors[c]
                         for c in CATEGORIES], dtype=np.int64)
    by_cat = []
    tot = {"raw": 0, "stored": 0, "cost": 0.0, "baseline": 0}
    for ci, c in enumerate(CATEGORIES):
        sel = cat == ci
        if not sel.any():
            continue
        s = cfg.strategy_for(c, scoring.replication_factors.get(c))
        raw = int(sizes[sel].sum())
        shard = -(-sizes[sel] // s.shard_div)
        stored = int((shard * s.n_shards).sum())
        cost = stored * cfg.tiers[s.tier].byte_cost
        baseline = int(raw * rf_vec[ci])
        by_cat.append({
            "category": c, "files": int(sel.sum()), "strategy": s.spec(),
            "bytes_raw": raw, "bytes_stored": stored,
            "cost_units": round(cost, 3),
            "bytes_replicate_baseline": baseline,
            "bytes_saved_vs_baseline": baseline - stored,
        })
        tot["raw"] += raw
        tot["stored"] += stored
        tot["cost"] += cost
        tot["baseline"] += baseline
    out = {
        "storage_config": args.storage_config,
        "note": "logical estimate — shard counts are not capped at the "
                "node count (a live run's `storage` record is)",
        "files": len(manifest), "files_categorized": len(pairs),
        "per_category": by_cat,
        "bytes_raw": tot["raw"], "bytes_stored": tot["stored"],
        "cost_units": round(tot["cost"], 3),
        "bytes_replicate_baseline": tot["baseline"],
        "stored_vs_baseline_ratio": round(
            tot["baseline"] / tot["stored"], 4) if tot["stored"] else None,
    }
    print(json.dumps(out, indent=2))
    return 0


def _cmd_scenarios(args) -> int:
    """Declarative scenario matrix (cdrs_tpu/scenarios): list the named
    presets and suites, run one cell, or sweep a suite's matrix — every
    cell runs the controller end to end and is gated on INVARIANTS (zero
    silent loss, churn-budget conservation, domain diversity, SLO
    bounds, sampled kill/resume bit-identity); a failing cell prints a
    one-line seeded repro command."""
    from .scenarios import (
        PRESETS,
        SUITES,
        ScenarioSpec,
        preset,
        run_cell,
        suite_cells,
    )

    if args.action == "list":
        print("presets:")
        for name in sorted(PRESETS):
            sp = PRESETS[name]
            axes = [(sp.workload or {}).get("kind", "poisson")]
            if sp.drift:
                axes.append(f"drift={sp.drift['kind']}")
            if sp.faults:
                axes.append("faults")
            if sp.racks:
                axes.append("racks")
            if sp.storage:
                axes.append(f"storage={sp.storage}")
            if sp.serve:
                axes.append(f"serve={sp.serve.get('policy', 'p2c')}")
            if sp.scrub:
                axes.append("scrub")
            if sp.resume_window is not None:
                axes.append("resume-check")
            print(f"  {name:<22} n={sp.n_files:<5} "
                  f"windows={sp.n_windows:<3} seed={sp.seed:<3} "
                  + " ".join(axes))
        print("suites:")
        for name, (names, n_random) in SUITES.items():
            print(f"  {name:<22} {len(names)} presets "
                  f"+ {n_random} random cells")
        return 0

    if args.action == "run":
        suite = None
        if args.preset:
            if args.seed:
                # A preset names its PINNED workload; the shifted
                # variants the multi-seed CI sweep runs are suite cells.
                print("error: --preset runs the pinned cell and takes "
                      "no --seed — use --suite ... --seed N --cell "
                      f"{args.preset} for the shifted variant",
                      file=sys.stderr)
                return 2
            spec = preset(args.preset)
        elif args.cell:
            suite = args.suite
            cells = {c.name: c for c in suite_cells(suite, args.seed)}
            if args.cell not in cells:
                print(f"error: no cell {args.cell!r} in suite {suite!r} "
                      f"(have {sorted(cells)})", file=sys.stderr)
                return 2
            spec = cells[args.cell]
        elif args.spec:
            text = args.spec
            inline = text.lstrip().startswith("{")
            if not inline:
                try:
                    with open(text, encoding="utf-8") as f:
                        text = f.read()
                except OSError as e:
                    print(f"error: cannot read spec file {args.spec}: "
                          f"{e.strerror or e}", file=sys.stderr)
                    return 2
            try:
                doc = json.loads(text)
                if not isinstance(doc, dict):
                    raise ValueError("spec must be a JSON object")
                if "spec" in doc and isinstance(doc["spec"], dict):
                    # A banked search-corpus entry wraps the spec.
                    doc = doc["spec"]
                spec = ScenarioSpec.from_dict(doc)
            except (TypeError, ValueError) as e:
                src = "--spec" if inline else args.spec
                print(f"error: invalid scenario spec in {src}: {e}",
                      file=sys.stderr)
                return 2
        else:
            print("error: scenarios run needs --preset NAME, --cell NAME "
                  "(with --suite), or --spec JSON|FILE", file=sys.stderr)
            return 2
        cell = run_cell(spec, suite=suite,
                        suite_seed=args.seed if suite else 0)
        print(json.dumps(cell, indent=2))
        if not cell["ok"]:
            print(f"FAILED; repro: {cell['repro']}", file=sys.stderr)
            return 1
        return 0

    if args.action == "search":
        from .scenarios.search import (
            SEARCH_BASE,
            distill_corpus,
            load_corpus,
            run_search,
        )

        base = tuple(s for s in (args.base or "").split(",") if s) \
            or SEARCH_BASE
        try:
            out = run_search(
                seed=args.seed, budget_cells=args.budget_cells,
                budget_seconds=args.budget_seconds,
                corpus_dir=args.corpus, base=base,
                progress=lambda line: print(line, file=sys.stderr,
                                            flush=True))
        except (KeyError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.distill:
            d = distill_corpus(load_corpus(args.corpus))
            os.makedirs(args.corpus, exist_ok=True)
            path = os.path.join(args.corpus, "distilled.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(d, f, indent=2, sort_keys=True)
                f.write("\n")
            out["distilled"] = {"path": path, "names": d["names"],
                                "coverage_bits": d["coverage_bits"],
                                "fingerprint": d["fingerprint"]}
        if args.out:
            parent = os.path.dirname(args.out)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(out, f, indent=2)
                f.write("\n")
        digest = {k: out[k] for k in (
            "seed", "budget_cells", "iterations", "cells_run",
            "baseline_bits", "coverage_bits", "new_coverage_cells",
            "fingerprint", "seconds")}
        digest["violations"] = len(out["violations"])
        if "distilled" in out:
            digest["distilled"] = out["distilled"]
        print(json.dumps(digest, indent=2))
        # Violations are banked FINDINGS (with shrunk repro lines), not
        # sweep regressions: the search exits green so a nightly soak
        # keeps accumulating corpus instead of aborting at first blood.
        for v in out["violations"]:
            sh = v.get("shrunk") or {}
            print(f"finding: {','.join(v.get('failed') or ()) or v.get('error')}"
                  f" — repro: {sh.get('repro') or v['repro']}",
                  file=sys.stderr)
        return 0

    if args.action == "triage":
        from .scenarios.search import triage_corpus

        out = triage_corpus(
            args.corpus,
            progress=lambda line: print(line, file=sys.stderr,
                                        flush=True))
        if args.out:
            parent = os.path.dirname(args.out)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(args.out, "w", encoding="utf-8") as f:
                json.dump(out, f, indent=2, sort_keys=True)
                f.write("\n")
        print(json.dumps({k: out[k] for k in (
            "names", "n_violations", "ok", "seconds")}, indent=2))
        if not out["ok"]:
            # A still-red violation means the bug it banked is NOT
            # fixed: do not promote, do fail the build.
            for r in out["results"]:
                if not r["ok"]:
                    print(f"STILL RED: {r['name']} "
                          f"({','.join(r['failed'])})\n"
                          f"  repro: {r['repro']}", file=sys.stderr)
            return 1
        return 0

    # sweep
    from .scenarios.sweep import format_cell_line, run_sweep

    try:
        out = run_sweep(
            args.suite, seed=args.seed, round_no=args.round_no,
            history=args.history or None,
            extra=args.extra_cells or None,
            progress=lambda line: print(line, file=sys.stderr,
                                        flush=True))
    except ValueError as e:
        # run_cells validates the seed/round/history combination before
        # any cell runs (per-cell baselines are defined at seed 0).
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.metrics:
        from .obs import JsonlSink

        sink = JsonlSink(args.metrics)
        try:
            for c in out["cells"]:
                sink.emit({"kind": "cell",
                           **{k: v for k, v in c.items() if k != "spec"}})
        finally:
            sink.close()
    if args.out:
        parent = os.path.dirname(args.out)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
    digest = {k: out[k] for k in ("suite", "seed", "n_cells", "n_failed",
                                  "invariants_checked", "ok", "seconds")}
    if "round" in out:
        digest["round"] = out["round"]
    if "history_appended" in out:
        digest["history_appended"] = out["history_appended"]
    print(json.dumps(digest, indent=2))
    if not out["ok"]:
        for c in out["cells"]:
            if not c["ok"]:
                print(format_cell_line(c), file=sys.stderr)
        return 1
    return 0


def _cmd_bench(args) -> int:
    import contextlib

    try:
        from .benchmarks.harness import run_bench
    except ImportError as e:
        print(f"benchmark harness not available: {e}", file=sys.stderr)
        return 1
    with contextlib.ExitStack() as stack:
        tel = _open_telemetry(args, stack, f"bench.config{args.config}")
        if tel is not None:
            # Tracing would swap the timed kernels for their traced
            # variants — benches carry spans/counters only.
            tel.kmeans_trace = False
        out = run_bench(config=args.config, backend=args.backend,
                        mesh_shape=_parse_mesh(args.mesh),
                        update=getattr(args, "update", None),
                        e2e=getattr(args, "e2e", False),
                        dtype=getattr(args, "dtype", None))
    from .obs import run_metadata

    out["run_meta"] = run_metadata()
    print(json.dumps(out))
    return 0


def _cmd_metrics(args) -> int:
    """Inspect a telemetry JSONL stream (obs/metrics_cli.py)."""
    from .obs.metrics_cli import main as metrics_main

    return metrics_main(args.rest)


def _cmd_trace(args) -> int:
    """Per-decision causal traces of the streaming daemon
    (obs/trace.py): list decisions slowest-first, render one decision's
    span tree, export deterministic Chrome/Perfetto JSON."""
    from .obs.trace import main as trace_main

    return trace_main(args.rest)


def _cmd_status(args) -> int:
    """One-shot consumer of a live daemon's operational plane
    (obs/httpz.py): fetch /statusz (+probe verdicts) from a daemon
    started with --http and render the compact status block."""
    from .obs.metrics_cli import base_url, fetch_statusz, statusz_lines

    base = base_url(args.url)
    try:
        doc = fetch_statusz(base)
    except (OSError, ValueError) as e:
        print(f"error: {base} unreachable: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(doc, sort_keys=True, indent=1))
        return 0
    for line in statusz_lines(base, doc):
        print(line)
    # Probe verdicts ride along: the two bits a balancer would read.
    import urllib.error
    import urllib.request

    for probe in ("/readyz", "/healthz"):
        try:
            with urllib.request.urlopen(base + probe, timeout=5) as r:
                body = r.read().decode("utf-8").strip()
                code = r.status
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8").strip()
            code = e.code
        except OSError as e:
            body, code = str(e), None
        print(f"{probe}:  {code} {body}")
    return 0


def _cmd_explain(args) -> int:
    """Decision provenance (obs/explain.py): reconstruct why a file
    lives where it does, why a category scored what it did, or what a
    window's signals/traffic/alerts were — offline, from the metrics
    JSONL + checkpoint."""
    from .obs.explain import main as explain_main

    return explain_main(args.rest)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdrs", description="Clustering-driven replication strategy (TPU-native)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("gen", help="generate synthetic file population")
    p.add_argument("--n", type=int, default=200)
    p.add_argument("--hdfs_dir", default="/user/root/synth")
    p.add_argument("--min_size", type=int, default=1024)
    p.add_argument("--max_size", type=int, default=1024 * 1024)
    p.add_argument("--nodes", default="dn1,dn2,dn3")
    p.add_argument("--age_days_max", type=float, default=365.0)
    p.add_argument("--out_manifest", default="metadata.csv")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--write_payloads", action="store_true")
    p.set_defaults(fn=_cmd_gen)

    p = sub.add_parser("simulate", help="simulate Poisson access events")
    p.add_argument("--manifest", required=True)
    p.add_argument("--out", default="access.log")
    p.add_argument("--duration_seconds", type=float, default=300.0)
    p.add_argument("--clients", default="dn1,dn2,dn3,dn4")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--engine", choices=["numpy", "native"], default="numpy",
                   help="'native' = threaded C++ generator (runtime/native.py)")
    p.add_argument("--format", choices=["auto", "csv", "binary"],
                   default="auto",
                   help="log format: 'csv' = the reference access.log "
                        "contract; 'binary' = the columnar .cdrsb fast path "
                        "(every reader auto-detects it); 'auto' = binary "
                        "when --out ends in .cdrsb")
    p.set_defaults(fn=_cmd_simulate)

    p = sub.add_parser("features", help="extract the 5 per-file features")
    p.add_argument("--manifest", required=True)
    p.add_argument("--access_log", required=True)
    p.add_argument("--out", default="features_out/")
    _add_backend_arg(p)  # --mesh shards the event stream over chips
    p.set_defaults(fn=_cmd_features)

    p = sub.add_parser("cluster", help="KMeans++ clustering + category scoring")
    p.add_argument("--input_path", required=True)
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--output_csv", default="final_categories.csv")
    p.add_argument("--assignments_csv", default=None)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--medians_from_data", action="store_true")
    p.add_argument("--scoring_config", default=None, metavar="JSON|validated",
                   help="weights/directions/medians/rf config file, or "
                        "'validated' for the built-in workload-tuned tables")
    _add_backend_arg(p)
    _add_init_method_arg(p)
    _add_metrics_arg(p)
    p.set_defaults(fn=_cmd_cluster)

    p = sub.add_parser("pipeline", aliases=["run"],
                       help="end-to-end: gen -> sim -> features -> cluster")
    p.add_argument("--n", type=int, default=200)
    p.add_argument("--duration_seconds", type=float, default=600.0)
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--outdir", default="output")
    p.add_argument("--medians_from_data", action="store_true")
    p.add_argument("--scoring_config", default=None, metavar="JSON|validated")
    p.add_argument("--evaluate", action="store_true",
                   help="apply decided rf on the simulated cluster and report "
                        "locality/load/storage vs uniform baselines")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace (TensorBoard/Perfetto)")
    _add_backend_arg(p)
    _add_init_method_arg(p)
    _add_metrics_arg(p)
    p.set_defaults(fn=_cmd_pipeline)

    p = sub.add_parser("evaluate", help="apply replication factors on the "
                       "simulated cluster; report locality/load/storage")
    p.add_argument("--manifest", required=True)
    p.add_argument("--access_log", required=True)
    p.add_argument("--assignments_csv", required=True,
                   help="per-file path,cluster,category table "
                        "(cluster --assignments_csv output)")
    p.add_argument("--nodes", default=None,
                   help="datanode names (default: manifest nodes)")
    p.add_argument("--default_rf", type=int, default=1)
    p.add_argument("--scoring_config", default=None, metavar="JSON",
                   help="scoring config the assignments were produced with "
                        "(source of the category -> replication-factor table)")
    p.add_argument("--emit_plan", default=None, metavar="CSV",
                   help="write the per-file target-rf plan (path,category,rf)"
                        " — the exportable decision a real cluster can apply")
    p.add_argument("--emit_setrep", default=None, metavar="SH",
                   help="write an 'hdfs dfs -setrep' command list applying "
                        "the plan on a live HDFS")
    p.set_defaults(fn=_cmd_evaluate)

    p = sub.add_parser("stream", help="stream the access log in batches, then cluster")
    p.add_argument("--manifest", required=True)
    p.add_argument("--access_log", required=True)
    p.add_argument("--batch_size", type=int, default=1_000_000,
                   help="events per feature-fold batch")
    p.add_argument("--kmeans_batch", type=int, default=None, metavar="ROWS",
                   help="rows per incremental mini-batch KMeans step "
                        "(jax backend; default: full-batch Lloyd)")
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--output_csv", default="final_categories.csv")
    p.add_argument("--medians_from_data", action="store_true")
    p.add_argument("--scoring_config", default=None, metavar="JSON")
    p.add_argument("--checkpoint", default=None, metavar="NPZ",
                   help="crash-safe folding (jax backend): snapshot the fold "
                        "state + log offset here every --checkpoint_every "
                        "batches; rerunning the same command resumes")
    p.add_argument("--checkpoint_every", type=int, default=25, metavar="B")
    _add_backend_arg(p)
    _add_init_method_arg(p)
    _add_metrics_arg(p)
    p.set_defaults(fn=_cmd_stream)

    def _add_control_args(p: argparse.ArgumentParser) -> None:
        """Options shared by the control and chaos subcommands."""
        p.add_argument("--manifest", required=True)
        p.add_argument("--access_log", required=True,
                       help="globally time-sorted log (CSV access.log or "
                            ".cdrsb)")
        p.add_argument("--window_seconds", type=float, default=60.0)
        p.add_argument("--k", type=int, default=8)
        p.add_argument("--seed", type=int, default=42)
        p.add_argument("--drift_threshold", type=float, default=0.05,
                       help="drift score at/above which a re-cluster runs")
        p.add_argument("--full_drift", type=float, default=0.30,
                       metavar="SCORE",
                       help="drift at/above which the warm start is "
                            "abandoned (fresh init, full iteration budget)")
        p.add_argument("--warm_max_iter", type=int, default=25)
        p.add_argument("--max_bytes", type=int, default=None, metavar="BYTES",
                       help="per-window migration byte budget (default: "
                            "unbounded); chaos runs charge repair traffic "
                            "against the same budget first")
        p.add_argument("--max_files", type=int, default=None, metavar="N",
                       help="per-window migrated-file cap (default: "
                            "unbounded)")
        p.add_argument("--hysteresis", type=int, default=1, metavar="WINDOWS",
                       help="windows a migrated file stays frozen "
                            "(anti-flap)")
        p.add_argument("--decay", type=float, default=1.0,
                       help="per-window feature-counter decay; < 1.0 "
                            "re-weights toward recent traffic (numpy "
                            "backend)")
        p.add_argument("--default_rf", type=int, default=1)
        p.add_argument("--batch_size", type=int, default=1_000_000,
                       help="events per log read batch (windows re-slice "
                            "it)")
        _add_metrics_arg(p)  # window records interleave with the telemetry
        p.add_argument("--plan_out", default=None, metavar="CSV",
                       help="write the final applied plan "
                            "(path,category,rf)")
        p.add_argument("--checkpoint", default=None, metavar="NPZ",
                       help="snapshot the controller state here every "
                            "--checkpoint_every windows; rerunning the same "
                            "command resumes with an identical plan "
                            "sequence")
        p.add_argument("--checkpoint_every", type=int, default=1,
                       metavar="W")
        p.add_argument("--max_windows", type=int, default=None,
                       help="stop after N processed windows (stepping a "
                            "live controller)")
        p.add_argument("--no_evaluate", action="store_true",
                       help="skip the per-window locality/balance replay")
        p.add_argument("--overlap", action="store_true",
                       help="double-buffer windows: dispatch window t+1's "
                            "(jit'd) cluster step before window t's host "
                            "planning runs (JAX async dispatch); "
                            "decision-identical to the serial order, "
                            "suspended around checkpoints")
        p.add_argument("--serve", action="store_true",
                       help="route every window's reads through the read "
                            "router (serve/): latency p50/p95/p99, SLO "
                            "burn, utilization and hotspot fields on the "
                            "window records; hotspot spikes trigger "
                            "re-clusters")
        p.add_argument("--serve_policy",
                       choices=["primary", "random", "least_loaded",
                                "p2c"], default="p2c")
        p.add_argument("--serve_seed", type=int, default=0)
        p.add_argument("--serve_service_ms", type=float, default=0.5)
        p.add_argument("--serve_slo_ms", type=float, default=10.0)
        p.add_argument("--serve_slo_availability", type=float,
                       default=0.999)
        p.add_argument("--no_hotspot_recluster", action="store_true",
                       help="observe hotspots without feeding them back "
                            "into the re-cluster trigger")
        p.add_argument("--placement",
                       choices=["materialized", "functional",
                                "materialized_hash"],
                       default="materialized",
                       help="placement representation (placement_fn/): "
                            "'materialized' = the historical rng chooser "
                            "+ dense replica-map state; 'functional' = "
                            "CRUSH-style stateless hash chooser — "
                            "checkpoints store only per-file exceptions "
                            "over the computed base and serve-mode reads "
                            "resolve replicas on the fly; "
                            "'materialized_hash' = the hash chooser over "
                            "the dense representation (the equivalence "
                            "oracle)")
        p.add_argument("--medians_from_data", action="store_true")
        p.add_argument("--scoring_config", default=None,
                       metavar="JSON|validated")
        p.add_argument("--storage_config", default=None,
                       metavar="JSON|replicate|ec_archival",
                       help="storage strategies (cdrs_tpu/storage): a "
                            "JSON config mapping categories to "
                            "replicate(rf)/ec(k,m) strategies on "
                            "hot/warm/cold tiers, 'replicate' for the "
                            "explicit degenerate config, or "
                            "'ec_archival' for the built-in EC(6,3)-"
                            "cold Archival preset; inspect with "
                            "'cdrs storage show'")
        _add_backend_arg(p)
        _add_init_method_arg(p)

    p = sub.add_parser("control", help="online replication controller: "
                       "windowed drift detection -> incremental re-cluster "
                       "-> bounded-churn migration")
    _add_control_args(p)
    p.set_defaults(fn=_cmd_control)

    p = sub.add_parser("daemon", help="always-on streaming controller: "
                       "tail the growing binary event log, decide per "
                       "window, publish epoch-pinned placements, "
                       "checkpoint with an ingest cursor for bit-identical "
                       "resume")
    _add_control_args(p)
    p.add_argument("--follow", action="store_true",
                   help="keep tailing the log for appended blocks "
                        "(default: process to EOF once and exit)")
    p.add_argument("--poll", type=float, default=0.5, metavar="SECONDS",
                   help="follow-mode poll cadence")
    p.add_argument("--max_seconds", type=float, default=None,
                   help="stop (checkpoint + digest) after this much wall "
                        "clock")
    p.add_argument("--recluster", choices=["controller", "minibatch"],
                   default="controller",
                   help="'minibatch' additionally advances a warm-started "
                        "mini-batch Lloyd step per window (live "
                        "centroid/inertia telemetry; jax backend; "
                        "decisions unchanged)")
    p.add_argument("--minibatch_rows", type=int, default=2048,
                   metavar="ROWS")
    p.add_argument("--digest_out", default=None, metavar="JSON",
                   help="additionally write the final digest here")
    p.add_argument("--http", default=None, metavar="HOST:PORT",
                   help="serve the live operational plane while running "
                        "(obs/httpz.py): /metrics (Prometheus), "
                        "/healthz, /readyz, /statusz, /debug/trace — "
                        "off the decision path; port 0 binds an "
                        "ephemeral port (printed to stderr)")
    p.add_argument("--brownout", action="store_true",
                   help="engage the overload brownout ladder "
                        "(daemon/brownout.py): as decision lag crosses "
                        "each rung's threshold, shed optional work in "
                        "fixed order (skip minibatch -> defer scrub -> "
                        "cap trace exemplars -> coalesce windows -> "
                        "shed a bounded fraction of reads), recovering "
                        "hysteretically in reverse")
    p.add_argument("--brownout_engage", default=None, metavar="CSV",
                   help="5 comma-separated lag-window thresholds, one "
                        "per rung (default 2,3,4,6,8)")
    p.add_argument("--brownout_release", default=None, metavar="CSV",
                   help="5 release thresholds, each strictly below its "
                        "engage threshold (default 1,1.5,2,3,4)")
    p.add_argument("--shed_fraction", type=float, default=None,
                   metavar="F",
                   help="fraction of reads rejected while the shed_reads "
                        "rung is engaged (default 0.2)")
    p.add_argument("--supervise", action="store_true",
                   help="run under the crash supervisor "
                        "(daemon/supervise.py): restart on abnormal "
                        "exit with capped exponential backoff — safe "
                        "because a killed daemon resumes bit-identically "
                        "from its last durable cursor")
    p.add_argument("--max_restarts", type=int, default=5, metavar="N",
                   help="give up after N consecutive crash-restarts")
    p.set_defaults(fn=_cmd_daemon)

    p = sub.add_parser("chaos", help="fault-injected controller run: node "
                       "crash/recover/decommission/flaky events, durability "
                       "accounting, self-healing re-replication under the "
                       "migration churn budget")
    _add_control_args(p)
    p.add_argument("--kill", action="append", metavar="NODE@W[-W2]",
                   help="crash NODE at window W (optionally recovering "
                        "after W2, e.g. dn2@3-7); repeatable")
    p.add_argument("--recover", action="append", metavar="NODE@W",
                   help="recover a crashed NODE at window W; repeatable")
    p.add_argument("--decommission", action="append", metavar="NODE@W",
                   help="permanently remove NODE at window W (replicas "
                        "destroyed); repeatable")
    p.add_argument("--flaky", action="append", metavar="NODE@W[-W2][:P]",
                   help="repair copies to NODE fail with probability P "
                        "(default 0.5) over windows W..W2, e.g. "
                        "dn1@2-6:0.5; repeatable")
    p.add_argument("--racks", default=None, metavar="SPEC",
                   help="failure domains: ';'-separated rack groups, each "
                        "'name=n1,n2' or bare 'n1,n2' (auto-named), e.g. "
                        "'r0=dn1,dn2;r1=dn3,dn4' — placement spreads "
                        "replicas across racks, durability accounting "
                        "gains the correlated-risk tier")
    p.add_argument("--topology", default=None, metavar="JSON|FILE",
                   help="geo-hierarchical failure domains (inline JSON "
                        "or a file): {'nodes': [...], 'levels': "
                        "['rack', 'region'], 'rack': {'r0': "
                        "['dn1','dn2'], ...}, 'region': {'eu': "
                        "['r0','r1'], ...}, 'edge_bytes': {...}, "
                        "'edge_latency': {...}} — placement spreads "
                        "replicas across the HIGHEST level first, "
                        "repair charges WAN copies their edge byte "
                        "cost, durability reports per-level correlated "
                        "risk, and fault specs accept domain scopes "
                        "(crash:region:eu@3-7).  Mutually exclusive "
                        "with --racks")
    p.add_argument("--partition", action="append",
                   metavar="NODES@W[-W2]",
                   help="network-partition a '+'-joined node set over "
                        "windows W..W2 (unreachable as a group, replicas "
                        "intact), e.g. dn1+dn2@4-6; repeatable")
    p.add_argument("--degrade", action="append",
                   metavar="NODE@W[-W2][:M]",
                   help="straggler: NODE moves repair bytes at Mx nominal "
                        "throughput (default 0.5) over windows W..W2 — "
                        "copies through it charge size/M of the churn "
                        "budget, e.g. dn3@2-6:0.25; repeatable")
    p.add_argument("--corrupt", action="append",
                   metavar="NODE[#FILE]@W[:F]",
                   help="SILENT corruption: rot a seeded fraction F "
                        "(default 0.1) of NODE's copies at window W "
                        "(dn2@3:0.25), or exactly FILE's copy on NODE "
                        "(dn2#17@3) — invisible until a verified read "
                        "(--scrub, the serve read path, or a repair "
                        "source check) touches it; repeatable")
    p.add_argument("--scrub", type=int, default=None, metavar="BYTES",
                   help="background scrubber (faults/scrub.py): "
                        "verification-read BYTES per window round-robin "
                        "over the population — capped by what remains of "
                        "the shared churn budget after repairs — "
                        "quarantining latent corruption into the repair "
                        "queue")
    p.add_argument("--no_verify_reads", action="store_true",
                   help="with --serve: serve rotten copies as if intact "
                        "(the unverified baseline; reads_corrupt_served "
                        "counts the garbage) instead of detect-and-"
                        "redirect")
    p.add_argument("--schedule", default=None, metavar="JSON",
                   help="load additional fault events from a JSON file "
                        "(the --schedule_out format)")
    p.add_argument("--schedule_out", default=None, metavar="JSON",
                   help="write the expanded schedule here (replayable via "
                        "--schedule)")
    p.add_argument("--random_faults", type=int, default=None, metavar="W",
                   help="add a seeded random schedule spanning W windows "
                        "(never downs the last node)")
    p.add_argument("--fault_seed", type=int, default=0,
                   help="seed of --random_faults")
    p.add_argument("--corrupt_rate", type=float, default=0.0,
                   help="with --random_faults: per-window probability an "
                        "up node silently rots a seeded fraction of its "
                        "copies (default 0 = no corruption rolls, "
                        "pre-existing schedules unchanged)")
    p.add_argument("--corrupt_frac", type=float, default=0.05,
                   help="fraction of a node's copies each --corrupt_rate "
                        "event rots")
    p.add_argument("--repair_seed", type=int, default=0,
                   help="seed of the deterministic flaky-failure rolls")
    p.set_defaults(fn=_cmd_chaos)

    p = sub.add_parser("serve", help="read-path SLO replay: route the log's "
                       "reads against a placement (replica-selection "
                       "policies, FIFO queue latency model, p99/SLO burn, "
                       "hotspot detection; optional partitions/stragglers)")
    p.add_argument("--manifest", required=True)
    p.add_argument("--access_log", required=True,
                   help="globally time-sorted log (CSV access.log or "
                        ".cdrsb)")
    p.add_argument("--window_seconds", type=float, default=60.0)
    p.add_argument("--policy", choices=["primary", "random", "least_loaded",
                                        "p2c"], default="p2c",
                   help="replica selection: primary-only | seeded random | "
                        "global least-loaded | power-of-two-choices "
                        "(default)")
    p.add_argument("--seed", type=int, default=0,
                   help="replica-choice seed (per-window streams derive "
                        "from it)")
    p.add_argument("--default_rf", type=int, default=2)
    p.add_argument("--service_ms", type=float, default=0.5,
                   help="per-read service time at nominal node throughput")
    p.add_argument("--slo_ms", type=float, default=10.0,
                   help="read-latency SLO target")
    p.add_argument("--slo_availability", type=float, default=0.999,
                   help="SLO availability objective (error budget = 1 - "
                        "this)")
    p.add_argument("--racks", default=None, metavar="SPEC",
                   help="failure domains (the chaos --racks spec): "
                        "placement spreads replicas across racks")
    p.add_argument("--placement",
                   choices=["materialized", "functional",
                            "materialized_hash"],
                   default="materialized",
                   help="placement source: 'functional' resolves each "
                        "window's replicas on the fly through the "
                        "CRUSH-style hash chooser (no materialized map "
                        "— O(unique files) router memory); "
                        "'materialized_hash' materializes the same "
                        "chooser (the equivalence oracle); default is "
                        "the historical rng chooser")
    p.add_argument("--kill", action="append", metavar="NODE@W[-W2]",
                   help="crash NODE over windows W..W2; repeatable")
    p.add_argument("--partition", action="append", metavar="NODES@W[-W2]",
                   help="network-partition a '+'-joined node set; "
                        "repeatable")
    p.add_argument("--degrade", action="append", metavar="NODE@W[-W2][:M]",
                   help="straggler: NODE serves reads at Mx nominal speed "
                        "(service time / M); repeatable")
    p.add_argument("--corrupt", action="append",
                   metavar="NODE[#FILE]@W[:F]",
                   help="silently rot NODE's copies at window W (the "
                        "chaos --corrupt spec); reads that select a "
                        "rotten copy detect + redirect (or, with "
                        "--no_verify_reads, serve the garbage)")
    p.add_argument("--no_verify_reads", action="store_true",
                   help="serve rotten copies as if intact "
                        "(reads_corrupt_served counts the damage)")
    p.add_argument("--batch_size", type=int, default=1_000_000)
    p.add_argument("--max_windows", type=int, default=None)
    _add_metrics_arg(p)
    p.set_defaults(fn=_cmd_serve)

    p = sub.add_parser("storage", help="storage strategies: resolve a "
                       "replicate/EC/tier config ('show') or estimate "
                       "its byte/cost footprint over a manifest "
                       "('estimate')")
    p.add_argument("action", choices=["show", "estimate"],
                   help="show = resolved per-category strategy table; "
                        "estimate = byte/cost footprint of a category "
                        "assignment vs the replicate baseline")
    p.add_argument("--storage_config", default="ec_archival",
                   metavar="JSON|replicate|ec_archival",
                   help="strategy config (default: the built-in "
                        "EC(6,3)-cold Archival preset)")
    p.add_argument("--manifest", default=None,
                   help="(estimate) manifest CSV")
    p.add_argument("--assignments_csv", default=None,
                   help="(estimate) per-file path,cluster,category table "
                        "(cluster --assignments_csv / control --plan_out)")
    p.add_argument("--medians_from_data", action="store_true")
    p.add_argument("--scoring_config", default=None,
                   metavar="JSON|validated",
                   help="scoring config supplying the replicate-fallback "
                        "rf table")
    p.set_defaults(fn=_cmd_storage)

    p = sub.add_parser("scenarios", help="declarative scenario matrix: "
                       "list presets/suites, run one cell, or sweep a "
                       "suite gated on invariants (zero silent loss, "
                       "churn budget, domain diversity, SLO, sampled "
                       "kill/resume bit-identity)")
    p.add_argument("action",
                   choices=["list", "run", "sweep", "search", "triage"],
                   help="list = named presets + suites; run = one cell "
                        "(--preset / --suite+--cell / --spec); sweep = "
                        "every cell of --suite, nonzero exit on any "
                        "invariant failure; search = seeded coverage-"
                        "guided failure-space search (mutate corpus "
                        "cells, keep new-coverage ones, shrink "
                        "violations to minimal repros); triage = rerun "
                        "every banked violation and promote the green "
                        "ones into regression-locked triage-* cells "
                        "(nonzero exit while any still reproduces)")
    p.add_argument("--suite", default="ci-smoke",
                   help="cell suite (default ci-smoke; see 'scenarios "
                        "list')")
    p.add_argument("--seed", type=int, default=0,
                   help="suite seed: deterministically parameterizes the "
                        "random cells")
    p.add_argument("--preset", default=None, metavar="NAME",
                   help="(run) a named preset cell")
    p.add_argument("--cell", default=None, metavar="NAME",
                   help="(run) one cell of --suite — the failing-cell "
                        "repro path")
    p.add_argument("--spec", default=None, metavar="JSON|FILE",
                   help="(run) an inline spec JSON object or a path to "
                        "one")
    p.add_argument("--out", default=None, metavar="JSON",
                   help="(sweep/triage) write the full artifact here "
                        "(sweep: per-cell invariants, metrics, "
                        "bench_records; triage: the promoted cell file "
                        "for --extra_cells)")
    p.add_argument("--extra_cells", action="append", default=None,
                   metavar="JSON",
                   help="(sweep) corpus cell file(s) to ride along with "
                        "the suite — distilled.json / triage.json "
                        "({'cells': [...], 'names': [...]}); pinned "
                        "repros, never seed-shifted; repeatable")
    p.add_argument("--round", type=int, default=None, dest="round_no",
                   help="(sweep) PR-round stamp: appends the per-cell "
                        "bench_records to --history (regress."
                        "append_history, deduped — re-runs never "
                        "double-append)")
    p.add_argument("--history", default="data/bench_history.jsonl",
                   metavar="JSONL",
                   help="(sweep) trajectory history the per-cell records "
                        "append to when --round is given")
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="(sweep) emit per-cell records as 'cell' events "
                        "here; 'cdrs metrics summarize' renders a "
                        "Scenarios digest")
    p.add_argument("--budget-cells", type=int, default=50,
                   dest="budget_cells",
                   help="(search) mutation iterations to attempt "
                        "(deterministic in --seed; default 50)")
    p.add_argument("--budget-seconds", type=float, default=None,
                   dest="budget_seconds",
                   help="(search) wall-clock cap: truncates the same "
                        "seeded sequence (the nightly-soak bound)")
    p.add_argument("--corpus", default="data/search_corpus",
                   metavar="DIR",
                   help="(search/triage) corpus directory: banked cells "
                        "seed the next run's frontier; violations land "
                        "under violations/ with shrunk repro lines")
    p.add_argument("--base", default=None, metavar="P1,P2,...",
                   help="(search) comma-separated preset names seeding "
                        "the corpus (default: the cheap cross-domain "
                        "SEARCH_BASE set)")
    p.add_argument("--distill", action="store_true",
                   help="(search) after the run, greedily distill the "
                        "banked corpus to a minimal cell set covering "
                        "the whole discovered frontier "
                        "(<corpus>/distilled.json, deterministic)")
    p.set_defaults(fn=_cmd_scenarios)

    p = sub.add_parser("bench", help="benchmark harness (BASELINE.md configs)")
    p.add_argument("--config", type=int, default=1)
    p.add_argument("--update",
                   choices=["auto", "matmul", "scatter", "pallas"],
                   default=None,
                   help="Lloyd assign+reduce strategy (default: the config's; "
                        "auto = pallas on TPU, matmul elsewhere)")
    p.add_argument("--e2e", action="store_true",
                   help="measure wall-clock time-to-categories (sharded "
                        "features -> kmeans -> scoring -> host) instead of "
                        "Lloyd iterations/sec")
    p.add_argument("--dtype", choices=["float32", "bfloat16", "float64"],
                   default=None,
                   help="points dtype override (jax configs; bfloat16 halves "
                        "the HBM stream — centroids/stats stay float32)")
    _add_backend_arg(p, default=None)  # None = the config's own backend
    _add_metrics_arg(p)
    p.set_defaults(fn=_cmd_bench)

    p = sub.add_parser("metrics", help="inspect a telemetry JSONL stream: "
                       "summarize | tail | export | report | watch | "
                       "alerts | regress")
    p.add_argument("rest", nargs=argparse.REMAINDER,
                   help="summarize FILE | tail FILE [-n N] | "
                        "export FILE --format prometheus [--out FILE] | "
                        "report FILE [-o HTML] | watch FILE | "
                        "alerts FILE [--follow] [--rules JSON] | "
                        "regress RUN.json [--report-only]")
    p.set_defaults(fn=_cmd_metrics)

    p = sub.add_parser("trace", help="per-decision causal traces of the "
                       "streaming daemon: list | show | export "
                       "(Chrome/Perfetto trace_event JSON)")
    p.add_argument("rest", nargs=argparse.REMAINDER,
                   help="list FILE [--limit N] | show FILE WINDOW | "
                        "export FILE [--out JSON] [--canonical]")
    p.set_defaults(fn=_cmd_trace)

    p = sub.add_parser("status", help="one-shot status of a live daemon "
                       "started with --http: /statusz digest plus the "
                       "/readyz and /healthz probe verdicts")
    p.add_argument("url", metavar="HOST:PORT|URL",
                   help="the daemon's --http address (scheme optional)")
    p.add_argument("--json", action="store_true",
                   help="print the raw /statusz JSON instead of the "
                        "human block")
    p.set_defaults(fn=_cmd_status)

    p = sub.add_parser("explain", help="decision provenance: why a file "
                       "lives where it does (slot-by-slot chooser "
                       "narration + cause-tagged move history), why a "
                       "category scored what it did (per-feature "
                       "Table-2 decomposition), what a window's "
                       "signals/traffic/alerts were")
    p.add_argument("rest", nargs=argparse.REMAINDER,
                   help="file ID --manifest CSV [--metrics JSONL] "
                        "[--checkpoint NPZ] [--topology JSON|--racks "
                        "SPEC] | category NAME --checkpoint NPZ | "
                        "window W --metrics JSONL")
    p.set_defaults(fn=_cmd_explain)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
