"""Serving layer: vectorized read routing, tail-latency SLOs, hotspots.

The read path the placement pipeline was missing (ROADMAP open item 2):

* ``router`` — batched replica selection (primary / random /
  least-loaded / power-of-two-choices) over the live replica map with
  reachability masks and straggler throughput factors from ``faults``,
  plus an exact per-node FIFO queue model yielding a latency sample per
  read — p50/p95/p99 and SLO burn per window.
* ``hotspot`` — EWMA top-k per-file spike detector whose firing feeds
  back into the controller as a drift signal (flash crowd -> re-cluster
  without waiting for cumulative feature drift).

Consumed by ``ControllerConfig.serve`` (control/controller.py), the
``cdrs serve`` CLI, and ``benchmarks/serve_bench.py``.  numpy-only: a
base install can serve.
"""

from .hotspot import HotspotDetector, HotspotResult
from .router import (
    POLICIES,
    ReadRouter,
    ServeConfig,
    SloSpec,
    WindowServeResult,
    emit_window_telemetry,
)
from .view import ReadView, read_view

__all__ = [
    "POLICIES",
    "HotspotDetector",
    "HotspotResult",
    "ReadRouter",
    "ReadView",
    "ServeConfig",
    "SloSpec",
    "WindowServeResult",
    "emit_window_telemetry",
    "read_view",
]
