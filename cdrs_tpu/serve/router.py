"""Vectorized read router: request-level serving against a live placement.

The pipeline decides *where replicas live*; until now nothing served reads
against them — ``cluster/evaluate.py`` replays locality offline, so the
best reportable number was a hit ratio.  The observable that matters in a
serving system is **tail latency under load** (Dean & Barroso, *The Tail
at Scale*: p99 is the product metric, replica choice is the lever), which
only a request-level model can produce.  This router is that model, fully
vectorized over the access log — no per-request Python:

* **Replica selection** per read, among the file's REACHABLE replicas
  (``faults.ClusterState`` masks; a static placement treats every assigned
  slot as reachable).  A client holding a replica is always served
  locally (the HDFS short-circuit read, and exactly the locality rule of
  cluster/evaluate.py — the router's locality equals the offline replay's
  by construction).  Remote reads pick a replica by policy:

  - ``primary``       — first reachable slot (slot 0 is the placement's
                        primary; under faults, the first survivor).
  - ``random``        — seeded uniform over reachable replicas
                        (cluster/evaluate.py's remote rule).
  - ``least_loaded``  — the reachable replica on the node with the least
                        accumulated busy-time (global knowledge).
  - ``p2c``           — power-of-two-choices (Mitzenmacher): two seeded
                        random probes, keep the less-loaded — near
                        least-loaded quality at random-choice cost, the
                        classic tail-latency lever.

  Load feedback for ``least_loaded``/``p2c`` is batch-synchronous: reads
  route in time-ordered chunks (``ServeConfig.chunk``) against a load
  snapshot taken at the chunk boundary, then the snapshot absorbs the
  chunk.  Decisions inside a chunk share one snapshot — the approximation
  that keeps the router vectorized; chunk size trades fidelity for speed.

* **Queue model** per node: single FIFO server with a constant per-read
  service time ``service_ms / node_throughput`` — the straggler factors
  from ``faults`` (``degrade:dn3@2-6:0.25``) directly stretch service
  times.  For constant service time ``s`` the FIFO recurrence
  ``f_k = max(a_k, f_{k-1}) + s`` has the closed vectorized form
  ``f_k = s·(k+1) + max_{j<=k}(a_j − s·j)`` (a running max), so every
  read gets an EXACT latency sample — queueing delay included — with one
  ``np.maximum.accumulate`` per node.  An overloaded node (arrival rate
  above ``1/s``) builds queue linearly and its tail blows up, which is
  precisely the behaviour replica-selection policies exist to avoid.

* **SLO accounting**: an ``SloSpec`` (``target_ms``, ``availability``)
  turns the latency samples into burn — the fraction of reads over target
  (plus unavailable reads) divided by the error budget ``1 −
  availability``; burn > 1 means the window consumed more than its
  share of the budget.

Reads of files with zero reachable replicas are **unavailable**: no
latency sample, counted separately (they are the numerator of the
``unavailable_read_fraction`` durability metric).

Determinism: given (replica map, masks, throughputs, events, policy,
seed) the routing — and therefore every latency percentile — is
bit-reproducible; the controller seeds the per-window rng from
``(ServeConfig.seed, window_index)`` so kill/resume replays identically.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

__all__ = ["POLICIES", "SloSpec", "ServeConfig", "WindowServeResult",
           "ReadRouter", "emit_window_telemetry"]


def emit_window_telemetry(tel, rec: dict, latency_ms=None) -> None:
    """One serving window's observations through a Telemetry instrument.

    The SINGLE emission path for the ``serve.*`` schema (the table in
    docs/ARCHITECTURE.md) — the controller (control/controller.py) and
    the standalone ``cdrs serve`` command both call it, so the two
    streams cannot drift apart.  ``rec`` is the window record carrying
    ``WindowServeResult.record_fields()`` (plus hotspot/trigger fields
    when present); ``latency_ms`` is the window's raw sample array,
    emitted as ONE bucketed ``hist_bulk`` event (obs/telemetry.py — not
    one ``hist`` event per read).  No-op for non-serving records.
    """
    if rec.get("reads_routed") is None:
        return
    if rec["reads_routed"]:
        tel.counter_inc("serve.reads_routed", rec["reads_routed"])
    if rec.get("reads_unavailable"):
        tel.counter_inc("serve.reads_unavailable",
                        rec["reads_unavailable"])
    if rec.get("reads_corrupt_served"):
        tel.counter_inc("integrity.reads_corrupt_served",
                        rec["reads_corrupt_served"])
    if rec.get("reads_corrupt_detected"):
        tel.counter_inc("integrity.reads_corrupt_detected",
                        rec["reads_corrupt_detected"])
    if rec.get("latency_p99_ms") is not None:
        tel.gauge("serve.latency_p50_ms", rec["latency_p50_ms"])
        tel.gauge("serve.latency_p99_ms", rec["latency_p99_ms"])
    tel.gauge("serve.utilization_max", rec.get("utilization_max", 0.0))
    tel.gauge("serve.slo_burn", rec.get("slo_burn", 0.0))
    if rec.get("hotspot_files"):
        tel.counter_inc("serve.hotspot.windows")
        tel.gauge("serve.hotspot.score", rec.get("hotspot_score", 0.0))
    if rec.get("recluster_trigger") == "hotspot":
        tel.counter_inc("serve.reclusters.hotspot")
    if latency_ms is not None and len(latency_ms):
        tel.histogram_bulk("serve.latency_ms", latency_ms)

POLICIES: tuple[str, ...] = ("primary", "random", "least_loaded", "p2c")


@dataclass(frozen=True)
class SloSpec:
    """Read-path SLO: latency target and availability objective."""

    #: A read slower than this counts against the error budget.
    target_ms: float = 10.0
    #: Fraction of reads that must meet the target AND be served at all;
    #: the error budget is ``1 - availability``.
    availability: float = 0.999

    def __post_init__(self):
        if self.target_ms <= 0:
            raise ValueError(f"target_ms must be > 0, got {self.target_ms}")
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"availability must be in (0, 1), got {self.availability}")


@dataclass
class ServeConfig:
    """Knobs of the read router + hotspot feedback (module docstring)."""

    policy: str = "p2c"
    #: Seed of the replica-choice rng; the controller derives a per-window
    #: stream from ``(seed, window_index)`` so resume replays identically.
    seed: int = 0
    #: Per-read service time at NOMINAL node throughput; a straggler at
    #: factor m serves one read in ``service_ms / m``.
    service_ms: float = 0.5
    #: Reads per load-feedback chunk (least_loaded/p2c): decisions inside
    #: a chunk share one load snapshot.  Larger chunks are faster but
    #: herd (stale-load oscillation — every decision in the chunk sees
    #: the same "coolest" node); 4096 keeps p2c within a few percent of
    #: per-request feedback while still routing millions of reads/sec.
    chunk: int = 4096
    slo: SloSpec = field(default_factory=SloSpec)
    #: Hotspot detector (serve/hotspot.py): EWMA smoothing of per-file
    #: window read counts, spike = count >= spike_factor x EWMA (and >=
    #: min_reads); the top_k hottest files ride the window record.
    hotspot_alpha: float = 0.3
    hotspot_spike_factor: float = 4.0
    hotspot_min_reads: int = 50
    hotspot_top_k: int = 8
    #: Feed the hotspot signal back into the controller as a drift
    #: trigger: a flash crowd forces a re-cluster the window it lands,
    #: without waiting for the cumulative feature fold to notice.
    recluster_on_hotspot: bool = True
    #: Verify reads against the integrity layer (faults ``slot_corrupt``):
    #: a read that selects a rotten copy DETECTS it (checksum mismatch),
    #: redirects to a clean reachable copy with one extra service-time of
    #: latency, and reports the copy for quarantine.  False = the
    #: unverified baseline: rotten copies are served as if they were fine
    #: and only ``reads_corrupt_served`` records the damage.  Irrelevant
    #: (and bit-identical either way) when no corruption exists.
    verify_reads: bool = True

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"unknown routing policy {self.policy!r} (want one of "
                f"{POLICIES})")
        if self.service_ms <= 0:
            raise ValueError(
                f"service_ms must be > 0, got {self.service_ms}")
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        if not 0.0 < self.hotspot_alpha <= 1.0:
            raise ValueError(
                f"hotspot_alpha must be in (0, 1], got {self.hotspot_alpha}")
        if self.hotspot_spike_factor <= 1.0:
            raise ValueError(
                f"hotspot_spike_factor must be > 1, got "
                f"{self.hotspot_spike_factor}")
        if self.hotspot_top_k < 1:
            raise ValueError(
                f"hotspot_top_k must be >= 1, got {self.hotspot_top_k}")


@dataclass
class WindowServeResult:
    """One routed batch/window of reads and its latency/SLO digest."""

    n_reads: int                  # reads presented to the router
    n_routed: int                 # reads that found a reachable replica
    n_unavailable: int            # reads with zero reachable replicas
    n_local: int                  # served by the client's own node
    server: np.ndarray            # (n_reads,) int32 node id, -1 unavailable
    latency_ms: np.ndarray        # (n_routed,) float64, routed reads only
    #: None when NO read was routed (a full outage has no latency sample
    #: — reporting p99=0 for the worst window would invert reality).
    p50_ms: float | None
    p95_ms: float | None
    p99_ms: float | None
    reads_per_node: np.ndarray    # (n_nodes,) int64
    utilization: np.ndarray       # (n_nodes,) busy-time / window span
    slo_violations: int           # over-target + unavailable
    slo_burn: float               # violation fraction / error budget
    #: Integrity layer (``slot_corrupt`` passed): reads that selected a
    #: rotten copy and were SERVED anyway (verification off — garbage on
    #: the wire) vs DETECTED (verification on: redirected to a clean
    #: copy, or refused when none exists).
    n_corrupt_served: int = 0
    n_corrupt_detected: int = 0
    #: (k, 2) int64 unique (file, node) pairs of detected rotten copies —
    #: the caller quarantines them and feeds the files to the scrubber as
    #: hints.  None when verification was off or nothing was detected.
    corrupt_pairs: np.ndarray | None = None

    @property
    def locality(self) -> float:
        """Local reads / total reads — cluster/evaluate.py's definition
        (unavailable reads count as non-local)."""
        return self.n_local / self.n_reads if self.n_reads else 1.0

    @property
    def utilization_max(self) -> float:
        return float(self.utilization.max()) if self.utilization.size \
            else 0.0

    def record_fields(self) -> dict:
        """The window-record slice of this result (JSONL-safe scalars;
        latency percentiles are None for a window that routed nothing)."""
        rnd = lambda v: None if v is None else round(v, 6)  # noqa: E731
        return {
            "reads_routed": self.n_routed,
            "reads_unavailable": self.n_unavailable,
            "serve_locality": round(self.locality, 6),
            "latency_p50_ms": rnd(self.p50_ms),
            "latency_p95_ms": rnd(self.p95_ms),
            "latency_p99_ms": rnd(self.p99_ms),
            "utilization_max": round(self.utilization_max, 6),
            "slo_violations": self.slo_violations,
            "slo_burn": round(self.slo_burn, 6),
            "reads_corrupt_served": self.n_corrupt_served,
            "reads_corrupt_detected": self.n_corrupt_detected,
        }


def _pick_rank(ok: np.ndarray, rank: np.ndarray) -> np.ndarray:
    """Slot index of the ``rank``-th True per row of ``ok`` (rank < row
    count of Trues; rows with no True return slot 0 — callers mask)."""
    csum = np.cumsum(ok, axis=1)
    return np.argmax(csum > rank[:, None], axis=1).astype(np.int32)


class ReadRouter:
    """Routes read batches against a replica map (module docstring)."""

    def __init__(self, n_nodes: int, cfg: ServeConfig):
        if n_nodes < 1:
            raise ValueError(f"n_nodes must be >= 1, got {n_nodes}")
        self.n_nodes = int(n_nodes)
        self.cfg = cfg

    # -- selection ---------------------------------------------------------
    def _select(self, cand: np.ndarray, ok: np.ndarray, n_ok: np.ndarray,
                service_s: np.ndarray, rng: np.random.Generator
                ) -> np.ndarray:
        """(e,) int32 server node per read (-1 = unavailable); reads must
        be in time order — the load feedback consumes them chunkwise."""
        policy = self.cfg.policy
        e = cand.shape[0]
        any_ok = n_ok > 0
        if policy == "primary":
            slot = np.argmax(ok, axis=1)
            server = cand[np.arange(e), slot].astype(np.int32)
            server[~any_ok] = -1
            return server
        if policy == "random":
            rank = np.minimum((rng.random(e) * n_ok).astype(np.int64),
                              np.maximum(n_ok - 1, 0))
            slot = _pick_rank(ok, rank)
            server = cand[np.arange(e), slot].astype(np.int32)
            server[~any_ok] = -1
            return server

        # Load-feedback policies: chunked batch-synchronous routing.
        server = np.full(e, -1, dtype=np.int32)
        load = np.zeros(self.n_nodes, dtype=np.float64)  # busy seconds
        chunk = self.cfg.chunk
        safe = np.clip(cand, 0, None)
        if policy == "p2c":
            r1 = rng.random(e)
            r2 = rng.random(e)
        for lo in range(0, e, chunk):
            hi = min(lo + chunk, e)
            c_cand = cand[lo:hi]
            c_ok = ok[lo:hi]
            c_any = any_ok[lo:hi]
            rows = np.arange(hi - lo)
            if policy == "least_loaded":
                node_load = np.where(c_ok, load[safe[lo:hi]], np.inf)
                slot = np.argmin(node_load, axis=1)
                srv = c_cand[rows, slot].astype(np.int32)
            else:  # p2c: two probes with replacement, keep the cooler one
                n1 = np.maximum(n_ok[lo:hi] - 1, 0)
                rank1 = np.minimum((r1[lo:hi] * n_ok[lo:hi]).astype(
                    np.int64), n1)
                rank2 = np.minimum((r2[lo:hi] * n_ok[lo:hi]).astype(
                    np.int64), n1)
                s1 = c_cand[rows, _pick_rank(c_ok, rank1)]
                s2 = c_cand[rows, _pick_rank(c_ok, rank2)]
                srv = np.where(load[np.clip(s2, 0, None)]
                               < load[np.clip(s1, 0, None)],
                               s2, s1).astype(np.int32)
            srv[~c_any] = -1
            server[lo:hi] = srv
            routed = srv[srv >= 0]
            if routed.size:
                load += np.bincount(routed, minlength=self.n_nodes) \
                    * service_s
        return server

    # -- the queue model ---------------------------------------------------
    def _latency(self, server: np.ndarray, ts: np.ndarray,
                 service_s: np.ndarray) -> np.ndarray:
        """(e,) seconds; NaN for unavailable reads.  Exact per-node FIFO
        with constant service time: ``f_k = s(k+1) + cummax(a_j - s j)``
        (the closed form of ``f_k = max(a_k, f_{k-1}) + s``)."""
        lat = np.full(server.shape[0], np.nan)
        for node in range(self.n_nodes):
            m = server == node
            if not m.any():
                continue
            a = ts[m]
            s = service_s[node]
            k = np.arange(a.size, dtype=np.float64)
            finish = s * (k + 1.0) + np.maximum.accumulate(a - s * k)
            lat[m] = finish - a
        return lat

    # -- entry -------------------------------------------------------------
    def route(self, replica_map: np.ndarray, slot_ok: np.ndarray,
              node_throughput: np.ndarray, *, ts: np.ndarray,
              pid: np.ndarray, client: np.ndarray,
              window_seconds: float | None = None,
              rng: np.random.Generator | None = None,
              extra_ms: np.ndarray | None = None,
              edge_ms: np.ndarray | None = None,
              slot_corrupt: np.ndarray | None = None) -> WindowServeResult:
        """Route one time-ordered batch of reads.

        ``replica_map``: (n_files, R) int32 node ids, -1 = empty slot.
        ``slot_ok``: (n_files, R) bool — slot holds a replica that can
        serve (``ClusterState.reachable_mask()``; a static placement
        passes ``replica_map >= 0``).  ``node_throughput``: (n_nodes,)
        straggler factors in (0, 1].  ``ts``/``pid``/``client``: per-read
        epoch seconds (ascending), file id, and client node id (-1 =
        outside the topology).  ``window_seconds`` scales utilization
        (default: the batch's time span).

        ``extra_ms``: optional (n_reads,) additive latency per read on
        top of the queue model — the storage layer's degraded-read and
        tier penalties (a cold-tier read is slower end to end; a read of
        an EC file whose primary shard is down must gather k shards
        before it can answer).  The extra time is transfer/decode work
        on the CLIENT side of the queue, so it does not occupy the
        chosen server — queue waits are unchanged, the latency sample
        (and therefore the percentiles and SLO burn) carries it.

        ``edge_ms``: optional (n_nodes, n_nodes) added latency for a
        read served ACROSS the topology hierarchy — indexed
        ``[client, server]`` (the geo topology's
        ``latency_matrix``-derived propagation delay; WAN ≫ rack).
        Reads from clients outside the topology (``client == -1``) add
        nothing.  Propagation is wire time on the client side of the
        queue: server busy-time and queue waits are unchanged, the
        latency sample (percentiles, SLO burn) carries it.

        ``slot_corrupt``: optional (n_files, R) bool — slots whose copy
        has silently rotted (``ClusterState.slot_corrupt``).  With
        ``cfg.verify_reads`` the router detects a rotten selection
        (checksum on read), redirects it to the first clean reachable
        slot at one extra service-time of latency — or refuses it
        (unavailable) when no clean copy survives — and reports the
        detected (file, node) pairs for quarantine.  Without
        verification the read is served rotten and only counted.
        """
        rng = rng or np.random.default_rng(self.cfg.seed)
        ts = np.asarray(ts, dtype=np.float64)
        pid = np.asarray(pid)
        client = np.asarray(client)
        e = int(pid.shape[0])
        thr = np.asarray(node_throughput, dtype=np.float64)
        service_s = (self.cfg.service_ms / 1000.0) / np.maximum(thr, 1e-9)

        if e == 0:
            z = np.zeros(self.n_nodes)
            return WindowServeResult(
                n_reads=0, n_routed=0, n_unavailable=0, n_local=0,
                server=np.zeros(0, dtype=np.int32),
                latency_ms=np.zeros(0), p50_ms=None, p95_ms=None,
                p99_ms=None, reads_per_node=z.astype(np.int64),
                utilization=z, slo_violations=0, slo_burn=0.0)

        cand = replica_map[pid]                       # (e, R)
        ok = slot_ok[pid]
        n_ok = ok.sum(axis=1)
        local = ((cand == client[:, None]) & ok).any(axis=1) & (client >= 0)

        server = self._select(cand, ok, n_ok, service_s, rng)
        # Local reads short-circuit to the client AFTER selection so the
        # load-feedback policies still account their busy time in order.
        # (Selection already charged a replica for them; the local node IS
        # one of the replicas, so the approximation only shifts which
        # holder was charged inside one chunk.)
        server = np.where(local, client.astype(np.int32), server)

        # Integrity: reads whose SELECTED copy is rot (detect-on-read).
        n_corrupt_served = n_corrupt_detected = 0
        corrupt_pairs = None
        retry_ms = None
        if slot_corrupt is not None:
            corr = slot_corrupt[pid]                   # (e, R)
            sel_corrupt = (((cand == server[:, None]) & corr).any(axis=1)
                           & (server >= 0))
            if sel_corrupt.any():
                if self.cfg.verify_reads:
                    n_corrupt_detected = int(sel_corrupt.sum())
                    pairs = np.stack([pid[sel_corrupt].astype(np.int64),
                                      server[sel_corrupt].astype(np.int64)],
                                     axis=1)
                    corrupt_pairs = np.unique(pairs, axis=0)
                    # Redirect to the first clean reachable slot; the
                    # wasted rotten read costs one extra service time.
                    clean_ok = ok & ~corr
                    rows = np.arange(e)
                    alt = cand[rows, np.argmax(clean_ok, axis=1)]
                    has_clean = clean_ok.any(axis=1)
                    redirect = sel_corrupt & has_clean
                    server = np.where(redirect, alt.astype(np.int32),
                                      server)
                    # No clean copy left: refuse the read (unavailable)
                    # rather than serve garbage.
                    server[sel_corrupt & ~has_clean] = -1
                    retry_ms = np.where(redirect,
                                        float(self.cfg.service_ms), 0.0)
                    # Redirects/refusals moved reads off (or onto) the
                    # client node: locality is a fact about the FINAL
                    # server.  Without corruption this reconstruction
                    # equals the pre-selection mask exactly (a selected
                    # client node implies an ok client slot).
                    local = (server >= 0) & (server
                                             == client.astype(np.int32))
                else:
                    # Unverified baseline: garbage goes out on the wire.
                    n_corrupt_served = int(sel_corrupt.sum())

        unavailable = server < 0
        n_unavail = int(unavailable.sum())
        lat_s = self._latency(server, ts, service_s)
        routed = ~unavailable
        latency_ms = lat_s[routed] * 1000.0
        if extra_ms is not None:
            latency_ms = latency_ms + np.asarray(extra_ms,
                                                 dtype=np.float64)[routed]
        if retry_ms is not None:
            latency_ms = latency_ms + retry_ms[routed]
        if edge_ms is not None:
            cl = np.where(client >= 0, client, 0)
            hop = np.asarray(edge_ms, dtype=np.float64)[
                cl, np.clip(server, 0, None)]
            hop = np.where(client >= 0, hop, 0.0)
            latency_ms = latency_ms + hop[routed]

        counts = np.bincount(server[routed], minlength=self.n_nodes
                             ).astype(np.int64)
        span = float(window_seconds) if window_seconds else \
            max(float(ts[-1] - ts[0]), 1e-9)
        utilization = counts * service_s / max(span, 1e-9)

        if latency_ms.size:
            p50, p95, p99 = (float(np.percentile(latency_ms, q))
                             for q in (50.0, 95.0, 99.0))
        else:
            # A full outage routed nothing: there IS no latency sample.
            p50 = p95 = p99 = None
        slo = self.cfg.slo
        violations = int((latency_ms > slo.target_ms).sum()) + n_unavail
        burn = (violations / e) / (1.0 - slo.availability)

        return WindowServeResult(
            n_reads=e, n_routed=int(routed.sum()),
            n_unavailable=n_unavail, n_local=int(local.sum()),
            server=server, latency_ms=latency_ms,
            p50_ms=p50, p95_ms=p95, p99_ms=p99,
            reads_per_node=counts, utilization=utilization,
            slo_violations=violations, slo_burn=float(burn),
            n_corrupt_served=n_corrupt_served,
            n_corrupt_detected=n_corrupt_detected,
            corrupt_pairs=corrupt_pairs)
