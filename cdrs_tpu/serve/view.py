"""One shared resolution of "what can serve this window's reads".

Three surfaces used to hand the router its inputs with their own copies
of the same branch — ``cdrs serve`` (cli.py), the controller's serve
wiring (control/controller.py) and the chaos replay — each deciding
between the mutable fault state and a static placement inline.  That
duplication is exactly where the functional placement mode must plug in
(resolve ONLY the window's files, O(unique pids) memory instead of the
O(n_files x rf) materialized map), so the branch lives here once:

* ``state=``      — the fault path: the live ``ClusterState``'s dense
  map, reachability mask, straggler factors and (when rot exists) the
  corruption mask;
* ``resolver=``   — the functional path: a callable mapping unique file
  ids to their computed slot rows; the view's map is (n_unique, R) and
  ``pid`` is remapped onto it — the O(1)-memory router;
* ``placement=``  — the materialized static path (legacy behaviour).

The router (serve/router.py) only ever indexes ``replica_map[pid]``, so
a compacted per-window map with remapped pids routes bit-identically to
the full map — the equivalence the functional mode's serve-locality
check rests on (tests/test_placement_fn.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["ReadView", "read_view"]


@dataclass
class ReadView:
    """Router inputs for one window's reads (see module docstring)."""

    replica_map: np.ndarray          # (n_files | n_unique, R) int32
    slot_ok: np.ndarray              # same shape, bool
    node_throughput: np.ndarray      # (n_nodes,) float64
    slot_corrupt: np.ndarray | None  # same shape as replica_map, or None
    pid: np.ndarray                  # read file ids, remapped if compacted
    #: Population file id behind each ROW of a compacted view; None =
    #: rows are population-indexed (callers overlaying per-file masks
    #: index with this when present).
    file_ids: np.ndarray | None = None


def read_view(pid: np.ndarray, *, state=None, placement=None,
              resolver=None, n_nodes: int | None = None) -> ReadView:
    """Resolve the serving view for ``pid`` from exactly one source.

    ``state`` wins (the live fault path), then ``resolver`` (functional
    subset resolution; needs ``n_nodes``), then ``placement`` (static
    materialized map).  ``resolver(unique_pids) -> (k, R) int32 rows``
    must return -1-padded slot rows — ``placement_fn.compute_placement``
    output, plus any exception overlay the caller maintains.
    """
    if state is not None:
        if getattr(state, "read_rows", None) is not None:
            # Lowmem functional backend: resolve ONLY this window's
            # unique files (rows + reachability + sparse rot) — the
            # fault path's O(unique pids) counterpart of the static
            # resolver below.  Routing is bit-identical: the router
            # only ever indexes replica_map[pid].
            uniq, inv = np.unique(pid, return_inverse=True)
            rows, ok, corrupt = state.read_rows(uniq)
            return ReadView(rows, ok, state.node_throughput, corrupt,
                            inv.astype(pid.dtype if pid.dtype.kind == "i"
                                       else np.int64), file_ids=uniq)
        corrupt = state.slot_corrupt if state.has_corruption else None
        return ReadView(state.replica_map, state.reachable_mask(),
                        state.node_throughput, corrupt, pid)
    if resolver is not None:
        if n_nodes is None:
            raise ValueError("read_view(resolver=...) needs n_nodes for "
                             "the throughput vector")
        uniq, inv = np.unique(pid, return_inverse=True)
        rows = np.asarray(resolver(uniq), dtype=np.int32)
        return ReadView(rows, rows >= 0, np.ones(n_nodes), None,
                        inv.astype(pid.dtype if pid.dtype.kind == "i"
                                   else np.int64), file_ids=uniq)
    if placement is None:
        raise ValueError("read_view needs one of state=, resolver=, "
                         "placement=")
    rm = placement.replica_map
    return ReadView(rm, rm >= 0, np.ones(len(placement.topology)), None,
                    pid)
