"""EWMA top-k hotspot detector — the serving layer's drift signal.

Feature drift (control/drift.py) sees a workload shift only after enough
events fold into the CUMULATIVE feature state to move centroids or
category populations — a flash crowd landing on a cohort late in a long
run is diluted by history and never trips the detector.  The hotspot
detector watches the *per-window* read-count vector instead: each file
carries an EWMA baseline of its window read counts, and a window where a
file's count reaches ``spike_factor`` x its baseline (and at least
``min_reads`` in absolute terms — a 2-read file "spiking" to 9 is noise)
fires the signal.  The controller treats a firing exactly like drift
crossing its threshold: re-cluster NOW, so migration starts rolling the
hot cohort toward a higher replication factor windows before the feature
fold would have noticed.

Pure arithmetic on the count vector — no RNG, no dependence on the
router's seed — so detection is deterministic and seed-invariant by
construction (property-tested).  The EWMA state rides the controller's
npz checkpoint (``state_arrays``/``load_state_arrays``), keeping
kill/resume bit-identical mid-flash-crowd.

The first observed window initializes the baseline and never fires: a
cold controller re-clusters anyway, and a baseline must exist before
"x4 over baseline" means anything.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = ["HotspotResult", "HotspotDetector"]


@dataclass(frozen=True)
class HotspotResult:
    """One window's verdict."""

    #: Any file spiked past the threshold this window.
    fired: bool
    #: max(count / max(EWMA, 1)) over all files — the drift-style signal
    #: magnitude (1.0 = stationary; the threshold is ``spike_factor``).
    score: float
    #: Top-k spiking file ids, hottest (highest ratio) first.
    files: tuple[int, ...]


class HotspotDetector:
    """Carries the per-file EWMA baseline across windows."""

    def __init__(self, n_files: int, *, alpha: float = 0.3,
                 spike_factor: float = 4.0, min_reads: int = 50,
                 top_k: int = 8):
        self.n_files = int(n_files)
        self.alpha = float(alpha)
        self.spike_factor = float(spike_factor)
        self.min_reads = int(min_reads)
        self.top_k = int(top_k)
        self.ewma = np.zeros(self.n_files, dtype=np.float64)
        self.initialized = False

    def observe(self, counts: np.ndarray) -> HotspotResult:
        """Score one window's per-file read counts and fold them into the
        baseline.  Detection happens BEFORE the fold — a spike is judged
        against the pre-spike baseline."""
        counts = np.asarray(counts, dtype=np.float64)
        if counts.shape != (self.n_files,):
            raise ValueError(
                f"counts shape {counts.shape} != ({self.n_files},)")
        if not self.initialized:
            self.ewma = counts.copy()
            self.initialized = True
            return HotspotResult(fired=False, score=1.0, files=())
        ratio = counts / np.maximum(self.ewma, 1.0)
        hot = (counts >= self.min_reads) & (ratio >= self.spike_factor)
        score = float(ratio.max()) if ratio.size else 1.0
        files: tuple[int, ...] = ()
        if hot.any():
            ids = np.flatnonzero(hot)
            order = np.lexsort((ids, -ratio[ids]))  # ratio desc, id asc
            files = tuple(int(i) for i in ids[order][:self.top_k])
        self.ewma = self.alpha * counts + (1.0 - self.alpha) * self.ewma
        return HotspotResult(fired=bool(hot.any()), score=score,
                             files=files)

    # -- checkpoint (controller npz contract) ------------------------------
    def state_arrays(self) -> dict[str, np.ndarray]:
        return {
            "serve_ewma": self.ewma.copy(),
            "serve_ewma_init": np.asarray([self.initialized]),
        }

    def load_state_arrays(self, arrays: dict) -> None:
        ewma = np.asarray(arrays["serve_ewma"], dtype=np.float64)
        if ewma.shape != (self.n_files,):
            raise ValueError(
                f"checkpoint serve_ewma shape {ewma.shape} != "
                f"({self.n_files},) — stale checkpoint?")
        self.ewma = ewma.copy()
        self.initialized = bool(np.asarray(arrays["serve_ewma_init"])[0])
