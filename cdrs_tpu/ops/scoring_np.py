"""NumPy cluster scoring/classification backend.

Vectorized re-implementation of the reference's ``ClusterClassifier``
(reference: src/scoring.py:3-130) with identical semantics:

For cluster c with per-feature medians m and global medians g, and category
weights w >= 0, directions dir in {-1, 0, +1}:

* delta = m - g                                       (scoring.py:74)
* Moderate: score += w * (1 - |delta|)**2  iff |delta| < 0.1   (scoring.py:77-79)
* Others:   score += w * delta**2          iff dir == 0 or sign(delta) == dir
                                                       (scoring.py:81-82)
* winner = argmax score; exact-equality ties broken by the highest
  replication factor (scoring.py:102-107) — so an all-zero-score cluster
  classifies as Archival (rf 4 > Hot 3 > Shared 2 > Moderate 1,
  reference: src/main.py:57-62).

Note ``np.sign(0) == 0`` means a zero delta only scores when dir == 0 —
preserved (SURVEY.md §2.3, §6.1.9).

Instead of the reference's dict-of-lists clusters we operate on arrays:
``cluster_medians`` is (k, n_features) and the whole score table is one
(k, n_categories) computation, which is also the shape the JAX kernel uses.
"""

from __future__ import annotations

import numpy as np

from ..config import ScoringConfig

__all__ = [
    "compute_cluster_medians",
    "compute_cluster_medians_hist",
    "score_table",
    "score_table_terms",
    "classify_medians",
    "classify",
    "HIST_MEDIAN_THRESHOLD",
]

#: Row count past which "auto" median selection switches from exact sorting
#: to fixed-bin histograms — shared by both backends (ops/scoring_jax
#: re-exports it) so they take the same route on the same data.
HIST_MEDIAN_THRESHOLD = 2_000_000


def compute_cluster_medians(
    X: np.ndarray, labels: np.ndarray, k: int
) -> np.ndarray:
    """Per-cluster per-feature medians, (k, d).

    Reference: src/scoring.py:40-55 (np.median per cluster/feature).  Empty
    clusters get NaN medians — the reference can't produce empty clusters at
    this stage because main.py groups by observed labels; NaN rows score 0 for
    every category and therefore tie-break to Archival, which matches the
    "no evidence" default of SURVEY.md §2.3.
    """
    k_eff = int(k)
    d = X.shape[1]
    out = np.full((k_eff, d), np.nan, dtype=np.float64)
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    boundaries = np.searchsorted(sorted_labels, np.arange(k_eff + 1))
    for j in range(k_eff):
        lo, hi = boundaries[j], boundaries[j + 1]
        if hi > lo:
            out[j] = np.median(X[order[lo:hi]], axis=0)
    return out


def _medians_from_hist_np(H, counts, lo_f, w_f, bins):
    """(k,) medians off a (k, bins) histogram — numpy mirror of
    ops/scoring_jax._medians_from_hist (same middle-rank + intra-bin linear
    interpolation, so both backends agree bin-for-bin)."""
    cum = np.cumsum(H, axis=1)
    r0 = (counts - 1) // 2
    r1 = counts // 2

    def value_at(r):
        j = np.argmax(cum > r[:, None], axis=1)
        cum_before = np.where(
            j > 0,
            np.take_along_axis(cum, np.maximum(j - 1, 0)[:, None], 1)[:, 0],
            0,
        )
        h = np.take_along_axis(H, j[:, None], 1)[:, 0]
        frac = (r - cum_before + 0.5) / np.maximum(h, 1)
        return (j.astype(np.float64) + frac) * (w_f / bins)

    med = lo_f + 0.5 * (value_at(r0) + value_at(r1))
    return np.where(counts > 0, med, np.nan)


def compute_cluster_medians_hist(
    X: np.ndarray, labels: np.ndarray, k: int, bins: int = 2048,
    with_global: bool = False,
):
    """(k, d) approximate per-cluster medians via fixed-bin histograms —
    numpy twin of ops/scoring_jax.compute_cluster_medians_hist_jax (error
    <= feature_range / bins; constant columns exact; NaN for empty
    clusters).  ``with_global=True`` also returns the (d,) global medians
    read off the same histograms (one data pass)."""
    n, d = X.shape
    labels = np.asarray(labels, dtype=np.int64)
    counts = np.bincount(labels, minlength=k)
    lo = X.min(axis=0)
    hi = X.max(axis=0)
    out = np.full((k, d), np.nan, dtype=np.float64)
    gout = np.empty(d, dtype=np.float64)
    n_total = np.array([n], dtype=np.int64)
    for f in range(d):
        if hi[f] <= lo[f]:   # constant column: the value itself, exactly
            out[:, f] = np.where(counts > 0, lo[f], np.nan)
            gout[f] = lo[f]
            continue
        w_f = hi[f] - lo[f]
        b = np.clip(((X[:, f] - lo[f]) / w_f * bins).astype(np.int64),
                    0, bins - 1)
        H = np.bincount(labels * bins + b, minlength=k * bins).reshape(k, bins)
        out[:, f] = _medians_from_hist_np(H, counts, lo[f], w_f, bins)
        if with_global:
            gout[f] = _medians_from_hist_np(
                H.sum(axis=0, keepdims=True), n_total, lo[f], w_f, bins)[0]
    if with_global:
        return out, gout
    return out


def score_table_terms(
    cluster_medians: np.ndarray,
    cfg: ScoringConfig,
    global_medians: np.ndarray | None = None,
) -> np.ndarray:
    """(k, n_categories, n_features) GATED per-feature score terms.

    The decomposition behind ``cdrs explain category`` (obs/explain.py):
    ``score_table`` is exactly the feature-axis sum of this array, so a
    per-feature contribution listing reconciles with the decision to the
    last bit — one math, two views.  A zero entry means the gate closed
    (direction mismatch, or |delta| outside the Moderate band) or the
    cluster median was NaN (empty cluster).
    """
    W = np.asarray(cfg.weight_matrix(), dtype=np.float64)        # (C, d)
    D = np.asarray(cfg.direction_matrix(), dtype=np.float64)     # (C, d)
    if global_medians is None:
        global_medians = np.asarray(
            [cfg.global_medians[f] for f in cfg.features], dtype=np.float64
        )
    delta = cluster_medians - global_medians[None, :]            # (k, d)
    valid = ~np.isnan(delta)
    delta = np.where(valid, delta, 0.0)
    abs_d = np.abs(delta)

    # (k, C, d) broadcast of the per-feature terms.
    delta_b = delta[:, None, :]
    absd_b = abs_d[:, None, :]
    valid_b = valid[:, None, :]

    is_moderate = np.array([c == "Moderate" for c in cfg.categories])  # (C,)

    # Non-Moderate gate: dir == 0 or sign(delta) == dir (scoring.py:81).
    gate_dir = (D[None, :, :] == 0) | (np.sign(delta_b) == D[None, :, :])
    term_dir = W[None, :, :] * absd_b**2

    # Moderate gate: |delta| < band, reward (1 - |delta|)^2 (scoring.py:77-79).
    gate_mod = absd_b < cfg.moderate_band
    term_mod = W[None, :, :] * (1.0 - absd_b) ** 2

    gate = np.where(is_moderate[None, :, None], gate_mod, gate_dir) & valid_b
    term = np.where(is_moderate[None, :, None], term_mod, term_dir)
    return np.where(gate, term, 0.0)  # (k, C, d)


def score_table(
    cluster_medians: np.ndarray,
    cfg: ScoringConfig,
    global_medians: np.ndarray | None = None,
) -> np.ndarray:
    """(k, n_categories) score matrix.

    Vectorizes reference src/scoring.py:57-84 over all clusters and categories
    at once.  NaN medians (empty clusters) contribute 0.
    """
    return score_table_terms(cluster_medians, cfg,
                             global_medians).sum(axis=2)  # (k, C)


def classify_medians(
    cluster_medians: np.ndarray,
    cfg: ScoringConfig,
    global_medians: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Category index per cluster + the score table.

    Tie-break on exact score equality by the highest replication factor
    (reference: src/scoring.py:102-107).
    """
    scores = score_table(cluster_medians, cfg, global_medians)   # (k, C)
    rf = np.asarray(cfg.rf_vector(), dtype=np.float64)           # (C,)
    max_score = scores.max(axis=1, keepdims=True)
    tied = scores == max_score
    # Among tied categories pick the one with the largest rf; np.argmax picks
    # the first maximum, matching the reference's sort(reverse=True)[0] for
    # distinct rf values (all rf values are distinct: 3,2,1,4).
    winner = np.argmax(np.where(tied, rf[None, :], -np.inf), axis=1)
    return winner, scores


def classify(
    X: np.ndarray,
    labels: np.ndarray,
    k: int,
    cfg: ScoringConfig | None = None,
    global_medians: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Full classification: medians -> scores -> categories.

    Returns ``(category_idx (k,), scores (k, C), cluster_medians (k, d))``.
    Reference call stack: src/scoring.py:111-130.

    Honors ``cfg.median_method`` exactly like the jax backend (ADVICE r2):
    "sort" = exact medians, "hist" = fixed-bin histogram medians, "auto" =
    hist past HIST_MEDIAN_THRESHOLD rows — so both backends take the same
    route on the same data.
    """
    cfg = cfg or ScoringConfig()
    method = getattr(cfg, "median_method", "auto")
    if method == "auto":
        method = "hist" if X.shape[0] > HIST_MEDIAN_THRESHOLD else "sort"
    if method == "bisect":
        # The MXU rank-bisection is a jax/TPU strategy; its numpy twin in
        # accuracy class (error <= range/2^iters vs range/bins) is the
        # histogram path — same config runs on both backends.
        method = "hist"
    if method not in ("sort", "hist"):
        raise ValueError(f"unknown median_method {method!r}")
    want_global = global_medians is None and cfg.compute_global_medians_from_data
    if method == "hist":
        medians, gmeds = compute_cluster_medians_hist(
            X, labels, k, bins=int(getattr(cfg, "median_bins", 2048)),
            with_global=True)
        if want_global:
            global_medians = gmeds
    else:
        medians = compute_cluster_medians(X, labels, k)
        if want_global:
            global_medians = np.median(X, axis=0)
    winner, scores = classify_medians(medians, cfg, global_medians)
    return winner, scores, medians
