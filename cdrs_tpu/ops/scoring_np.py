"""NumPy cluster scoring/classification backend.

Vectorized re-implementation of the reference's ``ClusterClassifier``
(reference: src/scoring.py:3-130) with identical semantics:

For cluster c with per-feature medians m and global medians g, and category
weights w >= 0, directions dir in {-1, 0, +1}:

* delta = m - g                                       (scoring.py:74)
* Moderate: score += w * (1 - |delta|)**2  iff |delta| < 0.1   (scoring.py:77-79)
* Others:   score += w * delta**2          iff dir == 0 or sign(delta) == dir
                                                       (scoring.py:81-82)
* winner = argmax score; exact-equality ties broken by the highest
  replication factor (scoring.py:102-107) — so an all-zero-score cluster
  classifies as Archival (rf 4 > Hot 3 > Shared 2 > Moderate 1,
  reference: src/main.py:57-62).

Note ``np.sign(0) == 0`` means a zero delta only scores when dir == 0 —
preserved (SURVEY.md §2.3, §6.1.9).

Instead of the reference's dict-of-lists clusters we operate on arrays:
``cluster_medians`` is (k, n_features) and the whole score table is one
(k, n_categories) computation, which is also the shape the JAX kernel uses.
"""

from __future__ import annotations

import numpy as np

from ..config import ScoringConfig

__all__ = [
    "compute_cluster_medians",
    "score_table",
    "classify_medians",
    "classify",
]


def compute_cluster_medians(
    X: np.ndarray, labels: np.ndarray, k: int
) -> np.ndarray:
    """Per-cluster per-feature medians, (k, d).

    Reference: src/scoring.py:40-55 (np.median per cluster/feature).  Empty
    clusters get NaN medians — the reference can't produce empty clusters at
    this stage because main.py groups by observed labels; NaN rows score 0 for
    every category and therefore tie-break to Archival, which matches the
    "no evidence" default of SURVEY.md §2.3.
    """
    k_eff = int(k)
    d = X.shape[1]
    out = np.full((k_eff, d), np.nan, dtype=np.float64)
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    boundaries = np.searchsorted(sorted_labels, np.arange(k_eff + 1))
    for j in range(k_eff):
        lo, hi = boundaries[j], boundaries[j + 1]
        if hi > lo:
            out[j] = np.median(X[order[lo:hi]], axis=0)
    return out


def score_table(
    cluster_medians: np.ndarray,
    cfg: ScoringConfig,
    global_medians: np.ndarray | None = None,
) -> np.ndarray:
    """(k, n_categories) score matrix.

    Vectorizes reference src/scoring.py:57-84 over all clusters and categories
    at once.  NaN medians (empty clusters) contribute 0.
    """
    W = np.asarray(cfg.weight_matrix(), dtype=np.float64)        # (C, d)
    D = np.asarray(cfg.direction_matrix(), dtype=np.float64)     # (C, d)
    if global_medians is None:
        global_medians = np.asarray(
            [cfg.global_medians[f] for f in cfg.features], dtype=np.float64
        )
    delta = cluster_medians - global_medians[None, :]            # (k, d)
    valid = ~np.isnan(delta)
    delta = np.where(valid, delta, 0.0)
    abs_d = np.abs(delta)

    # (k, C, d) broadcast of the per-feature terms.
    delta_b = delta[:, None, :]
    absd_b = abs_d[:, None, :]
    valid_b = valid[:, None, :]

    is_moderate = np.array([c == "Moderate" for c in cfg.categories])  # (C,)

    # Non-Moderate gate: dir == 0 or sign(delta) == dir (scoring.py:81).
    gate_dir = (D[None, :, :] == 0) | (np.sign(delta_b) == D[None, :, :])
    term_dir = W[None, :, :] * absd_b**2

    # Moderate gate: |delta| < band, reward (1 - |delta|)^2 (scoring.py:77-79).
    gate_mod = absd_b < cfg.moderate_band
    term_mod = W[None, :, :] * (1.0 - absd_b) ** 2

    gate = np.where(is_moderate[None, :, None], gate_mod, gate_dir) & valid_b
    term = np.where(is_moderate[None, :, None], term_mod, term_dir)
    return np.where(gate, term, 0.0).sum(axis=2)  # (k, C)


def classify_medians(
    cluster_medians: np.ndarray,
    cfg: ScoringConfig,
    global_medians: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Category index per cluster + the score table.

    Tie-break on exact score equality by the highest replication factor
    (reference: src/scoring.py:102-107).
    """
    scores = score_table(cluster_medians, cfg, global_medians)   # (k, C)
    rf = np.asarray(cfg.rf_vector(), dtype=np.float64)           # (C,)
    max_score = scores.max(axis=1, keepdims=True)
    tied = scores == max_score
    # Among tied categories pick the one with the largest rf; np.argmax picks
    # the first maximum, matching the reference's sort(reverse=True)[0] for
    # distinct rf values (all rf values are distinct: 3,2,1,4).
    winner = np.argmax(np.where(tied, rf[None, :], -np.inf), axis=1)
    return winner, scores


def classify(
    X: np.ndarray,
    labels: np.ndarray,
    k: int,
    cfg: ScoringConfig | None = None,
    global_medians: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Full classification: medians -> scores -> categories.

    Returns ``(category_idx (k,), scores (k, C), cluster_medians (k, d))``.
    Reference call stack: src/scoring.py:111-130.
    """
    cfg = cfg or ScoringConfig()
    medians = compute_cluster_medians(X, labels, k)
    if global_medians is None and cfg.compute_global_medians_from_data:
        global_medians = np.median(X, axis=0)
    winner, scores = classify_medians(medians, cfg, global_medians)
    return winner, scores, medians
