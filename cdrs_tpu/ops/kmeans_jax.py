"""TPU-native KMeans++ — sharded JAX kernels.

Re-designs the reference's single-threaded NumPy KMeans
(reference: src/kmeans_plusplus.py:3-50) as XLA-compiled SPMD kernels over a
``jax.sharding.Mesh``:

* **Assignment** — the reference's dense ``(n, k, d)`` broadcast
  (kmeans_plusplus.py:33) becomes the matmul expansion
  ``argmin_k(‖c‖² − 2·x·Cᵀ)`` — one MXU matmul per step, never materializing
  the (n, k, d) temporary.  Points are sharded along the ``data`` mesh axis,
  centroids replicated.
* **Update** — the reference's k masked means (kmeans_plusplus.py:38-43)
  become one ``segment_sum`` of (weighted x, weight) per shard followed by a
  single ``lax.psum`` over ICI — the TPU equivalent of Spark's shuffle /
  an NCCL allreduce (SURVEY.md §2.5).
* **D² init** — the reference recomputes all pairwise distances each round
  (kmeans_plusplus.py:13-17, O(n·k²·d)); here the min-distance state is
  incremental (O(n·d) per round) and the categorical draw runs **on device**
  via the Gumbel-max trick with a cross-shard argmax, so the k-round loop is
  a single ``lax.fori_loop`` with zero host syncs.
* **Convergence** — ``lax.while_loop`` on the Frobenius centroid shift
  (reference tol semantics, kmeans_plusplus.py:45-48); labels returned are
  the assignment against the pre-update centroids, exactly the reference's
  loop order.
* **Empty clusters** — reseeded to a uniformly drawn data point from the
  threaded PRNG key (the reference used the *unseeded* global RNG,
  kmeans_plusplus.py:43 — fixed per SURVEY.md §6.1.2).

Padded rows (for even sharding) carry weight 0 and are excluded from sums,
counts, and sampling.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import DATA_AXIS, make_mesh, pad_rows

__all__ = [
    "pairwise_sq_dists_jax",
    "assign_labels_jax",
    "kmeans_jax",
    "kmeans_jax_full",
]


def pairwise_sq_dists_jax(x: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Squared Euclidean distances (n, k) via ‖x‖² − 2·x·Cᵀ + ‖c‖².

    Matches ops/kmeans_np.pairwise_sq_dists (the golden model); the matmul is
    the MXU-friendly form of reference kmeans_plusplus.py:14-17.
    """
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    c_sq = jnp.sum(c * c, axis=1)
    return jnp.maximum(x_sq - 2.0 * (x @ c.T) + c_sq[None, :], 0.0)


def assign_labels_jax(x: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Nearest-centroid labels; drops the per-row-constant ‖x‖² term
    (same trick as ops/kmeans_np.assign_labels)."""
    c_sq = jnp.sum(c * c, axis=1)
    d = c_sq[None, :] - 2.0 * (x @ c.T)
    return jnp.argmin(d, axis=1).astype(jnp.int32)


def _sq_dist_to_row(x: jnp.ndarray, x_sq: jnp.ndarray, row: jnp.ndarray) -> jnp.ndarray:
    """(n,) squared distances of every x row to one centroid row."""
    return jnp.maximum(x_sq - 2.0 * (x @ row) + jnp.dot(row, row), 0.0)


# ---------------------------------------------------------------------------
# Shard-local kernel bodies (run inside shard_map; axis name DATA_AXIS)
# ---------------------------------------------------------------------------


def _pick_row_global(x: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """Row of the global argmax of ``scores`` across all shards.

    Cross-shard argmax: pmax of the local max, deterministic tie-break by the
    lowest device rank, then a psum-select of the winning row — communicates
    O(d), never gathers points.
    """
    rank = lax.axis_index(DATA_AXIS)
    ndev = lax.axis_size(DATA_AXIS)
    local_max = jnp.max(scores)
    local_arg = jnp.argmax(scores)
    gmax = lax.pmax(local_max, DATA_AXIS)
    owner = jnp.where(local_max == gmax, rank, ndev)
    sel = rank == lax.pmin(owner, DATA_AXIS)
    row = jnp.where(sel, x[local_arg], jnp.zeros((x.shape[1],), x.dtype))
    return lax.psum(row, DATA_AXIS)


def _d2_init_local(x, w, key, *, k):
    """KMeans++ D² sampling, shard-local view (x: (n_loc, d) shard).

    Gumbel-max: argmax(log p_i + G_i) is a categorical draw ∝ p_i, and argmax
    distributes across shards (see _pick_row_global) — so each of the k rounds
    is pure on-device compute + two scalar collectives + one O(d) psum.
    Degenerate rounds (all residual distances 0) fall back to a uniform draw
    (reference: kmeans_np.kmeans_plusplus_init fallback).
    """
    rank = lax.axis_index(DATA_AXIS)
    d = x.shape[1]
    x_sq = jnp.sum(x * x, axis=1)
    neg_inf = jnp.array(-jnp.inf, x.dtype)

    def sample(round_idx, logits):
        noise_key = jax.random.fold_in(jax.random.fold_in(key, round_idx), rank)
        g = jax.random.gumbel(noise_key, logits.shape, x.dtype)
        return _pick_row_global(x, jnp.where(w > 0, logits + g, neg_inf))

    # Round 0: uniform over valid points (reference kmeans_plusplus.py:9-10).
    c0 = sample(0, jnp.zeros_like(x_sq))
    centroids = jnp.zeros((k, d), x.dtype).at[0].set(c0)
    min_sq = _sq_dist_to_row(x, x_sq, c0)

    def round_body(i, carry):
        centroids, min_sq = carry
        total = lax.psum(jnp.sum(min_sq * w), DATA_AXIS)
        # p_i ∝ min_sq_i  ⇒  logits = log(min_sq); log(0) = -inf is exactly
        # "probability zero".  All-zero residuals ⇒ uniform fallback.
        logits = jnp.where(total > 0, jnp.log(min_sq), jnp.zeros_like(min_sq))
        ci = sample(i, logits)
        centroids = centroids.at[i].set(ci)
        min_sq = jnp.minimum(min_sq, _sq_dist_to_row(x, x_sq, ci))
        return centroids, min_sq

    centroids, _ = lax.fori_loop(1, k, round_body, (centroids, min_sq))
    return centroids


def _lloyd_local(x, w, centroids, key, *, k, n_valid, tol, max_iter):
    """Lloyd loop, shard-local view.  Returns (centroids, labels, iters, shift).

    Labels are the assignment against the centroids *before* the final update
    (reference loop order, kmeans_plusplus.py:33-48).
    """
    n_loc = x.shape[0]
    rank = lax.axis_index(DATA_AXIS)
    offset = rank * n_loc

    def cond(carry):
        _, _, _, it, shift = carry
        return (it < max_iter) & ((it == 0) | (shift >= tol))

    def body(carry):
        c, _, key, it, _ = carry
        labels = assign_labels_jax(x, c)
        sums = jax.ops.segment_sum(x * w[:, None], labels, num_segments=k)
        counts = jax.ops.segment_sum(w, labels, num_segments=k)
        sums = lax.psum(sums, DATA_AXIS)
        counts = lax.psum(counts, DATA_AXIS)

        # Seeded empty-cluster reseed: one uniform global index per cluster,
        # fetched without a gather (each shard contributes its owned rows).
        key, sub = jax.random.split(key)
        reseed_idx = jax.random.randint(sub, (k,), 0, n_valid)
        rel = reseed_idx - offset
        owned = (rel >= 0) & (rel < n_loc)
        cand = lax.psum(
            jnp.where(owned[:, None], x[jnp.clip(rel, 0, n_loc - 1)], 0.0),
            DATA_AXIS,
        )

        new_c = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts, 1.0)[:, None],
            cand,
        )
        shift = jnp.sqrt(jnp.sum((new_c - c) ** 2))
        return new_c, labels, key, it + 1, shift

    init = (
        centroids,
        jnp.zeros((n_loc,), jnp.int32),
        key,
        jnp.array(0, jnp.int32),
        jnp.array(jnp.inf, x.dtype),
    )
    c, labels, _, it, shift = lax.while_loop(cond, body, init)
    return c, labels, it, shift


# ---------------------------------------------------------------------------
# Compiled entry points
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _build_kmeans(n_valid, d, k, ndev, max_iter, tol, with_init, dtype_name):
    """Compile the full sharded kmeans for one (shape, mesh, config) point."""
    mesh = make_mesh(n_data=ndev)

    def local_fn(x, w, c0, key):
        if with_init:
            centroids = c0
        else:
            centroids = _d2_init_local(x, w, key, k=k)
        lloyd_key = jax.random.fold_in(key, 0x10D)  # distinct stream from init
        return _lloyd_local(
            x, w, centroids, lloyd_key,
            k=k, n_valid=n_valid, tol=tol, max_iter=max_iter,
        )

    sharded = jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(), P()),
        out_specs=(P(), P(DATA_AXIS), P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)


def kmeans_jax_full(
    X,
    k: int,
    tol: float = 1e-4,
    seed: int | None = None,
    max_iter: int = 100,
    init_centroids=None,
    mesh_shape: dict[str, int] | None = None,
    dtype=None,
):
    """Sharded KMeans++ + Lloyd.  Returns (centroids, labels, n_iter, shift).

    Reference entry point: src/kmeans_plusplus.py:24 ``kmeans(X, k, ...)``.
    ``init_centroids`` overrides the D² init (used by the numpy-parity tests so
    both backends iterate from identical starting points).
    ``mesh_shape={"data": N}`` shards rows over N devices; default 1.
    """
    X = np.asarray(X)
    if dtype is None:
        dtype = X.dtype if np.issubdtype(X.dtype, np.floating) else np.float32
    n, d = X.shape
    if k > n:
        raise ValueError(f"k={k} exceeds number of samples n={n}")
    ndev = int((mesh_shape or {}).get(DATA_AXIS, 1))

    Xp, n_valid = pad_rows(X.astype(dtype, copy=False), ndev)
    # Padded rows carry weight 0 and reseed draws are bounded by n_valid, so
    # padding never leaks into sums, counts, or sampling.
    w = np.zeros(Xp.shape[0], dtype=dtype)
    w[:n] = 1.0

    with_init = init_centroids is not None
    c0 = (
        np.asarray(init_centroids, dtype=dtype)
        if with_init
        else np.zeros((k, d), dtype=dtype)
    )
    key = jax.random.PRNGKey(0 if seed is None else int(seed))

    fn = _build_kmeans(
        n_valid, d, int(k), ndev, int(max_iter), float(tol),
        with_init, np.dtype(dtype).name,
    )
    centroids, labels, it, shift = fn(Xp, w, c0, key)
    return centroids, labels[:n], int(it), float(shift)


def kmeans_jax(
    X,
    k: int,
    tol: float = 1e-4,
    seed: int | None = None,
    max_iter: int = 100,
    init_centroids=None,
    mesh_shape: dict[str, int] | None = None,
    dtype=None,
):
    """Reference-shaped API: returns (centroids, labels)."""
    centroids, labels, _, _ = kmeans_jax_full(
        X, k, tol=tol, seed=seed, max_iter=max_iter,
        init_centroids=init_centroids, mesh_shape=mesh_shape, dtype=dtype,
    )
    return centroids, labels
