"""TPU-native KMeans++ — sharded JAX kernels.

Re-designs the reference's single-threaded NumPy KMeans
(reference: src/kmeans_plusplus.py:3-50) as XLA-compiled SPMD kernels over a
``jax.sharding.Mesh``:

* **Assignment** — the reference's dense ``(n, k, d)`` broadcast
  (kmeans_plusplus.py:33) becomes the matmul expansion
  ``argmin_k(‖c‖² − 2·x·Cᵀ)`` — one MXU matmul per step, never materializing
  the (n, k, d) temporary.  Points are sharded along the ``data`` mesh axis,
  centroids replicated.
* **Update** — the reference's k masked means (kmeans_plusplus.py:38-43)
  become one ``segment_sum`` of (weighted x, weight) per shard followed by a
  single ``lax.psum`` over ICI — the TPU equivalent of Spark's shuffle /
  an NCCL allreduce (SURVEY.md §2.5).
* **D² init** — the reference recomputes all pairwise distances each round
  (kmeans_plusplus.py:13-17, O(n·k²·d)); here the min-distance state is
  incremental (O(n·d) per round) and the categorical draw runs **on device**
  via the Gumbel-max trick with a cross-shard argmax, so the k-round loop is
  a single ``lax.fori_loop`` with zero host syncs.
* **Convergence** — ``lax.while_loop`` on the Frobenius centroid shift
  (reference tol semantics, kmeans_plusplus.py:45-48); labels returned are
  the assignment against the pre-update centroids, exactly the reference's
  loop order.
* **Empty clusters** — reseeded to a uniformly drawn data point from the
  threaded PRNG key (the reference used the *unseeded* global RNG,
  kmeans_plusplus.py:43 — fixed per SURVEY.md §6.1.2).

Padded rows (for even sharding) carry weight 0 and are excluded from sums,
counts, and sampling.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import (DATA_AXIS, MODEL_AXIS, make_mesh, pad_rows,
                             prefix_mask, shard_map_compat)

__all__ = [
    "pairwise_sq_dists_jax",
    "assign_labels_jax",
    "kmeans_jax",
    "kmeans_jax_full",
    "padding_multiple",
    "resolve_update",
    "resolve_init_method",
]

#: "auto" init flips from d2 to kmeans|| at this k.  D² is k sequential
#: rounds (7.5 s at k=1024, config 3 — 3x the 5-iter Lloyd budget) while
#: kmeans||'s 5 rounds are k-independent (0.33 s); the recorded quality gate
#: (data/init_quality_r5.json: final-inertia ratio ~1.00 across 5 seeds at
#: configs 2 and 3, pipeline planted accuracy within seed noise) shows
#: nothing is lost.  Below this k the D² cost is negligible and its
#: reference-faithful semantics win by default.
AUTO_INIT_KMEANS_PAR_MIN_K = 256



@functools.lru_cache(maxsize=64)
def _device_key(seed: int):
    """Per-seed PRNG key, staged on device once.

    ``jax.random.PRNGKey`` per call costs a host->device dispatch; on a
    remote-tunnel backend that is ~25-100 ms of fixed latency per kmeans
    call (measured: ~230 ms of per-call transfers before this cache)."""
    return jax.block_until_ready(jax.random.PRNGKey(seed))


@functools.lru_cache(maxsize=16)
def _device_scalar_i32(v: int):
    return jax.block_until_ready(jnp.asarray(v, jnp.int32))


def _zero_centroids(k: int, d: int, dtype_name: str):
    # Placeholder for the unused c0 operand when the init runs on device;
    # canonicalize f64 -> f32 silently when x64 is off (jnp.zeros warns).
    # Canonicalization happens BEFORE the cache key so flipping
    # jax_enable_x64 mid-process can't serve a stale-dtype buffer.
    if dtype_name == "float64" and not jax.config.jax_enable_x64:
        dtype_name = "float32"
    return _zero_centroids_cached(k, d, dtype_name)


@functools.lru_cache(maxsize=16)
def _zero_centroids_cached(k: int, d: int, dtype_name: str):
    return jax.block_until_ready(jnp.zeros((k, d), dtype_name))


def _stat_dtype(dtype):
    """Accumulator/centroid dtype for a given points dtype.

    Sub-f32 floats (bfloat16/float16) keep the POINTS low-precision — halving
    the HBM stream the Lloyd step is bound by — but centroids, per-cluster
    sums, counts, and the convergence shift stay float32: a bf16 count
    saturates at 256 and a bf16 sum of ~n/k values has ~2 useful digits.
    f32/f64 pass through unchanged (full-precision parity paths).
    """
    d = jnp.dtype(dtype)
    if d in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.dtype(jnp.float32)
    return d


def pallas_tile(k: int) -> int | None:
    """Column tile for the fused kernel at this k, or None when no tile
    fits VMEM (single source: ops/pallas_kernels.lloyd_tile — the tuning
    notes live there).  ``chunk_rows`` deliberately plays no part: it
    bounds the XLA scan's (chunk, k) HBM buffer, while the pallas kernel's
    working set is VMEM-tiled internally and never materializes (n, k) at
    all — on v5e the kernel beats the 131072-row matmul scan ~2x at
    config 3 (k=1024) precisely by using its own much smaller tile."""
    from .pallas_kernels import lloyd_tile

    return lloyd_tile(k)


def resolve_update(update: str, nmodel: int = 1, dtype=np.float32,
                   k: int | None = None) -> str:
    """Resolve the "auto" Lloyd assign+reduce strategy.

    "auto" -> "pallas" on a real TPU backend with an unsharded centroid
    table, f32 or bf16 data, and a k whose VMEM tile exists (the fastest
    measured path: the fused feature-major VMEM kernel, ~3.5x the XLA
    matmul path on v5e at 1M x 32, k=128); "matmul" everywhere else (CPU
    tests run the pallas kernel only in interpret mode, which is orders of
    magnitude slower than XLA).  Explicitly requested strategies pass
    through untouched.
    """
    if update != "auto":
        return update
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        on_tpu = False
    pallas_dtypes = (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16))
    if not (on_tpu and nmodel == 1 and jnp.dtype(dtype) in pallas_dtypes):
        return "matmul"
    if k is not None and pallas_tile(k) is None:
        return "matmul"
    return "pallas"


def resolve_init_method(init_method: str, k: int) -> str:
    """Resolve the "auto" centroid init.

    "auto" -> "kmeans||" once k reaches ``AUTO_INIT_KMEANS_PAR_MIN_K``
    (the D² init's k sequential rounds dominate e2e time at large k;
    quality gate recorded in data/init_quality_r5.json), "d2" below it.
    Explicit choices pass through untouched.  Feasibility (kmeans||'s
    per-round sample must fit one shard) is checked downstream by
    ``kmeans_jax_full``, which falls back to d2 for auto-resolved runs.
    """
    if init_method != "auto":
        return init_method
    return "kmeans||" if int(k) >= AUTO_INIT_KMEANS_PAR_MIN_K else "d2"


def padding_multiple(ndata: int, chunk_rows: int | None, update: str,
                     k: int | None = None) -> int:
    """Row-count multiple the kernel pads/shards to.

    Single source for callers (e.g. the benchmark harness) that pre-stage a
    sharded device array and must match ``kmeans_jax_full``'s padding rule:
    each of the ``ndata`` shards must hold a whole number of chunks
    (matmul/scatter scan) or pallas tiles (``pallas_tile(k)``).
    """
    if resolve_update(update, k=k) == "pallas":
        from .pallas_kernels import LLOYD_TILE_COLS

        return int(ndata) * int(pallas_tile(k) if k is not None
                                else LLOYD_TILE_COLS)
    return int(ndata) * int(chunk_rows or 1)


def pairwise_sq_dists_jax(x: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Squared Euclidean distances (n, k) via ‖x‖² − 2·x·Cᵀ + ‖c‖².

    Matches ops/kmeans_np.pairwise_sq_dists (the golden model); the matmul is
    the MXU-friendly form of reference kmeans_plusplus.py:14-17.
    """
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)
    c_sq = jnp.sum(c * c, axis=1)
    return jnp.maximum(x_sq - 2.0 * (x @ c.T) + c_sq[None, :], 0.0)


def assign_labels_jax(x: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    """Nearest-centroid labels; drops the per-row-constant ‖x‖² term
    (same trick as ops/kmeans_np.assign_labels)."""
    c_sq = jnp.sum(c * c, axis=1)
    d = c_sq[None, :] - 2.0 * (x @ c.T)
    return jnp.argmin(d, axis=1).astype(jnp.int32)


def _sq_dist_to_row(x: jnp.ndarray, x_sq: jnp.ndarray, row: jnp.ndarray) -> jnp.ndarray:
    """(n,) squared distances of every x row to one centroid row."""
    return jnp.maximum(x_sq - 2.0 * (x @ row) + jnp.dot(row, row), 0.0)


# ---------------------------------------------------------------------------
# Shard-local kernel bodies (run inside shard_map; axis name DATA_AXIS)
# ---------------------------------------------------------------------------


def _pick_row_global(x: jnp.ndarray, scores: jnp.ndarray,
                     sharded: bool = True) -> jnp.ndarray:
    """Row of the global argmax of ``scores`` across all shards.

    Cross-shard argmax: pmax of the local max, deterministic tie-break by the
    lowest device rank, then a psum-select of the winning row — communicates
    O(d), never gathers points.  Unsharded: a plain argmax gather.
    """
    if not sharded:
        return x[jnp.argmax(scores)]
    rank = lax.axis_index(DATA_AXIS)
    # lax.axis_size is missing from older jax releases; psum(1) over the
    # axis is the portable spelling and folds to a constant at trace time.
    ndev = lax.psum(jnp.int32(1), DATA_AXIS)
    local_max = jnp.max(scores)
    local_arg = jnp.argmax(scores)
    gmax = lax.pmax(local_max, DATA_AXIS)
    owner = jnp.where(local_max == gmax, rank, ndev)
    sel = rank == lax.pmin(owner, DATA_AXIS)
    row = jnp.where(sel, x[local_arg], jnp.zeros((x.shape[1],), x.dtype))
    return lax.psum(row, DATA_AXIS)


def _row_noise(key, round_idx, *, n_noise, n_loc, ndata, rank, dtype,
               sharded):
    """Per-row Gumbel noise for one sampling round, MESH-SHAPE-INVARIANT.

    The draw is replicated over the ``n_noise`` valid-row prefix — a static
    length independent of the mesh (threefry is NOT prefix-stable across
    shapes, so the length must not depend on padding) — from a key that no
    longer folds the shard rank, then zero-padded to the padded total and
    sliced to this shard's rows.  The same (seed, round, global row) hence
    draws the same noise at any ``data=N``, which is what makes the D²/
    kmeans|| inits (and every controller decision downstream of a cold
    re-cluster) identical across mesh shapes.  Padded rows get 0; every
    caller masks them to -inf before the argmax.  Costs O(n) RNG per shard
    per round (redundant across shards) — noise generation is noise next
    to the O(n·d) distance pass each round already pays.
    """
    key_r = jax.random.fold_in(jax.random.fold_in(key, round_idx), 0)
    g = jax.random.gumbel(key_r, (n_noise,), dtype)
    n_pad = n_loc * ndata
    if n_pad != n_noise:
        g = jnp.concatenate([g, jnp.zeros((n_pad - n_noise,), dtype)])
    if not sharded:
        return g
    return lax.dynamic_slice_in_dim(g, rank * n_loc, n_loc)


def _d2_init_local(x, w, key, *, k, n_valid, ndata, sharded=True):
    """KMeans++ D² sampling, shard-local view (x: (n_loc, d) shard).

    Gumbel-max: argmax(log p_i + G_i) is a categorical draw ∝ p_i, and argmax
    distributes across shards (see _pick_row_global) — so each of the k rounds
    is pure on-device compute + two scalar collectives + one O(d) psum.
    Noise is keyed to the GLOBAL row (``_row_noise``), so the selected
    centroids are identical at any mesh shape on the same seed.
    Degenerate rounds (all residual distances 0) fall back to a uniform draw
    (reference: kmeans_np.kmeans_plusplus_init fallback).
    """
    rank = lax.axis_index(DATA_AXIS) if sharded else jnp.int32(0)
    n_loc, d = x.shape
    x_sq = jnp.sum(x * x, axis=1)
    neg_inf = jnp.array(-jnp.inf, x.dtype)

    def sample(round_idx, logits):
        g = _row_noise(key, round_idx, n_noise=n_valid, n_loc=n_loc,
                       ndata=ndata, rank=rank, dtype=x.dtype,
                       sharded=sharded)
        return _pick_row_global(x, jnp.where(w > 0, logits + g, neg_inf),
                                sharded)

    # Round 0: uniform over valid points (reference kmeans_plusplus.py:9-10).
    c0 = sample(0, jnp.zeros_like(x_sq))
    centroids = jnp.zeros((k, d), x.dtype).at[0].set(c0)
    min_sq = _sq_dist_to_row(x, x_sq, c0)

    def round_body(i, carry):
        centroids, min_sq = carry
        total = jnp.sum(min_sq * w)
        if sharded:
            total = lax.psum(total, DATA_AXIS)
        # p_i ∝ min_sq_i  ⇒  logits = log(min_sq); log(0) = -inf is exactly
        # "probability zero".  All-zero residuals ⇒ uniform fallback.
        logits = jnp.where(total > 0, jnp.log(min_sq), jnp.zeros_like(min_sq))
        ci = sample(i, logits)
        centroids = centroids.at[i].set(ci)
        min_sq = jnp.minimum(min_sq, _sq_dist_to_row(x, x_sq, ci))
        return centroids, min_sq

    centroids, _ = lax.fori_loop(1, k, round_body, (centroids, min_sq))
    return centroids


# ---------------------------------------------------------------------------
# k-means|| init (Bahmani et al., VLDB'12 — public algorithm), TPU-shaped
# ---------------------------------------------------------------------------


def _weighted_kmeanspp(c, wts, key, k):
    """Weighted D² reduction of a small candidate set to k centroids.

    Runs replicated (identical on every shard: the PRNG stream does NOT fold
    in the shard rank), so it needs no collectives.  Zero-weight candidates
    are never drawn.
    """
    n_cand, d = c.shape
    c_sq = jnp.sum(c * c, axis=1)
    neg_inf = jnp.array(-jnp.inf, c.dtype)
    wlog = jnp.where(wts > 0, jnp.log(wts), neg_inf)

    g0 = jax.random.gumbel(jax.random.fold_in(key, 0), (n_cand,), c.dtype)
    i0 = jnp.argmax(wlog + g0)           # sample ∝ weight
    cent = jnp.zeros((k, d), c.dtype).at[0].set(c[i0])
    min_sq = _sq_dist_to_row(c, c_sq, c[i0])

    def body(i, carry):
        cent, min_sq = carry
        total = jnp.sum(min_sq * wts)
        # p ∝ w * D²; all-zero residuals -> weighted-uniform fallback.
        logits = jnp.where(total > 0,
                           wlog + jnp.log(jnp.maximum(min_sq, 1e-38)),
                           wlog)
        g = jax.random.gumbel(jax.random.fold_in(key, i), (n_cand,), c.dtype)
        idx = jnp.argmax(logits + g)
        ci = c[idx]
        cent = cent.at[i].set(ci)
        min_sq = jnp.minimum(min_sq, _sq_dist_to_row(c, c_sq, ci))
        return cent, min_sq

    cent, _ = lax.fori_loop(1, k, body, (cent, min_sq))
    return cent


def _weighted_lloyd_small(c, wts, cent, iters):
    """A few weighted Lloyd iterations on the candidate set (replicated)."""
    k = cent.shape[0]
    # Carry in the stat dtype: wts are f32 for bf16 candidates, so the
    # updated centroids promote — the loop carry must match from iter 0.
    cent = cent.astype(_stat_dtype(c.dtype))

    def body(_, cent):
        lab = assign_labels_jax(c, cent)
        sums = jax.ops.segment_sum(c * wts[:, None], lab, num_segments=k)
        counts = jax.ops.segment_sum(wts, lab, num_segments=k)
        return jnp.where(counts[:, None] > 0,
                         sums / jnp.maximum(counts, 1.0)[:, None], cent)

    return lax.fori_loop(0, iters, body, cent)


def _kmeans_par_init_local(x, w, key, *, k, rounds, per_round, n_valid,
                           ndata, cand_lloyd_iters=10, sharded=True):
    """k-means|| init, shard-local view — O(rounds) passes instead of k.

    The reference's D² init is inherently sequential in k (1024 rounds at the
    BASELINE configs — SURVEY.md §7.4); k-means|| replaces it with ``rounds``
    oversampling passes, each drawing ``per_round`` points ∝ D² *without
    replacement* via distributed Gumbel top-m (each shard takes a local
    top-m of log(D²)+Gumbel, an ``all_gather`` of (m,) scores + (m, d) rows
    merges them into the global top-m — O(rounds · m · d) communicated, the
    points matrix never moves).  This is a documented, statically-shaped
    stand-in for the paper's Bernoulli sampling (which draws a *random
    number* of points — impossible under XLA's static shapes).  Candidates
    are then weighted by an assignment count pass and reduced to k with a
    replicated weighted D² + a few weighted Lloyd steps (Bahmani §3.3).
    Round noise is keyed to the GLOBAL row (``_row_noise``), so the drawn
    candidate set is identical at any mesh shape on the same seed.
    """
    rank = lax.axis_index(DATA_AXIS) if sharded else jnp.int32(0)
    n_loc, d = x.shape
    x_sq = jnp.sum(x * x, axis=1)
    neg_inf = jnp.array(-jnp.inf, x.dtype)
    n_cand = 1 + rounds * per_round

    key_rounds, key_reduce = jax.random.split(key)

    def noise(round_idx):
        return _row_noise(key_rounds, round_idx, n_noise=n_valid,
                          n_loc=n_loc, ndata=ndata, rank=rank,
                          dtype=x.dtype, sharded=sharded)

    # Round 0: one uniform draw (same as D² round 0).
    c0 = _pick_row_global(x, jnp.where(w > 0, noise(0), neg_inf), sharded)
    cands = jnp.zeros((n_cand, d), x.dtype).at[0].set(c0)
    min_sq = _sq_dist_to_row(x, x_sq, c0)

    def round_body(r, carry):
        cands, min_sq = carry
        g = noise(r + 1)
        total = jnp.sum(min_sq * w)
        if sharded:
            total = lax.psum(total, DATA_AXIS)
        logits = jnp.where(total > 0,
                           jnp.log(jnp.maximum(min_sq, 1e-38)),
                           jnp.zeros_like(min_sq))
        scores = jnp.where(w > 0, logits + g, neg_inf)
        vals, idx = lax.top_k(scores, per_round)          # local top-m
        rows = x[idx]                                     # (m, d)
        if sharded:
            all_vals = lax.all_gather(vals, DATA_AXIS).reshape(-1)
            all_rows = lax.all_gather(rows, DATA_AXIS).reshape(-1, d)
            _, gsel = lax.top_k(all_vals, per_round)      # global top-m
            new_rows = all_rows[gsel]                     # replicated (m, d)
        else:
            new_rows = rows                               # local IS global
        cands = lax.dynamic_update_slice(cands, new_rows,
                                         (1 + r * per_round, 0))
        d2new = jnp.maximum(
            x_sq[:, None] - 2.0 * (x @ new_rows.T)
            + jnp.sum(new_rows * new_rows, axis=1)[None, :], 0.0)
        return cands, jnp.minimum(min_sq, d2new.min(axis=1))

    cands, _ = lax.fori_loop(0, rounds, round_body, (cands, min_sq))

    # Weight candidates by how many points they own (one assignment pass).
    # Counts accumulate in the stat dtype — a bf16 sum of ones stalls at 256
    # (same contract as _weighted_cluster_stats).
    lab = assign_labels_jax(x, cands)
    wts = jax.ops.segment_sum(w.astype(_stat_dtype(w.dtype)), lab,
                              num_segments=n_cand)
    if sharded:
        wts = lax.psum(wts, DATA_AXIS)

    cent = _weighted_kmeanspp(cands, wts, key_reduce, k)
    return _weighted_lloyd_small(cands, wts, cent, cand_lloyd_iters)


def _weighted_cluster_stats(xc, wc, lab, k, update):
    """Per-cluster (sum, count) for one row block.

    ``matmul`` builds the weighted one-hot assignment matrix and reduces with
    a (k, n)x(n, d) matmul — MXU work, ~3x faster than scatter on TPU.
    ``scatter`` uses ``segment_sum`` — less memory (no (n, k) one-hot), and
    bit-identical to numpy's bincount ordering.

    Stats accumulate in ``_stat_dtype`` (f32 for bf16 points): the MXU takes
    bf16 inputs natively but a bf16 *sum* of ~n/k terms is unusable.
    """
    acc = _stat_dtype(xc.dtype)
    if update == "matmul":
        oh = jax.nn.one_hot(lab, k, dtype=acc) * wc[:, None].astype(acc)
        return jnp.dot(oh.T, xc, preferred_element_type=acc), oh.sum(axis=0)
    sums = jax.ops.segment_sum(
        xc.astype(acc) * wc[:, None].astype(acc), lab, num_segments=k)
    counts = jax.ops.segment_sum(wc.astype(acc), lab, num_segments=k)
    return sums, counts


def _assign_reduce(x, w, c, k, chunk_rows, update="matmul", n_valid=None,
                   xt=None, sharded=True, with_inertia=False):
    """Fused assignment + per-cluster (sum, count) reduction for one shard.

    ``chunk_rows=None`` materializes the full (n_loc, k) distance block — fast
    when it fits.  Otherwise a ``lax.scan`` over row tiles keeps peak memory at
    (chunk_rows × k) while accumulating the (k, d) sums in-place — the tiling
    the reference's dense (n, k, d) broadcast lacks (SURVEY.md §3.2 hot loop #4,
    §7.4 "memory at 100M×128").

    ``with_inertia=True`` (telemetry convergence traces, obs/) additionally
    returns the shard-local weighted inertia Σ w·‖x − c_label‖² as a fourth
    output, recovered from the distance block the assignment already
    computes plus one O(n·d) ‖x‖² pass — not supported on the pallas path
    (the fused kernel never exposes distances; ``kmeans_jax_full`` resolves
    traced runs to the matmul strategy).
    """
    if update == "pallas":
        if with_inertia:
            raise ValueError("inertia traces unavailable on the pallas path")
        # Fused VMEM-resident feature-major kernel (ops/pallas_kernels.py).
        # The shard-local valid count is derived exactly from the static
        # global n_valid (a float mask sum would saturate at 2**24 rows in
        # f32).  The Lloyd while_loop discards labels, so the kernel omits
        # that output — an unused custom-call output can't be DCE'd and
        # would DMA an (n,) buffer per iteration.  ``xt`` is the (d, n_loc)
        # transposed view, computed ONCE outside the loop by the caller (the
        # per-iteration transpose would cost more than it saves).
        from .pallas_kernels import lloyd_assign_reduce_pallas_t

        n_loc = x.shape[0]
        row0 = lax.axis_index(DATA_AXIS) * n_loc if sharded else 0
        nv = jnp.clip(n_valid - row0, 0, n_loc).astype(jnp.int32)
        labels, sums, counts = lloyd_assign_reduce_pallas_t(
            x.T if xt is None else xt, c, nv,
            tile_cols=pallas_tile(k), with_labels=False)
        acc = _stat_dtype(x.dtype)
        return labels, sums.astype(acc), counts.astype(acc)

    acc = _stat_dtype(x.dtype)
    if chunk_rows is None:
        if not with_inertia:
            labels = assign_labels_jax(x, c)
            sums, counts = _weighted_cluster_stats(x, w, labels, k, update)
            return labels, sums, counts
        c_sq = jnp.sum(c * c, axis=1)
        dist = c_sq[None, :] - 2.0 * (x @ c.T)     # ‖x‖² dropped for argmin
        labels = jnp.argmin(dist, axis=1).astype(jnp.int32)
        x_sq = jnp.sum((x * x).astype(acc), axis=1)
        min_sq = jnp.maximum(dist.min(axis=1).astype(acc) + x_sq, 0.0)
        inertia = jnp.sum(w.astype(acc) * min_sq)
        sums, counts = _weighted_cluster_stats(x, w, labels, k, update)
        return labels, sums, counts, inertia

    n_loc, d = x.shape
    nch = n_loc // chunk_rows
    xr = x.reshape(nch, chunk_rows, d)
    wr = w.reshape(nch, chunk_rows)
    c_sq = jnp.sum(c * c, axis=1)

    def step(carry, xw):
        sums, counts, inertia = carry
        xc, wc = xw
        dist = c_sq[None, :] - 2.0 * (xc @ c.T)
        lab = jnp.argmin(dist, axis=1).astype(jnp.int32)
        s, cnt = _weighted_cluster_stats(xc, wc, lab, k, update)
        if with_inertia:
            x_sq = jnp.sum((xc * xc).astype(acc), axis=1)
            min_sq = jnp.maximum(dist.min(axis=1).astype(acc) + x_sq, 0.0)
            inertia = inertia + jnp.sum(wc.astype(acc) * min_sq)
        return (sums + s, counts + cnt, inertia), lab

    (sums, counts, inertia), labels = lax.scan(
        step,
        (jnp.zeros((k, d), acc), jnp.zeros((k,), acc),
         jnp.zeros((), acc)),
        (xr, wr),
    )
    if with_inertia:
        return labels.reshape(n_loc), sums, counts, inertia
    return labels.reshape(n_loc), sums, counts


def _assign_only(x, c, chunk_rows, update="matmul", xt=None, k=None):
    """Labels for one shard without the stats reduction (post-loop pass).

    On the pallas path the labels ride the fused kernel too (first-min
    tie-break, same as argmin): the XLA fallback materializes an
    (chunk, k) distance block in HBM per scan step — at config 3 that one
    epilogue pass costs as much as several fused Lloyd iterations.
    """
    if update == "pallas":
        from .pallas_kernels import lloyd_assign_reduce_pallas_t

        labels, _, _ = lloyd_assign_reduce_pallas_t(
            x.T if xt is None else xt, c, n_valid=x.shape[0],
            tile_cols=pallas_tile(k if k is not None else c.shape[0]))
        return labels
    if chunk_rows is None:
        return assign_labels_jax(x, c)
    n_loc, d = x.shape
    xr = x.reshape(n_loc // chunk_rows, chunk_rows, d)
    c_sq = jnp.sum(c * c, axis=1)

    def step(_, xc):
        dist = c_sq[None, :] - 2.0 * (xc @ c.T)
        return None, jnp.argmin(dist, axis=1).astype(jnp.int32)

    _, labels = lax.scan(step, None, xr)
    return labels.reshape(n_loc)


def _lloyd_local(x, w, centroids, key, iter_offset, *, k, n_valid, tol,
                 max_iter, chunk_rows=None, update="matmul", sharded=True,
                 trace=False):
    """Lloyd loop, shard-local view.  Returns (centroids, labels, iters, shift)
    — plus ``(trace_inertia, trace_shift)`` (max_iter,)-shaped buffers when
    ``trace`` is set.

    Labels are the assignment against the centroids *before* the final update
    (reference loop order, kmeans_plusplus.py:33-48) — computed in one extra
    assignment pass after the loop rather than carried through it: an (n,)
    buffer in the while_loop carry blocks XLA from fusing the
    argmin/one-hot/matmul chain and costs ~3x per iteration (measured on
    v5e: 24 ms vs 7 ms per iteration at n=1M, k=128).

    ``trace`` (telemetry convergence traces, obs/) carries two (max_iter,)
    f32 buffers through the loop — per-iteration inertia (against the
    pre-update centroids, the standard convention) and centroid shift —
    written at index ``it`` and emitted post-hoc by the caller; entries past
    the converged iteration stay zero.  The scalars ride the existing
    reduction pass, so tracing costs one O(n·d) ‖x‖² pass per iteration,
    not a second assignment.
    """
    n_loc = x.shape[0]
    offset = lax.axis_index(DATA_AXIS) * n_loc if sharded else 0
    # Feature-major copy for the pallas kernel, materialized once before the
    # loop (loop-invariant closure): for d < 128 the row-major (n, d) layout
    # is lane-padded to 128 in HBM, so reading it costs 128/d x the logical
    # bytes per iteration; (d, n) is dense.
    xt = x.T if update == "pallas" else None

    def cond(carry):
        it, shift = carry[2], carry[3]
        return (it < max_iter) & ((it == 0) | (shift >= tol))

    def body(carry):
        c, _, it = carry[0], carry[1], carry[2]
        if not trace:
            _, sums, counts = _assign_reduce(x, w, c, k, chunk_rows, update,
                                             n_valid=n_valid, xt=xt,
                                             sharded=sharded)
            return _update_step(c, sums, counts, it)
        _, sums, counts, inertia = _assign_reduce(
            x, w, c, k, chunk_rows, update, n_valid=n_valid, xt=xt,
            sharded=sharded, with_inertia=True)
        if sharded:
            inertia = lax.psum(inertia, DATA_AXIS)
        tr_inertia, tr_shift = carry[4], carry[5]
        tr_inertia = tr_inertia.at[it].set(inertia.astype(tr_inertia.dtype))
        new_c, c_prev, it1, shift = _update_step(c, sums, counts, it)
        tr_shift = tr_shift.at[it].set(shift.astype(tr_shift.dtype))
        return new_c, c_prev, it1, shift, tr_inertia, tr_shift

    def _update_step(c, sums, counts, it):
        if sharded:
            sums = lax.psum(sums, DATA_AXIS)
            counts = lax.psum(counts, DATA_AXIS)
        # Reseed key depends on the GLOBAL iteration index (iter_offset + it),
        # not on a per-call split chain — blocked/checkpointed runs draw the
        # same stream as uninterrupted ones (utils/checkpoint.py).
        sub = jax.random.fold_in(key, iter_offset + it)

        def with_reseed(_):
            # Seeded empty-cluster reseed: one uniform global index per
            # cluster, fetched without a gather (each shard contributes its
            # owned rows).  Behind lax.cond because empty clusters are rare
            # and per-kernel launch overhead dominates small ops on TPU;
            # the predicate is psum-replicated so every shard takes the same
            # branch (collectives inside stay aligned).
            reseed_idx = jax.random.randint(sub, (k,), 0, n_valid)
            rel = reseed_idx - offset
            owned = (rel >= 0) & (rel < n_loc)
            cand = jnp.where(owned[:, None],
                             x[jnp.clip(rel, 0, n_loc - 1)], 0.0)
            if sharded:
                cand = lax.psum(cand, DATA_AXIS)
            return jnp.where(
                counts[:, None] > 0,
                sums / jnp.maximum(counts, 1.0)[:, None],
                cand,
            )

        def no_empty(_):
            return sums / jnp.maximum(counts, 1.0)[:, None]

        new_c = lax.cond(jnp.any(counts == 0), with_reseed, no_empty, None)
        shift = jnp.sqrt(jnp.sum((new_c - c) ** 2))
        return new_c, c, it + 1, shift

    init = (
        centroids,
        centroids,
        jnp.array(0, jnp.int32),
        jnp.array(jnp.inf, centroids.dtype),
    )
    if trace:
        init = init + (jnp.zeros((max_iter,), jnp.float32),
                       jnp.zeros((max_iter,), jnp.float32))
    if tol <= 0:
        # Fixed iteration budget (tol disabled): a static-trip fori_loop —
        # identical iteration count (shift >= 0 keeps the while cond true)
        # but ~0.4 ms/iter cheaper on v5e, where the dynamic trip count
        # blocks XLA's cross-iteration scheduling.
        out = lax.fori_loop(0, max_iter, lambda _, carry: body(carry), init)
    else:
        out = lax.while_loop(cond, body, init)
    c, c_prev, it, shift = out[:4]
    labels = _assign_only(x, c_prev, chunk_rows, update=update, xt=xt, k=k)
    if trace:
        return c, labels, it, shift, out[4], out[5]
    return c, labels, it, shift


def _lloyd_local_2d(x, w, c_loc, key, iter_offset, *, k, n_valid, tol,
                    max_iter, chunk_rows=None, update="matmul"):
    """Lloyd loop on a 2D (data, model) mesh — tensor-parallel centroids.

    Points are sharded over ``data`` (as in _lloyd_local); the centroid table
    is additionally sharded over ``model``: each shard holds k_loc = k/M rows,
    computes distances only to those (an (n_loc, k_loc) matmul), and the
    global argmin is recovered with two tiny ``model``-axis collectives
    (pmin of the best distance, then pmin of the candidate global index,
    which also reproduces NumPy's first-minimum tie-break).  This keeps both
    the FLOPs and the O(n·k) distance buffer partitioned when k is large
    (the 100M x 128, k=1024 BASELINE config).
    """
    n_loc = x.shape[0]
    k_loc = c_loc.shape[0]
    d_rank = lax.axis_index(DATA_AXIS)
    m_rank = lax.axis_index(MODEL_AXIS)
    offset = d_rank * n_loc
    k_off = m_rank * k_loc

    def assign_block(c_loc, xc):
        """Global labels for one row block (two tiny model-axis collectives)."""
        c_sq = jnp.sum(c_loc * c_loc, axis=1)
        d_loc = c_sq[None, :] - 2.0 * (xc @ c_loc.T)         # (rows, k_loc)
        lmin = d_loc.min(axis=1)
        larg = (jnp.argmin(d_loc, axis=1) + k_off).astype(jnp.int32)
        gmin = lax.pmin(lmin, MODEL_AXIS)
        return lax.pmin(jnp.where(lmin == gmin, larg, k), MODEL_AXIS)

    def assign_2d(c_loc):
        if chunk_rows is None:
            return assign_block(c_loc, x)
        xr = x.reshape(n_loc // chunk_rows, chunk_rows, -1)
        _, labels = lax.scan(lambda _, xc: (None, assign_block(c_loc, xc)), None, xr)
        return labels.reshape(n_loc)

    def assign_reduce_2d(c_loc):
        """Labels + full-(k,) stats, tiled over row chunks when requested."""
        if chunk_rows is None:
            labels = assign_block(c_loc, x)
            sums, counts = _weighted_cluster_stats(x, w, labels, k, update)
            return labels, sums, counts
        nch = n_loc // chunk_rows
        xr = x.reshape(nch, chunk_rows, -1)
        wr = w.reshape(nch, chunk_rows)

        def step(carry, xw):
            sums, counts = carry
            xc, wc = xw
            lab = assign_block(c_loc, xc)
            s, cnt = _weighted_cluster_stats(xc, wc, lab, k, update)
            return (sums + s, counts + cnt), lab

        acc = _stat_dtype(x.dtype)
        (sums, counts), labels = lax.scan(
            step,
            (jnp.zeros((k, x.shape[1]), acc), jnp.zeros((k,), acc)),
            (xr, wr),
        )
        return labels.reshape(n_loc), sums, counts

    def cond(carry):
        _, _, it, shift = carry
        return (it < max_iter) & ((it == 0) | (shift >= tol))

    def body(carry):
        c_loc, _, it, _ = carry
        # Full (k,) stats computed redundantly per model shard (cheap), then
        # each shard keeps its own block — replaces an all-gather of labels.
        _, sums, counts = assign_reduce_2d(c_loc)
        sums = lax.psum(sums, DATA_AXIS)
        counts = lax.psum(counts, DATA_AXIS)
        sums_loc = lax.dynamic_slice_in_dim(sums, k_off, k_loc)
        counts_loc = lax.dynamic_slice_in_dim(counts, k_off, k_loc)
        sub = jax.random.fold_in(key, iter_offset + it)  # global-iter stream

        def with_reseed(_):
            # Rare path behind lax.cond (see _lloyd_local); the predicate is
            # computed from the full psum-replicated counts so all shards —
            # across both mesh axes — branch identically.
            reseed_idx = lax.dynamic_slice_in_dim(
                jax.random.randint(sub, (k,), 0, n_valid), k_off, k_loc
            )
            rel = reseed_idx - offset
            owned = (rel >= 0) & (rel < n_loc)
            cand = lax.psum(
                jnp.where(owned[:, None], x[jnp.clip(rel, 0, n_loc - 1)], 0.0),
                DATA_AXIS,
            )
            return jnp.where(
                counts_loc[:, None] > 0,
                sums_loc / jnp.maximum(counts_loc, 1.0)[:, None],
                cand,
            )

        def no_empty(_):
            return sums_loc / jnp.maximum(counts_loc, 1.0)[:, None]

        new_c = lax.cond(jnp.any(counts == 0), with_reseed, no_empty, None)
        shift = jnp.sqrt(
            lax.psum(jnp.sum((new_c - c_loc) ** 2), MODEL_AXIS)
        )
        return new_c, c_loc, it + 1, shift

    init = (
        c_loc,
        c_loc,
        jnp.array(0, jnp.int32),
        jnp.array(jnp.inf, c_loc.dtype),
    )
    if tol <= 0:
        # Static-trip loop for a fixed iteration budget (see _lloyd_local).
        c_loc, c_prev, it, shift = lax.fori_loop(
            0, max_iter, lambda _, carry: body(carry), init)
    else:
        c_loc, c_prev, it, shift = lax.while_loop(cond, body, init)
    labels = assign_2d(c_prev)
    return c_loc, labels, it, shift


# ---------------------------------------------------------------------------
# Compiled entry points
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _build_kmeans(n_valid, d, k, ndata, nmodel, max_iter, tol, with_init,
                  dtype_name, chunk_rows=None, update="matmul",
                  init_method="d2", init_rounds=5, init_per_round=0,
                  with_trace=False):
    """Compile the full sharded kmeans for one (shape, mesh, config) point.

    ``with_trace`` compiles the convergence-traced variant (two extra
    (max_iter,) outputs; telemetry, obs/) — a separate cache entry, so
    flipping telemetry on does not evict or perturb the production
    program."""
    k_loc = k // nmodel
    # Single-device bypass: a 1x1 mesh still pays shard_map's collective
    # plumbing (~0.9 ms/iter at config 2 on v5e — the raw fused kernel runs
    # 1.10 ms).  The same local body runs under plain jit with the
    # collectives compiled out; identical PRNG streams (rank folds in 0
    # either way).  Precedent: the streaming fold's one-device bypass.
    sharded = ndata > 1 or nmodel > 1

    def local_fn(x, c0, key, iter_offset):
        w = prefix_mask(x, n_valid, sharded=sharded)
        # Split once: the init stream folds in round indices [0, k) and the
        # Lloyd stream folds in global iteration indices — a single fold_in
        # domain would collide for k > the fold constant (the round-269
        # correlation ADVICE r1 flagged).  split() keys never overlap.
        init_key, lloyd_key = jax.random.split(key)
        if with_init:
            centroids = c0
        elif init_method == "kmeans||":
            centroids = _kmeans_par_init_local(
                x, w, init_key, k=k, rounds=init_rounds,
                per_round=init_per_round, n_valid=n_valid, ndata=ndata,
                sharded=sharded)
        else:
            centroids = _d2_init_local(x, w, init_key, k=k, n_valid=n_valid,
                                       ndata=ndata, sharded=sharded)
        # Centroids iterate in the stat dtype (f32 for bf16 points): the init
        # samples/averages in x's dtype, the Lloyd loop must not.
        centroids = centroids.astype(_stat_dtype(x.dtype))
        if nmodel == 1:
            return _lloyd_local(
                x, w, centroids, lloyd_key, iter_offset,
                k=k, n_valid=n_valid, tol=tol, max_iter=max_iter,
                chunk_rows=chunk_rows, update=update, sharded=sharded,
                trace=with_trace,
            )
        c_loc = lax.dynamic_slice_in_dim(
            centroids, lax.axis_index(MODEL_AXIS) * k_loc, k_loc
        )
        return _lloyd_local_2d(
            x, w, c_loc, lloyd_key, iter_offset,
            k=k, n_valid=n_valid, tol=tol, max_iter=max_iter,
            chunk_rows=chunk_rows, update=update,
        )

    if not sharded:
        return jax.jit(local_fn)
    mesh = make_mesh(n_data=ndata, n_model=nmodel)
    if nmodel == 1:
        c_spec = P()
    else:
        c_spec = P(MODEL_AXIS, None)
    out_specs = (c_spec, P(DATA_AXIS), P(), P())
    if with_trace:
        out_specs = out_specs + (P(), P())  # psum-replicated trace buffers
    mapped = shard_map_compat(
        local_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(), P(), P()),
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(mapped)


def kmeans_jax_full(
    X,
    k: int,
    tol: float = 1e-4,
    seed: int | None = None,
    max_iter: int = 100,
    init_centroids=None,
    mesh_shape: dict[str, int] | None = None,
    dtype=None,
    chunk_rows: int | None = None,
    update: str = "auto",
    n_valid: int | None = None,
    iter_offset: int = 0,
    init_method: str = "d2",
    init_oversample: float = 2.0,
    init_rounds: int = 5,
    block_scalars: bool = True,
):
    """Sharded KMeans++ + Lloyd.  Returns (centroids, labels, n_iter, shift).

    ``block_scalars=False`` skips the final device->host fetch of
    ``(n_iter, shift)`` and returns them as device scalars: the call then
    does not synchronize at all, so a downstream stage (e.g. the fused
    scoring program) dispatches immediately behind the Lloyd work — on a
    remote-tunnel backend the skipped fetch is a ~25-100 ms pipeline
    stall.  Callers needing Python ints fetch after their own final sync
    (``int(n_iter)`` works on the returned array).

    ``iter_offset`` shifts the global iteration index used for the reseed PRNG
    stream — a blocked/checkpointed run passing its completed-iteration count
    draws exactly the stream an uninterrupted run would (utils/checkpoint.py).

    Reference entry point: src/kmeans_plusplus.py:24 ``kmeans(X, k, ...)``.
    ``init_centroids`` overrides the D² init (used by the numpy-parity tests so
    both backends iterate from identical starting points).
    ``mesh_shape={"data": N}`` shards rows over N devices (data parallel);
    adding ``"model": M`` also shards the centroid table over M devices
    (tensor parallel, k divisible by M).  Default: single device.

    ``init_method="kmeans||"`` swaps the k-round D² init for the documented
    k-means|| oversampling init (SURVEY.md §7.4): ``init_rounds`` passes each
    drawing ``ceil(init_oversample * k / init_rounds)`` candidates — the init
    cost stops scaling with k (D² is 1024 sequential rounds at the BASELINE
    k=1024 configs).  Different (but comparable-quality) starting centroids
    than "d2"; not available with ``init_centroids``.  ``"auto"`` resolves
    by k (``resolve_init_method``: kmeans|| at k >= 256, d2 below, falling
    back to d2 when the oversample exceeds shard rows).
    """
    from .pallas_kernels import _enforce_pad_env

    # Eager, per-call: already-traced kernels replay without re-executing
    # the wrapper's Python, so this is where a mid-session
    # CDRS_TPU_ENFORCE_PAD flip gets its one-time ignored-flip warning.
    _enforce_pad_env()
    is_device_array = isinstance(X, jax.Array)
    if not is_device_array:
        X = np.asarray(X)
    if dtype is None:
        dtype = X.dtype if jnp.issubdtype(X.dtype, jnp.floating) else np.float32
    n, d = X.shape
    if k > n:
        raise ValueError(f"k={k} exceeds number of samples n={n}")
    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))
    nmodel = int((mesh_shape or {}).get(MODEL_AXIS, 1))
    if k % nmodel != 0:
        raise ValueError(f"k={k} must be divisible by the model axis size {nmodel}")
    if update not in ("auto", "matmul", "scatter", "pallas"):
        raise ValueError(f"unknown update strategy {update!r}")
    update = resolve_update(update, nmodel, dtype, k=k)

    # Telemetry (obs/): when an instrument is active with kmeans tracing on,
    # run the convergence-traced program (per-iteration inertia + shift
    # carried in the loop state, emitted post-hoc).  The fused pallas kernel
    # never exposes distances, so traced runs resolve to the matmul
    # strategy — a documented diagnostic-mode substitution.  Model-sharded
    # meshes stay untraced (the 2D loop has no traced variant).
    from ..obs import current as _obs_current

    _tel = _obs_current()
    with_trace = (_tel is not None and _tel.kmeans_trace and nmodel == 1)
    if with_trace and update == "pallas":
        update = "matmul"

    # pallas tiles rows internally (pallas_kernels.lloyd_tile), so shards
    # must divide it.
    multiple = padding_multiple(ndata, chunk_rows, update, k=k)
    if is_device_array:
        # Device-resident input (pipeline / benchmark / streaming path): never
        # copy to host.  ``n_valid`` marks the true row count when the caller
        # pre-padded; any remaining misalignment is padded on device (an HBM
        # copy — still far cheaper than a host round trip).  Padded rows get
        # weight 0 and are excluded from reseed draws, exactly like the host
        # padding path.
        Xp = X.astype(dtype)
        n_valid = n if n_valid is None else int(n_valid)
        if n_valid > n:
            raise ValueError(f"n_valid={n_valid} exceeds rows {n}")
        rem = (-Xp.shape[0]) % multiple
        if rem:
            Xp = jnp.pad(Xp, ((0, rem), (0, 0)))
        if update == "pallas" and n_valid < n:
            # The fused kernel's contract requires the padded tail to be
            # zero vectors (its wrapper corrects counts instead of masking
            # per tile).  Our own jnp.pad above guarantees rows [n, n_pad);
            # only rows [n_valid, n) — the CALLER's pre-padding — may hold
            # anything, so zero exactly when those exist (one O(n) pass per
            # call, not per iteration, and none on the common un-pre-padded
            # path).
            Xp = jnp.where(
                jnp.arange(Xp.shape[0])[:, None] < n_valid, Xp,
                jnp.zeros((), Xp.dtype))
    else:
        if n_valid is not None and n_valid != n:
            raise ValueError("n_valid is only for pre-padded device arrays")
        Xp, n_valid = pad_rows(X.astype(dtype, copy=False), multiple)
    # Padded rows get weight 0 inside the kernel (mask derived from n_valid)
    # and reseed draws are bounded by n_valid, so padding never leaks into
    # sums, counts, or sampling.

    with_init = init_centroids is not None
    # Keep device-resident init centroids on device (np.asarray here would be
    # a device->host fetch followed by a host->device upload, per call).
    # Centroids live in the stat dtype (f32 for bf16 points, _stat_dtype).
    cdtype = _stat_dtype(dtype)
    c0 = (
        jnp.asarray(init_centroids, dtype=cdtype)
        if with_init
        else _zero_centroids(int(k), int(d), jnp.dtype(cdtype).name)
    )
    key = _device_key(0 if seed is None else int(seed))

    if update == "pallas" and nmodel > 1:
        raise ValueError("pallas update not supported on a model-sharded mesh")
    if update == "pallas" and pallas_tile(k) is None:
        raise ValueError(
            f"k={k} exceeds the pallas kernel's VMEM budget "
            f"(no (k_pad, tile) block fits); use update='matmul'")
    if init_method not in ("auto", "d2", "kmeans||"):
        raise ValueError(f"unknown init_method {init_method!r}")
    auto_init = init_method == "auto"
    init_method = resolve_init_method(init_method, k)
    init_per_round = 0
    if init_method == "kmeans||" and not with_init:
        init_per_round = max(1, int(np.ceil(init_oversample * k / init_rounds)))
        n_loc = Xp.shape[0] // ndata
        if init_per_round > n_loc:
            if auto_init:
                # Tiny shards (k comparable to shard rows): the oversample
                # doesn't fit, and at that scale D² is cheap anyway.
                init_method, init_per_round = "d2", 0
            else:
                raise ValueError(
                    f"kmeans|| needs per-round sample {init_per_round} <= "
                    f"shard rows {n_loc}; use init_method='d2' at this scale")
    build_args = (
        n_valid, d, int(k), ndata, nmodel, int(max_iter), float(tol),
        with_init, np.dtype(dtype).name, chunk_rows, update,
        init_method, int(init_rounds), init_per_round, with_trace,
    )
    _misses_before = _build_kmeans.cache_info().misses
    fn = _build_kmeans(*build_args)
    _sig = None
    if _tel is not None:
        # Recompile detector: the aval signature (input shape/dtype plus
        # _build_kmeans's static cache key) names the program; the actual
        # recompile verdict is the lru_cache miss delta — exact even when
        # the kernel was warm before telemetry activated.
        from ..obs.jaxtools import aval_signature

        _sig = aval_signature(Xp, static=build_args)
        _tel.record_kernel_call(
            "kmeans_jax_full", _sig,
            compiled=_build_kmeans.cache_info().misses > _misses_before)
    if k > n_valid:
        raise ValueError(f"k={k} exceeds number of valid samples {n_valid}")
    call_args = (Xp, c0, key, _device_scalar_i32(int(iter_offset)))
    if _tel is not None and _tel.xprof:
        # XLA cost capture (obs/xprof.py): lower+compile explicitly once
        # per signature, emit flops/bytes/memory + compile wall-clock as
        # xla.* events, reuse the AOT executable afterwards.  Mesh runs
        # additionally stamp the facts XLA's cost model doesn't expose:
        # device count and the per-Lloyd-iteration psum traffic estimate
        # (the (k, d+1) sufficient-statistics all-reduce).
        from ..obs.xprof import instrumented_call

        _extra = None
        if ndata * nmodel > 1:
            from ..parallel.mesh import collective_bytes_estimate

            payload = int(k) * (d + 1) * jnp.dtype(_stat_dtype(dtype)).itemsize
            _extra = {"devices": ndata * nmodel,
                      "collective_bytes_per_iter":
                          collective_bytes_estimate(payload, ndata)}
        out = instrumented_call("kmeans_jax_full", fn, call_args,
                                signature=_sig, extra=_extra)
    else:
        out = fn(*call_args)
    centroids, labels, it, shift = out[:4]
    if with_trace:
        # Trace emission synchronizes (the buffers must come to host);
        # telemetry-off runs keep the fetch-free block_scalars=False path.
        it, shift = jax.device_get((it, shift))
        n_iter = int(it)
        _tel.emit_kmeans_trace(
            "kmeans_jax_full",
            inertia=np.asarray(out[4])[:n_iter],
            shift=np.asarray(out[5])[:n_iter],
            backend="jax", k=int(k), n=int(n_valid), update=update)
        return centroids, labels[:n_valid], n_iter, float(shift)
    if not block_scalars:
        return centroids, labels[:n_valid], it, shift
    # One host fetch for both scalars — int(it); float(shift) would be two
    # device->host round trips (each ~25-100 ms on remote-tunnel backends).
    it, shift = jax.device_get((it, shift))
    return centroids, labels[:n_valid], int(it), float(shift)


def kmeans_jax(X, k: int, **kwargs):
    """Reference-shaped API: returns (centroids, labels).

    Accepts every ``kmeans_jax_full`` knob (tol, seed, max_iter,
    init_centroids, mesh_shape, dtype, chunk_rows, update, n_valid,
    block_scalars, ...).  Since (n_iter, shift) are discarded, the scalar
    fetch is skipped by default — this call never synchronizes; the
    caller's own use of centroids/labels is the sync point.
    """
    kwargs.setdefault("block_scalars", False)
    centroids, labels, _, _ = kmeans_jax_full(X, k, **kwargs)
    return centroids, labels
