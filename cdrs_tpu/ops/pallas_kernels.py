"""Pallas TPU kernels — fused Lloyd assignment + cluster-stats reduction.

The XLA path (ops/kmeans_jax._assign_reduce) round-trips two (n, k) blocks
through HBM per iteration: the distance matrix (argmin input) and the one-hot
assignment (update-matmul input).  At n=1M, k=128, f32 that is ~2 GB of HBM
traffic per Lloyd iteration versus ~130 MB of actual input.  This kernel fuses
the whole step per row tile inside VMEM:

    for each tile of TILE_N rows (sequential TPU grid):
        dist   = c_sq - 2 x_tile @ C^T          (MXU, VMEM-resident)
        labels = argmin(dist)                    (VPU)
        onehot = labels == iota                  (VPU, VMEM-resident)
        sums  += onehot^T @ x_tile               (MXU accumulation)
        counts+= colsum(onehot)

so HBM sees x once plus the tiny (k, d) outputs — the memory-bound limit.

Feature count d and cluster count k are padded to the 128-lane boundary in the
wrapper (zero feature columns leave distances unchanged; padded centroid rows
are pushed to +inf distance so argmin never selects them).

Reference hot loop being replaced: the (n, k, d) broadcast at
src/kmeans_plusplus.py:33 (SURVEY.md §3.2 hot loop #4).

Where the config-2 time goes (round-5 issue-rate analysis)
----------------------------------------------------------
VERDICT r4 #1 asked for >= 2x at n=1M, d=32, k=128 or a written analysis.
Measured on v5e, same-process fori-chained 500-iteration windows (the only
methodology the remote tunnel admits), ~0.65-0.70 ms/iter baseline in the
measurement process:

* **The kernel is compute-bound, not bandwidth-bound.**  A fixed-tile
  variant (every grid step reads the same VMEM-resident tile — zero HBM
  streaming) times IDENTICALLY to the streaming kernel (0.678 vs 0.688
  ms/iter).  The DMA pipeline fully hides the x stream behind compute.
  The "~0.21 ms read floor" the round-4 notes compared against is a
  linear-scan number; the achievable stream rate for this (d=32, T)
  tile shape is 0.31-0.36 ms — and it is hidden anyway.
* **Half the compute is the distance matmul.**  Matmul-only: 0.34 ms/iter
  (~25 TFLOP/s effective — the d=32 contraction fills a quarter of the
  128-wide MXU reduction dimension).  Casting both operands to bf16 in
  VMEM does NOT help (0.34 -> 0.34): the cost is contraction-depth-bound,
  not precision-bound.  Padding d to 128 would 4x the FLOPs for 4x the
  utilization — a wash — and 4x the HBM stream.
* **The rest splits between the stats matmul and the argmin chain.**
  dist+min only: 0.57; + one-hot + stats matmul: 0.57 (the second matmul
  overlaps the VPU chain almost entirely); + first-match tie resolution +
  counts colsum: 0.65-0.69.
* **Variants tried and measured (same process, best-of-N):** packed
  argmin via bitcast+index-in-mantissa (-3%); multi-hot ``dist == dmin``
  with fractional tie weights folded into a (d+1)-row stats matmul (-2%);
  tie handling deleted outright (UNSOUND upper bound: -9%); both-operand
  bf16 matmuls (0%); pre-blocked fully-contiguous (n/T, d, T) layout (0%
  — DMA was never the issue); transposed (T, k_pad) block with lane-major
  argmin (8x WORSE); tiles {1024: +15%, 2048: baseline, 4096: -4%,
  8192: -4%} — 4096 adopted.
* **Conclusion:** at ~0.67 ms/iter the fused kernel sits within 2x of its
  own distance-matmul lower bound (0.34 ms).  Every further win requires
  either not materializing the (k_pad, T) distance block (exact Lloyd
  does not admit that) or raising MXU utilization at d=32 (fixed by the
  problem shape).  The remaining ~0.33 ms is the argmin/one-hot/counts
  chain whose individual removal attempts each bought < 10%.
"""

from __future__ import annotations

import functools
import os
import warnings

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["lloyd_assign_reduce_pallas", "lloyd_assign_reduce_pallas_t",
           "label_segment_matmul", "seg_tile", "pallas_available"]

_LANE = 128

#: ``CDRS_TPU_ENFORCE_PAD=1`` read ONCE at import: the guard is baked into
#: kernels at trace time, so flipping the variable after modules loaded (and
#: kernels possibly compiled) cannot take effect — a mid-session flip used
#: to do nothing silently; now ``_enforce_pad_env`` warns once instead.
#: Compiled kernels replay without re-running the wrapper's Python, so the
#: Lloyd entry point (kmeans_jax_full) also calls it eagerly per invocation
#: — the flip is noticed even when every shape is already traced.
_ENFORCE_PAD = os.environ.get("CDRS_TPU_ENFORCE_PAD") == "1"
_enforce_pad_warned = False


def _enforce_pad_env() -> bool:
    """The import-time CDRS_TPU_ENFORCE_PAD value, warning (once) when the
    environment has since been flipped to a different value."""
    global _enforce_pad_warned
    now = os.environ.get("CDRS_TPU_ENFORCE_PAD") == "1"
    if now != _ENFORCE_PAD and not _enforce_pad_warned:
        _enforce_pad_warned = True
        warnings.warn(
            "CDRS_TPU_ENFORCE_PAD changed after cdrs_tpu.ops.pallas_kernels "
            "was imported; the guard is applied at trace time, so the new "
            f"value is IGNORED (still using {_ENFORCE_PAD}).  Set the "
            "variable before importing (or pass enforce_pad=True per call).",
            RuntimeWarning, stacklevel=3)
    return _ENFORCE_PAD

#: The fused kernels' two (k_pad, tile) f32 VMEM blocks (distance + one-hot)
#: must fit comfortably under the 16 MB scoped-VMEM limit:
#: k_pad * tile <= 2^20 elements = 2 x 4 MB blocks.
_VMEM_ELEMS = 1 << 20

#: Column tile the Lloyd kernel iterates internally.  4096 won the round-5
#: interleaved same-process v5e sweep at k=128 (median 0.672 ms/iter vs
#: 0.699 at 2048 / 0.676 at 8192, n=1M d=32, production Lloyd loop; the
#: round-4 "2048 best" ranking came from cross-process windows, which the
#: tunnel makes incomparable).  At k_pad >= 512 only smaller tiles fit the
#: VMEM budget and the ladder below takes over (k=1024 measured best at
#: 1024: 31.7 ms/iter vs 35.0 at 512, n=4M d=128).
LLOYD_TILE_COLS = 4096


def lloyd_tile(k: int) -> int | None:
    """Column tile for the fused Lloyd kernel at this k, or None when no
    tile fits the VMEM budget (callers fall back to the XLA matmul path)."""
    k_pad = _pad_to(max(int(k), 8), _LANE)
    for t in (LLOYD_TILE_COLS, 2048, 1024, 512):
        if k_pad * t <= _VMEM_ELEMS:
            return t
    return None


def pallas_available() -> bool:
    """True when running on a real TPU backend (otherwise use interpret)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _pad_to(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _kernel(nv_ref, x_ref, c_ref, csq_ref, sums_ref, counts_ref, labels_ref, *,
            k_pad, tile_rows):
    i = pl.program_id(0)
    n_valid = nv_ref[0, 0]  # runtime scalar: shard-local valid row count
    x = x_ref[:]                      # (T, d_pad)
    c = c_ref[:]                      # (k_pad, d_pad)

    dist = csq_ref[:] - 2.0 * jax.lax.dot_general(
        x, c,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (T, k_pad); csq row-broadcasts

    # argmin via min + first-match (Mosaic lacks a direct argmin lowering);
    # all iota/compares stay 2D (1D->2D i1 reshapes are rejected).
    cols2 = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, k_pad), 1)
    dmin = jnp.min(dist, axis=1, keepdims=True)           # (T, 1)
    lab2 = jnp.min(jnp.where(dist == dmin, cols2, k_pad), axis=1,
                   keepdims=True)                          # (T, 1) first min
    labels_ref[:] = lab2[:, 0].astype(jnp.int32)

    row0 = i * tile_rows
    rows2 = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, k_pad), 0)
    oh = ((lab2 == cols2) & ((row0 + rows2) < n_valid)).astype(x.dtype)

    s = jax.lax.dot_general(
        oh, x,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (k_pad, d_pad)
    # f32 accumulation regardless of x dtype (a bf16 ones-sum saturates
    # past 256) — same contract as the feature-major kernel.
    cnt = jnp.sum(oh.astype(jnp.float32), axis=0)      # (k_pad,)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = s
        counts_ref[:] = cnt[None, :]

    @pl.when(i > 0)
    def _acc():
        sums_ref[:] += s
        counts_ref[:] += cnt[None, :]


def _warn_f32_count_ceiling(n_shard: int, caller: str) -> None:
    if n_shard > (1 << 24):
        # f32 grid accumulation of per-cluster counts loses integer
        # exactness once one cluster owns > 2^24 rows on this shard —
        # possible (though pathological) at this shard size.  The bisect
        # path int32-accumulates for exactly this reason.
        warnings.warn(
            f"{caller}: shard has {n_shard} rows; a cluster owning > 2^24 "
            "(~16.7M) of them overflows the f32 count accumulator's "
            "exact-integer range. Shard the data axis further if cluster "
            "sizes can be that skewed.",
            stacklevel=4)


@functools.lru_cache(maxsize=64)
def _build(n_rows, d, k, tile_rows, dtype_name, interpret):
    _warn_f32_count_ceiling(n_rows, "lloyd_assign_reduce_pallas")
    # Feature dim is used as-is (Mosaic lane-pads minor dims internally; an
    # explicit zero-pad to 128 would 4x the matmul FLOPs at d=32 and
    # materialize a padded copy of x in HBM).  k is padded so the argmin /
    # one-hot lanes are full; padded centroids sit at +inf distance.
    d_pad = d
    k_pad = _pad_to(max(k, 8), _LANE)
    grid = n_rows // tile_rows

    kern = functools.partial(_kernel, k_pad=k_pad, tile_rows=tile_rows)

    call = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tile_rows, d_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_rows,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_rows,), jnp.int32),
        ],
        interpret=bool(interpret),
    )

    dtype = jnp.dtype(dtype_name)

    def fn(x, c, n_valid):
        # Pad centroids to k_pad rows pushed to +inf distance (via c_sq) so
        # the argmin never selects them.  ||c||^2 in f32 from the centroids
        # actually used by the matmul (same contract as _build_t).
        big = jnp.asarray(1e30, jnp.float32)
        c_p = jnp.zeros((k_pad, d_pad), dtype).at[:k].set(c.astype(dtype))
        c32 = c_p.astype(jnp.float32)
        c_sq = jnp.sum(c32 * c32, axis=1)
        c_sq = jnp.where(jax.lax.iota(jnp.int32, k_pad) < k, c_sq, big)
        nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
        sums, counts, labels = call(nv, x, c_p, c_sq[None, :])
        return labels, sums[:k], counts[0, :k]

    return fn


def _kernel_t(xt_ref, c_ref, csq_ref, sums_ref, counts_ref,
              labels_ref, *, k_pad, tile_cols):
    """Feature-major body: one (k_pad, TN) distance block per grid step.

    The row-major kernel reads x as (T, d) tiles; for d < 128 XLA stores the
    (n, d) array lane-padded to 128 (layout T(8,128)), so every iteration
    moves 128/d times the logical bytes.  Feature-major (d, n) is fully
    dense — the lane dimension is n — and both matmuls are plain
    (M, K) @ (K, N) forms on the MXU.
    """
    i = pl.program_id(0)
    xt = xt_ref[:]                     # (d, TN)
    c = c_ref[:]                       # (k_pad, d)

    dist = csq_ref[:] - 2.0 * jax.lax.dot_general(
        c, xt,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (k_pad, TN); csq (k_pad, 1) broadcasts

    rows2 = jax.lax.broadcasted_iota(jnp.int32, (k_pad, tile_cols), 0)
    dmin = jnp.min(dist, axis=0, keepdims=True)            # (1, TN)
    lab2 = jnp.min(jnp.where(dist == dmin, rows2, k_pad), axis=0,
                   keepdims=True)                           # (1, TN) first min
    if labels_ref is not None:
        labels_ref[:] = lab2.astype(jnp.int32)

    # No validity mask: padded columns are REQUIRED to be zero vectors (the
    # wrapper contract), so they add nothing to sums and all land on the one
    # centroid argmin(csq) picks — the wrapper subtracts their count there.
    # Dropping the iota/compare/multiply saves a full (k_pad, TN) VPU pass
    # per tile (~5% of the kernel at k=1024 on v5e).
    oh = (rows2 == lab2).astype(xt.dtype)                   # (k_pad, TN)

    s = jax.lax.dot_general(
        oh, xt,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (k_pad, d)
    # f32 accumulation regardless of x dtype (a bf16 sum of ones saturates
    # past 256).
    cnt = jnp.sum(oh.astype(jnp.float32), axis=1)      # (k_pad,)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = s
        counts_ref[:] = cnt[:, None]

    @pl.when(i > 0)
    def _acc():
        sums_ref[:] += s
        counts_ref[:] += cnt[:, None]


def _kernel_t_no_labels(xt_ref, c_ref, csq_ref, sums_ref, counts_ref,
                        *, k_pad, tile_cols):
    _kernel_t(xt_ref, c_ref, csq_ref, sums_ref, counts_ref, None,
              k_pad=k_pad, tile_cols=tile_cols)


@functools.lru_cache(maxsize=64)
def _build_t(n_cols, d, k, tile_cols, dtype_name, interpret, with_labels):
    _warn_f32_count_ceiling(n_cols, "lloyd_assign_reduce_pallas_t")
    k_pad = _pad_to(max(k, 8), _LANE)
    grid = n_cols // tile_cols

    if with_labels:
        kern = functools.partial(_kernel_t, k_pad=k_pad, tile_cols=tile_cols)
    else:
        kern = functools.partial(_kernel_t_no_labels, k_pad=k_pad,
                                 tile_cols=tile_cols)

    out_specs = [
        pl.BlockSpec((k_pad, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((k_pad, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((k_pad, d), jnp.float32),
        jax.ShapeDtypeStruct((k_pad, 1), jnp.float32),
    ]
    if with_labels:
        out_specs.append(pl.BlockSpec((1, tile_cols), lambda i: (0, i),
                                      memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((1, n_cols), jnp.int32))

    call = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((d, tile_cols), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=bool(interpret),
    )

    dtype = jnp.dtype(dtype_name)

    def fn(xt, c, n_valid):
        big = jnp.asarray(1e30, jnp.float32)
        c_p = jnp.zeros((k_pad, d), dtype).at[:k].set(c.astype(dtype))
        # ||c||^2 in f32 from the (possibly bf16-rounded) centroids actually
        # used in the matmul — the distance ranking stays consistent.
        c32 = c_p.astype(jnp.float32)
        c_sq = jnp.sum(c32 * c32, axis=1)
        c_sq = jnp.where(jax.lax.iota(jnp.int32, k_pad) < k, c_sq, big)
        out = call(xt, c_p, c_sq[:, None])
        labels = out[2][0] if with_labels else None
        # Padded columns are zero vectors (wrapper contract): they add
        # nothing to sums but all count toward the centroid nearest the
        # origin — the kernel's first-min over csq, i.e. argmin(c_sq).
        # Subtract them here instead of masking inside the kernel (a full
        # (k_pad, TN) VPU pass per tile).
        counts = out[1][:, 0]
        j_pad = jnp.argmin(c_sq)
        # Difference in int32 BEFORE the f32 cast: n_valid itself exceeds
        # f32's 2^24 integer range on >16M-row shards; the pad count never.
        counts = counts.at[j_pad].add(
            (jnp.asarray(n_valid, jnp.int32) - n_cols).astype(jnp.float32))
        return labels, out[0][:k], counts[:k]

    return fn


def lloyd_assign_reduce_pallas_t(xt, c, n_valid, tile_cols: int | None = None,
                                 interpret: bool | None = None,
                                 with_labels: bool = True,
                                 enforce_pad: bool = False):
    """Feature-major fused assignment + (sums, counts).

    ``xt``: (d, n_cols) — the points matrix TRANSPOSED, n_cols % tile_cols
    == 0.  Columns past ``n_valid`` MUST be zero vectors (every caller
    zero-pads): instead of masking them per tile — a full (k_pad, TN) VPU
    pass — the wrapper subtracts their count from the origin-nearest
    centroid they deterministically land on.  A caller that cannot
    guarantee the zero-pad must pass ``enforce_pad=True`` (one extra
    ``where`` pass over xt that zeroes the tail) — non-zero pad columns
    otherwise SILENTLY corrupt sums/counts.  ``CDRS_TPU_ENFORCE_PAD=1``
    in the environment turns the guard on globally (debug aid; read ONCE
    at module import — flipping it afterwards is ignored with a one-time
    RuntimeWarning, since already-traced kernels replay without the
    guard).  Their
    labels are produced but meaningless (argmin of ||c||²).  ``c``:
    (k, d).  Returns (labels (n_cols,) int32 or None, sums (k, d) f32,
    counts (k,) f32) — same semantics as ``lloyd_assign_reduce_pallas``
    on zero-padded input, but reading x in its dense layout: for d < 128
    the row-major (n, d) array is lane-padded 128/d x in HBM, which made
    the row-major kernel bandwidth-bound on padding bytes.

    Precision ceiling: per-cluster counts accumulate in f32 across the
    grid, exact only while every cluster's shard-local count stays below
    2^24 (~16.7M rows).  The wrapper warns (once per shape) past that —
    at the demonstrated bf16 shard sizes (13.1M rows/chip) the ceiling is
    unreachable unless one cluster owns essentially the whole shard.
    """
    if interpret is None:
        interpret = not pallas_available()
    d, n_cols = xt.shape
    if enforce_pad or _enforce_pad_env():
        keep = jax.lax.iota(jnp.int32, n_cols) < jnp.asarray(n_valid,
                                                             jnp.int32)
        xt = jnp.where(keep[None, :], xt, jnp.zeros((), xt.dtype))
    k = c.shape[0]
    if tile_cols is None:
        tile_cols = lloyd_tile(k)
        if tile_cols is None:
            raise ValueError(
                f"k={k} exceeds the kernel's VMEM budget (no tile fits)")
    if n_cols % tile_cols:
        raise ValueError(f"cols {n_cols} not a multiple of tile_cols {tile_cols}")
    fn = _build_t(n_cols, d, k, int(tile_cols),
                  jnp.dtype(xt.dtype).name, bool(interpret), bool(with_labels))
    return fn(xt, c, n_valid)


def lloyd_assign_reduce_pallas(x, c, n_valid, tile_rows: int = 1024,
                               interpret: bool | None = None):
    """Fused assignment + (sums, counts) for one device's rows (row-major).

    ``x``: (n_rows, d) with n_rows % tile_rows == 0 (caller pads rows;
    tile_rows must be a multiple of 1024 to match XLA's 1D layout tiling);
    ``c``: (k, d).  ``n_valid`` may be a traced scalar (shard-local count) —
    rows >= n_valid get zero weight (their labels are still produced but
    meaningless).  Returns (labels (n_rows,) int32, sums (k, d) f32,
    counts (k,) f32).  Call from inside jit for fusion with neighbors.

    The Lloyd loop itself uses the feature-major variant
    (``lloyd_assign_reduce_pallas_t``): for d < 128 the row-major (n, d)
    layout is lane-padded to 128 in HBM, so this kernel pays 128/d x the
    logical read bytes.  Kept as the layout-matching API for callers whose
    x is already row-major and read once.
    """
    if interpret is None:
        interpret = not pallas_available()
    n_rows, d = x.shape
    k = c.shape[0]
    if n_rows % tile_rows:
        raise ValueError(f"rows {n_rows} not a multiple of tile_rows {tile_rows}")
    fn = _build(n_rows, d, k, int(tile_rows),
                jnp.dtype(x.dtype).name, bool(interpret))
    return fn(x, c, n_valid)


# ---------------------------------------------------------------------------
# Label-segmented matmul reduce: sums[k, d] = sum_i e_{lab_i} (x) y_i
# ---------------------------------------------------------------------------


def _kernel_seg(lab_ref, y_ref, sums_ref, *, k_pad, tile_rows):
    """One (TN, k_pad) one-hot block from GIVEN labels, then an MXU reduce.

    The same fused structure as the Lloyd kernel minus the distance/argmin:
    used where the segment ids are already known and an XLA ``segment_sum``
    would scatter (1 update per element, ~7 ns each on v5e — the bisection
    median driver replaces its 10M scatter-adds per feature-pass with one
    matmul per tile, the one-hot never leaving VMEM).
    """
    i = pl.program_id(0)
    lab = lab_ref[:]                   # (TN, 1) int32
    y = y_ref[:]                       # (TN, d)
    cols2 = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, k_pad), 1)
    oh = (cols2 == lab).astype(y.dtype)
    s = jax.lax.dot_general(
        oh, y,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (k_pad, d)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = s

    @pl.when(i > 0)
    def _acc():
        sums_ref[:] += s


@functools.lru_cache(maxsize=64)
def _build_seg(n_rows, d, k, tile_rows, dtype_name, interpret):
    k_pad = _pad_to(max(k, 8), _LANE)
    grid = n_rows // tile_rows
    kern = functools.partial(_kernel_seg, k_pad=k_pad, tile_rows=tile_rows)
    call = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tile_rows, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_rows, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((k_pad, d), jnp.float32)],
        interpret=bool(interpret),
    )

    def fn(lab, y):
        (sums,) = call(lab[:, None], y)
        return sums[:k]

    return fn


def label_segment_matmul(lab, y, k: int, tile_rows: int | None = None,
                         interpret: bool | None = None):
    """``sums[k, d] = sum_i onehot(lab_i) (x) y[i, :]`` on the MXU.

    ``lab``: (n,) int32 in [0, k) — out-of-range labels (e.g. -1 padding)
    contribute nothing.  ``y``: (n, d) row-major (dense for d >= 128; pass
    bf16 for MXU rate — accumulation is always f32).  n % tile_rows == 0
    (pad with lab = -1).  Returns (k, d) float32.
    """
    if interpret is None:
        interpret = not pallas_available()
    n, d = y.shape
    if tile_rows is None:
        tile_rows = seg_tile(k)
    if n % tile_rows:
        raise ValueError(f"rows {n} not a multiple of tile_rows {tile_rows}")
    fn = _build_seg(n, d, int(k), int(tile_rows),
                    jnp.dtype(y.dtype).name, bool(interpret))
    return fn(lab.astype(jnp.int32), y)


def seg_tile(k: int) -> int:
    """Default row tile for ``label_segment_matmul`` at this k.

    Single source for callers that must pre-pad rows to the tile grid
    (e.g. the bisection-median driver): the (TN, k_pad) one-hot block is
    the big VMEM resident, same budget rule as the Lloyd kernel.  Unlike
    ``lloyd_tile`` this never returns None — the tile shrinks (down to the
    8-row f32 sublane minimum) so huge k stays within the VMEM budget
    instead of overflowing it.
    """
    k_pad = _pad_to(max(int(k), 8), _LANE)
    return max(8, min(2048, _VMEM_ELEMS // k_pad))
