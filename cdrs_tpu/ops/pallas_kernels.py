"""Pallas TPU kernels — fused Lloyd assignment + cluster-stats reduction.

The XLA path (ops/kmeans_jax._assign_reduce) round-trips two (n, k) blocks
through HBM per iteration: the distance matrix (argmin input) and the one-hot
assignment (update-matmul input).  At n=1M, k=128, f32 that is ~2 GB of HBM
traffic per Lloyd iteration versus ~130 MB of actual input.  This kernel fuses
the whole step per row tile inside VMEM:

    for each tile of TILE_N rows (sequential TPU grid):
        dist   = c_sq - 2 x_tile @ C^T          (MXU, VMEM-resident)
        labels = argmin(dist)                    (VPU)
        onehot = labels == iota                  (VPU, VMEM-resident)
        sums  += onehot^T @ x_tile               (MXU accumulation)
        counts+= colsum(onehot)

so HBM sees x once plus the tiny (k, d) outputs — the memory-bound limit.

Feature count d and cluster count k are padded to the 128-lane boundary in the
wrapper (zero feature columns leave distances unchanged; padded centroid rows
are pushed to +inf distance so argmin never selects them).

Reference hot loop being replaced: the (n, k, d) broadcast at
src/kmeans_plusplus.py:33 (SURVEY.md §3.2 hot loop #4).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["lloyd_assign_reduce_pallas", "pallas_available"]

_LANE = 128


def pallas_available() -> bool:
    """True when running on a real TPU backend (otherwise use interpret)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _pad_to(v: int, m: int) -> int:
    return ((v + m - 1) // m) * m


def _kernel(nv_ref, x_ref, c_ref, csq_ref, sums_ref, counts_ref, labels_ref, *,
            k_pad, tile_rows):
    i = pl.program_id(0)
    n_valid = nv_ref[0, 0]  # runtime scalar: shard-local valid row count
    x = x_ref[:]                      # (T, d_pad)
    c = c_ref[:]                      # (k_pad, d_pad)

    dist = csq_ref[:] - 2.0 * jax.lax.dot_general(
        x, c,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (T, k_pad); csq row-broadcasts

    # argmin via min + first-match (Mosaic lacks a direct argmin lowering);
    # all iota/compares stay 2D (1D->2D i1 reshapes are rejected).
    cols2 = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, k_pad), 1)
    dmin = jnp.min(dist, axis=1, keepdims=True)           # (T, 1)
    lab2 = jnp.min(jnp.where(dist == dmin, cols2, k_pad), axis=1,
                   keepdims=True)                          # (T, 1) first min
    labels_ref[:] = lab2[:, 0].astype(jnp.int32)

    row0 = i * tile_rows
    rows2 = jax.lax.broadcasted_iota(jnp.int32, (tile_rows, k_pad), 0)
    oh = ((lab2 == cols2) & ((row0 + rows2) < n_valid)).astype(x.dtype)

    s = jax.lax.dot_general(
        oh, x,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                  # (k_pad, d_pad)
    cnt = jnp.sum(oh, axis=0)          # (k_pad,)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = s
        counts_ref[:] = cnt[None, :]

    @pl.when(i > 0)
    def _acc():
        sums_ref[:] += s
        counts_ref[:] += cnt[None, :]


@functools.lru_cache(maxsize=32)
def _build(n_rows, d, k, tile_rows, dtype_name, interpret):
    # Feature dim is used as-is (Mosaic lane-pads minor dims internally; an
    # explicit zero-pad to 128 would 4x the matmul FLOPs at d=32 and
    # materialize a padded copy of x in HBM).  k is padded so the argmin /
    # one-hot lanes are full; padded centroids sit at +inf distance.
    d_pad = d
    k_pad = _pad_to(max(k, 8), _LANE)
    grid = n_rows // tile_rows

    kern = functools.partial(_kernel, k_pad=k_pad, tile_rows=tile_rows)

    call = pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tile_rows, d_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_rows,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_rows,), jnp.int32),
        ],
        interpret=bool(interpret),
    )

    dtype = jnp.dtype(dtype_name)

    def fn(x, c, n_valid):
        # Pad centroids to k_pad rows pushed to +inf distance (via c_sq) so
        # the argmin never selects them.
        big = jnp.asarray(1e30, dtype)
        c_p = jnp.zeros((k_pad, d_pad), dtype).at[:k].set(c)
        c_sq = jnp.sum(c_p * c_p, axis=1)
        c_sq = jnp.where(jax.lax.iota(jnp.int32, k_pad) < k, c_sq, big)
        nv = jnp.asarray(n_valid, jnp.int32).reshape(1, 1)
        sums, counts, labels = call(nv, x, c_p, c_sq[None, :])
        return labels, sums[:k], counts[0, :k]

    return fn


def lloyd_assign_reduce_pallas(x, c, n_valid, tile_rows: int = 1024,
                               interpret: bool | None = None):
    """Fused assignment + (sums, counts) for one device's rows.

    ``x``: (n_rows, d) with n_rows % tile_rows == 0 (caller pads rows;
    tile_rows must be a multiple of 1024 to match XLA's 1D layout tiling);
    ``c``: (k, d).  ``n_valid`` may be a traced scalar (shard-local count) —
    rows >= n_valid get zero weight (their labels are still produced but
    meaningless).  Returns (labels (n_rows,) int32, sums (k, d) f32,
    counts (k,) f32).  Call from inside jit for fusion with neighbors.
    """
    if interpret is None:
        interpret = not pallas_available()
    n_rows, d = x.shape
    k = c.shape[0]
    if n_rows % tile_rows:
        raise ValueError(f"rows {n_rows} not a multiple of tile_rows {tile_rows}")
    fn = _build(n_rows, d, k, int(tile_rows),
                jnp.dtype(x.dtype).name, bool(interpret))
    return fn(x, c, n_valid)
