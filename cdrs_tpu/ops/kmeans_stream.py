"""Streaming mini-batch KMeans — the BASELINE config-5 path.

The reference has no streaming mode (its KMeans is one in-memory NumPy call,
src/kmeans_plusplus.py:24); BASELINE.json's north star adds a 1B-event
streaming scenario.  This implements web-scale mini-batch KMeans (Sculley,
WWW'10 — public algorithm) as a jit-compiled sharded update:

* state = (centroids (k, d), per-center counts (k,)) resident on device
* per batch: assign (matmul expansion argmin) -> per-center batch sums/counts
  (one-hot matmul, psum over the data mesh axis) -> per-center learning rate
  eta_j = batch_count_j / total_count_j -> convex update
  ``c_j <- (1 - eta_j) c_j + eta_j batch_mean_j``
* the first batch can seed centroids with the same on-device D² init used by
  the full-batch kernel (ops/kmeans_jax._d2_init_local)

The update is a pure function of (state, batch): restartable mid-stream by
checkpointing two small arrays (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import (DATA_AXIS, make_mesh, pad_rows, prefix_mask,
                             shard_map_compat)
from .kmeans_jax import _d2_init_local, _weighted_cluster_stats, assign_labels_jax

__all__ = ["MiniBatchState", "minibatch_init", "minibatch_update", "MiniBatchKMeans"]


@dataclass
class MiniBatchState:
    centroids: jax.Array   # (k, d)
    #: (k,) int32 — total points ever assigned per center.  Integer on
    #: purpose (ADVICE r2): float32 totals distort the eta = bcount/total
    #: learning-rate decay past 2**24 points per center, well within the
    #: 1B-row streaming target.  int32 is exact to 2.1e9 per center.
    counts: jax.Array
    n_batches: int = 0


@functools.lru_cache(maxsize=32)
def _build_init(n_rows, n_valid, d, k, ndata, dtype_name):
    mesh = make_mesh(n_data=ndata)

    def local_fn(x, key):
        return _d2_init_local(x, prefix_mask(x, n_valid), key, k=k,
                              n_valid=n_valid, ndata=ndata)

    return jax.jit(shard_map_compat(
        local_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P()),
        out_specs=P(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def _build_update(n_rows, n_valid, d, k, ndata, dtype_name, update):
    mesh = make_mesh(n_data=ndata)

    def local_fn(x, centroids, counts):
        w = prefix_mask(x, n_valid)
        labels = assign_labels_jax(x, centroids)
        sums, bcounts = _weighted_cluster_stats(x, w, labels, k, update)
        sums = lax.psum(sums, DATA_AXIS)
        bcounts = lax.psum(bcounts, DATA_AXIS)

        # Integer running totals (exact); the f32 per-batch counts are exact
        # too (one-hot sums, batch <= 2**24 rows/center).
        new_counts = counts + bcounts.astype(counts.dtype)
        total_f = jnp.maximum(new_counts, 1).astype(x.dtype)
        eta = jnp.where(bcounts > 0, bcounts / total_f, 0.0)
        bmean = sums / jnp.maximum(bcounts, 1.0)[:, None]
        new_c = centroids + eta[:, None] * (bmean - centroids)
        return new_c, new_counts, labels

    return jax.jit(shard_map_compat(
        local_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(), P()),
        out_specs=(P(), P(), P(DATA_AXIS)),
        check_vma=False,
    ))


def _prep_batch(xb, ndata, dtype):
    """Pad one batch for even sharding; returns (rows, n_valid).

    Device batches are padded on device (weight-0 rows via prefix_mask, same
    contract as the host path)."""
    if isinstance(xb, jax.Array):
        n_valid = xb.shape[0]
        rem = (-n_valid) % ndata
        xb = xb.astype(dtype)
        if rem:
            xb = jnp.pad(xb, ((0, rem), (0, 0)))
        return xb, n_valid
    xb = np.asarray(xb)
    return pad_rows(xb.astype(dtype, copy=False), ndata)


def minibatch_init(
    first_batch,
    k: int,
    seed: int | None = None,
    mesh_shape: dict[str, int] | None = None,
    dtype=np.float32,
) -> MiniBatchState:
    """Seed centroids via the on-device D² init over the first batch."""
    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))
    xp, n_valid = _prep_batch(first_batch, ndata, np.dtype(dtype))
    if n_valid < k:
        raise ValueError(
            f"first mini-batch has {n_valid} rows < k={k}; the D2 init "
            f"would draw duplicate centroids")
    fn = _build_init(xp.shape[0], n_valid, xp.shape[1], int(k), ndata,
                     np.dtype(dtype).name)
    key = jax.random.PRNGKey(0 if seed is None else int(seed))
    centroids = fn(xp, key)
    return MiniBatchState(
        centroids=centroids,
        counts=jnp.zeros((k,), jnp.int32),
        n_batches=0,
    )


def minibatch_update(
    state: MiniBatchState,
    batch,
    mesh_shape: dict[str, int] | None = None,
    update: str = "matmul",
):
    """One mini-batch step.  Returns (new_state, labels_for_batch)."""
    ndata = int((mesh_shape or {}).get(DATA_AXIS, 1))
    dtype = np.dtype(state.centroids.dtype)
    xp, n_valid = _prep_batch(batch, ndata, dtype)
    k = state.centroids.shape[0]
    fn = _build_update(xp.shape[0], n_valid, xp.shape[1], int(k), ndata,
                       dtype.name, update)
    new_c, new_counts, labels = fn(xp, state.centroids, state.counts)
    return (
        MiniBatchState(new_c, new_counts, state.n_batches + 1),
        labels[:n_valid],
    )


class MiniBatchKMeans:
    """Convenience wrapper: feed batches, read centroids/labels.

    >>> mb = MiniBatchKMeans(k=128, seed=0, mesh_shape={"data": 8})
    >>> for xb in batches: mb.partial_fit(xb)
    >>> mb.centroids  # (k, d)
    """

    def __init__(self, k: int, seed: int | None = None,
                 mesh_shape: dict[str, int] | None = None, dtype=np.float32):
        self.k = int(k)
        self.seed = seed
        self.mesh_shape = mesh_shape
        self.dtype = dtype
        self.state: MiniBatchState | None = None

    def partial_fit(self, batch):
        if self.state is None:
            self.state = minibatch_init(
                batch, self.k, seed=self.seed,
                mesh_shape=self.mesh_shape, dtype=self.dtype,
            )
        self.state, labels = minibatch_update(
            self.state, batch, mesh_shape=self.mesh_shape
        )
        return labels

    @property
    def centroids(self) -> np.ndarray:
        if self.state is None:
            raise ValueError("no batches seen yet")
        return np.asarray(self.state.centroids)

    def predict(self, X) -> np.ndarray:
        if self.state is None:
            raise ValueError("no batches seen yet")
        return np.asarray(assign_labels_jax(jnp.asarray(X, dtype=self.dtype),
                                            self.state.centroids))
