"""NumPy KMeans++ reference backend.

Reproduces the behaviour of the reference's hand-rolled KMeans
(reference: src/kmeans_plusplus.py:3-50) with the documented fixes
(SURVEY.md §6.1):

* ``max_iter = max(100, n/100)`` was a float and crashed ``range`` for
  n > 10,000 (kmeans_plusplus.py:29) — fixed to ``max(100, n // 100)``.
* Empty-cluster reseeding used the global ``np.random`` state, ignoring the
  seeded generator (kmeans_plusplus.py:43) — fixed to draw from the same
  seeded ``Generator`` so runs are reproducible.

Semantics kept bit-for-bit where sane:

* D² init: first centroid uniform, each next sampled with probability
  proportional to the min squared distance to already-chosen centroids
  (kmeans_plusplus.py:9-20).
* Lloyd: assignment by argmin Euclidean distance; update by per-cluster mean;
  convergence when the Frobenius norm of the centroid shift < tol
  (kmeans_plusplus.py:31-48).
* The returned ``labels`` are the assignment computed against the centroids
  *before* the final update — exactly the reference's loop order
  (kmeans_plusplus.py:33-48 computes labels, then updates, then breaks).

The O(n·k·d) dense distance broadcast of the reference is replaced by the
``‖x‖² − 2·x·Cᵀ + ‖c‖²`` matmul expansion computed in tiles, so this backend
also stays usable at the 1M–10M scale without materializing (n, k, d).
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "pairwise_sq_dists",
    "assign_labels",
    "kmeans_plusplus_init",
    "lloyd_step",
    "kmeans",
]

# Rows of points per distance tile: bounds temp memory at tile * k floats.
_TILE = 65536


def pairwise_sq_dists(X: np.ndarray, C: np.ndarray, tile: int = _TILE) -> np.ndarray:
    """Squared Euclidean distances (n, k) via the matmul expansion, tiled over rows.

    Equivalent to ``np.linalg.norm(X[:, None, :] - C[None, :, :], axis=2) ** 2``
    (reference: src/kmeans_plusplus.py:14-17, 33) without the (n, k, d) temp.
    Clamped at 0 to absorb the expansion's negative rounding residue.
    """
    n = X.shape[0]
    c_sq = np.einsum("kd,kd->k", C, C)
    out = np.empty((n, C.shape[0]), dtype=np.result_type(X.dtype, np.float64))
    for start in range(0, n, tile):
        xs = X[start:start + tile]
        x_sq = np.einsum("nd,nd->n", xs, xs)
        d = x_sq[:, None] - 2.0 * (xs @ C.T) + c_sq[None, :]
        np.maximum(d, 0.0, out=d)
        out[start:start + tile] = d
    return out


def kmeans_plusplus_init(
    X: np.ndarray,
    k: int,
    random_state: int | np.random.Generator | None = None,
) -> np.ndarray:
    """D² (KMeans++) initialization (reference: src/kmeans_plusplus.py:3-22).

    Uses the incremental min-distance formulation: after adding centroid i we
    only compute distances to that one centroid and take an elementwise min,
    O(n·d) per round instead of the reference's O(n·i·d) full recompute.
    The sampled sequence is distribution-identical (the min over all chosen
    centroids is the same quantity).
    """
    rng = np.random.default_rng(random_state)
    n, d = X.shape
    if k > n:
        raise ValueError(f"k={k} exceeds number of samples n={n}")
    centroids = np.empty((k, d), dtype=X.dtype)

    first = int(rng.integers(0, n))
    centroids[0] = X[first]

    min_sq = pairwise_sq_dists(X, centroids[0:1])[:, 0]
    for i in range(1, k):
        total = min_sq.sum()
        if total <= 0:
            # Degenerate data (all points identical to chosen centroids):
            # fall back to a uniform draw.
            idx = int(rng.integers(0, n))
        else:
            idx = int(rng.choice(n, p=min_sq / total))
        centroids[i] = X[idx]
        np.minimum(min_sq, pairwise_sq_dists(X, centroids[i:i + 1])[:, 0], out=min_sq)
    return centroids


def assign_labels(X: np.ndarray, centroids: np.ndarray,
                  tile: int = _TILE) -> np.ndarray:
    """Nearest-centroid assignment, computed tile-by-tile so the (n, k)
    distance matrix is never materialized (peak temp = tile × k)."""
    n = X.shape[0]
    labels = np.empty(n, dtype=np.int64)
    c_sq = np.einsum("kd,kd->k", centroids, centroids)
    for start in range(0, n, tile):
        xs = X[start:start + tile]
        # ‖x‖² is constant per row — argmin doesn't need it.
        d = c_sq[None, :] - 2.0 * (xs @ centroids.T)
        labels[start:start + tile] = np.argmin(d, axis=1)
    return labels


def lloyd_step(
    X: np.ndarray,
    centroids: np.ndarray,
    rng: np.random.Generator,
) -> tuple[np.ndarray, np.ndarray, float]:
    """One Lloyd iteration: assign, update, measure shift.

    Returns ``(new_centroids, labels, shift)`` where ``labels`` is the
    assignment against the *input* centroids and ``shift`` the Frobenius norm
    of the centroid movement (reference: src/kmeans_plusplus.py:33-45).
    Empty clusters are reseeded to a random data point drawn from ``rng``
    (reference behaviour at kmeans_plusplus.py:42-43, but seeded).
    """
    n = X.shape[0]
    k = centroids.shape[0]
    labels = assign_labels(X, centroids)

    # Per-cluster sums and counts in one pass (replaces the reference's k
    # masked means, kmeans_plusplus.py:38-43).
    sums = np.stack(
        [np.bincount(labels, weights=X[:, j], minlength=k) for j in range(X.shape[1])],
        axis=1,
    )
    counts = np.bincount(labels, minlength=k).astype(np.float64)

    new_centroids = np.empty_like(centroids)
    nonempty = counts > 0
    new_centroids[nonempty] = (sums[nonempty]
                               / counts[nonempty, None]).astype(centroids.dtype)
    for j in np.flatnonzero(~nonempty):
        new_centroids[j] = X[int(rng.integers(0, n))]

    shift = float(np.linalg.norm(new_centroids - centroids))
    return new_centroids, labels, shift


def kmeans(
    X: np.ndarray,
    k: int,
    number_of_files: int | None = None,
    tol: float = 1e-4,
    random_state: int | None = None,
    max_iter: int | None = None,
    init_centroids: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Full KMeans++ + Lloyd, reference signature preserved
    (reference: src/kmeans_plusplus.py:24).

    ``init_centroids`` overrides the D² init — used by the numpy-vs-jax parity
    tests so both backends iterate from identical starting points.

    Returns ``(centroids, labels)``; see module docstring for the exact label
    semantics.
    """
    X = np.asarray(X)
    if not np.issubdtype(X.dtype, np.floating):
        X = X.astype(np.float64)  # integer input would truncate centroid means
    n = X.shape[0]
    if number_of_files is None:
        number_of_files = n
    rng = np.random.default_rng(random_state)

    if init_centroids is not None:
        centroids = np.array(init_centroids, dtype=X.dtype)
    else:
        centroids = kmeans_plusplus_init(X, k, random_state=rng)

    if max_iter is None:
        from ..utils.params import default_max_iter

        max_iter = default_max_iter(number_of_files)

    # Telemetry (obs/): per-iteration convergence trace when an instrument
    # with kmeans tracing is active.  Inertia is measured against the
    # pre-update centroids (the assignment ``labels`` was computed on),
    # matching the jax backend's traced convention.  Expanded as
    # Σ‖x‖² − 2·Σ_j n_j⟨mean_j, c_j⟩ + Σ_j n_j‖c_j‖² so the per-iteration
    # cost is one bincount + k·d flops — never an (n, d) residual temp
    # (the naive form costs ~60% of the whole config-1 pipeline).
    from ..obs import current as _obs_current

    tel = _obs_current()
    tracing = tel is not None and tel.kmeans_trace
    tr_inertia: list[float] = []
    tr_shift: list[float] = []
    x_sq_total = float(np.einsum("nd,nd->", X, X)) if tracing else 0.0

    labels = np.zeros(n, dtype=np.int64)
    for _ in range(max_iter):
        prev = centroids
        centroids, labels, shift = lloyd_step(X, centroids, rng)
        if tracing:
            counts = np.bincount(labels, minlength=k).astype(np.float64)
            nz = counts > 0
            # For non-empty clusters lloyd_step's update IS sums/counts, so
            # the cluster sum s_j = mean_j · n_j; empty clusters contribute
            # no points (their reseeded row is irrelevant to inertia).
            cross = np.einsum("kd,kd->k", centroids[nz], prev[nz])
            prev_sq = np.einsum("kd,kd->k", prev[nz], prev[nz])
            tr_inertia.append(max(0.0, x_sq_total + float(
                np.dot(counts[nz], prev_sq - 2.0 * cross))))
            tr_shift.append(float(shift))
        if shift < tol:
            break
    if tracing:
        tel.emit_kmeans_trace("kmeans_np", inertia=tr_inertia,
                              shift=tr_shift, backend="numpy", k=int(k),
                              n=int(n))
    return centroids, labels
