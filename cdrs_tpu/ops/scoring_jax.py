"""JAX cluster scoring/classification — jit-compiled, mesh-friendly.

Same semantics as ops/scoring_np (the golden model; reference:
src/scoring.py:3-130), re-shaped for XLA:

* **Per-cluster medians** — the reference's per-cluster ``np.median`` calls
  (scoring.py:50-55) need ragged groups; under jit we instead lexsort each
  feature column by (label, value) so every cluster's values are a contiguous
  sorted run, then gather the two middle elements per run from computed
  offsets.  Static shapes, one sort per feature, no host round-trips.
* **Score table** — one (k, C, d) masked broadcast: direction gate
  ``dir == 0 | sign(delta) == dir`` for non-Moderate, ``|delta| < band`` with
  reward ``(1 - |delta|)²`` for Moderate (scoring.py:77-82).
* **Tie-break** — argmax of replication factor among score-tied categories
  (scoring.py:102-107): all-zero clusters classify Archival.

Empty clusters get NaN medians which score 0 everywhere (same contract as the
numpy backend).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..config import ScoringConfig

__all__ = [
    "compute_cluster_medians_jax",
    "score_table_jax",
    "classify_jax",
]


@functools.partial(jax.jit, static_argnames=("k",))
def compute_cluster_medians_jax(x: jnp.ndarray, labels: jnp.ndarray, k: int) -> jnp.ndarray:
    """(k, d) per-cluster per-feature medians; NaN rows for empty clusters."""
    n = x.shape[0]
    ones = jnp.ones((n,), x.dtype)
    counts = jax.ops.segment_sum(ones, labels, num_segments=k).astype(jnp.int32)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix sum

    def median_one_feature(col):
        order = jnp.lexsort((col, labels))
        vals = col[order]
        lo = starts + (counts - 1) // 2
        hi = starts + counts // 2
        med = (vals[jnp.clip(lo, 0, n - 1)] + vals[jnp.clip(hi, 0, n - 1)]) * 0.5
        return jnp.where(counts > 0, med, jnp.nan)

    return jax.vmap(median_one_feature, in_axes=1, out_axes=1)(x)


@jax.jit
def score_table_jax(
    cluster_medians: jnp.ndarray,   # (k, d)
    global_medians: jnp.ndarray,    # (d,)
    W: jnp.ndarray,                 # (C, d) weights
    D: jnp.ndarray,                 # (C, d) directions in {-1, 0, +1}
    is_moderate: jnp.ndarray,       # (C,) bool
    moderate_band: jnp.ndarray,     # scalar
) -> jnp.ndarray:
    """(k, C) score matrix (reference: src/scoring.py:57-84, vectorized)."""
    delta = cluster_medians - global_medians[None, :]
    valid = ~jnp.isnan(delta)
    delta = jnp.where(valid, delta, 0.0)
    abs_d = jnp.abs(delta)

    delta_b = delta[:, None, :]
    absd_b = abs_d[:, None, :]
    valid_b = valid[:, None, :]

    gate_dir = (D[None, :, :] == 0) | (jnp.sign(delta_b) == D[None, :, :])
    term_dir = W[None, :, :] * absd_b**2
    gate_mod = absd_b < moderate_band
    term_mod = W[None, :, :] * (1.0 - absd_b) ** 2

    mod = is_moderate[None, :, None]
    gate = jnp.where(mod, gate_mod, gate_dir) & valid_b
    term = jnp.where(mod, term_mod, term_dir)
    return jnp.where(gate, term, 0.0).sum(axis=2)


@jax.jit
def _pick_winner(scores: jnp.ndarray, rf: jnp.ndarray) -> jnp.ndarray:
    """Argmax score with replication-factor tie-break (scoring.py:102-107)."""
    tied = scores == scores.max(axis=1, keepdims=True)
    return jnp.argmax(jnp.where(tied, rf[None, :], -jnp.inf), axis=1)


def classify_jax(
    X,
    labels,
    k: int,
    cfg: ScoringConfig | None = None,
    global_medians=None,
):
    """Full classification: medians -> scores -> categories.

    Returns ``(category_idx (k,), scores (k, C), cluster_medians (k, d))`` as
    jax arrays.  Mirrors ops/scoring_np.classify (reference: scoring.py:111-130).
    """
    cfg = cfg or ScoringConfig()
    x = jnp.asarray(X)
    labels = jnp.asarray(labels).astype(jnp.int32)

    medians = compute_cluster_medians_jax(x, labels, int(k))
    if global_medians is None:
        if cfg.compute_global_medians_from_data:
            global_medians = jnp.median(x, axis=0)
        else:
            global_medians = jnp.asarray(
                [cfg.global_medians[f] for f in cfg.features], dtype=x.dtype
            )
    else:
        global_medians = jnp.asarray(global_medians, dtype=x.dtype)

    W = jnp.asarray(np.array(cfg.weight_matrix(), dtype=np.float64), dtype=x.dtype)
    D = jnp.asarray(np.array(cfg.direction_matrix(), dtype=np.float64), dtype=x.dtype)
    is_mod = jnp.asarray(np.array([c == "Moderate" for c in cfg.categories]))
    rf = jnp.asarray(np.array(cfg.rf_vector(), dtype=np.float64), dtype=x.dtype)

    scores = score_table_jax(
        medians, global_medians, W, D, is_mod, jnp.asarray(cfg.moderate_band, x.dtype)
    )
    winner = _pick_winner(scores, rf)
    return winner, scores, medians
