"""JAX cluster scoring/classification — jit-compiled, mesh-friendly.

Same semantics as ops/scoring_np (the golden model; reference:
src/scoring.py:3-130), re-shaped for XLA:

* **Per-cluster medians** — the reference's per-cluster ``np.median`` calls
  (scoring.py:50-55) need ragged groups; under jit we instead lexsort each
  feature column by (label, value) so every cluster's values are a contiguous
  sorted run, then gather the two middle elements per run from computed
  offsets.  Static shapes, one sort per feature, no host round-trips.
* **Histogram medians at scale** — a full per-feature n-sort is the wrong
  shape for 10M+ rows (SURVEY.md §7.4); ``compute_cluster_medians_hist_jax``
  instead bins each feature into a fixed ``(k, bins)`` histogram (one
  ``segment_sum`` per feature — O(n) and TPU-reduction-friendly) and reads
  both middle-rank values off the cumulative counts with intra-bin linear
  interpolation.  Error is bounded by the bin width of the feature's value
  range; category assignments are compared against the exact path in
  tests/test_scoring_jax.py.  ``classify_jax`` switches automatically past
  ``HIST_MEDIAN_THRESHOLD`` rows.
* **Score table** — one (k, C, d) masked broadcast: direction gate
  ``dir == 0 | sign(delta) == dir`` for non-Moderate, ``|delta| < band`` with
  reward ``(1 - |delta|)²`` for Moderate (scoring.py:77-82).
* **Tie-break** — argmax of replication factor among score-tied categories
  (scoring.py:102-107): all-zero clusters classify Archival.

Empty clusters get NaN medians which score 0 everywhere (same contract as the
numpy backend).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..config import ScoringConfig
from ..parallel.mesh import DATA_AXIS, shard_map_compat

__all__ = [
    "compute_cluster_medians_jax",
    "compute_cluster_medians_hist_jax",
    "score_table_jax",
    "classify_jax",
    "resolve_median_method",
    "HIST_MEDIAN_THRESHOLD",
]

#: Row count past which "auto" switches from exact sort-based medians to
#: histogram medians — single-sourced in the numpy backend so both backends
#: route identically on the same data (ADVICE r2).
from .scoring_np import HIST_MEDIAN_THRESHOLD  # noqa: E402  (re-export)


@functools.partial(jax.jit, static_argnames=("k",))
def compute_cluster_medians_jax(x: jnp.ndarray, labels: jnp.ndarray,
                                k: int) -> jnp.ndarray:
    """(k, d) per-cluster per-feature medians; NaN rows for empty clusters."""
    n = x.shape[0]
    ones = jnp.ones((n,), x.dtype)
    counts = jax.ops.segment_sum(ones, labels, num_segments=k).astype(jnp.int32)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix sum

    def median_one_feature(col):
        order = jnp.lexsort((col, labels))
        vals = col[order]
        lo = starts + (counts - 1) // 2
        hi = starts + counts // 2
        med = (vals[jnp.clip(lo, 0, n - 1)] + vals[jnp.clip(hi, 0, n - 1)]) * 0.5
        return jnp.where(counts > 0, med, jnp.nan)

    return jax.vmap(median_one_feature, in_axes=1, out_axes=1)(x)


def _medians_from_hist(H, counts, lo_f, w_f, bins, ftype):
    """(k,) medians from a (k, bins) histogram: both middle-rank values off
    the cumulative counts, linearly interpolated inside the bin."""
    cum = jnp.cumsum(H, axis=1)
    r0 = (counts - 1) // 2   # 0-indexed middle ranks (lower/upper)
    r1 = counts // 2

    def value_at(r):
        # First bin whose cumulative count exceeds rank r holds it.
        j = jnp.argmax(cum > r[:, None], axis=1)                 # (k,)
        cum_before = jnp.where(
            j > 0,
            jnp.take_along_axis(cum, jnp.maximum(j - 1, 0)[:, None], 1)[:, 0],
            0,
        )
        h = jnp.take_along_axis(H, j[:, None], 1)[:, 0]
        frac = (r - cum_before + 0.5) / jnp.maximum(h, 1)
        return (j.astype(ftype) + frac.astype(ftype)) * (w_f / bins)

    med = lo_f + 0.5 * (value_at(r0) + value_at(r1))
    return jnp.where(counts > 0, med, jnp.nan)


@functools.partial(jax.jit, static_argnames=("k", "bins", "with_global"))
def _hist_medians(x, labels, k: int, bins: int, with_global: bool):
    """Per-cluster (k, d) + optionally global (d,) medians in ONE data pass.

    One ``segment_sum`` over composite (label, bin) keys per feature — O(n·d)
    with (k, bins) working memory per feature (``lax.map`` keeps features
    sequential, so peak memory is independent of d).  Error <=
    feature_range / bins; constant columns are exact.  NaN rows for empty
    clusters (same contract as the exact kernel).  The global medians reuse
    the already-built histograms (summed over clusters) — no second pass.
    """
    n = x.shape[0]
    ftype = x.dtype
    ones = jnp.ones((n,), jnp.int32)
    counts = jax.ops.segment_sum(ones, labels, num_segments=k)   # (k,)
    n_total = jnp.full((1,), n, counts.dtype)

    lo = x.min(axis=0)
    hi = x.max(axis=0)

    def one_feature(args):
        col, lo_f, hi_f = args
        w_f = jnp.where(hi_f > lo_f, hi_f - lo_f, 1.0)
        b = jnp.clip(((col - lo_f) / w_f * bins).astype(jnp.int32), 0, bins - 1)
        H = jax.ops.segment_sum(
            ones, labels * bins + b, num_segments=k * bins
        ).reshape(k, bins)
        exact_const = hi_f <= lo_f  # constant column: the value itself
        med = jnp.where(
            exact_const, lo_f,
            _medians_from_hist(H, counts, lo_f, w_f, bins, ftype))
        if with_global:
            gmed = jnp.where(
                exact_const, lo_f,
                _medians_from_hist(H.sum(0, keepdims=True), n_total,
                                   lo_f, w_f, bins, ftype))[0]
        else:
            gmed = jnp.zeros((), ftype)
        return med, gmed

    meds, gmeds = lax.map(one_feature, (x.T, lo, hi))   # (d, k), (d,)
    return meds.T, gmeds


def compute_cluster_medians_hist_jax(
    x: jnp.ndarray, labels: jnp.ndarray, k: int, bins: int = 2048,
) -> jnp.ndarray:
    """(k, d) approximate per-cluster medians via fixed-bin histograms."""
    return _hist_medians(x, labels, k, bins, False)[0]


#: Rows per chunk of the bisection median scan — bounds the (chunk, 2d)
#: comparison buffer (bf16) so the pass never materializes an O(n·d) y.
_BISECT_CHUNK = 1 << 20


@functools.partial(jax.jit, static_argnames=("k", "bins", "with_global"))
def _bisect_medians(x, labels, k: int, bins: int, with_global: bool):
    """Per-cluster (k, d) + optionally global (d,) medians by parallel
    bisection — scatter-free.

    The histogram path costs one ``segment_sum`` scatter PER ELEMENT per
    feature (~7 ns each on v5e: 9.2 s at 10M x 128, k=1024).  Bisection
    reframes the median as ceil(log2(bins)) rank queries answered on the
    MXU: per iteration, per (cluster, feature) thresholds are gathered per
    row, compared (one fused pass over x), and counted with the one-hot
    label matmul (ops/pallas_kernels.label_segment_matmul — the Lloyd
    update structure with y = the 0/1 comparison matrix).  ~0.9 s for the
    same workload.  Error <= feature_range / 2^iters with iters =
    ceil(log2(bins)) + 1 — at the default bins=2048 that is half the
    histogram path's bin width (and the hist path adds in-bin
    interpolation error on top).

    Both middle ranks (r0 = (cnt-1)//2, r1 = cnt//2) bisect simultaneously
    (stacked along the feature axis); the result averages them — the same
    even-count contract as the sort and hist kernels.  NaN rows for empty
    clusters; constant columns are exact.
    """
    x, labels = _bisect_pad(x, labels, k)
    return _bisect_core(x, labels, k, bins, with_global, sharded=False)


def _bisect_pad(x, labels, k: int):
    """Pad rows to the scan grid with the -1 sentinel label (never matches
    a one-hot column; masked out of counts and min/max).

    Inputs at or below one chunk pad only to the kernel tile (a tiny input
    — e.g. one shard of a small sharded run — must not pay a full-chunk
    zero pass); larger inputs pad to a whole number of chunks.  Either way
    ``_bisect_core``'s ``chunk = min(chunk, n_pad)`` divides ``n_pad``.
    """
    from .pallas_kernels import seg_tile

    n = x.shape[0]
    chunk = _bisect_chunk(k)
    mult = seg_tile(k) if n <= chunk else chunk
    n_pad = int(np.ceil(max(n, 1) / mult)) * mult
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        labels = jnp.pad(labels, (0, n_pad - n), constant_values=-1)
    return x, labels


def _bisect_chunk(k: int) -> int:
    from .pallas_kernels import pallas_available, seg_tile

    chunk = (_BISECT_CHUNK if pallas_available()
             else min(_BISECT_CHUNK, 1 << 14))
    tile = seg_tile(k)
    return max(tile, (chunk // tile) * tile)


def _bisect_core(x, labels, k: int, bins: int, with_global: bool,
                 sharded: bool):
    """Bisection body over one device's (chunk-grid-padded) rows.

    ``sharded=True`` runs inside shard_map over DATA_AXIS: the per-shard
    (2, k, d)-shaped counts are psum-merged each iteration — the only
    cross-shard traffic; x never moves.  Labels < 0 mark padded/invalid
    rows on either path.

    Scale ceiling: counts, ``n_total`` and the rank targets are int32, so
    the GLOBAL median targets overflow silently past 2^31 total valid
    rows (~2x the demonstrated 1B-event scenario; per-cluster counts have
    far more headroom).  Past that, raise ``bins``' companion structures
    to int64 (requires jax x64) or shard the global-median query.
    """
    from .pallas_kernels import label_segment_matmul

    n_pad, d = x.shape
    ftype = x.dtype
    iters = max(8, int(np.ceil(np.log2(max(bins, 2)))) + 1)
    chunk = min(_bisect_chunk(k), n_pad)

    def psum_(v):
        return lax.psum(v, DATA_AXIS) if sharded else v

    valid = labels >= 0
    wi = valid.astype(jnp.int32)
    lab_c = jnp.where(valid, labels, 0)
    counts = psum_(jax.ops.segment_sum(wi, lab_c, num_segments=k))   # (k,)
    n_total = jnp.sum(counts)
    big = jnp.asarray(jnp.inf, ftype)
    lo_f = jnp.min(jnp.where(valid[:, None], x, big), axis=0)
    hi_f = jnp.max(jnp.where(valid[:, None], x, -big), axis=0)
    if sharded:
        lo_f = lax.pmin(lo_f, DATA_AXIS)
        hi_f = lax.pmax(hi_f, DATA_AXIS)

    nch = n_pad // chunk
    xr = x.reshape(nch, chunk, d)
    labr = labels.reshape(nch, chunk)

    # Ranks: value at 0-indexed rank r is the smallest v with
    # count(x <= v) >= r + 1.
    r0 = ((counts - 1) // 2 + 1).astype(jnp.int32)   # target count, rank lo
    r1 = (counts // 2 + 1).astype(jnp.int32)         # target count, rank hi
    targets = jnp.stack([r0, r1])                     # (2, k)
    g_targets = jnp.stack([(n_total - 1) // 2 + 1,
                           n_total // 2 + 1]).astype(jnp.int32)

    lo = jnp.broadcast_to(lo_f, (2, k, d)).astype(jnp.float32)
    hi = jnp.broadcast_to(hi_f, (2, k, d)).astype(jnp.float32)
    glo = jnp.broadcast_to(lo_f, (2, d)).astype(jnp.float32)
    ghi = jnp.broadcast_to(hi_f, (2, d)).astype(jnp.float32)

    def body(_, carry):
        lo, hi, glo, ghi = carry
        thr = 0.5 * (lo + hi)                         # (2, k, d)
        gthr = 0.5 * (glo + ghi)                      # (2, d)
        thr_cat = jnp.concatenate([thr[0], thr[1]], axis=1)   # (k, 2d)

        def chunk_body(acc, args):
            cb, gcb = acc
            xc, lc = args
            # Per-row thresholds for both ranks; the gather + compare + cast
            # fuse into the (chunk, 2d) bf16 y — no (chunk, 2d) f32 buffer.
            t_rows = thr_cat[jnp.clip(lc, 0, k - 1)]          # (chunk, 2d)
            xx = jnp.concatenate([xc, xc], axis=1)            # (chunk, 2d)
            y = (xx.astype(jnp.float32) <= t_rows).astype(jnp.bfloat16)
            # Per-chunk kernel sums are exact integers <= chunk (< 2^24);
            # accumulate across chunks in int32 — an f32 running total loses
            # count exactness past 16.7M rows per cluster.
            cb = cb + label_segment_matmul(lc, y, k).astype(jnp.int32)
            if with_global:
                gy = (xc.astype(jnp.float32)[None] <= gthr[:, None, :])
                gcb = gcb + jnp.sum(gy & (lc >= 0)[None, :, None], axis=1,
                                    dtype=jnp.int32)
            return (cb, gcb), None

        (cb_cat, gcb), _ = lax.scan(
            chunk_body,
            (jnp.zeros((k, 2 * d), jnp.int32),
             jnp.zeros((2, d), jnp.int32)),
            (xr, labr))
        cb_cat = psum_(cb_cat)
        cb = jnp.stack([cb_cat[:, :d], cb_cat[:, d:]])        # (2, k, d)

        ge = cb >= targets[:, :, None]
        lo = jnp.where(ge, lo, thr)
        hi = jnp.where(ge, thr, hi)
        if with_global:
            gge = psum_(gcb) >= g_targets[:, None]
            glo = jnp.where(gge, glo, gthr)
            ghi = jnp.where(gge, gthr, ghi)
        return lo, hi, glo, ghi

    lo, hi, glo, ghi = lax.fori_loop(0, iters, body, (lo, hi, glo, ghi))

    exact_const = hi_f <= lo_f
    med = (0.25 * (lo[0] + hi[0] + lo[1] + hi[1])).astype(ftype)  # rank avg
    med = jnp.where(exact_const[None, :], lo_f[None, :], med)
    med = jnp.where(counts[:, None] > 0, med, jnp.nan)
    if with_global:
        gmed = (0.25 * (glo[0] + ghi[0] + glo[1] + ghi[1])).astype(ftype)
        gmed = jnp.where(exact_const, lo_f, gmed)
    else:
        gmed = jnp.zeros((d,), ftype)
    return med, gmed


@functools.lru_cache(maxsize=16)
def _build_bisect_medians_sharded(k: int, bins: int, with_global: bool,
                                  ndata: int, nmodel: int = 1):
    """Compile the data-sharded bisection-median kernel.

    Same dispatch convention as the sharded histogram path (x and labels
    arrive sharded over the data axis, outputs replicated); padded rows
    carry the sentinel label ``k`` (mapped to -1 for the core, whose
    one-hot ignores it).  Cross-shard traffic per iteration is one psum of
    the (k, 2d) count block (+ (2, d) global counts) — x never moves.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import make_mesh

    mesh = make_mesh(n_data=ndata, n_model=nmodel)

    def local_fn(x_loc, lab_loc):
        lab = jnp.where(lab_loc < k, lab_loc, -1).astype(jnp.int32)
        x_p, lab_p = _bisect_pad(x_loc, lab, k)
        return _bisect_core(x_p, lab_p, k, bins, with_global, sharded=True)

    return jax.jit(shard_map_compat(
        local_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=32)
def _build_hist_medians_sharded(k: int, bins: int, with_global: bool,
                                ndata: int, nmodel: int = 1):
    """Compile the data-sharded histogram-median kernel (VERDICT r2 #5).

    Each shard bins its rows into per-(cluster, bin) counts; one ``psum``
    of the (k, bins) histogram per feature merges them — the feature matrix
    never moves off its shards (at 100M x 128 it cannot: ~51 GB f32 spans
    the whole v5e-8 mesh).  Padded rows carry the sentinel label ``k`` and
    are masked out of counts, histograms, and the min/max range.
    Reference semantics: per-cluster medians of src/scoring.py:40-55.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, make_mesh

    # Same mesh shape as the clustering stage (x arrives sharded over data,
    # replicated over any model axis) so dispatch needs no resharding; the
    # median reductions only ever communicate over the data axis.
    mesh = make_mesh(n_data=ndata, n_model=nmodel)

    def local_fn(x_loc, lab_loc):
        ftype = x_loc.dtype
        valid = lab_loc < k
        wi = valid.astype(jnp.int32)
        lab_c = jnp.where(valid, lab_loc, 0)
        counts = lax.psum(
            jax.ops.segment_sum(wi, lab_c, num_segments=k), DATA_AXIS)
        n_total = jnp.sum(counts)[None]
        big = jnp.asarray(jnp.inf, ftype)
        lo = lax.pmin(
            jnp.min(jnp.where(valid[:, None], x_loc, big), axis=0), DATA_AXIS)
        hi = lax.pmax(
            jnp.max(jnp.where(valid[:, None], x_loc, -big), axis=0), DATA_AXIS)

        def one_feature(args):
            col, lo_f, hi_f = args
            w_f = jnp.where(hi_f > lo_f, hi_f - lo_f, 1.0)
            b = jnp.clip(((col - lo_f) / w_f * bins).astype(jnp.int32),
                         0, bins - 1)
            H = lax.psum(
                jax.ops.segment_sum(wi, lab_c * bins + b,
                                    num_segments=k * bins),
                DATA_AXIS).reshape(k, bins)
            exact_const = hi_f <= lo_f
            med = jnp.where(
                exact_const, lo_f,
                _medians_from_hist(H, counts, lo_f, w_f, bins, ftype))
            if with_global:
                gmed = jnp.where(
                    exact_const, lo_f,
                    _medians_from_hist(H.sum(0, keepdims=True), n_total,
                                       lo_f, w_f, bins, ftype))[0]
            else:
                gmed = jnp.zeros((), ftype)
            return med, gmed

        meds, gmeds = lax.map(one_feature, (x_loc.T, lo, hi))
        return meds.T, gmeds

    return jax.jit(shard_map_compat(
        local_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    ))


def _bisect_medians_sharded(x, labels, k: int, bins: int, with_global: bool,
                            ndata: int, nmodel: int = 1):
    """Data-sharded bisection medians (same calling convention as
    ``_hist_medians_sharded``; sentinel label k pads to a shard multiple)."""
    x = jnp.asarray(x)
    labels = jnp.asarray(labels).astype(jnp.int32)
    rem = (-x.shape[0]) % ndata
    if rem:
        x = jnp.pad(x, ((0, rem), (0, 0)))
        labels = jnp.pad(labels, (0, rem), constant_values=k)
    fn = _build_bisect_medians_sharded(int(k), int(bins), bool(with_global),
                                       int(ndata), int(nmodel))
    return fn(x, labels)


def _hist_medians_sharded(x, labels, k: int, bins: int, with_global: bool,
                          ndata: int, nmodel: int = 1):
    """Data-sharded histogram medians over an ``ndata``-way mesh.

    ``x`` (n, d) and ``labels`` (n,) may be host arrays (they are padded to
    a shard multiple with the sentinel label and resharded by jit) or
    already-sharded device arrays.  Returns ((k, d) medians, (d,) global
    medians or zeros).
    """
    x = jnp.asarray(x)
    labels = jnp.asarray(labels).astype(jnp.int32)
    rem = (-x.shape[0]) % ndata
    if rem:
        x = jnp.pad(x, ((0, rem), (0, 0)))
        labels = jnp.pad(labels, (0, rem), constant_values=k)
    fn = _build_hist_medians_sharded(int(k), int(bins), bool(with_global),
                                     int(ndata), int(nmodel))
    return fn(x, labels)


def resolve_median_method(method: str, ndata: int, n_rows: int) -> str:
    """Resolve the ``median_method`` knob to a concrete algorithm.

    "auto": exact sort below ``HIST_MEDIAN_THRESHOLD`` rows on a single
    device; past it (or on any sharded mesh) bisection on a real TPU
    backend, histogram elsewhere (the r5 flip — sharded auto used to
    conservatively pick hist; the sharded bisect is parity-tested at
    atol=0 against single-device bisect).  "sort" raises on a sharded
    mesh: a distributed exact sort is the wrong shape for the scales
    that need sharding (SURVEY.md §7.4).
    """
    if ndata > 1 and method == "sort":
        raise ValueError(
            "median_method='sort' is single-device; sharded scoring "
            "(mesh_shape data > 1) uses histogram or bisection medians "
            "— pass median_method='hist', 'bisect', or 'auto'")
    if method == "auto":
        if ndata == 1 and n_rows <= HIST_MEDIAN_THRESHOLD:
            return "sort"
        from .pallas_kernels import pallas_available

        return "bisect" if pallas_available() else "hist"
    if method not in ("sort", "hist", "bisect"):
        raise ValueError(f"unknown median_method {method!r}")
    return method


@jax.jit
def score_table_jax(
    cluster_medians: jnp.ndarray,   # (k, d)
    global_medians: jnp.ndarray,    # (d,)
    W: jnp.ndarray,                 # (C, d) weights
    D: jnp.ndarray,                 # (C, d) directions in {-1, 0, +1}
    is_moderate: jnp.ndarray,       # (C,) bool
    moderate_band: jnp.ndarray,     # scalar
) -> jnp.ndarray:
    """(k, C) score matrix (reference: src/scoring.py:57-84, vectorized)."""
    delta = cluster_medians - global_medians[None, :]
    valid = ~jnp.isnan(delta)
    delta = jnp.where(valid, delta, 0.0)
    abs_d = jnp.abs(delta)

    delta_b = delta[:, None, :]
    absd_b = abs_d[:, None, :]
    valid_b = valid[:, None, :]

    gate_dir = (D[None, :, :] == 0) | (jnp.sign(delta_b) == D[None, :, :])
    term_dir = W[None, :, :] * absd_b**2
    gate_mod = absd_b < moderate_band
    term_mod = W[None, :, :] * (1.0 - absd_b) ** 2

    mod = is_moderate[None, :, None]
    gate = jnp.where(mod, gate_mod, gate_dir) & valid_b
    term = jnp.where(mod, term_mod, term_dir)
    return jnp.where(gate, term, 0.0).sum(axis=2)


@jax.jit
def _pick_winner(scores: jnp.ndarray, rf: jnp.ndarray) -> jnp.ndarray:
    """Argmax score with replication-factor tie-break (scoring.py:102-107)."""
    tied = scores == scores.max(axis=1, keepdims=True)
    return jnp.argmax(jnp.where(tied, rf[None, :], -jnp.inf), axis=1)


def classify_jax(
    X,
    labels,
    k: int,
    cfg: ScoringConfig | None = None,
    global_medians=None,
    mesh_shape: dict[str, int] | None = None,
):
    """Full classification: medians -> scores -> categories.

    Returns ``(category_idx (k,), scores (k, C), cluster_medians (k, d))`` as
    jax arrays.  Mirrors ops/scoring_np.classify (reference: scoring.py:111-130).

    Median strategy follows ``cfg.median_method``: ``"sort"`` (exact),
    ``"hist"`` (fixed-bin histogram, O(n)), ``"bisect"`` (scatter-free
    rank bisection on the MXU — ~10x the hist path on TPU at 10M x 128,
    k=1024; ops/pallas_kernels.label_segment_matmul), or ``"auto"``
    (past HIST_MEDIAN_THRESHOLD rows: bisect on a real TPU backend, hist
    elsewhere).

    ``mesh_shape={"data": N}`` runs the median stage under shard_map with X
    and labels sharded over the data axis — X never gathers to one device.
    Sharded ``"hist"`` psums per-shard (k, bins) histograms per feature;
    sharded ``"bisect"`` psums the (k, 2d) count block per iteration.  A
    distributed exact sort is the wrong shape for the scales that need
    sharding (SURVEY.md §7.4), so ``median_method="sort"`` raises; sharded
    ``"auto"`` resolves like the single-device auto — bisect on a real TPU
    backend, hist elsewhere.
    """
    cfg = cfg or ScoringConfig()
    x = jnp.asarray(X)
    labels = jnp.asarray(labels).astype(jnp.int32)
    ndata = int((mesh_shape or {}).get("data", 1))

    method = resolve_median_method(getattr(cfg, "median_method", "auto"),
                                   ndata, x.shape[0])
    bins = int(getattr(cfg, "median_bins", 2048))

    want_global = global_medians is None and cfg.compute_global_medians_from_data
    if global_medians is not None:
        gm = jnp.asarray(global_medians, dtype=x.dtype)
    elif want_global:
        gm = None  # computed on device inside the fused program
    else:
        gm = jnp.asarray([cfg.global_medians[f] for f in cfg.features],
                         dtype=x.dtype)

    W = jnp.asarray(np.array(cfg.weight_matrix(), dtype=np.float64), dtype=x.dtype)
    D = jnp.asarray(np.array(cfg.direction_matrix(), dtype=np.float64), dtype=x.dtype)
    is_mod = jnp.asarray(np.array([c == "Moderate" for c in cfg.categories]))
    rf = jnp.asarray(np.array(cfg.rf_vector(), dtype=np.float64), dtype=x.dtype)

    static = (method, int(k), bins, bool(want_global), ndata,
              int((mesh_shape or {}).get("model", 1)))
    fused = _build_classify(*static)
    args = (x, labels, gm, W, D, is_mod,
            jnp.asarray(cfg.moderate_band, x.dtype), rf)
    from ..obs import current as _obs_current

    _tel = _obs_current()
    if _tel is not None and _tel.xprof:
        # XLA cost capture for the fused classification program (medians ->
        # score table -> winner): flops/bytes/compile-seconds as xla.*
        # events, once per abstract signature (obs/xprof.py).  Sharded
        # programs stamp the device count so the roofline rows read
        # against mesh size.
        from ..obs.jaxtools import aval_signature
        from ..obs.xprof import instrumented_call

        nmodel = int((mesh_shape or {}).get("model", 1))
        extra = ({"devices": ndata * nmodel} if ndata * nmodel > 1
                 else None)
        return instrumented_call(
            "classify_jax", fused, args,
            signature=aval_signature(x, labels, gm, static=static),
            extra=extra)
    return fused(*args)


@functools.lru_cache(maxsize=64)
def _build_classify(method: str, k: int, bins: int, use_data_gm: bool,
                    ndata: int, nmodel: int):
    """One jit program for the whole classification tail: medians -> score
    table -> winner.  Previously three separate dispatches (medians, scores,
    pick) — on a remote-tunnel backend each dispatch carries ~60-100 ms of
    fixed latency, a visible slice of the 2.5-3 s config-3/4 e2e paths.
    The scoring tables arrive as traced arguments, so one compiled program
    serves every ScoringConfig of the same shape."""

    def fused(x, labels, gm, W, D, is_mod, band, rf):
        if ndata > 1:
            sharded_medians = (_bisect_medians_sharded if method == "bisect"
                               else _hist_medians_sharded)
            medians, gmeds = sharded_medians(x, labels, k, bins, use_data_gm,
                                             ndata, nmodel)
        elif method == "bisect":
            medians, gmeds = _bisect_medians(x, labels, k, bins, use_data_gm)
        elif method == "hist":
            # Global medians (when needed) fall out of the same histograms —
            # one data pass total.
            medians, gmeds = _hist_medians(x, labels, k, bins, use_data_gm)
        else:
            medians = compute_cluster_medians_jax(x, labels, k)
            gmeds = jnp.median(x, axis=0) if use_data_gm else None
        # use_data_gm is static per compiled program: exactly one of the two
        # sources exists (gm arrives as None — an empty pytree leaf — on the
        # from-data path, and vice versa).
        global_medians = gmeds if use_data_gm else gm
        scores = score_table_jax(medians, global_medians, W, D, is_mod, band)
        winner = _pick_winner(scores, rf)
        return winner, scores, medians

    return jax.jit(fused)
