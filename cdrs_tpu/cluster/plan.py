"""Exportable replication plan — the hook that can act on a REAL cluster.

The reference stores files in a live HDFS (``hdfs dfs -put``,
src/generator.py:9-10,39) with a uniform dfs.replication=1
(docker/hadoop.env:2), decides per-category factors (main.py:131-142) — and
never applies them.  The rebuild applies them inside its own simulator
(cluster/placement.py); this module closes the remaining gap by exporting the
decision in forms an external cluster can consume:

* a **plan file** (CSV ``path,category,rf``) — the per-file target
  replication factor, machine-readable and round-trippable;
* an **``hdfs dfs -setrep`` command list** (a shell script, one command per
  rf group) — directly runnable against the HDFS the reference's compose
  cluster stands up.

Plans are pure data: building one touches no cluster.  ``read_plan_csv``
round-trips ``write_plan_csv`` exactly.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass

import numpy as np

from ..config import ScoringConfig

__all__ = ["PlanEntry", "build_plan", "write_plan_csv", "read_plan_csv",
           "write_setrep_script"]

#: Paths per ``hdfs dfs -setrep`` invocation.  setrep accepts many paths per
#: call; batching bounds the command-line length (HDFS paths in the
#: reference's layout are short, but plans may cover millions of files).
_SETREP_BATCH = 500


@dataclass(frozen=True)
class PlanEntry:
    path: str
    category: str
    rf: int


def build_plan(paths, categories, cfg: ScoringConfig | None = None,
               rf=None) -> list[PlanEntry]:
    """Per-file target-rf plan from decided categories.

    ``rf`` overrides the config's category -> rf table when given (one int
    per file); otherwise factors come from ``cfg.replication_factors`` —
    the same table the cluster stage decided with (reference
    main.py:131-142 semantics).  Unknown categories raise: a plan with a
    silently-defaulted rf would mis-replicate on a real cluster.
    """
    cfg = cfg or ScoringConfig()
    paths = list(paths)
    categories = list(categories)
    if len(paths) != len(categories):
        raise ValueError(
            f"{len(paths)} paths vs {len(categories)} categories")
    if rf is not None:
        rf = np.asarray(rf, dtype=np.int64)
        if rf.shape != (len(paths),):
            raise ValueError(f"rf shape {rf.shape} != ({len(paths)},)")
        factors = [int(r) for r in rf]
    else:
        table = cfg.replication_factors
        missing = sorted({c for c in categories if c not in table})
        if missing:
            raise ValueError(
                f"categories {missing} have no replication factor in the "
                f"scoring config (known: {sorted(table)})")
        factors = [int(table[c]) for c in categories]
    return [PlanEntry(p, c, f)
            for p, c, f in zip(paths, categories, factors)]


def write_plan_csv(path: str, entries: list[PlanEntry]) -> None:
    """``path,category,rf`` — one row per file, header included."""
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["path", "category", "rf"])
        for e in entries:
            w.writerow([e.path, e.category, e.rf])


def read_plan_csv(path: str) -> list[PlanEntry]:
    """Inverse of ``write_plan_csv`` (exact round-trip)."""
    out = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            out.append(PlanEntry(row["path"], row["category"],
                                 int(row["rf"])))
    return out


def write_setrep_script(path: str, entries: list[PlanEntry],
                        batch: int = _SETREP_BATCH,
                        wait: bool = False) -> int:
    """Write a shell script of ``hdfs dfs -setrep`` commands applying the plan.

    Files are grouped by target rf (one setrep per batch of ``batch`` paths)
    so the script issues O(#rf-values * #files/batch) commands, not one per
    file.  ``wait=True`` adds ``-w`` (block until re-replication completes —
    slow on real clusters, per the HDFS docs, but deterministic).  Returns
    the number of setrep commands written.  Paths are single-quoted (with
    quote-escaping) for shell safety.
    """
    def q(s: str) -> str:
        return "'" + s.replace("'", "'\\''") + "'"

    by_rf: dict[int, list[str]] = {}
    for e in entries:
        by_rf.setdefault(e.rf, []).append(e.path)

    n_cmds = 0
    flag = "-w " if wait else ""
    with open(path, "w") as f:
        f.write("#!/bin/sh\n# Generated replication plan "
                f"({len(entries)} files, {len(by_rf)} rf groups).\n"
                "# Apply with: sh this_script  (requires the hdfs CLI "
                "on PATH and a running namenode).\nset -e\n")
        for rf in sorted(by_rf):
            paths = by_rf[rf]
            for i in range(0, len(paths), batch):
                chunk = " ".join(q(p) for p in paths[i:i + batch])
                f.write(f"hdfs dfs -setrep {flag}{rf} {chunk}\n")
                n_cmds += 1
    return n_cmds
