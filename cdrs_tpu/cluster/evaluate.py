"""Policy evaluation: replay the access log against a replica placement.

The reference plants ground truth and decides replication factors but never
measures what they achieve (SURVEY.md §4.2, §6 "pipeline decides factors but
never applies them").  This module replays the simulated access log against a
placement and reports:

* **read locality** — fraction of reads whose client holds a replica
  (the quantity the paper's Hot/Shared categories exist to improve);
* **load balance** — reads served per node (local reads served locally,
  remote reads by a seeded-random replica holder), writes fanned out to every
  replica (the HDFS write pipeline); balance = max/mean;
* **storage** — bytes per node including replicas.

``compare_policies`` puts the clustering-driven factors side by side with
uniform baselines (dfs.replication=1, the reference's sim-cluster setting,
and uniform 3, the HDFS default).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..io.events import EventLog, Manifest
from .placement import ClusterTopology, PlacementResult, place_replicas

__all__ = ["PolicyMetrics", "evaluate_placement", "compare_policies"]


@dataclass
class PolicyMetrics:
    read_locality: float          # local reads / total reads
    reads_per_node: np.ndarray    # (#nodes,)
    writes_per_node: np.ndarray   # (#nodes,) replica write amplification incl.
    load_balance: float           # max/mean of total ops per node (1.0 = even)
    storage_per_node: np.ndarray  # (#nodes,) bytes
    total_storage: int
    n_reads: int
    n_writes: int

    def summary(self) -> dict:
        return {
            "read_locality": self.read_locality,
            "load_balance": self.load_balance,
            "total_storage_bytes": int(self.total_storage),
            "reads_per_node": self.reads_per_node.tolist(),
            "writes_per_node": self.writes_per_node.tolist(),
            "storage_per_node": self.storage_per_node.tolist(),
            "n_reads": self.n_reads,
            "n_writes": self.n_writes,
        }


def _client_to_topology(events: EventLog, topology: ClusterTopology) -> np.ndarray:
    node_by_name = {nm: i for i, nm in enumerate(topology.nodes)}
    lut = np.asarray([
        node_by_name.get(c, -1) for c in events.clients
    ], dtype=np.int32)
    return lut[events.client_id]


def evaluate_placement(
    manifest: Manifest,
    events: EventLog,
    placement: PlacementResult,
    seed: int | None = 0,
) -> PolicyMetrics:
    topology = placement.topology
    n_nodes = len(topology)

    keep = events.path_id >= 0
    pid = events.path_id[keep]
    op = events.op[keep]
    client = _client_to_topology(events, topology)[keep]

    reads = op == 0
    writes = ~reads

    rmap = placement.replica_map[pid]                    # (e, max_rf)
    holds = placement.holds(pid, client)

    # Reads: local if the client holds a replica; otherwise served by a
    # seeded-random replica of the file.
    rng = np.random.default_rng(seed)
    rf = placement.rf[pid]
    pick = (rng.random(len(pid)) * rf).astype(np.int32)
    remote_server = rmap[np.arange(len(pid)), pick]
    server = np.where(holds, client, remote_server)

    read_server = server[reads]
    reads_per_node = np.bincount(read_server[read_server >= 0],
                                 minlength=n_nodes).astype(np.int64)
    n_reads = int(reads.sum())
    read_locality = float(holds[reads].mean()) if n_reads else 1.0

    # Writes: every replica receives the write (HDFS pipeline).
    wmap = rmap[writes]
    writes_per_node = np.bincount(
        wmap[wmap >= 0].ravel(), minlength=n_nodes).astype(np.int64)
    n_writes = int(writes.sum())

    total_ops = reads_per_node + writes_per_node
    mean_ops = total_ops.mean() if total_ops.sum() else 1.0
    load_balance = float(total_ops.max() / max(mean_ops, 1e-12))

    # A hand-built PlacementResult may omit storage_per_node (it defaults
    # to None); derive it from the replica map rather than crashing.
    storage = placement.compute_storage(manifest.size_bytes)

    return PolicyMetrics(
        read_locality=read_locality,
        reads_per_node=reads_per_node,
        writes_per_node=writes_per_node,
        load_balance=load_balance,
        storage_per_node=storage,
        total_storage=int(storage.sum()),
        n_reads=n_reads,
        n_writes=n_writes,
    )


def compare_policies(
    manifest: Manifest,
    events: EventLog,
    policy_rf: np.ndarray,
    topology: ClusterTopology | None = None,
    baselines: dict[str, int] | None = None,
    seed: int | None = 0,
) -> dict:
    """Side-by-side metrics: clustering-driven rf vs uniform baselines.

    Default baselines: uniform 1 (the reference sim cluster's
    dfs.replication=1, docker/hadoop.env:2) and uniform 3 (HDFS default).
    """
    topology = topology or ClusterTopology()
    baselines = baselines if baselines is not None else {"uniform_1": 1,
                                                         "uniform_3": 3}
    out = {}
    for name, rf in baselines.items():
        placement = place_replicas(
            manifest, np.full(len(manifest), rf, dtype=np.int32),
            topology, seed)
        out[name] = evaluate_placement(manifest, events, placement, seed).summary()
    placement = place_replicas(manifest, policy_rf, topology, seed)
    out["policy"] = evaluate_placement(manifest, events, placement, seed).summary()
    return out
