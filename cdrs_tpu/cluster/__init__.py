"""Simulated distributed file system — the reference's L0 substrate, closed-loop.

The reference stands up a 6-container Hadoop/Spark cluster purely as a place
for files to live (docker/docker-compose.yml:4-79) and *decides* replication
factors without ever applying them (hadoop.env pins dfs.replication=1 —
SURVEY.md §6 "no actual replication performed").  This package replaces that
role analytically and goes one step further: it applies the decided factors
(block placement over simulated datanodes) and replays the access log against
the placement to measure what the policy actually buys — read locality,
load balance, and storage cost (SURVEY.md §4.2's missing validation loop).
"""

from .placement import (ClusterTopology, PlacementResult, place_replicas,
                        place_stripes, reset_rf_cap_warning)
from .evaluate import PolicyMetrics, evaluate_placement, compare_policies
from .plan import (PlanEntry, build_plan, write_plan_csv, read_plan_csv,
                   write_setrep_script)

__all__ = [
    "ClusterTopology", "PlacementResult", "place_replicas",
    "place_stripes", "reset_rf_cap_warning",
    "PolicyMetrics", "evaluate_placement", "compare_policies",
    "PlanEntry", "build_plan", "write_plan_csv", "read_plan_csv",
    "write_setrep_script",
]
