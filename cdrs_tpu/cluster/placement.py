"""Replica placement over simulated datanodes.

HDFS-like policy, fully vectorized: replica 0 lives on the file's primary
node (the reference manifest's ``primary_node`` column, generator.py:44);
additional replicas go to distinct other nodes chosen by a seeded random
permutation per file (the statistical shape of HDFS's random target chooser,
minus rack topology).  Deterministic given (manifest, rf, seed).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field

import numpy as np

from ..io.events import Manifest

__all__ = ["ClusterTopology", "PlacementResult", "place_replicas"]

#: One warning per process: the cap itself is HDFS behaviour and placement
#: runs per window in the controller — the *first* silent downgrade is the
#: operator-relevant event (e.g. Archival rf=4 on a 3-node topology).
_RF_CAP_WARNED = False


@dataclass
class ClusterTopology:
    """Datanode set.  The reference's compose file runs one real datanode and
    imagines three (SURVEY.md §5 note); here the node set is explicit."""

    nodes: tuple[str, ...] = ("dn1", "dn2", "dn3")

    def __len__(self) -> int:
        return len(self.nodes)


@dataclass
class PlacementResult:
    """Replica assignment: (n, max_rf) node ids, -1 where rf < max_rf."""

    replica_map: np.ndarray          # (n, max_rf) int32
    rf: np.ndarray                   # (n,) int32 effective rf (capped at #nodes)
    topology: ClusterTopology
    storage_per_node: np.ndarray = field(default=None)  # (#nodes,) bytes

    def holds(self, pid: np.ndarray, node: np.ndarray) -> np.ndarray:
        """Bool per event: does ``node`` hold a replica of file ``pid``?

        ``node < 0`` (a client outside the topology) is never a holder — it
        must not match the -1 padding slots of mixed-rf rows.
        """
        return (self.replica_map[pid] == node[:, None]).any(axis=1) & (node >= 0)


def place_replicas(
    manifest: Manifest,
    rf_per_file: np.ndarray,
    topology: ClusterTopology | None = None,
    seed: int | None = 0,
) -> PlacementResult:
    """Place ``rf_per_file`` replicas of each file onto the topology.

    ``rf`` is capped at the node count (HDFS behaviour for small clusters).
    Replica 0 is the primary node; the remaining ``rf-1`` are drawn without
    replacement from the other nodes via per-file random priority sort.
    """
    topology = topology or ClusterTopology()
    n = len(manifest)
    n_nodes = len(topology)
    node_by_name = {nm: i for i, nm in enumerate(topology.nodes)}

    # Manifest primary ids index manifest.nodes; remap onto the topology via
    # a per-name LUT (O(vocabulary), not O(files)).  Unknown nodes spread over
    # the topology via a *stable* hash (Python's str hash is salted per
    # process and would break run-to-run determinism).
    import zlib

    lut = np.asarray([
        node_by_name.get(nm, zlib.crc32(nm.encode()) % n_nodes)
        for nm in manifest.nodes
    ], dtype=np.int32)
    primary = lut[manifest.primary_node_id]

    rf_want = np.asarray(rf_per_file, dtype=np.int32)
    n_capped = int((rf_want > n_nodes).sum())
    if n_capped:
        global _RF_CAP_WARNED
        if not _RF_CAP_WARNED:
            _RF_CAP_WARNED = True
            warnings.warn(
                f"replication factor capped at the node count for "
                f"{n_capped} files (requested up to {int(rf_want.max())}, "
                f"topology has {n_nodes} nodes) — replicas are "
                f"distinct-per-node, so e.g. Archival rf=4 on a 3-node "
                f"topology places 3", stacklevel=2)
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is not None:
            tel.counter_inc("placement.rf_capped", n_capped)
    rf = np.minimum(rf_want, n_nodes)
    rf = np.maximum(rf, 1)
    max_rf = int(rf.max())

    rng = np.random.default_rng(seed)
    # Random priorities per (file, node); primary forced to the front.
    prio = rng.random((n, n_nodes))
    prio[np.arange(n), primary] = -1.0          # sorts first
    order = np.argsort(prio, axis=1).astype(np.int32)  # (n, n_nodes)

    replica_map = order[:, :max_rf].copy()
    mask = np.arange(max_rf)[None, :] < rf[:, None]
    replica_map[~mask] = -1

    storage = np.zeros(n_nodes, dtype=np.int64)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    for j in range(max_rf):
        col = replica_map[:, j]
        sel = col >= 0
        np.add.at(storage, col[sel], sizes[sel])

    return PlacementResult(replica_map=replica_map, rf=rf, topology=topology,
                           storage_per_node=storage)
