"""Replica placement over simulated datanodes, failure-domain aware.

HDFS-like policy, fully vectorized: replica 0 lives on the file's primary
node (the reference manifest's ``primary_node`` column, generator.py:44);
replica 1 goes to a node in a *different failure domain* than the primary,
replica 2 to a second node in that same remote domain, and any further
replicas to distinct nodes by seeded random priority — the shape of HDFS's
rack-aware block placement (Shvachko et al., MSST 2010: local node, remote
rack, same remote rack, then spread) over `ClusterTopology.domains`.

A flat topology (no ``domains``) treats every node as its own failure
domain, which makes the policy degenerate *bit-for-bit* to the historical
distinct-node random chooser: replica 1's "different domain" is simply the
best-priority non-primary node, and a one-node "second domain" has no
second member to boost.  Deterministic given (manifest, rf, seed) either
way — no per-file Python loop.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field

import numpy as np

from ..io.events import Manifest

__all__ = ["ClusterTopology", "PlacementResult", "place_replicas",
           "place_stripes", "reset_rf_cap_warning"]


class _OnceWarning:
    """Per-process one-shot warning latch, resettable for test isolation.

    The rf cap itself is HDFS behaviour and placement runs per window in
    the controller — the *first* silent downgrade is the operator-relevant
    event (e.g. Archival rf=4 on a 3-node topology).  A module-global bool
    (the previous implementation) could never be re-armed, so tests after
    the first firing could not assert the warning.
    """

    def __init__(self) -> None:
        self.fired = False

    def reset(self) -> None:
        self.fired = False

    def warn(self, message: str) -> None:
        if self.fired:
            return
        self.fired = True
        warnings.warn(message, stacklevel=3)


_RF_CAP_WARNING = _OnceWarning()


def reset_rf_cap_warning() -> None:
    """Re-arm the one-shot rf-cap warning (test isolation hook)."""
    _RF_CAP_WARNING.reset()


@dataclass
class ClusterTopology:
    """Datanode set with failure domains.  The reference's compose file runs
    one real datanode and imagines three (SURVEY.md §5 note); here the node
    set is explicit, and each node maps to a failure domain (rack/zone) so
    correlated failures — a rack losing power, a switch partitioning half
    the cluster — are expressible."""

    nodes: tuple[str, ...] = ("dn1", "dn2", "dn3")
    #: Per-node failure-domain name, parallel to ``nodes``.  Empty = every
    #: node is its own domain (the flat topology: node loss IS domain loss,
    #: and domain-aware placement reduces to the distinct-node policy).
    domains: tuple[str, ...] = ()

    def __post_init__(self):
        self.nodes = tuple(self.nodes)
        self.domains = tuple(self.domains)
        if not self.nodes:
            raise ValueError("topology needs at least one node")
        if len(set(self.nodes)) != len(self.nodes):
            dupes = sorted({n for n in self.nodes
                            if self.nodes.count(n) > 1})
            raise ValueError(
                f"duplicate node names in topology: {dupes} — every node "
                f"must be unique (a duplicate silently corrupts "
                f"storage_per_node accounting)")
        if self.domains and len(self.domains) != len(self.nodes):
            raise ValueError(
                f"domains has {len(self.domains)} entries for "
                f"{len(self.nodes)} nodes — must be parallel to nodes "
                f"(one failure-domain name per node)")

    def __len__(self) -> int:
        return len(self.nodes)

    @property
    def domain_names(self) -> tuple[str, ...]:
        """Distinct domain names in first-appearance order."""
        src = self.domains if self.domains else self.nodes
        return tuple(dict.fromkeys(src))

    @property
    def n_domains(self) -> int:
        return len(self.domain_names)

    def domain_index(self) -> np.ndarray:
        """(n_nodes,) int32: each node's domain id (domain_names order)."""
        src = self.domains if self.domains else self.nodes
        idx = {d: i for i, d in enumerate(self.domain_names)}
        return np.asarray([idx[d] for d in src], dtype=np.int32)

    @classmethod
    def from_racks(cls, nodes, racks: dict) -> "ClusterTopology":
        """Topology from a ``node -> domain`` mapping.

        Every mapped node must exist; nodes the mapping omits fall back to
        their own singleton domain (flat behaviour for the unmapped rest).
        """
        nodes = tuple(nodes)
        unknown = sorted(set(racks) - set(nodes))
        if unknown:
            raise ValueError(
                f"rack map names nodes outside the topology {nodes}: "
                f"{unknown}")
        return cls(nodes, tuple(str(racks.get(n, n)) for n in nodes))

    @classmethod
    def from_rack_spec(cls, nodes, spec: str) -> "ClusterTopology":
        """Topology from a CLI rack spec: ``;``-separated groups, each
        ``name=n1,n2`` or bare ``n1,n2`` (auto-named rack0, rack1, ...) —
        e.g. ``--racks 'r0=dn1,dn2;r1=dn3,dn4'``."""
        racks: dict[str, str] = {}
        seen_names: set[str] = set()
        for i, group in enumerate(g for g in spec.split(";") if g.strip()):
            if "=" in group:
                name, members = group.split("=", 1)
                name = name.strip()
            else:
                name, members = f"rack{i}", group
            if name in seen_names:
                # An auto-generated rack0 colliding with an explicit
                # 'rack0=' would silently merge two groups into one
                # failure domain — exactly the separation the spec was
                # written to buy.
                raise ValueError(
                    f"duplicate rack name {name!r} in spec {spec!r} "
                    f"(auto-named bare groups use rack0, rack1, ...)")
            seen_names.add(name)
            for m in members.split(","):
                m = m.strip()
                if not m:
                    continue
                if m in racks:
                    raise ValueError(
                        f"node {m!r} appears in two rack groups "
                        f"({racks[m]!r} and {name!r}) in spec {spec!r}")
                racks[m] = name
        if not racks:
            raise ValueError(f"rack spec {spec!r} names no nodes")
        return cls.from_racks(nodes, racks)


@dataclass
class PlacementResult:
    """Replica assignment: (n, max_rf) node ids, -1 where rf < max_rf."""

    replica_map: np.ndarray          # (n, max_rf) int32
    rf: np.ndarray                   # (n,) int32 effective rf (capped at #nodes)
    topology: ClusterTopology
    #: (#nodes,) bytes; ``place_replicas`` always fills it, but a
    #: hand-built result may omit it — consumers must guard or call
    #: ``compute_storage``.
    storage_per_node: np.ndarray | None = field(default=None)

    def holds(self, pid: np.ndarray, node: np.ndarray) -> np.ndarray:
        """Bool per event: does ``node`` hold a replica of file ``pid``?

        ``node < 0`` (a client outside the topology) is never a holder — it
        must not match the -1 padding slots of mixed-rf rows.
        """
        return (self.replica_map[pid] == node[:, None]).any(axis=1) & (node >= 0)

    def compute_storage(self, size_bytes: np.ndarray) -> np.ndarray:
        """(#nodes,) replica bytes from the map; fills ``storage_per_node``
        when the constructor left it None."""
        if self.storage_per_node is None:
            sizes = np.asarray(size_bytes, dtype=np.int64)
            storage = np.zeros(len(self.topology), dtype=np.int64)
            sel = self.replica_map >= 0
            np.add.at(storage, self.replica_map[sel],
                      np.broadcast_to(sizes[:, None],
                                      self.replica_map.shape)[sel])
            self.storage_per_node = storage
        return self.storage_per_node

    def domain_counts(self) -> np.ndarray:
        """(n,) int32: distinct failure domains each file's replicas span."""
        dom = self.topology.domain_index()
        assigned = self.replica_map >= 0
        counts = np.zeros(self.replica_map.shape[0], dtype=np.int32)
        slot_dom = dom[np.clip(self.replica_map, 0, None)]
        for d in range(self.topology.n_domains):
            counts += ((slot_dom == d) & assigned).any(axis=1)
        return counts


def place_replicas(
    manifest: Manifest,
    rf_per_file: np.ndarray,
    topology: ClusterTopology | None = None,
    seed: int | None = 0,
    size_bytes: np.ndarray | None = None,
    method: str = "rng",
) -> PlacementResult:
    """Place ``rf_per_file`` replicas of each file onto the topology.

    ``rf`` is capped at the node count (HDFS behaviour for small clusters).
    Replica 0 is the primary node.  With failure domains, replica 1 is the
    best-priority node in a seeded-random *remote* domain and replica 2 the
    second-best node of that same domain (HDFS rack-aware: off-rack, then
    same remote rack); the remaining ``rf-3`` are drawn without replacement
    from the other nodes via per-file random priority sort.  On a flat
    topology every node is its own domain and the policy is exactly the
    historical distinct-node random chooser.

    ``method`` selects the priority source: ``"rng"`` (default) is the
    historical per-placement rng matrix — a function of the whole
    population, so it can only be materialized; ``"hash"`` draws the
    SAME structural policy's priorities from the stateless per-(file,
    node-name) hash of ``placement_fn.compute_placement``, making this
    call the materialized twin of the functional chooser (one
    implementation, two surfaces — the equivalence oracle of
    ``--placement functional``).
    """
    topology = topology or ClusterTopology()
    n = len(manifest)
    n_nodes = len(topology)

    # Manifest primary ids remap onto the topology via the shared
    # per-name LUT (placement_fn.primary_on_topology): O(vocabulary),
    # stable-hash spread for unknown names.
    from ..placement_fn.compute import primary_on_topology

    primary = primary_on_topology(manifest.nodes,
                                  manifest.primary_node_id, topology)

    rf_want = np.asarray(rf_per_file, dtype=np.int32)
    n_capped = int((rf_want > n_nodes).sum())
    if n_capped:
        _RF_CAP_WARNING.warn(
            f"replication factor capped at the node count for "
            f"{n_capped} files (requested up to {int(rf_want.max())}, "
            f"topology has {n_nodes} nodes) — replicas are "
            f"distinct-per-node, so e.g. Archival rf=4 on a 3-node "
            f"topology places 3")
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is not None:
            tel.counter_inc("placement.rf_capped", n_capped)
    rf = np.minimum(rf_want, n_nodes)
    rf = np.maximum(rf, 1)
    max_rf = int(rf.max())

    if method == "hash":
        from ..placement_fn.compute import compute_placement

        replica_map, rf = compute_placement(
            np.arange(n, dtype=np.int64), rf, primary, topology,
            0 if seed is None else int(seed))
        result = PlacementResult(replica_map=replica_map, rf=rf,
                                 topology=topology)
        result.compute_storage(manifest.size_bytes if size_bytes is None
                               else size_bytes)
        return result
    if method != "rng":
        raise ValueError(f"unknown placement method {method!r} "
                         f"(want 'rng' or 'hash')")

    rng = np.random.default_rng(seed)
    # Random priorities per (file, node); the sort key starts as the raw
    # priorities and gets the structured slots forced to the front.
    prio = rng.random((n, n_nodes))
    key = prio.copy()
    key[np.arange(n), primary] = -3.0           # replica 0: the primary
    dom = topology.domain_index()
    if topology.n_domains > 1 and n_nodes > 1:
        # Remote domain per file: the domain of the best-priority node
        # OUTSIDE the primary's domain (a seeded random domain choice
        # weighted exactly like the node choice itself).
        same = dom[None, :] == dom[primary][:, None]       # (n, n_nodes)
        remote_prio = np.where(same, np.inf, prio)
        best_remote = np.argmin(remote_prio, axis=1)       # (n,)
        has_remote = np.isfinite(remote_prio[np.arange(n), best_remote])
        in_rdom = ((dom[None, :] == dom[best_remote][:, None])
                   & ~same & has_remote[:, None])
        # Replica 1 = best node of the remote domain, replica 2 = its
        # second-best (same remote rack, HDFS-style).  Everything else
        # keeps its raw priority — "rest on distinct nodes".
        masked = np.where(in_rdom, prio, np.inf)
        part = np.partition(masked, 1, axis=1)
        m1, m2 = part[:, 0], part[:, 1]
        key = np.where(np.isfinite(m1)[:, None] & (masked == m1[:, None]),
                       -2.0, key)
        key = np.where(np.isfinite(m2)[:, None] & (masked == m2[:, None]),
                       -1.0, key)
    order = np.argsort(key, axis=1).astype(np.int32)       # (n, n_nodes)

    replica_map = order[:, :max_rf].copy()
    mask = np.arange(max_rf)[None, :] < rf[:, None]
    replica_map[~mask] = -1

    result = PlacementResult(replica_map=replica_map, rf=rf,
                             topology=topology)
    result.compute_storage(manifest.size_bytes if size_bytes is None
                           else size_bytes)
    return result


def place_stripes(
    manifest: Manifest,
    shards_per_file: np.ndarray,
    topology: ClusterTopology | None = None,
    seed: int | None = 0,
    shard_bytes: np.ndarray | None = None,
    method: str = "rng",
) -> PlacementResult:
    """Vectorized stripe placement for storage strategies (cdrs_tpu/storage).

    An erasure-coded file's k+m shards want exactly what replicas want:
    distinct nodes, spread across failure domains (Ceph CRUSH places EC
    chunks with the same rule it places replicas) — so stripe placement
    IS ``place_replicas`` over the per-file shard count.  A replicate
    strategy's ``n_shards == rf``, so a config with only ``replicate``
    strategies degenerates bit-for-bit to today's placements.  The one
    difference is byte accounting: a slot of an EC file holds
    ``shard_bytes`` (~ size/k) rather than the full size, so
    ``storage_per_node`` is computed from ``shard_bytes`` when given.
    """
    return place_replicas(manifest, shards_per_file, topology, seed,
                          size_bytes=shard_bytes, method=method)
