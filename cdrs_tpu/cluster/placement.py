"""Replica placement over simulated datanodes, failure-domain aware.

HDFS-like policy, fully vectorized: replica 0 lives on the file's primary
node (the reference manifest's ``primary_node`` column, generator.py:44);
replica 1 goes to a node in a *different failure domain* than the primary,
replica 2 to a second node in that same remote domain, and any further
replicas to distinct nodes by seeded random priority — the shape of HDFS's
rack-aware block placement (Shvachko et al., MSST 2010: local node, remote
rack, same remote rack, then spread) over `ClusterTopology.domains`.

A flat topology (no ``domains``) treats every node as its own failure
domain, which makes the policy degenerate *bit-for-bit* to the historical
distinct-node random chooser: replica 1's "different domain" is simply the
best-priority non-primary node, and a one-node "second domain" has no
second member to boost.  Deterministic given (manifest, rf, seed) either
way — no per-file Python loop.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass, field

import numpy as np

from ..io.events import Manifest

__all__ = ["ClusterTopology", "PlacementResult", "place_replicas",
           "place_stripes", "reset_rf_cap_warning"]


class _OnceWarning:
    """Per-process one-shot warning latch, resettable for test isolation.

    The rf cap itself is HDFS behaviour and placement runs per window in
    the controller — the *first* silent downgrade is the operator-relevant
    event (e.g. Archival rf=4 on a 3-node topology).  A module-global bool
    (the previous implementation) could never be re-armed, so tests after
    the first firing could not assert the warning.
    """

    def __init__(self) -> None:
        self.fired = False

    def reset(self) -> None:
        self.fired = False

    def warn(self, message: str) -> None:
        if self.fired:
            return
        self.fired = True
        warnings.warn(message, stacklevel=3)


_RF_CAP_WARNING = _OnceWarning()


def reset_rf_cap_warning() -> None:
    """Re-arm the one-shot rf-cap warning (test isolation hook)."""
    _RF_CAP_WARNING.reset()


@dataclass
class ClusterTopology:
    """Datanode set with failure domains.  The reference's compose file runs
    one real datanode and imagines three (SURVEY.md §5 note); here the node
    set is explicit, and each node maps to a failure domain (rack/zone) so
    correlated failures — a rack losing power, a switch partitioning half
    the cluster — are expressible.

    **Hierarchy** (geo-hierarchical topologies, ROADMAP item 6): ``levels``
    stacks coarser failure domains ON TOP of the base ``domains`` level —
    CRUSH's host -> rack -> row/region -> datacenter bucket tree.  Each
    entry is ``(level_name, per-node domain names)``, finest first, and
    every level must be a strict coarsening of the level below (a rack
    split across two regions is a spec bug, rejected by name).  Per-edge
    ``edge_bytes``/``edge_latency`` multipliers price a copy/read that
    crosses each boundary class (off-rack, off-region, ...; WAN ≫ rack);
    empty = all 1.0, which keeps every byte/latency account bit-identical
    to the pre-hierarchy behaviour.  A topology without ``levels``
    degenerates bit-for-bit to the historical one-level semantics."""

    nodes: tuple[str, ...] = ("dn1", "dn2", "dn3")
    #: Per-node failure-domain name, parallel to ``nodes``.  Empty = every
    #: node is its own domain (the flat topology: node loss IS domain loss,
    #: and domain-aware placement reduces to the distinct-node policy).
    domains: tuple[str, ...] = ()
    #: Hierarchy levels ABOVE the base domain, finest first: each entry is
    #: ``(level_name, per-node domain names parallel to nodes)``.  Empty =
    #: the historical one-level topology.
    levels: tuple = ()
    #: Byte-cost multipliers per boundary class, one per hierarchy level
    #: including the base (``(off-domain, off-level-1, ...)``): a repair
    #: copy whose route crosses class ``c`` charges ``edge_bytes[c-1]`` x
    #: its wire bytes against the churn budget.  Empty = all 1.0.
    edge_bytes: tuple = ()
    #: Latency multipliers, same indexing: a read served across class
    #: ``c`` adds ``(edge_latency[c-1] - 1) x service_ms`` propagation
    #: delay.  Empty = all 1.0.
    edge_latency: tuple = ()
    #: Name of the base ``domains`` level (hierarchy specs; cosmetic for
    #: flat topologies).  Region-scoped fault events (``crash:region:eu``)
    #: resolve level tokens against this plus the ``levels`` names.
    domain_level_name: str = "rack"

    def __post_init__(self):
        self.nodes = tuple(self.nodes)
        self.domains = tuple(self.domains)
        if not self.nodes:
            raise ValueError("topology needs at least one node")
        if len(set(self.nodes)) != len(self.nodes):
            dupes = sorted({n for n in self.nodes
                            if self.nodes.count(n) > 1})
            raise ValueError(
                f"duplicate node names in topology: {dupes} — every node "
                f"must be unique (a duplicate silently corrupts "
                f"storage_per_node accounting)")
        if self.domains and len(self.domains) != len(self.nodes):
            raise ValueError(
                f"domains has {len(self.domains)} entries for "
                f"{len(self.nodes)} nodes — must be parallel to nodes "
                f"(one failure-domain name per node)")
        self.levels = tuple((str(nm), tuple(str(d) for d in doms))
                            for nm, doms in self.levels)
        if self.levels and not self.domains:
            raise ValueError(
                "hierarchy levels require a base domains level (the "
                "finest failure domain) — give every node a domain")
        for nm, doms in self.levels:
            if len(doms) != len(self.nodes):
                raise ValueError(
                    f"hierarchy level {nm!r} has {len(doms)} entries for "
                    f"{len(self.nodes)} nodes — must be parallel to nodes")
        # Strict coarsening: two nodes sharing a domain at level i must
        # share it at every level above, or a "rack" straddles two
        # "regions" and the failure-domain math silently lies.
        below_name = self.domain_level_name or "domain"
        below = self.domains
        for nm, doms in self.levels:
            owner: dict[str, tuple[str, str]] = {}
            for node, lo, hi in zip(self.nodes, below, doms):
                if lo in owner and owner[lo][0] != hi:
                    raise ValueError(
                        f"hierarchy level {nm!r}: {below_name} {lo!r} "
                        f"spans {hi!r} (node {node!r}) and "
                        f"{owner[lo][0]!r} (node {owner[lo][1]!r}) — "
                        f"each {below_name} must nest inside exactly "
                        f"one {nm}")
                owner.setdefault(lo, (hi, node))
            below_name, below = nm, doms
        # Domain LUTs once (the former per-call rebuild in
        # n_domains/domain_spread was O(nodes) per query): one names
        # tuple + one int32 index array per level, base first.  Built
        # BEFORE the edge validation below, which names the boundary
        # classes in its error message.
        self._level_names = (self.domain_level_name or "rack",) + tuple(
            nm for nm, _ in self.levels)
        for label, edges in (("edge_bytes", self.edge_bytes),
                             ("edge_latency", self.edge_latency)):
            edges = tuple(float(x) for x in edges)
            setattr(self, label, edges)
            if edges and len(edges) != self.n_levels + 1:
                raise ValueError(
                    f"{label} has {len(edges)} entries for "
                    f"{self.n_levels + 1} boundary classes "
                    f"({self._class_names()}) — one multiplier per class")
            if any(x < 1.0 for x in edges):
                raise ValueError(
                    f"{label} multipliers must be >= 1.0 (crossing a "
                    f"boundary is never cheaper than staying inside), "
                    f"got {edges}")
        self._dom_names: list[tuple[str, ...]] = []
        self._dom_index: list[np.ndarray] = []
        for doms in (self.domains if self.domains else self.nodes,
                     *(d for _, d in self.levels)):
            names = tuple(dict.fromkeys(doms))
            idx = {d: i for i, d in enumerate(names)}
            self._dom_names.append(names)
            self._dom_index.append(np.asarray([idx[d] for d in doms],
                                              dtype=np.int32))

    def __len__(self) -> int:
        return len(self.nodes)

    # -- hierarchy accessors (level 0 = base domains) -----------------------
    @property
    def n_levels(self) -> int:
        """Hierarchy levels ABOVE the base domain (0 = historical)."""
        return len(self.levels)

    @property
    def level_names(self) -> tuple[str, ...]:
        """Level names, base first (``rack`` unless renamed)."""
        return self._level_names

    def domain_names_at(self, level: int) -> tuple[str, ...]:
        """Distinct domain names of one level, first-appearance order."""
        return self._dom_names[level]

    def domain_index_at(self, level: int) -> np.ndarray:
        """(n_nodes,) int32 domain ids at ``level`` (cached; read-only)."""
        return self._dom_index[level]

    def n_domains_at(self, level: int) -> int:
        return len(self._dom_names[level])

    def top_domain_index(self) -> np.ndarray:
        """(n_nodes,) int32 ids at the COARSEST level (regions when the
        hierarchy has them; the base domains otherwise)."""
        return self._dom_index[self.n_levels]

    def nodes_in(self, level_name: str, domain: str) -> tuple[str, ...]:
        """Node names inside one named domain of one named level — the
        region-scoped fault expansion (``crash:region:eu``)."""
        if level_name not in self._level_names:
            raise ValueError(
                f"unknown hierarchy level {level_name!r} (this topology "
                f"has {self._level_names})")
        lvl = self._level_names.index(level_name)
        if domain not in self._dom_names[lvl]:
            raise ValueError(
                f"level {level_name!r} has no domain {domain!r} "
                f"(have {self._dom_names[lvl]})")
        want = self._dom_names[lvl].index(domain)
        idx = self._dom_index[lvl]
        return tuple(n for n, d in zip(self.nodes, idx) if d == want)

    def _class_names(self) -> tuple[str, ...]:
        return tuple(f"off-{nm}" for nm in self._level_names)

    def separation(self) -> np.ndarray:
        """(n_nodes, n_nodes) int8 boundary class between node pairs:
        0 = same base domain, c >= 1 = the pair first reunites at level
        ``c`` (c = n_levels + 1: different top-level domains — WAN)."""
        n = len(self.nodes)
        sep = np.zeros((n, n), dtype=np.int8)
        for lvl in range(self.n_levels + 1):
            idx = self._dom_index[lvl]
            sep[idx[:, None] != idx[None, :]] = lvl + 1
        return sep

    def byte_cost_matrix(self) -> np.ndarray:
        """(n_nodes, n_nodes) float64 per-copy byte-cost multiplier (all
        ones without ``edge_bytes`` — bit-identical accounting)."""
        return self._edge_matrix(self.edge_bytes)

    def latency_matrix(self) -> np.ndarray:
        """(n_nodes, n_nodes) float64 read-latency multiplier."""
        return self._edge_matrix(self.edge_latency)

    def _edge_matrix(self, edges: tuple) -> np.ndarray:
        n = len(self.nodes)
        if not edges:
            return np.ones((n, n), dtype=np.float64)
        mult = np.asarray((1.0,) + tuple(edges), dtype=np.float64)
        return mult[self.separation()]

    @property
    def domain_names(self) -> tuple[str, ...]:
        """Distinct base-domain names in first-appearance order."""
        return self._dom_names[0]

    @property
    def n_domains(self) -> int:
        return len(self._dom_names[0])

    def domain_index(self) -> np.ndarray:
        """(n_nodes,) int32: each node's base-domain id (cached —
        computed once in ``__post_init__``; treat as read-only)."""
        return self._dom_index[0]

    @classmethod
    def from_racks(cls, nodes, racks: dict) -> "ClusterTopology":
        """Topology from a ``node -> domain`` mapping.

        Every mapped node must exist; nodes the mapping omits fall back to
        their own singleton domain (flat behaviour for the unmapped rest).
        """
        nodes = tuple(nodes)
        unknown = sorted(set(racks) - set(nodes))
        if unknown:
            raise ValueError(
                f"rack map names nodes outside the topology {nodes}: "
                f"{unknown}")
        return cls(nodes, tuple(str(racks.get(n, n)) for n in nodes))

    @classmethod
    def from_rack_spec(cls, nodes, spec: str) -> "ClusterTopology":
        """Topology from a CLI rack spec: ``;``-separated groups, each
        ``name=n1,n2`` or bare ``n1,n2`` (auto-named rack0, rack1, ...) —
        e.g. ``--racks 'r0=dn1,dn2;r1=dn3,dn4'``."""
        racks: dict[str, str] = {}
        seen_names: set[str] = set()
        for i, group in enumerate(g for g in spec.split(";") if g.strip()):
            if "=" in group:
                name, members = group.split("=", 1)
                name = name.strip()
            else:
                name, members = f"rack{i}", group
            if name in seen_names:
                # An auto-generated rack0 colliding with an explicit
                # 'rack0=' would silently merge two groups into one
                # failure domain — exactly the separation the spec was
                # written to buy.
                raise ValueError(
                    f"duplicate rack name {name!r} in spec {spec!r} "
                    f"(auto-named bare groups use rack0, rack1, ...)")
            seen_names.add(name)
            for m in members.split(","):
                m = m.strip()
                if not m:
                    continue
                if m in racks:
                    raise ValueError(
                        f"node {m!r} appears in two rack groups "
                        f"({racks[m]!r} and {name!r}) in spec {spec!r}")
                racks[m] = name
        if not racks:
            raise ValueError(f"rack spec {spec!r} names no nodes")
        return cls.from_racks(nodes, racks)

    @classmethod
    def from_hierarchy(cls, spec: dict) -> "ClusterTopology":
        """Topology from a hierarchy spec dict (the ``--topology JSON``
        CLI contract)::

            {"nodes": ["dn1", ...],
             "levels": ["rack", "region"],          # finest first
             "rack":   {"r0": ["dn1", "dn2"], ...}, # groups NODES
             "region": {"eu": ["r0", "r1"], ...},   # groups racks
             "edge_bytes":   {"rack": 1.0, "region": 4.0},   # optional
             "edge_latency": {"rack": 2.0, "region": 20.0}}  # optional

        Level 0 groups nodes; level i groups level i-1's domain names.
        Every validation error names the offending level and node/group —
        a mis-typed hierarchy must fail loudly, not flatten silently.
        A one-entry ``levels`` list degenerates to the plain rack
        topology (``levels=()`` — bit-for-bit the historical policy).
        """
        if not isinstance(spec, dict):
            raise ValueError(
                f"topology spec must be a JSON object, got "
                f"{type(spec).__name__}")
        known = {"nodes", "levels", "edge_bytes", "edge_latency"}
        level_names = [str(x) for x in spec.get("levels", ())]
        if not level_names:
            raise ValueError(
                "topology spec needs 'levels': an ordered list of "
                "hierarchy level names, finest first (e.g. "
                "['rack', 'region'])")
        dupes = sorted({x for x in level_names
                        if level_names.count(x) > 1})
        if dupes:
            raise ValueError(f"duplicate level names in spec: {dupes}")
        unknown = sorted(set(spec) - known - set(level_names))
        if unknown:
            raise ValueError(
                f"unknown topology spec keys {unknown} (want nodes/"
                f"levels/edge_bytes/edge_latency plus one group map per "
                f"level in {level_names})")
        nodes = tuple(str(n) for n in spec.get("nodes", ()))
        if not nodes:
            raise ValueError("topology spec needs a non-empty 'nodes'")
        # Resolve each level bottom-up: members of level i are level
        # i-1's domain names (nodes at i = 0).
        member_domain: dict[str, str] = {}   # member -> its domain, per lvl
        members = nodes
        per_node: list[tuple[str, ...]] = []   # per-level node domains
        node_dom = {n: n for n in nodes}       # node -> domain at lvl-1
        for lvl, name in enumerate(level_names):
            groups = spec.get(name)
            if not isinstance(groups, dict) or not groups:
                raise ValueError(
                    f"level {name!r}: spec needs a non-empty group map "
                    f"{{domain: [members]}} under the {name!r} key")
            member_domain = {}
            for dom, mem in groups.items():
                for m in mem:
                    m = str(m)
                    if m not in members:
                        kind = "node" if lvl == 0 else level_names[lvl - 1]
                        raise ValueError(
                            f"level {name!r}: group {dom!r} names "
                            f"unknown {kind} {m!r} (have "
                            f"{sorted(members)})")
                    if m in member_domain:
                        raise ValueError(
                            f"level {name!r}: {m!r} appears in both "
                            f"{member_domain[m]!r} and {dom!r} — a "
                            f"member belongs to exactly one domain")
                    member_domain[m] = str(dom)
            missing = sorted(set(members) - set(member_domain))
            if missing:
                kind = "node" if lvl == 0 else level_names[lvl - 1]
                raise ValueError(
                    f"level {name!r}: {kind} {missing[0]!r} is not "
                    f"assigned to any {name} group "
                    f"(unassigned: {missing})")
            node_dom = {n: member_domain[node_dom[n]] for n in nodes}
            per_node.append(tuple(node_dom[n] for n in nodes))
            members = tuple(dict.fromkeys(member_domain.values()))

        def _edges(key: str) -> tuple:
            raw = spec.get(key)
            if raw is None:
                return ()
            if isinstance(raw, dict):
                bad = sorted(set(raw) - set(level_names))
                if bad:
                    raise ValueError(
                        f"{key} names unknown level {bad[0]!r} "
                        f"(levels: {level_names})")
                miss = [x for x in level_names if x not in raw]
                if miss:
                    raise ValueError(
                        f"{key} is missing a multiplier for level "
                        f"{miss[0]!r} — give one per level or omit the "
                        f"key entirely")
                return tuple(float(raw[x]) for x in level_names)
            return tuple(float(x) for x in raw)

        return cls(
            nodes=nodes, domains=per_node[0],
            levels=tuple((level_names[i], per_node[i])
                         for i in range(1, len(level_names))),
            edge_bytes=_edges("edge_bytes"),
            edge_latency=_edges("edge_latency"),
            domain_level_name=level_names[0])

    def to_hierarchy_dict(self) -> dict:
        """The ``from_hierarchy`` spec of this topology (round-trip)."""
        out: dict = {"nodes": list(self.nodes),
                     "levels": list(self.level_names)}
        for lvl, name in enumerate(self.level_names):
            doms = (self.domains if lvl == 0 else self.levels[lvl - 1][1])
            groups: dict[str, list[str]] = {}
            if lvl == 0:
                for n, d in zip(self.nodes, doms):
                    groups.setdefault(d, []).append(n)
            else:
                lower = (self.domains if lvl == 1
                         else self.levels[lvl - 2][1])
                seen = set()
                for lo, hi in zip(lower, doms):
                    if lo not in seen:
                        seen.add(lo)
                        groups.setdefault(hi, []).append(lo)
            out[name] = groups
        if self.edge_bytes:
            out["edge_bytes"] = {nm: x for nm, x in
                                 zip(self.level_names, self.edge_bytes)}
        if self.edge_latency:
            out["edge_latency"] = {nm: x for nm, x in
                                   zip(self.level_names,
                                       self.edge_latency)}
        return out


@dataclass
class PlacementResult:
    """Replica assignment: (n, max_rf) node ids, -1 where rf < max_rf."""

    replica_map: np.ndarray          # (n, max_rf) int32
    rf: np.ndarray                   # (n,) int32 effective rf (capped at #nodes)
    topology: ClusterTopology
    #: (#nodes,) bytes; ``place_replicas`` always fills it, but a
    #: hand-built result may omit it — consumers must guard or call
    #: ``compute_storage``.
    storage_per_node: np.ndarray | None = field(default=None)

    def holds(self, pid: np.ndarray, node: np.ndarray) -> np.ndarray:
        """Bool per event: does ``node`` hold a replica of file ``pid``?

        ``node < 0`` (a client outside the topology) is never a holder — it
        must not match the -1 padding slots of mixed-rf rows.
        """
        return (self.replica_map[pid] == node[:, None]).any(axis=1) & (node >= 0)

    def compute_storage(self, size_bytes: np.ndarray) -> np.ndarray:
        """(#nodes,) replica bytes from the map; fills ``storage_per_node``
        when the constructor left it None."""
        if self.storage_per_node is None:
            sizes = np.asarray(size_bytes, dtype=np.int64)
            storage = np.zeros(len(self.topology), dtype=np.int64)
            sel = self.replica_map >= 0
            np.add.at(storage, self.replica_map[sel],
                      np.broadcast_to(sizes[:, None],
                                      self.replica_map.shape)[sel])
            self.storage_per_node = storage
        return self.storage_per_node

    def domain_counts(self) -> np.ndarray:
        """(n,) int32: distinct failure domains each file's replicas span."""
        dom = self.topology.domain_index()
        assigned = self.replica_map >= 0
        counts = np.zeros(self.replica_map.shape[0], dtype=np.int32)
        slot_dom = dom[np.clip(self.replica_map, 0, None)]
        for d in range(self.topology.n_domains):
            counts += ((slot_dom == d) & assigned).any(axis=1)
        return counts


def place_replicas(
    manifest: Manifest,
    rf_per_file: np.ndarray,
    topology: ClusterTopology | None = None,
    seed: int | None = 0,
    size_bytes: np.ndarray | None = None,
    method: str = "rng",
    local_mask: np.ndarray | None = None,
) -> PlacementResult:
    """Place ``rf_per_file`` replicas of each file onto the topology.

    ``rf`` is capped at the node count (HDFS behaviour for small clusters).
    Replica 0 is the primary node.  With failure domains, replica 1 is the
    best-priority node in a seeded-random *remote* domain and replica 2 the
    second-best node of that same domain (HDFS rack-aware: off-rack, then
    same remote rack); the remaining ``rf-3`` are drawn without replacement
    from the other nodes via per-file random priority sort.  On a flat
    topology every node is its own domain and the policy is exactly the
    historical distinct-node random chooser.

    ``method`` selects the priority source: ``"rng"`` (default) is the
    historical per-placement rng matrix — a function of the whole
    population, so it can only be materialized; ``"hash"`` draws the
    SAME structural policy's priorities from the stateless per-(file,
    node-name) hash of ``placement_fn.compute_placement``, making this
    call the materialized twin of the functional chooser (one
    implementation, two surfaces — the equivalence oracle of
    ``--placement functional``).
    """
    topology = topology or ClusterTopology()
    n = len(manifest)
    n_nodes = len(topology)

    # Manifest primary ids remap onto the topology via the shared
    # per-name LUT (placement_fn.primary_on_topology): O(vocabulary),
    # stable-hash spread for unknown names.
    from ..placement_fn.compute import primary_on_topology

    primary = primary_on_topology(manifest.nodes,
                                  manifest.primary_node_id, topology)

    rf_want = np.asarray(rf_per_file, dtype=np.int32)
    n_capped = int((rf_want > n_nodes).sum())
    if n_capped:
        _RF_CAP_WARNING.warn(
            f"replication factor capped at the node count for "
            f"{n_capped} files (requested up to {int(rf_want.max())}, "
            f"topology has {n_nodes} nodes) — replicas are "
            f"distinct-per-node, so e.g. Archival rf=4 on a 3-node "
            f"topology places 3")
        from ..obs import current as _obs_current

        tel = _obs_current()
        if tel is not None:
            tel.counter_inc("placement.rf_capped", n_capped)
    rf = np.minimum(rf_want, n_nodes)
    rf = np.maximum(rf, 1)
    max_rf = int(rf.max())

    if method == "hash":
        from ..placement_fn.compute import compute_placement

        replica_map, rf = compute_placement(
            np.arange(n, dtype=np.int64), rf, primary, topology,
            0 if seed is None else int(seed), local_mask=local_mask)
        result = PlacementResult(replica_map=replica_map, rf=rf,
                                 topology=topology)
        result.compute_storage(manifest.size_bytes if size_bytes is None
                               else size_bytes)
        return result
    if method != "rng":
        raise ValueError(f"unknown placement method {method!r} "
                         f"(want 'rng' or 'hash')")

    rng = np.random.default_rng(seed)
    # Random priorities per (file, node); the sort key starts as the raw
    # priorities and gets the structured slots forced to the front.
    prio = rng.random((n, n_nodes))
    if topology.n_levels > 0:
        # Geo-hierarchical topology: the SAME greedy highest-level-first
        # policy as the hash chooser (placement_fn.hierarchical_fill —
        # one structural policy, two priority sources), fed rng-packed
        # priorities.  One-level topologies never reach here: the legacy
        # path below stays bit-for-bit.
        from ..placement_fn.compute import (
            PRIO_MAX,
            clip_shards_for_locality,
            hierarchical_fill,
        )

        if n_nodes > 63:
            raise ValueError(
                f"hierarchical placement supports up to 63 nodes "
                f"(6-bit packed node ids), got {n_nodes}")
        rf = clip_shards_for_locality(rf, primary, topology, local_mask)
        max_rf = int(rf.max()) if n else 1
        packed = ((prio * (1 << 26)).astype(np.uint32) << np.uint32(6)) \
            | np.arange(n_nodes, dtype=np.uint32)[None, :]
        w = np.ascontiguousarray(packed.T)
        cols = np.arange(n)
        replica_map = np.empty((n, max_rf), dtype=np.int32)
        replica_map[:, 0] = primary
        w[primary, cols] = PRIO_MAX
        if local_mask is not None:
            lc = np.asarray(local_mask, dtype=bool)
            if lc.any():
                dt = topology.top_domain_index()
                w[(dt[:, None] != dt[primary][None, :])
                  & lc[None, :]] = PRIO_MAX
        if max_rf >= 2:
            hierarchical_fill(w, replica_map, primary, max_rf, topology)
        mask = np.arange(max_rf)[None, :] < rf[:, None]
        replica_map[~mask] = -1
        result = PlacementResult(replica_map=replica_map, rf=rf,
                                 topology=topology)
        result.compute_storage(manifest.size_bytes if size_bytes is None
                               else size_bytes)
        return result
    key = prio.copy()
    key[np.arange(n), primary] = -3.0           # replica 0: the primary
    dom = topology.domain_index()
    if topology.n_domains > 1 and n_nodes > 1:
        # Remote domain per file: the domain of the best-priority node
        # OUTSIDE the primary's domain (a seeded random domain choice
        # weighted exactly like the node choice itself).
        same = dom[None, :] == dom[primary][:, None]       # (n, n_nodes)
        remote_prio = np.where(same, np.inf, prio)
        best_remote = np.argmin(remote_prio, axis=1)       # (n,)
        has_remote = np.isfinite(remote_prio[np.arange(n), best_remote])
        in_rdom = ((dom[None, :] == dom[best_remote][:, None])
                   & ~same & has_remote[:, None])
        # Replica 1 = best node of the remote domain, replica 2 = its
        # second-best (same remote rack, HDFS-style).  Everything else
        # keeps its raw priority — "rest on distinct nodes".
        masked = np.where(in_rdom, prio, np.inf)
        part = np.partition(masked, 1, axis=1)
        m1, m2 = part[:, 0], part[:, 1]
        key = np.where(np.isfinite(m1)[:, None] & (masked == m1[:, None]),
                       -2.0, key)
        key = np.where(np.isfinite(m2)[:, None] & (masked == m2[:, None]),
                       -1.0, key)
    order = np.argsort(key, axis=1).astype(np.int32)       # (n, n_nodes)

    replica_map = order[:, :max_rf].copy()
    mask = np.arange(max_rf)[None, :] < rf[:, None]
    replica_map[~mask] = -1

    result = PlacementResult(replica_map=replica_map, rf=rf,
                             topology=topology)
    result.compute_storage(manifest.size_bytes if size_bytes is None
                           else size_bytes)
    return result


def place_stripes(
    manifest: Manifest,
    shards_per_file: np.ndarray,
    topology: ClusterTopology | None = None,
    seed: int | None = 0,
    shard_bytes: np.ndarray | None = None,
    method: str = "rng",
    local_mask: np.ndarray | None = None,
) -> PlacementResult:
    """Vectorized stripe placement for storage strategies (cdrs_tpu/storage).

    An erasure-coded file's k+m shards want exactly what replicas want:
    distinct nodes, spread across failure domains (Ceph CRUSH places EC
    chunks with the same rule it places replicas) — so stripe placement
    IS ``place_replicas`` over the per-file shard count.  A replicate
    strategy's ``n_shards == rf``, so a config with only ``replicate``
    strategies degenerates bit-for-bit to today's placements.  The one
    difference is byte accounting: a slot of an EC file holds
    ``shard_bytes`` (~ size/k) rather than the full size, so
    ``storage_per_node`` is computed from ``shard_bytes`` when given.
    """
    return place_replicas(manifest, shards_per_file, topology, seed,
                          size_bytes=shard_bytes, method=method,
                          local_mask=local_mask)
