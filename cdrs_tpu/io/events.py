"""Struct-of-arrays event/manifest representation and CSV IO.

The reference's layer boundaries are files on disk: ``metadata.csv`` (manifest,
reference: src/generator.py:60-64) and ``access.log`` (CSV rows
``ts_iso,path,op,client,pid``, reference: src/access_simulator.py:61-63).
This module keeps those on-disk contracts but converts everything to dense
integer/float arrays at ingest — paths and client nodes are interned to int32
ids, timestamps become float64 epoch seconds — because that is the only
representation a TPU kernel can consume (SURVEY.md §7.2 "data representation").
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass, field
from datetime import datetime, timezone

import numpy as np

__all__ = ["Manifest", "EventLog", "parse_iso_ts", "client_vocabulary",
           "OP_READ", "OP_WRITE", "BINARY_MAGIC", "is_binary_log"]

OP_READ = np.int8(0)
OP_WRITE = np.int8(1)

#: Magic prefix of the binary columnar event log (.cdrsb).  The CSV
#: access.log stays the interchange contract (reference:
#: src/access_simulator.py:61-63); the binary format is the fast path for
#: billion-event feeds, where CSV parsing — not the device fold — was the
#: pipeline wall (VERDICT r4 #2: 437 s ingest+fold, >60% of it parsing).
BINARY_MAGIC = b"CDRSBEV1"


def is_binary_log(path) -> bool:
    """True when ``path`` starts with the binary event-log magic."""
    try:
        with open(path, "rb") as f:
            return f.read(len(BINARY_MAGIC)) == BINARY_MAGIC
    except OSError:
        return False


def client_vocabulary(manifest: "Manifest", extra_clients=()):
    """Shared client-id vocabulary: manifest nodes first (so ids align with
    ``primary_node_id`` — the locality comparison is id-based), then any extra
    simulator clients.  Returns (clients list, pool int32 array of the ids of
    ``extra_clients``)."""
    clients = list(manifest.nodes)
    for c in extra_clients:
        if c not in clients:
            clients.append(c)
    pool = np.asarray([clients.index(c) for c in extra_clients], dtype=np.int32)
    return clients, pool


def parse_iso_ts(s: str) -> float:
    """ISO-8601 (optionally ``Z``-suffixed, ms precision) -> epoch seconds (UTC).

    The reference emits ``%Y-%m-%dT%H:%M:%S.%f`` truncated to ms plus ``Z``
    (src/access_simulator.py:5-6) and parses with Spark ``to_timestamp``
    (src/compute_features.py:28-29).  We parse in pure Python, treating naive
    stamps as UTC.
    """
    s = s.strip()
    if s.endswith("Z"):
        s = s[:-1]
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


@dataclass
class Manifest:
    """Interned file population.

    Columns mirror metadata.csv (path, creation_ts, primary_node, size_bytes,
    category — reference: src/generator.py:47-53).
    """

    paths: list[str]
    creation_ts: np.ndarray          # (n,) float64 epoch seconds
    primary_node_id: np.ndarray      # (n,) int32, index into ``nodes``
    size_bytes: np.ndarray           # (n,) int64
    category: list[str]              # planted ground-truth, lowercase
    nodes: list[str]                 # node-id vocabulary
    path_to_id: dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        if not self.path_to_id:
            self.path_to_id = {p: i for i, p in enumerate(self.paths)}

    def __len__(self) -> int:
        return len(self.paths)

    #: Columns a manifest CSV cannot be read without.
    _REQUIRED_COLUMNS = ("path", "creation_ts", "primary_node")

    @classmethod
    def read_csv(cls, path: str) -> "Manifest":
        """Read metadata.csv; IO/shape failures raise ONE clean one-line
        error naming the path (the `cdrs metrics` error contract): a
        missing file stays FileNotFoundError, a header- or row-level
        defect (no header, missing required columns, unparseable
        timestamp/size) raises ValueError."""
        paths, creation, nodes_col, sizes, cats = [], [], [], [], []
        try:
            f = open(path, newline="")
        except FileNotFoundError:
            raise FileNotFoundError(
                f"missing manifest {path!r}: no such file") from None
        with f:
            reader = csv.DictReader(f)
            missing = [c for c in cls._REQUIRED_COLUMNS
                       if c not in (reader.fieldnames or ())]
            if missing:
                raise ValueError(
                    f"truncated/corrupt manifest {path!r}: "
                    + ("no header row" if not reader.fieldnames
                       else f"missing columns {missing}"))
            try:
                for row in reader:
                    paths.append(row["path"])
                    creation.append(parse_iso_ts(row["creation_ts"]))
                    nodes_col.append(row["primary_node"])
                    sizes.append(int(row.get("size_bytes", 0) or 0))
                    cats.append(row.get("category") or "moderate")
            except (KeyError, TypeError, ValueError, AttributeError) as e:
                raise ValueError(
                    f"truncated/corrupt manifest {path!r}: row "
                    f"{reader.line_num} unreadable "
                    f"({type(e).__name__}: {e})") from None
        node_vocab: dict[str, int] = {}
        node_ids = np.empty(len(nodes_col), dtype=np.int32)
        for i, nm in enumerate(nodes_col):
            node_ids[i] = node_vocab.setdefault(nm, len(node_vocab))
        return cls(
            paths=paths,
            # The reference truncates creation timestamps to whole seconds via
            # Spark unix_timestamp (src/compute_features.py:16-17).
            creation_ts=np.floor(np.asarray(creation, dtype=np.float64)),
            primary_node_id=node_ids,
            size_bytes=np.asarray(sizes, dtype=np.int64),
            category=cats,
            nodes=list(node_vocab),
        )

    def write_csv(self, path: str) -> None:
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["path", "creation_ts", "primary_node",
                        "size_bytes", "category"])
            for i, p in enumerate(self.paths):
                ts = datetime.fromtimestamp(float(self.creation_ts[i]), tz=timezone.utc)
                w.writerow([
                    p,
                    ts.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z",
                    self.nodes[int(self.primary_node_id[i])],
                    int(self.size_bytes[i]),
                    self.category[i],
                ])


@dataclass
class EventLog:
    """Access events as struct-of-arrays.

    ``path_id`` is -1 for events whose path is absent from the manifest; the
    feature kernels drop them, matching the reference's manifest-anchored left
    joins (src/compute_features.py:56-59).
    """

    ts: np.ndarray          # (e,) float64 epoch seconds (fractional)
    path_id: np.ndarray     # (e,) int32
    op: np.ndarray          # (e,) int8, OP_READ/OP_WRITE
    client_id: np.ndarray   # (e,) int32 into ``clients``
    clients: list[str]

    def __len__(self) -> int:
        return len(self.ts)

    @classmethod
    def read_csv(cls, path: str, manifest: Manifest,
                 native: bool | None = None) -> "EventLog":
        """Read the whole log as one EventLog.

        Uses the chunked C++ parser + native interning (runtime/native.py)
        when available — byte-exact with the Python path, ~10x+ faster on
        large logs; ``native=False`` forces pure Python, ``None``
        auto-detects.  Quoted CSVs fall back automatically.
        """
        batches = list(cls.read_csv_batches(path, manifest, batch_size=None,
                                            native=native))
        if not batches:
            return cls(
                ts=np.zeros(0), path_id=np.zeros(0, dtype=np.int32),
                op=np.zeros(0, dtype=np.int8),
                client_id=np.zeros(0, dtype=np.int32),
                clients=list(manifest.nodes),
            )
        return batches[0]  # batch_size=None yields exactly one batch

    @classmethod
    def concat(cls, parts: "list[EventLog]") -> "EventLog":
        """Concatenate batches into one EventLog.

        The client vocabulary grows monotonically across a batch stream
        (every reader's contract), so the LAST batch's vocabulary is the
        union and its ids are valid for every earlier batch.
        """
        if not parts:
            raise ValueError("concat needs at least one batch")
        if len(parts) == 1:
            return parts[0]
        return cls(
            ts=np.concatenate([b.ts for b in parts]),
            path_id=np.concatenate([b.path_id for b in parts]),
            op=np.concatenate([b.op for b in parts]),
            client_id=np.concatenate([b.client_id for b in parts]),
            clients=parts[-1].clients,
        )

    #: Rows per internal native chunk when reading "the whole file at once"
    #: (keeps the parse blobs bounded; output batches are concatenated).
    _NATIVE_CHUNK_ROWS = 4_000_000

    @classmethod
    def read_csv_batches(cls, path: str, manifest: Manifest,
                         batch_size: int | None = 1_000_000,
                         native: bool | None = None,
                         start_offset: int = 0,
                         with_offsets: bool = False):
        """Yield EventLog batches of up to ``batch_size`` rows (streaming IO;
        ``None`` = everything in one batch).

        The client vocabulary is threaded across batches (ids shared with the
        manifest's node vocabulary so the locality comparison
        client_node == primary_node works on ids); the whole log is never
        resident when a batch size is given.

        Ingestion is native by default (VERDICT r2 #4: chunked C++ parse +
        hash-map interning, no Python row loop); rows the native grammar
        cannot take (CSV quoting, malformed rows, exotic timestamps) hand
        over to the python csv parser from the exact byte offset reached.
        ``native=True`` raises when the library cannot be built (mirroring
        ``read_csv`` — a silent python fallback would run the 1B-event
        stream through a per-row loop).

        ``start_offset`` resumes the scan from a byte offset previously
        reported via ``with_offsets=True``, which changes the yield to
        ``(batch, next_offset)`` pairs — ``next_offset`` is the byte just
        past the batch's last row, valid as a later ``start_offset``, or
        None once the python fallback parser has taken over (csv.reader
        read-ahead makes mid-stream tells meaningless).  Both are the
        checkpoint/resume hooks of features/streaming.fold_stream.

        A file carrying the ``CDRSBEV1`` magic is read as the binary
        columnar log instead (``read_binary_batches`` — no parsing at
        all); every contract above holds, with offsets at block
        boundaries.
        """
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"missing event log {path!r}: no such file")
        if is_binary_log(path):
            # Binary columnar log: same yield contract, no parsing at all
            # (``native`` is irrelevant — the columns are read directly).
            gen = cls.read_binary_batches(path, manifest,
                                          batch_size=batch_size,
                                          start_offset=start_offset)
        else:
            if native is True:
                from ..runtime.native import native_available

                if not native_available():
                    raise RuntimeError(
                        "native log parser unavailable (library not built; "
                        "needs g++/make)")
            gen = cls._read_batches_impl(path, manifest, batch_size, native,
                                         start_offset)
        if batch_size is not None:
            if with_offsets:
                yield from gen
            else:
                yield from (b for b, _ in gen)
            return
        # batch_size=None contract: everything in ONE batch (the impl may
        # still chunk internally to bound the native parse blobs).  A single
        # whole-file batch has no meaningful resume offset — with_offsets
        # keeps the (batch, offset) shape but reports None.
        batches = [b for b, _ in gen]
        if not batches:
            return
        out = cls.concat(batches)
        yield (out, None) if with_offsets else out

    @classmethod
    def _read_batches_impl(cls, path: str, manifest: Manifest,
                           batch_size: int | None, native: bool | None,
                           start_offset: int = 0):
        """Raw (batch, next_offset|None) stream: native chunks, then python
        csv from the byte offset where (if anywhere) the native grammar gave
        up."""
        client_vocab: dict[str, int] = {nm: i for i, nm in enumerate(manifest.nodes)}
        clients = list(manifest.nodes)
        rows_per_chunk = batch_size or cls._NATIVE_CHUNK_ROWS

        offset = int(start_offset)
        if native is not False:
            from ..runtime.native import InternMap, native_available, \
                parse_log_chunk_native

            if native_available():
                path_map = InternMap(manifest.paths)
                client_map = InternMap(clients)
                while True:
                    chunk = parse_log_chunk_native(path, offset, rows_per_chunk)
                    if chunk is None:
                        break  # python csv takes over from `offset`
                    ts, op, pblob, poff, cblob, coff, nxt = chunk
                    if len(ts) == 0:
                        # rows==0 means EOF only when the scan actually
                        # reached the end of the file; a chunk can also
                        # legally parse zero rows (blank lines followed by a
                        # single row larger than the native blob caps) — in
                        # that case the remainder belongs to the python
                        # parser, not the bin (ADVICE r3).
                        import os

                        if nxt >= os.path.getsize(path):
                            return  # EOF
                        offset = nxt
                        break  # python csv takes over from `offset`
                    pid = path_map.lookup(pblob, poff)
                    # Unseen clients get the next ids (insertion order —
                    # identical vocabulary growth to the python csv path).
                    cid = client_map.insert_lookup(cblob, coff)
                    for s in client_map.names_from(len(clients)):
                        client_vocab[s] = len(clients)
                        clients.append(s)
                    yield cls(ts=ts, path_id=pid, op=op, client_id=cid,
                              clients=list(clients)), nxt
                    offset = nxt

        def flush(ts, pid, op, cid):
            return cls(
                ts=np.asarray(ts, dtype=np.float64),
                path_id=np.asarray(pid, dtype=np.int32),
                op=np.asarray(op, dtype=np.int8),
                client_id=np.asarray(cid, dtype=np.int32),
                clients=list(clients),
            )

        ts, pid, op, cid = [], [], [], []
        with open(path, newline="") as f:
            if offset:
                f.seek(offset)
            for row in csv.reader(f):
                if not row:
                    continue
                ts.append(parse_iso_ts(row[0]))
                pid.append(manifest.path_to_id.get(row[1], -1))
                op.append(1 if row[2] == "WRITE" else 0)
                c = row[3]
                if c not in client_vocab:
                    client_vocab[c] = len(clients)
                    clients.append(c)
                cid.append(client_vocab[c])
                if batch_size is not None and len(ts) >= batch_size:
                    yield flush(ts, pid, op, cid), None
                    ts, pid, op, cid = [], [], [], []
        if ts:
            yield flush(ts, pid, op, cid), None

    # -- binary columnar log (.cdrsb) ------------------------------------

    @staticmethod
    def _vocab_bytes(strings) -> tuple[bytes, bytes]:
        """(offsets int64[(n+1)] bytes, utf-8 blob) for a string table."""
        enc = [s.encode("utf-8") for s in strings]
        off = np.zeros(len(enc) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in enc], out=off[1:])
        return off.tobytes(), b"".join(enc)

    @staticmethod
    def _vocab_hash(coff: bytes, cblob: bytes, poff: bytes,
                    pblob: bytes) -> int:
        import hashlib

        h = hashlib.blake2b(digest_size=8)
        for part in (coff, cblob, poff, pblob):
            h.update(part)
        return int.from_bytes(h.digest(), "little")

    #: Rows per block ``write_binary`` splits large logs into.  Bounds the
    #: reader's per-block residency (8M rows = 136 MB of columns) so a
    #: billion-event log streams block by block — reads overlap the device
    #: fold instead of materializing 17 GB before the first batch.
    BINARY_BLOCK_ROWS = 8_388_608

    def write_binary(self, path: str, manifest: Manifest,
                     append: bool = False,
                     block_rows: int | None = None) -> int:
        """Write/append the binary columnar event log (.cdrsb).

        Layout (little-endian): ``CDRSBEV1`` magic, int64 n_clients /
        n_paths / vocab-hash, the client and path string tables
        (int64[(n+1)] offsets + utf-8 blob each), then blocks of
        ``[int64 count][f64 ts][i32 pid][i8 op][i32 cid]`` until EOF.
        ``pid`` indexes the embedded path table (= the manifest's path
        order); ``cid`` the embedded client table.  Rows with
        ``path_id == -1`` are skipped, like ``write_csv``.

        ``append=True`` adds blocks to an existing file after verifying
        the vocab hash (a mismatched population must fail loudly, not
        produce rows indexing the wrong table).  Returns rows written.
        Rows are split into blocks of ``block_rows`` (default
        ``BINARY_BLOCK_ROWS``) so readers stream with bounded memory.
        """
        coff, cblob = self._vocab_bytes(self.clients)
        poff, pblob = self._vocab_bytes(manifest.paths)
        vhash = self._vocab_hash(coff, cblob, poff, pblob)

        valid = self.path_id >= 0
        if valid.all():
            ts, pid, op, cid = self.ts, self.path_id, self.op, self.client_id
        else:
            ts, pid, op, cid = (self.ts[valid], self.path_id[valid],
                                self.op[valid], self.client_id[valid])

        header = (BINARY_MAGIC
                  + np.asarray([len(self.clients), len(manifest.paths)],
                               dtype=np.int64).tobytes()
                  + np.asarray([vhash], dtype=np.uint64).tobytes())
        if append and os.path.exists(path) and os.path.getsize(path):
            with open(path, "rb") as f:
                head = f.read(len(header))
            if head[:len(BINARY_MAGIC)] != BINARY_MAGIC:
                raise ValueError(f"{path!r} is not a binary event log")
            if head != header:
                raise ValueError(
                    f"{path!r} was written with a different client/path "
                    "vocabulary — appending would corrupt its id columns")
            mode = "ab"
            parts = []
        else:
            mode = "wb"
            parts = [header, coff, cblob, poff, pblob]
        n = int(len(ts))
        if block_rows is not None and int(block_rows) <= 0:
            raise ValueError(f"block_rows must be positive, got {block_rows}")
        step = int(block_rows) if block_rows else self.BINARY_BLOCK_ROWS
        with open(path, mode) as f:
            for p in parts:
                f.write(p)
            for lo in range(0, max(n, 1), step):
                hi = min(n, lo + step)
                f.write(np.asarray([hi - lo], dtype=np.int64).tobytes())
                np.ascontiguousarray(ts[lo:hi], dtype=np.float64).tofile(f)
                np.ascontiguousarray(pid[lo:hi], dtype=np.int32).tofile(f)
                np.ascontiguousarray(op[lo:hi], dtype=np.int8).tofile(f)
                np.ascontiguousarray(cid[lo:hi], dtype=np.int32).tofile(f)
        return n

    @classmethod
    def _binary_luts(cls, file_clients, file_paths, manifest: Manifest):
        """Remap tables from a binary log's embedded vocabularies onto the
        CALLER's manifest: ``(plut|None, clut, clients)``.  ``plut`` is
        None when the file's path table IS the manifest's (identity — the
        common same-population case); unknown clients extend the
        vocabulary past ``manifest.nodes`` in file order."""
        if file_paths == manifest.paths:
            plut = None
        else:
            plut = np.asarray(
                [manifest.path_to_id.get(p, -1) for p in file_paths],
                dtype=np.int32)
        clients = list(manifest.nodes)
        cvocab = {nm: i for i, nm in enumerate(clients)}
        clut = np.empty(len(file_clients), dtype=np.int32)
        for i, nm in enumerate(file_clients):
            if nm not in cvocab:
                cvocab[nm] = len(clients)
                clients.append(nm)
            clut[i] = cvocab[nm]
        return plut, clut, clients

    @staticmethod
    def _read_block(f, pos: int, size: int, path: str,
                    n_paths: int, n_clients: int):
        """Parse ONE block at byte ``pos`` (file cursor already there).

        Returns ``(ts, pid, op, cid, next_pos)`` with RAW (pre-LUT) id
        columns — ``ts`` is None for a legal empty block.  Raises the
        canonical truncated/corrupt ValueError when the block's bytes
        run past ``size`` or its ids fall outside the embedded tables.
        Shared by ``read_binary_batches`` and the daemon tailer (which
        treats the truncation case as "wait for more bytes" instead)."""
        head = np.fromfile(f, dtype=np.int64, count=1)
        bn = int(head[0]) if head.size == 1 else -1
        need = 8 + bn * (8 + 4 + 1 + 4)
        if bn < 0 or pos + need > size:
            raise ValueError(
                f"truncated/corrupt block at byte {pos} of {path!r}")
        if bn == 0:
            return None, None, None, None, pos + need
        ts = np.fromfile(f, dtype=np.float64, count=bn)
        pid = np.fromfile(f, dtype=np.int32, count=bn)
        op = np.fromfile(f, dtype=np.int8, count=bn)
        cid = np.fromfile(f, dtype=np.int32, count=bn)
        # Range-check BEFORE the LUT remap: out-of-range ids would wrap
        # via numpy negative indexing into silently wrong rows.
        if pid.size and (int(pid.min()) < 0 or int(pid.max()) >= n_paths):
            raise ValueError(
                f"truncated/corrupt block at byte {pos} of {path!r}: "
                f"path id outside [0, {n_paths})")
        if cid.size and (int(cid.min()) < 0
                         or int(cid.max()) >= n_clients):
            raise ValueError(
                f"truncated/corrupt block at byte {pos} of {path!r}: "
                f"client id outside [0, {n_clients})")
        return ts, pid, op, cid, pos + need

    @classmethod
    def _try_read_binary_header(cls, path: str):
        """Defensive header probe: ``(clients, paths, first_block_offset)``
        when the header + vocab tables are fully on disk, ``None`` when the
        file is a valid PREFIX still being written (the daemon tailer's
        wait-for-more signal), and a one-line ValueError naming the path
        when the bytes present cannot be a binary event log header.

        ``_read_binary_header`` trusts the file; this probe trusts nothing
        — every length is checked before parsing, so a torn header never
        surfaces as a numpy short-read artifact."""
        try:
            size = os.path.getsize(path)
        except OSError:
            raise FileNotFoundError(
                f"missing event log {path!r}: no such file") from None
        with open(path, "rb") as f:
            head = f.read(len(BINARY_MAGIC) + 24)
            if len(head) < len(BINARY_MAGIC):
                if not BINARY_MAGIC.startswith(head):
                    raise ValueError(
                        f"truncated/corrupt header of {path!r}: bad magic")
                return None
            if head[:len(BINARY_MAGIC)] != BINARY_MAGIC:
                raise ValueError(
                    f"truncated/corrupt header of {path!r}: bad magic")
            if len(head) < len(BINARY_MAGIC) + 24:
                return None
            n_clients, n_paths = (int(x) for x in np.frombuffer(
                head[len(BINARY_MAGIC):len(BINARY_MAGIC) + 16],
                dtype=np.int64))
            if n_clients < 0 or n_paths < 0:
                raise ValueError(
                    f"truncated/corrupt header of {path!r}: negative "
                    f"vocabulary size")

            def table(n):
                off_b = f.read(8 * (n + 1))
                if len(off_b) < 8 * (n + 1):
                    return None
                off = np.frombuffer(off_b, dtype=np.int64)
                if int(off[0]) != 0 or (np.diff(off) < 0).any():
                    raise ValueError(
                        f"truncated/corrupt header of {path!r}: "
                        f"non-monotonic string-table offsets")
                want = int(off[-1]) if n else 0
                blob = f.read(want)
                if len(blob) < want:
                    return None
                try:
                    return [blob[off[i]:off[i + 1]].decode("utf-8")
                            for i in range(n)]
                except UnicodeDecodeError:
                    raise ValueError(
                        f"truncated/corrupt header of {path!r}: "
                        f"undecodable string table") from None

            clients = table(n_clients)
            if clients is None:
                return None
            paths = table(n_paths)
            if paths is None:
                return None
            first_block = f.tell()
        if first_block > size:  # pragma: no cover - file shrank mid-probe
            return None
        return clients, paths, first_block

    @classmethod
    def _read_binary_header(cls, f):
        """Parse header + vocab tables; returns (clients, paths,
        first_block_offset)."""
        head = f.read(len(BINARY_MAGIC) + 24)
        if head[:len(BINARY_MAGIC)] != BINARY_MAGIC:
            raise ValueError("not a binary event log")
        n_clients, n_paths = np.frombuffer(
            head[len(BINARY_MAGIC):len(BINARY_MAGIC) + 16], dtype=np.int64)

        def table(n):
            off = np.fromfile(f, dtype=np.int64, count=n + 1)
            blob = f.read(int(off[-1]) if n else 0)
            return [blob[off[i]:off[i + 1]].decode("utf-8")
                    for i in range(n)]

        clients = table(int(n_clients))
        paths = table(int(n_paths))
        return clients, paths, f.tell()

    @classmethod
    def read_binary_batches(cls, path: str, manifest: Manifest,
                            batch_size: int | None = 1_000_000,
                            start_offset: int = 0):
        """Yield ``(EventLog, next_offset|None)`` from a .cdrsb log.

        ``pid``/``cid`` columns are remapped onto the CALLER's manifest:
        paths absent from it become -1 (the CSV reader's left-join
        semantics) and unknown clients extend the vocabulary past
        ``manifest.nodes`` in file order.  Ids are range-checked against
        the embedded string tables BEFORE the remap — a corrupt block
        whose ids are negative or past the table would otherwise wrap
        through the LUTs via numpy negative indexing into silently wrong
        rows (ADVICE r5); it raises the same corrupt-block ValueError as
        a truncated block.  Blocks larger than ``batch_size`` are sliced
        (zero-copy views); offsets are reported at block boundaries only
        (mid-block slices yield None), so any reported offset is a valid
        later ``start_offset``.  ``batch_size=None`` concatenates every
        block into ONE EventLog (the ``read_csv_batches`` whole-file
        contract), yielded with offset None.
        """
        probe = cls._try_read_binary_header(path)
        if probe is None:
            raise ValueError(
                f"truncated/corrupt header of {path!r}: file ends inside "
                f"the header/vocabulary tables")
        file_clients, file_paths, first_block = probe
        plut, clut, clients = cls._binary_luts(file_clients, file_paths,
                                               manifest)
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(first_block)
            pos = int(start_offset) if start_offset else first_block
            if pos < first_block or pos > size:
                raise ValueError(
                    f"start_offset {pos} outside the block region "
                    f"[{first_block}, {size}] of {path!r}")
            f.seek(pos)
            whole: list[EventLog] = []  # batch_size=None: accumulate blocks
            while pos < size:
                ts, pid, op, cid, pos = cls._read_block(
                    f, pos, size, path, len(file_paths), len(file_clients))
                if ts is None:
                    continue  # legal empty block (e.g. an empty final flush)
                bn = len(ts)
                if plut is not None:
                    pid = plut[pid]
                cid = clut[cid]
                if batch_size is None:
                    whole.append(cls(ts=ts, path_id=pid, op=op,
                                     client_id=cid, clients=list(clients)))
                    continue
                step = max(1, int(batch_size))
                for lo in range(0, bn, step):
                    hi = min(bn, lo + step)
                    yield cls(ts=ts[lo:hi], path_id=pid[lo:hi],
                              op=op[lo:hi], client_id=cid[lo:hi],
                              clients=list(clients)), \
                        (pos if hi == bn else None)
            if batch_size is None and whole:
                yield cls.concat(whole), None

    def write_csv(self, path: str, manifest: Manifest) -> None:
        """Emit the reference's access.log format (ts,path,op,client,pid).

        Events with ``path_id == -1`` (path unknown to the manifest) are
        skipped — their original path string was not retained at ingest.
        Uses the native writer when available and no string needs CSV
        quoting (~50x the python csv loop — the 1B-event feed is ~60 GB).
        """
        needs_quoting = any(
            any(ch in s for ch in (",", '"', "\n", "\r"))
            for s in (*manifest.paths, *self.clients))
        if not needs_quoting:
            from ..runtime.native import native_available, \
                write_access_log_native

            if native_available():
                valid = self.path_id >= 0
                if valid.all():
                    # No invalid rows (the overwhelmingly common case):
                    # skip the boolean-mask copies — 17 GB of temporaries
                    # at the 1B-event scale.
                    cols = (self.ts, self.path_id, self.op, self.client_id)
                else:
                    cols = (self.ts[valid], self.path_id[valid],
                            self.op[valid], self.client_id[valid])
                write_access_log_native(path, *cols,
                                        manifest.paths, self.clients)
                return
        with open(path, "w", newline="") as f:
            # "\n" terminator (csv default is "\r\n") — byte parity with the
            # native writer; both csv.reader and the native parser accept it.
            w = csv.writer(f, lineterminator="\n")
            out_i = 0   # EMITTED-row index: the native writer gets
            for i in range(len(self.ts)):   # pre-filtered arrays, so its
                if self.path_id[i] < 0:     # tag column counts valid rows
                    continue
                # Millisecond field computed exactly as the native writer
                # does — truncate (t - floor(t)) * 1000.0 with the same IEEE
                # double ops — so both writers emit byte-identical rows.
                t = float(self.ts[i])
                whole = int(np.floor(t))
                ms = min(int((t - whole) * 1000.0), 999)
                dt = datetime.fromtimestamp(whole, tz=timezone.utc)
                iso = dt.strftime("%Y-%m-%dT%H:%M:%S") + f".{ms:03d}Z"
                op = "WRITE" if self.op[i] else "READ"
                w.writerow([
                    iso, manifest.paths[int(self.path_id[i])], op,
                    self.clients[int(self.client_id[i])],
                    1000 + out_i % 9000,
                ])
                out_i += 1
