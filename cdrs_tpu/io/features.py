"""Feature-CSV ingestion for the decision layer.

Replicates the reference's input resolution (src/main.py:155-168): a directory
resolves to ``part-00000*.csv`` inside it (the Spark output convention), a glob
is expanded, and the first match is used.  Unlike the reference we warn when
extra matches are silently ignored (SURVEY.md §6.1.12).
"""

from __future__ import annotations

import glob
import os
import sys

import numpy as np
import pandas as pd

from ..config import CLUSTERING_FEATURES

__all__ = ["resolve_features_path", "load_feature_matrix"]


def resolve_features_path(input_path: str) -> str:
    if os.path.isdir(input_path):
        pattern = os.path.join(input_path, "part-00000*.csv")
        matches = sorted(glob.glob(pattern))
        if not matches:
            # Our own pipeline writes features.csv; accept any csv in the dir.
            matches = sorted(glob.glob(os.path.join(input_path, "*.csv")))
    else:
        matches = sorted(glob.glob(input_path))
    if not matches:
        raise FileNotFoundError(f"no features CSV matching {input_path!r}")
    if len(matches) > 1:
        print(f"warning: {len(matches)} feature files matched; using {matches[0]}",
              file=sys.stderr)
    return matches[0]


def load_feature_matrix(
    input_path: str,
    features: tuple[str, ...] = CLUSTERING_FEATURES,
    dtype=np.float64,
) -> tuple[np.ndarray, list[str]]:
    """(n, 5) matrix of the normalized clustering features + the path column
    (reference: src/main.py:75-81)."""
    path = resolve_features_path(input_path)
    df = pd.read_csv(path)
    missing = [f for f in features if f not in df.columns]
    if missing:
        raise ValueError(f"features CSV {path} missing columns: {missing}")
    X = df[list(features)].to_numpy(dtype=dtype)
    paths = df["path"].tolist() if "path" in df.columns \
        else [str(i) for i in range(len(df))]
    return X, paths
