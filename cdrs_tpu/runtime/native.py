"""ctypes bindings for the native runtime library (native/cdrs_native.cpp).

The compute path of this framework is JAX/XLA/Pallas; the *runtime* around it
(event generation, log ingest) has native C++ implementations here, mirroring
how the reference leans on the JVM/Spark for its data plane (SURVEY.md §2.4).

Everything degrades gracefully: ``load()`` returns None when the library is
absent and cannot be built (no g++), and every caller falls back to the
NumPy/pure-Python path.  The library is built lazily with ``make -C native``
on first use.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

import numpy as np

__all__ = ["load", "native_available", "simulate_events_native",
           "parse_access_log_native"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libcdrs_native.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False

_i64 = ctypes.c_int64
_u64 = ctypes.c_uint64
_f64 = ctypes.c_double
_p_f64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_p_i8 = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
_p_char = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _try_build() -> bool:
    if not os.path.isdir(_NATIVE_DIR) or shutil.which("make") is None:
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_LIB_PATH) and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None

        lib.sim_counts.restype = _i64
        lib.sim_counts.argtypes = [_i64, _p_f64, _p_f64, _f64, _u64, _p_i64]
        lib.sim_fill.restype = None
        lib.sim_fill.argtypes = [
            _i64, _p_i64, _p_f64, _p_f64, _p_f64, _p_i32, _p_i32, _i64,
            _f64, _f64, _u64, _i64, _p_f64, _p_i32, _p_i8, _p_i32,
        ]
        lib.log_scan.restype = _i64
        lib.log_scan.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(_i64), ctypes.POINTER(_i64)]
        lib.log_fill.restype = _i64
        lib.log_fill.argtypes = [
            ctypes.c_char_p, _i64, _i64, _i64, _p_f64, _p_i8,
            _p_char, _p_i64, _p_char, _p_i64,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load() is not None


def simulate_events_native(
    read_rate: np.ndarray,
    write_rate: np.ndarray,
    locality: np.ndarray,
    primary_node: np.ndarray,
    client_pool: np.ndarray,
    duration: float,
    sim_start: float,
    seed: int,
    n_threads: int = 0,
):
    """Threaded Poisson event generation.  Returns (ts, pid, op, client),
    globally time-sorted.  Raises RuntimeError when the library is missing."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++/make?)")
    if len(client_pool) == 0:
        raise ValueError("client_pool must be non-empty")
    n = len(read_rate)
    read_rate = np.ascontiguousarray(read_rate, dtype=np.float64)
    write_rate = np.ascontiguousarray(write_rate, dtype=np.float64)
    locality = np.ascontiguousarray(locality, dtype=np.float64)
    primary_node = np.ascontiguousarray(primary_node, dtype=np.int32)
    client_pool = np.ascontiguousarray(client_pool, dtype=np.int32)

    counts = np.empty(n, dtype=np.int64)
    total = int(lib.sim_counts(n, read_rate, write_rate, float(duration),
                               int(seed) & (2**64 - 1), counts))
    ts = np.empty(total, dtype=np.float64)
    pid = np.empty(total, dtype=np.int32)
    op = np.empty(total, dtype=np.int8)
    client = np.empty(total, dtype=np.int32)
    lib.sim_fill(n, counts, read_rate, write_rate, locality, primary_node,
                 client_pool, len(client_pool), float(duration),
                 float(sim_start), int(seed) & (2**64 - 1), int(n_threads),
                 ts, pid, op, client)
    return ts, pid, op, client


def parse_access_log_native(path: str):
    """Fast access.log parse.  Returns (ts, op, path_strs, client_strs) with
    paths/clients as Python string lists, or None when the native parser
    cannot handle the file (quoted CSV) or the library is unavailable."""
    lib = load()
    if lib is None:
        return None
    pb = _i64(0)
    cb = _i64(0)
    rows = int(lib.log_scan(path.encode(), ctypes.byref(pb), ctypes.byref(cb)))
    if rows < 0:
        return None  # IO error or quoted CSV -> python fallback
    ts = np.empty(rows, dtype=np.float64)
    op = np.empty(rows, dtype=np.int8)
    path_blob = np.empty(max(pb.value, 1), dtype=np.uint8)
    client_blob = np.empty(max(cb.value, 1), dtype=np.uint8)
    path_off = np.empty(rows + 1, dtype=np.int64)
    client_off = np.empty(rows + 1, dtype=np.int64)
    got = int(lib.log_fill(path.encode(), rows, int(pb.value), int(cb.value),
                           ts, op, path_blob, path_off,
                           client_blob, client_off))
    if got != rows or np.isnan(ts).any():
        # Re-read mismatch or a timestamp the native grammar rejects: let the
        # python csv path handle (and properly diagnose) the file.
        return None
    pbytes = path_blob.tobytes()
    cbytes = client_blob.tobytes()
    paths = [pbytes[path_off[i]:path_off[i + 1]].decode("utf-8", "replace")
             for i in range(rows)]
    clients = [cbytes[client_off[i]:client_off[i + 1]].decode("utf-8", "replace")
               for i in range(rows)]
    return ts, op, paths, clients
