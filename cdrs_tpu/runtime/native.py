"""ctypes bindings for the native runtime library (native/cdrs_native.cpp).

The compute path of this framework is JAX/XLA/Pallas; the *runtime* around it
(event generation, log ingest) has native C++ implementations here, mirroring
how the reference leans on the JVM/Spark for its data plane (SURVEY.md §2.4).

Everything degrades gracefully: ``load()`` returns None when the library is
absent and cannot be built (no g++), and every caller falls back to the
NumPy/pure-Python path.  The library is built lazily with ``make -C native``
on first use.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading

import numpy as np

__all__ = ["load", "native_available", "simulate_events_native",
           "parse_log_chunk_native", "write_access_log_native", "InternMap"]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libcdrs_native.so")

_lock = threading.Lock()
_lib = None
_load_attempted = False

_i64 = ctypes.c_int64
_u64 = ctypes.c_uint64
_f64 = ctypes.c_double
_p_f64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_p_i8 = np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS")
_p_char = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _try_build() -> bool:
    if not os.path.isdir(_NATIVE_DIR) or shutil.which("make") is None:
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_attempted
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_LIB_PATH) and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None

        lib.sim_counts.restype = _i64
        lib.sim_counts.argtypes = [_i64, _p_f64, _p_f64, _f64, _u64, _p_i64]
        lib.sim_fill.restype = None
        lib.sim_fill.argtypes = [
            _i64, _p_i64, _p_f64, _p_f64, _p_f64, _p_i32, _p_i32, _i64,
            _f64, _f64, _u64, _i64, _p_f64, _p_i32, _p_i8, _p_i32,
        ]
        lib.log_write.restype = _i64
        lib.log_write.argtypes = [
            ctypes.c_char_p, _i64, _p_f64, _p_i32, _p_i8, _p_i32,
            _p_char, _p_i64, _p_char, _p_i64, _i64,
        ]
        lib.log_fill_chunk.restype = _i64
        lib.log_fill_chunk.argtypes = [
            ctypes.c_char_p, _i64, _i64, _i64, _i64, _p_f64, _p_i8,
            _p_char, _p_i64, _p_char, _p_i64, ctypes.POINTER(_i64),
        ]
        lib.intern_build.restype = ctypes.c_void_p
        lib.intern_build.argtypes = [_p_char, _p_i64, _i64]
        lib.intern_free.restype = None
        lib.intern_free.argtypes = [ctypes.c_void_p]
        lib.intern_size.restype = _i64
        lib.intern_size.argtypes = [ctypes.c_void_p]
        lib.intern_lookup.restype = None
        lib.intern_lookup.argtypes = [
            ctypes.c_void_p, _p_char, _p_i64, _i64, _p_i32]
        lib.intern_insert_lookup.restype = _i64
        lib.intern_insert_lookup.argtypes = [
            ctypes.c_void_p, _p_char, _p_i64, _i64, _p_i32]
        lib.intern_export_bytes.restype = _i64
        lib.intern_export_bytes.argtypes = [ctypes.c_void_p, _i64]
        lib.intern_export.restype = None
        lib.intern_export.argtypes = [ctypes.c_void_p, _i64, _p_char, _p_i64]
        _lib = lib
        return _lib


def native_available() -> bool:
    return load() is not None


def simulate_events_native(
    read_rate: np.ndarray,
    write_rate: np.ndarray,
    locality: np.ndarray,
    primary_node: np.ndarray,
    client_pool: np.ndarray,
    duration: float,
    sim_start: float,
    seed: int,
    n_threads: int = 0,
):
    """Threaded Poisson event generation.  Returns (ts, pid, op, client),
    globally time-sorted.  Raises RuntimeError when the library is missing."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++/make?)")
    if len(client_pool) == 0:
        raise ValueError("client_pool must be non-empty")
    n = len(read_rate)
    read_rate = np.ascontiguousarray(read_rate, dtype=np.float64)
    write_rate = np.ascontiguousarray(write_rate, dtype=np.float64)
    locality = np.ascontiguousarray(locality, dtype=np.float64)
    primary_node = np.ascontiguousarray(primary_node, dtype=np.int32)
    client_pool = np.ascontiguousarray(client_pool, dtype=np.int32)

    counts = np.empty(n, dtype=np.int64)
    total = int(lib.sim_counts(n, read_rate, write_rate, float(duration),
                               int(seed) & (2**64 - 1), counts))
    ts = np.empty(total, dtype=np.float64)
    pid = np.empty(total, dtype=np.int32)
    op = np.empty(total, dtype=np.int8)
    client = np.empty(total, dtype=np.int32)
    lib.sim_fill(n, counts, read_rate, write_rate, locality, primary_node,
                 client_pool, len(client_pool), float(duration),
                 float(sim_start), int(seed) & (2**64 - 1), int(n_threads),
                 ts, pid, op, client)
    return ts, pid, op, client


def write_access_log_native(path: str, ts, pid, op, client,
                            paths, clients, append: bool = False) -> int:
    """Emit access.log rows (``iso_ts,path,op,client,pid``) at native speed.

    ``paths``/``clients`` are the string tables indexed by pid/client ids.
    Rows with pid < 0 are the caller's to filter (ids index the tables
    directly here).  Returns rows written."""
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++/make?)")
    pblob, poff = _strings_to_blob(paths)
    cblob, coff = _strings_to_blob(clients)
    if len(pblob) == 0:
        pblob = np.zeros(1, dtype=np.uint8)
    if len(cblob) == 0:
        cblob = np.zeros(1, dtype=np.uint8)
    n = len(ts)
    got = int(lib.log_write(
        path.encode(), n,
        np.ascontiguousarray(ts, dtype=np.float64),
        np.ascontiguousarray(pid, dtype=np.int32),
        np.ascontiguousarray(op, dtype=np.int8),
        np.ascontiguousarray(client, dtype=np.int32),
        pblob, poff, cblob, coff, 1 if append else 0))
    if got != n:
        raise IOError(f"log_write wrote {got} of {n} rows to {path}")
    return got


def _strings_to_blob(strings):
    """(uint8 blob, int64 offsets) encoding of a string list."""
    encoded = [s.encode("utf-8") for s in strings]
    off = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in encoded], out=off[1:])
    blob = np.frombuffer(b"".join(encoded), dtype=np.uint8).copy() \
        if encoded else np.zeros(0, dtype=np.uint8)
    return np.ascontiguousarray(blob), off


class InternMap:
    """Native string->id map (path/client interning without a Python loop).

    Ids are the positions of ``strings`` at construction.  ``lookup`` maps a
    (blob, offsets) batch of byte strings to int32 ids (-1 = absent).
    """

    def __init__(self, strings):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable (no g++/make?)")
        self._lib = lib
        blob, off = _strings_to_blob(strings)
        if len(blob) == 0:
            blob = np.zeros(1, dtype=np.uint8)  # non-null pointer
        self._handle = ctypes.c_void_p(
            lib.intern_build(blob, off, len(strings)))

    def lookup(self, blob: np.ndarray, off: np.ndarray) -> np.ndarray:
        n = len(off) - 1
        out = np.empty(n, dtype=np.int32)
        if len(blob) == 0:
            blob = np.zeros(1, dtype=np.uint8)
        self._lib.intern_lookup(self._handle, np.ascontiguousarray(blob),
                                np.ascontiguousarray(off), n, out)
        return out

    def insert_lookup(self, blob: np.ndarray, off: np.ndarray) -> np.ndarray:
        """Lookup that ASSIGNS the next id to unseen strings (growing
        vocabulary, insertion order) — new names are readable via
        ``names_from``."""
        n = len(off) - 1
        out = np.empty(n, dtype=np.int32)
        if len(blob) == 0:
            blob = np.zeros(1, dtype=np.uint8)
        self._lib.intern_insert_lookup(
            self._handle, np.ascontiguousarray(blob),
            np.ascontiguousarray(off), n, out)
        return out

    def __len__(self) -> int:
        return int(self._lib.intern_size(self._handle))

    def names_from(self, start: int) -> list[str]:
        """Names with id >= start, in id order."""
        count = len(self) - int(start)
        if count <= 0:
            return []
        nbytes = int(self._lib.intern_export_bytes(self._handle, int(start)))
        blob = np.empty(max(nbytes, 1), dtype=np.uint8)
        off = np.empty(count + 1, dtype=np.int64)
        self._lib.intern_export(self._handle, int(start), blob, off)
        raw = blob.tobytes()
        return [raw[off[i]:off[i + 1]].decode("utf-8", "replace")
                for i in range(count)]

    def __del__(self):  # pragma: no cover - interpreter-shutdown dependent
        h, lib = getattr(self, "_handle", None), getattr(self, "_lib", None)
        if h and lib is not None:
            lib.intern_free(h)


#: Blob bytes reserved per row in a chunk (paths and clients are far
#: shorter in practice; a longer row just ends the chunk early).
_CHUNK_BYTES_PER_ROW = 256


def parse_log_chunk_native(path: str, offset: int, max_rows: int):
    """Parse up to ``max_rows`` rows starting at byte ``offset``.

    Returns ``(ts, op, path_blob, path_off, client_blob, client_off,
    next_offset)`` — raw columnar output for InternMap lookups — or None
    when the chunk needs the python csv parser (quoting / malformed row /
    missing library), in which case the caller resumes from ``offset``.
    An empty chunk at EOF returns arrays of length 0.
    """
    lib = load()
    if lib is None:
        return None
    cap = max_rows * _CHUNK_BYTES_PER_ROW
    ts = np.empty(max_rows, dtype=np.float64)
    op = np.empty(max_rows, dtype=np.int8)
    path_blob = np.empty(cap, dtype=np.uint8)
    client_blob = np.empty(cap, dtype=np.uint8)
    path_off = np.empty(max_rows + 1, dtype=np.int64)
    client_off = np.empty(max_rows + 1, dtype=np.int64)
    nxt = _i64(0)
    rows = int(lib.log_fill_chunk(
        path.encode(), int(offset), int(max_rows), cap, cap,
        ts, op, path_blob, path_off, client_blob, client_off,
        ctypes.byref(nxt)))
    if rows < 0:
        return None  # quoting/malformed/IO: python fallback from `offset`
    if rows == 0 and int(nxt.value) == int(offset):
        sz = os.path.getsize(path)
        if offset < sz:
            # A single row larger than the whole chunk budget — pathological;
            # let the python parser take it from here.
            return None
    if rows and np.isnan(ts[:rows]).any():
        return None  # timestamp grammar the native parser rejects
    return (ts[:rows], op[:rows], path_blob[:path_off[rows]], path_off[:rows + 1],
            client_blob[:client_off[rows]], client_off[:rows + 1],
            int(nxt.value))
