"""clustering-driven-replication-strategy-tpu — TPU-native rebuild of
Harounnn/Clustering-Driven-Replication-Strategy.

A framework that synthesizes an HDFS-like file population and access workload,
extracts per-file access features, clusters files with KMeans++, and classifies
each cluster into a replication category (Hot/Shared/Moderate/Archival) —
re-designed for TPU: JAX/XLA kernels, jax.sharding meshes, Pallas distance
kernels, with a NumPy reference backend for behavioural parity.

Package map (SURVEY.md §7):
  config    — typed configuration for every stage
  sim       — population generator + Poisson access simulator (L1)
  features  — feature extraction backends (L2): numpy golden model, jax segment ops
  ops       — numerical kernels (L3): kmeans, scoring, distance, segment, quantile
  parallel  — mesh construction, shard_map kernels, collectives (multi-chip)
  models    — the flagship ReplicationPolicyModel + streaming variant (L4)
  io        — on-disk contracts (metadata.csv / access.log / features CSV)
  control   — online replication controller: windowed drift detection,
              incremental re-cluster, bounded-churn migration (L4+)
  compat    — drop-in reference API (kmeans(), ClusterClassifier)
  runtime   — native C++ runtime bindings (event generation, log parsing)
  cli       — the single `cdrs` CLI (L5)
"""

__version__ = "0.1.0"

from .config import (  # noqa: F401
    CATEGORIES,
    CLUSTERING_FEATURES,
    GeneratorConfig,
    KMeansConfig,
    PipelineConfig,
    ScoringConfig,
    SimulatorConfig,
)
