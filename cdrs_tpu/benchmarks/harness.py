"""Benchmark harness — the BASELINE.md workload configs.

The reference publishes no numbers (SURVEY.md §6); the targets come from
BASELINE.json: north-star metric is **Lloyd iterations/sec** (the reference's
hot loop #4, src/kmeans_plusplus.py:33), numpy-vs-jax on identical workloads.

Configs (BASELINE.md table):

  1: 10K files x 8 features,  k=10    — numpy CPU baseline scale
  2: 1M  files x 32 features, k=128   — single chip, in-HBM
  3: 10M files x 128 features, k=1024 — single chip, tiled assignment
  4: 100M files x 128 features, k=1024 — 8-chip data-parallel (needs a slice)
  5: streaming mini-batch off the simulator feed

Synthetic data is generated **on device** for the large configs (the host
never holds the matrix) as an isotropic Gaussian-blob mixture — the shape of
the feature matrix the pipeline's feature stage emits.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

__all__ = ["BenchConfig", "CONFIGS", "run_bench", "synth_blobs_np"]


@dataclass(frozen=True)
class BenchConfig:
    n: int
    d: int
    k: int
    backend: str
    iters: int = 20
    chunk_rows: int | None = None
    mesh_shape: tuple[tuple[str, int], ...] | None = None  # hashable dict items
    dtype: str = "float32"
    #: Lloyd assign+reduce strategy: "auto" | "matmul" | "scatter" | "pallas"
    #: (ops/kmeans_jax._assign_reduce; "auto" = pallas on TPU, matmul else).
    update: str = "auto"
    #: Lloyd budget for the --e2e time-to-categories run.  Decoupled from
    #: ``iters``: the iter/s metric wants windows long enough to amortize
    #: the tunnel's fixed per-call latency (thousands), while e2e is a
    #: one-shot wall-clock workload whose definition must stay stable
    #: across rounds.  None = use ``iters``.
    e2e_iters: int | None = None
    # numpy baseline is measured directly when n <= direct_np_limit, else on a
    # row subsample and extrapolated linearly in n (documented estimate).
    direct_np_limit: int = 2_000_000

    def mesh_dict(self) -> dict[str, int] | None:
        return dict(self.mesh_shape) if self.mesh_shape else None


CONFIGS: dict[int, BenchConfig] = {
    1: BenchConfig(n=10_000, d=8, k=10, backend="numpy", iters=10),
    # Long windows: one kmeans call carries ~60-100 ms of fixed dispatch +
    # host-fetch latency through the remote tunnel.  Window-length
    # convergence (100/300/1000/3000 iters: 1.19/0.86/0.62/0.55 ms/iter)
    # shows the fixed cost must be amortized below the percent level for
    # the metric to be the chip's rate rather than the tunnel's.
    2: BenchConfig(n=1_048_576, d=32, k=128, backend="jax", iters=2000,
                   e2e_iters=100),
    3: BenchConfig(n=10_485_760, d=128, k=1024, backend="jax", iters=50,
                   chunk_rows=131_072, e2e_iters=5),
    4: BenchConfig(n=104_857_600, d=128, k=1024, backend="jax", iters=50,
                   chunk_rows=131_072, mesh_shape=(("data", 8),),
                   e2e_iters=5),
    # 5 = streaming: n is the file population, iters the number of event
    # batches; see _bench_streaming (events/sec is the metric).
    5: BenchConfig(n=1_048_576, d=32, k=128, backend="jax", iters=10),
}

STREAM_BATCH_EVENTS = 1_048_576


def _synth_event_batch(rng, n_files, e, t0):
    """Vectorized synthetic event batch (time-ordered), numpy struct-of-arrays."""
    ts = t0 + np.sort(rng.random(e)) * 60.0
    return {
        "pid": rng.integers(0, n_files, size=e, dtype=np.int32),
        "ts": ts,
        "op": (rng.random(e) < 0.2).astype(np.int8),
        "client": rng.integers(0, 4, size=e, dtype=np.int32),
    }


def _numpy_stream_fold(batch, n_files, counters):
    """APPROXIMATE numpy fold (freq/writes + per-batch concurrency, no
    cross-batch carry) — reported for transparency; ``vs_baseline``
    compares against the exact numpy streaming backend
    (features/streaming_np), which computes what the device fold computes."""
    pid, ts, op, client = batch["pid"], batch["ts"], batch["op"], batch["client"]
    counters["freq"] += np.bincount(pid, minlength=n_files)
    counters["writes"] += np.bincount(pid, weights=(op == 1), minlength=n_files)
    sec = np.floor(ts).astype(np.int64)
    sec -= sec.min()
    key = pid.astype(np.int64) * (int(sec.max()) + 1) + sec
    uniq, cnt = np.unique(key, return_counts=True)
    np.maximum.at(counters["conc"], uniq // (int(sec.max()) + 1),
                  cnt.astype(np.float64))


def _bench_streaming(cfg: BenchConfig, seed: int,
                     mesh_shape: dict[str, int] | None = None) -> dict:
    """Events/sec through the device stream fold vs the numpy fold.

    ``mesh_shape={"data": N}`` runs the event-sharded fold (the v5e-8
    BASELINE config-5 scenario; features/streaming.py)."""
    import jax
    import jax.numpy as jnp

    from ..features.streaming import _build_update, _prep_batch
    from ..io.events import EventLog, Manifest

    n, e = cfg.n, STREAM_BATCH_EVENTS
    ndata = int((mesh_shape or {}).get("data", 1))
    requested = ndata
    if ndata > len(jax.devices()):
        # Largest available power of two — always divides the 2^20 batch.
        ndata = 1 << (len(jax.devices()).bit_length() - 1)
    rng = np.random.default_rng(seed)
    e_shard = e + ((-e) % ndata)  # padded like stream_update does

    manifest = Manifest(
        paths=[f"/f{i}" for i in range(n)],
        creation_ts=np.zeros(n),
        primary_node_id=rng.integers(0, 4, size=n, dtype=np.int32),
        size_bytes=np.ones(n, dtype=np.int64),
        category=["moderate"] * n, nodes=["dn1", "dn2", "dn3", "dn4"])

    def dev_state():
        z = jnp.zeros((n,), jnp.int32)
        return [z, z, z, z, jnp.full((n,), -1, jnp.int32), z]

    batches = [_synth_event_batch(rng, n, e, 1.7e9 + 60.0 * i)
               for i in range(cfg.iters)]
    logs = [EventLog(ts=b["ts"], path_id=b["pid"], op=b["op"],
                     client_id=b["client"], clients=manifest.nodes)
            for b in batches]

    # The PRODUCTION prep (features/streaming._prep_batch) decides the wire
    # format and builds the columns — the bench measures the same kernel fed
    # the same encoding as the real pipeline.
    prepped = []
    sec_base = None
    for lg in logs:
        pb = _prep_batch(lg, manifest, sec_base=sec_base,
                         pad_target=e_shard, ndata=ndata)
        sec_base = pb.sec_base
        prepped.append(pb)
    wire = prepped[0].wire
    fn = _build_update(e_shard, n, ndata, wire)

    def dev_args(pb):
        if pb.wire == "packed":
            return (jnp.asarray(pb.pid), jnp.asarray(pb.sec),
                    jnp.asarray(np.int32(pb.sec0)))
        return (jnp.asarray(pb.pid), jnp.asarray(pb.sec),
                jnp.asarray(pb.flags))

    dev_batches = [dev_args(pb) for pb in prepped]
    # Force the staged host->device transfers to complete before the timed
    # loop: jnp.asarray is async, and on the tunnel backend a deferred ~5 MB
    # upload per batch would otherwise land inside the measurement (the
    # metric is the device fold rate; transfer-bound e2e is the 1B scenario).
    jax.block_until_ready(dev_batches)

    # warmup + timed pass
    st = dev_state()
    st = list(fn(*dev_batches[0], *st))
    np.asarray(st[0])
    st = dev_state()
    t0 = time.perf_counter()
    for db in dev_batches:
        st = list(fn(*db, *st))
    np.asarray(st[0])  # sync
    dev_eps = (cfg.iters * e) / (time.perf_counter() - t0)

    # Exact numpy streaming backend (features/streaming_np): the same
    # semantics as the device fold — this is the ``vs_baseline`` denominator.
    from ..features.streaming_np import stream_init_np, stream_update_np

    np_batches = max(2, cfg.iters // 4)
    st_np = stream_init_np(n)
    st_np = stream_update_np(st_np, logs[0], manifest)   # warmup
    t0 = time.perf_counter()
    for lg in logs[1:np_batches + 1]:
        st_np = stream_update_np(st_np, lg, manifest)
    np_exact_eps = (np_batches * e) / (time.perf_counter() - t0)

    counters = {"freq": np.zeros(n), "writes": np.zeros(n), "conc": np.zeros(n)}
    t0 = time.perf_counter()
    for b in batches[:np_batches]:
        _numpy_stream_fold(b, n, counters)
    np_approx_eps = (np_batches * e) / (time.perf_counter() - t0)

    suffix = f"_mesh{ndata}" if ndata > 1 else ""
    out = {
        "config": 5, "n": n, "d": cfg.d, "k": cfg.k,
        "batch_events": e, "batches": cfg.iters,
        "metric": f"stream_events_per_sec_n{n}_batch{e}{suffix}",
        "value": dev_eps,
        "unit": "event/s",
        "vs_baseline": dev_eps / np_exact_eps,
        "numpy_exact_events_per_sec": np_exact_eps,
        "numpy_approx_events_per_sec": np_approx_eps,
        "backend": "jax",
        "mesh_data": ndata,
        "wire": wire,
    }
    if ndata != requested:
        out["mesh_downscaled_to"] = {"data": ndata}
    return out


def _bench_e2e(cfg: BenchConfig, config_num: int, seed: int,
               mesh_shape: dict[str, int] | None, update: str) -> dict:
    """Wall-clock time-to-categories: device-resident features -> sharded
    KMeans -> sharded scoring -> host category table (VERDICT r2 #6 — the
    measurable stand-in for BASELINE config 4's "<60 s end-to-end").

    The feature matrix is synthesized on device (sharded over the mesh),
    clustered for exactly ``cfg.e2e_iters`` Lloyd iterations from a D² init,
    and classified with scatter-free bisection medians on TPU (psum'd when
    sharded; "auto" elsewhere); the clock stops when the per-cluster
    categories land on host.  The numpy baseline runs the same
    pipeline (same iteration budget, exact medians) on a row subsample and
    scales linearly.
    """
    import jax

    from ..config import ScoringConfig
    from ..ops.kmeans_jax import kmeans_jax_full
    from ..ops.scoring_jax import classify_jax

    n, d, k = cfg.n, cfg.d, cfg.k
    e2e_iters = cfg.e2e_iters if cfg.e2e_iters is not None else cfg.iters
    X = _synth_blobs_device(n, d, min(k, 64), seed, cfg.dtype, mesh_shape)
    X = jax.block_until_ready(X)
    # Scoring tables spanning the synthetic d features (the pipeline's real
    # tables cover its 5 features; the benchmark scores all d columns so the
    # median/score kernels carry the full width).
    feats = tuple(f"f{i}" for i in range(d))
    dirs = {"Hot": 1, "Shared": 1, "Moderate": 0, "Archival": -1}
    scoring = ScoringConfig(
        features=feats,
        global_medians={f: 0.5 for f in feats},
        weights={c: {f: 1.0 for f in feats} for c in dirs},
        directions={c: {f: v for f in feats} for c, v in dirs.items()},
        # On the chip the scatter-free bisect medians win at every e2e scale
        # (at 1M rows "auto" would pick the exact sort, ~0.45 s slower);
        # sharded meshes run the psum'd bisection.  Elsewhere (CPU e2e,
        # tests) keep auto — interpret-mode pallas would crawl.  Disclosed
        # in the result as ``median_method``.
        median_method=("bisect" if jax.default_backend() == "tpu"
                       else "auto"),
        compute_global_medians_from_data=True)

    def run_once(init_method):
        t0 = time.perf_counter()
        # block_scalars=False: no mid-pipeline sync — the scoring program
        # dispatches straight behind the Lloyd work, and the ONLY fetch is
        # the final categories (the quantity the clock is defined on).
        centroids, labels, it, _ = kmeans_jax_full(
            X, k, tol=0.0, seed=seed, max_iter=e2e_iters,
            mesh_shape=mesh_shape, dtype=np.dtype(cfg.dtype),
            chunk_rows=cfg.chunk_rows, update=update,
            init_method=init_method, block_scalars=False)
        winner, _, _ = classify_jax(X, labels, k, scoring,
                                    mesh_shape=mesh_shape)
        cats = np.asarray(winner)   # clock stops when categories hit host
        return time.perf_counter() - t0, int(it), cats

    # kmeans|| init: its cost does not grow with k (D² is k sequential
    # rounds — 7.7 s alone at k=1024 on v5e); fall back where its per-round
    # sample cannot fit the shard.
    try:
        run_once("kmeans||")        # compile pass
        init_method = "kmeans||"
    except ValueError:
        run_once("d2")
        init_method = "d2"
    secs, it, cats = run_once(init_method)

    # numpy baseline: same pipeline shape on a subsample, scaled in n.
    n_sub = min(n, 200_000)
    Xs = synth_blobs_np(n_sub, d, min(k, 64), seed)
    from ..ops.kmeans_np import lloyd_step
    from ..ops.scoring_np import classify as classify_np

    rng = np.random.default_rng(seed)
    c = _init_from_rows(Xs, k, seed)
    t0 = time.perf_counter()
    labels_np = None
    for _ in range(max(1, min(2, e2e_iters))):
        c, labels_np, _ = lloyd_step(Xs, c, rng)
    per_iter = (time.perf_counter() - t0) / max(1, min(2, e2e_iters))
    import dataclasses

    t0 = time.perf_counter()
    classify_np(Xs, labels_np, k,
                dataclasses.replace(scoring, median_method="sort"))
    np_score = time.perf_counter() - t0
    np_secs = (per_iter * e2e_iters + np_score) * (n / n_sub)

    return {
        "config": int(config_num),
        "e2e": True,
        "n": n, "d": d, "k": k,
        "metric": f"e2e_seconds_to_categories_n{n}_d{d}_k{k}",
        "value": secs,
        "unit": "s",
        "vs_baseline": np_secs / secs,   # >1 = faster than the numpy pipeline
        "lloyd_iters": it,
        "init_method": init_method,
        "median_method": scoring.median_method,
        "files_per_sec": n / secs,
        "categories_found": sorted(set(int(x) for x in cats)),
        "numpy_seconds_estimated": np_secs,
        "backend": "jax",
        "update": update,
        "dtype": cfg.dtype,
        "mesh": dict(mesh_shape or {}),
        "jax_devices": len(jax.devices()),
        "jax_platform": jax.devices()[0].platform,
    }


def synth_blobs_np(n: int, d: int, k_true: int, seed: int = 0) -> np.ndarray:
    """Host-side Gaussian blob mixture (small configs)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k_true, d)) * 3.0
    lab = rng.integers(0, k_true, size=n)
    return (centers[lab] + rng.normal(size=(n, d)) * 0.5).astype(np.float64)


def _synth_blobs_device(n, d, k_true, seed, dtype, mesh_shape):
    """On-device blob generation, sharded over the data axis when a mesh is
    given — the host never materializes the (n, d) matrix."""
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import DATA_AXIS, mesh_from_shape

    key = jax.random.PRNGKey(seed)

    def gen():
        ck, lk, nk = jax.random.split(key, 3)
        centers = jax.random.normal(ck, (k_true, d), dtype) * 3.0
        lab = jax.random.randint(lk, (n,), 0, k_true)
        noise = jax.random.normal(nk, (n, d), dtype) * 0.5
        return centers[lab] + noise

    if mesh_shape:
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = mesh_from_shape(mesh_shape)
        sharding = NamedSharding(mesh, P(DATA_AXIS, None))
        return jax.jit(gen, out_shardings=sharding)()
    return jax.jit(gen)()


def _init_from_rows(X, k: int, seed: int):
    """Random-row init shared by both timed paths (keeps timing init-free)."""
    rng = np.random.default_rng(seed)
    idx = np.sort(rng.choice(X.shape[0], size=k, replace=False))
    return np.asarray(X[idx])


def _time_numpy_lloyd(X: np.ndarray, k: int, init: np.ndarray, iters: int) -> float:
    """Seconds per Lloyd iteration for the numpy backend."""
    from ..ops.kmeans_np import lloyd_step

    rng = np.random.default_rng(0)
    c = init.copy()
    # warmup iteration (BLAS thread spin-up, cache effects)
    lloyd_step(X, c, rng)
    t0 = time.perf_counter()
    for _ in range(iters):
        c, _, _ = lloyd_step(X, c, rng)
    return (time.perf_counter() - t0) / iters


#: Subtraction-based init timings below this fraction of the baseline pass are
#: below the harness's measurement resolution and reported as None (VERDICT r2
#: weak #4: a clamped 0.0 read as "init is free").
INIT_TIMING_FLOOR_FRAC = 0.05


def _time_init(X, k: int, init: np.ndarray, mesh_shape, chunk_rows, dtype,
               method: str, update: str = "matmul") -> float | None:
    """Seconds for one D²/k-means|| init (compile excluded).

    Measured as (init + one assignment pass) minus an assignment-only run
    with fixed centroids — max_iter=0 skips the Lloyd loop in both.
    Returns None when the method can't run at this shape (kmeans|| per-round
    sample exceeding shard rows) or when the subtraction lands below the
    measurement floor (INIT_TIMING_FLOOR_FRAC of the baseline pass) — a
    near-zero difference is timing noise, not a free init.
    """
    import jax

    from ..ops.kmeans_jax import kmeans_jax_full

    kwargs = dict(tol=0.0, seed=0, max_iter=0, mesh_shape=mesh_shape,
                  dtype=dtype, chunk_rows=chunk_rows, update=update)
    init_dev = jax.block_until_ready(jax.device_put(np.asarray(init, dtype)))

    def timed(**kw):
        c, _, _, _ = kmeans_jax_full(X, k, **kwargs, **kw)  # compile/warmup
        np.asarray(c)
        t0 = time.perf_counter()
        c, _, _, _ = kmeans_jax_full(X, k, **kwargs, **kw)
        np.asarray(c)
        return time.perf_counter() - t0

    try:
        full = timed(init_method=method)
    except ValueError:
        return None
    base = timed(init_centroids=init_dev)
    diff = full - base
    if diff <= INIT_TIMING_FLOOR_FRAC * base:
        return None
    return diff


def _time_jax_lloyd(X, k: int, init: np.ndarray, iters: int,
                    mesh_shape, chunk_rows, dtype,
                    update: str = "matmul",
                    repeats: int = 5) -> tuple[float, list[float]]:
    """Seconds per Lloyd iteration for the jax backend (compile excluded).

    Times ``repeats`` independent windows of ``iters`` iterations each and
    returns (best window sec/iter, all window sec/iter).  Best-of-N because
    the noise on a remote-tunnel backend (dispatch jitter, competing tunnel
    traffic) is strictly additive — the fastest window is the closest
    observation of the chip's actual rate.  ``iters`` must be large enough
    to amortize the tunnel's fixed ~60-100 ms per-call latency (see the
    CONFIGS comment); with long windows the spread collapses to ~±2%.
    """
    import jax

    from ..ops.kmeans_jax import kmeans_jax_full

    # Stage the init on device outside the timed region — a numpy array here
    # costs a per-call host->device upload (fixed ~100+ ms on remote-tunnel
    # backends, polluting the steady-state iteration metric).
    init_dev = jax.block_until_ready(jax.device_put(np.asarray(init, dtype)))
    kwargs = dict(
        tol=0.0,  # never converge: run exactly max_iter iterations
        seed=0,
        init_centroids=init_dev,
        mesh_shape=mesh_shape,
        dtype=dtype,
        chunk_rows=chunk_rows,
        update=update,
        max_iter=iters,  # warmup must hit the SAME compiled program
    )
    # First call compiles (cached by shape/config in _build_kmeans).
    # kmeans_jax_full device_gets (it, shift) before returning — that host
    # fetch IS the sync; fetching centroids again here would add a second
    # ~25 ms tunnel round trip per window (~0.25 ms/iter of fake cost at
    # 100 iters).
    c, lab, it, _ = kmeans_jax_full(X, k, **kwargs)
    windows = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        c, lab, it, _ = kmeans_jax_full(X, k, **kwargs)
        windows.append((time.perf_counter() - t0) / iters)
        assert it == iters
    return min(windows), windows


def _quality_one(n_files: int, duration: float, seed: int,
                 backend: str = "numpy", init_method: str = "d2",
                 k: int = 8) -> dict:
    from ..config import (GeneratorConfig, KMeansConfig, PipelineConfig,
                          SimulatorConfig, validated_scoring_config)
    from ..pipeline import run_pipeline

    result = run_pipeline(PipelineConfig(
        backend=backend,
        generator=GeneratorConfig(n_files=n_files, seed=seed),
        simulator=SimulatorConfig(duration_seconds=duration, seed=seed + 1),
        kmeans=KMeansConfig(k=k, seed=42, init_method=init_method),
        scoring=validated_scoring_config(),
        evaluate=True,
    ))
    ev = result.evaluation
    return {
        "n_files": n_files,
        "planted_accuracy": result.planted_accuracy,
        "read_locality_policy": ev["policy"]["read_locality"],
        "read_locality_uniform1": ev["uniform_1"]["read_locality"],
        "read_locality_gain": (ev["policy"]["read_locality"]
                               - ev["uniform_1"]["read_locality"]),
        "storage_vs_uniform1": (ev["policy"]["total_storage_bytes"]
                                / ev["uniform_1"]["total_storage_bytes"]),
    }


def decision_quality_metrics(seed: int = 21) -> dict:
    """Decision quality as tracked bench numbers (VERDICT r2 next #1).

    Runs three deterministic seeded workloads (300 files/300 s, 2000
    files/600 s, 100K files/600 s) through the standard pipeline
    (pipeline.run_pipeline, evaluate=True) with the validated scoring
    tables and reports planted-category recovery plus the read-locality
    gain over the reference's uniform rf=1.  The small workload's numbers
    are the fields tests/test_cluster.py asserts lower bounds on; the
    larger ones guard against the tables being tuned to one tiny scenario
    (VERDICT r4 #10: 100K recorded 0.832 accuracy / +0.133 locality —
    within a point of the toy scales).  Deterministic, ~25 s total.
    """
    out = _quality_one(300, 300.0, seed)
    out["at_2000_files"] = _quality_one(2000, 600.0, seed + 100)
    out["at_100000_files"] = _quality_one(100_000, 600.0, seed)
    return out


def run_bench(config: int = 2, backend: str | None = None,
              seed: int = 0, mesh_shape: dict[str, int] | None = None,
              update: str | None = None, quality: bool = True,
              e2e: bool = False, dtype: str | None = None) -> dict:
    """Run one BASELINE config; returns the bench JSON dict.

    ``vs_baseline`` is jax-iterations/sec over numpy-iterations/sec on the
    same workload (>= 1 means faster than the reference-style numpy path).
    For configs past ``direct_np_limit`` rows the numpy time is measured on a
    row subsample and scaled linearly in n (the Lloyd step is O(n·k·d));
    the result notes this with ``numpy_estimated: true``.
    ``update`` overrides the config's Lloyd assign+reduce strategy
    ("auto" | "matmul" | "scatter" | "pallas"; "auto" resolves to the fused
    pallas kernel on TPU when its VMEM blocks fit, else matmul — the
    recorded ``update`` field is the resolved strategy).
    ``e2e`` switches the metric from Lloyd iterations/sec to wall-clock
    time-to-categories: sharded features -> kmeans -> sharded scoring ->
    host categories (the BASELINE config-4 "<60 s end-to-end" stand-in).
    """
    cfg = CONFIGS[int(config)]
    backend = backend or cfg.backend
    if dtype is not None:
        # Points dtype override (e.g. "bfloat16": halves the HBM stream the
        # Lloyd step is bound by; centroids/stats stay f32 — _stat_dtype).
        # Backend check first: a numpy run must not be told to flip x64.
        if backend == "numpy":
            raise ValueError("--dtype selects the jax points dtype; "
                             "not applicable to --backend numpy")
        if str(dtype) == "float64":
            import jax
            if not jax.config.jax_enable_x64:
                raise ValueError(
                    "--dtype float64 needs JAX_ENABLE_X64=1; without it jax "
                    "silently computes in float32 and the recorded dtype "
                    "would lie")
        import dataclasses as _dc
        cfg = _dc.replace(cfg, dtype=str(dtype))
    update_requested = update
    update = update or cfg.update
    if int(config) == 5:
        if backend != "jax":
            raise ValueError("config 5 (streaming) is a jax fold; "
                             "--backend numpy is not supported")
        if update_requested:
            raise ValueError("--update applies to the Lloyd configs, not the "
                             "streaming fold (config 5)")
        if dtype is not None:
            raise ValueError("--dtype applies to the Lloyd configs, not the "
                             "streaming fold (config 5)")
        result = _bench_streaming(cfg, seed, mesh_shape=mesh_shape)
        if quality:
            result["decision_quality"] = decision_quality_metrics()
        return result
    if backend == "numpy" and update_requested:
        raise ValueError("--update selects a jax assign+reduce strategy; "
                         "not applicable to --backend numpy")
    if e2e and backend != "jax":
        raise ValueError("--e2e measures the jax pipeline; "
                         "--backend numpy is not supported")
    quality_block = decision_quality_metrics() if quality else None

    result: dict = {}
    if not e2e:
        np_iters = max(2, min(3, cfg.iters))

        # The subsample guard applies regardless of backend — a direct numpy
        # measurement at 100M x 128 float64 would need ~107 GB of host RAM.
        if cfg.n <= cfg.direct_np_limit:
            X_np = synth_blobs_np(cfg.n, cfg.d, min(cfg.k, 64), seed)
            np_sub = X_np
            np_scale = 1.0
            numpy_estimated = False
        else:
            n_sub = cfg.direct_np_limit // 4
            X_np = None
            np_sub = synth_blobs_np(n_sub, cfg.d, min(cfg.k, 64), seed)
            np_scale = cfg.n / n_sub
            numpy_estimated = True

        init_np = _init_from_rows(np_sub, cfg.k, seed)
        np_sec = _time_numpy_lloyd(np_sub, cfg.k, init_np, np_iters) * np_scale
        np_ips = 1.0 / np_sec

        result = {
            "config": int(config),
            "n": cfg.n, "d": cfg.d, "k": cfg.k,
            "numpy_iters_per_sec": np_ips,
            "numpy_estimated": numpy_estimated,
        }

        if quality_block is not None:
            result["decision_quality"] = quality_block

    if backend == "numpy":
        result.update({
            "metric": f"lloyd_iters_per_sec_n{cfg.n}_d{cfg.d}_k{cfg.k}",
            "value": np_ips,
            "unit": "iter/s",
            "vs_baseline": 1.0,
            "backend": "numpy",
        })
        return result

    import jax

    mesh_shape = mesh_shape or cfg.mesh_dict()

    # HBM guard: the workload must fit the devices actually present (config 4
    # assumes 8 chips; on a 1-chip runner 100M x 128 f32 is 51 GB against
    # ~16 GB of HBM).  Scale n down by powers of two, keeping d/k/mesh — the
    # recorded metric name carries the true n and ``n_downscaled_from`` the
    # config's.
    # X is sharded over the data axis only (replicated across model shards),
    # so per-device bytes scale with the data axis — counting the model axis
    # here would under-estimate per-chip residency (ADVICE r3).
    ndev = max(1, min(int((mesh_shape or {}).get("data", 1)),
                      len(jax.devices())))
    # Per-chip budget for the points matrix: ~5 GiB of the v5e's 16 GiB —
    # the pallas path holds x AND its feature-major transpose, plus labels
    # and scan temporaries.
    hbm_budget = 5 * 2**30
    n_cfg = cfg.n
    n_run = n_cfg
    itemsize = np.dtype(cfg.dtype).itemsize
    while n_run > 1 and (n_run // ndev) * cfg.d * itemsize > hbm_budget:
        n_run //= 2
    if n_run != n_cfg:
        # round to a sharding/chunk-friendly multiple
        mult = max(int(cfg.chunk_rows or 1) * ndev, ndev)
        n_run = max(mult, (n_run // mult) * mult)
        import dataclasses as _dc

        cfg = _dc.replace(cfg, n=n_run)
        result["n_downscaled_from"] = n_cfg
        result["n"] = n_run
        if not e2e:
            # The numpy baseline was extrapolated to the config's n; rescale
            # to the n actually run (the Lloyd step is linear in n).
            np_ips = np_ips * (n_cfg / n_run)
            np_sec = 1.0 / np_ips
            result["numpy_iters_per_sec"] = np_ips
            result["numpy_estimated"] = True

    if mesh_shape:
        need = int(np.prod(list(mesh_shape.values())))
        if need > len(jax.devices()):
            # Scale the mesh down to what the host actually has (e.g. config 4
            # on a single-chip runner): the largest power of two <= device
            # count whose (data * chunk_rows) still divides the row count —
            # a raw device count like 3 or 6 would fail the sharding check.
            avail = len(jax.devices())
            ndata = 1 << (avail.bit_length() - 1)
            while ndata > 1 and cfg.n % (ndata * (cfg.chunk_rows or 1)):
                ndata //= 2
            mesh_shape = {"data": ndata}
            result["mesh_downscaled_to"] = mesh_shape

    # Resolve "auto" with the shape that will actually run (mesh model axis,
    # dtype, k, chunk) so the recorded ``update`` is the strategy executed —
    # and matches what kmeans_jax_full itself would resolve.
    from ..ops.kmeans_jax import resolve_update

    update = resolve_update(update,
                            nmodel=int((mesh_shape or {}).get("model", 1)),
                            dtype=cfg.dtype, k=cfg.k)

    if e2e:
        out = _bench_e2e(cfg, int(config), seed, mesh_shape, update)
        for key in ("mesh_downscaled_to", "n_downscaled_from"):
            if key in result:
                out[key] = result[key]
        if quality_block is not None:
            out["decision_quality"] = quality_block
        return out

    dtype = np.dtype(cfg.dtype)
    if X_np is not None:
        # Stage the matrix in HBM once, outside the timed region — the metric
        # is steady-state iteration rate, matching the numpy measurement
        # (whose data is already resident in RAM).
        from ..ops.kmeans_jax import padding_multiple

        multiple = padding_multiple(
            int((mesh_shape or {}).get("data", 1)), cfg.chunk_rows, update,
            k=cfg.k)
        if cfg.n % multiple == 0:
            if mesh_shape and mesh_shape.get("data", 1) > 1:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel.mesh import DATA_AXIS, mesh_from_shape

                sharding = NamedSharding(mesh_from_shape(mesh_shape),
                                         P(DATA_AXIS, None))
                X = jax.device_put(X_np.astype(dtype), sharding)
            else:
                X = jax.device_put(X_np.astype(dtype))
            X = jax.block_until_ready(X)
        else:
            X = X_np
        init = init_np  # numpy and jax timings start from identical centroids
    else:
        X = _synth_blobs_device(cfg.n, cfg.d, min(cfg.k, 64), seed, cfg.dtype,
                                mesh_shape)
        init = np.asarray(X[: cfg.k]).astype(dtype)

    jax_sec, windows = _time_jax_lloyd(X, cfg.k, init, cfg.iters, mesh_shape,
                                       cfg.chunk_rows, dtype, update)
    jax_ips = 1.0 / jax_sec
    # Disclosure: every timed window's rate (best is the headline; the spread
    # is the tunnel/dispatch noise, not kernel behavior).
    result["window_iters_per_sec"] = [1.0 / w for w in windows]
    result["window_iters_per_sec_median"] = float(
        1.0 / np.median(windows))

    # Init cost (SURVEY.md §7.4: the D² loop is k sequential rounds — the
    # north-star configs need to know whether it dominates, and what the
    # kmeans|| alternative buys).  None = not measurable (below the timing
    # floor) or not runnable at this shape; never reported as 0.0.
    for method, field in (("d2", "init_seconds_d2"),
                          ("kmeans||", "init_seconds_kmeans_par")):
        result[field] = _time_init(X, cfg.k, init, mesh_shape, cfg.chunk_rows,
                                   dtype, method, update)

    result.update({
        "metric": f"lloyd_iters_per_sec_n{cfg.n}_d{cfg.d}_k{cfg.k}",
        "value": jax_ips,
        "unit": "iter/s",
        "vs_baseline": jax_ips / np_ips,
        "backend": "jax",
        "update": update,
        "dtype": cfg.dtype,
        "jax_devices": len(jax.devices()),
        "jax_platform": jax.devices()[0].platform,
    })
    return result
