"""One-command benchmark sweep — reproduces the per-round summary artifact.

    python -m cdrs_tpu.benchmarks.summary --out data/bench_summary.json

Runs every BASELINE config through ``run_bench`` (iter/s + e2e), the
ingestion bench, and the bfloat16 capacity point, emitting the RAW
``run_bench``/``bench_ingest`` records under ``hardware / lloyd / e2e /
streaming / ingestion`` keys — the curated per-round
``data/bench_r*_summary.json`` files are hand-assembled views of one such
sweep (every number traceable to a record here).  Each step is
fault-isolated: a failing config records its error string instead of
aborting the sweep.  Runtime on the single tunnel chip: ~20-30 minutes,
dominated by the config-3/4 syntheses and the numpy baselines.
"""

from __future__ import annotations

import argparse
import json
import sys


def _step(out: dict, key: str, fn):
    try:
        out[key] = fn()
    except Exception as e:  # fault-isolate: record, keep sweeping
        out[key] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[summary] {key} FAILED: {e}", file=sys.stderr)


#: Telemetry overhead budget on the config-1-scale pipeline path
#: (ISSUE 2 acceptance: ≤ 5% wall-clock vs --metrics disabled).
TELEMETRY_OVERHEAD_BUDGET = 1.05


def telemetry_overhead(n_files: int = 10_000, duration: float = 120.0,
                       repeats: int = 15, runs_per_window: int = 2) -> dict:
    """Wall-clock cost of the telemetry layer on the config-1-scale
    pipeline path (numpy backend, 10K files): the full instrumented
    surface — stage spans, gauges, per-Lloyd-iteration convergence
    traces, the JSONL sink — against the identical run with telemetry
    off.  The two variants run *interleaved*; the headline ratio compares
    the best window of each side (the repo's standard methodology — noise
    on a shared single-core host is strictly additive, so the fastest
    window is the closest observation of the true cost) and every window
    plus the per-round paired ratios are disclosed so a reviewer sees the
    spread.  ``within_budget`` asserts the ≤ 5% acceptance bound.
    Recorded by the sweep, not CI-timed.
    """
    import os
    import tempfile
    import time

    from ..config import (GeneratorConfig, KMeansConfig, PipelineConfig,
                          SimulatorConfig, validated_scoring_config)
    from ..obs import JsonlSink, Telemetry
    from ..pipeline import run_pipeline

    cfg = PipelineConfig(
        backend="numpy",
        generator=GeneratorConfig(n_files=n_files, seed=5),
        simulator=SimulatorConfig(duration_seconds=duration, seed=6),
        kmeans=KMeansConfig(k=8, seed=42),
        scoring=validated_scoring_config(),
        evaluate=False,
    )

    def timed() -> float:
        # One window = several back-to-back runs: a single ~0.2 s run is
        # smaller than this class of host's scheduling jitter.
        t0 = time.perf_counter()
        for _ in range(max(1, runs_per_window)):
            run_pipeline(cfg)
        return time.perf_counter() - t0

    timed()  # warmup (imports, BLAS spin-up) outside both measurements
    plain_windows: list[float] = []
    instr_windows: list[float] = []
    ratios: list[float] = []
    events = 0
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "telemetry.jsonl")
        for r in range(max(1, repeats)):
            # Paired, order-alternated rounds: machine drift on a shared
            # single-core host moves both sides of a pair together, so the
            # per-round ratio is robust where absolute windows are not.
            def instr() -> float:
                with Telemetry(JsonlSink(path)):
                    return timed()

            if r % 2 == 0:
                p, i = timed(), instr()
            else:
                i, p = instr(), timed()
            plain_windows.append(p)
            instr_windows.append(i)
            ratios.append(i / p)
        with open(path) as f:
            events = sum(1 for _ in f)
    ratios.sort()
    ratio = min(instr_windows) / min(plain_windows)
    return {
        "n_files": n_files,
        "plain_seconds": min(plain_windows),
        "telemetry_seconds": min(instr_windows),
        "plain_windows": plain_windows,
        "telemetry_windows": instr_windows,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": ratio,
        "events_emitted": events,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def telemetry_overhead_control(n_files: int = 20_000,
                               duration: float = 480.0,
                               window_seconds: float = 60.0,
                               repeats: int = 9) -> dict:
    """Wall-clock cost of telemetry + the decision-quality audit on the
    online controller path (ISSUE 3 acceptance: the PR-2 ≤ 5% budget must
    still hold with audit enabled).  Same interleaved paired methodology
    as :func:`telemetry_overhead`; the instrumented side runs the full
    surface a ``cdrs control --metrics`` run activates — window records
    through the sink, counters/histograms, and per-window audit events
    (silhouette/Davies-Bouldin, entropy/TV, byte cost, anomaly flags).
    Sized so windows carry real work (20K files: drift + re-cluster +
    placement replay per window): the telemetry/audit cost is a small
    per-window fixed term plus O(n·k) audit geometry — the same cost
    class as the drift detector the loop already pays — so a toy
    population would overstate the ratio by measuring mostly the fixed
    term."""
    import os
    import tempfile
    import time

    from ..config import (GeneratorConfig, KMeansConfig, SimulatorConfig,
                          validated_scoring_config)
    from ..control import ControllerConfig, ReplicationController
    from ..obs import JsonlSink, Telemetry
    from ..sim.access import simulate_access
    from ..sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=n_files, seed=7))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=8))
    cfg = ControllerConfig(window_seconds=window_seconds,
                           kmeans=KMeansConfig(k=8, seed=42),
                           scoring=validated_scoring_config())

    def run_plain() -> float:
        t0 = time.perf_counter()
        ReplicationController(manifest, cfg).run(events)
        return time.perf_counter() - t0

    def run_instr(path: str) -> float:
        # Fresh stream per repeat: the sink appends, and the reported
        # audit_events_per_run must count ONE run, not the whole loop.
        if os.path.exists(path):
            os.remove(path)
        t0 = time.perf_counter()
        with Telemetry(JsonlSink(path)):
            ReplicationController(manifest, cfg).run(events,
                                                     metrics_path=path)
        return time.perf_counter() - t0

    run_plain()  # warmup
    plain_windows: list[float] = []
    instr_windows: list[float] = []
    ratios: list[float] = []
    audit_events = 0
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.jsonl")
        for r in range(max(1, repeats)):
            if r % 2 == 0:
                p, i = run_plain(), run_instr(path)
            else:
                i, p = run_instr(path), run_plain()
            plain_windows.append(p)
            instr_windows.append(i)
            ratios.append(i / p)
        from ..obs import read_events

        audit_events = sum(1 for e in read_events(path)
                           if e.get("kind") == "audit")
    ratios.sort()
    ratio = min(instr_windows) / min(plain_windows)
    return {
        "n_files": n_files,
        "windows_per_run": int(duration // window_seconds),
        "plain_seconds": min(plain_windows),
        "telemetry_audit_seconds": min(instr_windows),
        "plain_windows": plain_windows,
        "telemetry_windows": instr_windows,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": ratio,
        "audit_events_per_run": audit_events,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def xprof_overhead(n: int = 200_000, d: int = 16, k: int = 32,
                   calls: int = 12) -> dict:
    """Steady-state cost of the XLA cost capture (obs/xprof.py) on the jax
    kmeans path: telemetry-on calls route through the cached AOT
    executable (Python dispatch) instead of jit's C++ fast path, so the
    per-call overhead is a fixed dispatch delta — measured here against a
    workload sized so kernels, not dispatch, dominate (the capture itself
    — one extra lower/compile + one synced call — happens once per program
    signature and is reported separately, not amortized in)."""
    import time

    import numpy as np

    from ..obs import Telemetry
    from ..ops.kmeans_jax import kmeans_jax_full

    rng = np.random.default_rng(11)
    X = rng.normal(size=(n, d)).astype(np.float32)

    def one_call() -> float:
        t0 = time.perf_counter()
        kmeans_jax_full(X, k, seed=0, max_iter=5)
        return time.perf_counter() - t0

    kmeans_jax_full(X, k, seed=0, max_iter=5)  # compile outside both sides
    tel = Telemetry(kmeans_trace=False)  # isolate xprof: no traced program
    with tel:
        capture_seconds = one_call()  # AOT capture: lower+compile+sync
    plain_times: list[float] = []
    instr_times: list[float] = []
    # Interleaved pairs: host drift moves both sides of a pair together
    # (the repo's standard methodology) — on ~1 s CPU calls machine noise
    # is ~10%, far above the dispatch delta being measured.
    for r in range(max(1, calls)):
        if r % 2 == 0:
            plain_times.append(one_call())
            with tel:
                instr_times.append(one_call())
        else:
            with tel:
                instr_times.append(one_call())
            plain_times.append(one_call())
    ratio = min(instr_times) / min(plain_times)
    return {
        "n": n, "d": d, "k": k,
        "plain_seconds_per_call": min(plain_times),
        "xprof_seconds_per_call": min(instr_times),
        "plain_calls": plain_times,
        "xprof_calls": instr_times,
        "capture_seconds_one_time": capture_seconds,
        "overhead_ratio": ratio,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def run_summary(quality: bool = True) -> dict:
    import jax

    from .harness import run_bench

    out: dict = {
        "hardware": {
            "jax_devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
        },
        "lloyd": {},
        "e2e": {},
    }

    # quality once (embedded in the config-2 record; ~2 pipeline runs)
    _step(out["lloyd"], "config1",
          lambda: run_bench(config=1, quality=False))
    _step(out["lloyd"], "config2",
          lambda: run_bench(config=2, quality=quality))
    _step(out["lloyd"], "config2_matmul",
          lambda: run_bench(config=2, update="matmul", quality=False))
    _step(out["lloyd"], "config3",
          lambda: run_bench(config=3, quality=False))
    _step(out["lloyd"], "config4",
          lambda: run_bench(config=4, quality=False))
    _step(out["lloyd"], "config4_bf16",
          lambda: run_bench(config=4, dtype="bfloat16", quality=False))
    _step(out, "streaming",
          lambda: run_bench(config=5, quality=False))

    for cfg_num in (2, 3, 4):
        _step(out["e2e"], f"config{cfg_num}",
              lambda c=cfg_num: run_bench(config=c, e2e=True, quality=False))

    def ingest():
        from .ingest import bench_ingest
        return bench_ingest()

    _step(out, "ingestion", ingest)
    if quality:
        # Rides the quality flag: like the decision-quality runs these are
        # real workloads (~10-60 s), skipped by --no_quality sweeps.
        _step(out, "telemetry_overhead", telemetry_overhead)
        _step(out, "telemetry_overhead_control", telemetry_overhead_control)
        _step(out, "xprof_overhead", xprof_overhead)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None, metavar="JSON",
                   help="write the summary here (default: stdout only)")
    p.add_argument("--no_quality", action="store_true",
                   help="skip the decision-quality pipeline runs")
    args = p.parse_args(argv)

    out = run_summary(quality=not args.no_quality)
    text = json.dumps(out, indent=2, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"[summary] wrote {args.out}", file=sys.stderr)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
