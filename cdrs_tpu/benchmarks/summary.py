"""One-command benchmark sweep — reproduces the per-round summary artifact.

    python -m cdrs_tpu.benchmarks.summary --out data/bench_summary.json

Runs every BASELINE config through ``run_bench`` (iter/s + e2e), the
ingestion bench, and the bfloat16 capacity point, emitting the RAW
``run_bench``/``bench_ingest`` records under ``hardware / lloyd / e2e /
streaming / ingestion`` keys — the curated per-round
``data/bench_r*_summary.json`` files are hand-assembled views of one such
sweep (every number traceable to a record here).  Each step is
fault-isolated: a failing config records its error string instead of
aborting the sweep.  Runtime on the single tunnel chip: ~20-30 minutes,
dominated by the config-3/4 syntheses and the numpy baselines.
"""

from __future__ import annotations

import argparse
import json
import sys


def _step(out: dict, key: str, fn):
    try:
        out[key] = fn()
    except Exception as e:  # fault-isolate: record, keep sweeping
        out[key] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[summary] {key} FAILED: {e}", file=sys.stderr)


def run_summary(quality: bool = True) -> dict:
    import jax

    from .harness import run_bench

    out: dict = {
        "hardware": {
            "jax_devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
        },
        "lloyd": {},
        "e2e": {},
    }

    # quality once (embedded in the config-2 record; ~2 pipeline runs)
    _step(out["lloyd"], "config1",
          lambda: run_bench(config=1, quality=False))
    _step(out["lloyd"], "config2",
          lambda: run_bench(config=2, quality=quality))
    _step(out["lloyd"], "config2_matmul",
          lambda: run_bench(config=2, update="matmul", quality=False))
    _step(out["lloyd"], "config3",
          lambda: run_bench(config=3, quality=False))
    _step(out["lloyd"], "config4",
          lambda: run_bench(config=4, quality=False))
    _step(out["lloyd"], "config4_bf16",
          lambda: run_bench(config=4, dtype="bfloat16", quality=False))
    _step(out, "streaming",
          lambda: run_bench(config=5, quality=False))

    for cfg_num in (2, 3, 4):
        _step(out["e2e"], f"config{cfg_num}",
              lambda c=cfg_num: run_bench(config=c, e2e=True, quality=False))

    def ingest():
        from .ingest import bench_ingest
        return bench_ingest()

    _step(out, "ingestion", ingest)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None, metavar="JSON",
                   help="write the summary here (default: stdout only)")
    p.add_argument("--no_quality", action="store_true",
                   help="skip the decision-quality pipeline runs")
    args = p.parse_args(argv)

    out = run_summary(quality=not args.no_quality)
    text = json.dumps(out, indent=2, default=str)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"[summary] wrote {args.out}", file=sys.stderr)
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
