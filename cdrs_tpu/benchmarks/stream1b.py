"""The 1B-event streaming scenario, end to end on real IO (BASELINE config 5).

``python -m cdrs_tpu.benchmarks.stream1b --events 1e9`` runs the whole
data plane at the BASELINE.json scale, nothing synthetic-in-memory about it:

1. generate a manifest (default 1M files),
2. simulate the access stream with the threaded C++ engine,
3. write the reference-format ``access.log`` with the native writer
   (~60 GB at 1B rows),
4. ingest it back through the chunked native parser + interning,
5. fold every batch into the device feature state (features/streaming),
6. finalize the feature table.

Prints one JSON line with per-stage seconds/rates and the end-to-end
events/sec.  The log is written to --workdir (default: a temp dir, deleted
afterwards) — budget ~65 GB of disk for the full run.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time

import numpy as np

__all__ = ["run_stream1b"]


def run_stream1b(events: int = 1_000_000_000, n_files: int = 1_000_000,
                 batch_size: int = 4_000_000, seed: int = 0,
                 workdir: str | None = None, keep_log: bool = False,
                 base_dir: str = "/user/root/synth",
                 log_format: str = "csv") -> dict:
    from ..config import GeneratorConfig, SimulatorConfig
    from ..features.streaming import fold_stream, stream_finalize
    from ..sim.access import simulate_access
    from ..sim.generator import generate_population

    if log_format not in ("csv", "binary"):
        raise ValueError(f"log_format must be 'csv' or 'binary', "
                         f"got {log_format!r}")
    td = workdir or tempfile.mkdtemp(prefix="cdrs_stream1b_")
    os.makedirs(td, exist_ok=True)
    log = os.path.join(
        td, "access.cdrsb" if log_format == "binary" else "access.log")
    out: dict = {"events_requested": int(events), "n_files": int(n_files),
                 "batch_size": int(batch_size), "log_format": log_format}
    if keep_log:
        out["log_path"] = log  # a kept ~60 GB file must be findable
    try:
        t0 = time.perf_counter()
        manifest = generate_population(GeneratorConfig(
            n_files=n_files, seed=seed, base_dir=base_dir))
        out["gen_seconds"] = time.perf_counter() - t0

        # Size the simulated window so the expected event count hits the
        # target: rates are per-second per file.
        probe = simulate_access(manifest, SimulatorConfig(
            duration_seconds=60.0, seed=seed + 1), engine="native")
        rate = len(probe) / 60.0
        del probe
        duration = max(60.0, events / max(rate, 1.0))

        t0 = time.perf_counter()
        ev = simulate_access(manifest, SimulatorConfig(
            duration_seconds=duration, seed=seed + 1), engine="native")
        out["simulate_seconds"] = time.perf_counter() - t0
        out["events_simulated"] = len(ev)
        out["simulate_events_per_sec"] = len(ev) / out["simulate_seconds"]

        t0 = time.perf_counter()
        if log_format == "binary":
            ev.write_binary(log, manifest)
        else:
            ev.write_csv(log, manifest)
        out["write_seconds"] = time.perf_counter() - t0
        out["write_rows_per_sec"] = len(ev) / out["write_seconds"]
        out["log_bytes"] = os.path.getsize(log)
        n_events = len(ev)
        del ev  # the stream must not stay resident (that is the point)

        t0 = time.perf_counter()
        stats: dict = {}
        # Crash-safe by default: the hour-scale fold snapshots its state +
        # log offset beside the log; a rerun with the same workdir resumes.
        state = fold_stream(log, manifest, batch_size=batch_size,
                            stats=stats,
                            checkpoint_path=os.path.join(td, "stream.ckpt.npz"))
        table = stream_finalize(state, manifest)
        total = time.perf_counter() - t0
        out.update({
            # Busy times of the two pipelined halves: parse+prep runs on the
            # producer thread, transfer+fold on the main thread — wall time
            # is ~max of the two, not their sum (the overlap is the point).
            "ingest_parse_prep_seconds": stats.get("producer_seconds"),
            "ingest_parse_seconds": stats.get("parse_seconds"),
            "ingest_prep_seconds": stats.get("prep_seconds"),
            "fold_seconds": stats.get("fold_seconds"),
            "ingest_plus_fold_seconds": total,
            "ingest_events_per_sec": n_events / total,
            "end_to_end_seconds": (out["gen_seconds"]
                                   + out["simulate_seconds"]
                                   + out["write_seconds"] + total),
            "metric": f"stream1b_events_per_sec_n{n_files}_e{n_events}",
            "value": n_events / total,
            "unit": "event/s",
            "feature_rows": int(np.asarray(table.raw).shape[0]),
        })
        return out
    finally:
        if not keep_log and workdir is None:
            shutil.rmtree(td, ignore_errors=True)
        elif not keep_log:
            try:
                os.unlink(log)
            except OSError:
                pass


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--events", type=float, default=1e9)
    p.add_argument("--n_files", type=int, default=1_000_000)
    p.add_argument("--batch_size", type=int, default=4_000_000)
    p.add_argument("--workdir", default=None,
                   help="where the log lands (default: temp dir, deleted)")
    p.add_argument("--keep_log", action="store_true")
    p.add_argument("--base_dir", default="/user/root/synth",
                   help="manifest path prefix (shorter -> smaller log; the "
                        "1B-row log is ~73 GB at the default, ~62 GB at /s)")
    p.add_argument("--format", choices=["csv", "binary"], default="csv",
                   help="log format: 'csv' = the ~62-73 GB reference "
                        "contract; 'binary' = the ~17 GB columnar .cdrsb "
                        "fast path (VERDICT r4 #2)")
    args = p.parse_args()
    print(json.dumps(run_stream1b(
        events=int(args.events), n_files=args.n_files,
        batch_size=args.batch_size, workdir=args.workdir,
        keep_log=args.keep_log, base_dir=args.base_dir,
        log_format=args.format)))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
