"""Failure-space search effectiveness: guided vs blind, shrink quality.

The acceptance artifact of the coverage-guided scenario search
(``data/search_bench.json``), two claims:

**Guided beats blind** (``run_effectiveness``): on a fixed seed and a
MATCHED cell budget, the coverage-guided mutator (scenarios/search.py)
must reach a strictly larger coverage fingerprint than blind
``random_cell`` sampling — both start from the same base-preset
coverage, the blind arm gets exactly as many extra cells as the guided
arm actually ran.  Coverage is the harness fingerprint
(``coverage_bits``): fault kinds applied, durability tiers entered,
repair/detection branches taken, alerts fired, lineage causes, and the
invariant branches evaluated non-vacuously.

**Violations shrink to tiny repros** (``run_shrinker``): a planted
invariant violation with a known 2-event minimal cause (silent
corruption of one node's copies + decommission of the last clean
holder, padded with healing noise spans) must delta-debug down to a
<= 3-event repro (Yuan et al., OSDI 2014: nearly all catastrophic
failures reproduce with <= 3 input events) whose one-line repro command
reruns RED verbatim through the real CLI.

``python -m cdrs_tpu.benchmarks.search_bench`` writes the artifact and
appends round-19 rows to ``data/bench_history.jsonl``
(regress.append_history, deduped); ``--quick`` shrinks the budget for
the CI smoke step and never appends.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os

from ..scenarios.harness import run_cell
from ..scenarios.presets import random_cell
from ..scenarios.search import (
    SEARCH_BASE,
    planted_violation_spec,
    run_search,
    shrink_cell,
)

__all__ = ["run_effectiveness", "run_shrinker"]


def run_effectiveness(seed: int = 0, budget_cells: int = 60) -> dict:
    """Guided search vs blind random sampling at a matched cell budget
    (module docstring).  Deterministic in ``seed``."""
    guided = run_search(seed=seed, budget_cells=budget_cells,
                        bank=False, corpus_dir="", shrink=False)
    # The blind arm: same base coverage, plus exactly as many random
    # cells as the guided arm actually RAN (mutation draws that failed
    # validation cost the guided arm budget but no run — the comparison
    # charges both arms per executed cell).
    from ..scenarios.presets import preset
    from ..scenarios.search import _sanitize  # same base, same stripping

    base_union: set = set()
    for name in guided["base"]:
        base_union |= set(run_cell(_sanitize(preset(name),
                                             name=name))["coverage"])
    blind_bits = set(base_union)
    for i in range(guided["cells_run"]):
        blind_bits |= set(run_cell(random_cell(i, seed))["coverage"])
    baseline = len(base_union)
    blind_total = len(blind_bits)
    return {
        "seed": int(seed),
        "budget_cells": int(budget_cells),
        "cells_run": guided["cells_run"],
        "baseline_bits": baseline,
        "guided_bits": guided["coverage_bits"],
        "guided_new_cells": guided["new_coverage_cells"],
        "guided_violations": len(guided["violations"]),
        "blind_bits": blind_total,
        "advantage_bits": guided["coverage_bits"] - blind_total,
        "guided_exceeds_blind": guided["coverage_bits"] > blind_total,
        "seconds": guided["seconds"],
    }


def run_shrinker(seed: int = 0) -> dict:
    """Plant the known-minimal-cause violation, shrink it, and rerun the
    emitted repro line verbatim through the CLI (module docstring)."""
    from ..cli import main as cli_main

    spec = planted_violation_spec(seed)
    planted = run_cell(spec)
    sh = shrink_cell(spec)
    # The repro line is `python -m cdrs_tpu scenarios run --spec '...'`;
    # rerun its --spec payload through the real CLI entry point and
    # require the red exit the line promises.
    payload = sh["repro"].split("--spec ", 1)[1].strip().strip("'")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cli_main(["scenarios", "run", "--spec", payload])
    rerun = json.loads(buf.getvalue())
    return {
        "seed": int(seed),
        "planted_ok": planted["ok"],
        "planted_events": len((spec.faults or {}).get("specs") or ()),
        "planted_failed": sorted(k for k, v in
                                 planted["invariants"].items() if not v),
        "shrunk_events": sh["n_events"],
        "shrunk_schedule": sh["events"],
        "shrunk_failed": sh["failed"],
        "oracle_runs": sh["oracle_runs"],
        "repro": sh["repro"],
        "repro_exit_code": rc,
        "repro_failed": sorted(k for k, v in
                               rerun["invariants"].items() if not v),
        "repro_reruns_red": rc == 1 and not rerun["ok"],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/search_bench.json")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--budget-cells", type=int, default=60,
                   dest="budget_cells")
    p.add_argument("--round", type=int, default=19, dest="round_no",
                   help="PR-round stamp for the regress history")
    p.add_argument("--quick", action="store_true",
                   help="small budget for smoke runs (CI); never "
                        "appends to the history")
    from .regress import add_history_argument

    add_history_argument(p)
    args = p.parse_args(argv)

    budget = 12 if args.quick else args.budget_cells
    effectiveness = run_effectiveness(seed=args.seed, budget_cells=budget)
    shrinker = run_shrinker(seed=args.seed)

    out: dict = {
        "round": args.round_no,
        "effectiveness": effectiveness,
        "shrinker": shrinker,
    }
    out["criteria"] = {
        "guided_exceeds_blind": effectiveness["guided_exceeds_blind"],
        "new_coverage_found": effectiveness["guided_new_cells"] >= 1,
        "planted_violation_detected": not shrinker["planted_ok"],
        "shrunk_to_3_events_or_fewer": shrinker["shrunk_events"] <= 3,
        "shrunk_repro_reruns_red": shrinker["repro_reruns_red"],
    }
    out["bench_records"] = [
        {"metric": "search_coverage_bits",
         "value": float(effectiveness["guided_bits"]), "unit": "bits",
         "direction": "higher", "backend": "numpy"},
        {"metric": "search_coverage_advantage_bits",
         "value": float(effectiveness["advantage_bits"]), "unit": "bits",
         "direction": "higher", "backend": "numpy"},
        {"metric": "search_shrunk_repro_events",
         "value": float(shrinker["shrunk_events"]), "unit": "events",
         "direction": "lower", "backend": "numpy"},
    ]

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    appended = 0
    if not args.quick:
        from .regress import append_history, extract_records, \
            resolve_history_path

        history = resolve_history_path(args)
        if history:
            appended = append_history(
                history, extract_records(out,
                                         os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "guided_bits": effectiveness["guided_bits"],
                      "blind_bits": effectiveness["blind_bits"],
                      "shrunk_events": shrinker["shrunk_events"],
                      "history_appended": appended}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
