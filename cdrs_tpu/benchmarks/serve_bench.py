"""Serving-layer baselines: routed throughput, tail latency, hotspots.

The read-path counterpart of control_bench/chaos_bench.  Four scenario
families, one artifact (``data/serve_bench.json``):

**Batch routing throughput** (``run_throughput``): a config-2-scale
population (2^20 files) with a skewed synthetic read stream, routed in
one batch per policy.  The acceptance line: >= 1M simulated reads/sec
through the full router (selection + queue model + percentiles) with no
per-request Python.

**Chaos tail latency** (``run_chaos_p99``): 8 nodes in 4 racks serving a
zipf-skewed stream at meaningful utilization while a rack partitions and
a survivor straggles (service time x4) — the *Tail at Scale* scenario.
Every policy routes the SAME windows on the same seed; reported p99 per
policy must show power-of-two-choices beating random-replica (the
Mitzenmacher claim, measured, not assumed).

**Flash crowd** (``run_flash_crowd``): a transient read burst lands on a
cohort late in a controller run.  The CUMULATIVE feature fold dilutes
the burst, so the drift detector stays below threshold — the drift-only
controller never re-clusters.  The serve-enabled controller's hotspot
detector (EWMA spike over per-window counts) fires the window the burst
lands and triggers the re-cluster, with the ``hotspot_recluster`` audit
flag as the trail.  This is the acceptance demo: hotspot feedback
catches what feature drift cannot.

**Telemetry overhead** (``serve_overhead``): the standard interleaved
paired rounds with the SERVING instrumentation active — per-window
routing, latency hist_bulk, serve gauges — must stay <= 1.05x.

``bench_records`` in the artifact feed ``cdrs metrics regress``
(benchmarks/regress.py ``bench_records`` support) so the serving numbers
join the trajectory gate.

``python -m cdrs_tpu.benchmarks.serve_bench`` writes the artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

import numpy as np

from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..serve import POLICIES, ReadRouter, ServeConfig
from ..sim.access import simulate_flash_crowd
from ..sim.generator import generate_population

__all__ = ["run_throughput", "run_chaos_p99", "run_flash_crowd",
           "serve_overhead"]


def _skewed_reads(n_files: int, n_reads: int, n_nodes: int, *,
                  span_seconds: float, seed: int, skew: float = 3.0):
    """(ts, pid, client) of a time-sorted, popularity-skewed read stream:
    pid ~ floor(n · u^skew) concentrates traffic on low ids (a zipf-ish
    head) — the imbalance load-aware policies exist to absorb."""
    rng = np.random.default_rng(seed)
    ts = np.sort(rng.random(n_reads) * span_seconds)
    pid = (n_files * rng.random(n_reads) ** skew).astype(np.int32)
    client = rng.integers(0, n_nodes, n_reads).astype(np.int32)
    return ts, pid, client


def _uniform_placement(n_files: int, nodes: tuple[str, ...], rf: int,
                       seed: int = 0):
    """rf distinct replicas per file via place_replicas on a synthetic
    manifest (primary uniform over nodes)."""
    from ..cluster import ClusterTopology, place_replicas

    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=nodes))
    return manifest, place_replicas(
        manifest, np.full(n_files, rf, dtype=np.int32),
        ClusterTopology(nodes=nodes), seed=seed)


def run_throughput(n_files: int = 1 << 20, n_reads: int = 4_000_000,
                   n_nodes: int = 16, rf: int = 3,
                   seed: int = 21) -> dict:
    """Batch-mode routed reads/sec per policy at config-2 scale."""
    nodes = tuple(f"dn{i}" for i in range(1, n_nodes + 1))
    _, placement = _uniform_placement(n_files, nodes, rf, seed=seed)
    rm = placement.replica_map
    slot_ok = rm >= 0
    thr = np.ones(n_nodes)
    ts, pid, client = _skewed_reads(n_files, n_reads, n_nodes,
                                    span_seconds=60.0, seed=seed + 1)
    out: dict = {"n_files": n_files, "n_reads": n_reads,
                 "n_nodes": n_nodes, "rf": rf, "policies": {}}
    for policy in POLICIES:
        router = ReadRouter(n_nodes, ServeConfig(policy=policy, seed=seed))
        t0 = time.perf_counter()
        res = router.route(rm, slot_ok, thr, ts=ts, pid=pid, client=client,
                           window_seconds=60.0)
        dt = time.perf_counter() - t0
        out["policies"][policy] = {
            "reads_per_sec": round(n_reads / dt, 1),
            "seconds": round(dt, 4),
            "p50_ms": round(res.p50_ms, 4),
            "p99_ms": round(res.p99_ms, 4),
            "utilization_max": round(res.utilization_max, 4),
        }
    out["best_reads_per_sec"] = max(p["reads_per_sec"]
                                    for p in out["policies"].values())
    return out


_CHAOS_NODES = tuple(f"dn{i}" for i in range(1, 9))
_CHAOS_RACKS = "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6;r3=dn7,dn8"


def run_chaos_p99(n_files: int = 20_000, n_windows: int = 10,
                  window_seconds: float = 60.0,
                  reads_per_window: int = 150_000, rf: int = 3,
                  service_ms: float = 1.0, seed: int = 23) -> dict:
    """Per-policy p99 under a partition + straggler schedule.

    Rack r1 partitions over windows 3-5 (its replicas unreachable) and
    dn7 degrades to 0.4x throughput over windows 2-7 (service time
    x2.5) — under random-replica the straggler's arrival rate exceeds
    its degraded capacity and its queue grows linearly (the *Tail at
    Scale* pathology: p50 untouched, p99 explodes); p2c sees the queue
    through the load signal and routes around it.  Every policy routes
    the identical windows; per-policy p99 is over the merged latency
    samples of all windows."""
    from ..cluster import ClusterTopology, place_replicas
    from ..faults import FaultSchedule
    from ..faults.state import ClusterState

    topology = ClusterTopology.from_rack_spec(_CHAOS_NODES, _CHAOS_RACKS)
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_CHAOS_NODES))
    placement = place_replicas(
        manifest, np.full(n_files, rf, dtype=np.int32), topology, seed=0)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    schedule = FaultSchedule.from_specs([
        "partition:dn3+dn4@3-5",
        "degrade:dn7@2-7:0.4",
    ])
    n_nodes = len(_CHAOS_NODES)
    windows = []
    for w in range(n_windows):
        ts, pid, client = _skewed_reads(
            n_files, reads_per_window, n_nodes,
            span_seconds=window_seconds, seed=seed + 100 + w)
        windows.append((ts + w * window_seconds, pid, client))

    out: dict = {
        "n_files": n_files, "n_windows": n_windows,
        "reads_per_window": reads_per_window, "rf": rf,
        "service_ms": service_ms,
        "nodes": list(_CHAOS_NODES), "racks": _CHAOS_RACKS,
        "schedule": [e.spec() for e in schedule],
        "policies": {},
    }
    for policy in POLICIES:
        state = ClusterState(placement, sizes)
        router = ReadRouter(n_nodes, ServeConfig(
            policy=policy, seed=seed, service_ms=service_ms))
        samples: list[np.ndarray] = []
        unavail = 0
        per_window_p99 = []
        for w, (ts, pid, client) in enumerate(windows):
            for ev in schedule.for_window(w):
                state.apply_event(ev)
            res = router.route(
                state.replica_map, state.reachable_mask(),
                state.node_throughput, ts=ts, pid=pid, client=client,
                window_seconds=window_seconds,
                rng=np.random.default_rng([seed, w]))
            samples.append(res.latency_ms)
            unavail += res.n_unavailable
            per_window_p99.append(round(res.p99_ms, 4))
        lat = np.concatenate(samples)
        out["policies"][policy] = {
            "p50_ms": round(float(np.percentile(lat, 50)), 4),
            "p95_ms": round(float(np.percentile(lat, 95)), 4),
            "p99_ms": round(float(np.percentile(lat, 99)), 4),
            "per_window_p99_ms": per_window_p99,
            "reads_unavailable": int(unavail),
        }
    out["p2c_beats_random_p99"] = (out["policies"]["p2c"]["p99_ms"]
                                   < out["policies"]["random"]["p99_ms"])
    return out


_FLASH_NODES = ("dn1", "dn2", "dn3", "dn4", "dn5")


def run_flash_crowd(n_files: int = 400, seed: int = 29,
                    duration: float = 1800.0, n_windows: int = 15,
                    burst_windows: tuple[int, int] = (10, 10),
                    boost: float = 40.0, k: int = 12,
                    hotspot_min_reads: int = 15,
                    drift_threshold: float = 0.10) -> dict:
    """Hotspot feedback vs drift-only on a flash crowd (module
    docstring); the acceptance scenario.

    The quantitative point the artifact pins: the burst moves the drift
    statistic to ~0.065 — INSIDE this workload's ordinary noise band
    (0.05-0.09 in burst-free windows), so no drift threshold can catch
    the flash crowd without also false-firing on noise; the hotspot
    ratio separates 37x-vs-4x.  ``drift_threshold`` sits above the noise
    band (the tuning that stops the false fires), and the drift-only
    controller consequently sleeps through the burst while the hotspot
    path re-clusters the window it lands."""
    window_seconds = duration / n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_FLASH_NODES))
    cohort = np.asarray([c == "archival" for c in manifest.category])
    b0, b1 = burst_windows
    events, _ = simulate_flash_crowd(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1),
        cohort=cohort, start=b0 * window_seconds,
        duration=(b1 - b0 + 1) * window_seconds, boost=boost)

    def mk(hotspot_feedback: bool) -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            drift_threshold=drift_threshold,
            kmeans=KMeansConfig(k=k, seed=42),
            scoring=validated_scoring_config(),
            serve=ServeConfig(policy="p2c", seed=seed,
                              hotspot_min_reads=hotspot_min_reads,
                              recluster_on_hotspot=hotspot_feedback))
        return ReplicationController(manifest, cfg)

    # Drift-only side first: prove the burst stays under the drift
    # threshold (no re-cluster in or after the burst windows).
    plain = mk(hotspot_feedback=False).run(events)

    # Feedback side under telemetry: the audit stream carries the
    # hotspot_recluster flag the acceptance asks for.
    from ..obs import JsonlSink, Telemetry, read_events

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "serve.jsonl")
        with Telemetry(JsonlSink(path)):
            fed = mk(hotspot_feedback=True).run(events, metrics_path=path)
        stream = read_events(path)
    audit_flags = {int(e["window"]): e.get("flags", [])
                   for e in stream if e.get("kind") == "audit"}

    def window_digest(res):
        return [{
            "window": r["window"],
            "drift": None if r.get("drift") is None
            else round(r["drift"], 5),
            "recluster": r["recluster"],
            "trigger": r.get("recluster_trigger"),
            "hotspot_score": r.get("hotspot_score"),
            "hotspot_files": (r.get("hotspot_files") or [])[:4],
            "latency_p99_ms": r.get("latency_p99_ms"),
        } for r in res.records]

    burst_set = set(range(b0, n_windows))
    drift_reclusters = [r["window"] for r in plain.records
                        if r["recluster"] and r["window"] in burst_set]
    hotspot_reclusters = [
        r["window"] for r in fed.records
        if r.get("recluster_trigger") == "hotspot"]
    burst_drift = [r.get("drift") for r in plain.records
                   if r["window"] == b0]
    flagged = [w for w, flags in audit_flags.items()
               if "hotspot_recluster" in flags]
    return {
        "n_files": n_files, "n_windows": n_windows,
        "window_seconds": window_seconds,
        "burst_windows": list(burst_windows), "boost": boost,
        "cohort_files": int(cohort.sum()),
        "drift_threshold": drift_threshold,
        "drift_noise_band_max": max(
            (r["drift"] for r in plain.records
             if r.get("drift") is not None
             and r["window"] not in burst_set), default=None),
        "drift_at_burst": burst_drift[0] if burst_drift else None,
        "hotspot_score_at_burst": next(
            (r.get("hotspot_score") for r in fed.records
             if r["window"] == b0), None),
        "drift_only": {
            "reclusters_at_or_after_burst": drift_reclusters,
            "windows": window_digest(plain),
        },
        "hotspot_feedback": {
            "hotspot_reclusters": hotspot_reclusters,
            "audit_hotspot_flag_windows": flagged,
            "windows": window_digest(fed),
        },
        "hotspot_catches_what_drift_misses":
            bool(hotspot_reclusters) and not drift_reclusters
            and hotspot_reclusters[0] == b0
            and hotspot_reclusters[0] in flagged,
    }


def serve_overhead(n_files: int = 20_000, duration: float = 480.0,
                   window_seconds: float = 60.0, repeats: int = 9) -> dict:
    """Telemetry wall-clock ratio with SERVING instrumentation on.

    Interleaved paired rounds, best-window ratio (the repo's standard
    methodology), at the control-overhead scale
    (summary.telemetry_overhead_control's 20k files): both sides run the
    serve-enabled controller (router + hotspot every window); the
    instrumented side additionally streams window records, serve
    gauges/counters and the per-window latency hist_bulk (whose cost is
    capped by HIST_BULK_SAMPLE_CAP — fixed per window no matter the read
    volume).  Pins the acceptance: serving telemetry stays <= 1.05x."""
    from ..benchmarks.summary import TELEMETRY_OVERHEAD_BUDGET
    from ..obs import JsonlSink, Telemetry
    from ..sim.access import simulate_access

    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=7, nodes=_FLASH_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=8))

    def mk() -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            kmeans=KMeansConfig(k=8, seed=42),
            scoring=validated_scoring_config(),
            serve=ServeConfig(policy="p2c", seed=3))
        return ReplicationController(manifest, cfg)

    def run_plain() -> float:
        t0 = time.perf_counter()
        mk().run(events)
        return time.perf_counter() - t0

    def run_instr(path: str) -> float:
        if os.path.exists(path):
            os.remove(path)
        t0 = time.perf_counter()
        with Telemetry(JsonlSink(path)):
            mk().run(events, metrics_path=path)
        return time.perf_counter() - t0

    run_plain()  # warmup
    plain_times: list[float] = []
    instr_times: list[float] = []
    ratios: list[float] = []
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.jsonl")
        for r in range(max(1, repeats)):
            if r % 2 == 0:
                p, i = run_plain(), run_instr(path)
            else:
                i, p = run_instr(path), run_plain()
            plain_times.append(p)
            instr_times.append(i)
            ratios.append(i / p)
    ratios.sort()
    ratio = min(instr_times) / min(plain_times)
    return {
        "n_files": n_files,
        "windows_per_run": int(duration // window_seconds),
        "plain_seconds": min(plain_times),
        "telemetry_seconds": min(instr_times),
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": ratio,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/serve_bench.json")
    p.add_argument("--round", type=int, default=6, dest="round_no",
                   help="PR-round stamp for the regress history (the "
                        "filename carries no rNN, so the artifact itself "
                        "records which round produced it)")
    p.add_argument("--reads", type=int, default=4_000_000,
                   help="batch-throughput read count")
    p.add_argument("--no_overhead", action="store_true",
                   help="skip the paired telemetry-overhead rounds")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI)")
    args = p.parse_args(argv)

    if args.quick:
        throughput = run_throughput(n_files=1 << 16, n_reads=200_000)
        # Same utilization regime as the full run (the p2c-vs-random p99
        # gap needs the straggler overloaded): fewer reads, slower disks.
        chaos = run_chaos_p99(n_files=4000, reads_per_window=60_000,
                              n_windows=6, service_ms=4.0)
        flash = run_flash_crowd(n_files=200, duration=900.0, n_windows=9,
                                burst_windows=(6, 6), k=8)
    else:
        throughput = run_throughput(n_reads=args.reads)
        chaos = run_chaos_p99()
        flash = run_flash_crowd()

    out: dict = {
        "round": args.round_no,
        "throughput": throughput,
        "chaos_p99": chaos,
        "flash_crowd": flash,
    }
    if not args.no_overhead:
        out["overhead"] = serve_overhead()

    out["criteria"] = {
        "routed_1m_reads_per_sec":
            throughput["best_reads_per_sec"] >= 1_000_000,
        "p2c_beats_random_p99": chaos["p2c_beats_random_p99"],
        "hotspot_catches_what_drift_misses":
            flash["hotspot_catches_what_drift_misses"],
        **({"overhead_within_budget": out["overhead"]["within_budget"]}
           if not args.no_overhead else {}),
    }
    # Comparable metrics for the trajectory gate (regress bench_records):
    # deterministic p99s band tightly; throughput bands per platform.
    out["bench_records"] = [
        {"metric": "serve_routed_reads_per_sec",
         "value": throughput["best_reads_per_sec"], "unit": "reads/s",
         "backend": "numpy"},
        {"metric": "serve_chaos_p99_ms_p2c",
         "value": chaos["policies"]["p2c"]["p99_ms"], "unit": "ms",
         "backend": "numpy"},
        {"metric": "serve_chaos_p99_ms_random",
         "value": chaos["policies"]["random"]["p99_ms"], "unit": "ms",
         "backend": "numpy"},
    ]

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"out": args.out, **out["criteria"],
                      "best_reads_per_sec":
                          throughput["best_reads_per_sec"],
                      "p99_p2c": chaos["policies"]["p2c"]["p99_ms"],
                      "p99_random": chaos["policies"]["random"]["p99_ms"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
