"""Bench trajectory regression gate — ``cdrs metrics regress``.

The per-round driver captures (``BENCH_r0*.json``) are five disconnected
files nothing reads; this module makes the trajectory *enforceable*:

* **ingest** — ``BENCH_r*.json`` driver captures (and raw ``bench.py``
  detail JSON) flatten into one canonical append-only history,
  ``data/bench_history.jsonl`` — one line per (round, metric) with the
  value, unit, direction, and the platform it was measured on.  Robust to
  the drivers' truncation: a capture whose ``parsed`` is null is scraped
  from its ``tail`` text (the r05 file holds only the last 2000 bytes of
  the detail JSON; the metric/value fragments and the nested config blocks
  survive).
* **check** — a fresh bench run is compared per metric against a tolerance
  band anchored at the BEST of the trailing ``window`` history values
  (compare-against-recent-best; ± ``tolerance``).  Bands only form
  between runs on the SAME
  platform (``jax_platform``): a CPU CI runner is never judged against the
  TPU trajectory — it reports ``no_baseline`` and passes, which is the
  report-only posture .github/workflows/ci.yml runs until a stable runner
  baseline exists.  A regression (worse than the band in the metric's bad
  direction — ``iter/s`` down, ``seconds`` up) exits nonzero so CI can
  gate on it; an improvement is reported as such, not flagged.

No jax import anywhere: the gate must run on any host that can read JSON.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

__all__ = ["extract_records", "ingest_files", "load_history", "check_run",
           "append_history", "history_key", "add_history_argument",
           "resolve_history_path", "main"]

#: Units where smaller is better (wall-clock style metrics); everything
#: else (iter/s, files/s, events/s) is throughput, larger is better.
_LOWER_BETTER_UNITS = {"s", "seconds", "ms"}

_NESTED_BLOCKS = ("config3", "config4_rehearsal")


def _direction(metric: str, unit: str | None) -> str:
    if (unit or "") in _LOWER_BETTER_UNITS or metric.startswith("e2e_"):
        return "lower"
    return "higher"


def _record_from(detail: dict, source: str, round_no: int | None
                 ) -> dict | None:
    """One history record from a bench detail dict (driver ``parsed`` or a
    nested config block); None when it is not a completed capture."""
    if not isinstance(detail, dict) or "metric" not in detail \
            or "value" not in detail:
        return None
    if "error" in detail or "skipped" in detail:
        return None
    rec = {
        "round": round_no,
        "source": source,
        "metric": detail["metric"],
        "value": float(detail["value"]),
        "unit": detail.get("unit"),
        # An explicit direction wins: the scenario sweep pins e.g. churn
        # bytes as lower-is-better, which no unit heuristic can know.
        "direction": detail.get("direction")
        or _direction(detail["metric"], detail.get("unit")),
        "platform": detail.get("jax_platform")
        or ("numpy" if detail.get("backend") == "numpy" else None),
        "devices": detail.get("jax_devices"),
        "backend": detail.get("backend"),
    }
    if detail.get("vs_baseline") is not None:
        rec["vs_baseline"] = float(detail["vs_baseline"])
    return rec


_METRIC_RE = re.compile(
    r'"metric":\s*"(?P<metric>[^"]+)",\s*"value":\s*'
    r'(?P<value>[-+0-9.eE]+),\s*"unit":\s*"(?P<unit>[^"]+)"'
    r'(?:,\s*"vs_baseline":\s*(?P<vsb>[-+0-9.eE]+))?')
_PLATFORM_RE = re.compile(r'"jax_platform":\s*"(\w+)"')


def _scrape_tail(tail: str, source: str, round_no: int | None
                 ) -> list[dict]:
    """Records regex-scraped from a truncated driver ``tail``.

    The stdout contract line and the detail JSON both carry the
    metric/value/unit(/vs_baseline) quadruple; nested config blocks carry
    their own.  Platform association: the detail JSON stamps
    ``jax_platform`` after each metric's fields, so each match takes the
    first platform occurrence following it.  Duplicate (metric, value)
    pairs (contract line + detail line) collapse to one record.
    """
    platforms = [(m.start(), m.group(1))
                 for m in _PLATFORM_RE.finditer(tail)]
    seen: set[tuple] = set()
    records = []
    for m in _METRIC_RE.finditer(tail):
        key = (m.group("metric"), m.group("value"))
        if key in seen:
            continue
        seen.add(key)
        platform = next((p for pos, p in platforms if pos > m.end()), None)
        rec = {
            "round": round_no,
            "source": source,
            "metric": m.group("metric"),
            "value": float(m.group("value")),
            "unit": m.group("unit"),
            "direction": _direction(m.group("metric"), m.group("unit")),
            "platform": platform,
            "scraped": True,
        }
        if m.group("vsb") is not None:
            rec["vs_baseline"] = float(m.group("vsb"))
        records.append(rec)
    return records


def extract_records(doc, source: str) -> list[dict]:
    """Flatten one bench artifact (driver capture or raw detail JSON) into
    history records: the headline metric plus completed nested config
    blocks (``config3``, ``config4_rehearsal``) and — for artifacts that
    carry several comparable metrics, like ``data/serve_bench.json`` —
    every entry of a top-level ``bench_records`` list."""
    round_no = None
    m = re.search(r"r(\d+)", os.path.basename(source))
    if m:
        round_no = int(m.group(1))
    # Artifacts whose filename carries no round (data/serve_bench.json)
    # stamp it explicitly — ingest stays reproducible from the file alone.
    if isinstance(doc, dict) and isinstance(doc.get("round"), int):
        round_no = doc["round"]
    if isinstance(doc, dict) and "n" in doc and "cmd" in doc:
        round_no = int(doc["n"])
        detail = doc.get("parsed")
        if detail is None:
            return _scrape_tail(doc.get("tail") or "", source, round_no)
    else:
        detail = doc
    if not isinstance(detail, dict):
        return []
    records = []
    rec = _record_from(detail, source, round_no)
    if rec:
        records.append(rec)
    for block in _NESTED_BLOCKS:
        rec = _record_from(detail.get(block), source, round_no)
        if rec:
            records.append(rec)
    for entry in detail.get("bench_records") or ():
        rec = _record_from(entry, source, round_no)
        if rec:
            records.append(rec)
    return records


def ingest_files(paths: list[str]) -> list[dict]:
    records = []
    for path in paths:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        # Basename only: the history must not bake in the ingesting
        # machine's directory layout.
        records.extend(extract_records(doc, os.path.basename(path)))
    records.sort(key=lambda r: ((r.get("round") is None, r.get("round")),
                                str(r.get("metric"))))
    return records


def write_history(path: str, records: list[dict]) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r, sort_keys=True) + "\n")


def history_key(rec: dict) -> tuple:
    """The identity of one history row: (round, metric, platform).

    One bench measurement per PR round per platform — re-ingesting the
    same artifact (or re-running a sweep) must be a no-op, and a
    re-measured value for an existing key keeps the ORIGINAL row (the
    history is an append-only ledger, not a cache)."""
    return (rec.get("round"), rec.get("metric"), rec.get("platform"))


def append_history(path: str, records: list[dict]) -> int:
    """Append ``records`` to the history, deduplicated on
    ``history_key`` — the shared helper behind the scenario sweep and
    the bench drivers (plan_bench/integrity_bench used to note "appended
    manually").  Existing rows are never rewritten or re-sorted (the
    append-only artifact-order contract tests/test_regress.py pins);
    new rows append in the given order.  Returns the number of rows
    actually appended."""
    have: set[tuple] = set()
    if os.path.exists(path):
        have = {history_key(r) for r in load_history(path)}
    fresh = []
    for rec in records:
        key = history_key(rec)
        if key in have:
            continue
        have.add(key)
        fresh.append(rec)
    if not fresh:
        return 0
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        for r in fresh:
            f.write(json.dumps(r, sort_keys=True) + "\n")
    return len(fresh)


def add_history_argument(parser) -> None:
    """The shared ``--history`` flag of the auto-appending benches
    (plan_bench, integrity_bench, the scenario sweep's drivers): one
    definition so the ledger policy cannot drift between them."""
    parser.add_argument(
        "--history", default=None, metavar="JSONL",
        help="append the bench_records here (regress.append_history: "
             "deduped on (round, metric, platform), so re-runs never "
             "double-append). Default: data/bench_history.jsonl for "
             "full runs, DISABLED for --quick — a smoke-scale "
             "measurement must never become the ledger row a real run "
             "is then deduped against; '' disables explicitly")


def resolve_history_path(args) -> str:
    """The ledger path the parsed ``--history`` flag means: the given
    path verbatim when set ('' = disabled), else the default ledger —
    unless the run is ``--quick``, which never auto-appends."""
    if args.history is not None:
        return args.history
    return "" if getattr(args, "quick", False) \
        else "data/bench_history.jsonl"


def load_history(path: str) -> list[dict]:
    records = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def _parse_run_text(text: str):
    """A run artifact as JSON, tolerating surrounding noise.

    ``bench.py`` prints the one-line stdout contract (metric/value only)
    and the FULL detail record — the one carrying ``backend``/
    ``jax_platform`` the banding needs — to stderr, where jax warnings
    interleave.  A clean JSON document parses directly; otherwise the
    LAST line holding a JSON object with a ``metric`` key wins (the
    detail record is printed after the contract line)."""
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and ("metric" in doc or "parsed" in doc):
            return doc
    raise ValueError("no JSON bench record found in the run artifact")


def check_run(run_records: list[dict], history: list[dict], *,
              tolerance: float = 0.15, window: int = 3) -> list[dict]:
    """Per-metric verdicts of a fresh run against the history bands.

    Band anchor: the BEST of the trailing ``window`` same-(metric,
    platform) history values — "do not regress from what the trajectory
    recently demonstrated", the same posture as pytest-benchmark's
    compare-against-best.  A trajectory mid-improvement (the recorded
    config-2 history quadruples over three rounds) makes a mean or median
    anchor uselessly loose; ``tolerance`` (default 15%) absorbs the
    observed ~6% round-to-round noise.  Statuses: ``regression`` (outside
    the band, bad side), ``improved`` (beyond the anchor by the same
    margin, good side), ``pass`` (inside), ``no_baseline`` (no comparable
    history — different platform or a new metric; always passes).
    """
    by_key: dict[tuple, list[dict]] = {}
    for h in history:
        by_key.setdefault((h.get("metric"), h.get("platform")),
                          []).append(h)
    verdicts = []
    for rec in run_records:
        key = (rec.get("metric"), rec.get("platform"))
        hist = by_key.get(key, [])
        v: dict = {"metric": rec.get("metric"),
                   "platform": rec.get("platform"),
                   "value": rec.get("value"), "unit": rec.get("unit")}
        if not hist:
            v["status"] = "no_baseline"
            verdicts.append(v)
            continue
        hist = sorted(hist, key=lambda h: (h.get("round") is None,
                                           h.get("round")))
        recent = [float(h["value"]) for h in hist[-max(1, window):]]
        direction = rec.get("direction") or _direction(
            rec.get("metric", ""), rec.get("unit"))
        baseline = max(recent) if direction == "higher" else min(recent)
        value = float(rec["value"])
        v.update({"baseline": baseline, "direction": direction,
                  "n_history": len(hist), "tolerance": tolerance})
        if direction == "higher":
            band_low = baseline * (1.0 - tolerance)
            v["band_low"] = band_low
            if value < band_low:
                v["status"] = "regression"
            elif value > baseline * (1.0 + tolerance):
                v["status"] = "improved"
            else:
                v["status"] = "pass"
        else:
            band_high = baseline * (1.0 + tolerance)
            v["band_high"] = band_high
            if value > band_high:
                v["status"] = "regression"
            elif value < baseline * (1.0 - tolerance):
                v["status"] = "improved"
            else:
                v["status"] = "pass"
        verdicts.append(v)
    return verdicts


def _print_verdicts(verdicts: list[dict], out=None) -> None:
    out = out or sys.stdout
    for v in verdicts:
        status = v["status"]
        line = f"  [{status:<11}] {v['metric']} = {v['value']:g}"
        if "baseline" in v:
            arrow = "<" if "band_low" in v else ">"
            band = v.get("band_low", v.get("band_high"))
            line += (f" {v.get('unit', '')} (baseline {v['baseline']:g}, "
                     f"regression when {arrow} {band:g}, "
                     f"{v['n_history']} rounds of history)")
        else:
            line += (f" {v.get('unit', '')} (no comparable history on "
                     f"platform {v.get('platform')!r})")
        print(line, file=out)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cdrs metrics regress",
        description="compare a bench run against the recorded trajectory "
                    "bands (nonzero exit on regression)")
    parser.add_argument("run", nargs="?", default=None,
                        help="fresh bench artifact (driver capture or raw "
                             "bench.py detail JSON); '-' reads stdin")
    parser.add_argument("--history", default="data/bench_history.jsonl",
                        metavar="JSONL",
                        help="canonical trajectory history "
                             "(default: data/bench_history.jsonl)")
    parser.add_argument("--ingest", nargs="+", default=None,
                        metavar="JSON",
                        help="ingest these BENCH artifacts into the "
                             "history instead of checking a run: an "
                             "existing history is appended to, deduped "
                             "on (round, metric, platform) — idempotent "
                             "— and built fresh when absent")
    parser.add_argument("--tolerance", type=float, default=0.15,
                        help="band half-width as a fraction of the "
                             "baseline (default 0.15)")
    parser.add_argument("--window", type=int, default=3,
                        help="trailing history rounds whose BEST value "
                             "anchors the band (default 3)")
    parser.add_argument("--report-only", action="store_true",
                        help="print verdicts but exit 0 even on "
                             "regression (CI before a stable runner "
                             "baseline exists)")
    parser.add_argument("--json", action="store_true",
                        help="emit the verdicts as JSON")
    args = parser.parse_args(argv)

    if args.ingest:
        records = ingest_files(args.ingest)
        if not records:
            print("error: no bench records found in the given files",
                  file=sys.stderr)
            return 2
        # Idempotent by (round, metric, platform) on BOTH paths: an
        # existing history is appended to (never re-sorted — the
        # append-only artifact-order contract), a fresh one is built
        # with the same within-batch dedup, and re-ingesting the same
        # artifacts is a no-op either way.
        appended = append_history(args.history, records)
        skipped = len(records) - appended
        print(f"ingested {appended} records from "
              f"{len(args.ingest)} files -> {args.history}"
              + (f" ({skipped} already present)" if skipped else ""))
        return 0

    if not args.run:
        parser.error("a RUN.json to check (or --ingest) is required")
    try:
        if args.run == "-":
            text = sys.stdin.read()
        else:
            with open(args.run, encoding="utf-8") as f:
                text = f.read()
        doc = _parse_run_text(text)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"error: cannot read run {args.run}: {e}", file=sys.stderr)
        return 2
    run_records = extract_records(doc, args.run if args.run != "-"
                                  else "stdin")
    if not run_records:
        print("error: no metric records in the run artifact",
              file=sys.stderr)
        return 2
    try:
        history = load_history(args.history)
    except OSError as e:
        print(f"error: cannot read history {args.history}: {e}",
              file=sys.stderr)
        return 2

    verdicts = check_run(run_records, history, tolerance=args.tolerance,
                         window=args.window)
    if args.json:
        print(json.dumps(verdicts, indent=2))
    else:
        print(f"bench regression check vs {args.history} "
              f"(tolerance {args.tolerance:g}, window {args.window}):")
        _print_verdicts(verdicts)
    regressions = [v for v in verdicts if v["status"] == "regression"]
    if regressions:
        print(f"REGRESSION: {len(regressions)} metric(s) below the "
              f"trajectory band", file=sys.stderr)
        if not args.report_only:
            return 1
        print("(report-only mode: exiting 0)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
