"""Streaming-daemon baselines: decision latency, serving under re-plan.

The always-on controller daemon's acceptance artifact
(``data/daemon_bench.json``), three scenario families:

**Event-to-decision latency** (``run_decision_latency``): the daemon
tails a binary event log and every closed window must become an
admitted plan + published epoch in sub-second time — p99 over the
per-window carve -> decide -> publish wall-clock (the daemon's
``decision_seconds`` samples).  Acceptance: p99 < 1 s.

**Serving under re-clustering** (``run_serve_under_recluster``): the
epoch-pinned read path must sustain >= 1M routed reads/s WHILE the
daemon re-clusters and republishes placement epochs underneath.  The
daemon ingests the whole log in a background thread; the foreground
pins ``publisher.pin()`` once per read batch and routes through the
epoch's functional resolver (``PlacementEpoch.read_view`` -> the full
router).  The run must observe at least two distinct epochs across its
batches — serving genuinely crossed a republication, it did not just
race past a finished daemon.

**Decayed-fold identity** (``run_decay_identity``): with decay = 1.0
the daemon's per-window decayed sufficient-statistics fold must be
DECISION-identical to the windowed batch controller — same per-window
plan hashes, same final category populations, same per-file durability
tiers (rf) — on three seeds.  The daemon's controller gets the decayed
accumulator force-enabled (it is normally elided at decay = 1.0) so the
claim is about the decayed code path, not about it being skipped.

Decision latency runs WITH tracing on (obs/trace.py): the artifact's
``stage_attribution`` columns are the critical-path shares and the
``trace_reconciled`` criterion asserts the exact integer-ns segment
telescoping on every traced decision.  Round 18 additionally attaches
the live operational plane (obs/httpz.py) with a scraper polling
``/metrics`` + ``/statusz`` for the whole run — the reported latency
numbers carry the endpoint cost they claim to, and the final scrape is
format-linted (``live_endpoint`` block).

``python -m cdrs_tpu.benchmarks.daemon_bench`` writes the artifact and
appends round-18 rows to ``data/bench_history.jsonl``
(regress.append_history, deduped); ``--quick`` shrinks scales for the
CI smoke step and never appends.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time

import numpy as np

from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..daemon import DaemonConfig, StreamDaemon
from ..serve import ReadRouter, ServeConfig
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_decision_latency", "run_serve_under_recluster",
           "run_decay_identity"]

_NODES = ("dn1", "dn2", "dn3", "dn4", "dn5")


def _controller(manifest, window_seconds: float, k: int,
                decay: float = 1.0) -> ReplicationController:
    cfg = ControllerConfig(
        window_seconds=window_seconds, default_rf=2, decay=decay,
        kmeans=KMeansConfig(k=k, seed=42),
        scoring=validated_scoring_config())
    return ReplicationController(manifest, cfg)


def _population(n_files: int, duration: float, seed: int):
    manifest = generate_population(GeneratorConfig(
        n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=duration, seed=seed + 1))
    return manifest, events


def run_decision_latency(n_files: int = 20_000, n_windows: int = 20,
                         window_seconds: float = 60.0, k: int = 12,
                         seed: int = 41) -> dict:
    """p99 window-close-to-admitted-decision latency through the full
    daemon path (binary-log tail -> carve -> fold -> decide -> epoch
    publish), at the control-overhead scale — WITH decision tracing on
    (obs/trace.py rides the metrics sink) AND the live operational
    plane attached under an active scraper (obs/httpz.py), so the
    reported numbers carry the full observability cost they claim to
    and each decision's critical path is attributed per stage."""
    import urllib.request

    from ..obs import prom
    from ..obs.httpz import ObsServer

    manifest, events = _population(n_files, n_windows * window_seconds,
                                   seed)
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        metrics = os.path.join(td, "metrics.jsonl")
        events.write_binary(log, manifest)
        daemon = StreamDaemon(_controller(manifest, window_seconds, k))
        with ObsServer() as srv:
            daemon.attach_http(srv)
            stop = threading.Event()
            counter = {"n": 0}

            def scrape():
                while not stop.is_set():
                    for path in ("/metrics", "/statusz"):
                        try:
                            with urllib.request.urlopen(
                                    srv.url + path, timeout=2) as r:
                                r.read()
                            counter["n"] += 1
                        except OSError:
                            pass
                    stop.wait(0.1)

            th = threading.Thread(target=scrape, daemon=True)
            th.start()
            dig = daemon.run(log, metrics_path=metrics)
            stop.set()
            th.join(timeout=5.0)
            with urllib.request.urlopen(srv.url + "/metrics",
                                        timeout=5) as r:
                final_scrape = r.read().decode("utf-8")
            snap = srv.snapshot
        live_endpoint = {
            "scrapes": int(counter["n"]),
            "snapshot_seq": int(snap.seq),
            "snapshot_consistent": bool(
                snap.seq == snap.windows_processed
                == snap.epochs_published),
            "metrics_lint_clean": prom.lint(final_scrape) == [],
        }
        with open(metrics, encoding="utf-8") as f:
            evs = [json.loads(line) for line in f]
    lat = np.asarray(daemon.decision_seconds, dtype=np.float64)
    from ..obs.aggregate import collect, critical_path_digest

    agg = collect(evs)
    cp = critical_path_digest(agg["decisions"], agg["windows"]) or {}
    return {
        "n_files": n_files,
        "n_windows": int(dig["windows_processed"]),
        "events": int(dig["events_ingested"]),
        "epochs_published": int(dig["epochs_published"]),
        "decision_p50_seconds": round(float(np.quantile(lat, 0.5)), 6),
        "decision_p99_seconds": float(dig["decision_p99_seconds"]),
        "decision_max_seconds": round(float(lat.max()), 6),
        "sub_second_p99": bool(dig["decision_p99_seconds"] < 1.0),
        "traced_decisions": int(dig["traced_decisions"]),
        "trace_reconciled": bool(cp.get("reconciled", False)),
        "stage_attribution": {
            name: round(share, 4)
            for name, share in (cp.get("stage_shares") or {}).items()},
        "event_to_decision_p99_seconds": round(
            float(cp.get("total_p99_seconds", 0.0)), 6),
        "live_endpoint": live_endpoint,
    }


def run_serve_under_recluster(n_files: int = 1 << 15,
                              n_windows: int = 24,
                              window_seconds: float = 60.0,
                              k: int = 16,
                              reads_per_batch: int = 1_000_000,
                              min_batches: int = 4,
                              max_batches: int = 64,
                              seed: int = 43) -> dict:
    """Routed reads/s through the pinned epoch while the daemon
    re-clusters and republishes underneath (module docstring)."""
    manifest, events = _population(n_files, n_windows * window_seconds,
                                   seed)
    rng = np.random.default_rng(seed + 7)
    n_nodes = len(_NODES)
    router = ReadRouter(n_nodes, ServeConfig(policy="p2c", seed=seed))

    batches: list[dict] = []
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        events.write_binary(log, manifest)
        daemon = StreamDaemon(_controller(manifest, window_seconds, k))
        t = threading.Thread(target=daemon.run, args=(log,), daemon=True)
        t.start()
        while daemon.publisher.pin() is None and t.is_alive():
            time.sleep(0.002)
        # Route batches pinned one-epoch-each until the daemon finishes
        # (and at least ``min_batches`` either way): skewed pids, the
        # zipf-ish head the load-aware policy exists to absorb.
        while (t.is_alive() or len(batches) < min_batches) \
                and len(batches) < max_batches:
            ep = daemon.publisher.pin()
            ts = np.sort(rng.random(reads_per_batch) * window_seconds)
            pid = (n_files
                   * rng.random(reads_per_batch) ** 3.0).astype(np.int32)
            client = rng.integers(0, n_nodes,
                                  reads_per_batch).astype(np.int32)
            t0 = time.perf_counter()
            rv = ep.read_view(pid)
            res = router.route(rv.replica_map, rv.slot_ok,
                               rv.node_throughput, ts=ts, pid=rv.pid,
                               client=client,
                               window_seconds=window_seconds)
            dt = time.perf_counter() - t0
            batches.append({"epoch": int(ep.epoch_id),
                            "seconds": round(dt, 4),
                            "p99_ms": round(res.p99_ms, 4)})
        t.join()
    total_reads = reads_per_batch * len(batches)
    total_seconds = sum(b["seconds"] for b in batches)
    epochs_seen = sorted({b["epoch"] for b in batches})
    return {
        "n_files": n_files,
        "reads_per_batch": reads_per_batch,
        "batches": len(batches),
        "reads_per_sec": round(total_reads / total_seconds, 1),
        "epochs_published": int(daemon.publisher.published_total),
        "epochs_seen_while_routing": epochs_seen,
        "per_batch": batches,
        "sustained_1m_reads_per_sec":
            total_reads / total_seconds >= 1_000_000,
        "reclustered_underneath": len(epochs_seen) >= 2,
    }


def run_decay_identity(n_files: int = 2_000, n_windows: int = 12,
                       window_seconds: float = 120.0, k: int = 10,
                       seeds: tuple[int, ...] = (0, 1, 2)) -> dict:
    """Decay=1.0 decayed live fold vs windowed batch controller:
    decision identity per seed (plan hashes, category populations,
    durability tiers)."""
    per_seed = []
    for seed in seeds:
        manifest, events = _population(
            n_files, n_windows * window_seconds, 100 + seed)
        batch = _controller(manifest, window_seconds, k)
        res = batch.run(events)
        live = _controller(manifest, window_seconds, k)
        # Force the decayed accumulator on (normally elided at
        # decay=1.0) so the identity claim exercises the decayed path.
        live._dec = {key: np.zeros(len(manifest))
                     for key in ("access_freq", "writes", "local_acc",
                                 "conc_max")}
        live._dec_obs_end = None
        with tempfile.TemporaryDirectory() as td:
            log = os.path.join(td, "events.cdrsb")
            events.write_binary(log, manifest)
            daemon = StreamDaemon(live)
            daemon.run(log)
        hashes_batch = [r["plan_hash"] for r in res.records]
        hashes_live = [r["plan_hash"] for r in daemon.records]
        pops_batch = np.bincount(batch.current_cat, minlength=k)
        pops_live = np.bincount(live.current_cat, minlength=k)
        per_seed.append({
            "seed": seed,
            "windows": len(daemon.records),
            "plan_hashes_identical": hashes_batch == hashes_live,
            "category_populations_identical":
                bool(np.array_equal(pops_batch, pops_live)),
            "durability_tiers_identical":
                bool(np.array_equal(batch.current_rf, live.current_rf)),
        })
    return {
        "n_files": n_files, "n_windows": n_windows, "seeds": list(seeds),
        "per_seed": per_seed,
        "decay_one_identical": all(
            s["plan_hashes_identical"]
            and s["category_populations_identical"]
            and s["durability_tiers_identical"] for s in per_seed),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/daemon_bench.json")
    p.add_argument("--round", type=int, default=18, dest="round_no",
                   help="PR-round stamp for the regress history")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI); never appends "
                        "to the history")
    from .regress import add_history_argument

    add_history_argument(p)
    args = p.parse_args(argv)

    if args.quick:
        latency = run_decision_latency(n_files=2_000, n_windows=8)
        serve = run_serve_under_recluster(
            n_files=1 << 13, n_windows=12, reads_per_batch=200_000,
            min_batches=3)
        decay = run_decay_identity(n_files=500, n_windows=8,
                                   seeds=(0, 1, 2))
    else:
        latency = run_decision_latency()
        serve = run_serve_under_recluster()
        decay = run_decay_identity()

    out: dict = {
        "round": args.round_no,
        "decision_latency": latency,
        "serve_under_recluster": serve,
        "decay_identity": decay,
    }
    out["criteria"] = {
        "decision_p99_sub_second": latency["sub_second_p99"],
        "trace_reconciled": latency["trace_reconciled"],
        "endpoint_scraped_during_run":
            latency["live_endpoint"]["scrapes"] > 0
            and latency["live_endpoint"]["snapshot_consistent"]
            and latency["live_endpoint"]["metrics_lint_clean"],
        "routed_1m_reads_per_sec_during_recluster":
            serve["sustained_1m_reads_per_sec"]
            and serve["reclustered_underneath"],
        "decay_one_decision_identical": decay["decay_one_identical"],
    }
    out["bench_records"] = [
        {"metric": "daemon_decision_p99_seconds",
         "value": latency["decision_p99_seconds"], "unit": "s",
         "direction": "lower", "backend": "numpy"},
        {"metric": "daemon_routed_reads_per_sec",
         "value": serve["reads_per_sec"], "unit": "reads/s",
         "backend": "numpy"},
    ]

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    appended = 0
    if not args.quick:
        from .regress import append_history, extract_records, \
            resolve_history_path

        history = resolve_history_path(args)
        if history:
            appended = append_history(
                history, extract_records(out,
                                         os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "decision_p99_seconds":
                          latency["decision_p99_seconds"],
                      "reads_per_sec": serve["reads_per_sec"],
                      "history_appended": appended}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
