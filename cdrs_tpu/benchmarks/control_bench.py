"""Controller-vs-batch benchmark: adaptation speed and churn under a shift.

Scenario: a seeded population serves a stationary workload, then mid-stream
a cohort's planted categories flip hot<->archival
(sim/access.simulate_access_with_shift) — the canonical "popularity moved"
event dynamic replication exists for.  Two strategies replay the same log
window by window:

* **controller** — the online loop (control/controller.py): carried decayed
  feature fold, drift-gated warm re-clusters, bounded-churn scheduling with
  hysteresis.
* **batch baseline** — "re-run the whole batch pipeline and apply the whole
  new plan": every window recomputes features over ALL events so far
  (features/numpy_backend), re-clusters from a fresh init, and applies the
  entire new plan at once (no budget, no hysteresis).

Reported per strategy: **time-to-adapt** (windows after the shift until the
majority of the flipped cohort is planned into its new planted category) and
**cumulative bytes migrated** (size x added replicas; replica drops are
free).  ``python -m cdrs_tpu.benchmarks.control_bench`` writes the JSON
artifact to ``data/control_bench.json``.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from ..config import (
    CATEGORIES,
    GeneratorConfig,
    KMeansConfig,
    PLANTED_TO_CATEGORY,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController, iter_windows
from ..features.numpy_backend import compute_features
from ..io.events import EventLog
from ..models.replication import ReplicationPolicyModel
from ..sim.access import simulate_access_with_shift
from ..sim.generator import generate_population

__all__ = ["run_control_bench"]


def run_control_bench(
    n_files: int = 300,
    seed: int = 7,
    duration: float = 2400.0,
    n_windows: int = 20,
    k: int = 12,
    decay: float = 0.7,
    drift_threshold: float = 0.02,
    max_bytes_frac: float = 0.15,
    adapt_majority: float = 0.5,
) -> dict:
    """Run the shifted-workload scenario; returns the artifact dict."""
    window_seconds = duration / n_windows
    shift_at = duration / 2.0
    shift_window = int(shift_at // window_seconds)

    manifest = generate_population(GeneratorConfig(n_files=n_files, seed=seed))
    flip = {"hot": "archival", "archival": "hot"}
    events, flipped = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1),
        shift_at=shift_at, category_flip=flip)

    # Ground truth for the flipped cohort AFTER the shift.
    target_idx = np.asarray([
        CATEGORIES.index(PLANTED_TO_CATEGORY[flip[c]]) if f else -1
        for c, f in zip(manifest.category, flipped)], dtype=np.int64)
    cohort = np.flatnonzero(flipped)

    def cohort_match(cat_per_file: np.ndarray) -> float:
        return float((np.asarray(cat_per_file)[cohort]
                      == target_idx[cohort]).mean())

    scoring = validated_scoring_config()
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    # Churn budget: a fraction of the bytes one full uniform-rf=3 rollout
    # would move — a budget in the workload's own units.
    max_bytes = int(max_bytes_frac * float(sizes.sum()) * 2)

    # --- controller -------------------------------------------------------
    cfg = ControllerConfig(
        window_seconds=window_seconds, decay=decay,
        drift_threshold=drift_threshold, full_recluster_drift=0.30,
        hysteresis_windows=1, max_bytes_per_window=max_bytes,
        kmeans=KMeansConfig(k=k, seed=42), scoring=scoring)
    ctl = ReplicationController(manifest, cfg)
    ctl_match, ctl_loc, ctl_bytes = [], [], []
    t0 = time.perf_counter()
    records = []
    for w, win in iter_windows(events, manifest, window_seconds):
        rec = ctl.process_window(w, win)
        records.append(rec)
        ctl_match.append(cohort_match(ctl.current_cat))
        ctl_loc.append(rec["locality_after"])
        ctl_bytes.append(rec["bytes_migrated"])
    ctl_seconds = time.perf_counter() - t0

    # --- batch baseline ---------------------------------------------------
    base_model = ReplicationPolicyModel(
        kmeans_cfg=KMeansConfig(k=k, seed=42), scoring_cfg=scoring,
        backend="numpy")
    rf_vec = np.asarray(scoring.rf_vector(), dtype=np.int64)
    cur_rf = np.ones(n_files, dtype=np.int64)
    base_match, base_bytes = [], []
    seen: list[EventLog] = []
    t0 = time.perf_counter()
    for w, win in iter_windows(events, manifest, window_seconds):
        if len(win):
            seen.append(win)
        table = compute_features(manifest, EventLog.concat(seen))
        decision = base_model.run(np.asarray(table.norm))
        cat = np.asarray(decision.category_idx)[np.asarray(decision.labels)]
        new_rf = rf_vec[cat]
        base_bytes.append(int((sizes * np.maximum(new_rf - cur_rf, 0)).sum()))
        cur_rf = new_rf
        base_match.append(cohort_match(cat))
    base_seconds = time.perf_counter() - t0

    def adapt_at(match: list[float]) -> int | None:
        for w in range(shift_window, len(match)):
            if match[w] >= adapt_majority:
                return w - shift_window
        return None

    ctl_total = int(np.sum(ctl_bytes))
    base_total = int(np.sum(base_bytes))
    out = {
        "scenario": {
            "n_files": n_files, "seed": seed, "duration_seconds": duration,
            "window_seconds": window_seconds, "n_windows": n_windows,
            "shift_at": shift_at, "shift_window": shift_window,
            "category_flip": flip, "n_flipped": int(flipped.sum()),
            "k": k, "decay": decay, "drift_threshold": drift_threshold,
            "max_bytes_per_window": max_bytes,
            "adapt_majority": adapt_majority,
        },
        "controller": {
            "windows_to_adapt": adapt_at(ctl_match),
            "bytes_migrated_total": ctl_total,
            "bytes_migrated_per_window": [int(b) for b in ctl_bytes],
            "cohort_match_per_window": [round(m, 4) for m in ctl_match],
            "locality_per_window": [None if v is None else round(v, 4)
                                    for v in ctl_loc],
            "reclusters": sum(1 for r in records if r["recluster"]),
            "full_reclusters": sum(1 for r in records
                                   if r["recluster_mode"] == "full"),
            "seconds": round(ctl_seconds, 3),
        },
        "baseline": {
            "windows_to_adapt": adapt_at(base_match),
            "bytes_migrated_total": base_total,
            "bytes_migrated_per_window": base_bytes,
            "cohort_match_per_window": [round(m, 4) for m in base_match],
            "seconds": round(base_seconds, 3),
        },
    }
    out["criteria"] = {
        "controller_adapted": out["controller"]["windows_to_adapt"]
        is not None,
        "controller_fewer_bytes": ctl_total < base_total,
    }
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/control_bench.json")
    p.add_argument("--n_files", type=int, default=300)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--duration", type=float, default=2400.0)
    p.add_argument("--windows", type=int, default=20)
    p.add_argument("--k", type=int, default=12)
    p.add_argument("--decay", type=float, default=0.7)
    args = p.parse_args(argv)

    out = run_control_bench(n_files=args.n_files, seed=args.seed,
                            duration=args.duration, n_windows=args.windows,
                            k=args.k, decay=args.decay)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"out": args.out, **out["criteria"],
                      "controller_bytes": out["controller"][
                          "bytes_migrated_total"],
                      "baseline_bytes": out["baseline"][
                          "bytes_migrated_total"],
                      "controller_adapt": out["controller"][
                          "windows_to_adapt"],
                      "baseline_adapt": out["baseline"]["windows_to_adapt"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
