"""Integrity baselines: mean-time-to-detect vs scrub budget, and what rot
costs without scrubbing.

The data-integrity counterpart of chaos_bench.py.  Three scenario
families over the 5-node topology:

**MTTD vs scrub budget** (``run_mttd_sweep``): a silent-corruption event
rots every copy on one node mid-run; the background scrubber
(faults/scrub.py) is the only detector (serve off, no node failures).
For each budget in the sweep (fractions of the worst-case full-lap scan
bytes) the bench measures the per-copy detection latency in windows and
checks it against the budget-implied bound: a round-robin scan spending
``B`` bytes/window over a population whose lap costs at most ``L`` bytes
must touch every copy within ``ceil(L / B) + 1`` windows (+1 for cursor
alignment).  All injected corruptions must be detected within the bound
at every budget.

**Rot + kill overlap** (``run_overlap_bench``): rot lands at one window,
a node holding the clean second copies dies a few windows later — the
race scrubbing exists to win.  Scrubbed + verified-read side: detection
and verified repair heal every file before the kill — zero true losses,
zero corrupt reads served.  Unscrubbed + unverified side (the baseline
production systems without a scanner actually run): garbage goes out on
the read path (``reads_corrupt_served``) and the kill turns latent rot
into permanent ground-truth loss (``true_lost``), while the blind
durability tiers never report more than the truth.  A mid-scrub
kill/resume of the scrubbed side must be bit-identical (scrub cursor +
hint queue + rot masks ride the npz checkpoint).

**Telemetry overhead** (``integrity_overhead``): the interleaved paired
methodology (chaos_bench lineage) with the corrupt fault, the scrubber
and the integrity record accounting active on BOTH sides — scrub
accounting must keep telemetry inside the repo's ≤ 1.05x budget.

``python -m cdrs_tpu.benchmarks.integrity_bench`` writes
``data/integrity_bench.json``; ``--quick`` shrinks sizes for the CI
smoke.  The bench_record (detection-margin ratio at the half-lap
budget) is auto-appended to ``data/bench_history.jsonl`` through
``benchmarks/regress.append_history`` — append-only, deduplicated on
(round, metric, platform), so re-runs never double-append.  ``--quick``
runs never append (a smoke-scale row must not become the ledger entry a
real run is deduped against); ``--history ''`` disables explicitly.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import numpy as np

from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..faults import FaultSchedule, ScrubConfig
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_mttd_sweep", "run_overlap_bench", "integrity_overhead"]

_NODES = ("dn1", "dn2", "dn3", "dn4", "dn5")


def _min_rf2_scoring():
    """validated scoring with every category at rf >= 2: one rotten copy
    is always recoverable from a clean peer (rf=1 singletons would rot
    unrecoverably by construction and muddy the loss accounting)."""
    base = validated_scoring_config()
    return dataclasses.replace(
        base, replication_factors={c: max(2, r) for c, r in
                                   base.replication_factors.items()})


def _strip(records: list[dict]) -> list[dict]:
    return [{k: v for k, v in r.items() if k != "seconds"} for r in records]


def _lap_upper_bytes(manifest, scoring, default_rf: int) -> int:
    """Worst-case bytes of one full scrub lap: every file at the largest
    rf the scoring table (or the default) can assign.  An upper bound —
    the real per-window rf mix is below it — so the implied detection
    bound is conservative, never flattering."""
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    rf_max = max(max(scoring.replication_factors.values()), default_rf)
    return int(sizes.sum()) * rf_max


def run_mttd_sweep(
    n_files: int = 400,
    seed: int = 17,
    duration: float = 1800.0,
    n_windows: int = 15,
    corrupt_window: int = 2,
    k: int = 12,
    budget_fracs: tuple[float, ...] = (0.125, 0.25, 0.5),
) -> dict:
    """Detection latency vs scrub budget (module docstring)."""
    window_seconds = duration / n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1))
    scoring = _min_rf2_scoring()
    lap = _lap_upper_bytes(manifest, scoring, default_rf=2)
    schedule_specs = [f"corrupt:dn2@{corrupt_window}:1.0"]

    sweep = []
    for frac in budget_fracs:
        budget = max(int(lap * frac), 1)
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            hysteresis_windows=1, kmeans=KMeansConfig(k=k, seed=42),
            scoring=scoring,
            fault_schedule=FaultSchedule.from_specs(schedule_specs),
            scrub=ScrubConfig(bytes_per_window=budget))
        res = ReplicationController(manifest, cfg).run(events)
        # Per-window detections: latency of a copy found at window w is
        # w - corrupt_window + 1 (the scrub pass of the landing window
        # counts as one window of scanning).
        lat_counts: list[tuple[int, int]] = []
        for r in res.records:
            found = (r.get("scrub") or {}).get("corrupt_found", 0)
            if found:
                lat_counts.append(
                    (int(r["window"]) - corrupt_window + 1, found))
        detected = sum(c for _, c in lat_counts)
        integ = res.summary()["integrity"]
        bound = int(np.ceil(lap / budget)) + 1
        max_lat = max((lw for lw, _ in lat_counts), default=None)
        sweep.append({
            "budget_bytes_per_window": budget,
            "budget_lap_fraction": frac,
            "bound_windows": bound,
            "injected_detected": detected,
            "residual_corrupt_final": integ["corrupt_copies_final"],
            "true_lost_final": integ["true_lost_final"],
            "mttd_mean_windows": round(
                sum(lw * c for lw, c in lat_counts) / detected, 3)
            if detected else None,
            "mttd_max_windows": max_lat,
            "detected_within_bound":
                detected > 0 and integ["corrupt_copies_final"] == 0
                and max_lat is not None and max_lat <= bound,
            "scrub_bytes_total": integ["scrub_bytes_total"],
        })
    return {
        "scenario": {
            "n_files": n_files, "seed": seed, "nodes": list(_NODES),
            "duration_seconds": duration, "n_windows": n_windows,
            "window_seconds": window_seconds, "k": k,
            "corrupt": schedule_specs[0], "default_rf": 2,
            "lap_upper_bytes": lap,
            "replication_factors": scoring.replication_factors,
        },
        "sweep": sweep,
    }


def run_overlap_bench(
    n_files: int = 400,
    seed: int = 17,
    duration: float = 1800.0,
    n_windows: int = 15,
    corrupt_window: int = 2,
    kill_window: int = 6,
    k: int = 12,
    resume_check: bool = True,
) -> dict:
    """Rot + node-kill overlap, scrubbed vs unscrubbed (module
    docstring)."""
    from ..serve import ServeConfig, SloSpec

    window_seconds = duration / n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1))
    scoring = _min_rf2_scoring()
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    lap = _lap_upper_bytes(manifest, scoring, default_rf=2)
    specs = [f"corrupt:dn2@{corrupt_window}:1.0",
             f"crash:dn3@{kill_window}"]
    max_bytes = int(3 * float(sizes.sum()))  # repairs + scrub both fit

    def mk(scrub_on: bool, verify: bool) -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            max_bytes_per_window=max_bytes, hysteresis_windows=1,
            kmeans=KMeansConfig(k=k, seed=42), scoring=scoring,
            fault_schedule=FaultSchedule.from_specs(specs),
            serve=ServeConfig(policy="p2c", seed=0, service_ms=0.5,
                              slo=SloSpec(target_ms=10.0,
                                          availability=0.999),
                              verify_reads=verify),
            scrub=ScrubConfig(bytes_per_window=max(lap // 2, 1))
            if scrub_on else None)
        return ReplicationController(manifest, cfg)

    def side(scrub_on: bool, verify: bool) -> tuple[dict, object]:
        t0 = time.perf_counter()
        res = mk(scrub_on, verify).run(events)
        summ = res.summary()
        integ = summ["integrity"]
        timeline = [{
            "window": r["window"], "fault_events": r["fault_events"],
            "corrupt_copies": r["integrity"]["corrupt_copies"],
            "true_lost": r["integrity"]["true_lost"],
            "detected_scrub": r["integrity"]["detected_scrub"],
            "detected_read": r["integrity"]["detected_read"],
            "detected_repair": r["integrity"]["detected_repair"],
            "reads_corrupt_served": r.get("reads_corrupt_served") or 0,
            "lost_blind": r["durability"]["lost"],
            "repair_moves": r["repair_moves"],
        } for r in res.records]
        return {
            "timeline": timeline,
            "true_lost_final": integ["true_lost_final"],
            "true_lost_max": integ["true_lost_max"],
            "corrupt_reads_served": integ["corrupt_reads_served"],
            "detected_total": integ["detected_total"],
            "detected_scrub": integ["detected_scrub"],
            "detected_read": integ["detected_read"],
            "blind_lost_final": summ["durability"]["lost_final"],
            "run_seconds": round(time.perf_counter() - t0, 3),
        }, res

    scrubbed, sres = side(scrub_on=True, verify=True)
    unscrubbed, _ = side(scrub_on=False, verify=False)

    out: dict = {
        "scenario": {
            "n_files": n_files, "seed": seed, "nodes": list(_NODES),
            "duration_seconds": duration, "n_windows": n_windows,
            "window_seconds": window_seconds, "k": k,
            "schedule": specs, "default_rf": 2,
            "scrub_bytes_per_window": max(lap // 2, 1),
            "max_bytes_per_window": max_bytes,
            "replication_factors": scoring.replication_factors,
        },
        "scrubbed": scrubbed,
        "unscrubbed": unscrubbed,
    }

    if resume_check:
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "integrity.npz")
            a = mk(True, True).run(events, checkpoint_path=ck,
                                   max_windows=corrupt_window + 2)
            b = mk(True, True).run(events, checkpoint_path=ck)
            identical = (_strip(a.records) + _strip(b.records)
                         == _strip(sres.records)
                         and bool(np.array_equal(b.rf, sres.rf))
                         and bool(np.array_equal(b.category_idx,
                                                 sres.category_idx)))
        out["kill_resume"] = {
            "killed_after_window": corrupt_window + 1,
            "bit_identical": identical,
        }
    return out


def integrity_overhead(n_files: int = 8000, duration: float = 1440.0,
                       window_seconds: float = 60.0,
                       repeats: int = 9) -> dict:
    """Telemetry wall-clock ratio with the INTEGRITY machinery active.

    Interleaved paired rounds, best-window ratio (the repo's standard
    noisy-host methodology): both sides run the corrupt fault, the
    budgeted scrubber and per-window integrity records; the instrumented
    side additionally streams ``scrub.*``/``integrity.*`` counters and
    gauges, window records and audit events through the sink.  The
    24-window run length keeps each sample several seconds long — at the
    chaos_bench 8-window scale a single sample is ~2s and the shared
    host's jitter exceeds the 5% effect being measured."""
    import tempfile

    from ..benchmarks.summary import TELEMETRY_OVERHEAD_BUDGET
    from ..obs import JsonlSink, Telemetry

    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=7, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=8))
    n_windows = int(duration // window_seconds)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    schedule = FaultSchedule.from_specs([
        f"corrupt:dn2@{max(n_windows // 3, 1)}:0.2",
        f"crash:dn4@{max(n_windows // 2, 2)}-{max(3 * n_windows // 4, 3)}",
    ])

    def mk() -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            kmeans=KMeansConfig(k=8, seed=42),
            scoring=_min_rf2_scoring(),
            fault_schedule=FaultSchedule(schedule.events),
            scrub=ScrubConfig(bytes_per_window=int(sizes.sum()) // 4))
        return ReplicationController(manifest, cfg)

    def run_plain() -> float:
        t0 = time.perf_counter()
        mk().run(events)
        return time.perf_counter() - t0

    def run_instr(path: str) -> float:
        if os.path.exists(path):
            os.remove(path)
        t0 = time.perf_counter()
        with Telemetry(JsonlSink(path)):
            mk().run(events, metrics_path=path)
        return time.perf_counter() - t0

    run_plain()  # warmup
    plain_runs: list[float] = []
    instr_runs: list[float] = []
    ratios: list[float] = []
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.jsonl")
        for r in range(max(1, repeats)):
            if r % 2 == 0:
                p, i = run_plain(), run_instr(path)
            else:
                i, p = run_instr(path), run_plain()
            plain_runs.append(p)
            instr_runs.append(i)
            ratios.append(i / p)
    ratios.sort()
    ratio = min(instr_runs) / min(plain_runs)
    return {
        "n_files": n_files,
        "windows_per_run": n_windows,
        "plain_seconds": min(plain_runs),
        "telemetry_seconds": min(instr_runs),
        "plain_runs": plain_runs,
        "telemetry_runs": instr_runs,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": ratio,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/integrity_bench.json")
    p.add_argument("--n_files", type=int, default=400)
    p.add_argument("--seed", type=int, default=17)
    p.add_argument("--duration", type=float, default=1800.0)
    p.add_argument("--windows", type=int, default=15)
    p.add_argument("--corrupt_window", type=int, default=2)
    p.add_argument("--kill_window", type=int, default=6)
    p.add_argument("--k", type=int, default=12)
    p.add_argument("--round_no", type=int, default=9)
    from .regress import add_history_argument

    add_history_argument(p)
    p.add_argument("--no_overhead", action="store_true",
                   help="skip the paired telemetry-overhead rounds")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI)")
    args = p.parse_args(argv)

    if args.quick:
        kw = dict(n_files=160, seed=args.seed, duration=720.0,
                  n_windows=8, corrupt_window=2, k=8)
        mttd = run_mttd_sweep(budget_fracs=(0.25, 0.5), **kw)
        overlap = run_overlap_bench(kill_window=4, **kw)
    else:
        kw = dict(n_files=args.n_files, seed=args.seed,
                  duration=args.duration, n_windows=args.windows,
                  corrupt_window=args.corrupt_window, k=args.k)
        mttd = run_mttd_sweep(**kw)
        overlap = run_overlap_bench(kill_window=args.kill_window, **kw)

    # The half-lap budget's detection margin: bound / actual max latency
    # (>= 1 means the scan met its budget-implied bound) — deterministic
    # per seed, so it bands tightly in the trajectory gate.
    half = next(s for s in mttd["sweep"]
                if s["budget_lap_fraction"] == 0.5)
    margin = (half["bound_windows"] / half["mttd_max_windows"]
              if half["mttd_max_windows"] else None)

    out: dict = {
        "round": args.round_no,
        "mttd": mttd,
        "overlap": overlap,
        "criteria": {
            "all_detected_within_bound": all(
                s["detected_within_bound"] for s in mttd["sweep"]),
            "scrubbed_zero_files_lost":
                overlap["scrubbed"]["true_lost_final"] == 0,
            "scrubbed_zero_corrupt_reads":
                overlap["scrubbed"]["corrupt_reads_served"] == 0,
            "unscrubbed_serves_corrupt_reads":
                overlap["unscrubbed"]["corrupt_reads_served"] > 0,
            "unscrubbed_loses_files":
                overlap["unscrubbed"]["true_lost_final"] >= 1,
            **({"mid_scrub_resume_bit_identical":
                overlap["kill_resume"]["bit_identical"]}
               if "kill_resume" in overlap else {}),
        },
        "bench_records": [
            {"metric": "integrity_mttd_margin_half_lap",
             "value": round(margin, 4) if margin else 0.0, "unit": "x",
             "backend": "numpy"},
        ],
    }

    if not args.no_overhead:
        overhead = integrity_overhead()
        out["overhead"] = overhead
        out["criteria"]["overhead_within_budget"] = overhead[
            "within_budget"]

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    from .regress import append_history, extract_records, \
        resolve_history_path

    history = resolve_history_path(args)
    appended = 0
    if history:
        appended = append_history(
            history, extract_records(out, os.path.basename(args.out)))
    print(json.dumps({
        "out": args.out, **out["criteria"],
        "history_appended": appended,
        "mttd_margin_half_lap": out["bench_records"][0]["value"],
        "unscrubbed_true_lost": overlap["unscrubbed"]["true_lost_final"],
        "unscrubbed_corrupt_reads":
            overlap["unscrubbed"]["corrupt_reads_served"],
    }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
