"""Overload-resilience baselines: sustained overfeed, brownout serving.

The overload acceptance artifact (``data/overload_bench.json``), three
scenario families over the brownout-enabled streaming daemon
(daemon/brownout.py + the lag accounting in daemon/core.py):

**Sustained overfeed** (``run_overload_sustain``): a live writer
appends the event log at >= 2x the daemon's calibrated decision rate —
twice as many windows arrive per second as the un-degraded loop can
decide.  Without the ladder that lag grows without bound; WITH it the
``coalesce`` rung multiplies decision capacity (up to ``coalesce_max``
windows per decision), so lag must plateau below a fixed bound, the
ladder must engage >= 2 rungs, and once the feed relaxes to 0.5x the
ladder must release all the way back to rung 0 (hysteretic, in reverse
order).  Acceptance: bounded lag + engaged + fully recovered.

**Serving availability under brownout** (``run_availability``): a
maximally-overfed log (pre-written, so the daemon starts the whole
stream behind) with the serve path on and a crash fault in the window
grid, thresholds low enough that the ladder rides to ``shed_reads``.
Availability over the whole run — routed reads that found a live
replica, out of all reads MINUS the explicitly-shed ones — must stay
>= 99%: shedding is an explicit, bounded, seeded rejection, never
silent unavailability.  Acceptance: availability >= 0.99 with sheds
actually exercised.

**Coalescing determinism** (``run_coalesce_determinism``): the same
overfed log run twice must produce byte-identical window records and
rung transitions — merged decisions, group sizes, shed counts and all
(the decision-reproducibility contract degraded mode inherits).  Mass
conservation: every ingested event folds into exactly one decision and
every decision publishes exactly one epoch.

``python -m cdrs_tpu.benchmarks.overload_bench`` writes the artifact
and appends round-20 rows to ``data/bench_history.jsonl``
(regress.append_history, deduped); ``--quick`` shrinks scales for the
CI smoke step and never appends.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time

import numpy as np

from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..daemon import BrownoutConfig, DaemonConfig, StreamDaemon
from ..faults import FaultSchedule, ScrubConfig
from ..io.events import EventLog
from ..serve import ServeConfig
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_overload_sustain", "run_availability",
           "run_coalesce_determinism"]

_NODES = ("dn1", "dn2", "dn3", "dn4", "dn5")


def _controller(manifest, window_seconds: float, k: int, *,
                serve: bool = False,
                faults: bool = False) -> ReplicationController:
    cfg = ControllerConfig(
        window_seconds=window_seconds, default_rf=2, backend="numpy",
        kmeans=KMeansConfig(k=k, seed=42),
        scoring=validated_scoring_config(),
        serve=ServeConfig(policy="p2c", seed=3) if serve else None,
        fault_schedule=(FaultSchedule.from_specs(["crash:dn2@3-3"])
                        if faults else None),
        scrub=(ScrubConfig(bytes_per_window=10**9) if faults else None))
    return ReplicationController(manifest, cfg)


def _population(n_files: int, duration: float, seed: int):
    manifest = generate_population(GeneratorConfig(
        n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=duration, seed=seed + 1))
    return manifest, events


def _window_slices(events, window_seconds: float) -> list[EventLog]:
    """The event log cut on the controller's window grid — the unit the
    live feeder appends (whole windows, so window closes are driven by
    the FEED rate, which is the quantity under test).  The grid origin
    matches control/windows.py: floor of the first event's timestamp."""
    t0 = np.floor(events.ts[0])
    idx = np.floor_divide(events.ts - t0, window_seconds).astype(np.int64)
    out = []
    for w in range(int(idx.max()) + 1):
        m = idx == w
        out.append(EventLog(ts=events.ts[m], path_id=events.path_id[m],
                            op=events.op[m], client_id=events.client_id[m],
                            clients=events.clients))
    return out


def run_overload_sustain(n_files: int = 2_000, n_burst: int = 24,
                         n_calm: int = 16,
                         window_seconds: float = 60.0, k: int = 10,
                         overfeed: float = 2.0,
                         seed: int = 47) -> dict:
    """Live >= 2x overfeed, then a 0.5x calm-down (module docstring):
    bounded lag, ladder engaged, full hysteretic recovery."""
    n_windows = 1 + n_burst + n_calm
    manifest, events = _population(n_files,
                                   n_windows * window_seconds, seed)
    slices = _window_slices(events, window_seconds)

    with tempfile.TemporaryDirectory() as td:
        # Calibrate the un-degraded decision rate: mean seconds per
        # decided window over the same workload, ladder off.
        log = os.path.join(td, "cal.cdrsb")
        events.write_binary(log, manifest)
        cal = StreamDaemon(_controller(manifest, window_seconds, k))
        cal.run(log)
        d_mean = max(float(np.mean(cal.decision_seconds)), 0.005)

        live = os.path.join(td, "live.cdrsb")
        slices[0].write_binary(live, manifest)
        # Release thresholds sit ABOVE the follow-mode floor: the
        # trailing partial window never closes, so measured lag bottoms
        # out around 1 window — a release bound of exactly 1.0 would
        # make full recovery a rounding coin-flip.
        bc = BrownoutConfig(hold=1,
                            release=(1.2, 1.5, 2.0, 3.0, 4.0))
        daemon = StreamDaemon(
            _controller(manifest, window_seconds, k),
            DaemonConfig(follow=True, poll=d_mean / 4.0, brownout=bc))

        def feeder():
            # Absolute-deadline pacing: slice i lands at its scheduled
            # instant regardless of how long appends take, so the feed
            # rate is exactly the one claimed.  Burst phase: `overfeed`
            # windows arrive per calibrated decision time; calm phase:
            # one window per 2 decision times.
            start = time.monotonic()
            due = 0.0
            for i, sl in enumerate(slices[1:], start=1):
                due += (d_mean / overfeed if i <= n_burst
                        else d_mean * 2.0)
                delay = start + due - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                sl.write_binary(live, manifest, append=True)
            # Let the daemon drain the backlog and walk the ladder back
            # down, then stop it between windows.
            # The trailing partial window never closes in follow mode,
            # so "drained" is level 0 with lag below the bottom engage
            # threshold (nothing further can happen), not exactly zero.
            deadline = time.monotonic() + 120.0
            floor = bc.engage[0]
            while time.monotonic() < deadline:
                if daemon._lag["windows"] < floor \
                        and daemon._ladder.level == 0:
                    break
                time.sleep(d_mean / 2.0)
            daemon.request_stop("bench_done")

        th = threading.Thread(target=feeder)
        th.start()
        dig = daemon.run(live)
        th.join()

    lag_series = [r["daemon"]["lag_windows"] for r in daemon.records]
    levels = [r["daemon"]["brownout_level"] for r in daemon.records]
    max_level = max(levels, default=0)
    engaged = [t for t in daemon.brownout_log if t["state"] == "engage"]
    released = [t for t in daemon.brownout_log
                if t["state"] == "release"]
    # Bounded: at >= 2x the feed outruns decisions, so in the worst
    # case the whole burst is pending at once — lag may spike to the
    # injected backlog (n_burst windows, plus one coalesce group of
    # grid slack) but NEVER past it: the calm-phase feed must be
    # absorbed as it arrives, not compound on top of the backlog, and
    # the backlog itself must fully drain by the end.
    bound = n_burst + bc.coalesce_max
    return {
        "n_windows": n_windows,
        "overfeed": overfeed,
        "decision_seconds_calibrated": round(d_mean, 5),
        "windows_decided": len(daemon.records),
        "windows_coalesced": int(dig["brownout"]["windows_coalesced"]),
        "max_lag_windows": max(lag_series, default=0.0),
        "lag_bound_windows": float(bound),
        "max_rung_engaged": int(max_level),
        "rungs_engaged": sorted({t["rung"] for t in engaged}),
        "rung_transitions": len(daemon.brownout_log),
        "final_rung": int(dig["brownout"]["level"]),
        "final_lag_windows": float(dig["lag"]["windows"]),
        "stop_reason": dig["stop_reason"],
        "lag_bounded": max(lag_series, default=0.0) <= bound
            and dig["lag"]["windows"] < bc.engage[0],
        "ladder_engaged": max_level >= 2,
        "recovered_to_rung0": dig["brownout"]["level"] == 0
            and len(released) >= max_level,
    }


def _overfed_daemon(manifest, window_seconds: float, k: int):
    """Brownout daemon that starts a whole pre-written log behind, with
    thresholds low enough to ride the ladder to ``shed_reads``."""
    return StreamDaemon(
        _controller(manifest, window_seconds, k, serve=True,
                    faults=True),
        DaemonConfig(brownout=BrownoutConfig(
            engage=(0.5, 1.0, 1.5, 2.0, 3.0),
            release=(0.2, 0.4, 0.6, 0.8, 1.0), hold=1)))


def run_availability(n_files: int = 4_000, n_windows: int = 16,
                     window_seconds: float = 120.0, k: int = 10,
                     seed: int = 53) -> dict:
    """Routed-read availability across a fully-overfed brownout run:
    >= 99% excluding the explicit, seeded sheds."""
    manifest, events = _population(n_files,
                                   n_windows * window_seconds, seed)
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        events.write_binary(log, manifest)
        daemon = _overfed_daemon(manifest, window_seconds, k)
        dig = daemon.run(log)
    recs = daemon.records
    n_reads = sum(int(r.get("n_reads", 0)) for r in recs)
    shed = sum(int(r.get("reads_shed", 0)) for r in recs)
    unavailable = sum(int(r.get("unavailable_reads", 0)) for r in recs)
    served = n_reads - shed
    availability = (served - unavailable) / served if served else 1.0
    return {
        "n_reads": n_reads,
        "reads_shed": shed,
        "shed_fraction_of_total": round(shed / n_reads, 4)
            if n_reads else 0.0,
        "reads_unavailable": unavailable,
        "availability_excluding_sheds": round(availability, 6),
        "max_rung_engaged": max(
            (r["daemon"]["brownout_level"] for r in recs), default=0),
        "windows_with_sheds": sum(
            1 for r in recs if r.get("reads_shed", 0) > 0),
        "epochs_published": int(dig["epochs_published"]),
        "sheds_exercised": shed > 0,
        "available_99": availability >= 0.99,
    }


def run_coalesce_determinism(n_files: int = 4_000, n_windows: int = 16,
                             window_seconds: float = 120.0, k: int = 10,
                             seed: int = 53) -> dict:
    """Double-run identity of the degraded decision stream + mass
    conservation of coalesced folds (module docstring)."""

    def _strip(recs):
        return [{kk: v for kk, v in r.items() if kk != "seconds"}
                for r in recs]

    manifest, events = _population(n_files,
                                   n_windows * window_seconds, seed)
    runs = []
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        events.write_binary(log, manifest)
        for _ in range(2):
            daemon = _overfed_daemon(manifest, window_seconds, k)
            dig = daemon.run(log)
            runs.append((daemon, dig))
    (d1, dig1), (d2, _) = runs
    groups = [r["daemon"]["coalesced"] for r in d1.records]
    return {
        "windows_in_log": n_windows,
        "decisions": len(d1.records),
        "coalesce_groups": groups,
        "windows_coalesced": int(dig1["brownout"]["windows_coalesced"]),
        "records_identical": _strip(d1.records) == _strip(d2.records),
        "transitions_identical": d1.brownout_log == d2.brownout_log,
        "events_conserved": sum(r["n_events"] for r in d1.records)
            == d1.events_ingested,
        "one_epoch_per_decision": dig1["epochs_published"]
            == dig1["windows_processed"] == len(d1.records),
        "coalescing_engaged": any(g > 1 for g in groups),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/overload_bench.json")
    p.add_argument("--round", type=int, default=20, dest="round_no",
                   help="PR-round stamp for the regress history")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI); never appends "
                        "to the history")
    from .regress import add_history_argument

    add_history_argument(p)
    args = p.parse_args(argv)

    if args.quick:
        sustain = run_overload_sustain(n_files=600, n_burst=16,
                                       n_calm=12)
        avail = run_availability(n_files=1_500, n_windows=12)
        det = run_coalesce_determinism(n_files=1_500, n_windows=12)
    else:
        sustain = run_overload_sustain()
        avail = run_availability()
        det = run_coalesce_determinism()

    out: dict = {
        "round": args.round_no,
        "overload_sustain": sustain,
        "availability_under_brownout": avail,
        "coalesce_determinism": det,
    }
    out["criteria"] = {
        "lag_bounded_under_2x_overfeed": sustain["lag_bounded"]
            and sustain["ladder_engaged"],
        "ladder_recovered_to_rung0": sustain["recovered_to_rung0"],
        "availability_99_excluding_sheds": avail["available_99"]
            and avail["sheds_exercised"],
        "coalescing_deterministic": det["records_identical"]
            and det["transitions_identical"]
            and det["events_conserved"]
            and det["one_epoch_per_decision"]
            and det["coalescing_engaged"],
    }
    out["bench_records"] = [
        {"metric": "overload_max_lag_windows",
         "value": sustain["max_lag_windows"], "unit": "windows",
         "direction": "lower", "backend": "numpy"},
        {"metric": "overload_availability_excluding_sheds",
         "value": avail["availability_excluding_sheds"],
         "unit": "fraction", "direction": "higher", "backend": "numpy"},
        {"metric": "overload_windows_coalesced",
         "value": det["windows_coalesced"], "unit": "windows",
         "backend": "numpy"},
    ]

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    appended = 0
    if not args.quick:
        from .regress import append_history, extract_records, \
            resolve_history_path

        history = resolve_history_path(args)
        if history:
            appended = append_history(
                history, extract_records(out,
                                         os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "max_lag_windows": sustain["max_lag_windows"],
                      "availability":
                          avail["availability_excluding_sheds"],
                      "history_appended": appended}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
