"""Geo-hierarchical placement + elasticity: the round-14 ledger.

ROADMAP item 6's claims, pinned as measurements:

* **region_loss** — kill a whole region (4 of 12 nodes, correlated) on
  the SAME workload seed under (a) the geo hierarchy and (b) the same
  racks without a region level: hierarchy-aware placement must end with
  ZERO lost files while flat placement measurably loses some — for
  replicate (rf >= 2) and EC(6,3) strategies, in both the materialized
  (rng) and functional (hash) placement modes.
* **hier_throughput** — the hierarchical greedy chooser's recompute
  rate (files/s and resolved placements/s on one core) next to the flat
  chooser's, so the cost of the descend-and-spread policy is a ledger
  number, not a guess.
* **black_friday** — the elastic loop end to end: flash crowd ->
  SLO-burn scale-out (capacity doubles), rebalance traffic EXACTLY the
  addition-pruned epoch-diff moved set and inside the shared churn
  budget, final-window p99 back under the SLO bound, drain back to
  baseline capacity.
* **wan_partition** — partition a region off the WAN with region-local
  cold stripes homed in it: stranded files (unreachable, not lost),
  repairs stalled (partition backoff, no budget burned on doomed WAN
  copies), full heal convergence after.

``python -m cdrs_tpu.benchmarks.geo_bench`` writes
``data/geo_bench.json`` and appends round-14 rows to
``data/bench_history.jsonl`` (regress.append_history, deduped);
``--quick`` shrinks scales for the CI smoke step and never appends.
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

from ..cluster.placement import ClusterTopology
from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ElasticPolicy, ReplicationController
from ..faults import FaultSchedule
from ..placement_fn import compute_placement
from ..sim.access import simulate_access, simulate_flash_crowd
from ..sim.generator import generate_population
from ..storage import resolve_storage_config, storage_config_from_dict

__all__ = ["run_geo_bench"]

_NODES12 = tuple(f"dn{i}" for i in range(1, 13))
_GEO = {
    "nodes": list(_NODES12),
    "levels": ["rack", "region"],
    "rack": {f"r{j}": [f"dn{2 * j + 1}", f"dn{2 * j + 2}"]
             for j in range(6)},
    "region": {"eu": ["r0", "r1"], "us": ["r2", "r3"],
               "ap": ["r4", "r5"]},
    "edge_bytes": {"rack": 1.0, "region": 4.0},
    "edge_latency": {"rack": 1.5, "region": 8.0},
}
_FLAT = {"nodes": list(_NODES12), "levels": ["rack"],
         "rack": _GEO["rack"]}
_EU = ("dn1", "dn2", "dn3", "dn4")


def _min_rf2_scoring():
    import dataclasses

    s = validated_scoring_config()
    rfs = dict(s.replication_factors)
    rfs["Moderate"] = max(2, rfs["Moderate"])
    return dataclasses.replace(s, replication_factors=rfs)


# -- region-loss contrast ----------------------------------------------------

def _region_loss_run(n_files: int, seed: int, topo_spec: dict,
                     mode: str, ec: bool) -> dict:
    man = generate_population(GeneratorConfig(
        n_files=n_files, seed=seed, nodes=_NODES12))
    events = simulate_access(man, SimulatorConfig(
        duration_seconds=1800.0, seed=seed + 1))
    if "region" in topo_spec["levels"]:
        specs = ["crash:region:eu@5-9"]
    else:
        specs = [f"crash:{n}@5-9" for n in _EU]
    scoring = _min_rf2_scoring()
    cfg = ControllerConfig(
        window_seconds=120.0, default_rf=2, drift_threshold=0.02,
        max_bytes_per_window=int(
            np.asarray(man.size_bytes, np.int64).sum() * 0.25),
        kmeans=KMeansConfig(k=10, seed=42), scoring=scoring,
        topology=ClusterTopology.from_hierarchy(topo_spec),
        fault_schedule=FaultSchedule(FaultSchedule.from_specs(specs)),
        placement_mode=mode,
        storage=(resolve_storage_config("ec_archival", scoring)
                 if ec else None))
    t0 = time.perf_counter()
    res = ReplicationController(man, cfg).run(events)
    dur = [r["durability"] for r in res.records if r.get("durability")]
    return {
        "lost_max": int(max(d["lost"] for d in dur)),
        "lost_final": int(dur[-1]["lost"]),
        "under_replicated_final": int(dur[-1]["under_replicated"]),
        "repair_bytes_total": int(sum(r.get("repair_bytes", 0)
                                      for r in res.records)),
        "seconds": round(time.perf_counter() - t0, 2),
    }


def _bench_region_loss(n_files: int, seed: int) -> dict:
    out: dict = {"n_files": n_files, "seed": seed,
                 "killed_region_nodes": list(_EU)}
    for strat in ("replicate", "ec"):
        for mode in ("materialized", "functional"):
            hier = _region_loss_run(n_files, seed, _GEO, mode,
                                    strat == "ec")
            flat = _region_loss_run(n_files, seed, _FLAT, mode,
                                    strat == "ec")
            out[f"{strat}_{mode}"] = {
                "lost_max_hier": hier["lost_max"],
                "lost_max_flat": flat["lost_max"],
                "lost_final_hier": hier["lost_final"],
                "seconds": hier["seconds"] + flat["seconds"],
            }
            print(json.dumps({"region_loss": f"{strat}/{mode}",
                              "lost_hier": hier["lost_max"],
                              "lost_flat": flat["lost_max"]}))
    return out


# -- hierarchical chooser throughput -----------------------------------------

def _bench_hier_throughput(n: int, rounds: int) -> dict:
    rng = np.random.default_rng(3)
    fids = np.arange(n, dtype=np.int64)
    prim = rng.integers(0, 12, n).astype(np.int32)
    rf3 = np.full(n, 3, dtype=np.int32)
    geo = ClusterTopology.from_hierarchy(_GEO)
    flat = ClusterTopology(_NODES12)
    best = {"hier": float("inf"), "flat": float("inf")}
    slots = {}
    for r in range(rounds):
        order = ("hier", "flat") if r % 2 == 0 else ("flat", "hier")
        for case in order:
            topo = geo if case == "hier" else flat
            t0 = time.perf_counter()
            _, rr = compute_placement(fids, rf3, prim, topo, 0)
            best[case] = min(best[case], time.perf_counter() - t0)
            slots[case] = int(rr.sum())
    return {
        "n_files": n, "rounds": rounds,
        "hier_files_per_sec": round(n / best["hier"], 1),
        "hier_placements_per_sec": round(slots["hier"] / best["hier"],
                                         1),
        "flat_placements_per_sec": round(slots["flat"] / best["flat"],
                                         1),
        "hier_vs_flat_cost": round(best["hier"] / best["flat"], 2),
    }


# -- black friday (elasticity) -----------------------------------------------

def _bench_black_friday(n_files: int, seed: int) -> dict:
    man = generate_population(GeneratorConfig(n_files=n_files,
                                              seed=seed))
    cohort = np.asarray([c == "hot" for c in man.category])
    events, _ = simulate_flash_crowd(
        man, SimulatorConfig(duration_seconds=1800.0, seed=seed + 1),
        cohort=cohort, start=450.0, duration=540.0, boost=25.0)
    from ..serve import ServeConfig, SloSpec

    pol = ElasticPolicy(pool=("sb1", "sb2", "sb3"), burn_hot=0.4,
                        util_hot=0.9, hot_windows=2, util_cool=0.5,
                        cool_windows=2, drain_spacing=1)
    max_bytes = int(np.asarray(man.size_bytes, np.int64).sum() * 0.25)
    cfg = ControllerConfig(
        window_seconds=120.0, default_rf=2, drift_threshold=0.02,
        max_bytes_per_window=max_bytes,
        kmeans=KMeansConfig(k=8, seed=42),
        scoring=validated_scoring_config(),
        placement_mode="functional", elastic=pol,
        serve=ServeConfig(policy="p2c", service_ms=6.0,
                          slo=SloSpec(target_ms=60.0)))
    t0 = time.perf_counter()
    res = ReplicationController(man, cfg).run(events)
    recs = res.records
    el = [r.get("elastic") or {} for r in recs]
    moved = sum(e.get("moved", 0) for e in el)
    rebal = sum(e.get("rebalanced", 0) for e in el)
    p99 = [r.get("latency_p99_ms") for r in recs]
    crowd_peak = max(p for p in p99 if p is not None)
    budget_ok = all(
        r.get("repair_bytes", 0) + r["bytes_migrated"]
        + (r.get("elastic") or {}).get("rebalance_bytes", 0)
        <= max_bytes for r in recs)
    return {
        "n_files": n_files, "seed": seed,
        "scaled_out_window": next(
            (r["window"] for r, e in zip(recs, el) if "added" in e),
            None),
        "moved_set": int(moved),
        "rebalanced": int(rebal),
        "rebalance_equals_moved": moved == rebal and moved > 0,
        "rebalance_bytes": int(sum(e.get("rebalance_bytes", 0)
                                   for e in el)),
        "budget_conserved": bool(budget_ok),
        "p99_peak_ms": round(float(crowd_peak), 2),
        "p99_final_ms": round(float(p99[-1]), 3),
        "p99_recovery_x": round(float(crowd_peak) / float(p99[-1]), 1),
        "drained_back_to_baseline": bool(
            recs[-1]["durability"]["nodes_up"] == 3),
        "seconds": round(time.perf_counter() - t0, 2),
    }


# -- WAN partition (stranded != lost) ----------------------------------------

def _bench_wan_partition(n_files: int, seed: int) -> dict:
    man = generate_population(GeneratorConfig(
        n_files=n_files, seed=seed, nodes=_NODES12))
    events = simulate_access(man, SimulatorConfig(
        duration_seconds=1800.0, seed=seed + 1))
    scoring = _min_rf2_scoring()
    cfg = ControllerConfig(
        window_seconds=120.0, default_rf=2, drift_threshold=0.02,
        max_bytes_per_window=int(
            np.asarray(man.size_bytes, np.int64).sum() * 0.25),
        kmeans=KMeansConfig(k=10, seed=42), scoring=scoring,
        topology=ClusterTopology.from_hierarchy(_GEO),
        fault_schedule=FaultSchedule(FaultSchedule.from_specs(
            ["partition:region:eu@4-7"])),
        placement_mode="functional",
        storage=storage_config_from_dict(
            {"strategies": {"Archival": {"k": 2, "m": 1, "tier": "cold",
                                         "locality": "region"}}}))
    t0 = time.perf_counter()
    res = ReplicationController(man, cfg).run(events)
    dur = [r["durability"] for r in res.records if r.get("durability")]
    stranded_peak = max(d.get("unreachable", 0) for d in dur)
    lost_while_stranded = max(
        d["lost"] for d in dur if d.get("unreachable", 0) > 0)
    return {
        "n_files": n_files, "seed": seed,
        "stranded_peak": int(stranded_peak),
        "lost_while_stranded": int(lost_while_stranded),
        "stalled_repairs": int(sum(
            r.get("repair_deferred_partition", 0)
            for r in res.records)),
        "healed_final": bool(
            dur[-1].get("unreachable", 0) == 0
            and dur[-1]["under_replicated"] == 0
            and dur[-1]["lost"] == 0),
        "seconds": round(time.perf_counter() - t0, 2),
    }


def run_geo_bench(*, contrast_n: int, chooser_n: int, elastic_n: int,
                  seed: int = 21, rounds: int = 3) -> dict:
    out: dict = {"methodology":
                 "interleaved paired rounds, best-of-rounds "
                 "(chooser); single seeded runs (scenario benches)"}
    out["region_loss"] = _bench_region_loss(contrast_n, seed)
    out["hier_throughput"] = _bench_hier_throughput(chooser_n, rounds)
    print(json.dumps({"hier_mplacements_per_sec": round(
        out["hier_throughput"]["hier_placements_per_sec"] / 1e6, 2)}))
    out["black_friday"] = _bench_black_friday(elastic_n, seed + 2)
    print(json.dumps({"black_friday_p99_recovery":
                      out["black_friday"]["p99_recovery_x"]}))
    out["wan_partition"] = _bench_wan_partition(contrast_n, seed + 1)
    print(json.dumps({"wan_stranded_peak":
                      out["wan_partition"]["stranded_peak"]}))
    rl = out["region_loss"]
    out["criteria"] = {
        "region_loss_zero_hier_all_modes": all(
            rl[k]["lost_max_hier"] == 0
            for k in ("replicate_materialized", "replicate_functional",
                      "ec_materialized", "ec_functional")),
        "region_loss_positive_flat_all_modes": all(
            rl[k]["lost_max_flat"] > 0
            for k in ("replicate_materialized", "replicate_functional",
                      "ec_materialized", "ec_functional")),
        "black_friday_rebalance_equals_moved":
            out["black_friday"]["rebalance_equals_moved"],
        "black_friday_budget_conserved":
            out["black_friday"]["budget_conserved"],
        "black_friday_drained":
            out["black_friday"]["drained_back_to_baseline"],
        "wan_stranded_not_lost":
            out["wan_partition"]["stranded_peak"] > 0
            and out["wan_partition"]["lost_while_stranded"] == 0,
        "wan_heal_converged": out["wan_partition"]["healed_final"],
    }
    out["bench_records"] = [
        {"metric": "geo_regionloss_lost_flat_ec",
         "value": float(rl["ec_functional"]["lost_max_flat"]),
         "unit": "files", "direction": "higher", "backend": "numpy"},
        {"metric": "geo_hier_mplacements",
         "value": round(out["hier_throughput"]
                        ["hier_placements_per_sec"] / 1e6, 2),
         "unit": "M/s", "backend": "numpy"},
        {"metric": "geo_hier_vs_flat_cost",
         "value": out["hier_throughput"]["hier_vs_flat_cost"],
         "unit": "x", "direction": "lower", "backend": "numpy"},
        {"metric": "geo_blackfriday_p99_recovery",
         "value": out["black_friday"]["p99_recovery_x"], "unit": "x",
         "backend": "numpy"},
        {"metric": "geo_blackfriday_rebalance_bytes",
         "value": float(out["black_friday"]["rebalance_bytes"]),
         "unit": "bytes", "direction": "lower", "backend": "numpy"},
    ]
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/geo_bench.json")
    p.add_argument("--round", type=int, default=14, dest="round_no",
                   help="PR-round stamp for the regress history")
    from .regress import add_history_argument

    add_history_argument(p)
    p.add_argument("--rounds", type=int, default=3,
                   help="interleaved paired timing rounds (chooser)")
    p.add_argument("--quick", action="store_true",
                   help="small scales for smoke runs (CI); never "
                        "appends to the history")
    args = p.parse_args(argv)

    if args.quick:
        out = run_geo_bench(contrast_n=300, chooser_n=500_000,
                            elastic_n=200, rounds=2)
    else:
        out = run_geo_bench(contrast_n=400, chooser_n=10_000_000,
                            elastic_n=300, rounds=args.rounds)
    out["round"] = args.round_no
    out["quick"] = bool(args.quick)

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    appended = 0
    if not args.quick:
        from .regress import append_history, extract_records, \
            resolve_history_path

        history = resolve_history_path(args)
        if history:
            appended = append_history(
                history, extract_records(out,
                                         os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "history_appended": appended}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
