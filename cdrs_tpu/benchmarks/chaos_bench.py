"""Durability baselines: node kills, rack kills, partitions, stragglers.

The chaos counterpart of control_bench.py.  Two scenario families:

**Kill one node** (``run_chaos_bench`` -> data/chaos_bench.json): a
stationary workload on a 5-node topology settles into its category plan,
then one node crashes at a fixed window and never returns.  The
fault-injected controller (control/controller.py + faults/) must
re-replicate every under-replicated file back to its (effective) target
rf through the SAME per-window churn budget drift migrations use.
Reported: windows to full re-replication, repair traffic + per-window
proof the budget held, zero files lost (min-rf-2 scoring table — any rf=1
category trivially loses a node's singletons), kill/resume bit-identity,
and the telemetry-overhead ratio (≤ 1.05x budget, interleaved paired
rounds, best-window — the repo's standard methodology; the instrumented
schedule now includes a partition and a straggler so the new
fault-accounting paths are inside the measured loop).

**Rack kill + partition** (``run_rack_bench`` ->
data/chaos_rack_bench.json): a 6-node topology in 3 racks of 2.
(a) A whole rack crashes permanently: with the domain-aware placement
(``--racks``) every rf >= 2 file keeps a replica outside the dead rack —
ZERO lost; the SAME schedule under the flat (rack-blind) policy loses a
measurable file count — the HDFS/CRUSH rack-awareness claim, actually
measured.  (b) A rack-sized network partition opens and heals within the
run, with a straggler degrading one survivor: reads behind the partition
fail (counted), stranded repairs defer with backoff instead of burning
churn, straggler copies are charged size/throughput against the budget,
and after the heal the run ends with zero lost / zero correlated-risk
files; a controller killed mid-partition resumes bit-identically.

``python -m cdrs_tpu.benchmarks.chaos_bench`` writes both artifacts.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

import numpy as np

from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..faults import FaultSchedule
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_chaos_bench", "run_rack_bench", "chaos_overhead"]

_NODES = ("dn1", "dn2", "dn3", "dn4", "dn5")
#: Rack scenarios: 6 nodes in 3 racks of 2 — one rack is a minority the
#: cluster must survive losing outright.
_RACK_NODES = ("dn1", "dn2", "dn3", "dn4", "dn5", "dn6")
_RACK_SPEC = "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6"
_KILLED_RACK = ("dn3", "dn4")


def _min_rf2_scoring():
    """validated scoring with Moderate raised 1 -> 2 (module docstring)."""
    base = validated_scoring_config()
    rf = dict(base.replication_factors)
    rf["Moderate"] = max(2, rf["Moderate"])
    return dataclasses.replace(base, replication_factors=rf)


def _strip(records: list[dict]) -> list[dict]:
    """Records minus wall-clock noise: the bit-identity comparison key."""
    return [{k: v for k, v in r.items() if k != "seconds"} for r in records]


def run_chaos_bench(
    n_files: int = 400,
    seed: int = 11,
    duration: float = 1800.0,
    n_windows: int = 15,
    kill_window: int = 6,
    k: int = 12,
    max_bytes_frac: float = 0.25,
    resume_check: bool = True,
    overhead: bool = True,
    overhead_repeats: int = 9,
) -> dict:
    """Run the kill-one-node scenario; returns the artifact dict."""
    window_seconds = duration / n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1))
    scoring = _min_rf2_scoring()
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    max_bytes = int(max_bytes_frac * float(sizes.sum()))
    schedule = FaultSchedule.from_specs([f"crash:dn2@{kill_window}"])

    def mk() -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            max_bytes_per_window=max_bytes, hysteresis_windows=1,
            kmeans=KMeansConfig(k=k, seed=42), scoring=scoring,
            fault_schedule=FaultSchedule(schedule.events))
        return ReplicationController(manifest, cfg)

    t0 = time.perf_counter()
    res = mk().run(events)
    run_seconds = time.perf_counter() - t0

    timeline = []
    recover_at = None
    for r in res.records:
        d = r["durability"]
        degraded = d["lost"] + d["at_risk"] + d["under_replicated"]
        timeline.append({
            "window": r["window"], "fault_events": r["fault_events"],
            "nodes_up": d["nodes_up"], "lost": d["lost"],
            "at_risk": d["at_risk"],
            "under_replicated": d["under_replicated"],
            "repair_moves": r["repair_moves"],
            "repair_bytes": r["repair_bytes"],
            "repair_backlog": r["repair_backlog"],
            "bytes_migrated": r["bytes_migrated"],
            "locality_after": None if r["locality_after"] is None
            else round(r["locality_after"], 4),
        })
        if (r["window"] >= kill_window and degraded == 0
                and recover_at is None):
            recover_at = r["window"]
    lost_max = max(t["lost"] for t in timeline)
    budget_ok = all(t["repair_bytes"] + t["bytes_migrated"] <= max_bytes
                    for t in timeline)

    out: dict = {
        "scenario": {
            "n_files": n_files, "seed": seed, "nodes": list(_NODES),
            "duration_seconds": duration, "n_windows": n_windows,
            "window_seconds": window_seconds, "k": k,
            "kill": f"dn2@{kill_window}", "default_rf": 2,
            "replication_factors": scoring.replication_factors,
            "max_bytes_per_window": max_bytes,
            "max_bytes_frac": max_bytes_frac,
        },
        "timeline": timeline,
        "recovery": {
            "windows_to_full_re_replication":
                None if recover_at is None else recover_at - kill_window,
            "files_lost_max": lost_max,
            "repair_bytes_total": int(sum(t["repair_bytes"]
                                          for t in timeline)),
            "repair_moves_total": int(sum(t["repair_moves"]
                                          for t in timeline)),
            "unavailable_reads": res.summary()["durability"][
                "unavailable_reads"],
            "run_seconds": round(run_seconds, 3),
        },
    }

    if resume_check:
        import os
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "chaos.npz")
            a = mk().run(events, checkpoint_path=ck,
                         max_windows=kill_window + 2)  # killed mid-outage
            b = mk().run(events, checkpoint_path=ck)
            identical = (_strip(a.records) + _strip(b.records)
                         == _strip(res.records)
                         and bool(np.array_equal(b.rf, res.rf))
                         and bool(np.array_equal(b.category_idx,
                                                 res.category_idx)))
        out["kill_resume"] = {"killed_after_window": kill_window + 1,
                              "bit_identical": identical}

    if overhead:
        out["overhead"] = chaos_overhead(repeats=overhead_repeats)

    out["criteria"] = {
        "recovered_within_run": recover_at is not None,
        "zero_files_lost": lost_max == 0,
        "budget_respected": budget_ok,
        **({"kill_resume_bit_identical": out["kill_resume"][
            "bit_identical"]} if resume_check else {}),
        **({"overhead_within_budget": out["overhead"]["within_budget"]}
           if overhead else {}),
    }
    return out


def _durability_timeline(records: list[dict]) -> list[dict]:
    """Per-window durability/repair digest for the artifact timelines."""
    out = []
    for r in records:
        d = r["durability"]
        out.append({
            "window": r["window"], "fault_events": r["fault_events"],
            "nodes_up": d["nodes_up"],
            "nodes_partitioned": d.get("nodes_partitioned", 0),
            "lost": d["lost"], "unreachable": d.get("unreachable", 0),
            "at_risk": d["at_risk"],
            "under_replicated": d["under_replicated"],
            "correlated_risk": d.get("correlated_risk", 0),
            "repair_moves": r["repair_moves"],
            "repair_bytes": r["repair_bytes"],
            "repair_bytes_copied": r.get("repair_bytes_copied", 0),
            "repair_rebalanced": r.get("repair_rebalanced", 0),
            "repair_deferred_partition":
                r.get("repair_deferred_partition", 0),
            "repair_backlog": r["repair_backlog"],
            "bytes_migrated": r["bytes_migrated"],
            "unavailable_reads": r.get("unavailable_reads", 0),
        })
    return out


def run_rack_bench(
    n_files: int = 400,
    seed: int = 13,
    duration: float = 1800.0,
    n_windows: int = 15,
    kill_window: int = 5,
    partition_windows: tuple[int, int] = (4, 7),
    degrade_factor: float = 0.25,
    k: int = 12,
    max_bytes_frac: float = 0.25,
    resume_check: bool = True,
) -> dict:
    """Rack-kill + rack-partition scenarios (module docstring); returns
    the ``data/chaos_rack_bench.json`` artifact dict."""
    from ..cluster import ClusterTopology

    window_seconds = duration / n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_RACK_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1))
    scoring = _min_rf2_scoring()
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    max_bytes = int(max_bytes_frac * float(sizes.sum()))
    racked = ClusterTopology.from_rack_spec(_RACK_NODES, _RACK_SPEC)

    def mk(schedule: FaultSchedule,
           topology=None) -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            max_bytes_per_window=max_bytes, hysteresis_windows=1,
            kmeans=KMeansConfig(k=k, seed=42), scoring=scoring,
            fault_schedule=FaultSchedule(schedule.events),
            topology=topology)
        return ReplicationController(manifest, cfg)

    # -- (a) whole-rack kill: domain-aware vs flat placement ---------------
    kill = FaultSchedule.from_specs(
        [f"crash:{n}@{kill_window}" for n in _KILLED_RACK])
    sides = {}
    for name, topo in (("domain_aware", racked), ("flat", None)):
        res = mk(kill, topo).run(events)
        timeline = _durability_timeline(res.records)
        recover_at = next(
            (t["window"] for t in timeline
             if t["window"] >= kill_window
             and t["lost"] + t["at_risk"] + t["under_replicated"] == 0),
            None)
        sides[name] = {
            "timeline": timeline,
            "files_lost_max": max(t["lost"] for t in timeline),
            "files_lost_final": timeline[-1]["lost"],
            "correlated_risk_final": timeline[-1]["correlated_risk"],
            "windows_to_full_re_replication":
                None if recover_at is None else recover_at - kill_window,
            "repair_bytes_total": int(sum(t["repair_bytes"]
                                          for t in timeline)),
            "budget_respected": all(
                t["repair_bytes"] + t["bytes_migrated"] <= max_bytes
                for t in timeline),
        }

    # -- (b) rack partition that heals + straggler survivor ---------------
    p0, p1 = partition_windows
    part = FaultSchedule.from_specs([
        f"partition:{'+'.join(_KILLED_RACK)}@{p0}-{p1}",
        f"degrade:dn5@{p0}-{p1}:{degrade_factor:g}",
    ])
    pres = mk(part, racked).run(events)
    ptimeline = _durability_timeline(pres.records)
    psum = pres.summary()["durability"]
    partition_out: dict = {
        "schedule": [e.spec() for e in part],
        "timeline": ptimeline,
        "files_lost_max": max(t["lost"] for t in ptimeline),
        "unreachable_max": max(t["unreachable"] for t in ptimeline),
        "stalled_repairs": psum["partition_stalled_repairs"],
        "unavailable_reads": psum["unavailable_reads"],
        "lost_final": psum["lost_final"],
        "unreachable_final": psum["unreachable_final"],
        "correlated_risk_final": psum["correlated_risk_final"],
        "under_replicated_final": psum["under_replicated_final"],
        "budget_respected": all(
            t["repair_bytes"] + t["bytes_migrated"] <= max_bytes
            for t in ptimeline),
    }
    if resume_check:
        import os
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "rack.npz")
            a = mk(part, racked).run(events, checkpoint_path=ck,
                                     max_windows=p0 + 2)  # mid-partition
            b = mk(part, racked).run(events, checkpoint_path=ck)
            identical = (_strip(a.records) + _strip(b.records)
                         == _strip(pres.records)
                         and bool(np.array_equal(b.rf, pres.rf)))
        partition_out["kill_resume"] = {
            "killed_after_window": p0 + 1, "bit_identical": identical}

    out = {
        "scenario": {
            "n_files": n_files, "seed": seed, "nodes": list(_RACK_NODES),
            "racks": _RACK_SPEC, "killed_rack": list(_KILLED_RACK),
            "duration_seconds": duration, "n_windows": n_windows,
            "window_seconds": window_seconds, "k": k,
            "kill_window": kill_window,
            "partition_windows": list(partition_windows),
            "degrade": f"dn5@{p0}-{p1}:{degrade_factor:g}",
            "default_rf": 2,
            "replication_factors": scoring.replication_factors,
            "max_bytes_per_window": max_bytes,
            "max_bytes_frac": max_bytes_frac,
        },
        "rack_kill": sides,
        "rack_partition": partition_out,
        "criteria": {
            "domain_aware_zero_lost":
                sides["domain_aware"]["files_lost_max"] == 0,
            "flat_loses_files": sides["flat"]["files_lost_max"] > 0,
            "domain_recovered_within_run":
                sides["domain_aware"]["windows_to_full_re_replication"]
                is not None,
            "partition_heals_clean":
                partition_out["lost_final"] == 0
                and partition_out["unreachable_final"] == 0
                and partition_out["correlated_risk_final"] == 0,
            "budget_respected":
                sides["domain_aware"]["budget_respected"]
                and partition_out["budget_respected"],
            **({"partition_resume_bit_identical":
                partition_out["kill_resume"]["bit_identical"]}
               if resume_check else {}),
        },
    }
    return out


def chaos_overhead(n_files: int = 8000, duration: float = 480.0,
                   window_seconds: float = 60.0,
                   repeats: int = 9) -> dict:
    """Telemetry wall-clock ratio on the FAULT-MODE controller path.

    Same interleaved paired methodology as
    benchmarks/summary.telemetry_overhead_control, with the fault feed,
    durability accounting and repair planning active on BOTH sides — the
    instrumented side additionally streams window records, fault/
    durability/repair counters+gauges and audit events through the sink.
    The schedule includes a crash span, a network partition and a
    straggler, so the partition/correlated-risk accounting added for
    failure domains is inside the measured loop.  Pins the acceptance:
    fault accounting keeps telemetry inside the ≤ 1.05x budget."""
    import os
    import tempfile

    from ..benchmarks.summary import TELEMETRY_OVERHEAD_BUDGET
    from ..obs import JsonlSink, Telemetry

    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=7, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=8))
    n_windows = int(duration // window_seconds)
    schedule = FaultSchedule.from_specs([
        f"crash:dn2@{n_windows // 3}-{2 * n_windows // 3}",
        f"partition:dn4@{n_windows // 4}-{n_windows // 2}",
        f"degrade:dn5@{n_windows // 2}-{3 * n_windows // 4}:0.5",
    ])

    def mk() -> ReplicationController:
        cfg = ControllerConfig(window_seconds=window_seconds, default_rf=2,
                               kmeans=KMeansConfig(k=8, seed=42),
                               scoring=_min_rf2_scoring(),
                               fault_schedule=FaultSchedule(schedule.events))
        return ReplicationController(manifest, cfg)

    def run_plain() -> float:
        t0 = time.perf_counter()
        mk().run(events)
        return time.perf_counter() - t0

    def run_instr(path: str) -> float:
        if os.path.exists(path):
            os.remove(path)
        t0 = time.perf_counter()
        with Telemetry(JsonlSink(path)):
            mk().run(events, metrics_path=path)
        return time.perf_counter() - t0

    run_plain()  # warmup
    plain_windows: list[float] = []
    instr_windows: list[float] = []
    ratios: list[float] = []
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.jsonl")
        for r in range(max(1, repeats)):
            if r % 2 == 0:
                p, i = run_plain(), run_instr(path)
            else:
                i, p = run_instr(path), run_plain()
            plain_windows.append(p)
            instr_windows.append(i)
            ratios.append(i / p)
    ratios.sort()
    ratio = min(instr_windows) / min(plain_windows)
    return {
        "n_files": n_files,
        "windows_per_run": n_windows,
        "plain_seconds": min(plain_windows),
        "telemetry_seconds": min(instr_windows),
        "plain_windows": plain_windows,
        "telemetry_windows": instr_windows,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": ratio,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/chaos_bench.json")
    p.add_argument("--rack_out", default="data/chaos_rack_bench.json")
    p.add_argument("--n_files", type=int, default=400)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--duration", type=float, default=1800.0)
    p.add_argument("--windows", type=int, default=15)
    p.add_argument("--kill_window", type=int, default=6)
    p.add_argument("--k", type=int, default=12)
    p.add_argument("--no_overhead", action="store_true",
                   help="skip the paired telemetry-overhead rounds")
    p.add_argument("--scenario", choices=["kill", "rack", "all"],
                   default="all",
                   help="kill = one-node crash (data/chaos_bench.json); "
                        "rack = rack kill + partition "
                        "(data/chaos_rack_bench.json)")
    args = p.parse_args(argv)

    summary: dict = {}
    if args.scenario in ("kill", "all"):
        out = run_chaos_bench(n_files=args.n_files, seed=args.seed,
                              duration=args.duration, n_windows=args.windows,
                              kill_window=args.kill_window, k=args.k,
                              overhead=not args.no_overhead)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        summary.update({"out": args.out, **out["criteria"],
                        "windows_to_full_re_replication": out["recovery"][
                            "windows_to_full_re_replication"],
                        "repair_bytes_total": out["recovery"][
                            "repair_bytes_total"]})
    if args.scenario in ("rack", "all"):
        rack = run_rack_bench(n_files=args.n_files, seed=args.seed + 2,
                              duration=args.duration,
                              n_windows=args.windows, k=args.k)
        with open(args.rack_out, "w") as f:
            json.dump(rack, f, indent=2)
            f.write("\n")
        # Prefix the rack criteria: both scenarios define
        # budget_respected, and the rack value must not shadow the kill
        # scenario's in the combined stdout digest.
        summary.update({
            "rack_out": args.rack_out,
            **{f"rack_{k}": v for k, v in rack["criteria"].items()},
            "flat_files_lost_max": rack["rack_kill"]["flat"][
                "files_lost_max"],
        })
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
