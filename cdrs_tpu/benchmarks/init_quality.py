"""d2 vs kmeans|| init quality gate (VERDICT r4 #4).

The D² init is k sequential rounds — 7.5 s at config 3 (k=1024), 3x the
entire 5-iter Lloyd budget — while kmeans|| does the same job in 5 rounds
(0.33 s).  Flipping the default needs evidence that quality holds: this
module sweeps both inits across seeds at the BASELINE config-2/3 shapes and
records **final inertia** (the quantity Lloyd minimizes; reference
src/kmeans_plusplus.py has no quality metric at all) plus **planted-category
accuracy** through the full decision pipeline.

Run: ``python -m cdrs_tpu.benchmarks.init_quality [--out data/init_quality_r5.json]``
(a real chip makes the big shape fast; CPU works at reduced sizes via
``--small``).
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import numpy as np

__all__ = ["run_init_quality"]


def _inertia(X, centroids, labels, chunk: int = 131_072) -> float:
    """sum ||x_i - c[lab_i]||^2, chunked so no O(n*k) buffer materializes."""
    import jax
    import jax.numpy as jnp

    n = X.shape[0]
    chunk = min(chunk, n)
    n_pad = ((n + chunk - 1) // chunk) * chunk

    @functools.partial(jax.jit, static_argnames=("nc",))
    def run(x, c, lab, nc):
        xr = x.reshape(nc, chunk, x.shape[1])
        lr = lab.reshape(nc, chunk)

        def body(acc, args):
            xc, lc = args
            diff = xc.astype(jnp.float32) - c[lc].astype(jnp.float32)
            keep = lc >= 0
            # Per-chunk f32 sums are ~1e6-scale; the cross-chunk f32
            # accumulation error (~1e-7 relative) is far below the
            # init-to-init inertia differences being compared.
            return acc + jnp.sum(jnp.where(keep[:, None], diff * diff,
                                           0.0)), None

        acc, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xr, lr))
        return acc

    import jax.numpy as jnp
    if n_pad != n:
        X = jnp.pad(X, ((0, n_pad - n), (0, 0)))
        labels = jnp.pad(labels, (0, n_pad - n), constant_values=-1)
    return float(run(X, jnp.asarray(centroids), jnp.asarray(labels),
                     n_pad // chunk))


def _sweep_shape(n: int, d: int, k: int, chunk_rows, seeds, max_iter: int,
                 methods=("d2", "kmeans||")) -> dict:
    from ..ops.kmeans_jax import kmeans_jax_full
    from .harness import _synth_blobs_device

    out: dict = {"n": n, "d": d, "k": k, "max_iter": max_iter,
                 "seeds": list(seeds)}
    for method in methods:
        inertias, iters, secs = [], [], []
        for seed in seeds:
            X = _synth_blobs_device(n, d, min(k, 64), seed, "float32", None)
            t0 = time.perf_counter()
            c, lab, it, _ = kmeans_jax_full(
                X, k, seed=seed, max_iter=max_iter, tol=1e-4,
                chunk_rows=chunk_rows, update="auto", init_method=method)
            secs.append(time.perf_counter() - t0)
            inertias.append(_inertia(X, c, lab))
            iters.append(it)
        out[method] = {
            "inertia_per_seed": inertias,
            "inertia_mean": float(np.mean(inertias)),
            "inertia_std": float(np.std(inertias)),
            "n_iter_per_seed": iters,
            "wall_seconds_per_seed": secs,
        }
    if all(m in out for m in ("d2", "kmeans||")):
        out["inertia_ratio_kmeans_par_over_d2"] = (
            out["kmeans||"]["inertia_mean"] / out["d2"]["inertia_mean"])
    return out


def run_init_quality(small: bool = False, n_seeds: int = 5) -> dict:
    """The full gate: inertia sweeps at configs 2/3 + pipeline accuracy."""
    from .harness import _quality_one

    seeds = list(range(n_seeds))
    shapes = ([(131_072, 32, 128, None, 30), (262_144, 128, 1024, None, 10)]
              if small else
              [(1_048_576, 32, 128, None, 30),
               (10_485_760, 128, 1024, 131_072, 10)])
    result: dict = {"small": small, "shapes": []}
    for n, d, k, chunk, max_iter in shapes:
        result["shapes"].append(_sweep_shape(n, d, k, chunk, seeds, max_iter))

    # Decision quality through the whole pipeline (the metric that matters:
    # does the init change which categories files land in?).
    dq = {}
    for method in ("d2", "kmeans||"):
        dq[method] = {
            "at_300": _quality_one(300, 300.0, 21, backend="jax",
                                   init_method=method)["planted_accuracy"],
            "at_2000": _quality_one(2000, 600.0, 121, backend="jax",
                                    init_method=method)["planted_accuracy"],
        }
    result["decision_quality_planted_accuracy"] = dq
    return result


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="data/init_quality_r5.json")
    p.add_argument("--small", action="store_true",
                   help="reduced sizes (CPU-feasible)")
    p.add_argument("--seeds", type=int, default=5)
    args = p.parse_args()

    result = run_init_quality(small=args.small, n_seeds=args.seeds)
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "shapes"}, indent=2))
    for s in result["shapes"]:
        print(f"n={s['n']} d={s['d']} k={s['k']}: "
              f"ratio kmeans||/d2 = {s.get('inertia_ratio_kmeans_par_over_d2')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
