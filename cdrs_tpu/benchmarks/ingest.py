"""Log-ingestion throughput: chunked native parser vs python csv.

The 1B-event streaming config (BASELINE.md config 5) is gated on parse
speed before the device fold ever runs (VERDICT r2 #4: the python csv row
loop would spend hours there).  This microbench writes a synthetic
access.log and measures rows/sec through both paths of
``EventLog.read_csv_batches``:

    python -m cdrs_tpu.benchmarks.ingest [--rows 2000000] [--files 100000]

Prints one JSON line with rows/sec for both paths and the speedup.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

__all__ = ["bench_ingest"]


def _write_log(path: str, manifest, rows: int, seed: int = 0) -> None:
    from ..config import SimulatorConfig
    from ..io.events import EventLog
    from ..sim.access import simulate_access

    # Scale the simulated window until we have at least `rows` events, then
    # truncate — rates are per-second, so duration ~ rows / (files * rate).
    duration = max(60.0, rows / max(len(manifest), 1) * 6.0)
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=duration, seed=seed,
        clients=("client0", "client1", "client2")))
    take = min(rows, len(events))
    EventLog(ts=events.ts[:take], path_id=events.path_id[:take],
             op=events.op[:take], client_id=events.client_id[:take],
             clients=events.clients).write_csv(path, manifest)


def bench_ingest(rows: int = 2_000_000, files: int = 100_000,
                 batch_size: int = 1_000_000, seed: int = 0,
                 py_rows_cap: int = 500_000) -> dict:
    """Measure native vs python ingestion rows/sec on one synthetic log.

    The python path is timed on at most ``py_rows_cap`` rows and scaled
    (it is a per-row loop — linear in rows); the native path parses the
    whole file.
    """
    from ..config import GeneratorConfig
    from ..io.events import EventLog
    from ..runtime.native import native_available
    from ..sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=files, seed=seed))
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "access.log")
        _write_log(log, manifest, rows, seed)
        n_rows = sum(1 for _ in open(log, "rb"))

        native_rps = None
        if native_available():
            t0 = time.perf_counter()
            total = sum(len(b) for b in EventLog.read_csv_batches(
                log, manifest, batch_size=batch_size, native=True))
            native_rps = total / (time.perf_counter() - t0)
            assert total == n_rows

        # python path on a capped prefix (linear per-row cost)
        py_rows = 0
        t0 = time.perf_counter()
        for b in EventLog.read_csv_batches(log, manifest,
                                           batch_size=batch_size,
                                           native=False):
            py_rows += len(b)
            if py_rows >= py_rows_cap:
                break
        py_rps = py_rows / (time.perf_counter() - t0)

    out = {
        "metric": f"log_ingest_rows_per_sec_rows{n_rows}_files{files}",
        "rows": n_rows,
        "python_rows_per_sec": py_rps,
        "native_rows_per_sec": native_rps,
        "unit": "row/s",
    }
    if native_rps:
        out["value"] = native_rps
        out["vs_python"] = native_rps / py_rps
    return out


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=2_000_000)
    p.add_argument("--files", type=int, default=100_000)
    p.add_argument("--batch_size", type=int, default=1_000_000)
    args = p.parse_args()
    print(json.dumps(bench_ingest(args.rows, args.files, args.batch_size)))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
