"""Telemetry + decision-tracing overhead of the streaming daemon.

The repo's standing acceptance bound (ISSUE-2/3, re-checked every time
the observe path grows): a fully instrumented run must stay within
**1.05x** of the same run with telemetry off.  Round 17 adds per-decision
causal tracing (obs/trace.py) to the daemon's metrics sink — a
``decision_trace`` event per window, exemplar span trees for the N
slowest decisions, first-pin recording on the publisher — so this bench
re-measures the bound with ALL of that active.

Methodology (the repo's standard noisy-host discipline, matching
``data/telemetry_overhead_r15.json``): interleaved paired rounds — each
round runs the SAME binary log through a plain daemon (no metrics sink,
tracing off) and a traced daemon (metrics sink + tracing + audit path),
alternating, so host noise lands on both sides equally.  Headline is
the best-window ratio (min traced / min plain: the cleanest window each
side got); the per-round paired ratios and every raw window are
disclosed in the artifact.

``python -m cdrs_tpu.benchmarks.telemetry_overhead`` writes
``data/telemetry_overhead_r17.json``; ``--quick`` shrinks scales for CI
smoke and writes wherever ``--out`` points.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time

from ..config import GeneratorConfig, SimulatorConfig
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_overhead"]

BUDGET = 1.05


def _daemon(manifest, window_seconds: float, k: int):
    from ..config import KMeansConfig, validated_scoring_config
    from ..control import ControllerConfig, ReplicationController
    from ..daemon import StreamDaemon

    cfg = ControllerConfig(
        window_seconds=window_seconds, default_rf=2,
        kmeans=KMeansConfig(k=k, seed=42),
        scoring=validated_scoring_config())
    return StreamDaemon(ReplicationController(manifest, cfg))


def run_overhead(n_files: int = 20_000, n_windows: int = 8,
                 window_seconds: float = 60.0, k: int = 12,
                 rounds: int = 9, seed: int = 51) -> dict:
    """Paired plain-vs-traced daemon rounds over one shared binary log
    (module docstring).  Returns the artifact's ``daemon`` block."""
    manifest = generate_population(GeneratorConfig(
        n_files=n_files, seed=seed,
        nodes=("dn1", "dn2", "dn3", "dn4", "dn5")))
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=n_windows * window_seconds, seed=seed + 1))

    plain: list[float] = []
    traced: list[float] = []
    trace_events = 0
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        events.write_binary(log, manifest)
        for r in range(rounds):
            d = _daemon(manifest, window_seconds, k)
            t0 = time.perf_counter()
            d.run(log)
            plain.append(time.perf_counter() - t0)

            d = _daemon(manifest, window_seconds, k)
            metrics = os.path.join(td, f"m{r}.jsonl")
            t0 = time.perf_counter()
            dig = d.run(log, metrics_path=metrics)
            traced.append(time.perf_counter() - t0)
            trace_events = int(dig["traced_decisions"])

    ratios = sorted(t / p for t, p in zip(traced, plain))
    return {
        "n_files": n_files,
        "windows_per_run": n_windows,
        "plain_seconds": min(plain),
        "traced_seconds": min(traced),
        "plain_windows": plain,
        "traced_windows": traced,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": min(traced) / min(plain),
        "trace_events_per_run": trace_events,
        "budget": BUDGET,
        "within_budget": min(traced) / min(plain) <= BUDGET,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/telemetry_overhead_r17.json")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI)")
    args = p.parse_args(argv)

    if args.quick:
        block = run_overhead(n_files=2_000, n_windows=6, rounds=3)
    else:
        block = run_overhead()

    out = {
        "artifact": "telemetry_overhead_r17",
        "note": ("ISSUE-2/3 <=5% acceptance bound re-checked with the "
                 "round-17 decision-tracing surfaces active on the "
                 "daemon path: a decision_trace event per processed "
                 "window (exact integer-ns segment telescoping), "
                 "tail-sampled exemplar span trees, first-pin recording "
                 "on the epoch publisher, and the window/lineage/audit "
                 "stream of round 15.  Trace ANALYSIS (cdrs trace, "
                 "critical-path digests) is a consumer-side cost and "
                 "never runs in the loop.  Interleaved paired rounds, "
                 "best-window ratio (the repo's standard noisy-host "
                 "methodology); every window disclosed."),
        "daemon": block,
    }
    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({"out": args.out,
                      "overhead_ratio": block["overhead_ratio"],
                      "within_budget": block["within_budget"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
