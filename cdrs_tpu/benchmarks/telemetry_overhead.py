"""Telemetry + decision-tracing overhead of the streaming daemon.

The repo's standing acceptance bound (ISSUE-2/3, re-checked every time
the observe path grows): a fully instrumented run must stay within
**1.05x** of the same run with telemetry off.  Round 17 added
per-decision causal tracing (obs/trace.py); round 18 adds the live
operational plane (obs/httpz.py) — a per-window immutable snapshot
published to an in-process HTTP endpoint — so this bench measures BOTH:
the traced run, and a traced run with the endpoint attached and an
aggressive scraper polling ``/metrics`` + ``/statusz`` throughout
(scrape-under-load, the worst realistic Prometheus posture).

Methodology (the repo's standard noisy-host discipline, matching
``data/telemetry_overhead_r15.json``): interleaved paired rounds — each
round runs the SAME binary log through a plain daemon (no metrics sink,
tracing off), a traced daemon (metrics sink + tracing + audit path),
and a scraped daemon (traced + live endpoint + scraper), alternating,
so host noise lands on all sides equally.  Headline is the best-window
ratio (min instrumented / min plain: the cleanest window each side
got); the per-round paired ratios and every raw window are disclosed
in the artifact.

``python -m cdrs_tpu.benchmarks.telemetry_overhead`` writes
``data/telemetry_overhead_r18.json``; ``--quick`` shrinks scales for CI
smoke and writes wherever ``--out`` points.
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time
import urllib.request

from ..config import GeneratorConfig, SimulatorConfig
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_overhead"]

BUDGET = 1.05


def _daemon(manifest, window_seconds: float, k: int):
    from ..config import KMeansConfig, validated_scoring_config
    from ..control import ControllerConfig, ReplicationController
    from ..daemon import StreamDaemon

    cfg = ControllerConfig(
        window_seconds=window_seconds, default_rf=2,
        kmeans=KMeansConfig(k=k, seed=42),
        scoring=validated_scoring_config())
    return StreamDaemon(ReplicationController(manifest, cfg))


def _scraper(url: str, stop: threading.Event, counter: dict,
             interval: float = 0.1) -> None:
    """Aggressive live-endpoint consumer: poll /metrics + /statusz at
    10Hz for the whole run — 10x hotter than an aggressive 1s
    Prometheus scrape interval, 150x the 15s default."""
    while not stop.is_set():
        for path in ("/metrics", "/statusz"):
            try:
                with urllib.request.urlopen(url + path, timeout=2) as r:
                    r.read()
                counter["n"] += 1
            except OSError:
                pass
        stop.wait(interval)


def run_overhead(n_files: int = 20_000, n_windows: int = 8,
                 window_seconds: float = 60.0, k: int = 12,
                 rounds: int = 9, seed: int = 51) -> dict:
    """Paired plain / traced / scraped daemon rounds over one shared
    binary log (module docstring).  Returns the artifact's ``daemon``
    block."""
    from ..obs.httpz import ObsServer

    manifest = generate_population(GeneratorConfig(
        n_files=n_files, seed=seed,
        nodes=("dn1", "dn2", "dn3", "dn4", "dn5")))
    events = simulate_access(manifest, SimulatorConfig(
        duration_seconds=n_windows * window_seconds, seed=seed + 1))

    plain: list[float] = []
    traced: list[float] = []
    scraped: list[float] = []
    trace_events = 0
    scrapes = 0
    with tempfile.TemporaryDirectory() as td:
        log = os.path.join(td, "events.cdrsb")
        events.write_binary(log, manifest)
        for r in range(rounds):
            d = _daemon(manifest, window_seconds, k)
            t0 = time.perf_counter()
            d.run(log)
            plain.append(time.perf_counter() - t0)

            d = _daemon(manifest, window_seconds, k)
            metrics = os.path.join(td, f"m{r}.jsonl")
            t0 = time.perf_counter()
            dig = d.run(log, metrics_path=metrics)
            traced.append(time.perf_counter() - t0)
            trace_events = int(dig["traced_decisions"])

            # Scrape-under-load: same traced run, live endpoint
            # attached, a scraper hammering it the whole time.
            d = _daemon(manifest, window_seconds, k)
            metrics = os.path.join(td, f"s{r}.jsonl")
            with ObsServer() as srv:
                d.attach_http(srv)
                stop = threading.Event()
                counter = {"n": 0}
                th = threading.Thread(
                    target=_scraper, args=(srv.url, stop, counter),
                    daemon=True)
                th.start()
                t0 = time.perf_counter()
                d.run(log, metrics_path=metrics)
                scraped.append(time.perf_counter() - t0)
                stop.set()
                th.join(timeout=5.0)
                scrapes = counter["n"]

    ratios = sorted(t / p for t, p in zip(traced, plain))
    s_ratios = sorted(s / p for s, p in zip(scraped, plain))
    return {
        "n_files": n_files,
        "windows_per_run": n_windows,
        "plain_seconds": min(plain),
        "traced_seconds": min(traced),
        "scraped_seconds": min(scraped),
        "plain_windows": plain,
        "traced_windows": traced,
        "scraped_windows": scraped,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "scraped_paired_ratios": s_ratios,
        "scraped_paired_ratio_median": s_ratios[len(s_ratios) // 2],
        "overhead_ratio": min(traced) / min(plain),
        "scrape_overhead_ratio": min(scraped) / min(plain),
        "scrapes_last_run": scrapes,
        "trace_events_per_run": trace_events,
        "budget": BUDGET,
        "within_budget": min(traced) / min(plain) <= BUDGET,
        "scrape_within_budget": min(scraped) / min(plain) <= BUDGET,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/telemetry_overhead_r18.json")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI)")
    args = p.parse_args(argv)

    if args.quick:
        block = run_overhead(n_files=2_000, n_windows=6, rounds=3)
    else:
        block = run_overhead()

    out = {
        "artifact": "telemetry_overhead_r18",
        "note": ("ISSUE-2/3 <=5% acceptance bound re-checked with the "
                 "round-17 decision-tracing surfaces active on the "
                 "daemon path (a decision_trace event per processed "
                 "window with exact integer-ns segment telescoping, "
                 "tail-sampled exemplar span trees, first-pin recording "
                 "on the epoch publisher) PLUS the round-18 live "
                 "operational plane: a per-window immutable ObsSnapshot "
                 "published to the in-process HTTP endpoint "
                 "(obs/httpz.py), measured both unscraped (traced) and "
                 "with a 10Hz scraper polling /metrics + /statusz for "
                 "the whole run (scraped — scrape-under-load, 10x an "
                 "aggressive 1s Prometheus interval).  Trace "
                 "ANALYSIS (cdrs trace, critical-path digests) is a "
                 "consumer-side cost and never runs in the loop.  "
                 "Interleaved paired rounds, best-window ratio (the "
                 "repo's standard noisy-host methodology); every "
                 "window disclosed."),
        "daemon": block,
    }
    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({"out": args.out,
                      "overhead_ratio": block["overhead_ratio"],
                      "within_budget": block["within_budget"],
                      "scrape_overhead_ratio":
                          block["scrape_overhead_ratio"],
                      "scrape_within_budget":
                          block["scrape_within_budget"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
