"""Cost-vs-durability frontier: erasure-coded Archival vs rf=4.

The production question the storage subsystem exists to answer: **at
matched durability, how many bytes cheaper is EC Archival than
replicate(4)?**  Scenario (the whole-rack-kill chaos schedule, same
workload seed as ``chaos_rack_bench``): 12 nodes in 4 racks of 3, a
stationary workload settles into its category plan, then one whole rack
crashes permanently at a fixed window.  Three configurations run the
IDENTICAL schedule:

* ``baseline``   — no storage config at all (the pre-storage code path);
* ``replicate``  — the explicit all-``replicate`` StorageConfig, which
  must reproduce the baseline's records/placements/durability counts
  BIT-FOR-BIT (the degeneracy acceptance criterion);
* ``ec_archival``— Archival -> ``ec(6,3)`` on the cold tier (HDFS EC's
  RS(6,3) default shape), everything else replicate-hot.

Because a rack holds only 3 nodes and stripes place on 9 DISTINCT nodes,
a whole-rack kill can destroy at most 3 = m shards of any stripe — EC
survives the rack loss exactly like rack-aware rf=4 does (zero lost both
sides, the matched-durability premise), while storing Archival at 1.5x
raw bytes instead of 4x (the ``archival_bytes_ratio`` >= 2x criterion;
measured ~2.67x).  What EC pays instead is visible in the same artifact:
reconstruction repair traffic is ~k x the written shard bytes
(``repair_amplification``), charged against the SAME churn budget drift
migrations use.  A controller killed mid-outage resumes bit-identically
with EC strategy state riding the npz checkpoint.

``python -m cdrs_tpu.benchmarks.storage_bench`` writes
``data/storage_bench.json`` and (unless ``--no_overhead``) the
``data/storage_overhead_r7.json`` telemetry re-check.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import numpy as np

from ..cluster import ClusterTopology
from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..faults import FaultSchedule
from ..sim.access import simulate_access
from ..sim.generator import generate_population
from ..storage import StorageConfig

__all__ = ["run_storage_bench", "storage_overhead"]

_NODES = tuple(f"dn{i}" for i in range(1, 13))
_RACK_SPEC = ("r0=dn1,dn2,dn3;r1=dn4,dn5,dn6;"
              "r2=dn7,dn8,dn9;r3=dn10,dn11,dn12")
_KILLED_RACK = ("dn4", "dn5", "dn6")


def _min_rf2_scoring():
    """validated scoring with Moderate raised 1 -> 2 (any rf=1 category
    trivially loses a killed node's singletons — chaos_bench contract)."""
    base = validated_scoring_config()
    rf = dict(base.replication_factors)
    rf["Moderate"] = max(2, rf["Moderate"])
    return dataclasses.replace(base, replication_factors=rf)


def _strip(records: list[dict], with_storage: bool = True) -> list[dict]:
    """Records minus wall-clock noise; ``with_storage=False`` also drops
    the storage-only keys (the baseline-vs-replicate degeneracy key)."""
    drop = ("seconds",) if with_storage else (
        "seconds", "storage", "storage_conversions_retried")
    return [{k: v for k, v in r.items() if k not in drop} for r in records]


def run_storage_bench(
    n_files: int = 400,
    seed: int = 13,
    duration: float = 1800.0,
    n_windows: int = 15,
    kill_window: int = 5,
    k: int = 12,
    max_bytes_frac: float = 0.25,
    resume_check: bool = True,
) -> dict:
    """Run the frontier scenario; returns the artifact dict."""
    window_seconds = duration / n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1))
    scoring = _min_rf2_scoring()
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    max_bytes = int(max_bytes_frac * float(sizes.sum()))
    kill = FaultSchedule.from_specs(
        [f"crash:{n}@{kill_window}" for n in _KILLED_RACK])

    def mk(storage) -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            max_bytes_per_window=max_bytes, hysteresis_windows=1,
            kmeans=KMeansConfig(k=k, seed=42), scoring=scoring,
            fault_schedule=FaultSchedule(kill.events),
            topology=ClusterTopology.from_rack_spec(_NODES, _RACK_SPEC),
            storage=storage)
        return ReplicationController(manifest, cfg)

    sides: dict[str, dict] = {}
    results = {}
    for name, storage in (
            ("baseline", None),
            ("replicate", StorageConfig.from_scoring(scoring)),
            ("ec_archival", StorageConfig.ec_archival(scoring))):
        t0 = time.perf_counter()
        res = mk(storage).run(events)
        run_seconds = time.perf_counter() - t0
        results[name] = res
        timeline = []
        recover_at = None
        for r in res.records:
            d = r["durability"]
            degraded = d["lost"] + d["at_risk"] + d["under_replicated"]
            row = {
                "window": r["window"], "nodes_up": d["nodes_up"],
                "lost": d["lost"], "at_risk": d["at_risk"],
                "under_replicated": d["under_replicated"],
                "repair_moves": r["repair_moves"],
                "repair_bytes": r["repair_bytes"],
                "repair_bytes_copied": r.get("repair_bytes_copied", 0),
                "repair_backlog": r["repair_backlog"],
                "bytes_migrated": r["bytes_migrated"],
            }
            if r.get("storage"):
                row["bytes_stored"] = r["storage"]["bytes_stored"]
                row["archival_bytes"] = r["storage"][
                    "per_category_bytes"].get("Archival", 0)
            timeline.append(row)
            if (r["window"] >= kill_window and degraded == 0
                    and recover_at is None):
                recover_at = r["window"]
        rep_bytes = int(sum(t["repair_bytes"] for t in timeline))
        rep_copied = int(sum(t["repair_bytes_copied"] for t in timeline))
        side = {
            "timeline": timeline,
            "files_lost_max": max(t["lost"] for t in timeline),
            "windows_to_full_re_replication":
                None if recover_at is None else recover_at - kill_window,
            "repair_bytes_total": rep_bytes,
            "repair_bytes_copied_total": rep_copied,
            "repair_amplification":
                None if not rep_copied else round(rep_bytes / rep_copied,
                                                  3),
            "budget_respected": all(
                t["repair_bytes"] + t["bytes_migrated"] <= max_bytes
                for t in timeline),
            "run_seconds": round(run_seconds, 3),
        }
        if res.records and res.records[-1].get("storage"):
            side["storage_final"] = res.records[-1]["storage"]
        sides[name] = side

    # -- the degeneracy criterion -----------------------------------------
    identical = (
        _strip(results["baseline"].records, with_storage=False)
        == _strip(results["replicate"].records, with_storage=False)
        and bool(np.array_equal(results["baseline"].rf,
                                results["replicate"].rf))
        and bool(np.array_equal(results["baseline"].category_idx,
                                results["replicate"].category_idx)))

    # -- the frontier ------------------------------------------------------
    arch_rf4 = sides["replicate"]["storage_final"][
        "per_category_bytes"].get("Archival", 0)
    arch_ec = sides["ec_archival"]["storage_final"][
        "per_category_bytes"].get("Archival", 0)
    ratio = round(arch_rf4 / arch_ec, 4) if arch_ec else None
    frontier = {
        "archival_bytes_rf4": arch_rf4,
        "archival_bytes_ec63": arch_ec,
        "archival_bytes_ratio": ratio,
        "total_stored_rf": sides["replicate"]["storage_final"][
            "bytes_stored"],
        "total_stored_ec": sides["ec_archival"]["storage_final"][
            "bytes_stored"],
        "cost_units_rf": sides["replicate"]["storage_final"][
            "cost_units"],
        "cost_units_ec": sides["ec_archival"]["storage_final"][
            "cost_units"],
        "ec_repair_amplification":
            sides["ec_archival"]["repair_amplification"],
        "rf_repair_amplification":
            sides["replicate"]["repair_amplification"],
    }

    out: dict = {
        "scenario": {
            "n_files": n_files, "seed": seed, "nodes": list(_NODES),
            "racks": _RACK_SPEC, "killed_rack": list(_KILLED_RACK),
            "duration_seconds": duration, "n_windows": n_windows,
            "window_seconds": window_seconds, "k": k,
            "kill_window": kill_window, "default_rf": 2,
            "replication_factors": scoring.replication_factors,
            "ec_archival": "ec(6,3):cold",
            "max_bytes_per_window": max_bytes,
            "max_bytes_frac": max_bytes_frac,
        },
        "sides": sides,
        "frontier": frontier,
    }

    if resume_check:
        import tempfile

        storage = StorageConfig.ec_archival(scoring)
        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "storage.npz")
            a = mk(storage).run(events, checkpoint_path=ck,
                                max_windows=kill_window + 2)  # mid-outage
            b = mk(storage).run(events, checkpoint_path=ck)
            resume_identical = (
                _strip(a.records) + _strip(b.records)
                == _strip(results["ec_archival"].records)
                and bool(np.array_equal(b.rf, results["ec_archival"].rf)))
        out["kill_resume"] = {"killed_after_window": kill_window + 1,
                              "bit_identical": resume_identical}

    out["criteria"] = {
        "all_replicate_bit_identical": identical,
        "ec_zero_files_lost": sides["ec_archival"]["files_lost_max"] == 0,
        "rf4_zero_files_lost": sides["replicate"]["files_lost_max"] == 0,
        "ec_2x_fewer_archival_bytes": bool(ratio and ratio >= 2.0),
        "budget_respected": all(s["budget_respected"]
                                for s in sides.values()),
        **({"ec_resume_bit_identical": out["kill_resume"]["bit_identical"]}
           if resume_check else {}),
    }
    return out


def storage_overhead(n_files: int = 8000, duration: float = 480.0,
                     window_seconds: float = 60.0,
                     repeats: int = 9) -> dict:
    """Telemetry wall-clock ratio with STORAGE accounting enabled.

    Same interleaved paired methodology as ``chaos_overhead``
    (benchmarks/chaos_bench.py), with the EC-Archival storage config,
    fault feed, durability accounting and repair planning active on
    BOTH sides — the instrumented side additionally streams window
    records (now carrying the per-window ``storage`` digest), the
    ``storage.*`` gauges and the fault/durability/repair telemetry.
    The schedule includes a rack kill span, a partition and a straggler
    so conversion, reconstruction charging and the degraded accounting
    paths are all inside the measured loop.  Pins the acceptance:
    storage accounting keeps telemetry inside the <= 1.05x budget
    (data/storage_overhead_r7.json)."""
    import tempfile

    from ..benchmarks.summary import TELEMETRY_OVERHEAD_BUDGET
    from ..obs import JsonlSink, Telemetry

    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=7, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=8))
    n_windows = int(duration // window_seconds)
    schedule = FaultSchedule.from_specs([
        f"crash:dn4@{n_windows // 3}-{2 * n_windows // 3}",
        f"partition:dn7+dn8@{n_windows // 4}-{n_windows // 2}",
        f"degrade:dn10@{n_windows // 2}-{3 * n_windows // 4}:0.5",
    ])
    scoring = _min_rf2_scoring()

    def mk() -> ReplicationController:
        cfg = ControllerConfig(
            window_seconds=window_seconds, default_rf=2,
            kmeans=KMeansConfig(k=8, seed=42), scoring=scoring,
            fault_schedule=FaultSchedule(schedule.events),
            topology=ClusterTopology.from_rack_spec(_NODES, _RACK_SPEC),
            storage=StorageConfig.ec_archival(scoring))
        return ReplicationController(manifest, cfg)

    def run_plain() -> float:
        t0 = time.perf_counter()
        mk().run(events)
        return time.perf_counter() - t0

    def run_instr(path: str) -> float:
        if os.path.exists(path):
            os.remove(path)
        t0 = time.perf_counter()
        with Telemetry(JsonlSink(path)):
            mk().run(events, metrics_path=path)
        return time.perf_counter() - t0

    run_plain()  # warmup
    plain_times: list[float] = []
    instr_times: list[float] = []
    ratios: list[float] = []
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "t.jsonl")
        for r in range(max(1, repeats)):
            if r % 2 == 0:
                p, i = run_plain(), run_instr(path)
            else:
                i, p = run_instr(path), run_plain()
            plain_times.append(p)
            instr_times.append(i)
            ratios.append(i / p)
    ratios.sort()
    ratio = min(instr_times) / min(plain_times)
    return {
        "n_files": n_files,
        "windows_per_run": n_windows,
        "storage_config": "ec_archival",
        "plain_seconds": min(plain_times),
        "telemetry_seconds": min(instr_times),
        "plain_windows": plain_times,
        "telemetry_windows": instr_times,
        "paired_ratios": ratios,
        "paired_ratio_median": ratios[len(ratios) // 2],
        "overhead_ratio": ratio,
        "budget": TELEMETRY_OVERHEAD_BUDGET,
        "within_budget": ratio <= TELEMETRY_OVERHEAD_BUDGET,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/storage_bench.json")
    p.add_argument("--overhead_out", default="data/storage_overhead_r7.json")
    p.add_argument("--round", type=int, default=7, dest="round_no",
                   help="PR-round stamp for the regress history")
    p.add_argument("--n_files", type=int, default=400)
    p.add_argument("--seed", type=int, default=13)
    p.add_argument("--duration", type=float, default=1800.0)
    p.add_argument("--windows", type=int, default=15)
    p.add_argument("--kill_window", type=int, default=5)
    p.add_argument("--k", type=int, default=12)
    p.add_argument("--no_overhead", action="store_true",
                   help="skip the paired telemetry-overhead rounds")
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI)")
    args = p.parse_args(argv)

    if args.quick:
        out = run_storage_bench(n_files=160, seed=args.seed,
                                duration=720.0, n_windows=8,
                                kill_window=4, k=8)
    else:
        out = run_storage_bench(n_files=args.n_files, seed=args.seed,
                                duration=args.duration,
                                n_windows=args.windows,
                                kill_window=args.kill_window, k=args.k)
    out["round"] = args.round_no
    # Comparable metrics for the trajectory gate (regress bench_records):
    # the frontier ratio is deterministic per seed and bands tightly.
    out["bench_records"] = [
        {"metric": "storage_ec_archival_bytes_ratio",
         "value": out["frontier"]["archival_bytes_ratio"], "unit": "x",
         "backend": "numpy"},
    ]

    if not args.no_overhead:
        overhead = storage_overhead()
        with open(args.overhead_out, "w", encoding="utf-8") as f:
            json.dump(overhead, f, indent=2)
            f.write("\n")
        out["criteria"]["overhead_within_budget"] = overhead[
            "within_budget"]
        out["overhead"] = {k: overhead[k] for k in
                           ("overhead_ratio", "budget", "within_budget")}

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({"out": args.out, **out["criteria"],
                      "archival_bytes_ratio":
                          out["frontier"]["archival_bytes_ratio"],
                      "ec_repair_amplification":
                          out["frontier"]["ec_repair_amplification"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
