"""Benchmark harness for the BASELINE.md configs."""
