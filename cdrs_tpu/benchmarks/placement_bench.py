"""Functional placement vs the materialized map: the round-13 ledger.

ROADMAP item 3's claim is that the MAP, not the kernels, is the
memory / checkpoint-size / plan-diff bottleneck at 100M+ files.  This
bench pins the functional engine (cdrs_tpu/placement_fn) against the
materialized representation on the four observables the claim is made
of:

* **recompute** — vectorized placement recompute throughput of
  ``compute_placement`` on one CPU core (target >= 50M placements/s on
  the flat topology; a placement = one resolved replica slot), flat and
  rack-aware, vs the legacy rng+argsort chooser materializing the same
  population;
* **checkpoint** — on-disk bytes and save seconds of a fault-damaged
  10M-file ClusterState snapshot: dense representation (the
  ``(n_files, n_nodes)`` map + corruption mask) vs the functional
  exception overlay (target >= 20x smaller);
* **epoch_diff** — migration planning for a topology change (one node
  decommissioned out of 12): hash-twice-and-compare
  (``EpochMap.diff``, removal-pruned) vs materializing the new map with
  the legacy chooser and diffing against the stored one (target >= 10x
  faster at 10M files), with the pruned diff verified against the
  unpruned full compare;
* **controller window** — a REAL ``ReplicationController`` window at
  100M files on one host in ``--placement functional`` mode (numpy
  backend, serve routing through the O(unique pids) resolver, bounded
  Lloyd budget — the bench measures the placement plane, not kernel
  speed): the scale the materialized serve path cannot reach without
  an O(n_files x rf) map materialization per rf vector.

Timing follows the repo's noisy-host methodology: interleaved paired
rounds, best-of-rounds per side (the jitter-robust estimator the
overhead and plan benches use).

``python -m cdrs_tpu.benchmarks.placement_bench`` writes
``data/placement_bench.json`` and auto-appends its bench_records to
``data/bench_history.jsonl`` via ``regress.append_history`` (deduped on
(round, metric, platform)).  ``--quick`` shrinks every scale for CI
smoke and NEVER appends — a smoke-scale row must not become the ledger
entry a real run is banded against.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import tempfile
import time

import numpy as np

from ..cluster.placement import ClusterTopology, place_replicas
from ..placement_fn import EpochMap, FunctionalClusterState, compute_placement
from ..utils.checkpoint import save_state

__all__ = ["run_placement_bench"]

_NODES12 = tuple(f"dn{i}" for i in range(1, 13))
_RACKS12 = {f"dn{i}": f"r{(i - 1) // 3}" for i in range(1, 13)}
_REMOVED = "dn5"


class _ArrayManifest:
    """Manifest duck type backed by arrays only — no per-file Python
    strings, which is what makes the 100M-file window constructible on
    one host (a real Manifest's 100M path strings are ~10 GB of heap
    before the first array exists).  ``paths`` yields empty strings for
    the one consumer (FeatureTable construction) that lists it."""

    class _NullPaths:
        def __init__(self, n: int):
            self._n = n

        def __len__(self) -> int:
            return self._n

        def __iter__(self):
            return iter(itertools.repeat("", self._n))

    def __init__(self, n: int, nodes, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.nodes = list(nodes)
        self.primary_node_id = rng.integers(
            0, len(self.nodes), n).astype(np.int32)
        self.size_bytes = rng.integers(1 << 10, 1 << 20,
                                       n).astype(np.int64)
        self.creation_ts = np.full(n, 1.7e9) - rng.integers(
            0, 365 * 86400, n).astype(np.float64)
        self.paths = self._NullPaths(n)
        self.path_to_id = {"": -1}  # sentinel: already interned

    def __len__(self) -> int:
        return len(self.primary_node_id)


# -- recompute throughput ----------------------------------------------------

def _bench_recompute(n: int, rounds: int) -> dict:
    """Recompute throughput: uniform rf=3 (the HDFS default the paper's
    categories modulate around) flat and rack-aware, a mixed rf 2..4
    rack-aware row for the heterogeneous-category shape, and the legacy
    rng+argsort chooser materializing the same rf=3 population as the
    baseline (it must draw the WHOLE matrix to answer at all)."""
    rng = np.random.default_rng(3)
    fids = np.arange(n, dtype=np.int64)
    prim = rng.integers(0, 12, n).astype(np.int32)
    rf3 = np.full(n, 3, dtype=np.int32)
    rf_mixed = rng.integers(2, 5, n).astype(np.int32)
    flat = ClusterTopology(_NODES12)
    racked = ClusterTopology.from_racks(_NODES12, _RACKS12)
    man = _ArrayManifest(n, _NODES12, seed=3)
    man.primary_node_id = prim
    cases = {
        "flat": (flat, rf3, False),
        "racked": (racked, rf3, False),
        "racked_mixed": (racked, rf_mixed, False),
        "legacy_rng": (racked, rf3, True),
    }
    best: dict[str, float] = {k: float("inf") for k in cases}
    slots: dict[str, int] = {}
    for r in range(rounds):
        order = list(cases) if r % 2 == 0 else list(cases)[::-1]
        for case in order:
            topo, rf, legacy = cases[case]
            t0 = time.perf_counter()
            if legacy:
                place_replicas(man, rf, topo, seed=0, method="rng")
            else:
                _, rr = compute_placement(fids, rf, prim, topo, 0)
                slots[case] = int(rr.sum())
            best[case] = min(best[case], time.perf_counter() - t0)
    out = {"n_files": n, "rounds": rounds}
    for case in ("flat", "racked", "racked_mixed"):
        out[f"{case}_files_per_sec"] = round(n / best[case], 1)
        out[f"{case}_placements_per_sec"] = round(
            slots[case] / best[case], 1)
    out["legacy_rng_seconds"] = round(best["legacy_rng"], 4)
    out["racked_seconds"] = round(best["racked"], 4)
    out["recompute_vs_legacy_speedup"] = round(
        best["legacy_rng"] / best["racked"], 2)
    return out


# -- checkpoint bytes --------------------------------------------------------

def _damaged_state(n: int, sparse: bool) -> FunctionalClusterState:
    """A fault-damaged functional state (same base + same mutations on
    both representations).  ``sparse`` builds the OVERLAY backend —
    what ``--placement functional`` actually runs since the resident
    dense cache was retired (ROADMAP item 3's leftover): no dense map
    is materialized at any point, so both the checkpoint bytes AND the
    resident state are O(exceptions) + O(n) count caches."""
    from ..faults import FaultEvent, RepairScheduler
    from ..placement_fn import OverlayClusterState, primary_on_topology

    topo = ClusterTopology.from_racks(_NODES12, _RACKS12)
    man = _ArrayManifest(n, _NODES12, seed=5)
    rng = np.random.default_rng(5)
    rf = rng.integers(2, 4, n).astype(np.int32)
    primary = primary_on_topology(man.nodes, man.primary_node_id, topo)
    if sparse:
        state = OverlayClusterState.from_base(
            topo, man.size_bytes, n_shards=rf, primary=primary, seed=0)
    else:
        placement = place_replicas(man, rf, topo, seed=0, method="hash")
        state = FunctionalClusterState(
            placement, man.size_bytes, primary=primary,
            seed=0, sparse_checkpoint=False)
    state.apply_event(FaultEvent(0, "crash", "dn4"))
    # One budgeted repair window: the retargets it admits are exactly
    # the exceptions the sparse snapshot must carry.
    sched = RepairScheduler(seed=0)
    rf64 = rf.astype(np.int64)
    sched.sync(state, rf64)
    sched.schedule(1, state, rf64, np.zeros(n, dtype=np.int64),
                   max_bytes=int(man.size_bytes.sum() * 0.0002),
                   max_files=None)
    return state


def _bench_checkpoint(n: int) -> dict:
    """Checkpoint bytes AND resident placement-state bytes, dense vs
    overlay: the overlay (what functional mode runs) holds no
    (n, n_nodes) map or corruption mask at all, so its resident
    placement arrays are the O(n) count caches plus O(exceptions) —
    the ROADMAP item 3 leftover, measured."""
    out: dict = {"n_files": n}
    rf_hint = None
    # Overlay FIRST: peak RSS is monotonic, so its resident footprint
    # must be observed before the dense twin allocates its map.
    for label, sparse in (("sparse", True), ("dense", False)):
        state = _damaged_state(n, sparse)
        out[f"{label}_resident_mb"] = round(
            _state_resident_bytes(state) / 1e6, 1)
        if sparse:
            rf_hint = np.maximum(state.installed_shards, 1)
            arrays = state.state_arrays(rf_hint=rf_hint)
            out["exceptions"] = int(state.exception_fids().size)
        else:
            arrays = state.state_arrays()
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "state.npz")
            t0 = time.perf_counter()
            stats = save_state(path, arrays, {"bench": label})
            dt = time.perf_counter() - t0
        out[f"{label}_bytes"] = stats["bytes"]
        out[f"{label}_save_seconds"] = round(dt, 4)
        del state, arrays
    out["bytes_ratio"] = round(out["dense_bytes"]
                               / max(out["sparse_bytes"], 1), 2)
    out["resident_ratio"] = round(
        out["dense_resident_mb"] / max(out["sparse_resident_mb"], 0.1),
        2)
    return out


def _state_resident_bytes(state) -> int:
    """Resident bytes of a ClusterState's PLACEMENT arrays (dense map +
    corruption mask when they exist as real arrays, count caches,
    overlay rows) — the term the lowmem backend exists to shrink."""
    total = 0
    for name in ("replica_map", "slot_corrupt"):
        arr = state.__dict__.get(name)   # properties don't count
        if arr is not None:
            total += arr.nbytes
    for name in ("_live_counts", "_reach_counts", "_dom_spread",
                 "installed_shards", "min_live", "ec_k"):
        arr = getattr(state, name, None)
        if arr is not None:
            total += arr.nbytes
    ov = getattr(state, "_ov", None)
    if ov:
        total += sum(r.nbytes for r in ov.values())
    return total


# -- epoch diff vs materialized plan diff ------------------------------------

def _bench_epoch_diff(n: int, rounds: int) -> dict:
    man = _ArrayManifest(n, _NODES12, seed=7)
    rng = np.random.default_rng(7)
    shards = rng.integers(2, 5, n).astype(np.int32)
    topo_old = ClusterTopology.from_racks(_NODES12, _RACKS12)
    survivors = tuple(x for x in _NODES12 if x != _REMOVED)
    topo_new = ClusterTopology.from_racks(
        survivors, {k: v for k, v in _RACKS12.items() if k != _REMOVED})
    emap = EpochMap(man.nodes, topo_old, seed=0)
    emap.advance(topo_new)

    # Materialized side's stored "current" map, built OUTSIDE timing
    # (it exists before the topology change) — what its planner diffs
    # against.  Slot-set membership via per-row sorting so the compare
    # is order-insensitive, like the epoch diff's bitmask identity.
    old_map = place_replicas(man, shards, topo_old, seed=0).replica_map
    old_sorted = np.sort(old_map, axis=1)
    name_to_old = {nm: i for i, nm in enumerate(topo_old.nodes)}
    remap = np.asarray([name_to_old[x] for x in survivors]
                       + [len(_NODES12)], dtype=np.int32)

    t_fn = t_mat = float("inf")
    moved_fn = moved_mat = 0
    for r in range(rounds):
        order = ("fn", "mat") if r % 2 == 0 else ("mat", "fn")
        for side in order:
            t0 = time.perf_counter()
            if side == "fn":
                diff = emap.diff(0, 1, shards, man.primary_node_id)
                moved_fn = len(diff)
                t_fn = min(t_fn, time.perf_counter() - t0)
            else:
                new_map = place_replicas(man, shards, topo_new,
                                         seed=0).replica_map
                w = old_sorted.shape[1]
                new_ids = np.where(new_map >= 0,
                                   remap[np.clip(new_map, 0, None)], -1)
                pad = np.full((n, w - new_ids.shape[1]), -1,
                              dtype=np.int32) if w > new_ids.shape[1] \
                    else None
                if pad is not None:
                    new_ids = np.concatenate([new_ids, pad], axis=1)
                moved = (np.sort(new_ids, axis=1)
                         != old_sorted).any(axis=1)
                moved_mat = int(moved.sum())
                np.flatnonzero(moved)  # the plan's work list
                t_mat = min(t_mat, time.perf_counter() - t0)
    # Prune correctness: the removal-pruned diff must equal the full
    # hash-twice compare.
    full = emap.diff(0, 1, shards, man.primary_node_id, prune=False)
    zero = emap.diff(0, 0, shards, man.primary_node_id)
    return {
        "n_files": n, "rounds": rounds, "removed_node": _REMOVED,
        "functional_seconds": round(t_fn, 4),
        "materialized_seconds": round(t_mat, 4),
        "speedup": round(t_mat / t_fn, 2),
        "moved_functional": moved_fn,
        "moved_materialized_rng": moved_mat,
        "moved_fraction": round(moved_fn / n, 4),
        "prune_matches_full": bool(
            np.array_equal(np.sort(full.moved),
                           np.sort(emap.diff(0, 1, shards,
                                             man.primary_node_id).moved))),
        "same_epoch_zero_moves": len(zero) == 0,
    }


# -- the 100M-file controller window ----------------------------------------

def _bench_window(n: int, n_reads: int) -> dict:
    from ..config import KMeansConfig, validated_scoring_config
    from ..control import ControllerConfig, ReplicationController
    from ..io.events import EventLog
    from ..serve import ServeConfig

    man = _ArrayManifest(n, _NODES12, seed=9)
    rng = np.random.default_rng(9)
    # One window of read traffic over a hot subset (zipf-ish head).
    pid = rng.integers(0, max(n // 50, 1), n_reads).astype(np.int32)
    ts = np.sort(rng.uniform(0.0, 60.0, n_reads))
    events = EventLog(ts=ts, path_id=pid,
                      op=np.zeros(n_reads, dtype=np.int8),
                      client_id=rng.integers(0, 12,
                                             n_reads).astype(np.int32),
                      clients=list(man.nodes))
    cfg = ControllerConfig(
        window_seconds=60.0, default_rf=2, evaluate=False,
        placement_mode="functional",
        # Bounded Lloyd budget: the window must COMPLETE at 100M on one
        # core; kernel speed at this scale is ROADMAP items 1/2, not
        # this bench's subject.
        kmeans=KMeansConfig(k=8, seed=42, max_iter=3, tol=1e-3),
        scoring=validated_scoring_config(),
        serve=ServeConfig(policy="p2c"))
    ctl = ReplicationController(man, cfg)
    t0 = time.perf_counter()
    res = ctl.run(events, max_windows=1)
    dt = time.perf_counter() - t0
    rec = res.records[0]
    import resource

    return {
        "n_files": n, "n_reads": n_reads,
        "completed": bool(len(res.records) == 1
                          and rec.get("recluster")
                          and rec.get("reads_routed", 0) > 0
                          and (rec.get("placement") or {}).get("mode")
                          == "functional"),
        "seconds": round(dt, 2),
        "reads_routed": rec.get("reads_routed"),
        "serve_locality": rec.get("serve_locality"),
        "latency_p99_ms": rec.get("latency_p99_ms"),
        "plan_hash": rec.get("plan_hash"),
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2),
        "note": "real ReplicationController window, numpy backend, "
                "functional serve resolution, evaluate off, Lloyd "
                "budget capped at 3 iterations",
    }


def run_placement_bench(*, recompute_n: int, checkpoint_n: int,
                        diff_n: int, window_n: int, window_reads: int,
                        rounds: int = 3) -> dict:
    out: dict = {"methodology":
                 "interleaved paired rounds, best-of-rounds"}
    out["recompute"] = _bench_recompute(recompute_n, rounds)
    print(json.dumps({"recompute_mplacements_per_sec": round(
        out["recompute"]["flat_placements_per_sec"] / 1e6, 1)}))
    out["checkpoint"] = _bench_checkpoint(checkpoint_n)
    print(json.dumps({"checkpoint_ratio":
                      out["checkpoint"]["bytes_ratio"]}))
    out["epoch_diff"] = _bench_epoch_diff(diff_n, rounds)
    print(json.dumps({"epoch_diff_speedup":
                      out["epoch_diff"]["speedup"]}))
    out["controller_window"] = _bench_window(window_n, window_reads)
    print(json.dumps({"window_files": window_n,
                      "window_seconds":
                      out["controller_window"]["seconds"]}))
    out["criteria"] = {
        "recompute_50m_placements_per_sec":
            out["recompute"]["flat_placements_per_sec"] >= 50e6,
        "checkpoint_20x_smaller":
            out["checkpoint"]["bytes_ratio"] >= 20.0,
        "epoch_diff_10x_faster": out["epoch_diff"]["speedup"] >= 10.0,
        "epoch_diff_prune_exact":
            out["epoch_diff"]["prune_matches_full"]
            and out["epoch_diff"]["same_epoch_zero_moves"],
        "window_completed": out["controller_window"]["completed"],
    }
    out["bench_records"] = [
        {"metric": "placement_recompute_mplacements",
         "value": round(out["recompute"]["flat_placements_per_sec"]
                        / 1e6, 2),
         "unit": "M/s", "backend": "numpy"},
        {"metric": "placement_checkpoint_ratio",
         "value": out["checkpoint"]["bytes_ratio"], "unit": "x",
         "backend": "numpy"},
        {"metric": "placement_resident_ratio",
         "value": out["checkpoint"]["resident_ratio"], "unit": "x",
         "backend": "numpy"},
        {"metric": "placement_epoch_diff_speedup",
         "value": out["epoch_diff"]["speedup"], "unit": "x",
         "backend": "numpy"},
        {"metric": "placement_window_seconds",
         "value": out["controller_window"]["seconds"], "unit": "s",
         "backend": "numpy"},
    ]
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/placement_bench.json")
    p.add_argument("--round", type=int, default=13, dest="round_no",
                   help="PR-round stamp for the regress history")
    from .regress import add_history_argument

    add_history_argument(p)
    p.add_argument("--rounds", type=int, default=3,
                   help="interleaved paired timing rounds")
    p.add_argument("--quick", action="store_true",
                   help="small scales for smoke runs (CI); never "
                        "appends to the history")
    args = p.parse_args(argv)

    if args.quick:
        out = run_placement_bench(
            recompute_n=1_000_000, checkpoint_n=200_000,
            diff_n=1_000_000, window_n=2_000_000, window_reads=200_000,
            rounds=2)
    else:
        out = run_placement_bench(
            recompute_n=10_000_000, checkpoint_n=10_000_000,
            diff_n=10_000_000, window_n=100_000_000,
            window_reads=1_000_000, rounds=args.rounds)
    out["round"] = args.round_no
    out["quick"] = bool(args.quick)

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    appended = 0
    if not args.quick:
        from .regress import append_history, extract_records, \
            resolve_history_path

        history = resolve_history_path(args)
        if history:
            appended = append_history(
                history,
                extract_records(out, os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "history_appended": appended}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
