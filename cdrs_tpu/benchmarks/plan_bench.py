"""Planner wall-clock: the SoA control plane vs the legacy object path.

PR 8 rebuilt the per-window control plane as structure-of-arrays
(control/migrate.py, faults/repair.py) with DECISION-IDENTICAL semantics —
this bench pins the other half of the claim: at the ROADMAP's 10M-file
scale the planners are >= 10x faster than the object-at-a-time
implementations they replaced (kept verbatim in
``cdrs_tpu/compat/reference_planners`` as the baseline; the equivalence
itself is property-tested in tests/test_plan_vectorized.py and re-asserted
here on the bench scenarios).

Two planner scenarios per scale (1M and 10M files):

* **migration** — a large category flip (25% of files change category/rf)
  lands as one plan diff, then three budgeted admission windows each
  followed by a ``state_arrays`` checkpoint dump (the O(n log n)-per-
  checkpoint re-sort this PR removed is inside the measured slice);
* **repair** — a whole-rack kill (3 of 12 nodes) under a tight byte
  budget: backlog sync from the cluster's gaps, one budgeted repair pass,
  checkpoint dump.  The legacy path walks every damaged file per window;
  the SoA path classifies the non-admitted tail in one vectorized pass.

Timing follows the repo's noisy-host methodology: **interleaved paired
rounds** (object and SoA sides alternate within each round, order
flipping per round) and the reported ratio is **best-of-rounds object /
best-of-rounds SoA** — the jitter-robust estimator the overhead benches
use.  An **end-to-end** section runs a real controller (small scale, rack
kill + category drift) serial vs ``overlap_windows=True`` and records
windows/sec plus record bit-identity (the overlap acceptance contract; on
the numpy backend the overlap pipeline is exercised as a no-op schedule).

``python -m cdrs_tpu.benchmarks.plan_bench`` writes
``data/plan_bench.json`` and auto-appends its bench_record to
``data/bench_history.jsonl`` through ``benchmarks/regress.append_history``
— append-only, deduplicated on (round, metric, platform), so re-runs
never double-append.  ``--quick`` runs never append (a smoke-scale row
must not become the ledger entry a real run is deduped against);
``--history ''`` disables explicitly.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import time
import zlib

import numpy as np

from ..cluster import ClusterTopology, place_replicas
from ..compat.reference_planners import (
    ReferenceMigrationScheduler,
    ReferenceRepairScheduler,
    reference_plan_diff,
)
from ..config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from ..control import ControllerConfig, ReplicationController
from ..control.migrate import MigrationScheduler, plan_diff
from ..faults import ClusterState, FaultEvent, FaultSchedule, RepairScheduler
from ..sim.access import simulate_access
from ..sim.generator import generate_population

__all__ = ["run_plan_bench"]

_NODES = tuple(f"dn{i}" for i in range(1, 13))
_RACKS = {f"dn{i}": f"r{(i - 1) // 3}" for i in range(1, 13)}
_KILLED_RACK = ("dn4", "dn5", "dn6")
_FLIP_FRAC = 0.25
_ADMIT_WINDOWS = 3


# -- migration scenario ------------------------------------------------------

def _migration_arrays(n: int, seed: int) -> dict:
    """The category-flip scenario as plain arrays (shared by both sides)."""
    rng = np.random.default_rng(seed)
    rf_old = rng.integers(1, 5, n).astype(np.int64)
    cat_old = rng.integers(0, 4, n).astype(np.int64)
    rf_new, cat_new = rf_old.copy(), cat_old.copy()
    flip = rng.random(n) < _FLIP_FRAC
    m = int(flip.sum())
    rf_new[flip] = rng.integers(1, 5, m)
    cat_new[flip] = rng.integers(0, 4, m)
    sizes = rng.integers(1, 1 << 20, n).astype(np.int64)
    prio = np.round(rng.normal(size=n), 1)
    return {"n": n, "rf_old": rf_old, "cat_old": cat_old, "rf_new": rf_new,
            "cat_new": cat_new, "sizes": sizes, "prio": prio,
            "budget": int(sizes.sum() * 0.001), "changed": m}


def _admit_fp(applied) -> tuple:
    """Order-sensitive decision fingerprint of one window's admitted
    moves: count plus CRCs over the (file_index, bytes_moved) sequences —
    equal-count admissions that differ in WHICH files (or what order)
    cannot collide.  Works on a MoveSet (SoA side) or a PlanMove list
    (object side); computed OUTSIDE the timed region."""
    if hasattr(applied, "file_index"):
        idx = applied.file_index.astype(np.int64)
        byt = applied.bytes_moved.astype(np.int64)
    else:
        idx = np.asarray([mv.file_index for mv in applied], dtype=np.int64)
        byt = np.asarray([mv.bytes_moved for mv in applied], dtype=np.int64)
    return (idx.size, zlib.crc32(idx.tobytes()), zlib.crc32(byt.tobytes()))


def _time_migration(side: str, a: dict) -> tuple[float, list]:
    """One timed migration-planning pass: diff + submit + 3 budgeted
    windows, each followed by a checkpoint dump.  Returns (seconds,
    per-window decision fingerprint) — the fingerprint cross-checks the
    two sides admitted identical move sequences."""
    t0 = time.perf_counter()
    if side == "soa":
        sched = MigrationScheduler(a["n"], max_bytes_per_window=a["budget"],
                                   hysteresis_windows=1)
        sched.submit(plan_diff(a["rf_old"], a["rf_new"], a["cat_old"],
                               a["cat_new"], a["sizes"],
                               priority=a["prio"]))
    else:
        sched = ReferenceMigrationScheduler(
            a["n"], max_bytes_per_window=a["budget"], hysteresis_windows=1)
        sched.submit(reference_plan_diff(
            a["rf_old"], a["rf_new"], a["cat_old"], a["cat_new"],
            a["sizes"], priority=a["prio"]))
    admitted = []
    deferred = []
    for w in range(_ADMIT_WINDOWS):
        applied = sched.schedule(w)
        admitted.append(applied)
        deferred.append(sched.last_deferred_budget)
        if side == "soa":
            sched.state_arrays()
        else:
            # The legacy checkpoint: re-sort the dict backlog into the
            # historical column dump (what MigrationScheduler.state_arrays
            # did before PR 8).
            moves = sorted(sched.backlog.values(),
                           key=lambda mv: mv.file_index)
            {  # noqa: B018 - built for its cost, like the old path
                "sched_file_index": np.asarray(
                    [mv.file_index for mv in moves], dtype=np.int64),
                "sched_bytes_moved": np.asarray(
                    [mv.bytes_moved for mv in moves], dtype=np.int64),
                "sched_priority": np.asarray(
                    [mv.priority for mv in moves], dtype=np.float64),
            }
    dt = time.perf_counter() - t0
    fp = [(*_admit_fp(ap), df) for ap, df in zip(admitted, deferred)]
    return dt, fp


# -- repair scenario ---------------------------------------------------------

def _repair_states(n: int, seed: int) -> tuple[ClusterState, np.ndarray]:
    manifest = generate_population(
        GeneratorConfig(n_files=n, seed=seed, nodes=_NODES))
    topo = ClusterTopology.from_racks(_NODES, _RACKS)
    rng = np.random.default_rng(seed)
    rf = rng.integers(2, 4, n).astype(np.int32)
    placement = place_replicas(manifest, rf, topo, seed=0)
    state = ClusterState(placement, manifest.size_bytes)
    for nd in _KILLED_RACK:
        state.apply_event(FaultEvent(0, "crash", nd))
    return state, rf.astype(np.int64)


def _time_repair(side: str, state: ClusterState, rf: np.ndarray
                 ) -> tuple[float, tuple]:
    """One timed repair-planning pass on a PRIVATE copy of the killed
    cluster: backlog sync, one budgeted window, checkpoint dump."""
    cat = np.zeros(rf.shape[0], dtype=np.int64)
    budget = int(state.sizes.sum() * 0.0001)
    sched = (RepairScheduler(seed=0) if side == "soa"
             else ReferenceRepairScheduler(seed=0))
    t0 = time.perf_counter()
    sched.sync(state, rf)
    rep = sched.schedule(1, state, rf, cat, max_bytes=budget, max_files=200)
    if side == "soa":
        sched.state_arrays()
    else:
        tasks = sorted(sched.backlog.values(), key=lambda t: t.file_index)
        {
            "repair_file_index": np.asarray(
                [t.file_index for t in tasks], dtype=np.int64),
            "repair_attempts": np.asarray(
                [t.attempts for t in tasks], dtype=np.int64),
        }
    dt = time.perf_counter() - t0
    ap = np.asarray(rep.applied, dtype=np.int64).reshape(-1, 3)
    fp = (len(rep.applied), zlib.crc32(ap.tobytes()), rep.bytes_used,
          rep.bytes_copied, rep.files_touched, rep.deferred_budget,
          rep.deferred_no_target, len(sched.backlog))
    return dt, fp


def _paired_rounds(scale_label: str, n: int, seed: int, rounds: int) -> dict:
    """Interleaved paired rounds at one scale; best-of-rounds per side."""
    mig = _migration_arrays(n, seed)
    repair_base, rf = _repair_states(n, seed + 1)
    t_mig = {"object": [], "soa": []}
    t_rep = {"object": [], "soa": []}
    fps: dict[str, list] = {}
    for r in range(rounds):
        order = ("object", "soa") if r % 2 == 0 else ("soa", "object")
        for side in order:
            dt, fp = _time_migration(side, mig)
            t_mig[side].append(dt)
            fps.setdefault("mig_" + side, fp)
            state = copy.deepcopy(repair_base)
            dt, fp = _time_repair(side, state, rf)
            t_rep[side].append(dt)
            fps.setdefault("rep_" + side, fp)
    identical = (fps["mig_object"] == fps["mig_soa"]
                 and fps["rep_object"] == fps["rep_soa"])
    best = {k: min(v) for k, v in
            (("mig_object", t_mig["object"]), ("mig_soa", t_mig["soa"]),
             ("rep_object", t_rep["object"]), ("rep_soa", t_rep["soa"]))}
    obj = best["mig_object"] + best["rep_object"]
    soa = best["mig_soa"] + best["rep_soa"]
    return {
        "scale": scale_label, "n_files": n, "rounds": rounds,
        "moves_changed": mig["changed"],
        "repair_backlog": fps["rep_soa"][-1],
        "migration_seconds_object": round(best["mig_object"], 4),
        "migration_seconds_soa": round(best["mig_soa"], 4),
        "migration_speedup": round(best["mig_object"] / best["mig_soa"], 2),
        "repair_seconds_object": round(best["rep_object"], 4),
        "repair_seconds_soa": round(best["rep_soa"], 4),
        "repair_speedup": round(best["rep_object"] / best["rep_soa"], 2),
        "planner_seconds_object": round(obj, 4),
        "planner_seconds_soa": round(soa, 4),
        "planner_speedup": round(obj / soa, 2),
        "decisions_identical": bool(identical),
        "rounds_object_seconds": [round(x + y, 4) for x, y in
                                  zip(t_mig["object"], t_rep["object"])],
        "rounds_soa_seconds": [round(x + y, 4) for x, y in
                               zip(t_mig["soa"], t_rep["soa"])],
    }


# -- end-to-end windows/sec --------------------------------------------------

def _strip(records: list[dict]) -> list[dict]:
    return [{k: v for k, v in r.items() if k != "seconds"}
            for r in records]


def _e2e_windows(n_files: int, n_windows: int, seed: int) -> dict:
    """A real controller run (category drift + rack kill, budgeted churn)
    serial vs overlap: windows/sec end to end and record bit-identity."""
    duration = 60.0 * n_windows
    manifest = generate_population(
        GeneratorConfig(n_files=n_files, seed=seed, nodes=_NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=duration, seed=seed + 1))
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    kill = FaultSchedule.from_specs(
        [f"crash:{nd}@3" for nd in _KILLED_RACK])

    def run(overlap: bool):
        cfg = ControllerConfig(
            window_seconds=60.0, default_rf=2,
            max_bytes_per_window=int(sizes.sum() * 0.05),
            hysteresis_windows=1, drift_threshold=0.02,
            kmeans=KMeansConfig(k=16, seed=42),
            scoring=validated_scoring_config(),
            fault_schedule=FaultSchedule(kill.events),
            topology=ClusterTopology.from_racks(_NODES, _RACKS),
            overlap_windows=overlap)
        ctl = ReplicationController(manifest, cfg)
        t0 = time.perf_counter()
        res = ctl.run(events)
        return res, time.perf_counter() - t0

    res_serial, t_serial = run(False)
    res_overlap, t_overlap = run(True)
    s_serial = res_serial.summary()
    s_overlap = res_overlap.summary()
    return {
        "n_files": n_files, "windows": len(res_serial.records),
        "windows_per_sec_serial": round(len(res_serial.records) / t_serial,
                                        3),
        "windows_per_sec_overlap": round(
            len(res_overlap.records) / t_overlap, 3),
        "summary_windows_per_sec_serial": s_serial.get("windows_per_sec"),
        "summary_windows_per_sec_overlap": s_overlap.get("windows_per_sec"),
        "plan_seconds_fraction": s_serial.get("plan_seconds_fraction"),
        "overlap_bit_identical": (
            _strip(res_serial.records) == _strip(res_overlap.records)
            and bool(np.array_equal(res_serial.rf, res_overlap.rf))
            and bool(np.array_equal(res_serial.category_idx,
                                    res_overlap.category_idx))),
    }


def run_plan_bench(scales: list[int], rounds: int = 3, seed: int = 8,
                   e2e_files: int = 20_000, e2e_windows: int = 8) -> dict:
    out: dict = {"scales": [], "scenario": {
        "flip_fraction": _FLIP_FRAC, "admit_windows": _ADMIT_WINDOWS,
        "nodes": list(_NODES), "killed_rack": list(_KILLED_RACK),
        "migration_budget_frac": 0.001, "repair_budget_frac": 0.0001,
        "methodology": "interleaved paired rounds, best-of-rounds ratio"}}
    for n in scales:
        label = f"{n // 1_000_000}M" if n >= 1_000_000 else f"{n // 1000}k"
        row = _paired_rounds(label, n, seed, rounds)
        out["scales"].append(row)
        print(json.dumps({k: row[k] for k in
                          ("scale", "planner_speedup", "migration_speedup",
                           "repair_speedup", "decisions_identical")}))
    out["end_to_end"] = _e2e_windows(e2e_files, e2e_windows, seed)
    top = out["scales"][-1]
    out["criteria"] = {
        "planner_10x_at_top_scale": top["planner_speedup"] >= 10.0,
        "decisions_identical": all(s["decisions_identical"]
                                   for s in out["scales"]),
        "overlap_bit_identical": out["end_to_end"]["overlap_bit_identical"],
    }
    out["bench_records"] = [
        {"metric": "plan_planner_speedup_" + top["scale"].lower(),
         "value": top["planner_speedup"], "unit": "x", "backend": "numpy"},
    ]
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/plan_bench.json")
    p.add_argument("--round", type=int, default=8, dest="round_no",
                   help="PR-round stamp for the regress history")
    from .regress import add_history_argument

    add_history_argument(p)
    p.add_argument("--rounds", type=int, default=3,
                   help="interleaved paired timing rounds per scale")
    p.add_argument("--seed", type=int, default=8)
    p.add_argument("--quick", action="store_true",
                   help="small sizes for smoke runs (CI): one 100k scale, "
                        "2 rounds, tiny end-to-end")
    args = p.parse_args(argv)

    if args.quick:
        out = run_plan_bench([100_000], rounds=2, seed=args.seed,
                             e2e_files=3_000, e2e_windows=5)
    else:
        out = run_plan_bench([1_000_000, 10_000_000], rounds=args.rounds,
                             seed=args.seed)
    out["round"] = args.round_no

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    from .regress import append_history, extract_records, \
        resolve_history_path

    history = resolve_history_path(args)
    appended = 0
    if history:
        appended = append_history(
            history, extract_records(out, os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "history_appended": appended,
                      "top_scale_speedup":
                          out["scales"][-1]["planner_speedup"],
                      "windows_per_sec_overlap":
                          out["end_to_end"]["windows_per_sec_overlap"]}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
