"""Mesh-sharded control loop: decision identity, scaling, data=1 overhead.

ROADMAP item 1 (the "single biggest unlock for millions of users") shards
the whole per-window device computation — Lloyd assignment + centroid
update, scoring medians, the streaming feature fold, and the drift
detector's one-Lloyd-step — data-parallel over files across a
``jax.sharding.Mesh`` (one ``psum`` of the (k, d+1) sufficient statistics
per iteration; the (n, k) distance matrix and the feature table never
gather to one device).  This bench pins the three contracts that make the
mesh a pure RUNTIME choice:

* **decision identity** — a controller run at ``mesh_shape={"data": N}``
  makes exactly the decisions of the single-device path on the same seed
  (assignments, category populations, plan hashes, migrations; drift
  scalars agree to fp tolerance — float psum association), asserted
  in-bench across seeds 0/1/2, plus a checkpoint written at ``data=1``
  resumed at ``data=N`` (mesh shape is not checkpoint state).
* **throughput per device count** — Lloyd iter/s at the BASELINE
  config-2/config-3 SHAPES (d=32/k=128 and d=128/k=1024; n scales to the
  host so a CPU smoke terminates) across 1/2/4/8 devices.  On a real TPU
  mesh this is the near-linear-scaling observable (MULTICHIP_r0*
  lineage); on CPU's virtual devices the counts share one socket, so the
  numbers check the harness, not the hardware.
* **data=1 overhead** — the mesh path at ``data=1`` (the same shard_map
  body with collectives compiled out, plus the device drift kernel) holds
  within 5% of the historical single-device path on a config-2-shaped
  device pass, measured with the repo's interleaved-paired-rounds /
  best-of-rounds convention (the noisy-host methodology every overhead
  artifact uses).

``python -m cdrs_tpu.benchmarks.mesh_bench`` writes
``data/mesh_bench.json`` and auto-appends its bench_records to
``data/bench_history.jsonl`` via ``regress.append_history`` (``--quick``
never appends).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time

import numpy as np

__all__ = ["run_mesh_bench"]

#: BASELINE config-2 / config-3 kernel shapes (benchmarks/harness.CONFIGS);
#: n is a bench parameter so the same shape runs at host-feasible scale.
_SHAPES = {"config2": (32, 128), "config3": (128, 1024)}


def _available_device_counts(want: list[int]) -> list[int]:
    import jax

    have = jax.device_count()
    counts = [n for n in want if n <= have]
    if not counts:
        raise ValueError(
            f"no requested device count {want} fits this host's {have} "
            f"device(s); on CPU force virtual devices with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{max(want)}")
    return counts


# -- throughput per device count ---------------------------------------------

def _time_lloyd(X, k: int, init, mesh, iters: int, rounds: int) -> float:
    """Best-of-rounds wall seconds for ``iters`` fixed-trip Lloyd
    iterations (tol=0 — the static-trip loop), warm (compile excluded)."""
    from ..ops.kmeans_jax import kmeans_jax_full

    def once():
        c, _, it, _ = kmeans_jax_full(
            X, k, tol=0.0, seed=0, max_iter=iters, init_centroids=init,
            mesh_shape=mesh)
        return c

    import jax

    jax.block_until_ready(once())  # compile + warm
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        jax.block_until_ready(once())
        best = min(best, time.perf_counter() - t0)
    return best


def _throughput(shape_name: str, n: int, iters: int, rounds: int,
                device_counts: list[int], seed: int) -> dict:
    d, k = _SHAPES[shape_name]
    rng = np.random.default_rng(seed)
    X = rng.random((n, d), dtype=np.float32)
    init = X[rng.choice(n, k, replace=False)].copy()
    rows = []
    for ndev in device_counts:
        mesh = None if ndev == 1 else {"data": ndev}
        secs = _time_lloyd(X, k, init, mesh, iters, rounds)
        rows.append({
            "devices": ndev,
            "iters_per_sec": round(iters / secs, 3),
            "seconds": round(secs, 4),
        })
    from ..parallel.mesh import collective_bytes_estimate

    return {
        "shape": shape_name, "n": n, "d": d, "k": k, "iters": iters,
        "collective_bytes_per_iter_at_max": collective_bytes_estimate(
            k * (d + 1) * 4, device_counts[-1]),
        "per_device_count": rows,
    }


# -- decision identity --------------------------------------------------------

def _strip(records: list[dict]) -> list[dict]:
    """Decision view of the record stream: wall-clock, the mesh stamp and
    the fp-tolerance drift scalars removed (compared separately)."""
    drop = ("seconds", "mesh", "drift", "centroid_shift",
            "population_delta")
    return [{k: v for k, v in r.items() if k not in drop} for r in records]


def _controller_scenario(seed: int):
    from ..config import (GeneratorConfig, SimulatorConfig,
                          validated_scoring_config)
    from ..sim.access import simulate_access_with_shift
    from ..sim.generator import generate_population

    manifest = generate_population(
        GeneratorConfig(n_files=400, seed=seed))
    events, _ = simulate_access_with_shift(
        manifest, SimulatorConfig(duration_seconds=1200.0, seed=seed + 1),
        600.0, {"hot": "archival", "archival": "hot"})
    # Pinned to histogram medians on BOTH sides: the medians are integer
    # count statistics, bitwise identical at any mesh shape — whereas
    # "auto" resolves to the exact sort single-device and hist sharded,
    # which is a different (if equally valid) estimate per shape.
    scoring = dataclasses.replace(validated_scoring_config(),
                                  median_method="hist")
    return manifest, events, scoring


def _controller_run(manifest, events, scoring, mesh, seed,
                    checkpoint_path=None, max_windows=None):
    from ..config import KMeansConfig
    from ..control import ControllerConfig, ReplicationController

    cfg = ControllerConfig(
        window_seconds=100.0, drift_threshold=0.02, backend="jax",
        kmeans=KMeansConfig(k=12, seed=42), scoring=scoring,
        mesh_shape=mesh, default_rf=2)
    ctl = ReplicationController(manifest, cfg)
    return ctl.run(events, checkpoint_path=checkpoint_path,
                   max_windows=max_windows)


def _decision_identity(seeds: list[int], ndev: int) -> dict:
    """Mesh-vs-single-device controller equivalence + cross-shape resume."""
    import tempfile

    mesh = {"data": ndev}
    out: dict = {"seeds": [], "devices": ndev}
    all_ok = True
    for seed in seeds:
        manifest, events, scoring = _controller_scenario(seed)
        r1 = _controller_run(manifest, events, scoring, None, seed)
        rN = _controller_run(manifest, events, scoring, mesh, seed)
        # Both sides guarded for None: a divergent acceptance schedule
        # (one side's drift missing at some window) must surface as
        # decisions_identical=false below, not a TypeError mid-artifact.
        drift_diff = max(
            (abs(a["drift"] - b["drift"])
             for a, b in zip(r1.records, rN.records)
             if a.get("drift") is not None
             and b.get("drift") is not None), default=0.0)
        # Model-level: same assignments and category populations on the
        # final feature snapshot at both shapes.
        decisions_ok = (
            _strip(r1.records) == _strip(rN.records)
            and bool(np.array_equal(r1.rf, rN.rf))
            and bool(np.array_equal(r1.category_idx, rN.category_idx)))
        # Checkpoint portability: killed at data=1 mid-run, resumed at
        # data=N — decisions must stitch identically (mesh shape is a
        # runtime choice, not checkpoint state).
        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "mesh.npz")
            a = _controller_run(manifest, events, scoring, None, seed,
                                checkpoint_path=ck, max_windows=6)
            b = _controller_run(manifest, events, scoring, mesh, seed,
                                checkpoint_path=ck)
            resume_ok = (
                _strip(a.records) + _strip(b.records)
                == _strip(rN.records)
                and bool(np.array_equal(b.rf, rN.rf))
                and bool(np.array_equal(b.category_idx, rN.category_idx)))
        out["seeds"].append({
            "seed": seed,
            "windows": len(r1.records),
            "decisions_identical": bool(decisions_ok),
            "resume_across_shapes_identical": bool(resume_ok),
            "drift_score_max_diff": float(drift_diff),
        })
        all_ok = all_ok and decisions_ok and resume_ok \
            and drift_diff < 1e-5
    out["ok"] = bool(all_ok)
    return out


# -- data=1 overhead ----------------------------------------------------------

def _overhead(n: int, iters: int, rounds: int, seed: int) -> dict:
    """One config-2-shaped device pass (Lloyd + fused classify + drift)
    on the historical single-device path vs the mesh path at data=1,
    interleaved paired rounds, best-of-rounds ratio."""
    import jax

    from ..config import ScoringConfig
    from ..control.drift import detect_drift, detect_drift_jax
    from ..ops.kmeans_jax import kmeans_jax_full
    from ..ops.scoring_jax import classify_jax

    d, k = _SHAPES["config2"]
    rng = np.random.default_rng(seed)
    X = rng.random((n, d), dtype=np.float32)
    init = X[rng.choice(n, k, replace=False)].copy()
    cat_idx = rng.integers(0, 4, k)
    frac = np.full(4, 0.25)
    # Scoring/drift always run at the controller's 5-feature width (the
    # score tables are (C, 5) by construction); Lloyd carries the full
    # config-2 shape.  Global medians from data: the stock per-feature
    # table only covers the named 5 features.
    scoring = ScoringConfig(median_method="hist",
                            compute_global_medians_from_data=True)
    X5 = np.ascontiguousarray(X[:, :5])
    init5 = np.ascontiguousarray(init[:, :5])

    def one_pass(mesh):
        c, labels, _, _ = kmeans_jax_full(
            X, k, tol=0.0, seed=0, max_iter=iters, init_centroids=init,
            mesh_shape=mesh)
        winner, scores, med = classify_jax(X5, labels, k, scoring,
                                           mesh_shape=mesh)
        if mesh is None:
            detect_drift(X5, init5, cat_idx, frac, 4)
        else:
            detect_drift_jax(X5, init5, cat_idx, frac, 4, mesh_shape=mesh)
        return jax.block_until_ready((winner, scores, med))

    one_pass(None)          # compile + warm both sides
    one_pass({"data": 1})
    t = {"single": [], "mesh1": []}
    for r in range(rounds):
        order = (("single", None), ("mesh1", {"data": 1}))
        if r % 2:
            order = order[::-1]
        for name, mesh in order:
            t0 = time.perf_counter()
            one_pass(mesh)
            t[name].append(time.perf_counter() - t0)
    best_single = min(t["single"])
    best_mesh = min(t["mesh1"])
    return {
        "n": n, "d": d, "k": k, "iters": iters, "rounds": rounds,
        "seconds_single_device": round(best_single, 4),
        "seconds_mesh_data1": round(best_mesh, 4),
        "overhead_ratio": round(best_mesh / best_single, 4),
        "rounds_single_seconds": [round(x, 4) for x in t["single"]],
        "rounds_mesh_seconds": [round(x, 4) for x in t["mesh1"]],
        "methodology": "interleaved paired rounds, best-of-rounds ratio",
    }


# -- driver -------------------------------------------------------------------

def run_mesh_bench(n2: int, n3: int, iters2: int, iters3: int,
                   rounds: int, device_counts: list[int],
                   seeds: list[int], overhead_rounds: int,
                   seed: int = 0) -> dict:
    import jax

    device_counts = _available_device_counts(device_counts)
    ndev_max = device_counts[-1]
    out: dict = {
        "jax_platform": jax.default_backend(),
        "jax_devices": jax.device_count(),
        "device_counts": device_counts,
        "note": ("per-device scaling is meaningful on a real chip mesh; "
                 "CPU virtual devices share one socket and check the "
                 "harness, not the hardware"),
    }
    out["throughput"] = [
        _throughput("config2", n2, iters2, rounds, device_counts, seed),
        _throughput("config3", n3, iters3, rounds, device_counts, seed),
    ]
    for t in out["throughput"]:
        print(json.dumps({"shape": t["shape"],
                          "per_device_count": t["per_device_count"]}))
    out["decision_identity"] = _decision_identity(seeds, ndev_max)
    print(json.dumps({"decision_identity_ok":
                      out["decision_identity"]["ok"]}))
    # Full iteration budget for the overhead pass: per the noisy-host
    # methodology each timed side must run for seconds, not hundreds of
    # milliseconds, or jitter swamps a 5% effect.
    out["overhead"] = _overhead(n2, iters2, overhead_rounds, seed)
    print(json.dumps({"overhead_ratio": out["overhead"]["overhead_ratio"]}))

    ratio = out["overhead"]["overhead_ratio"]
    out["criteria"] = {
        "decision_identity_all_seeds": out["decision_identity"]["ok"],
        "data1_overhead_within_5pct": ratio <= 1.05,
    }
    # Only the throughput row feeds the trajectory ledger.  The overhead
    # RATIO stays an in-bench criterion (<= 1.05, hard-gated above): its
    # ideal value is ~1.0 with host jitter on both sides, so banding it
    # against a best-of-history baseline (the luckiest draw) would flag
    # phantom regressions forever — the same reason the telemetry/
    # integrity overhead artifacts are criteria, not ledger rows.
    top2 = out["throughput"][0]["per_device_count"][-1]
    out["bench_records"] = [
        {"metric": f"mesh_config2_iters_per_sec_d{top2['devices']}",
         "value": top2["iters_per_sec"], "unit": "iter/s",
         "backend": "jax", "jax_platform": out["jax_platform"],
         "jax_devices": out["jax_devices"]},
    ]
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default="data/mesh_bench.json")
    p.add_argument("--round", type=int, default=11, dest="round_no",
                   help="PR-round stamp for the regress history")
    from .regress import add_history_argument

    add_history_argument(p)
    p.add_argument("--n2", type=int, default=262_144,
                   help="rows for the config-2 SHAPE (d=32, k=128)")
    p.add_argument("--n3", type=int, default=65_536,
                   help="rows for the config-3 SHAPE (d=128, k=1024)")
    p.add_argument("--iters2", type=int, default=8)
    p.add_argument("--iters3", type=int, default=4)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--overhead_rounds", type=int, default=4)
    p.add_argument("--devices", default="1,2,4,8",
                   help="comma-separated device counts (clipped to the "
                        "host's)")
    p.add_argument("--seeds", default="0,1,2",
                   help="decision-identity controller seeds")
    p.add_argument("--quick", action="store_true",
                   help="smoke sizes for CI: tiny shapes, 1 seed")
    args = p.parse_args(argv)

    counts = [int(x) for x in args.devices.split(",") if x]
    if args.quick:
        out = run_mesh_bench(
            n2=16_384, n3=4_096, iters2=3, iters3=2, rounds=2,
            device_counts=counts, seeds=[0], overhead_rounds=2)
    else:
        out = run_mesh_bench(
            n2=args.n2, n3=args.n3, iters2=args.iters2, iters3=args.iters3,
            rounds=args.rounds, device_counts=counts,
            seeds=[int(s) for s in args.seeds.split(",") if s],
            overhead_rounds=args.overhead_rounds)
    out["round"] = args.round_no

    parent = os.path.dirname(args.out)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    from .regress import (append_history, extract_records,
                          resolve_history_path)

    history = resolve_history_path(args)
    appended = 0
    if history:
        appended = append_history(
            history, extract_records(out, os.path.basename(args.out)))
    print(json.dumps({"out": args.out, **out["criteria"],
                      "history_appended": appended}))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
