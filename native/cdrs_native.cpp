// cdrs_native — native runtime components for the TPU framework.
//
// The reference implements its host-side data plane in interpreted Python:
// a per-event Poisson loop (reference: src/access_simulator.py:16-38) and
// csv-module log parsing (consumed by Spark).  This library provides the
// native equivalents used by cdrs_tpu/runtime/native.py via ctypes:
//
//   * simulate_events — threaded Poisson access-event generation, sorted by
//     timestamp, deterministic per (seed, file) regardless of thread count.
//   * parse_access_log — access.log CSV reader emitting columnar arrays
//     (epoch seconds, op, and offset-indexed path/client byte ranges that
//     Python interns against the manifest).
//
// Exact distributional semantics match cdrs_tpu/sim/access.py (order-
// statistics Poisson: count ~ Poisson(lambda*T), times uniform on [0, T)),
// with a C++ RNG stream (std::mt19937_64) — deterministic but distinct from
// NumPy's Philox; tests compare distributions, not bitstreams.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#if defined(_OPENMP)
#include <parallel/algorithm>
#define CDRS_SORT __gnu_parallel::stable_sort
#else
#define CDRS_SORT std::stable_sort
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Event simulation
// ---------------------------------------------------------------------------

// Phase 1: per-file Poisson event counts.  Returns total events.
// counts_out: int64[n_files]
int64_t sim_counts(int64_t n_files, const double* read_rate,
                   const double* write_rate, double duration, uint64_t seed,
                   int64_t* counts_out) {
  int64_t total = 0;
  for (int64_t i = 0; i < n_files; ++i) {
    std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i);
    double lam = (read_rate[i] + write_rate[i]) * duration;
    int64_t c = 0;
    if (lam > 0) {
      std::poisson_distribution<int64_t> pois(lam);
      c = pois(rng);
    }
    counts_out[i] = c;
    total += c;
  }
  return total;
}

// Phase 2: fill event arrays (ts, pid, op, client), then sort by timestamp.
// Deterministic per (seed, file): each file's events come from an RNG seeded
// by (seed, i), independent of thread scheduling.  Arrays are caller-
// allocated with the total from sim_counts.
void sim_fill(int64_t n_files, const int64_t* counts, const double* read_rate,
              const double* write_rate, const double* locality,
              const int32_t* primary_node, const int32_t* client_pool,
              int64_t n_pool, double duration, double sim_start, uint64_t seed,
              int64_t n_threads, double* ts_out, int32_t* pid_out,
              int8_t* op_out, int32_t* client_out) {
  std::vector<int64_t> offsets(n_files + 1, 0);
  for (int64_t i = 0; i < n_files; ++i) offsets[i + 1] = offsets[i] + counts[i];
  const int64_t total = offsets[n_files];

  if (n_threads <= 0) {
    n_threads = (int64_t)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }

  std::atomic<int64_t> next_file(0);
  auto worker = [&]() {
    for (;;) {
      int64_t i = next_file.fetch_add(1);
      if (i >= n_files) return;
      // Re-seed as in sim_counts and discard the count draw (same
      // distribution + same engine state consume the same variates), so the
      // fill stream continues deterministically after it.
      std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i);
      double lam = (read_rate[i] + write_rate[i]) * duration;
      if (lam > 0) {
        std::poisson_distribution<int64_t> pois(lam);
        (void)pois(rng);
      }
      std::uniform_real_distribution<double> uni(0.0, 1.0);
      const double p_read =
          read_rate[i] / (read_rate[i] + write_rate[i] + 1e-12);
      const double loc = locality[i];
      const int32_t prim = primary_node[i];
      for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
        ts_out[j] = sim_start + uni(rng) * duration;
        pid_out[j] = (int32_t)i;
        op_out[j] = uni(rng) >= p_read ? 1 : 0;  // 1 = WRITE
        if (n_pool <= 0 || uni(rng) < loc) {
          client_out[j] = prim;
        } else {
          client_out[j] = client_pool[(int64_t)(uni(rng) * (double)n_pool) %
                                      n_pool];
        }
      }
    }
  };
  std::vector<std::thread> threads;
  for (int64_t t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();

  // Global time sort (reference: access_simulator.py:60).  Sort an index
  // permutation, then apply it column-by-column out of place.
  std::vector<int64_t> idx(total);
  for (int64_t i = 0; i < total; ++i) idx[i] = i;
  CDRS_SORT(idx.begin(), idx.end(), [&](int64_t a, int64_t b) {
    return ts_out[a] < ts_out[b];
  });
  std::vector<double> ts2(total);
  std::vector<int32_t> i2(total);
  for (int64_t i = 0; i < total; ++i) ts2[i] = ts_out[idx[i]];
  std::memcpy(ts_out, ts2.data(), sizeof(double) * total);
  for (int64_t i = 0; i < total; ++i) i2[i] = pid_out[idx[i]];
  std::memcpy(pid_out, i2.data(), sizeof(int32_t) * total);
  for (int64_t i = 0; i < total; ++i) i2[i] = client_out[idx[i]];
  std::memcpy(client_out, i2.data(), sizeof(int32_t) * total);
  std::vector<int8_t> o2(total);
  for (int64_t i = 0; i < total; ++i) o2[i] = op_out[idx[i]];
  std::memcpy(op_out, o2.data(), sizeof(int8_t) * total);
}

// ---------------------------------------------------------------------------
// access.log CSV parsing
// ---------------------------------------------------------------------------

// days-from-civil (Howard Hinnant's public-domain algorithm shape): epoch days
// for a proleptic Gregorian date.
static int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const int64_t yoe = y - era * 400;
  const int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + doe - 719468;
}

// Parse "YYYY-MM-DDTHH:MM:SS[.frac][Z|+HH:MM|-HH:MM]" -> epoch seconds.
// Returns NaN on malformed input (matching Python parse_iso_ts's accepted
// grammar; naive stamps are treated as UTC).
static double parse_iso(const char* s, int64_t len) {
  if (len < 19) return __builtin_nan("");
  auto num = [&](int64_t off, int64_t n) {
    int64_t v = 0;
    for (int64_t i = 0; i < n; ++i) {
      char c = s[off + i];
      if (c < '0' || c > '9') return (int64_t)-1;
      v = v * 10 + (c - '0');
    }
    return v;
  };
  int64_t Y = num(0, 4), M = num(5, 2), D = num(8, 2);
  int64_t h = num(11, 2), m = num(14, 2), sec = num(17, 2);
  if (Y < 0 || M < 0 || D < 0 || h < 0 || m < 0 || sec < 0)
    return __builtin_nan("");
  double frac = 0.0;
  int64_t i = 19;
  if (i < len && s[i] == '.') {
    double scale = 0.1;
    for (++i; i < len && s[i] >= '0' && s[i] <= '9'; ++i) {
      frac += (s[i] - '0') * scale;
      scale *= 0.1;
    }
  }
  double tz_off = 0.0;
  if (i < len) {
    if (s[i] == 'Z' && i + 1 == len) {
      // UTC marker
    } else if ((s[i] == '+' || s[i] == '-') && len - i >= 6 && s[i + 3] == ':') {
      int64_t oh = num(i + 1, 2), om = num(i + 4, 2);
      if (oh < 0 || om < 0 || len - i != 6) return __builtin_nan("");
      tz_off = (double)(oh * 3600 + om * 60) * (s[i] == '+' ? 1.0 : -1.0);
    } else {
      return __builtin_nan("");  // trailing junk -> python fallback
    }
  }
  return (double)(days_from_civil(Y, M, D) * 86400 + h * 3600 + m * 60 + sec) +
         frac - tz_off;
}

// Phase 1: count data rows and total path/client byte lengths.
// Returns row count, or -1 on IO error, -2 if the file uses CSV quoting,
// -3 if a non-empty row has fewer than 4 fields (caller falls back to the
// Python csv parser, which raises a proper diagnostic).
int64_t log_scan(const char* path, int64_t* path_bytes, int64_t* client_bytes) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t rows = 0, pb = 0, cb = 0;
  bool quoted = false, malformed = false;
  std::vector<char> buf(1 << 20);
  std::string line;
  line.reserve(512);
  size_t got;
  std::string carry;
  while ((got = std::fread(buf.data(), 1, buf.size(), f)) > 0) {
    size_t start = 0;
    for (size_t i = 0; i < got; ++i) {
      if (buf[i] == '"') quoted = true;
      if (buf[i] == '\n') {
        std::string full = carry + std::string(buf.data() + start, i - start);
        carry.clear();
        start = i + 1;
        if (full.empty()) continue;
        // fields: ts,path,op,client,pid
        size_t c1 = full.find(',');
        size_t c2 = c1 == std::string::npos ? std::string::npos
                                            : full.find(',', c1 + 1);
        size_t c3 = c2 == std::string::npos ? std::string::npos
                                            : full.find(',', c2 + 1);
        if (c3 == std::string::npos) { malformed = true; continue; }
        size_t c4 = full.find(',', c3 + 1);
        if (c4 == std::string::npos) c4 = full.size();
        pb += (int64_t)(c2 - c1 - 1);
        cb += (int64_t)(c4 - c3 - 1);
        ++rows;
      }
    }
    carry.append(buf.data() + start, got - start);
  }
  std::fclose(f);
  if (!carry.empty()) {
    size_t c1 = carry.find(',');
    size_t c2 = c1 == std::string::npos ? std::string::npos
                                        : carry.find(',', c1 + 1);
    size_t c3 = c2 == std::string::npos ? std::string::npos
                                        : carry.find(',', c2 + 1);
    if (c3 != std::string::npos) {
      size_t c4 = carry.find(',', c3 + 1);
      if (c4 == std::string::npos) c4 = carry.size();
      pb += (int64_t)(c2 - c1 - 1);
      cb += (int64_t)(c4 - c3 - 1);
      ++rows;
    } else {
      malformed = true;
    }
  }
  if (quoted) return -2;
  if (malformed) return -3;
  *path_bytes = pb;
  *client_bytes = cb;
  return rows;
}

// Phase 2: fill columnar output.  Path/client strings are concatenated into
// byte blobs with (rows+1) offset arrays; Python slices + interns them.
// Returns rows parsed, or -1 on IO error.
int64_t log_fill(const char* path, int64_t max_rows, int64_t path_cap,
                 int64_t client_cap, double* ts_out,
                 int8_t* op_out, char* path_blob, int64_t* path_off,
                 char* client_blob, int64_t* client_off) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t row = 0, ppos = 0, cpos = 0;
  bool overflow = false;
  path_off[0] = 0;
  client_off[0] = 0;
  std::vector<char> buf(1 << 20);
  std::string carry;
  size_t got;
  auto handle = [&](const char* s, size_t len) {
    if (len == 0 || row >= max_rows) return;
    const char* c1 = (const char*)memchr(s, ',', len);
    if (!c1) return;
    const char* c2 = (const char*)memchr(c1 + 1, ',', len - (c1 + 1 - s));
    if (!c2) return;
    const char* c3 = (const char*)memchr(c2 + 1, ',', len - (c2 + 1 - s));
    if (!c3) return;
    const char* c4 = (const char*)memchr(c3 + 1, ',', len - (c3 + 1 - s));
    const char* end4 = c4 ? c4 : s + len;
    size_t plen = c2 - c1 - 1;
    size_t clen = end4 - c3 - 1;
    // Bounds vs the scan-pass sizing: a file rewritten between the two
    // passes must not overflow the caller's numpy buffers.
    if (ppos + (int64_t)plen > path_cap || cpos + (int64_t)clen > client_cap) {
      overflow = true;
      return;
    }
    ts_out[row] = parse_iso(s, c1 - s);
    std::memcpy(path_blob + ppos, c1 + 1, plen);
    ppos += (int64_t)plen;
    // op field: "WRITE" -> 1 else 0
    op_out[row] = (c3 - c2 - 1 == 5 && std::memcmp(c2 + 1, "WRITE", 5) == 0)
                      ? 1 : 0;
    std::memcpy(client_blob + cpos, c3 + 1, clen);
    cpos += (int64_t)clen;
    ++row;
    path_off[row] = ppos;
    client_off[row] = cpos;
  };
  while ((got = std::fread(buf.data(), 1, buf.size(), f)) > 0) {
    size_t start = 0;
    for (size_t i = 0; i < got; ++i) {
      if (buf[i] == '\n') {
        if (!carry.empty()) {
          carry.append(buf.data() + start, i - start);
          handle(carry.data(), carry.size());
          carry.clear();
        } else {
          handle(buf.data() + start, i - start);
        }
        start = i + 1;
      }
    }
    carry.append(buf.data() + start, got - start);
  }
  if (!carry.empty()) handle(carry.data(), carry.size());
  std::fclose(f);
  if (overflow) return -1;
  return row;
}

}  // extern "C"
