// cdrs_native — native runtime components for the TPU framework.
//
// The reference implements its host-side data plane in interpreted Python:
// a per-event Poisson loop (reference: src/access_simulator.py:16-38) and
// csv-module log parsing (consumed by Spark).  This library provides the
// native equivalents used by cdrs_tpu/runtime/native.py via ctypes:
//
//   * simulate_events — threaded Poisson access-event generation, sorted by
//     timestamp, deterministic per (seed, file) regardless of thread count.
//   * log_fill_chunk / intern_* — chunked access.log CSV reader emitting
//     columnar arrays (epoch seconds, op, offset-indexed path/client byte
//     ranges) plus hash-map string interning, resumable by byte offset.
//
// Exact distributional semantics match cdrs_tpu/sim/access.py (order-
// statistics Poisson: count ~ Poisson(lambda*T), times uniform on [0, T)),
// with a C++ RNG stream (std::mt19937_64) — deterministic but distinct from
// NumPy's Philox; tests compare distributions, not bitstreams.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Event simulation
// ---------------------------------------------------------------------------

// Phase 1: per-file Poisson event counts.  Returns total events.
// counts_out: int64[n_files]
int64_t sim_counts(int64_t n_files, const double* read_rate,
                   const double* write_rate, double duration, uint64_t seed,
                   int64_t* counts_out) {
  int64_t total = 0;
  for (int64_t i = 0; i < n_files; ++i) {
    std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i);
    double lam = (read_rate[i] + write_rate[i]) * duration;
    int64_t c = 0;
    if (lam > 0) {
      std::poisson_distribution<int64_t> pois(lam);
      c = pois(rng);
    }
    counts_out[i] = c;
    total += c;
  }
  return total;
}

// Phase 2: fill event arrays (ts, pid, op, client), then sort by timestamp.
// Deterministic per (seed, file): each file's events come from an RNG seeded
// by (seed, i), independent of thread scheduling.  Arrays are caller-
// allocated with the total from sim_counts.
void sim_fill(int64_t n_files, const int64_t* counts, const double* read_rate,
              const double* write_rate, const double* locality,
              const int32_t* primary_node, const int32_t* client_pool,
              int64_t n_pool, double duration, double sim_start, uint64_t seed,
              int64_t n_threads, double* ts_out, int32_t* pid_out,
              int8_t* op_out, int32_t* client_out) {
  std::vector<int64_t> offsets(n_files + 1, 0);
  for (int64_t i = 0; i < n_files; ++i) offsets[i + 1] = offsets[i] + counts[i];
  const int64_t total = offsets[n_files];

  if (n_threads <= 0) {
    n_threads = (int64_t)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }

  std::atomic<int64_t> next_file(0);
  auto worker = [&]() {
    for (;;) {
      int64_t i = next_file.fetch_add(1);
      if (i >= n_files) return;
      // Re-seed as in sim_counts and discard the count draw (same
      // distribution + same engine state consume the same variates), so the
      // fill stream continues deterministically after it.
      std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)i);
      double lam = (read_rate[i] + write_rate[i]) * duration;
      if (lam > 0) {
        std::poisson_distribution<int64_t> pois(lam);
        (void)pois(rng);
      }
      std::uniform_real_distribution<double> uni(0.0, 1.0);
      const double p_read =
          read_rate[i] / (read_rate[i] + write_rate[i] + 1e-12);
      const double loc = locality[i];
      const int32_t prim = primary_node[i];
      for (int64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
        ts_out[j] = sim_start + uni(rng) * duration;
        pid_out[j] = (int32_t)i;
        op_out[j] = uni(rng) >= p_read ? 1 : 0;  // 1 = WRITE
        if (n_pool <= 0 || uni(rng) < loc) {
          client_out[j] = prim;
        } else {
          client_out[j] = client_pool[(int64_t)(uni(rng) * (double)n_pool) %
                                      n_pool];
        }
      }
    }
  };
  std::vector<std::thread> threads;
  for (int64_t t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();

  // Global time sort (reference: access_simulator.py:60).  An index
  // permutation + per-column gathers is cache-hostile at 1B events (every
  // comparison and every gather is a random read across a 17 GB working
  // set); instead: pack rows into 24 B structs, scatter them into time
  // buckets (sequential read, ~4K append streams), stable-sort each small
  // bucket by ts, and unpack sequentially.  Bucket append preserves input
  // order and the per-bucket sort is stable, so ties keep the original
  // (file-major) order — identical output to the stable index sort.
  struct Ev {
    double ts;
    int32_t pid;
    int32_t client;
    int8_t op;
  };
  const int64_t n_buckets =
      std::max<int64_t>(1, std::min<int64_t>(4096, total >> 18));
  std::vector<int64_t> bucket_pos(n_buckets + 1, 0);
  const double inv_span = duration > 0 ? (double)n_buckets / duration : 0.0;
  auto bucket_of = [&](double t) {
    int64_t b = (int64_t)((t - sim_start) * inv_span);
    return b < 0 ? 0 : (b >= n_buckets ? n_buckets - 1 : b);
  };
  for (int64_t i = 0; i < total; ++i) ++bucket_pos[bucket_of(ts_out[i]) + 1];
  for (int64_t b = 0; b < n_buckets; ++b) bucket_pos[b + 1] += bucket_pos[b];
  // Scatter straight from the column arrays — one 24 B/event temporary
  // (binned), not two; at 1B events that is the difference between ~24 GB
  // and ~48 GB of staging.
  std::vector<Ev> binned(total);
  {
    std::vector<int64_t> cur(bucket_pos.begin(), bucket_pos.end() - 1);
    for (int64_t i = 0; i < total; ++i)
      binned[cur[bucket_of(ts_out[i])]++] =
          Ev{ts_out[i], pid_out[i], client_out[i], op_out[i]};
  }

  std::atomic<int64_t> next_bucket(0);
  auto sort_worker = [&]() {
    for (;;) {
      int64_t b = next_bucket.fetch_add(1);
      if (b >= n_buckets) return;
      std::stable_sort(binned.begin() + bucket_pos[b],
                       binned.begin() + bucket_pos[b + 1],
                       [](const Ev& a, const Ev& c) { return a.ts < c.ts; });
    }
  };
  threads.clear();
  for (int64_t t = 0; t < n_threads; ++t) threads.emplace_back(sort_worker);
  for (auto& t : threads) t.join();

  for (int64_t i = 0; i < total; ++i) {
    ts_out[i] = binned[i].ts;
    pid_out[i] = binned[i].pid;
    client_out[i] = binned[i].client;
    op_out[i] = binned[i].op;
  }
}

// ---------------------------------------------------------------------------
// access.log CSV parsing
// ---------------------------------------------------------------------------

// days-from-civil (Howard Hinnant's public-domain algorithm shape): epoch days
// for a proleptic Gregorian date.
static int64_t days_from_civil(int64_t y, int64_t m, int64_t d) {
  y -= m <= 2;
  const int64_t era = (y >= 0 ? y : y - 399) / 400;
  const int64_t yoe = y - era * 400;
  const int64_t doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const int64_t doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + doe - 719468;
}

// Parse "YYYY-MM-DDTHH:MM:SS[.frac][Z|+HH:MM|-HH:MM]" -> epoch seconds.
// Returns NaN on malformed input (matching Python parse_iso_ts's accepted
// grammar; naive stamps are treated as UTC).
static double parse_iso(const char* s, int64_t len) {
  if (len < 19) return __builtin_nan("");
  auto num = [&](int64_t off, int64_t n) {
    int64_t v = 0;
    for (int64_t i = 0; i < n; ++i) {
      char c = s[off + i];
      if (c < '0' || c > '9') return (int64_t)-1;
      v = v * 10 + (c - '0');
    }
    return v;
  };
  int64_t Y = num(0, 4), M = num(5, 2), D = num(8, 2);
  int64_t h = num(11, 2), m = num(14, 2), sec = num(17, 2);
  if (Y < 0 || M < 0 || D < 0 || h < 0 || m < 0 || sec < 0)
    return __builtin_nan("");
  double frac = 0.0;
  int64_t i = 19;
  if (i < len && s[i] == '.') {
    double scale = 0.1;
    for (++i; i < len && s[i] >= '0' && s[i] <= '9'; ++i) {
      frac += (s[i] - '0') * scale;
      scale *= 0.1;
    }
  }
  double tz_off = 0.0;
  if (i < len) {
    if (s[i] == 'Z' && i + 1 == len) {
      // UTC marker
    } else if ((s[i] == '+' || s[i] == '-') && len - i >= 6 && s[i + 3] == ':') {
      int64_t oh = num(i + 1, 2), om = num(i + 4, 2);
      if (oh < 0 || om < 0 || len - i != 6) return __builtin_nan("");
      tz_off = (double)(oh * 3600 + om * 60) * (s[i] == '+' ? 1.0 : -1.0);
    } else {
      return __builtin_nan("");  // trailing junk -> python fallback
    }
  }
  return (double)(days_from_civil(Y, M, D) * 86400 + h * 3600 + m * 60 + sec) +
         frac - tz_off;
}

// ---------------------------------------------------------------------------
// Log writing (the inverse of ingestion — emitting the reference's
// access.log format at native speed; the python csv loop writes ~0.3M
// rows/s, hours at the 1B-event scale)
// ---------------------------------------------------------------------------

// Inverse of days_from_civil (Hinnant's civil_from_days shape).
static void civil_from_days(int64_t z, int64_t* y, int64_t* m, int64_t* d) {
  z += 719468;
  const int64_t era = (z >= 0 ? z : z - 146096) / 146097;
  const int64_t doe = z - era * 146097;
  const int64_t yoe = (doe - doe / 1460 + doe / 36524 - doe / 146096) / 365;
  const int64_t doy = doe - (365 * yoe + yoe / 4 - yoe / 100);
  const int64_t mp = (5 * doy + 2) / 153;
  *d = doy - (153 * mp + 2) / 5 + 1;
  *m = mp + (mp < 10 ? 3 : -9);
  *y = yoe + era * 400 + (*m <= 2);
}

// Append `n` rows "iso_ts,path,op,client,pid" to `path`.  Timestamps are
// formatted with millisecond precision + 'Z' (reference:
// src/access_simulator.py:5-6).  Paths/clients come as blob+offset string
// tables indexed by pid/client.  Returns rows written, -1 on IO error.
int64_t log_write(const char* path, int64_t n, const double* ts,
                  const int32_t* pid, const int8_t* op, const int32_t* client,
                  const char* pblob, const int64_t* poff,
                  const char* cblob, const int64_t* coff,
                  int64_t append) {
  FILE* f = std::fopen(path, append ? "ab" : "wb");
  if (!f) return -1;
  std::vector<char> buf(1 << 22);
  size_t pos = 0;
  // The stream is time-sorted, so consecutive rows usually share the whole
  // second: cache the formatted "YYYY-MM-DDTHH:MM:SS." prefix per second
  // (snprintf per row was the writer's bottleneck: 0.67M -> ~5M rows/s).
  int64_t last_whole = INT64_MIN;
  char datebuf[32];
  int datelen = 0;
  for (int64_t i = 0; i < n; ++i) {
    // The path/client blob reads are random across a multi-MB table (pids
    // are time-ordered, i.e. shuffled): prefetch a few rows ahead so the
    // misses overlap the formatting work.
    if (i + 8 < n) {
      __builtin_prefetch(&poff[pid[i + 8]]);
      __builtin_prefetch(&coff[client[i + 8]]);
    }
    if (i + 4 < n) {
      __builtin_prefetch(pblob + poff[pid[i + 4]]);
      __builtin_prefetch(cblob + coff[client[i + 4]]);
    }
    double t = ts[i];
    int64_t whole = (int64_t)t;
    if ((double)whole > t) --whole;               // floor for negative ts
    // Truncate to ms (no rounding) — byte-identical to the python
    // fallback writer, which computes (t - floor(t)) * 1000.0 and
    // truncates with the same IEEE double ops (ADVICE r3).
    int64_t ms = (int64_t)((t - (double)whole) * 1000.0);
    if (ms > 999) ms = 999;
    if (whole != last_whole) {
      int64_t days = whole / 86400;
      int64_t sod = whole - days * 86400;
      if (sod < 0) { sod += 86400; --days; }
      int64_t Y, M, D;
      civil_from_days(days, &Y, &M, &D);
      datelen = std::snprintf(
          datebuf, sizeof(datebuf), "%04lld-%02lld-%02lldT%02lld:%02lld:%02lld.",
          (long long)Y, (long long)M, (long long)D,
          (long long)(sod / 3600), (long long)((sod / 60) % 60),
          (long long)(sod % 60));
      last_whole = whole;
    }
    const int64_t p = pid[i], c = client[i];
    const int64_t plen = poff[p + 1] - poff[p];
    const int64_t clen = coff[c + 1] - coff[c];
    // row: ts(datelen+5), path, op(<=5), client, pid(4) + separators
    if (pos + (size_t)datelen + (size_t)plen + (size_t)clen + 32 > buf.size()) {
      if (std::fwrite(buf.data(), 1, pos, f) != pos) { std::fclose(f); return -1; }
      pos = 0;
    }
    std::memcpy(buf.data() + pos, datebuf, (size_t)datelen);
    pos += (size_t)datelen;
    buf[pos++] = (char)('0' + ms / 100);
    buf[pos++] = (char)('0' + (ms / 10) % 10);
    buf[pos++] = (char)('0' + ms % 10);
    buf[pos++] = 'Z';
    buf[pos++] = ',';
    std::memcpy(buf.data() + pos, pblob + poff[p], (size_t)plen); pos += (size_t)plen;
    buf[pos++] = ',';
    if (op[i]) { std::memcpy(buf.data() + pos, "WRITE", 5); pos += 5; }
    else { std::memcpy(buf.data() + pos, "READ", 4); pos += 4; }
    buf[pos++] = ',';
    std::memcpy(buf.data() + pos, cblob + coff[c], (size_t)clen); pos += (size_t)clen;
    buf[pos++] = ',';
    int64_t tag = 1000 + i % 9000;                // always 4 digits
    buf[pos++] = (char)('0' + tag / 1000);
    buf[pos++] = (char)('0' + (tag / 100) % 10);
    buf[pos++] = (char)('0' + (tag / 10) % 10);
    buf[pos++] = (char)('0' + tag % 10);
    buf[pos++] = '\n';
  }
  if (pos && std::fwrite(buf.data(), 1, pos, f) != pos) { std::fclose(f); return -1; }
  std::fclose(f);
  return n;
}

// ---------------------------------------------------------------------------
// Chunked log ingestion (streaming; the 1B-event feed must never be resident)
// ---------------------------------------------------------------------------

// Single-pass chunk parser: parse up to max_rows complete rows starting at
// byte `offset`, stopping early when the path/client blob capacities would
// overflow (the unread row starts at *next_offset — the caller simply issues
// the next chunk from there).  Returns rows parsed; -1 on IO error; -2 when
// a row uses CSV quoting; -3 when a non-empty row has fewer than 4 fields
// (for -2/-3, *next_offset is the offending row's start so the caller can
// resume with the python csv parser from that exact byte).
int64_t log_fill_chunk(const char* path, int64_t offset, int64_t max_rows,
                       int64_t path_cap, int64_t client_cap,
                       double* ts_out, int8_t* op_out,
                       char* path_blob, int64_t* path_off,
                       char* client_blob, int64_t* client_off,
                       int64_t* next_offset) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (offset > 0 && std::fseek(f, (long)offset, SEEK_SET) != 0) {
    std::fclose(f);
    return -1;
  }
  int64_t row = 0, ppos = 0, cpos = 0;
  int64_t line_start = offset;   // absolute byte offset of the current line
  int64_t consumed = offset;     // absolute offset just past the last row taken
  int err = 0;                   // 0 ok, -2 quoted, -3 malformed
  bool full = false;             // max_rows or caps reached
  std::vector<char> buf(1 << 20);
  std::string carry;
  size_t got;

  // Returns false when the chunk must stop (full or error).
  auto handle = [&](const char* s, size_t len, int64_t abs_end) -> bool {
    if (len == 0) { consumed = abs_end; return true; }
    if (row >= max_rows) { full = true; return false; }
    if (memchr(s, '"', len)) { err = -2; return false; }
    const char* c1 = (const char*)memchr(s, ',', len);
    const char* c2 = c1 ? (const char*)memchr(c1 + 1, ',', len - (c1 + 1 - s)) : nullptr;
    const char* c3 = c2 ? (const char*)memchr(c2 + 1, ',', len - (c2 + 1 - s)) : nullptr;
    if (!c3) { err = -3; return false; }
    const char* c4 = (const char*)memchr(c3 + 1, ',', len - (c3 + 1 - s));
    const char* end4 = c4 ? c4 : s + len;
    int64_t plen = (int64_t)(c2 - c1 - 1);
    int64_t clen = (int64_t)(end4 - c3 - 1);
    if (ppos + plen > path_cap || cpos + clen > client_cap) {
      full = true;   // next chunk starts at this row
      return false;
    }
    ts_out[row] = parse_iso(s, c1 - s);
    std::memcpy(path_blob + ppos, c1 + 1, (size_t)plen);
    ppos += plen;
    op_out[row] = (c3 - c2 - 1 == 5 && std::memcmp(c2 + 1, "WRITE", 5) == 0)
                      ? 1 : 0;
    std::memcpy(client_blob + cpos, c3 + 1, (size_t)clen);
    cpos += clen;
    ++row;
    path_off[row] = ppos;
    client_off[row] = cpos;
    consumed = abs_end;
    return true;
  };

  path_off[0] = 0;
  client_off[0] = 0;
  int64_t file_pos = offset;
  bool stop = false;
  while (!stop && (got = std::fread(buf.data(), 1, buf.size(), f)) > 0) {
    size_t start = 0;
    for (size_t i = 0; i < got; ++i) {
      if (buf[i] != '\n') continue;
      int64_t abs_end = file_pos + (int64_t)i + 1;
      bool ok;
      if (!carry.empty()) {
        carry.append(buf.data() + start, i - start);
        ok = handle(carry.data(), carry.size(), abs_end);
        carry.clear();
      } else {
        ok = handle(buf.data() + start, i - start, abs_end);
      }
      if (!ok) { stop = true; break; }
      start = i + 1;
      line_start = abs_end;
    }
    if (!stop) carry.append(buf.data() + start, got - start);
    file_pos += (int64_t)got;
  }
  if (!stop && !carry.empty()) {
    // Final line without a trailing newline.
    handle(carry.data(), carry.size(), file_pos);
  }
  std::fclose(f);
  if (err) { *next_offset = line_start; return err; }
  *next_offset = consumed;
  return row;
}

// ---------------------------------------------------------------------------
// Native string interning — path -> id lookups without a Python row loop
// ---------------------------------------------------------------------------

// Open-addressing hash table with software-prefetched probes.  At 1M+
// interned paths every probe is a cold cache miss (the 1M-file round-3
// profile was hash-probe bound at ~1.06M rows/s on this 1-core host); a
// flat power-of-two table of (hash64, id) slots needs ONE miss per probe
// instead of unordered_map's bucket + node + heap-string chain, and
// batched __builtin_prefetch hides even that one behind neighbouring rows.
// Full 64-bit hashes are stored so a slot mismatch almost never touches the
// key bytes; equal hashes still verify against the interned string (ids
// index `names`, insertion order — the exported vocabulary is unchanged).

static inline uint64_t hash_key(const char* s, size_t len) {
  // FNV-1a 64 with an avalanche finalizer (splitmix64) — cheap, and the
  // finalizer fixes FNV's weak high bits for power-of-two masking.
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < len; ++i) {
    h ^= (unsigned char)s[i];
    h *= 1099511628211ull;
  }
  h ^= h >> 30; h *= 0xbf58476d1ce4e5b9ull;
  h ^= h >> 27; h *= 0x94d049bb133111ebull;
  h ^= h >> 31;
  return h ? h : 1;  // 0 marks an empty slot
}

struct Slot {
  uint64_t h;   // 0 = empty
  int32_t id;
};

struct InternMap {
  std::vector<Slot> slots;          // one cache line covers hash AND id
  std::vector<std::string> names;   // id -> string (insertion order)
  uint64_t mask = 0;

  void rehash(size_t want) {
    size_t cap = 64;
    while (cap < want * 2) cap <<= 1;   // load factor <= 0.5
    std::vector<Slot> ns(cap, Slot{0, -1});
    uint64_t nm = cap - 1;
    for (const Slot& s : slots) {
      if (!s.h) continue;
      uint64_t j = s.h & nm;
      while (ns[j].h) j = (j + 1) & nm;
      ns[j] = s;
    }
    slots.swap(ns);
    mask = nm;
  }

  // Returns the slot holding `key`, or the empty slot where it belongs.
  inline uint64_t probe(uint64_t h, const char* key, size_t len) const {
    uint64_t j = h & mask;
    while (slots[j].h) {
      if (slots[j].h == h) {
        const std::string& nm = names[(size_t)slots[j].id];
        if (nm.size() == len && std::memcmp(nm.data(), key, len) == 0)
          return j;
      }
      j = (j + 1) & mask;
    }
    return j;
  }

  // Deduplicating insert (the growing client-vocabulary path).
  int32_t insert(const char* key, size_t len) {
    if ((names.size() + 1) * 2 > slots.size()) rehash(names.size() + 1);
    uint64_t h = hash_key(key, len);
    uint64_t j = probe(h, key, len);
    if (slots[j].h) return slots[j].id;
    int32_t id = (int32_t)names.size();
    slots[j] = Slot{h, id};
    names.emplace_back(key, len);
    return id;
  }
};

// Build an intern map from a byte blob + (n+1) offsets.  Ids are POSITIONS:
// names keeps all n entries (even duplicates) so exported vocabularies and
// intern_size match the input exactly; a duplicate key looks up its FIRST
// position (the unordered_map emplace semantics this table replaced).
void* intern_build(const char* blob, const int64_t* off, int64_t n) {
  auto* h = new InternMap();
  h->rehash((size_t)n + 1);
  h->names.reserve((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    const char* key = blob + off[i];
    const size_t len = (size_t)(off[i + 1] - off[i]);
    h->names.emplace_back(key, len);
    uint64_t hk = hash_key(key, len);
    uint64_t j = h->probe(hk, key, len);
    if (!h->slots[j].h) h->slots[j] = Slot{hk, (int32_t)i};
  }
  return h;
}

void intern_free(void* handle) { delete (InternMap*)handle; }

int64_t intern_size(void* handle) {
  return (int64_t)((InternMap*)handle)->names.size();
}

// out[i] = id of blob[off[i]:off[i+1]] in the map, or -1 when absent.
void intern_lookup(void* handle, const char* blob, const int64_t* off,
                   int64_t n, int32_t* out) {
  auto& m = *(InternMap*)handle;
  // Software-pipelined blocks: hash a block of keys and prefetch their
  // first slots, then probe — the table spills cache at 1M entries, so
  // overlapping the misses is worth ~2x on a single core.  (OpenMP threads
  // additionally split the chunk when cores exist.)
  constexpr int64_t B = 16;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static) if (n > 65536)
#endif
  for (int64_t base = 0; base < n; base += B) {
    const int64_t hi = base + B < n ? base + B : n;
    uint64_t hs[B];
    for (int64_t i = base; i < hi; ++i) {
      hs[i - base] = hash_key(blob + off[i], (size_t)(off[i + 1] - off[i]));
      __builtin_prefetch(&m.slots[hs[i - base] & m.mask]);
    }
    for (int64_t i = base; i < hi; ++i) {
      const char* key = blob + off[i];
      const size_t len = (size_t)(off[i + 1] - off[i]);
      uint64_t j = m.probe(hs[i - base], key, len);
      out[i] = m.slots[j].h ? m.slots[j].id : -1;
    }
  }
}

// Like intern_lookup, but unseen keys are INSERTED with the next id (growing
// vocabulary — the client-node interning path).  Returns the map size after.
int64_t intern_insert_lookup(void* handle, const char* blob,
                             const int64_t* off, int64_t n, int32_t* out) {
  auto* h = (InternMap*)handle;
  for (int64_t i = 0; i < n; ++i)
    out[i] = h->insert(blob + off[i], (size_t)(off[i + 1] - off[i]));
  return (int64_t)h->names.size();
}

// Total bytes of names[start:] — sizes the export blob.
int64_t intern_export_bytes(void* handle, int64_t start) {
  auto& names = ((InternMap*)handle)->names;
  int64_t total = 0;
  for (size_t i = (size_t)start; i < names.size(); ++i)
    total += (int64_t)names[i].size();
  return total;
}

// Export names[start:] as a blob + (count+1) offsets (insertion order).
void intern_export(void* handle, int64_t start, char* blob, int64_t* off) {
  auto& names = ((InternMap*)handle)->names;
  int64_t pos = 0, j = 0;
  off[0] = 0;
  for (size_t i = (size_t)start; i < names.size(); ++i) {
    std::memcpy(blob + pos, names[i].data(), names[i].size());
    pos += (int64_t)names[i].size();
    off[++j] = pos;
  }
}

}  // extern "C"
