#!/usr/bin/env python3
"""Driver benchmark entry point.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Runs BASELINE.md config 2 (1M files x 32 features, k=128) by default on
whatever accelerator JAX finds (the real TPU chip when available, CPU
otherwise): Lloyd iterations/sec, jax vs the reference-style numpy path on the
identical workload.  ``--config N`` selects another BASELINE config.
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--config", type=int, default=2)
    p.add_argument("--backend", default=None)
    p.add_argument("--update", default=None,
                   choices=["auto", "matmul", "scatter", "pallas"],
                   help="Lloyd assign+reduce strategy (default: the config's; "
                        "auto = pallas on TPU where it fits, matmul else)")
    p.add_argument("--e2e", action="store_true",
                   help="wall-clock time-to-categories instead of iter/s")
    args = p.parse_args()

    import os

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cdrs_tpu.benchmarks.harness import run_bench

    out = run_bench(config=args.config, backend=args.backend,
                    update=args.update, e2e=args.e2e)
    line = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": out["unit"],
        "vs_baseline": out["vs_baseline"],
    }
    print(json.dumps(line))
    # Full detail to stderr so the one-line stdout contract stays clean.
    print(json.dumps(out), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
