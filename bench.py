#!/usr/bin/env python3
"""Driver benchmark entry point.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Default (no ``--config``): the full driver capture — BASELINE.md config 2
(1M x 32, k=128 — the headline stdout metric, unchanged across rounds),
PLUS config 3 (10M x 128, k=1024 Lloyd iter/s) and the config-4 single-chip
rehearsal (bf16 points, e2e time-to-categories at the true 13.1M-row
per-chip shard) as ``config3`` / ``config4_rehearsal`` blocks in the detail
JSON (VERDICT r4 #6: the k=1024 headline numbers must be independently
captured by the driver, not only by builder-run artifacts).

``--config N`` runs exactly one config (the previous behavior).
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--config", type=int, default=None,
                   help="run a single BASELINE config (default: config 2 "
                        "plus the config-3/config-4-rehearsal capture; "
                        "--update/--dtype/--e2e apply to those captures "
                        "too)")
    p.add_argument("--backend", default=None)
    p.add_argument("--update", default=None,
                   choices=["auto", "matmul", "scatter", "pallas"],
                   help="Lloyd assign+reduce strategy (default: the config's; "
                        "auto = pallas on TPU where it fits, matmul else)")
    p.add_argument("--e2e", action="store_true",
                   help="wall-clock time-to-categories instead of iter/s")
    p.add_argument("--dtype", default=None,
                   choices=["float32", "bfloat16", "float64"])
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="emit telemetry (spans, kmeans convergence traces, "
                        "recompile counters) here; inspect with "
                        "'cdrs metrics summarize'")
    args = p.parse_args()

    import contextlib
    import os

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cdrs_tpu.benchmarks.harness import run_bench
    from cdrs_tpu.obs import run_metadata

    def emit_line(out):
        print(json.dumps({
            "metric": out["metric"],
            "value": out["value"],
            "unit": out["unit"],
            "vs_baseline": out["vs_baseline"],
        }), flush=True)

    stack = contextlib.ExitStack()
    if args.metrics:
        from cdrs_tpu.obs import JsonlSink, Telemetry

        # kmeans_trace=False: tracing swaps in the convergence-traced
        # program (and the matmul strategy) — it must not perturb the
        # kernels this harness exists to time.  Spans/counters only.
        tel = stack.enter_context(Telemetry(JsonlSink(args.metrics),
                                            kmeans_trace=False))
        stack.enter_context(tel.span("bench"))

    with stack:  # exception-safe: a failing capture still closes the sink
        out = run_bench(config=2 if args.config is None else args.config,
                        backend=args.backend, update=args.update,
                        e2e=args.e2e, dtype=args.dtype)
        # Contract line FIRST: the k=1024 captures below add ~30 min on the
        # tunnel host, and a driver timeout must not lose the headline.
        emit_line(out)
        if args.config is None:
            # The k=1024 headline configs, captured in the same driver run —
            # on a real TPU only (on a CPU-only host the 10M x 128 workloads
            # would hang the previously-fast default for hours; the driver's
            # bench host has the chip).  Failures are recorded, not fatal —
            # the config-2 contract line must survive a config-3 OOM on an
            # unexpected host.
            import jax

            if jax.default_backend() == "tpu":
                # --update/--dtype/--e2e apply to the extra captures too, so
                # a flagged driver run measures ONE strategy everywhere
                # instead of silently reverting the k=1024 captures to their
                # defaults.
                try:
                    out["config3"] = run_bench(config=3, quality=False,
                                               update=args.update,
                                               e2e=args.e2e,
                                               dtype=args.dtype)
                except Exception as e:  # pragma: no cover - depends on host
                    out["config3"] = {"error": f"{type(e).__name__}: {e}"}
                try:
                    # bf16 points double rows/chip: on one chip config 4
                    # downscales to 13.1M rows = the TRUE v5e-8 per-chip
                    # shard (104857600/8).  The rehearsal is DEFINED as an
                    # e2e bf16 run: --update/--dtype override it, --e2e is
                    # already on.
                    out["config4_rehearsal"] = run_bench(
                        config=4, quality=False, e2e=True,
                        update=args.update, dtype=args.dtype or "bfloat16")
                except Exception as e:  # pragma: no cover - depends on host
                    out["config4_rehearsal"] = {
                        "error": f"{type(e).__name__}: {e}"}
            else:
                note = ("skipped: no TPU backend (run bench.py --config N "
                        "to force)")
                out["config3"] = {"skipped": note}
                out["config4_rehearsal"] = {"skipped": note}

    # Environment stamp: makes BENCH_*.json trajectory files comparable
    # across machines (jax/numpy versions, backend, device count, x64).
    out["run_meta"] = run_metadata()
    # Full detail to stderr so the one-line stdout contract stays clean.
    print(json.dumps(out), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
