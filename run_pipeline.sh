#!/usr/bin/env bash
# End-to-end pipeline driver — the reference's run_pipeline.sh role
# (SURVEY.md §3.1) without the Docker/Hadoop machinery: every stage is a
# `cdrs` CLI call over durable file boundaries (metadata.csv -> access.log ->
# features_out -> final_categories.csv), and — unlike the reference, which
# stops at features — it runs clustering AND applies the decided replication
# factors on the simulated cluster.
#
# Usage: ./run_pipeline.sh [NUM_FILES] [DURATION_SECONDS]
set -euo pipefail

NUM_FILES="${1:-200}"
DURATION="${2:-600}"
K="${K:-4}"
OUTDIR="${OUTDIR:-output}"
BACKEND="${BACKEND:-numpy}"
PY="${PY:-python}"

cd "$(dirname "$0")"
mkdir -p "$OUTDIR"

info() { echo "[run_pipeline] $*"; }

info "1/5 generating $NUM_FILES files -> $OUTDIR/metadata.csv"
$PY -m cdrs_tpu gen --n "$NUM_FILES" --out_manifest "$OUTDIR/metadata.csv"

info "2/5 simulating $DURATION s of access events -> $OUTDIR/access.log"
$PY -m cdrs_tpu simulate --manifest "$OUTDIR/metadata.csv" \
  --out "$OUTDIR/access.log" --duration_seconds "$DURATION"

info "3/5 extracting features -> $OUTDIR/features_out/"
$PY -m cdrs_tpu features --manifest "$OUTDIR/metadata.csv" \
  --access_log "$OUTDIR/access.log" --out "$OUTDIR/features_out/" \
  --backend "$BACKEND"

info "4/5 clustering + scoring -> $OUTDIR/final_categories.csv"
$PY -m cdrs_tpu cluster --input_path "$OUTDIR/features_out/" --k "$K" \
  --output_csv "$OUTDIR/final_categories.csv" \
  --assignments_csv "$OUTDIR/assignments.csv" \
  --medians_from_data --backend "$BACKEND"

info "5/5 applying replication factors on the simulated cluster"
$PY -m cdrs_tpu evaluate --manifest "$OUTDIR/metadata.csv" \
  --access_log "$OUTDIR/access.log" \
  --assignments_csv "$OUTDIR/assignments.csv"

info "done — outputs in $OUTDIR/"
