# Orchestration entry points — the reference's Makefile role (SURVEY.md §2 #11)
# mapped onto the TPU-native framework.  Where the reference drives a Docker
# Hadoop/Spark cluster (make up/down/gen/sim/spark/pipeline/output), here every
# stage is a `cdrs` CLI subcommand and the "cluster" is a jax.sharding.Mesh —
# `make up` just verifies the device mesh is reachable.
#
# Knobs (reference: run_pipeline.sh NUM_FILES/DURATION, Makefile:36,41):
NUM_FILES ?= 200
DURATION ?= 600
K ?= 4
OUTDIR ?= output
BACKEND ?= numpy
PY ?= python

CDRS := $(PY) -m cdrs_tpu

.PHONY: up gen sim features cluster pipeline evaluate stream bench test native clean

up:  ## show the device mesh (replaces docker-compose up)
	$(PY) -c "import jax; print('devices:', jax.devices())"

gen:  ## synthetic population -> $(OUTDIR)/metadata.csv (reference: make gen)
	mkdir -p $(OUTDIR)
	$(CDRS) gen --n $(NUM_FILES) --out_manifest $(OUTDIR)/metadata.csv

sim: ## Poisson access log -> $(OUTDIR)/access.log (reference: make sim)
	$(CDRS) simulate --manifest $(OUTDIR)/metadata.csv \
	  --out $(OUTDIR)/access.log --duration_seconds $(DURATION)

features: ## five features -> $(OUTDIR)/features_out (reference: make spark)
	$(CDRS) features --manifest $(OUTDIR)/metadata.csv \
	  --access_log $(OUTDIR)/access.log --out $(OUTDIR)/features_out/ \
	  --backend $(BACKEND)

cluster: ## KMeans++ + scoring -> final_categories.csv (reference: main.py)
	$(CDRS) cluster --input_path $(OUTDIR)/features_out/ --k $(K) \
	  --output_csv $(OUTDIR)/final_categories.csv \
	  --assignments_csv $(OUTDIR)/assignments.csv \
	  --medians_from_data --backend $(BACKEND)

evaluate: ## apply rf on the simulated cluster, report locality/load/storage
	$(CDRS) evaluate --manifest $(OUTDIR)/metadata.csv \
	  --access_log $(OUTDIR)/access.log \
	  --assignments_csv $(OUTDIR)/assignments.csv

pipeline: ## end-to-end in one process (reference: make pipeline)
	$(CDRS) pipeline --n $(NUM_FILES) --duration_seconds $(DURATION) \
	  --k $(K) --outdir $(OUTDIR) --medians_from_data --evaluate \
	  --backend $(BACKEND)

stream: ## streaming variant over $(OUTDIR)/access.log
	$(CDRS) stream --manifest $(OUTDIR)/metadata.csv \
	  --access_log $(OUTDIR)/access.log --k $(K) \
	  --output_csv $(OUTDIR)/final_categories.csv --medians_from_data

bench: ## one-line benchmark JSON (BASELINE.md configs)
	$(PY) bench.py

test:
	$(PY) -m pytest tests/ -q

native: ## build the C++ runtime library
	$(MAKE) -C native

clean:
	rm -rf $(OUTDIR)
	$(MAKE) -C native clean
