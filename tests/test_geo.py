"""Geo-hierarchical failure domains, region-loss survival, elasticity.

``CDRS_CHAOS_SEED`` varies the workloads (CI sweeps 0/1/2) so the
acceptance claims — one-level degeneration bit-for-bit in BOTH
choosers, region-loss zero-lost vs measurable flat loss for replicate
AND EC in materialized AND functional modes, functional decision
identity vs the ``materialized_hash`` oracle, and mid-cell kill/resume
bit-identity — are checked against three genuinely different
populations, not one lucky seed.
"""

from __future__ import annotations

import os
import tempfile

import numpy as np
import pytest

from cdrs_tpu.cluster import ClusterTopology, place_replicas
from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import (
    ControllerConfig,
    ElasticPolicy,
    ReplicationController,
)
from cdrs_tpu.faults import ClusterState, FaultSchedule
from cdrs_tpu.placement_fn import addition_moved, compute_placement
from cdrs_tpu.scenarios import ScenarioSpec
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population
from cdrs_tpu.storage import StorageConfig, resolve_storage_config

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))

_NODES12 = tuple(f"dn{i}" for i in range(1, 13))
_GEO = {
    "nodes": list(_NODES12),
    "levels": ["rack", "region"],
    "rack": {f"r{j}": [f"dn{2 * j + 1}", f"dn{2 * j + 2}"]
             for j in range(6)},
    "region": {"eu": ["r0", "r1"], "us": ["r2", "r3"],
               "ap": ["r4", "r5"]},
    "edge_bytes": {"rack": 1.0, "region": 4.0},
    "edge_latency": {"rack": 1.5, "region": 8.0},
}
#: Same nodes, racks only — the flat contrast (no region level).
_FLAT = {"nodes": list(_NODES12), "levels": ["rack"],
         "rack": _GEO["rack"]}
#: The region 'eu' node set (r0 + r1).
_EU = ("dn1", "dn2", "dn3", "dn4")


def _geo():
    return ClusterTopology.from_hierarchy(_GEO)


def _rand_inputs(n=2000, rf_hi=6):
    rng = np.random.default_rng(300 + SEED)
    return (np.arange(n, dtype=np.int64),
            rng.integers(1, rf_hi, n).astype(np.int32),
            rng.integers(0, 12, n).astype(np.int32))


# -- topology spec -----------------------------------------------------------

def test_hierarchy_roundtrip_and_validation_names_offender():
    topo = _geo()
    assert topo.n_levels == 1
    assert topo.level_names == ("rack", "region")
    assert ClusterTopology.from_hierarchy(topo.to_hierarchy_dict()) == topo
    with pytest.raises(ValueError, match="unknown rack 'r9'"):
        ClusterTopology.from_hierarchy(
            {**_GEO, "region": {"eu": ["r0", "r9"], "us": ["r1", "r2"],
                                "ap": ["r3", "r4", "r5"]}})
    with pytest.raises(ValueError, match="'dn3'.*not assigned"):
        bad_racks = {k: [n for n in v if n != "dn3"]
                     for k, v in _GEO["rack"].items()}
        ClusterTopology.from_hierarchy({**_GEO, "rack": bad_racks})
    with pytest.raises(ValueError, match="rack 'r0' spans"):
        ClusterTopology(_NODES12,
                        tuple(_GEO["rack"].keys())[:1] * 12,
                        levels=(("region",
                                 ("eu",) * 6 + ("us",) * 6),))


def test_one_level_hierarchy_degenerates_bitforbit_both_choosers():
    """A one-level from_hierarchy spec IS the rack topology: both the
    rng and the hash chooser must reproduce the historical rack-aware
    placement bit-for-bit."""
    flat = ClusterTopology.from_hierarchy(_FLAT)
    assert flat.levels == () and flat.n_levels == 0
    racks = ClusterTopology.from_racks(
        _NODES12, {n: d for n, d in zip(flat.nodes, flat.domains)})
    man = generate_population(GeneratorConfig(
        n_files=500, seed=30 + SEED, nodes=_NODES12))
    rng = np.random.default_rng(SEED)
    rf = rng.integers(1, 5, 500).astype(np.int32)
    for method in ("rng", "hash"):
        a = place_replicas(man, rf, flat, seed=SEED, method=method)
        b = place_replicas(man, rf, racks, seed=SEED, method=method)
        assert np.array_equal(a.replica_map, b.replica_map), method
        assert np.array_equal(a.rf, b.rf), method


def test_hierarchical_chooser_properties():
    """Subset == full, nested-in-rf, chunk invariance, distinct nodes,
    and top-level max-spread (region counts differ by <= 1) under the
    hierarchy — the flat chooser's contracts carried up the tree."""
    fids, rf, prim = _rand_inputs()
    topo = _geo()
    full, rfc = compute_placement(fids, rf, prim, topo, SEED)
    dom_top = topo.top_domain_index()
    for i in range(len(fids)):
        row = full[i][full[i] >= 0]
        assert len(row) == rfc[i]
        assert len(set(row.tolist())) == len(row)
        assert row[0] == prim[i]
        counts = np.bincount(dom_top[row], minlength=3)
        assert counts.max() - counts.min() <= 1
    rng = np.random.default_rng(SEED)
    sub = rng.choice(len(fids), 137, replace=False)
    rows, _ = compute_placement(fids[sub], rf[sub], prim[sub], topo,
                                SEED, out_width=full.shape[1])
    assert np.array_equal(rows, full[sub])
    lo, lo_rf = compute_placement(fids, np.maximum(rf - 1, 1), prim,
                                  topo, SEED)
    for i in range(len(fids)):
        k = int(lo_rf[i])
        assert np.array_equal(lo[i][:k], full[i][:k])
    b, _ = compute_placement(fids, rf, prim, topo, SEED, chunk=173)
    assert np.array_equal(b, full)


def test_region_local_mask_pins_and_caps():
    fids, rf, prim = _rand_inputs()
    topo = _geo()
    rng = np.random.default_rng(SEED + 1)
    local = rng.random(len(fids)) < 0.5
    slots, rfc = compute_placement(fids, rf, prim, topo, SEED,
                                   local_mask=local)
    dom_top = topo.top_domain_index()
    for i in np.flatnonzero(local):
        row = slots[i][slots[i] >= 0]
        assert (dom_top[row] == dom_top[prim[i]]).all()
        assert rfc[i] == min(rf[i], 4)    # 4 nodes per region
    free, _ = compute_placement(fids, rf, prim, topo, SEED)
    assert np.array_equal(slots[~local], free[~local])


def test_addition_moved_is_exact():
    topo_old = _geo()
    spec2 = {
        "nodes": list(_NODES12) + ["sb1", "sb2"],
        "levels": ["rack", "region"],
        "rack": {**_GEO["rack"], "rs0": ["sb1"], "rs1": ["sb2"]},
        "region": {"eu": ["r0", "r1", "rs0"],
                   "us": ["r2", "r3", "rs1"], "ap": ["r4", "r5"]},
        "edge_bytes": _GEO["edge_bytes"],
        "edge_latency": _GEO["edge_latency"],
    }
    topo_new = ClusterTopology.from_hierarchy(spec2)
    fids, rf, prim = _rand_inputs(n=3000)
    moved = addition_moved(topo_old, topo_new, rf, prim, SEED)
    old_s, _ = compute_placement(fids, rf, prim, topo_old, SEED)
    new_s, _ = compute_placement(fids, rf, prim, topo_new, SEED)
    brute = [i for i in range(len(fids))
             if {topo_old.nodes[x] for x in old_s[i] if x >= 0}
             != {topo_new.nodes[x] for x in new_s[i] if x >= 0}]
    assert np.array_equal(moved, np.asarray(brute, dtype=np.int64))


# -- faults: region scopes + WAN pricing -------------------------------------

def test_region_scoped_schedule_expansion_and_errors():
    topo = _geo()
    sch = FaultSchedule.from_specs(
        ["crash:region:eu@3-6", "partition:region:us@2-4"])
    ex = sch.expand_domains(topo)
    specs = [e.spec() for e in ex]
    assert "partition:dn5+dn6+dn7+dn8@2" in specs
    assert {f"crash:dn{i}@3" for i in range(1, 5)} <= set(specs)
    ex.validate_nodes(topo.nodes)
    with pytest.raises(ValueError, match="no domain 'mars'"):
        FaultSchedule.from_specs(
            ["crash:region:mars@1"]).expand_domains(topo)
    with pytest.raises(ValueError, match="unknown hierarchy level"):
        FaultSchedule.from_specs(
            ["crash:zone:eu@1"]).expand_domains(topo)
    with pytest.raises(ValueError, match="unexpanded domain scopes"):
        sch.validate_nodes(topo.nodes)


def test_wan_copy_charge_and_in_region_preference():
    topo = _geo()
    man = generate_population(GeneratorConfig(
        n_files=50, seed=40 + SEED, nodes=_NODES12))
    p = place_replicas(man, np.full(50, 3, np.int32), topo, seed=SEED,
                       method="hash")
    st = ClusterState(p, np.asarray(man.size_bytes, np.int64))
    dom_top = topo.top_domain_index()
    f = 0
    row = st.row(f)
    holders = row[row >= 0]
    src_regions = set(dom_top[holders].tolist())
    in_t = next(i for i in range(12)
                if dom_top[i] in src_regions
                and i not in set(holders.tolist()))
    # rf=3 spreads one copy per region, so every region holds a source:
    # the in-region source wins the election and no multiplier applies.
    assert st.copy_charge(f, in_t) == int(st.shard_bytes[f])
    # Strand the file to ONE region: a cross-region target must charge
    # the 4x WAN multiplier.
    only = int(dom_top[holders[0]])
    for x in [int(v) for v in holders]:
        if int(dom_top[x]) != only:
            st.drop_replica(f, x)
    out_t = next(i for i in range(12) if int(dom_top[i]) != only)
    assert st.copy_charge(f, out_t) == int(np.ceil(
        int(st.shard_bytes[f]) * 4.0))


def test_per_level_correlated_risk_and_rebalance():
    """A rack-diverse but region-concentrated file is flagged at the
    region level and the repair pass rebalances it cross-region."""
    from cdrs_tpu.faults import RepairScheduler

    topo = _geo()
    man = generate_population(GeneratorConfig(
        n_files=60, seed=50 + SEED, nodes=_NODES12))
    rf = np.full(60, 2, np.int32)
    p = place_replicas(man, rf, topo, seed=SEED, method="hash")
    st = ClusterState(p, np.asarray(man.size_bytes, np.int64))
    # Force file 0 into two racks of ONE region (eu: nodes 0..3).
    row = st.row(0)
    for x in [int(v) for v in row[row >= 0]]:
        st.drop_replica(0, x)
    st.add_replica(0, 0)
    st.add_replica(0, 2)
    rf64 = rf.astype(np.int64)
    d = st.durability(rf64, np.full(60, -1, np.int64), ("Hot",))
    assert d["correlated_risk_levels"]["region"] == 1
    assert bool(st.correlated_mask(rf64)[0])
    sched = RepairScheduler(seed=SEED)
    sched.sync(st, rf64)
    rep = sched.schedule(1, st, rf64, np.full(60, -1, np.int64))
    assert rep.rebalanced >= 1
    d2 = st.durability(rf64, np.full(60, -1, np.int64), ("Hot",))
    assert d2["correlated_risk_levels"]["region"] == 0


# -- the acceptance contrast: region loss ------------------------------------

def _region_loss_controller(topo_spec, mode, storage, man, events,
                            ck=None, maxw=None):
    # The flat contrast has no region LEVEL to scope by — it kills the
    # same node set explicitly (identical physical event, the only
    # difference is whether placement knew the correlation existed).
    if "region" in topo_spec.get("levels", ()):
        specs = ["crash:region:eu@5-9"]
    else:
        specs = [f"crash:{n}@5-9" for n in _EU]
    schedule = FaultSchedule.from_specs(specs)
    scoring = validated_scoring_config()
    import dataclasses

    rfs = dict(scoring.replication_factors)
    rfs["Moderate"] = max(2, rfs["Moderate"])
    scoring = dataclasses.replace(scoring, replication_factors=rfs)
    cfg = ControllerConfig(
        window_seconds=120.0, default_rf=2, drift_threshold=0.02,
        max_bytes_per_window=int(
            np.asarray(man.size_bytes, np.int64).sum() * 0.25),
        kmeans=KMeansConfig(k=10, seed=42), scoring=scoring,
        topology=ClusterTopology.from_hierarchy(topo_spec),
        fault_schedule=FaultSchedule(schedule.events),
        placement_mode=mode,
        storage=(resolve_storage_config("ec_archival", scoring)
                 if storage else None))
    return ReplicationController(man, cfg).run(
        events, checkpoint_path=ck, max_windows=maxw)


@pytest.fixture(scope="module")
def geo_world():
    man = generate_population(GeneratorConfig(
        n_files=400, seed=60 + SEED, nodes=_NODES12))
    events = simulate_access(
        man, SimulatorConfig(duration_seconds=1800.0, seed=61 + SEED))
    return man, events


@pytest.mark.parametrize("storage", [False, True],
                         ids=["replicate", "ec"])
@pytest.mark.parametrize("mode", ["materialized", "functional"])
def test_region_loss_zero_lost_hier_vs_measurable_flat(
        geo_world, mode, storage):
    """The acceptance criterion: killing a whole region loses NOTHING
    under hierarchy-aware placement and measurably loses files on the
    racks-only topology — for replicate and EC strategies, in both
    placement modes, on the same seed.  Flat uses the same node kill
    (the region's node set) so only the topology's awareness differs."""
    man, events = geo_world
    hier = _region_loss_controller(_GEO, mode, storage, man, events)
    flat = _region_loss_controller(_FLAT, mode, storage, man, events)
    lost_hier = max(r["durability"]["lost"] for r in hier.records
                    if r.get("durability"))
    lost_flat = max(r["durability"]["lost"] for r in flat.records
                    if r.get("durability"))
    assert lost_hier == 0, (mode, storage)
    assert lost_flat > 0, (mode, storage)


def test_region_loss_functional_matches_oracle_and_resume(geo_world):
    man, events = geo_world
    fn = _region_loss_controller(_GEO, "functional", True, man, events)
    orc = _region_loss_controller(_GEO, "materialized_hash", True, man,
                                  events)
    strip = lambda rs, drop: [{k: v for k, v in r.items()  # noqa: E731
                               if k not in drop} for r in rs]
    assert strip(fn.records, ("seconds", "placement")) \
        == strip(orc.records, ("seconds", "placement"))
    assert np.array_equal(fn.rf, orc.rf)
    assert all(r["placement"]["mode"] == "functional"
               for r in fn.records)
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "c.npz")
        a = _region_loss_controller(_GEO, "functional", True, man,
                                    events, ck=ck, maxw=7)
        b = _region_loss_controller(_GEO, "functional", True, man,
                                    events, ck=ck)
        assert strip(a.records, ("seconds",)) \
            + strip(b.records, ("seconds",)) \
            == strip(fn.records, ("seconds",))
        assert np.array_equal(b.rf, fn.rf)


# -- region-local storage locality -------------------------------------------

def test_region_local_strategy_spec_roundtrip():
    from cdrs_tpu.storage import Strategy

    s = Strategy.from_spec("ec(2,1):cold:region")
    assert s.locality == "region" and s.k == 2 and s.tier == "cold"
    assert Strategy.from_spec(s.spec()) == s
    cfg = StorageConfig(strategies={"Archival": {
        "k": 2, "m": 1, "tier": "cold", "locality": "region"}})
    sv = cfg.vectors(("Hot", "Archival"), {"Hot": 3, "Archival": 4})
    assert list(sv.region_local) == [False, True]
    assert list(sv.file_region_local(np.asarray([-1, 0, 1]))) \
        == [False, False, True]


# -- elasticity --------------------------------------------------------------

def test_elastic_policy_validation_and_growth():
    pol = ElasticPolicy(pool=({"name": "sb1",
                               "domains": ("rs0", "eu")},))
    topo = _geo()
    pol.validate_against(topo)
    grown = pol.grown_topology(topo, ("sb1",))
    assert grown.nodes == topo.nodes + ("sb1",)
    assert grown.domains[-1] == "rs0"
    assert grown.levels[0][1][-1] == "eu"
    assert grown.edge_bytes == topo.edge_bytes
    with pytest.raises(ValueError, match="declares 0 domains"):
        ElasticPolicy(pool=("sb9",)).validate_against(topo)
    with pytest.raises(ValueError, match="already exists"):
        ElasticPolicy(pool=({"name": "dn1",
                             "domains": ("r0", "eu")},)
                      ).validate_against(topo)
    with pytest.raises(ValueError, match="non-empty pool"):
        ElasticPolicy(pool=())
    with pytest.raises(ValueError, match="hash placement"):
        ScenarioSpec(name="x", serve={"policy": "p2c"},
                     elastic={"pool": ["sb1"]})


def test_elastic_scale_out_drain_and_resume():
    """Scale-out from SLO burn, rebalance == the epoch-diff moved set
    inside the shared budget, drain back to baseline, and kill/resume
    across the grown-topology boundary — decision-identical to the
    materialized_hash oracle throughout."""
    from cdrs_tpu.serve import ServeConfig, SloSpec
    from cdrs_tpu.sim.access import simulate_flash_crowd

    man = generate_population(GeneratorConfig(n_files=300,
                                              seed=70 + SEED))
    cohort = np.asarray([c == "hot" for c in man.category])
    events, _ = simulate_flash_crowd(
        man, SimulatorConfig(duration_seconds=1800.0, seed=71 + SEED),
        cohort=cohort, start=450.0, duration=540.0, boost=25.0)
    pol = ElasticPolicy(pool=("sb1", "sb2", "sb3"), burn_hot=1.0,
                        util_hot=0.9, hot_windows=2, util_cool=0.5,
                        cool_windows=2, drain_spacing=1)

    def run(mode, ck=None, maxw=None):
        cfg = ControllerConfig(
            window_seconds=120.0, default_rf=2, drift_threshold=0.02,
            max_bytes_per_window=int(
                np.asarray(man.size_bytes, np.int64).sum() * 0.25),
            kmeans=KMeansConfig(k=8, seed=42),
            scoring=validated_scoring_config(),
            placement_mode=mode, elastic=pol,
            serve=ServeConfig(policy="p2c", service_ms=6.0,
                              slo=SloSpec(target_ms=60.0)))
        return ReplicationController(man, cfg).run(
            events, checkpoint_path=ck, max_windows=maxw)

    fn = run("functional")
    el = [r.get("elastic") or {} for r in fn.records]
    assert any("added" in e for e in el)
    moved = sum(e.get("moved", 0) for e in el)
    rebal = sum(e.get("rebalanced", 0) for e in el)
    assert moved == rebal and moved > 0
    assert el[-1].get("queue", 0) == 0
    drained = [n for e in el for n in e.get("drained", ())]
    assert drained == ["sb1", "sb2", "sb3"]
    assert fn.records[-1]["durability"]["nodes_up"] == 3
    mb = int(np.asarray(man.size_bytes, np.int64).sum() * 0.25)
    assert all(r.get("repair_bytes", 0) + r["bytes_migrated"]
               + (r.get("elastic") or {}).get("rebalance_bytes", 0)
               <= mb for r in fn.records)
    orc = run("materialized_hash")
    strip = lambda rs, drop=("seconds", "placement"): [  # noqa: E731
        {k: v for k, v in r.items() if k not in drop} for r in rs]
    assert strip(fn.records) == strip(orc.records)
    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "c.npz")
        a = run("functional", ck=ck, maxw=8)
        b = run("functional", ck=ck)
        assert strip(a.records, ("seconds",)) \
            + strip(b.records, ("seconds",)) \
            == strip(fn.records, ("seconds",))


# -- spec round trip ---------------------------------------------------------

def test_scenario_spec_roundtrip_geo_axes():
    """The repro contract for the new axes: topology (hierarchy dict),
    elastic (policy dict) and an inline storage dict survive
    to_dict/from_dict exactly."""
    spec = ScenarioSpec(
        name="geo-rt", n_files=120, seed=SEED, nodes=_NODES12,
        topology=_GEO, placement="functional",
        storage={"strategies": {"Archival": {
            "k": 2, "m": 1, "tier": "cold", "locality": "region"}}},
        faults={"specs": ["partition:region:eu@4-7"]},
        serve={"policy": "p2c"},
        elastic={"pool": [{"name": "sb1",
                           "domains": ["rs0", "eu"]}]})
    d = spec.to_dict()
    import json

    back = ScenarioSpec.from_dict(json.loads(json.dumps(d)))
    assert back == spec
    assert back.topology == _GEO
    with pytest.raises(ValueError, match="mutually exclusive"):
        ScenarioSpec(name="x", nodes=_NODES12, topology=_GEO,
                     racks="r0=dn1,dn2")
    with pytest.raises(ValueError, match="bad topology spec"):
        ScenarioSpec(name="x", nodes=_NODES12,
                     topology={"nodes": list(_NODES12),
                               "levels": ["rack"],
                               "rack": {"r0": ["dn1", "nope"]}})


# -- lowmem overlay ----------------------------------------------------------

def test_overlay_state_has_no_resident_dense_map(geo_world):
    """The ROADMAP item 3 leftover: functional mode's resident placement
    state is the overlay itself — exceptions only — and serve resolution
    goes through the O(unique pids) read_rows path."""
    from cdrs_tpu.placement_fn import OverlayClusterState, \
        primary_on_topology

    man, _ = geo_world
    topo = _geo()
    rf = np.full(len(man), 3, np.int32)
    st = OverlayClusterState.from_base(
        topo, np.asarray(man.size_bytes, np.int64), n_shards=rf,
        primary=primary_on_topology(man.nodes, man.primary_node_id,
                                    topo),
        seed=SEED)
    assert "replica_map" not in st.__dict__          # property, not array
    assert st.exception_fids().size == 0
    st.apply_rf_target(5, 4)
    assert st.exception_fids().size == 0             # base-form retarget
    from cdrs_tpu.faults import FaultEvent

    st.apply_event(FaultEvent(0, "decommission", "dn1"))
    exc = st.exception_fids()
    assert exc.size > 0
    # Every stored exception genuinely deviates from base; every
    # non-exception row IS its base (spot check).
    rows = st.rows(exc)
    base = st._fn_base_rows(exc)
    assert (rows != base).any(axis=1).all()
    uniq = np.arange(0, 50, dtype=np.int64)
    rr, ok, corrupt = st.read_rows(uniq)
    assert rr.shape == (50, 12) and corrupt is None
    assert np.array_equal(rr, st.rows(uniq))
