"""Failure domains, network partitions, stragglers (ISSUE 5):
domain-aware placement properties, topology validation, partition/degrade
fault kinds, correlated-risk durability, stall-aware repair, rack-kill
bench.

``CDRS_CHAOS_SEED`` varies the workload seeds — CI's partition+straggler
smoke step runs this file alongside the test_faults chaos matrix.
"""

import json
import os

import numpy as np
import pytest

from cdrs_tpu.cluster import (
    ClusterTopology,
    PlacementResult,
    evaluate_placement,
    place_replicas,
)
from cdrs_tpu.config import (
    CATEGORIES,
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.faults import (
    ClusterState,
    FaultEvent,
    FaultSchedule,
    RepairScheduler,
)
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))
NODES = ("dn1", "dn2", "dn3", "dn4", "dn5", "dn6")
RACK_SPEC = "r0=dn1,dn2;r1=dn3,dn4;r2=dn5,dn6"


def _racked():
    return ClusterTopology.from_rack_spec(NODES, RACK_SPEC)


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(
        GeneratorConfig(n_files=150, seed=51 + SEED, nodes=NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=600.0, seed=52 + SEED))
    return manifest, events


# -- topology validation (satellite) -----------------------------------------

def test_topology_rejects_duplicates_and_empty():
    with pytest.raises(ValueError, match="duplicate node names"):
        ClusterTopology(("dn1", "dn2", "dn1"))
    with pytest.raises(ValueError, match="at least one node"):
        ClusterTopology(())
    with pytest.raises(ValueError, match="parallel to nodes"):
        ClusterTopology(("dn1", "dn2"), domains=("r0",))


def test_topology_rack_mapping_and_spec():
    t = ClusterTopology.from_racks(("a", "b", "c"), {"a": "r0", "b": "r0"})
    assert t.domains == ("r0", "r0", "c")      # unmapped -> own domain
    assert t.n_domains == 2
    np.testing.assert_array_equal(t.domain_index(), [0, 0, 1])
    with pytest.raises(ValueError, match="outside the topology"):
        ClusterTopology.from_racks(("a", "b"), {"z": "r0"})

    t2 = ClusterTopology.from_rack_spec(NODES, RACK_SPEC)
    assert t2.domain_names == ("r0", "r1", "r2")
    t3 = ClusterTopology.from_rack_spec(("a", "b", "c"), "a,b;c")
    assert t3.domain_names == ("rack0", "rack1")
    with pytest.raises(ValueError, match="two rack groups"):
        ClusterTopology.from_rack_spec(("a", "b"), "r0=a;r1=a,b")
    with pytest.raises(ValueError, match="names no nodes"):
        ClusterTopology.from_rack_spec(("a",), ";")
    # An auto-named bare group colliding with an explicit 'rack0=' must
    # raise, not silently merge two groups into one failure domain.
    with pytest.raises(ValueError, match="duplicate rack name"):
        ClusterTopology.from_rack_spec(("a", "b", "c", "d"),
                                       "a,b;rack0=c,d")


# -- domain-aware placement properties (satellite) ---------------------------

def test_placement_domain_properties():
    """Property-style: over random rf vectors, placement (a) never
    co-locates two replicas on one node, (b) spans >= 2 domains whenever
    rf >= 2 and >= 2 domains exist, (c) is bit-identical across repeated
    calls, (d) puts replica 2 in replica 1's remote domain (HDFS shape)."""
    rng = np.random.default_rng(500 + SEED)
    topo = _racked()
    dom = topo.domain_index()
    for trial in range(4):
        n = int(rng.integers(40, 120))
        manifest = generate_population(GeneratorConfig(
            n_files=n, seed=int(rng.integers(0, 1000)), nodes=NODES))
        rf = rng.integers(1, 7, size=n).astype(np.int32)
        p = place_replicas(manifest, rf, topo, seed=trial)
        for i in range(n):
            reps = p.replica_map[i][p.replica_map[i] >= 0]
            assert len(set(reps.tolist())) == len(reps) == p.rf[i]
            assert p.replica_map[i, 0] == manifest.primary_node_id[i]
        dc = p.domain_counts()
        assert (dc[p.rf >= 2] >= 2).all()
        r3 = p.replica_map[p.rf >= 3]
        if len(r3):
            assert (dom[r3[:, 0]] != dom[r3[:, 1]]).all()
            assert (dom[r3[:, 1]] == dom[r3[:, 2]]).all()
        p2 = place_replicas(manifest, rf, topo, seed=trial)
        np.testing.assert_array_equal(p.replica_map, p2.replica_map)


def test_flat_topology_equals_singleton_domains():
    """No ``domains`` == every node its own domain == the historical flat
    policy: all three spell the same replica map."""
    manifest = generate_population(
        GeneratorConfig(n_files=80, seed=3 + SEED, nodes=NODES))
    rf = np.random.default_rng(SEED).integers(1, 5, size=80).astype(np.int32)
    flat = place_replicas(manifest, rf, ClusterTopology(NODES), seed=2)
    singl = place_replicas(manifest, rf,
                           ClusterTopology(NODES, domains=NODES), seed=2)
    np.testing.assert_array_equal(flat.replica_map, singl.replica_map)


def test_placement_result_storage_optional():
    """Satellite: ``storage_per_node`` defaults to None and consumers
    guard it — a hand-built PlacementResult evaluates fine, and the lazy
    compute matches the eager one."""
    manifest = generate_population(
        GeneratorConfig(n_files=40, seed=4, nodes=NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=60.0, seed=5))
    rf = np.full(40, 2, dtype=np.int32)
    eager = place_replicas(manifest, rf, ClusterTopology(NODES), seed=0)
    bare = PlacementResult(replica_map=eager.replica_map.copy(),
                           rf=eager.rf.copy(), topology=eager.topology)
    assert bare.storage_per_node is None
    m = evaluate_placement(manifest, events, bare, seed=0)
    np.testing.assert_array_equal(m.storage_per_node,
                                  eager.storage_per_node)
    assert m.total_storage == int(eager.storage_per_node.sum())


# -- schedule: partition / degrade kinds -------------------------------------

def test_schedule_partition_and_degrade_specs():
    s = FaultSchedule.from_specs(
        ["partition:dn3+dn4@4-6", "degrade:dn5@2-3:0.25"])
    # Window 4 carries degrade's span-end restore AND the partition start,
    # healing kinds first (KINDS order).
    assert [e.spec() for e in s.for_window(4)] == \
        ["restore:dn5@4", "partition:dn3+dn4@4"]
    assert s.for_window(7) == (FaultEvent(7, "heal", "dn3+dn4"),)
    assert s.for_window(2)[0].factor == 0.25
    assert s.for_window(4)[1].node_list == ("dn3", "dn4")
    assert s.nodes() == ("dn3", "dn4", "dn5")
    # heal sorts before partition within a window (KINDS order).
    s2 = FaultSchedule([FaultEvent(1, "partition", "dn1"),
                        FaultEvent(1, "heal", "dn2")])
    assert [e.kind for e in s2.for_window(1)] == ["heal", "partition"]
    # JSON round-trip carries the degrade factor.
    assert FaultSchedule.from_json(s.to_json()).events == s.events
    with pytest.raises(ValueError, match="factor must be in"):
        FaultEvent(0, "degrade", "dn1", factor=0.0)
    with pytest.raises(ValueError, match="only valid for partition/heal"):
        FaultEvent(0, "crash", "dn1+dn2")
    with pytest.raises(ValueError, match="outside the topology"):
        s.validate_nodes(("dn3", "dn5"))   # dn4 hides inside the group


# -- cluster state: partitions, stragglers, correlated risk ------------------

def _state(rf=2, n=24, topology=None, seed=None):
    topology = topology or _racked()
    manifest = generate_population(GeneratorConfig(
        n_files=n, seed=(10 + SEED) if seed is None else seed,
        nodes=topology.nodes))
    placement = place_replicas(manifest, np.full(n, rf, dtype=np.int32),
                               topology, seed=0)
    return ClusterState(placement, manifest.size_bytes)


def test_state_partition_reachable_vs_live():
    st = _state(rf=2)
    base = st.live_counts().copy()
    st.apply_event(FaultEvent(0, "partition", "dn3+dn4"))
    assert st.n_partitioned == 2 and st.n_available == 4
    # Data intact (live unchanged), service degraded (reachable drops).
    np.testing.assert_array_equal(st.live_counts(), base)
    held = ((st.replica_map == 2) | (st.replica_map == 3)).any(axis=1)
    np.testing.assert_array_equal(
        st.reachable_counts(), base - held.astype(np.int32))
    # Domain-aware rf=2 placement spans 2 racks: nothing is unreadable.
    assert not st.unreadable_mask().any()
    assert st.domains_reachable() == 2
    st.apply_event(FaultEvent(1, "heal", "dn3+dn4"))
    np.testing.assert_array_equal(st.reachable_counts(), base)
    assert st.n_available == 6


def test_state_stranded_files_flat_topology():
    """Flat topology + rf=1: partitioning a node strands its singleton
    replicas — unreachable (not lost), unreadable, healed by the heal."""
    st = _state(rf=1, topology=ClusterTopology(NODES))
    on_dn1 = (st.replica_map == 0).any(axis=1)
    if not on_dn1.any():
        pytest.skip("no singleton landed on dn1 at this seed")
    st.apply_event(FaultEvent(0, "partition", "dn1"))
    target = np.full(24, 1, dtype=np.int64)
    d = st.durability(target, np.zeros(24, dtype=np.int64), CATEGORIES)
    assert d["unreachable"] == int(on_dn1.sum()) and d["lost"] == 0
    np.testing.assert_array_equal(st.unreadable_mask(), on_dn1)
    # placement_view hides stranded replicas from the replay.
    assert (st.placement_view().rf[on_dn1] == 0).all()
    st.apply_event(FaultEvent(1, "heal", "dn1"))
    d2 = st.durability(target, np.zeros(24, dtype=np.int64), CATEGORIES)
    assert d2["unreachable"] == 0 and not st.unreadable_mask().any()


def test_state_degrade_restore_and_checkpoint_roundtrip():
    st = _state(rf=2)
    st.apply_event(FaultEvent(0, "degrade", "dn5", factor=0.25))
    st.apply_event(FaultEvent(0, "partition", "dn1"))
    assert st.node_throughput[4] == 0.25 and st.node_partitioned[0]
    arrays = st.state_arrays()
    st2 = _state(rf=2)
    st2.load_state_arrays(arrays)
    np.testing.assert_array_equal(st2.node_partitioned, st.node_partitioned)
    np.testing.assert_array_equal(st2.node_throughput, st.node_throughput)
    st.apply_event(FaultEvent(1, "restore", "dn5"))
    assert st.node_throughput[4] == 1.0
    # Back-compat: a pre-partition checkpoint (no partition/throughput
    # arrays) loads with defaults instead of raising.
    legacy = {k: v for k, v in arrays.items()
              if k not in ("fault_node_partitioned",
                           "fault_node_throughput")}
    st3 = _state(rf=2)
    st3.load_state_arrays(legacy)
    assert not st3.node_partitioned.any()
    assert (st3.node_throughput == 1.0).all()


def test_state_correlated_risk_matches_bruteforce():
    """Vectorized correlated/unreachable accounting == per-file brute
    force over random partition/crash states on the racked topology."""
    rng = np.random.default_rng(300 + SEED)
    topo = _racked()
    dom = topo.domain_index()
    for trial in range(5):
        st = _state(rf=1 + int(rng.integers(0, 3)), n=40,
                    seed=int(rng.integers(0, 1000)))
        target = rng.integers(1, 5, size=40).astype(np.int64)
        cat = rng.integers(-1, 4, size=40).astype(np.int64)
        for i in np.flatnonzero(rng.random(6) < 0.3):
            st.apply_event(FaultEvent(0, "crash", NODES[i]))
        for i in np.flatnonzero(rng.random(6) < 0.3):
            if st.node_up[i]:
                st.apply_event(FaultEvent(0, "partition", NODES[i]))
        d = st.durability(target, cat, CATEGORIES)
        reach_nodes = st.node_reachable()
        avail = int(reach_nodes.sum())
        doms_reach = len({int(dom[i]) for i in range(6) if reach_nodes[i]})
        lost = unreach = at_risk = under = corr = 0
        for f in range(40):
            row = st.replica_map[f]
            live = sum(1 for x in row if x >= 0 and st.node_up[x])
            reach = [int(x) for x in row
                     if x >= 0 and reach_nodes[int(x)]]
            eff = min(int(target[f]), avail)
            if live == 0:
                lost += 1
            elif not reach:
                unreach += 1
            elif len(reach) == 1 and eff >= 2:
                at_risk += 1
            elif 2 <= len(reach) < eff:
                under += 1
            if (len(reach) >= 2 and eff >= 2 and doms_reach >= 2
                    and len({int(dom[x]) for x in reach}) == 1):
                corr += 1
        assert (d["lost"], d["unreachable"], d["at_risk"],
                d["under_replicated"], d["correlated_risk"]) == \
            (lost, unreach, at_risk, under, corr)
        assert d["domains_reachable"] == doms_reach
        tier_sum = sum(v for c in d["per_category"].values()
                       for v in c.values())
        assert tier_sum == lost + unreach + at_risk + under


# -- repair: stalls, stragglers, spread rebalance ----------------------------

def test_repair_defers_stranded_without_burning_budget():
    """A file wholly behind a partition defers (deferred_partition, zero
    bytes), backs off exponentially, and repairs the window the partition
    heals — the stall backoff must not outlive the stranding."""
    st = _state(rf=1, topology=ClusterTopology(NODES))
    on_dn1 = (st.replica_map == 0).any(axis=1)
    if not on_dn1.any():
        pytest.skip("no singleton landed on dn1 at this seed")
    st.apply_event(FaultEvent(0, "partition", "dn1"))
    target = np.full(24, 2, dtype=np.int64)   # want 2, strand the source
    cat = np.zeros(24, dtype=np.int64)
    rs = RepairScheduler(seed=SEED)
    rs.sync(st, target)
    r0 = rs.schedule(0, st, target, cat, max_bytes=10**12)
    n_stranded = int(on_dn1.sum())
    assert r0.deferred_partition == n_stranded
    stranded_fid = int(np.flatnonzero(on_dn1)[0])
    assert rs.backlog[stranded_fid].stall_until > 1   # backoff armed
    # Stranded copies never touched the budget.
    bytes_reachable = sum(
        int(st.sizes[f]) for f in range(24) if not on_dn1[f])
    assert r0.bytes_used <= 2 * bytes_reachable
    r1 = rs.schedule(1, st, target, cat)
    assert r1.deferred_backoff >= n_stranded and r1.deferred_partition == 0
    # Heal: the stall backoff is ignored the moment a source is reachable.
    st.apply_event(FaultEvent(2, "heal", "dn1"))
    rs.sync(st, target)
    r2 = rs.schedule(2, st, target, cat)
    assert r2.deferred_partition == 0
    assert (st.reachable_counts() >= 2).all()


def test_repair_charges_straggler_inflation():
    """Copies routed through a degraded node charge size/throughput of
    budget while moving only ``size`` data bytes."""
    topo = ClusterTopology(("dn1", "dn2"))
    manifest = generate_population(
        GeneratorConfig(n_files=4, seed=1, nodes=topo.nodes))
    placement = place_replicas(manifest, np.full(4, 1, dtype=np.int32),
                               topo, seed=0)
    st = ClusterState(placement, manifest.size_bytes)
    st.apply_event(FaultEvent(0, "degrade", "dn1", factor=0.25))
    st.apply_event(FaultEvent(0, "degrade", "dn2", factor=0.25))
    target = np.full(4, 2, dtype=np.int64)
    rs = RepairScheduler(seed=SEED)
    rs.sync(st, target)
    rep = rs.schedule(0, st, target, np.zeros(4, dtype=np.int64))
    assert rep.bytes_copied == int(manifest.size_bytes.sum())
    assert rep.bytes_used == sum(
        int(np.ceil(int(s) / 0.25)) for s in manifest.size_bytes)
    st.apply_event(FaultEvent(1, "restore", "dn1"))
    st.apply_event(FaultEvent(1, "restore", "dn2"))
    # Budget admission uses the inflated charge: a degraded-route copy
    # bigger than the budget defers (after the first-copy exemption).
    st2 = ClusterState(place_replicas(
        manifest, np.full(4, 1, dtype=np.int32), topo, seed=0),
        manifest.size_bytes)
    st2.apply_event(FaultEvent(0, "degrade", "dn2", factor=0.5))
    rs2 = RepairScheduler(seed=SEED)
    rs2.sync(st2, target)
    budget = int(manifest.size_bytes.sum())   # fits raw, not inflated 2x
    rep2 = rs2.schedule(0, st2, target, np.zeros(4, dtype=np.int64),
                        max_bytes=budget)
    assert rep2.deferred_budget > 0
    assert rep2.bytes_used <= max(budget,
                                  2 * int(manifest.size_bytes.max()))


def test_repair_rebalances_correlated_files():
    """A file at target rf with both replicas in ONE rack gets one replica
    moved to a fresh rack (copy budgeted, drop free, net count equal)."""
    topo = _racked()
    manifest = generate_population(
        GeneratorConfig(n_files=6, seed=2, nodes=NODES))
    placement = place_replicas(manifest, np.full(6, 2, dtype=np.int32),
                               topo, seed=0)
    st = ClusterState(placement, manifest.size_bytes)
    # Force file 0 into rack r0 only (dn1=0, dn2=1).
    row = st.replica_map[0]
    for x in [int(v) for v in row[row >= 0]]:
        st.drop_replica(0, x)
    st.add_replica(0, 0)
    st.add_replica(0, 1)
    target = np.full(6, 2, dtype=np.int64)
    assert st.correlated_mask(target)[0]
    rs = RepairScheduler(seed=SEED)
    rs.sync(st, target)
    assert 0 in rs.backlog
    rep = rs.schedule(0, st, target, np.zeros(6, dtype=np.int64))
    assert rep.rebalanced >= 1
    assert not st.correlated_mask(target)[0]
    assert st.reachable_counts()[0] == 2      # move, not grow
    assert not rs.backlog.get(0)              # healed out of the backlog


# -- controller + auditor + CLI ----------------------------------------------

def test_controller_partition_stalls_then_heals(workload):
    """Flat topology, rf=1 default: a partitioned node strands singleton
    files (unreachable tier, unavailable reads, stalled repairs — NO
    budget burned on them), and the heal clears everything."""
    manifest, events = workload
    sched = FaultSchedule.from_specs(["partition:dn2@1-2"])
    res = ReplicationController(
        manifest, ControllerConfig(
            window_seconds=120.0, kmeans=KMeansConfig(k=8, seed=42),
            scoring=validated_scoring_config(), drift_threshold=10.0,
            fault_schedule=sched)).run(events)
    by_w = {r["window"]: r for r in res.records}
    if by_w[1]["durability"]["unreachable"] == 0:
        pytest.skip("no singleton replica landed on dn2 at this seed")
    assert by_w[1]["durability"]["lost"] == 0
    assert by_w[1]["repair_deferred_partition"] >= 0
    last = res.records[-1]["durability"]
    assert last["unreachable"] == 0 and last["lost"] == 0
    d = res.summary()["durability"]
    assert d["unreachable_max"] > 0 and d["unreachable_final"] == 0


def test_controller_rack_partition_with_straggler_resumes(tmp_path,
                                                          workload):
    """Racked topology + rack partition + straggler: domain spread keeps
    every file readable, the run heals clean, and kill/resume
    mid-partition is bit-identical (partition + throughput state ride the
    checkpoint)."""
    import dataclasses

    manifest, events = workload
    base = validated_scoring_config()
    scoring = dataclasses.replace(
        base, replication_factors={c: max(2, r) for c, r in
                                   base.replication_factors.items()})

    def mk():
        sched = FaultSchedule.from_specs(
            ["partition:dn3+dn4@1-2", "degrade:dn5@1-3:0.25"])
        return ReplicationController(
            manifest, ControllerConfig(
                window_seconds=120.0, default_rf=2,
                kmeans=KMeansConfig(k=8, seed=42), scoring=scoring,
                fault_schedule=sched, topology=_racked()))

    def strip(rs):
        return [{k: v for k, v in r.items() if k != "seconds"}
                for r in rs]

    ref = mk().run(events)
    assert all(r["durability"]["lost"] == 0 for r in ref.records)
    assert all(r["durability"]["unreachable"] == 0 for r in ref.records)
    last = ref.records[-1]["durability"]
    assert last["correlated_risk"] == 0 and last["under_replicated"] == 0
    ck = str(tmp_path / "part.npz")
    a = mk().run(events, checkpoint_path=ck, max_windows=2)  # mid-partition
    b = mk().run(events, checkpoint_path=ck)
    assert strip(a.records) + strip(b.records) == strip(ref.records)
    np.testing.assert_array_equal(b.rf, ref.rf)


def test_controller_topology_must_match_manifest(workload):
    manifest, _ = workload
    bad = ClusterTopology(("dn1", "dn2"))
    with pytest.raises(ValueError, match="manifest"):
        ReplicationController(
            manifest, ControllerConfig(
                kmeans=KMeansConfig(k=8, seed=42),
                fault_schedule=FaultSchedule.from_specs(["crash:dn1@0"]),
                topology=bad))


def test_audit_flags_domain_and_partition_anomalies():
    from cdrs_tpu.obs import Telemetry
    from cdrs_tpu.obs.audit import DecisionAuditor

    aud = DecisionAuditor(np.ones(4, dtype=np.int64), len(CATEGORIES))
    rec = {"window": 3, "recluster": False, "deferred_budget": 0,
           "repair_deferred_partition": 2, "repair_backlog": 0,
           "durability": {"under_replicated": 0, "at_risk": 0, "lost": 0,
                          "unreachable": 1, "correlated_risk": 3}}
    tel = Telemetry()
    with tel:
        ev = aud.audit_window(tel, window=3, rec=rec, X=None,
                              centroids=None,
                              rf=np.full(4, 2, dtype=np.int64),
                              cat=np.zeros(4, dtype=np.int64))
    assert "domain_diversity_violated" in ev["flags"]
    assert "partition_stalled_repairs" in ev["flags"]
    assert ev["durability"]["correlated_risk"] == 3
    assert tel.counters["audit.flags.domain_diversity_violated"] == 1


def test_cli_chaos_racks_partition_degrade(tmp_path, capsys):
    from cdrs_tpu.cli import main

    m = str(tmp_path / "m.csv")
    log = str(tmp_path / "a.log")
    assert main(["gen", "--n", "60", "--nodes", ",".join(NODES),
                 "--seed", str(60 + SEED), "--out_manifest", m]) == 0
    assert main(["simulate", "--manifest", m, "--out", log,
                 "--duration_seconds", "300", "--seed",
                 str(61 + SEED)]) == 0
    sched_out = str(tmp_path / "sched.json")
    capsys.readouterr()
    assert main(["chaos", "--manifest", m, "--access_log", log,
                 "--window_seconds", "60", "--scoring_config", "validated",
                 "--default_rf", "2", "--racks", RACK_SPEC,
                 "--partition", "dn3+dn4@1-2", "--degrade", "dn5@2-3:0.25",
                 "--schedule_out", sched_out]) == 0
    out = json.loads(capsys.readouterr().out)
    d = out["durability"]
    assert d["lost_final"] == 0 and d["unreachable_final"] == 0
    assert d["correlated_risk_final"] == 0
    rows = json.load(open(sched_out))
    assert {r["kind"] for r in rows} == {"partition", "heal", "degrade",
                                         "restore"}
    assert any(r.get("factor") == 0.25 for r in rows)
    # A malformed rack spec is a clean argparse-style failure, not a crash.
    with pytest.raises(ValueError, match="two rack groups"):
        main(["chaos", "--manifest", m, "--access_log", log,
              "--racks", "r0=dn1;r1=dn1", "--kill", "dn2@1"])


# -- rack-kill bench harness -------------------------------------------------

def test_rack_bench_small_scenario():
    """Rack kill at toy scale: zero lost under domain-aware placement,
    measurable loss under the flat policy on the same seed/schedule, the
    partition scenario heals clean and resumes bit-identically."""
    from cdrs_tpu.benchmarks.chaos_bench import run_rack_bench

    out = run_rack_bench(n_files=120, seed=17 + SEED, duration=720.0,
                         n_windows=8, kill_window=3,
                         partition_windows=(2, 4), resume_check=True)
    c = out["criteria"]
    assert c["domain_aware_zero_lost"]
    assert c["flat_loses_files"]
    assert c["domain_recovered_within_run"]
    assert c["partition_heals_clean"]
    assert c["budget_respected"]
    assert c["partition_resume_bit_identical"]
    assert out["rack_kill"]["flat"]["files_lost_max"] > 0
    assert out["rack_kill"]["domain_aware"]["files_lost_max"] == 0
