"""End-to-end pipeline tests: workload generation, simulation, IO round-trips,
and the planted-category recovery loop the reference never closed
(SURVEY.md §4.2).
"""

import csv
import os

import numpy as np
import pytest

from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    PipelineConfig,
    ScoringConfig,
    SimulatorConfig,
)
from cdrs_tpu.io.events import EventLog, Manifest
from cdrs_tpu.pipeline import run_pipeline
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


def test_generator_distributions():
    cfg = GeneratorConfig(n_files=2000, seed=0, min_size=100, max_size=200)
    m = generate_population(cfg, now=1_000_000.0)
    assert len(m) == 2000
    assert m.size_bytes.min() >= 100 and m.size_bytes.max() <= 200
    # ages within [0, 365d]
    ages = 1_000_000.0 - m.creation_ts
    assert ages.min() >= 0 and ages.max() <= 365 * 86400 + 1
    # category mix ~ (.10, .20, .50, .20) (generator.py:45)
    frac = {c: m.category.count(c) / len(m) for c in set(m.category)}
    assert abs(frac["hot"] - 0.10) < 0.03
    assert abs(frac["moderate"] - 0.50) < 0.05


def test_simulator_rates_and_sorting():
    gen = GeneratorConfig(n_files=400, seed=1)
    m = generate_population(gen, now=1_000_000.0)
    sim = SimulatorConfig(duration_seconds=300.0, seed=2)
    ev = simulate_access(m, sim, sim_start=1_000_000.0)
    assert len(ev) > 0
    assert np.all(np.diff(ev.ts) >= 0)  # globally time-sorted (l.60)
    assert ev.ts.min() >= 1_000_000.0
    assert ev.ts.max() <= 1_000_300.0

    # hot files produce far more traffic than archival (rates 1.0 vs 0.006)
    counts = np.bincount(ev.path_id, minlength=len(m))
    cat = np.array(m.category)
    hot_mean = counts[cat == "hot"].mean()
    arch_mean = counts[cat == "archival"].mean()
    assert hot_mean > 20 * max(arch_mean, 0.1)

    # locality bias: hot ~0.7 of accesses local, shared ~0.3
    local = ev.client_id == m.primary_node_id[ev.path_id]
    for name, lo, hi in (("hot", 0.55, 0.85), ("shared", 0.25, 0.55)):
        mask = cat[ev.path_id] == name
        frac = local[mask].mean()
        assert lo < frac < hi, (name, frac)


def test_manifest_and_log_roundtrip(tmp_path):
    gen = GeneratorConfig(n_files=50, seed=3)
    m = generate_population(gen, now=1_000_000.0)
    ev = simulate_access(m, SimulatorConfig(duration_seconds=60, seed=4),
                         sim_start=1_000_000.0)

    mpath = str(tmp_path / "metadata.csv")
    epath = str(tmp_path / "access.log")
    m.write_csv(mpath)
    ev.write_csv(epath, m)

    m2 = Manifest.read_csv(mpath)
    assert m2.paths == m.paths
    assert m2.category == m.category
    np.testing.assert_array_equal(m2.size_bytes, m.size_bytes)
    np.testing.assert_allclose(m2.creation_ts, m.creation_ts)  # sec-truncated

    ev2 = EventLog.read_csv(epath, m2)
    assert len(ev2) == len(ev)
    np.testing.assert_array_equal(ev2.path_id, ev.path_id)
    np.testing.assert_array_equal(ev2.op, ev.op)
    # timestamps round-trip at ms precision (now_iso_ms truncates to ms)
    np.testing.assert_allclose(ev2.ts, ev.ts, atol=1.5e-3)


def test_pipeline_end_to_end(tmp_path):
    cfg = PipelineConfig(
        generator=GeneratorConfig(n_files=400, seed=0),
        simulator=SimulatorConfig(duration_seconds=600, seed=1),
        kmeans=KMeansConfig(k=4, seed=42),
        scoring=ScoringConfig(compute_global_medians_from_data=True),
    )
    res = run_pipeline(cfg, outdir=str(tmp_path))
    assert res.n_files == 400
    assert res.n_events > 1000
    for f in ("metadata.csv", "access.log", "part-00000-features.csv",
              "final_categories.csv", "assignments.csv"):
        assert os.path.exists(tmp_path / f), f

    # final_categories.csv schema (reference: main.py:139-142)
    with open(tmp_path / "final_categories.csv") as fh:
        rows = list(csv.reader(fh))
    assert rows[0][:2] == ["centroid_id", "category"]
    assert len(rows) == 1 + cfg.kmeans.k
    for row in rows[1:]:
        assert row[0].startswith("CENTROID_")
        assert row[1] in ("Hot", "Shared", "Moderate", "Archival")
        # centroid id embeds the 4-decimal feature values (main.py:131-136)
        assert row[0] == "CENTROID_" + "_".join(
            f"{float(v):.4f}" for v in row[2:])


def test_planted_category_recovery():
    # The implicit validation loop of SURVEY.md §4.2 made executable: with
    # data-derived global medians the pipeline must beat the majority-class
    # baseline (moderate = 50%) and recover hot traffic specifically.
    cfg = PipelineConfig(
        generator=GeneratorConfig(n_files=800, seed=10),
        simulator=SimulatorConfig(duration_seconds=600, seed=11),
        kmeans=KMeansConfig(k=8, seed=42),
        scoring=ScoringConfig(compute_global_medians_from_data=True),
    )
    res = run_pipeline(cfg)
    assert res.planted_accuracy is not None and res.planted_accuracy > 0.5
    assert "Hot" in res.decision.categories


def test_jax_pipeline_device_resident_matches_host_path(tmp_path):
    """The jax pipeline keeps the feature table in HBM end-to-end; results
    must equal feeding the same features through host numpy (x64 = bit parity),
    including on a sharded mesh with a row count that doesn't divide it."""
    pytest.importorskip("jax")
    import jax

    from cdrs_tpu.features.jax_backend import compute_features_jax
    from cdrs_tpu.models.replication import ReplicationPolicyModel
    from cdrs_tpu.sim.access import simulate_access
    from cdrs_tpu.sim.generator import generate_population

    manifest = generate_population(GeneratorConfig(n_files=301, seed=5))  # 301: pads
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=120, seed=6))

    table_dev = compute_features_jax(manifest, events, mesh_shape={"data": 8},
                                     as_device=True)
    assert isinstance(table_dev.norm, jax.Array)
    table_host = compute_features_jax(manifest, events)

    model = ReplicationPolicyModel(
        kmeans_cfg=KMeansConfig(k=4, seed=0),
        scoring_cfg=ScoringConfig(compute_global_medians_from_data=True),
        backend="jax", mesh_shape={"data": 8},
    )
    dec_dev = model.run(table_dev.norm)   # device in, padded on device
    dec_host = model.run(np.asarray(table_host.norm))
    np.testing.assert_allclose(dec_dev.centroids, dec_host.centroids,
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_array_equal(dec_dev.labels, dec_host.labels)
    np.testing.assert_array_equal(dec_dev.category_idx, dec_host.category_idx)

    # run_pipeline on the jax backend goes through the same device path.  The
    # simulator anchors timestamps to wall-clock now (reference behaviour), so
    # compare within ONE run: clustering the features CSV the pipeline wrote
    # (full-precision repr round-trip) through the host path must bit-match
    # the device-resident decision.
    from cdrs_tpu.io.features import load_feature_matrix

    cfg = PipelineConfig(
        backend="jax",
        generator=GeneratorConfig(n_files=301, seed=5),
        simulator=SimulatorConfig(duration_seconds=120, seed=6),
        kmeans=KMeansConfig(k=4, seed=0),
        scoring=ScoringConfig(compute_global_medians_from_data=True),
        mesh_shape={"data": 8},
    )
    res = run_pipeline(cfg, outdir=str(tmp_path))
    X_csv, _ = load_feature_matrix(str(tmp_path / "part-00000-features.csv"))
    dec_csv = model.run(X_csv)
    np.testing.assert_allclose(res.decision.centroids, dec_csv.centroids,
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_array_equal(res.decision.labels, dec_csv.labels)


def test_cluster_csv_input_roundtrip(tmp_path):
    # features CSV -> cluster stage, via the on-disk contract.
    from cdrs_tpu.io.features import load_feature_matrix
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    cfg = PipelineConfig(
        generator=GeneratorConfig(n_files=100, seed=5),
        simulator=SimulatorConfig(duration_seconds=120, seed=6),
        kmeans=KMeansConfig(k=4, seed=42),
    )
    res = run_pipeline(cfg, outdir=str(tmp_path))
    X, paths = load_feature_matrix(str(tmp_path))
    assert X.shape == (100, 5)
    assert len(paths) == 100

    model = ReplicationPolicyModel(kmeans_cfg=KMeansConfig(k=4, seed=42))
    decision = model.run(X)
    np.testing.assert_array_equal(decision.labels, res.decision.labels)
    np.testing.assert_allclose(decision.centroids, res.decision.centroids)
