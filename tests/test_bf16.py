"""Mixed-precision (bfloat16 points) KMeans path.

Contract (ops/kmeans_jax._stat_dtype): the POINTS may be bfloat16 — halving
the HBM stream the Lloyd assignment is bandwidth-bound by — while centroids,
per-cluster sums, counts, and the convergence shift stay float32 (a bf16
count saturates at 256; a bf16 sum of ~n/k terms has ~2 useful digits).

Replaces the reference's float64-everywhere Lloyd loop
(src/kmeans_plusplus.py:24-50) with an accelerator-typed one; CPU runs the
matmul path, the real chip runs the same contract through the fused Pallas
kernel (tests/test_tpu_chip.py).
"""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp

from cdrs_tpu.ops.kmeans_jax import (
    _stat_dtype,
    _weighted_cluster_stats,
    kmeans_jax_full,
    resolve_update,
)


@pytest.fixture(scope="module")
def blobs():
    rng = np.random.default_rng(11)
    centers = rng.normal(size=(4, 8)) * 4.0
    X = np.concatenate([rng.normal(size=(300, 8)) * 0.4 + c for c in centers])
    return X.astype(np.float32)


def test_stat_dtype_mapping():
    assert _stat_dtype(jnp.bfloat16) == jnp.dtype(jnp.float32)
    assert _stat_dtype(jnp.float16) == jnp.dtype(jnp.float32)
    assert _stat_dtype(np.float32) == jnp.dtype(np.float32)
    assert _stat_dtype(np.float64) == jnp.dtype(np.float64)


def test_resolve_update_bf16(monkeypatch):
    # CPU: auto never picks pallas, any dtype.
    assert resolve_update("auto", dtype=jnp.bfloat16, k=128) == "matmul"
    # TPU backend: bf16 rides the fused kernel like f32; f64 does not.
    import cdrs_tpu.ops.kmeans_jax as kj
    monkeypatch.setattr(kj.jax, "default_backend", lambda: "tpu")
    assert kj.resolve_update("auto", dtype=jnp.bfloat16, k=128) == "pallas"
    assert kj.resolve_update("auto", dtype=np.float32, k=128) == "pallas"
    assert kj.resolve_update("auto", dtype=np.float64, k=128) == "matmul"


@pytest.mark.parametrize("update", ["matmul", "scatter"])
def test_bf16_stats_are_exact_f32(update):
    """Counts past bf16's 256-integer ceiling stay exact — the stats
    accumulate in f32 regardless of the points dtype."""
    n, d, k = 4096, 4, 3   # ~1365 rows/cluster: a bf16 count would saturate
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, d)), jnp.bfloat16)
    lab = jnp.asarray(rng.integers(0, k, size=n), jnp.int32)
    w = jnp.ones((n,), jnp.bfloat16)
    sums, counts = jax.jit(
        lambda xc, wc, l: _weighted_cluster_stats(xc, wc, l, k, update)
    )(x, w, lab)
    assert sums.dtype == jnp.float32
    assert counts.dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(counts), np.bincount(np.asarray(lab), minlength=k))
    ref = np.zeros((k, d), np.float32)
    np.add.at(ref, np.asarray(lab), np.asarray(x, np.float32))
    np.testing.assert_allclose(np.asarray(sums), ref, rtol=1e-5, atol=1e-3)


@pytest.mark.parametrize("mesh", [None, {"data": 4}])
def test_bf16_kmeans_near_parity_with_f32(blobs, mesh):
    """bf16 points, f32 centroids: same clustering as the f32 run on
    well-separated data (identical init; labels near-identical, centroids
    within bf16 rounding of the f32 ones)."""
    k = 4
    init = blobs[:k]
    c32, l32, it32, _ = kmeans_jax_full(
        blobs, k, seed=0, init_centroids=init, mesh_shape=mesh,
        dtype=np.float32)
    cbf, lbf, itbf, shift = kmeans_jax_full(
        blobs, k, seed=0, init_centroids=init, mesh_shape=mesh,
        dtype=jnp.bfloat16)
    assert cbf.dtype == jnp.float32        # centroids live in the stat dtype
    # boundary points may flip under bf16 rounding (~0.5% on this workload)
    assert (np.asarray(lbf) == np.asarray(l32)).mean() > 0.99
    np.testing.assert_allclose(np.asarray(cbf), np.asarray(c32),
                               rtol=2e-2, atol=2e-2)
    assert np.isfinite(shift)


def test_bf16_2d_mesh_chunked(blobs):
    """bf16 on a (data, model) mesh with row chunking — the 2D scan carry
    must accumulate in the stat dtype too (code-review regression)."""
    k = 4
    init = blobs[:k]
    c32, l32, *_ = kmeans_jax_full(
        blobs, k, seed=0, init_centroids=init,
        mesh_shape={"data": 2, "model": 2}, chunk_rows=100,
        dtype=np.float32)
    cbf, lbf, *_ = kmeans_jax_full(
        blobs, k, seed=0, init_centroids=init,
        mesh_shape={"data": 2, "model": 2}, chunk_rows=100,
        dtype=jnp.bfloat16)
    assert cbf.dtype == jnp.float32
    assert (np.asarray(lbf) == np.asarray(l32)).mean() > 0.99
    np.testing.assert_allclose(np.asarray(cbf), np.asarray(c32),
                               rtol=2e-2, atol=2e-2)


def test_bf16_dtype_inferred_from_device_array(blobs):
    """A bf16 device array keeps its dtype when ``dtype`` is omitted (the
    old np.issubdtype gate silently upcast bf16 to f32)."""
    X = jnp.asarray(blobs, jnp.bfloat16)
    c, lab, _, _ = kmeans_jax_full(X, 4, seed=0, init_centroids=blobs[:4])
    assert c.dtype == jnp.float32
    assert lab.shape == (blobs.shape[0],)


def test_bf16_pallas_interpret_parity(blobs):
    """The fused feature-major kernel under bf16 points (interpret mode):
    counts exact, sums within bf16 rounding, labels matching an f32
    recomputation from the same bf16-rounded inputs."""
    from cdrs_tpu.ops.pallas_kernels import lloyd_assign_reduce_pallas_t

    n, d = 1024, 8
    k = 7
    x = jnp.asarray(blobs[:n, :d], jnp.bfloat16)
    c = jnp.asarray(np.asarray(blobs[:k, :d]), jnp.float32)
    lab, sums, counts = lloyd_assign_reduce_pallas_t(
        x.T, c, n_valid=n, interpret=True, tile_cols=512)

    xf = np.asarray(x, np.float32)          # bf16-rounded values, f32 math
    cf = np.asarray(c.astype(jnp.bfloat16), np.float32)  # kernel casts c
    dist = (cf * cf).sum(1)[None, :] - 2.0 * (xf @ cf.T)
    lab_ref = dist.argmin(1)
    assert (np.asarray(lab) == lab_ref).mean() > 0.99
    np.testing.assert_array_equal(
        np.asarray(counts), np.bincount(lab_ref, minlength=k))
    ref = np.zeros((k, d), np.float32)
    np.add.at(ref, lab_ref, xf)
    np.testing.assert_allclose(np.asarray(sums), ref, rtol=2e-2, atol=2e-2)


def test_prepadded_garbage_tail_zeroed_on_pallas_path(blobs):
    """A caller-pre-padded device array with a NON-zero tail must not leak
    into pallas stats — kmeans_jax_full zeroes the tail in-program
    (code-review regression: the kernel no longer masks columns)."""
    k = 4
    n_valid = blobs.shape[0]
    pad = 2048 - (n_valid % 2048)
    garbage = np.full((pad, blobs.shape[1]), 1e6, np.float32)
    Xpad = jnp.asarray(np.concatenate([blobs, garbage]))
    init = blobs[:k]
    c_ref, l_ref, *_ = kmeans_jax_full(
        blobs, k, seed=0, init_centroids=init, update="matmul")
    c_pal, l_pal, *_ = kmeans_jax_full(
        Xpad, k, seed=0, init_centroids=init, update="pallas",
        n_valid=n_valid)
    np.testing.assert_allclose(np.asarray(c_pal), np.asarray(c_ref),
                               rtol=1e-4, atol=1e-4)
    assert (np.asarray(l_pal)[:n_valid] == np.asarray(l_ref)).mean() > 0.999


def test_bf16_kmeans_par_init_runs(blobs):
    """kmeans|| with bf16 points: candidate weights accumulate in f32
    (code-review regression — a bf16 sum of ones stalls at 256)."""
    c, lab, it, _ = kmeans_jax_full(
        jnp.asarray(blobs, jnp.bfloat16), 4, seed=3, max_iter=10,
        init_method="kmeans||")
    assert c.dtype == jnp.float32
    counts = np.bincount(np.asarray(lab), minlength=4)
    assert counts.sum() == blobs.shape[0]


def test_float64_requires_x64():
    """Explicit float64 without x64 must error, not silently run f32."""
    import jax
    from cdrs_tpu.benchmarks.harness import run_bench
    from cdrs_tpu.config import KMeansConfig
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    old = jax.config.jax_enable_x64
    try:
        jax.config.update("jax_enable_x64", False)
        with pytest.raises(ValueError, match="JAX_ENABLE_X64"):
            run_bench(config=1, backend="jax", dtype="float64", quality=False)
        m = ReplicationPolicyModel(
            kmeans_cfg=KMeansConfig(k=2, dtype="float64"), backend="jax")
        with pytest.raises(ValueError, match="JAX_ENABLE_X64"):
            m.cluster(np.ones((10, 3), np.float32))
    finally:
        jax.config.update("jax_enable_x64", old)


def test_pipeline_bf16_dtype_flows_through():
    """PipelineConfig -> model -> kmeans: dtype='bfloat16' produces a valid
    decision on the jax backend (sharded mesh), same category count."""
    from cdrs_tpu.config import (GeneratorConfig, KMeansConfig,
                                 PipelineConfig, ScoringConfig,
                                 SimulatorConfig)
    from cdrs_tpu.pipeline import run_pipeline

    cfg = PipelineConfig(
        backend="jax",
        generator=GeneratorConfig(n_files=150, seed=5),
        simulator=SimulatorConfig(duration_seconds=60, seed=6),
        kmeans=KMeansConfig(k=4, seed=0, dtype="bfloat16"),
        scoring=ScoringConfig(compute_global_medians_from_data=True),
        mesh_shape={"data": 2},
    )
    res = run_pipeline(cfg)
    assert res.decision.labels.shape == (150,)
    assert res.decision.centroids.dtype == np.float32
    assert len(res.decision.categories) == 4


def test_bench_config_dtype_override():
    """run_bench(dtype=...) rewrites the config and records the dtype."""
    from cdrs_tpu.benchmarks.harness import run_bench

    out = run_bench(config=1, backend="jax", dtype="bfloat16", quality=False)
    assert out["dtype"] == "bfloat16"
    assert out["value"] > 0
    with pytest.raises(ValueError):
        run_bench(config=1, backend="numpy", dtype="bfloat16", quality=False)
