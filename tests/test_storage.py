"""Tiered storage & erasure coding (ISSUE 7): strategy parsing/validation,
stripe placement, shard-aware durability tiers, EC reconstruction repair
charging, controller wiring with checkpointed strategy state, the
degraded-read serve penalty, and the ec(1, m) == replicate(m+1) property.

``CDRS_CHAOS_SEED`` varies the workload seeds — CI's storage smoke step
runs this file alongside the chaos matrix.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from cdrs_tpu.cluster import ClusterTopology, place_replicas, place_stripes
from cdrs_tpu.config import (
    GeneratorConfig,
    KMeansConfig,
    SimulatorConfig,
    validated_scoring_config,
)
from cdrs_tpu.control import ControllerConfig, ReplicationController
from cdrs_tpu.faults import ClusterState, FaultSchedule, RepairScheduler
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population
from cdrs_tpu.storage import (
    StorageConfig,
    Strategy,
    storage_config_from_dict,
)

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))
NODES = tuple(f"dn{i}" for i in range(1, 13))
RACK_SPEC = ("r0=dn1,dn2,dn3;r1=dn4,dn5,dn6;"
             "r2=dn7,dn8,dn9;r3=dn10,dn11,dn12")


def _min_rf2_scoring():
    base = validated_scoring_config()
    rf = dict(base.replication_factors)
    rf["Moderate"] = max(2, rf["Moderate"])
    return dataclasses.replace(base, replication_factors=rf)


def _strip(records):
    """Records minus wall-clock noise and the storage-only keys (the
    degeneracy comparisons allow the digest fields to exist)."""
    drop = ("seconds", "storage", "storage_conversions_retried")
    return [{k: v for k, v in r.items() if k not in drop}
            for r in records]


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(
        GeneratorConfig(n_files=160, seed=71 + SEED, nodes=NODES))
    events = simulate_access(
        manifest, SimulatorConfig(duration_seconds=600.0, seed=72 + SEED))
    return manifest, events


def _controller(manifest, scoring, storage, schedule=None, serve=None,
                max_bytes=None):
    return ReplicationController(manifest, ControllerConfig(
        window_seconds=60.0, default_rf=2, max_bytes_per_window=max_bytes,
        kmeans=KMeansConfig(k=8, seed=42), scoring=scoring,
        fault_schedule=schedule, serve=serve,
        topology=(ClusterTopology.from_rack_spec(NODES, RACK_SPEC)
                  if schedule is not None else None),
        storage=storage))


# -- strategy parsing & validation (satellite) -------------------------------

def test_strategy_spec_roundtrip():
    for spec, want in (
        ("replicate(3)", (3, 1, 1)),
        ("rf(4):warm", (4, 1, 1)),
        ("ec(6,3):cold", (9, 6, 6)),
        ("ec(1,2)", (3, 1, 1)),
    ):
        s = Strategy.from_spec(spec)
        assert (s.n_shards, s.min_live, s.shard_div) == want
        assert Strategy.from_spec(s.spec()) == s


def test_strategy_validation_names_category():
    with pytest.raises(ValueError, match="'Archival'.*k must be >= 1"):
        StorageConfig(strategies={"Archival": "ec(0,3)"})
    with pytest.raises(ValueError, match="'Hot'.*rf must be >= 1"):
        StorageConfig(strategies={"Hot": "replicate(0)"})
    with pytest.raises(ValueError, match="m must be >= 0"):
        Strategy.from_spec("ec(6,-1)")
    with pytest.raises(ValueError, match="unknown tier"):
        StorageConfig(strategies={"Hot": "replicate(3):lava"})
    with pytest.raises(ValueError, match="unknown storage config keys"):
        storage_config_from_dict({"strategy": {}})
    with pytest.raises(ValueError, match="unknown categories"):
        StorageConfig(strategies={"Warmish": "replicate(2)"}).vectors(
            ("Hot", "Archival"), {"Hot": 3, "Archival": 4})


def test_strategy_dict_must_size_itself():
    """A dict spec without rf/k would silently default to ec(1,0) — ONE
    copy — so it must be rejected, and mixed rf/ec keys are ambiguous."""
    with pytest.raises(ValueError, match="needs 'rf'"):
        Strategy.from_spec({"tier": "cold"})
    with pytest.raises(ValueError, match="needs 'rf'"):
        Strategy.from_spec({"kind": "ec", "m": 3})
    with pytest.raises(ValueError, match="ec keys"):
        Strategy.from_spec({"rf": 3, "k": 2})
    with pytest.raises(ValueError, match="must not carry 'rf'"):
        Strategy.from_spec({"kind": "ec", "rf": 3, "k": 2})
    assert Strategy.from_spec(
        {"k": 6, "m": 3, "tier": "cold"}).spec() == "ec(6,3):cold"
    assert Strategy.from_spec({"rf": 2}).spec() == "replicate(2):hot"


def test_ec_strategy_must_fit_topology():
    """Replicate rf caps at the node count; an EC stripe cannot — the
    controller must reject a stripe wider than the topology up front."""
    small = generate_population(
        GeneratorConfig(n_files=40, seed=3, nodes=("a", "b", "c")))
    scoring = _min_rf2_scoring()
    with pytest.raises(ValueError,
                       match="'Archival'.*9 distinct nodes.*has 3"):
        ReplicationController(small, ControllerConfig(
            window_seconds=60.0, default_rf=2,
            kmeans=KMeansConfig(k=8, seed=42), scoring=scoring,
            storage=StorageConfig.ec_archival(scoring)))


def test_scoring_rf_validated_at_parse_time():
    from cdrs_tpu.config import scoring_config_from_dict

    base = validated_scoring_config()
    bad = {"replication_factors": {**base.replication_factors,
                                   "Moderate": 0}}
    with pytest.raises(ValueError, match="'Moderate'.*>= 1"):
        scoring_config_from_dict(bad)
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    with pytest.raises(ValueError, match="'Shared'.*>= 1"):
        ReplicationPolicyModel(scoring_cfg=dataclasses.replace(
            base, replication_factors={**base.replication_factors,
                                       "Shared": -1}))


def test_vectors_arithmetic():
    scoring = _min_rf2_scoring()
    sv = StorageConfig.ec_archival(scoring).vectors(
        tuple(scoring.categories), scoring.replication_factors)
    i = sv.categories.index("Archival")
    assert (sv.n_shards[i], sv.min_live[i], sv.shard_div[i],
            sv.ec_k[i]) == (9, 6, 6, 1 * 6)
    j = sv.categories.index("Hot")
    assert (sv.n_shards[j], sv.min_live[j], sv.ec_k[j]) == (3, 1, 0)
    sizes = np.asarray([600, 601, 5])
    cat = np.asarray([i, i, -1])
    assert sv.file_shard_bytes(cat, sizes).tolist() == [100, 101, 5]
    assert sv.file_min_live(cat).tolist() == [6, 6, 1]


# -- stripe placement --------------------------------------------------------

def test_place_stripes_degenerates_to_place_replicas(workload):
    manifest, _ = workload
    topo = ClusterTopology.from_rack_spec(NODES, RACK_SPEC)
    rf = np.full(len(manifest), 3, dtype=np.int32)
    a = place_replicas(manifest, rf, topo, seed=0)
    b = place_stripes(manifest, rf, topo, seed=0)
    assert np.array_equal(a.replica_map, b.replica_map)
    assert np.array_equal(a.storage_per_node, b.storage_per_node)


def test_place_stripes_ec_shard_accounting(workload):
    manifest, _ = workload
    topo = ClusterTopology.from_rack_spec(NODES, RACK_SPEC)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    shards = np.full(len(manifest), 9, dtype=np.int32)
    shard_bytes = -(-sizes // 6)
    res = place_stripes(manifest, shards, topo, seed=0,
                        shard_bytes=shard_bytes)
    # 9 distinct nodes per stripe, never more than a rack's 3 nodes in
    # one domain -> a whole-rack kill can cost at most m=3 shards (a
    # stripe may fill exactly 3 racks, so the spread is 3 or 4).
    assert (res.rf == 9).all()
    assert res.domain_counts().min() >= 3
    dom = res.topology.domain_index()
    slot_dom = dom[np.clip(res.replica_map, 0, None)]
    per_rack = np.stack([((slot_dom == d) & (res.replica_map >= 0))
                         .sum(axis=1) for d in range(4)], axis=1)
    assert per_rack.max() <= 3
    assert res.storage_per_node.sum() == (shard_bytes * 9).sum()


# -- shard-aware durability & repair ----------------------------------------

def _ec_state(manifest, k=6, m=3):
    topo = ClusterTopology.from_rack_spec(NODES, RACK_SPEC)
    n = len(manifest)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    shards = np.full(n, k + m, dtype=np.int32)
    shard_bytes = -(-sizes // k)
    placement = place_stripes(manifest, shards, topo, seed=0,
                              shard_bytes=shard_bytes)
    state = ClusterState(placement, sizes)
    state.set_strategy_arrays(np.full(n, k, np.int32), shard_bytes,
                              np.full(n, k, np.int32))
    return state, shards


def test_ec_durability_tiers(workload):
    manifest, _ = workload
    state, shards = _ec_state(manifest)
    d = state.durability(shards, np.zeros(len(manifest), np.int64) - 1,
                         ("Hot", "Shared", "Moderate", "Archival"))
    assert d["lost"] == d["at_risk"] == d["under_replicated"] == 0
    # A whole-rack kill downs at most 3 shards: nothing lost, every
    # stripe that lost shards is under-replicated (reach 6..8 >= k=6).
    for node in ("dn4", "dn5", "dn6"):
        state.apply_event(FaultSchedule.from_specs(
            [f"crash:{node}@0"]).events[0])
    d = state.durability(shards, np.zeros(len(manifest), np.int64) - 1,
                         ("Hot", "Shared", "Moderate", "Archival"))
    assert d["lost"] == 0
    reach = state.reachable_counts()
    assert (reach >= 6).all()
    assert d["at_risk"] == int((reach == 6).sum())
    # Down to k-1 live shards -> the stripe is LOST even though shards
    # remain (the replicate tiers would call 5 live replicas healthy).
    for node in ("dn7", "dn8", "dn9", "dn10"):
        state.apply_event(FaultSchedule.from_specs(
            [f"crash:{node}@0"]).events[0])
    assert state.lost_mask().any()
    assert (state.lost_mask() == (state.live_counts() < 6)).all()


def test_ec_repair_reads_k_shards(workload):
    manifest, _ = workload
    state, shards = _ec_state(manifest)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    # Every file's reconstruction reads k x shard_bytes ~ the file size.
    f = 0
    assert state.repair_read_bytes(f) == int(-(-sizes[f] // 6)) * 6
    state.apply_event(FaultSchedule.from_specs(["crash:dn4@0"]).events[0])
    rep_sched = RepairScheduler(seed=0)
    rep_sched.sync(state, shards)
    cat = np.zeros(len(manifest), np.int64)
    rep = rep_sched.schedule(0, state, shards, cat)
    # Reconstruction amplification: budget charge ~= k x the written
    # shard bytes (no stragglers in this schedule).
    assert rep.bytes_copied > 0
    assert rep.bytes_used >= 5.9 * rep.bytes_copied
    d = state.durability(shards, cat, ("Hot",))
    assert d["under_replicated"] == d["at_risk"] == 0


def test_ec_charge_gated_by_slowest_of_k_fastest_sources(workload):
    """A k-shard rebuild reads from k distinct holders, so its budget
    charge is gated by the slowest of the k FASTEST reachable sources —
    not the single best one (which would erase straggler inflation)."""
    manifest, _ = workload
    state, shards = _ec_state(manifest)
    f = 0
    holders = [int(x) for x in state.replica_map[f]
               if int(x) >= 0]
    state.apply_event(FaultSchedule.from_specs(
        [f"crash:{state.nodes[holders[0]]}@0"]).events[0])
    for h in holders[1:5]:
        state.apply_event(FaultSchedule.from_specs(
            [f"degrade:{state.nodes[h]}@0-99:0.25"]).events[0])
    # 8 reachable sources, 4 degraded to 0.25: the 6 fastest include
    # two degraded holders -> the rebuild is gated at 0.25.
    sched = RepairScheduler(seed=0)
    target = state.pick_repair_target(f)
    assert float(state.node_throughput[target]) == 1.0
    charge = sched._charge(state, f, target)
    assert charge == int(np.ceil(state.repair_read_bytes(f) / 0.25))


def test_lost_stripe_has_no_source(workload):
    manifest, _ = workload
    state, shards = _ec_state(manifest)
    for node in NODES[:7]:  # 7 down -> 5 up < k=6
        state.apply_event(FaultSchedule.from_specs(
            [f"crash:{node}@0"]).events[0])
    assert state.lost_mask().all()
    rep_sched = RepairScheduler(seed=0)
    rep_sched.sync(state, shards)
    rep = rep_sched.schedule(0, state, shards,
                             np.zeros(len(manifest), np.int64))
    # Nothing repairable, nothing charged: below k live shards there is
    # no reconstruction source.  (Stripes already holding a shard on
    # every surviving node are not even backlog — no free target.)
    assert rep.files_touched == 0
    assert rep.bytes_used == 0
    assert rep.deferred_no_source == len(rep_sched.backlog) > 0


def test_ec_partition_stall_not_lost(workload):
    manifest, _ = workload
    state, shards = _ec_state(manifest)
    ev = FaultSchedule.from_specs(
        ["partition:dn1+dn2+dn3+dn4+dn5+dn6+dn7@0"]).events[0]
    state.apply_event(ev)
    # Stripes needing a shard from behind the partition may drop below
    # k REACHABLE while still >= k LIVE: unreachable, not lost.
    d = state.durability(shards, np.zeros(len(manifest), np.int64),
                         ("Hot",))
    assert d["lost"] == 0
    assert d["unreachable"] == int(
        (state.reachable_counts() < 6).sum())


# -- controller end to end ---------------------------------------------------

def test_all_replicate_config_is_bit_identical(workload):
    manifest, events = workload
    scoring = _min_rf2_scoring()
    schedule = FaultSchedule.from_specs(
        [f"crash:dn{i}@3" for i in (4, 5, 6)])
    base = _controller(manifest, scoring, None,
                       FaultSchedule(schedule.events)).run(events)
    rep = _controller(manifest, scoring,
                      StorageConfig.from_scoring(scoring),
                      FaultSchedule(schedule.events)).run(events)
    assert _strip(base.records) == _strip(rep.records)
    assert np.array_equal(base.rf, rep.rf)
    assert np.array_equal(base.category_idx, rep.category_idx)
    # The all-replicate run still carries the storage digest.
    assert rep.records[-1]["storage"]["ec_files"] == 0
    assert rep.records[-1]["storage"]["bytes_stored"] > 0


def test_ec_rack_kill_zero_lost_and_cheaper(workload):
    manifest, events = workload
    scoring = _min_rf2_scoring()
    schedule = FaultSchedule.from_specs(
        [f"crash:dn{i}@3" for i in (4, 5, 6)])
    ec = _controller(manifest, scoring, StorageConfig.ec_archival(scoring),
                     FaultSchedule(schedule.events)).run(events)
    assert max(r["durability"]["lost"] for r in ec.records) == 0
    last = ec.records[-1]["storage"]
    arch_ec = last["per_category_bytes"].get("Archival", 0)
    assert last["ec_files"] > 0
    assert arch_ec > 0
    rf4 = _controller(manifest, scoring, StorageConfig.from_scoring(
        scoring), FaultSchedule(schedule.events)).run(events)
    arch_rf4 = rf4.records[-1]["storage"]["per_category_bytes"].get(
        "Archival", 0)
    # Same category split (same seeds/model): EC(6,3) stores ~1.5x raw
    # vs rf=4's 4x -> >= 2x fewer Archival bytes.
    assert np.array_equal(ec.category_idx, rf4.category_idx)
    assert arch_rf4 >= 2.0 * arch_ec
    # Conversion charging, first plan window (every file leaves the
    # rf=2 default; later windows mix in EC->replicate re-encodes that
    # legitimately cost more): Archival rf=2 -> ec(6,3) writes ~1.5x
    # raw, CHEAPER than rf=2 -> rf=4's 2x top-up — an rf-delta charge
    # of full copies would bill the EC side 7x and flip this.
    ec0 = next(r["bytes_migrated"] for r in ec.records
               if r["moves_applied"])
    rf0 = next(r["bytes_migrated"] for r in rf4.records
               if r["moves_applied"])
    assert ec0 < rf0
    # Cold tier appears exactly when EC Archival does.
    assert "cold" in last["per_tier_bytes"]
    assert ec.summary()["storage"]["ec_files_final"] == last["ec_files"]


def test_ec_checkpoint_resume_bit_identical(workload, tmp_path):
    manifest, events = workload
    scoring = _min_rf2_scoring()
    schedule = FaultSchedule.from_specs(
        [f"crash:dn{i}@3-6" for i in (4, 5, 6)])
    storage = StorageConfig.ec_archival(scoring)

    def mk():
        return _controller(manifest, scoring, storage,
                           FaultSchedule(schedule.events))

    full = mk().run(events)
    ck = str(tmp_path / "ec.npz")
    a = mk().run(events, checkpoint_path=ck, max_windows=4)  # mid-outage
    b = mk().run(events, checkpoint_path=ck)
    assert _strip(a.records) + _strip(b.records) == _strip(full.records)
    assert [r.get("storage") for r in a.records + b.records] == \
        [r.get("storage") for r in full.records]
    assert np.array_equal(b.rf, full.rf)


def test_storage_checkpoint_flag_mismatch(workload, tmp_path):
    manifest, events = workload
    scoring = _min_rf2_scoring()
    ck = str(tmp_path / "c.npz")
    _controller(manifest, scoring, StorageConfig.ec_archival(scoring),
                FaultSchedule.from_specs(["crash:dn4@2"])).run(
        events, checkpoint_path=ck, max_windows=3)
    with pytest.raises(ValueError, match="storage=True"):
        _controller(manifest, scoring, None,
                    FaultSchedule.from_specs(["crash:dn4@2"])).run(
            events, checkpoint_path=ck)


# -- the ec(1, m) == replicate(m+1) property (satellite) ---------------------

@pytest.mark.parametrize("m", [1, 2])
def test_ec_1_m_equals_replicate_m_plus_1(workload, m):
    """ec(1, m) is m+1 full copies with a 1-shard read threshold — the
    strategy arithmetic collapses to replicate(m+1), so placement,
    durability tiers and repair scheduling must be bit-identical."""
    manifest, events = workload
    scoring = _min_rf2_scoring()
    schedule = FaultSchedule.from_specs(
        [f"crash:dn{4 + SEED}@2-5", "degrade:dn8@3-6:0.5"])

    def run(strategy):
        storage = StorageConfig(strategies={
            **{c: Strategy(kind="replicate", rf=r)
               for c, r in scoring.replication_factors.items()
               if c != "Archival"},
            "Archival": strategy})
        return _controller(manifest, scoring, storage,
                           FaultSchedule(schedule.events)).run(events)

    ec = run(Strategy.from_spec(f"ec(1,{m})"))
    rep = run(Strategy.from_spec(f"replicate({m + 1})"))
    assert _strip(ec.records) == _strip(rep.records)
    assert [r["storage"]["bytes_stored"] for r in ec.records] == \
        [r["storage"]["bytes_stored"] for r in rep.records]
    assert np.array_equal(ec.rf, rep.rf)
    assert np.array_equal(ec.category_idx, rep.category_idx)


# -- serve: degraded-read penalty --------------------------------------------

def test_degraded_ec_read_penalty(workload):
    """The storage->serve penalty arithmetic: a cold-tier EC read pays
    the tier stretch, and one whose PRIMARY shard is unreachable pays
    the k-shard gather on top; hot replicate files pay nothing."""
    manifest, events = workload
    from cdrs_tpu.serve import ServeConfig

    scoring = _min_rf2_scoring()
    ctl = _controller(manifest, scoring,
                      StorageConfig.ec_archival(scoring),
                      FaultSchedule.from_specs(["crash:dn4@9999"]),
                      serve=ServeConfig(policy="primary", seed=1,
                                        service_ms=2.0))
    cs = ctl._cluster_state
    arch = list(ctl._storage.categories).index("Archival")
    hot = list(ctl._storage.categories).index("Hot")
    ctl.current_cat[:] = hot
    ctl.current_cat[:4] = arch
    ctl._installed_cat[:] = ctl.current_cat  # encodings below are installed
    for f in range(4):
        cs.set_file_strategy(f, 6, int(cs.sizes[f] // 6) + 1, 6)
    slot_ok = cs.reachable_mask().copy()
    slot_ok[1, 0] = False          # file 1: primary shard down
    pen = ctl._serve_penalty_ms(slot_ok)
    cold_stretch = 2.0 * (1 / 0.25 - 1.0)      # tier throughput 0.25
    gather = 2.0 * (6 - 1) * (1 / 0.25)        # k-1 extra shard fetches
    assert pen[0] == pytest.approx(cold_stretch)
    assert pen[1] == pytest.approx(cold_stretch + gather)
    assert pen[10] == 0.0  # hot replicate: no penalty
    # And the router actually adds it to the latency samples.
    from cdrs_tpu.serve import ReadRouter, ServeConfig as SC

    router = ReadRouter(2, SC(policy="primary", seed=0, service_ms=1.0))
    rm = np.asarray([[0, 1]], dtype=np.int32)
    ok = rm >= 0
    ts = np.asarray([0.0, 10.0])
    pid = np.zeros(2, dtype=np.int64)
    client = np.full(2, -1, dtype=np.int64)
    base = router.route(rm, ok, np.ones(2), ts=ts, pid=pid, client=client,
                        rng=np.random.default_rng(0))
    bumped = router.route(rm, ok, np.ones(2), ts=ts, pid=pid,
                          client=client, rng=np.random.default_rng(0),
                          extra_ms=np.asarray([5.0, 0.0]))
    assert bumped.latency_ms[0] == pytest.approx(base.latency_ms[0] + 5.0)
    assert bumped.latency_ms[1] == pytest.approx(base.latency_ms[1])


def test_unreadable_stripe_routes_unavailable(workload):
    """A stripe below k REACHABLE shards cannot serve any read: the
    serve router must count its reads unavailable, agreeing with the
    durability accounting in the same window record."""
    manifest, events = workload
    from cdrs_tpu.serve import ServeConfig

    scoring = _min_rf2_scoring()
    # Partition 7 of 12 nodes: every stripe keeps >= k live shards but
    # many drop below k reachable -> unreachable, reads must fail.
    schedule = FaultSchedule.from_specs(
        ["partition:" + "+".join(f"dn{i}" for i in range(1, 8)) + "@1-9"])
    res = _controller(manifest, scoring,
                      StorageConfig.ec_archival(scoring),
                      FaultSchedule(schedule.events),
                      serve=ServeConfig(policy="p2c", seed=1)).run(events)
    ec_w = [r for r in res.records
            if r["storage"]["ec_files"] and r["durability"]["unreachable"]
            and r.get("reads_routed") is not None]
    assert ec_w, "scenario never produced unreachable EC windows"
    for r in ec_w:
        assert r["reads_unavailable"] >= r["unavailable_reads"] * 0 \
            and r["reads_routed"] + r["reads_unavailable"] == r["n_reads"]
        # The router's unavailable count equals the durability path's.
        assert r["reads_unavailable"] == r["unavailable_reads"]


def test_equal_shard_count_conversion_counts(workload):
    """replicate(3) -> ec(2,1) keeps the shard count; the conversion
    must still happen (and be reported) — the shard DELTA is 0."""
    manifest, _ = workload
    topo = ClusterTopology.from_rack_spec(NODES, RACK_SPEC)
    sizes = np.asarray(manifest.size_bytes, dtype=np.int64)
    placement = place_replicas(manifest, np.full(len(manifest), 3,
                                                 np.int32), topo, seed=0)
    state = ClusterState(placement, sizes)
    f = 0
    delta = state.apply_strategy_target(
        f, 2, int(-(-sizes[f] // 2)), 2, 3)
    assert delta == 0
    assert state.ec_k[f] == 2 and state.min_live[f] == 2
    assert int((state.replica_map[f] >= 0).sum()) == 3


def test_deferred_conversion_repair_maintains_installed_form(workload):
    """While a replicate->EC conversion is deferred (n_available < k),
    repair must maintain the file's INSTALLED replicate form — never top
    it up toward the unapplied 9-shard target, whose full-size copies
    the re-encode would drop the moment it lands."""
    manifest, events = workload
    scoring = _min_rf2_scoring()
    # Partition 7 of 12 nodes for the whole run: 5 reachable < k=6, so
    # every Archival conversion defers; plans are unaffected (same
    # events), so Archival files exist.
    spec = "partition:" + "+".join(f"dn{i}" for i in range(6, 13)) + "@0-9999"
    ctl = _controller(manifest, scoring, StorageConfig.ec_archival(scoring),
                      FaultSchedule.from_specs([spec]))
    ctl.run(events)
    cs = ctl._cluster_state
    arch = list(ctl._storage.categories).index("Archival")
    deferred = np.flatnonzero((ctl.current_cat == arch) & (cs.ec_k == 0))
    assert len(deferred), "scenario never deferred an Archival conversion"
    assigned = (cs.replica_map[deferred] >= 0).sum(axis=1)
    # Installed form is 2 copies; maintenance may re-copy one per
    # unreachable holder (old slot stays assigned), never reach the
    # old top-up level of eff=min(9, n_available)=5.
    assert assigned.max() <= 3
    assert (ctl.current_rf[deferred] == 9).all()


def test_deferred_conversion_bills_installed_tier(workload):
    """Bytes of a deferred rf->EC conversion are still full-size hot
    replicate copies — the window digest must bill them at the
    INSTALLED hot tier/cost, not the cold tier the unapplied target
    wants, and reads of them carry no EC degraded-read penalty."""
    manifest, events = workload
    from cdrs_tpu.serve import ServeConfig

    scoring = _min_rf2_scoring()
    spec = "partition:" + "+".join(f"dn{i}" for i in range(6, 13)) + "@0-9999"
    ctl = _controller(manifest, scoring, StorageConfig.ec_archival(scoring),
                      FaultSchedule.from_specs([spec]),
                      serve=ServeConfig(policy="primary", seed=1,
                                        service_ms=2.0))
    res = ctl.run(events)
    cs = ctl._cluster_state
    arch = list(ctl._storage.categories).index("Archival")
    deferred = np.flatnonzero((ctl.current_cat == arch) & (cs.ec_k == 0))
    assert len(deferred), "scenario never deferred an Archival conversion"
    assert not (ctl._installed_cat[deferred] == arch).any()
    last = res.records[-1]["storage"]
    # Every conversion deferred behind the partition: nothing ever
    # landed cold, so every stored byte bills hot at byte_cost 1.0.
    assert "cold" not in last["per_tier_bytes"]
    assert last["cost_units"] == pytest.approx(last["bytes_stored"])
    pen = ctl._serve_penalty_ms(np.ones(
        (len(manifest), cs.replica_map.shape[1]), dtype=bool))
    assert (pen[deferred] == 0.0).all()


# -- digests -----------------------------------------------------------------

def test_storage_digest_and_summarize(workload, capsys):
    manifest, events = workload
    scoring = _min_rf2_scoring()
    res = _controller(manifest, scoring,
                      StorageConfig.ec_archival(scoring),
                      FaultSchedule.from_specs(["crash:dn4@2"])).run(events)
    from cdrs_tpu.obs.aggregate import storage_digest
    from cdrs_tpu.obs.metrics_cli import summarize_events
    from cdrs_tpu.obs.report import render_html

    assert storage_digest([{"n_events": 1}]) is None
    d = storage_digest(res.records)
    assert d["bytes_stored_final"] == res.records[-1]["storage"][
        "bytes_stored"]
    windows = [{"kind": "window", **r} for r in res.records]
    summarize_events(windows)
    out = capsys.readouterr().out
    assert "Storage:" in out and "erasure-coded" in out
    html = render_html(windows)
    assert "Storage (tiers &amp; erasure coding)" in html


# -- cdrs storage CLI --------------------------------------------------------

def test_cli_storage_estimate(tmp_path, capsys):
    from cdrs_tpu.cli import main
    from cdrs_tpu.io.events import Manifest

    m = str(tmp_path / "m.csv")
    assert main(["gen", "--n", "40", "--nodes", ",".join(NODES),
                 "--seed", str(40 + SEED), "--out_manifest", m]) == 0
    manifest = Manifest.read_csv(m)
    cats = ["Hot", "Shared", "Moderate", "Archival"]
    a = str(tmp_path / "assign.csv")
    with open(a, "w") as f:
        f.write("path,category\n")
        for i, p in enumerate(manifest.paths[:20]):
            f.write(f"{p},{cats[i % 4]}\n")
        f.write("not/a/manifest/path,Hot\n")
    capsys.readouterr()
    assert main(["storage", "estimate", "--manifest", m,
                 "--assignments_csv", a,
                 "--storage_config", "ec_archival"]) == 0
    captured = capsys.readouterr()
    out = json.loads(captured.out)
    assert out["files"] == 40
    assert out["files_categorized"] == 20
    assert "1/21" in captured.err  # the shared partial-match warning
    arch = [r for r in out["per_category"] if r["category"] == "Archival"][0]
    assert arch["strategy"] == "ec(6,3):cold"
    assert arch["bytes_stored"] < arch["bytes_replicate_baseline"]
