"""PR 8 equivalence: the SoA planners vs the legacy object path.

The vectorized control plane (control/migrate.py, faults/repair.py) must be
DECISION-IDENTICAL to the object-at-a-time implementations it replaced —
admitted/deferred sets, ordering, byte accounting, backoff state, the lot.
The legacy path survives verbatim in ``cdrs_tpu/compat/reference_planners``
as the oracle; this module drives both over random scenarios and asserts
bit-identity, plus checkpoint round-trips mid-backlog.

``CDRS_CHAOS_SEED`` varies every rng below — CI sweeps it over 0/1/2 so
the equivalence is not a single-seed accident.
"""

import os

import numpy as np
import pytest

from cdrs_tpu.cluster import ClusterTopology, place_replicas
from cdrs_tpu.compat.reference_planners import (
    ReferenceMigrationScheduler,
    ReferenceRepairScheduler,
    reference_plan_diff,
)
from cdrs_tpu.config import GeneratorConfig
from cdrs_tpu.control.migrate import (
    MigrationScheduler,
    MoveSet,
    PlanMove,
    plan_diff,
)
from cdrs_tpu.faults import ClusterState, FaultEvent, RepairScheduler
from cdrs_tpu.sim.generator import generate_population

SEED = int(os.environ.get("CDRS_CHAOS_SEED", "0"))

NODES = ("dn1", "dn2", "dn3", "dn4", "dn5", "dn6")
RACKS = {"dn1": "r0", "dn2": "r0", "dn3": "r1", "dn4": "r1",
         "dn5": "r2", "dn6": "r2"}


# -- scenario generators -----------------------------------------------------

def _random_plan(rng, n):
    """A random target plan: rf/category vectors plus tie-heavy priorities
    (quantized so the file-index tiebreak is actually exercised)."""
    rf = rng.integers(1, 5, size=n).astype(np.int64)
    cat = rng.integers(0, 4, size=n).astype(np.int64)
    prio = np.round(rng.normal(size=n), 1)
    return rf, cat, prio


def _random_budget(rng, total_bytes):
    """(max_bytes, max_files) drawn across the regimes the admission loop
    branches on: unbounded, frozen, starving, loose."""
    max_bytes = rng.choice(
        [None, 0, int(total_bytes * 0.01) + 1,
         int(total_bytes * 0.2) + 1, int(total_bytes * 2) + 1])
    max_files = rng.choice([None, 1, 3, 17, 1000])
    return (None if max_bytes is None else int(max_bytes),
            None if max_files is None else int(max_files))


def _moves_tuples(moves):
    """Canonical per-move tuples from either a MoveSet or a PlanMove list."""
    return [(m.file_index, m.rf_old, m.rf_new, m.cat_old, m.cat_new,
             m.bytes_moved, m.priority) for m in moves]


def _backlog_dict(sched):
    """file -> move tuple, from either scheduler's backlog."""
    if isinstance(sched.backlog, MoveSet):
        return {t[0]: t for t in _moves_tuples(sched.backlog)}
    return {f: (m.file_index, m.rf_old, m.rf_new, m.cat_old, m.cat_new,
                m.bytes_moved, m.priority)
            for f, m in sched.backlog.items()}


# -- plan_diff ---------------------------------------------------------------

@pytest.mark.parametrize("case", range(4))
def test_plan_diff_matches_reference(case):
    rng = np.random.default_rng(900 + 10 * SEED + case)
    n = int(rng.integers(5, 300))
    rf_old, cat_old, _ = _random_plan(rng, n)
    rf_new, cat_new, prio = _random_plan(rng, n)
    sizes = rng.integers(1, 1 << 20, size=n).astype(np.int64)
    move_bytes = (rng.integers(0, 1 << 18, size=n).astype(np.int64)
                  if case % 2 else None)
    got = plan_diff(rf_old, rf_new, cat_old, cat_new, sizes,
                    priority=prio, move_bytes=move_bytes)
    want = reference_plan_diff(rf_old, rf_new, cat_old, cat_new, sizes,
                               priority=prio, move_bytes=move_bytes)
    assert _moves_tuples(got) == _moves_tuples(want)


def test_plan_diff_validates_shapes():
    with pytest.raises(ValueError, match="rf_new shape"):
        plan_diff(np.zeros(3, np.int64), np.zeros(2, np.int64),
                  np.zeros(3, np.int64), np.zeros(3, np.int64),
                  np.ones(3, np.int64))
    with pytest.raises(ValueError, match="move_bytes shape"):
        plan_diff(np.zeros(3, np.int64), np.ones(3, np.int64),
                  np.zeros(3, np.int64), np.zeros(3, np.int64),
                  np.ones(3, np.int64), move_bytes=np.ones(2, np.int64))


# -- migration scheduler -----------------------------------------------------

def _run_migration_pair(rng, n, windows, *, resume_at=None):
    """Drive the vectorized and reference schedulers through ``windows``
    random windows (fresh plans land at random windows, reservations vary)
    and assert bit-identity at every step.  ``resume_at`` checkpoints the
    vectorized scheduler through state_arrays at that window and continues
    on the restored copy — the kill/resume-mid-backlog contract."""
    sizes = rng.integers(0, 1 << 20, size=n).astype(np.int64)
    hyst = int(rng.integers(0, 3))
    rf0, cat0, _ = _random_plan(rng, n)
    total = int(sizes.sum()) * 2
    max_bytes, max_files = _random_budget(rng, total)
    vec = MigrationScheduler(n, max_bytes_per_window=max_bytes,
                             max_files_per_window=max_files,
                             hysteresis_windows=hyst)
    ref = ReferenceMigrationScheduler(n, max_bytes_per_window=max_bytes,
                                      max_files_per_window=max_files,
                                      hysteresis_windows=hyst)
    applied_rf, applied_cat = rf0.copy(), cat0.copy()
    for w in range(windows):
        if w == 0 or rng.random() < 0.5:
            rf_new, cat_new, prio = _random_plan(rng, n)
            moves = plan_diff(applied_rf, rf_new, applied_cat, cat_new,
                              sizes, priority=prio)
            vec.submit(moves)
            ref.submit(list(moves))
        bres = int(rng.integers(0, total // 4 + 1)) if rng.random() < 0.4 \
            else 0
        fres = int(rng.integers(0, 5)) if rng.random() < 0.4 else 0
        got = vec.schedule(w, bytes_reserved=bres, files_reserved=fres)
        want = ref.schedule(w, bytes_reserved=bres, files_reserved=fres)
        assert _moves_tuples(got) == _moves_tuples(want), f"window {w}"
        assert vec.last_deferred_hysteresis == ref.last_deferred_hysteresis
        assert vec.last_deferred_budget == ref.last_deferred_budget
        assert _backlog_dict(vec) == _backlog_dict(ref)
        assert vec.backlog_bytes == ref.backlog_bytes
        np.testing.assert_array_equal(vec.last_moved, ref.last_moved)
        for m in got:
            applied_rf[m.file_index] = m.rf_new
            applied_cat[m.file_index] = m.cat_new
        if resume_at is not None and w == resume_at:
            arrays = {k: v for k, v in vec.state_arrays().items()}
            # Round-trip through the npz dtypes a checkpoint would carry.
            restored = MigrationScheduler(
                n, max_bytes_per_window=max_bytes,
                max_files_per_window=max_files, hysteresis_windows=hyst)
            restored.load_state_arrays(arrays)
            assert _backlog_dict(restored) == _backlog_dict(vec)
            np.testing.assert_array_equal(restored.last_moved,
                                          vec.last_moved)
            vec = restored


@pytest.mark.parametrize("case", range(6))
def test_migration_scheduler_matches_reference(case):
    rng = np.random.default_rng(3000 + 100 * SEED + case)
    n = int(rng.integers(20, 400))
    _run_migration_pair(rng, n, windows=8)


def test_migration_resume_mid_backlog_is_bit_identical():
    rng = np.random.default_rng(4100 + SEED)
    _run_migration_pair(rng, 200, windows=10, resume_at=4)


def test_submit_duplicate_files_keep_last_like_reference():
    """The legacy dict backlog kept the LAST submitted move per file —
    a hand-built move list with duplicate file indices must behave
    identically on the SoA path (no double byte-charge, no conflicting
    rf targets)."""
    moves = [PlanMove(file_index=5, rf_old=1, rf_new=2, cat_old=0,
                      cat_new=1, bytes_moved=100, priority=2.0),
             PlanMove(file_index=3, rf_old=1, rf_new=3, cat_old=0,
                      cat_new=2, bytes_moved=50, priority=1.0),
             PlanMove(file_index=5, rf_old=1, rf_new=3, cat_old=0,
                      cat_new=2, bytes_moved=200, priority=0.5)]
    vec = MigrationScheduler(10, max_bytes_per_window=10_000)
    ref = ReferenceMigrationScheduler(10, max_bytes_per_window=10_000)
    vec.submit(moves)
    ref.submit(moves)
    assert len(vec.backlog) == 2
    assert _backlog_dict(vec) == _backlog_dict(ref)
    got = vec.schedule(0)
    want = ref.schedule(0)
    assert _moves_tuples(got) == _moves_tuples(want)
    assert [m.rf_new for m in got] == [3, 3]  # file 5's LAST row won


def test_migration_checkpoint_preserves_admission_order():
    """state_arrays dumps the backlog verbatim (admission order) and load
    re-canonicalizes — including a legacy file-index-ordered dump."""
    sched = MigrationScheduler(50, max_bytes_per_window=10_000)
    rng = np.random.default_rng(7 + SEED)
    rf_old, cat_old, _ = _random_plan(rng, 50)
    rf_new, cat_new, prio = _random_plan(rng, 50)
    sizes = rng.integers(1, 1 << 10, size=50).astype(np.int64)
    sched.submit(plan_diff(rf_old, rf_new, cat_old, cat_new, sizes,
                           priority=prio))
    arrays = sched.state_arrays()
    order = np.lexsort((arrays["sched_file_index"],
                        -arrays["sched_priority"]))
    np.testing.assert_array_equal(order, np.arange(len(order)))
    # A legacy checkpoint stored rows by file index: same backlog after load.
    legacy_order = np.argsort(arrays["sched_file_index"])
    legacy = {k: v[legacy_order] if k != "sched_last_moved" else v
              for k, v in arrays.items()}
    a, b = MigrationScheduler(50), MigrationScheduler(50)
    a.load_state_arrays(arrays)
    b.load_state_arrays(legacy)
    assert _backlog_dict(a) == _backlog_dict(b)
    np.testing.assert_array_equal(a.backlog.file_index, b.backlog.file_index)


# -- repair scheduler --------------------------------------------------------

def _mk_state(n, rng):
    manifest = generate_population(
        GeneratorConfig(n_files=n, seed=int(rng.integers(1 << 16)),
                        nodes=NODES))
    topo = ClusterTopology.from_racks(NODES, RACKS)
    rf = rng.integers(1, 4, size=n).astype(np.int32)
    placement = place_replicas(manifest, rf, topo, seed=0)
    return (ClusterState(placement, manifest.size_bytes),
            rf.astype(np.int64))


def _random_fault(rng, w):
    kind = rng.choice(["crash", "recover", "partition", "heal", "flaky",
                       "unflaky", "degrade", "restore"])
    if kind in ("partition", "heal"):
        k = int(rng.integers(1, 3))
        nodes = "+".join(sorted(rng.choice(NODES, size=k, replace=False)))
        return FaultEvent(w, kind, nodes)
    node = str(rng.choice(NODES))
    if kind == "flaky":
        return FaultEvent(w, kind, node,
                          fail_prob=float(rng.choice([0.3, 0.6, 0.9])))
    if kind == "degrade":
        return FaultEvent(w, kind, node,
                          factor=float(rng.choice([0.25, 0.5])))
    return FaultEvent(w, kind, node)


def _rep_tuple(rep):
    return (rep.applied, rep.bytes_used, rep.bytes_copied,
            rep.files_touched, rep.failed, rep.rebalanced,
            rep.deferred_budget, rep.deferred_backoff,
            rep.deferred_no_source, rep.deferred_no_target,
            rep.deferred_partition)


def _repair_backlog_dict(sched):
    return {int(f): (t.attempts, t.next_window, t.stalled, t.stall_until)
            for f, t in sched.backlog.items()}


def _run_repair_pair(rng, n, windows, *, resume_at=None):
    """Two identical ClusterStates take the same fault stream; the
    vectorized and reference repair schedulers drive one each.  Reports,
    backlogs, and the mutated placements must stay bit-identical."""
    import copy

    st_vec, rf = _mk_state(n, rng)
    # An identical, independent state for the reference planner.
    st_ref = copy.deepcopy(st_vec)

    cat = rng.integers(0, 4, size=n).astype(np.int64)
    total = int(st_vec.sizes.sum())
    max_bytes, max_files = _random_budget(rng, total // 2)
    vec = RepairScheduler(seed=SEED)
    ref = ReferenceRepairScheduler(seed=SEED)
    for w in range(windows):
        n_ev = int(rng.integers(0, 3))
        for _ in range(n_ev):
            ev = _random_fault(rng, w)
            st_vec.apply_event(ev)
            st_ref.apply_event(ev)
        vec.sync(st_vec, rf)
        ref.sync(st_ref, rf)
        assert _repair_backlog_dict(vec) == _repair_backlog_dict(ref), \
            f"window {w} post-sync"
        got = vec.schedule(w, st_vec, rf, cat, max_bytes=max_bytes,
                           max_files=max_files)
        want = ref.schedule(w, st_ref, rf, cat, max_bytes=max_bytes,
                            max_files=max_files)
        assert _rep_tuple(got) == _rep_tuple(want), f"window {w}"
        assert _repair_backlog_dict(vec) == _repair_backlog_dict(ref), \
            f"window {w} post-schedule"
        np.testing.assert_array_equal(st_vec.replica_map,
                                      st_ref.replica_map,
                                      err_msg=f"window {w}")
        np.testing.assert_array_equal(st_vec.node_bytes, st_ref.node_bytes)
        if resume_at is not None and w == resume_at:
            restored = RepairScheduler(seed=SEED)
            restored.load_state_arrays(vec.state_arrays())
            assert _repair_backlog_dict(restored) == _repair_backlog_dict(
                vec)
            vec = restored


@pytest.mark.parametrize("case", range(4))
def test_repair_scheduler_matches_reference(case):
    rng = np.random.default_rng(5000 + 100 * SEED + case)
    n = int(rng.integers(20, 150))
    _run_repair_pair(rng, n, windows=10)


def test_repair_resume_mid_outage_is_bit_identical():
    rng = np.random.default_rng(6200 + SEED)
    _run_repair_pair(rng, 80, windows=12, resume_at=5)


def test_repair_scheduler_matches_reference_with_ec():
    """Same equivalence with EC stripes in the mix (k-shard reconstruction
    charges, min_live existence thresholds)."""
    rng = np.random.default_rng(7300 + SEED)
    n = 60
    st_vec, rf = _mk_state(n, rng)
    import copy

    # EC-ify a random third of the files on BOTH states identically.
    ec_files = rng.choice(n, size=n // 3, replace=False)
    for f in ec_files:
        f = int(f)
        shard = max(int(st_vec.sizes[f]) // 2, 1)
        st_vec.set_file_strategy(f, 2, shard, 2)
        rf[f] = 3  # ec(2,1): 3 shards
    st_ref = copy.deepcopy(st_vec)
    cat = rng.integers(0, 4, size=n).astype(np.int64)
    vec, ref = RepairScheduler(seed=SEED), ReferenceRepairScheduler(
        seed=SEED)
    for w in range(8):
        for _ in range(int(rng.integers(0, 3))):
            ev = _random_fault(rng, w)
            st_vec.apply_event(ev)
            st_ref.apply_event(ev)
        vec.sync(st_vec, rf)
        ref.sync(st_ref, rf)
        got = vec.schedule(w, st_vec, rf, cat, max_bytes=200_000,
                           max_files=6)
        want = ref.schedule(w, st_ref, rf, cat, max_bytes=200_000,
                            max_files=6)
        assert _rep_tuple(got) == _rep_tuple(want), f"window {w}"
        np.testing.assert_array_equal(st_vec.replica_map,
                                      st_ref.replica_map)


def test_repair_pathological_rf_lexsort_fallback_terminates():
    """rf magnitudes large enough to overflow the packed int64 admission
    key route through the explicit lexsort fallback; an UNBUDGETED run
    through it must terminate (the fallback chunk is handed out exactly
    once) and still match the reference planner."""
    import copy

    rng = np.random.default_rng(9500 + SEED)
    st_vec, _ = _mk_state(12, rng)
    st_ref = copy.deepcopy(st_vec)
    # (3*span + span) * n_files >= 2^62 with span = rf.max() + 1.
    rf = np.full(12, np.int64(2) ** 60, dtype=np.int64)
    cat = rng.integers(0, 4, size=12).astype(np.int64)
    ev = FaultEvent(0, "crash", NODES[0])
    st_vec.apply_event(ev)
    st_ref.apply_event(ev)
    vec, ref = RepairScheduler(seed=SEED), ReferenceRepairScheduler(
        seed=SEED)
    vec.sync(st_vec, rf)
    ref.sync(st_ref, rf)
    got = vec.schedule(0, st_vec, rf, cat, max_bytes=None, max_files=None)
    want = ref.schedule(0, st_ref, rf, cat, max_bytes=None,
                        max_files=None)
    assert _rep_tuple(got) == _rep_tuple(want)
    np.testing.assert_array_equal(st_vec.replica_map, st_ref.replica_map)


def test_cached_counts_match_mask_reductions():
    """ClusterState's incrementally maintained counts equal the full mask
    reductions after an arbitrary mutation stream."""
    rng = np.random.default_rng(8400 + SEED)
    st, rf = _mk_state(40, rng)
    for w in range(12):
        for _ in range(int(rng.integers(0, 3))):
            st.apply_event(_random_fault(rng, w))
        f = int(rng.integers(0, 40))
        st.apply_rf_target(f, int(rng.integers(1, 4)))
        np.testing.assert_array_equal(
            st.live_counts(), st.live_mask().sum(axis=1))
        np.testing.assert_array_equal(
            st.reachable_counts(), st.reachable_mask().sum(axis=1))
        slot_dom = st.domain_index[np.clip(st.replica_map, 0, None)]
        reach = st.reachable_mask()
        spread = np.zeros(40, dtype=np.int32)
        for d in range(st.n_domains):
            spread += ((slot_dom == d) & reach).any(axis=1)
        np.testing.assert_array_equal(st.domain_spread(), spread)
