"""Mesh-utility and sharded-drift-kernel tests (PR 11).

``parallel/mesh.py`` carries the correctness of every uneven shard
(``pad_rows``/``prefix_mask``) and the CLI/scenario-JSON mesh-spec
validation (``mesh_from_shape``); ``control/drift.detect_drift_jax`` is
the mesh half of the drift detector, checked against the NumPy oracle.
Runs on the 8-device virtual CPU mesh (conftest.py).
"""

import numpy as np
import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cdrs_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_bytes_estimate,
    make_mesh,
    mesh_from_shape,
    pad_rows,
    prefix_mask,
    shard_map_compat,
    validate_mesh_shape,
)


# -- make_mesh / mesh_from_shape ---------------------------------------------

def test_make_mesh_error_names_axes():
    with pytest.raises(ValueError, match=r"data=16, model=1"):
        make_mesh(n_data=16)
    with pytest.raises(ValueError, match="xla_force_host_platform"):
        make_mesh(n_data=4, n_model=3)


@pytest.mark.parametrize("n", [1, 2, 8])
def test_mesh_from_shape_data_round_trip(n):
    """{"data": N} specs from CLI/scenario JSON build an N-way data mesh."""
    mesh = mesh_from_shape({"data": n})
    assert mesh.shape[DATA_AXIS] == n
    assert mesh.devices.size == n


def test_mesh_from_shape_model_axis():
    mesh = mesh_from_shape({"data": 4, "model": 2})
    assert dict(mesh.shape) == {"data": 4, "model": 2}


def test_mesh_from_shape_none_is_single_device():
    assert mesh_from_shape(None).devices.size == 1


def test_mesh_from_shape_rejects_unknown_axis():
    with pytest.raises(ValueError, match=r"\['dtaa'\]"):
        mesh_from_shape({"dtaa": 8})


def test_mesh_from_shape_rejects_nonpositive():
    with pytest.raises(ValueError, match="'data'"):
        mesh_from_shape({"data": 0})


def test_validate_mesh_shape_coerces_ints():
    assert validate_mesh_shape({"data": "4"}) == {"data": 4}
    assert validate_mesh_shape(None) == {}


def test_cli_mesh_spec_round_trips_through_mesh_from_shape():
    """The `--mesh` CLI parser and mesh_from_shape agree on the spec."""
    from cdrs_tpu.cli import _parse_mesh

    spec = _parse_mesh("data=4,model=2")
    assert spec == {"data": 4, "model": 2}
    assert dict(mesh_from_shape(spec).shape) == spec
    assert dict(mesh_from_shape(_parse_mesh("8")).shape) == {"data": 8}


# -- pad_rows / prefix_mask (the uneven-shard contract) ----------------------

def test_pad_rows_empty():
    x, n_valid = pad_rows(np.zeros((0, 3)), 8)
    assert n_valid == 0
    assert x.shape == (0, 3)


def test_pad_rows_fewer_rows_than_devices():
    x, n_valid = pad_rows(np.ones((3, 2)), 8)
    assert n_valid == 3
    assert x.shape == (8, 2)
    assert (x[3:] == 0).all() and (x[:3] == 1).all()


def test_pad_rows_exactly_divisible_is_identity():
    a = np.arange(16.0).reshape(8, 2)
    x, n_valid = pad_rows(a, 8)
    assert x is a and n_valid == 8


@pytest.mark.parametrize("n", [0, 1, 5, 7, 8, 9, 16, 997])
def test_pad_rows_multiple_and_valid_count(n):
    x, n_valid = pad_rows(np.ones((n, 2)), 8)
    assert n_valid == n
    assert x.shape[0] % 8 == 0
    assert x.shape[0] - n < 8


@pytest.mark.parametrize("n", [1, 5, 8, 9, 997])
def test_prefix_mask_sharded_agrees_with_host(n):
    """The in-program shard-local masks, concatenated in rank order, must
    equal the host-side prefix mask of the padded array."""
    x, n_valid = pad_rows(np.ones((n, 4), np.float32), 8)
    mesh = make_mesh(n_data=8)

    fn = jax.jit(shard_map_compat(
        lambda xs: prefix_mask(xs, n_valid),
        mesh=mesh, in_specs=(P(DATA_AXIS, None),),
        out_specs=P(DATA_AXIS), check_vma=False))
    got = np.asarray(fn(jnp.asarray(x)))
    want = (np.arange(x.shape[0]) < n_valid).astype(np.float32)
    np.testing.assert_array_equal(got, want)
    # Host bypass (sharded=False) is the same mask without the axis.
    host = np.asarray(prefix_mask(jnp.asarray(x), n_valid, sharded=False))
    np.testing.assert_array_equal(host, want)


def test_prefix_mask_zero_valid_rows():
    x = jnp.ones((8, 2))
    assert np.asarray(prefix_mask(x, 0, sharded=False)).sum() == 0


# -- collective-bytes estimate -----------------------------------------------

def test_collective_bytes_estimate():
    assert collective_bytes_estimate(1000, 1) == 0
    assert collective_bytes_estimate(1000, 2) == 2000   # 2·(N-1)·payload
    assert collective_bytes_estimate(1000, 8) == 14000


# -- sharded drift detector ---------------------------------------------------

@pytest.fixture(scope="module")
def drift_inputs():
    rng = np.random.default_rng(3)
    X = rng.random((997, 5)).astype(np.float32)
    c = rng.random((12, 5)).astype(np.float32)
    cat = rng.integers(0, 4, 12)
    frac = np.asarray([0.4, 0.3, 0.2, 0.1])
    return X, c, cat, frac


@pytest.mark.parametrize("ndev", [1, 2, 8])
def test_detect_drift_jax_matches_numpy_oracle(drift_inputs, ndev):
    from cdrs_tpu.control.drift import detect_drift, detect_drift_jax

    X, c, cat, frac = drift_inputs
    a = detect_drift(X, c, cat, frac, 4)
    b = detect_drift_jax(X, c, cat, frac, 4, mesh_shape={"data": ndev})
    assert b.score == pytest.approx(a.score, abs=1e-5)
    assert b.centroid_shift == pytest.approx(a.centroid_shift, abs=1e-5)
    assert b.population_delta == pytest.approx(a.population_delta,
                                               abs=1e-5)
    # Fractions are ratios of integer-exact psum'd counts.
    np.testing.assert_allclose(b.fractions, a.fractions, atol=1e-6)


def test_detect_drift_jax_fractions_identical_across_shapes(drift_inputs):
    from cdrs_tpu.control.drift import detect_drift_jax

    X, c, cat, frac = drift_inputs
    b1 = detect_drift_jax(X, c, cat, frac, 4, mesh_shape={"data": 1})
    b8 = detect_drift_jax(X, c, cat, frac, 4, mesh_shape={"data": 8})
    np.testing.assert_array_equal(b1.fractions, b8.fractions)
    assert b8.centroid_shift == pytest.approx(b1.centroid_shift, abs=1e-6)


def test_detect_drift_jax_fewer_rows_than_devices(drift_inputs):
    """n < n_devices: every shard but the first is all padding."""
    from cdrs_tpu.control.drift import detect_drift, detect_drift_jax

    X, c, cat, frac = drift_inputs
    a = detect_drift(X[:5], c, cat, frac, 4)
    b = detect_drift_jax(X[:5], c, cat, frac, 4, mesh_shape={"data": 8})
    assert b.score == pytest.approx(a.score, abs=1e-5)
    np.testing.assert_allclose(b.fractions, a.fractions, atol=1e-6)


def test_detect_drift_jax_rejects_bad_mesh(drift_inputs):
    from cdrs_tpu.control.drift import detect_drift_jax

    X, c, cat, frac = drift_inputs
    with pytest.raises(ValueError, match="unknown mesh axis"):
        detect_drift_jax(X, c, cat, frac, 4, mesh_shape={"rows": 8})
