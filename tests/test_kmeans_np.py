"""Unit tests for the NumPy KMeans++ backend (ops/kmeans_np.py).

Covers the reference contract (src/kmeans_plusplus.py) plus the documented
fixes: integer max_iter (no crash for n > 10,000) and seeded empty-cluster
reseeding (SURVEY.md §6.1.1-2).
"""

import numpy as np
import pytest

from cdrs_tpu.ops.kmeans_np import (
    kmeans,
    kmeans_plusplus_init,
    lloyd_step,
    pairwise_sq_dists,
)


def test_pairwise_matches_broadcast():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(57, 5))
    C = rng.normal(size=(7, 5))
    expected = np.linalg.norm(X[:, None, :] - C[None, :, :], axis=2) ** 2
    got = pairwise_sq_dists(X, C, tile=16)
    np.testing.assert_allclose(got, expected, atol=1e-9)


def test_init_shapes_and_membership():
    rng = np.random.default_rng(1)
    X = rng.normal(size=(200, 3))
    C = kmeans_plusplus_init(X, 5, random_state=42)
    assert C.shape == (5, 3)
    # every centroid must be an actual data point
    for c in C:
        assert np.any(np.all(np.isclose(X, c), axis=1))


def test_init_reproducible():
    X = np.random.default_rng(2).normal(size=(100, 4))
    a = kmeans_plusplus_init(X, 6, random_state=7)
    b = kmeans_plusplus_init(X, 6, random_state=7)
    np.testing.assert_array_equal(a, b)


def test_init_spreads_on_separated_clusters():
    # With 3 well-separated blobs and k=3, D^2 sampling must pick one point
    # from each blob (probability of failure is astronomically small).
    rng = np.random.default_rng(3)
    blobs = [rng.normal(loc=c, scale=0.01, size=(50, 2))
             for c in ((0, 0), (50, 0), (0, 50))]
    X = np.concatenate(blobs)
    C = kmeans_plusplus_init(X, 3, random_state=0)
    owners = {int(np.argmin([np.linalg.norm(c - b.mean(0)) for b in blobs])) for c in C}
    assert owners == {0, 1, 2}


def test_kmeans_recovers_blobs():
    rng = np.random.default_rng(4)
    centers = np.array([[0.0, 0.0], [10.0, 0.0], [0.0, 10.0], [10.0, 10.0]])
    X = np.concatenate([rng.normal(loc=c, scale=0.3, size=(100, 2)) for c in centers])
    centroids, labels = kmeans(X, 4, number_of_files=len(X), random_state=42)
    assert centroids.shape == (4, 2)
    assert labels.shape == (400,)
    # each found centroid is close to a true center, all 4 matched
    d = np.linalg.norm(centroids[:, None, :] - centers[None, :, :], axis=2)
    assert set(np.argmin(d, axis=1).tolist()) == {0, 1, 2, 3}
    assert d.min(axis=1).max() < 0.5
    # labels are consistent: points in the same blob share a label
    for b in range(4):
        blob_labels = labels[b * 100:(b + 1) * 100]
        assert len(set(blob_labels.tolist())) == 1


def test_kmeans_reproducible_with_seed():
    X = np.random.default_rng(5).normal(size=(300, 6))
    c1, l1 = kmeans(X, 5, random_state=9)
    c2, l2 = kmeans(X, 5, random_state=9)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(l1, l2)


def test_no_crash_above_10k_files():
    # Reference crashes: max(100, n/100) is a float for n > 10,000 and
    # range(float) raises TypeError (kmeans_plusplus.py:29-31, SURVEY.md §6.1.1).
    X = np.random.default_rng(6).normal(size=(10_050, 2))
    centroids, labels = kmeans(X, 3, number_of_files=len(X), random_state=0, max_iter=5)
    assert centroids.shape == (3, 2)


def test_k_greater_than_n_raises():
    X = np.zeros((3, 2))
    with pytest.raises(ValueError):
        kmeans_plusplus_init(X, 5, random_state=0)


def test_empty_cluster_reseeded_deterministically():
    # Force an empty cluster: a far-away initial centroid owns no points.
    X = np.random.default_rng(7).normal(size=(50, 2))
    init = np.array([[0.0, 0.0], [1000.0, 1000.0]])
    rng_a = np.random.default_rng(11)
    rng_b = np.random.default_rng(11)
    ca, la, _ = lloyd_step(X, init, rng_a)
    cb, lb, _ = lloyd_step(X, init, rng_b)
    np.testing.assert_array_equal(ca, cb)
    assert np.all(la == 0)  # nobody assigned to the far centroid
    # the empty cluster was reseeded to a real data point
    assert np.any(np.all(np.isclose(X, ca[1]), axis=1))


def test_labels_match_pre_update_centroids():
    # Reference loop order: labels computed against the centroids *before*
    # the final update (kmeans_plusplus.py:33-48).
    X = np.array([[0.0], [1.0], [10.0], [11.0]])
    init = np.array([[0.0], [10.0]])
    centroids, labels = kmeans(X, 2, init_centroids=init, random_state=0, max_iter=1)
    np.testing.assert_array_equal(labels, [0, 0, 1, 1])
    np.testing.assert_allclose(centroids, [[0.5], [10.5]])


def test_convergence_tolerance():
    # tol larger than any possible shift -> stops after first iteration.
    X = np.random.default_rng(8).normal(size=(100, 2))
    init = X[:3].copy()
    c_one, _ = kmeans(X, 3, init_centroids=init, random_state=0, max_iter=1)
    c_tol, _ = kmeans(X, 3, init_centroids=init, random_state=0, tol=1e12)
    np.testing.assert_allclose(c_one, c_tol)
