"""NumPy streaming-fold tests — deliberately NOT gated on jax.

features/streaming_np.py exists so ``cdrs stream --backend numpy`` runs on a
jax-free install (the optional 'tpu' extra); these tests run on such an
install and would catch an accidental jax import sneaking into that path.
"""

import numpy as np
import pytest

from cdrs_tpu.config import GeneratorConfig, KMeansConfig, SimulatorConfig
from cdrs_tpu.features.numpy_backend import compute_features
from cdrs_tpu.features.streaming_np import (
    stream_finalize_np, stream_init_np, stream_update_np)
from cdrs_tpu.io.events import EventLog
from cdrs_tpu.sim.access import simulate_access
from cdrs_tpu.sim.generator import generate_population


@pytest.fixture(scope="module")
def workload():
    manifest = generate_population(GeneratorConfig(n_files=100, seed=3))
    events = simulate_access(manifest, SimulatorConfig(duration_seconds=90.0, seed=3))
    return manifest, events


def _slice_events(events, lo, hi):
    return EventLog(
        ts=events.ts[lo:hi], path_id=events.path_id[lo:hi],
        op=events.op[lo:hi], client_id=events.client_id[lo:hi],
        clients=events.clients,
    )


@pytest.mark.parametrize("n_batches", [1, 3, 7])
def test_numpy_stream_fold_matches_batch_features(workload, n_batches):
    """The jax-free fold is bit-equal to the batch golden model over any
    batch split of a time-ordered log."""
    manifest, events = workload
    want = compute_features(manifest, events)

    state = stream_init_np(len(manifest))
    cuts = np.linspace(0, len(events), n_batches + 1).astype(int)
    cuts[1:-1] += 13  # shift interior cuts off any natural boundary
    cuts = np.clip(cuts, 0, len(events))
    for lo, hi in zip(cuts[:-1], cuts[1:]):
        state = stream_update_np(state, _slice_events(events, int(lo), int(hi)),
                                 manifest)
    got = stream_finalize_np(state, manifest)

    np.testing.assert_allclose(got.raw, want.raw, rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(got.norm, want.norm, rtol=1e-12, atol=1e-12)


def test_numpy_stream_concurrency_boundary_merge(workload):
    """A (path, second) run split across batches must count as one run."""
    manifest, _ = workload
    n = len(manifest)
    base = 1_700_000_000.0
    ts = np.array([base + 0.1, base + 0.2, base + 0.3, base + 0.4,
                   base + 0.5, base + 0.6])
    mk = lambda lo, hi: EventLog(
        ts=ts[lo:hi],
        path_id=np.zeros(hi - lo, dtype=np.int32),
        op=np.zeros(hi - lo, dtype=np.int8),
        client_id=np.zeros(hi - lo, dtype=np.int32),
        clients=["dn1"],
    )
    state = stream_init_np(n)
    state = stream_update_np(state, mk(0, 2), manifest)
    state = stream_update_np(state, mk(2, 6), manifest)
    got = stream_finalize_np(state, manifest)
    assert got.raw[0, 4] == 6.0


def test_minibatch_rejected_on_numpy_backend():
    from cdrs_tpu.models.replication import ReplicationPolicyModel

    X = np.random.default_rng(0).random((64, 5))
    with pytest.raises(ValueError, match="jax backend"):
        ReplicationPolicyModel(
            kmeans_cfg=KMeansConfig(k=4, batch_size=16), backend="numpy"
        ).run(X)


def test_cli_stream_numpy_backend(tmp_path, workload):
    """`cdrs stream --backend numpy` end-to-end, and early --kmeans_batch
    validation (before any streaming work happens)."""
    from cdrs_tpu.cli import main

    manifest, events = workload
    mpath, apath = tmp_path / "m.csv", tmp_path / "a.log"
    manifest.write_csv(str(mpath))
    events.write_csv(str(apath), manifest)

    out = tmp_path / "np.csv"
    rc = main(["stream", "--manifest", str(mpath), "--access_log", str(apath),
               "--batch_size", "512", "--k", "4", "--seed", "0",
               "--backend", "numpy", "--output_csv", str(out),
               "--medians_from_data"])
    assert rc == 0
    assert out.exists()

    # numpy + --kmeans_batch is rejected up front with a clear message
    rc = main(["stream", "--manifest", str(mpath), "--access_log", str(apath),
               "--kmeans_batch", "64", "--backend", "numpy",
               "--output_csv", str(tmp_path / "x.csv")])
    assert rc == 1
    assert not (tmp_path / "x.csv").exists()
