"""Real-chip (non-interpret) Pallas kernel tests.

The normal suite forces an 8-device CPU mesh (conftest.py), where the Pallas
kernels run in interpret mode only.  This module exercises the Mosaic-compiled
kernels on actual TPU hardware:

    CDRS_TPU_TESTS=1 python -m pytest tests/test_tpu_chip.py -q

Without that flag (or without a TPU) every test here skips — the rest of the
suite stays chip-free.  VERDICT r2 weak #3: the flagship kernel had only ever
compiled in interpret mode.
"""

import os

import numpy as np
import pytest

pytest.importorskip("jax")

import jax
import jax.numpy as jnp

if os.environ.get("CDRS_TPU_TESTS") != "1":
    pytest.skip("set CDRS_TPU_TESTS=1 to run real-chip tests",
                allow_module_level=True)
if jax.default_backend() != "tpu":
    pytest.skip("no TPU backend available", allow_module_level=True)

from cdrs_tpu.ops.kmeans_jax import kmeans_jax_full, resolve_update
from cdrs_tpu.ops.kmeans_np import assign_labels
from cdrs_tpu.ops.pallas_kernels import (lloyd_assign_reduce_pallas,
                                         lloyd_assign_reduce_pallas_t)


def _stats_from_labels(x, lab, k, n_valid):
    """(sums, counts) implied by a given label vector — the kernel's stats
    must match the stats of ITS OWN labels exactly (internal consistency);
    the labels themselves may flip on near-ties vs a float64 argmin (MXU
    f32 accumulation order differs from numpy's)."""
    w = np.zeros(x.shape[0])
    w[:n_valid] = 1.0
    sums = np.stack(
        [np.bincount(lab, weights=x[:, j] * w, minlength=k)
         for j in range(x.shape[1])], axis=1)
    counts = np.bincount(lab, weights=w, minlength=k)
    return sums, counts


@pytest.mark.parametrize("kernel,transposed", [
    (lloyd_assign_reduce_pallas, False),
    (lloyd_assign_reduce_pallas_t, True),
])
@pytest.mark.parametrize("n,d,k,n_valid", [
    (4096, 5, 7, 4096),
    (8192, 32, 128, 8000),
])
def test_kernel_on_chip(kernel, transposed, n, d, k, n_valid):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    x[n_valid:] = 0.0   # feature-major kernel contract: padded tail is zero
    c = x[:k].copy()
    xin = jnp.asarray(x).T if transposed else jnp.asarray(x)
    kw = {"tile_cols": 1024} if transposed else {"tile_rows": 1024}
    lab, sums, counts = kernel(xin, jnp.asarray(c), n_valid=n_valid,
                               interpret=False, **kw)
    lab = np.asarray(lab)
    lab_f64 = assign_labels(x.astype(np.float64), c.astype(np.float64))
    # near-ties may flip under f32 MXU accumulation; require near-agreement
    assert (lab == lab_f64).mean() > 0.99
    sums_np, counts_np = _stats_from_labels(x, lab, k, n_valid)
    # f32 MXU accumulation order differs from numpy's sequential bincount;
    # counts are exact (sums of 0/1), sums carry rounding noise.
    np.testing.assert_allclose(np.asarray(sums), sums_np, atol=0.2, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), counts_np, atol=0)


def test_kmeans_pallas_matches_matmul_on_chip():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(8192, 16)).astype(np.float32)
    init = X[:8].copy()
    c1, l1, *_ = kmeans_jax_full(X, 8, seed=0, max_iter=15, tol=0.0,
                                 init_centroids=init, update="matmul")
    c2, l2, *_ = kmeans_jax_full(X, 8, seed=0, max_iter=15, tol=0.0,
                                 init_centroids=init, update="pallas")
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c2), atol=1e-3)
    assert (np.asarray(l1) == np.asarray(l2)).mean() > 0.999


def test_auto_resolves_to_pallas_on_tpu():
    assert resolve_update("auto") == "pallas"
    assert resolve_update("auto", nmodel=2) == "matmul"
    assert resolve_update("matmul") == "matmul"


def test_bf16_pallas_on_chip():
    """Mixed precision on real hardware: bf16 points through the Mosaic
    kernel, f32 centroids/stats (tests/test_bf16.py runs the same contract
    in interpret mode)."""
    rng = np.random.default_rng(5)
    # Separated blobs: on structureless data every point is a near-tie and
    # bf16 rounding flips assignments wholesale (~7% on isotropic noise).
    centers = rng.normal(size=(16, 32)) * 4.0
    lab_true = rng.integers(0, 16, size=8192)
    X = (centers[lab_true] + rng.normal(size=(8192, 32)) * 0.4
         ).astype(np.float32)
    init = centers.astype(np.float32)
    assert resolve_update("auto", dtype=jnp.bfloat16, k=16) == "pallas"
    c32, l32, *_ = kmeans_jax_full(X, 16, seed=0, max_iter=10, tol=0.0,
                                   init_centroids=init, dtype=np.float32,
                                   update="pallas")
    cbf, lbf, *_ = kmeans_jax_full(X, 16, seed=0, max_iter=10, tol=0.0,
                                   init_centroids=init, dtype=jnp.bfloat16,
                                   update="pallas")
    assert cbf.dtype == jnp.float32
    assert (np.asarray(lbf) == np.asarray(l32)).mean() > 0.98
    np.testing.assert_allclose(np.asarray(cbf), np.asarray(c32),
                               rtol=5e-2, atol=5e-2)


def test_label_segment_matmul_on_chip():
    """The bisection-median count kernel (Mosaic-compiled): exact integer
    sums for 0/1 bf16 inputs, -1 labels excluded."""
    from cdrs_tpu.ops.pallas_kernels import label_segment_matmul

    rng = np.random.default_rng(6)
    n, d, k = 1 << 16, 128, 1024
    lab = rng.integers(-1, k, size=n).astype(np.int32)
    y = (rng.random((n, d)) < 0.5).astype(np.float32)
    got = np.asarray(label_segment_matmul(
        jnp.asarray(lab), jnp.asarray(y, jnp.bfloat16), k, interpret=False))
    want = np.zeros((k, d), np.float32)
    np.add.at(want, lab[lab >= 0], y[lab >= 0])
    np.testing.assert_array_equal(got, want)


def test_no_labels_epilogue_on_chip():
    """with_labels=False (the Lloyd-loop interior path — labels are only
    fetched on the last iteration) must produce identical stats to the
    labeled call on the Mosaic-compiled kernel."""
    rng = np.random.default_rng(7)
    n, d, k, n_valid = 8192, 32, 128, 8000
    x = rng.normal(size=(n, d)).astype(np.float32)
    x[n_valid:] = 0.0
    c = x[:k].copy()
    lab, sums_l, counts_l = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x).T, jnp.asarray(c), n_valid=n_valid, interpret=False,
        tile_cols=1024)
    none_lab, sums_n, counts_n = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x).T, jnp.asarray(c), n_valid=n_valid, interpret=False,
        tile_cols=1024, with_labels=False)
    assert none_lab is None and lab is not None
    np.testing.assert_array_equal(np.asarray(sums_l), np.asarray(sums_n))
    np.testing.assert_array_equal(np.asarray(counts_l), np.asarray(counts_n))


def test_enforce_pad_on_chip():
    """The enforce_pad guard (Mosaic-compiled): dirty pad columns produce
    the zero-pad results."""
    rng = np.random.default_rng(8)
    n, d, k, n_valid = 4096, 8, 16, 3000
    x = rng.normal(size=(n, d)).astype(np.float32)
    c = x[:k].copy()
    x_clean = x.copy()
    x_clean[n_valid:] = 0.0
    x_dirty = x.copy()
    x_dirty[n_valid:] = 77.0
    _, sums_ref, counts_ref = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x_clean).T, jnp.asarray(c), n_valid=n_valid,
        interpret=False, tile_cols=1024)
    _, sums_g, counts_g = lloyd_assign_reduce_pallas_t(
        jnp.asarray(x_dirty).T, jnp.asarray(c), n_valid=n_valid,
        interpret=False, tile_cols=1024, enforce_pad=True)
    np.testing.assert_array_equal(np.asarray(sums_g), np.asarray(sums_ref))
    np.testing.assert_array_equal(np.asarray(counts_g),
                                  np.asarray(counts_ref))


def test_sharded_bisect_on_one_device_mesh():
    """Sharded bisection medians on a real 1-device mesh (the shard_map +
    psum path, Mosaic-compiled): exact parity with the single-device bisect
    and category parity through classify_jax's sharded auto routing."""
    from cdrs_tpu.config import ScoringConfig
    from cdrs_tpu.ops.scoring_jax import (_bisect_medians,
                                          _bisect_medians_sharded,
                                          classify_jax)

    rng = np.random.default_rng(9)
    n, d, k = 1 << 15, 5, 8
    x = rng.random((n, d)).astype(np.float32)
    lab = rng.integers(0, k, size=n).astype(np.int32)

    med_1, g_1 = _bisect_medians(jnp.asarray(x), jnp.asarray(lab), k,
                                 2048, True)
    med_s, g_s = _bisect_medians_sharded(x, lab, k, 2048, True, ndata=1)
    np.testing.assert_array_equal(np.asarray(med_1), np.asarray(med_s))
    np.testing.assert_array_equal(np.asarray(g_1), np.asarray(g_s))

    # The r5 routing flip: on a real TPU backend, sharded auto (and
    # past-threshold single-device auto) resolves to bisect.
    from cdrs_tpu.ops.scoring_jax import (HIST_MEDIAN_THRESHOLD,
                                          resolve_median_method)

    assert resolve_median_method("auto", ndata=4, n_rows=1000) == "bisect"
    assert resolve_median_method("auto", ndata=1,
                                 n_rows=HIST_MEDIAN_THRESHOLD + 1) == "bisect"
    assert resolve_median_method("auto", ndata=1, n_rows=1000) == "sort"

    # And category parity through classify_jax's explicit bisect on a real
    # 1-device mesh vs single-device bisect (same algorithm, sharded path).
    cfg_b = ScoringConfig(compute_global_medians_from_data=True,
                          median_method="bisect")
    w_mesh, _, _ = classify_jax(x, lab, k, cfg_b, mesh_shape={"data": 1})
    w_single, _, _ = classify_jax(x, lab, k, cfg_b)
    np.testing.assert_array_equal(np.asarray(w_mesh), np.asarray(w_single))
